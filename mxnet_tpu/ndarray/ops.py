"""The operator library: MXNet op names & semantics over jax.numpy / lax.

TPU-native rebuild of the reference's NNVM-registered op library
(SURVEY.md §2.1 "Operator library (dense)", reference dirs:
``src/operator/tensor/``, ``src/operator/nn/``, ``src/operator/random/``,
``src/operator/control_flow.cc``). ~150k LoC of C++/CUDA kernels collapse to
jax.numpy/lax calls that XLA fuses and tiles onto the MXU/VPU; everything
routes through ``apply_nary`` so the imperative autograd tape sees each op.

Op hyper-parameters (dmlc Parameter structs in the reference) become plain
keyword arguments closed over before dispatch, keeping the dispatched function
pure over its array inputs (required for jax.vjp / jit).
"""
from __future__ import annotations

import builtins as _builtins
import math
import os as _os

import numpy as _np
import jax
import jax.numpy as jnp
from jax import lax

from ..base import MXNetError
from .ndarray import NDArray, apply_nary, _dtype_of, _ax, array, zeros, ones, \
    full, arange

__all__ = []  # populated at bottom


def _nd(x, like=None):
    if isinstance(x, NDArray):
        return x
    return array(x, ctx=like._ctx if like is not None else None)


def _register(fn):
    __all__.append(fn.__name__)
    return fn


# ======================================================================
# elementwise unary (reference: src/operator/tensor/elemwise_unary_op*.cc)
# ======================================================================

def _unary_factory(name, jfn):
    def op(data, **kwargs):
        return apply_nary(jfn, [data], name=name)
    op.__name__ = name
    op.__doc__ = f"Elementwise {name}. Reference: src/operator/tensor/elemwise_unary_op_basic.cc ({name})."
    return _register(op)


relu = _unary_factory("relu", jax.nn.relu)
sigmoid = _unary_factory("sigmoid", jax.nn.sigmoid)
softsign = _unary_factory("softsign", jax.nn.soft_sign)
tanh = _unary_factory("tanh", jnp.tanh)
degrees = _unary_factory("degrees", jnp.degrees)
radians = _unary_factory("radians", jnp.radians)
exp = _unary_factory("exp", jnp.exp)
log = _unary_factory("log", jnp.log)
log2 = _unary_factory("log2", jnp.log2)
log10 = _unary_factory("log10", jnp.log10)
log1p = _unary_factory("log1p", jnp.log1p)
expm1 = _unary_factory("expm1", jnp.expm1)
sqrt = _unary_factory("sqrt", jnp.sqrt)
rsqrt = _unary_factory("rsqrt", lax.rsqrt)
cbrt = _unary_factory("cbrt", jnp.cbrt)
square = _unary_factory("square", jnp.square)
abs = _unary_factory("abs", jnp.abs)
sign = _unary_factory("sign", jnp.sign)
round = _unary_factory("round", jnp.round)
rint = _unary_factory("rint", jnp.rint)
ceil = _unary_factory("ceil", jnp.ceil)
floor = _unary_factory("floor", jnp.floor)
trunc = _unary_factory("trunc", jnp.trunc)
fix = _unary_factory("fix", jnp.trunc)
negative = _unary_factory("negative", jnp.negative)
reciprocal = _unary_factory("reciprocal", jnp.reciprocal)
sin = _unary_factory("sin", jnp.sin)
cos = _unary_factory("cos", jnp.cos)
tan = _unary_factory("tan", jnp.tan)
arcsin = _unary_factory("arcsin", jnp.arcsin)
arccos = _unary_factory("arccos", jnp.arccos)
arctan = _unary_factory("arctan", jnp.arctan)
sinh = _unary_factory("sinh", jnp.sinh)
cosh = _unary_factory("cosh", jnp.cosh)
arcsinh = _unary_factory("arcsinh", jnp.arcsinh)
arccosh = _unary_factory("arccosh", jnp.arccosh)
arctanh = _unary_factory("arctanh", jnp.arctanh)
erf = _unary_factory("erf", jax.scipy.special.erf)
erfinv = _unary_factory("erfinv", jax.scipy.special.erfinv)
digamma = _unary_factory("digamma", jax.scipy.special.digamma)


@_register
def hard_sigmoid(data, alpha=0.2, beta=0.5):
    """y = clip(alpha*x + beta, 0, 1). Reference: src/operator/tensor/elemwise_unary_op_basic.cc (hard_sigmoid)."""
    return apply_nary(lambda d: jnp.clip(alpha * d + beta, 0.0, 1.0),
                      [data], name="hard_sigmoid")
gamma = _unary_factory("gamma", lambda d: jnp.exp(jax.scipy.special.gammaln(d)))
gammaln = _unary_factory("gammaln", jax.scipy.special.gammaln)
logical_not = _unary_factory("logical_not",
                             lambda d: (d == 0).astype(jnp.float32))
zeros_like = _unary_factory("zeros_like", jnp.zeros_like)
ones_like = _unary_factory("ones_like", jnp.ones_like)


@_register
def identity(data):
    return apply_nary(lambda d: d, [data], name="identity")


@_register
def cast(data, dtype):
    dt = _dtype_of(dtype)
    return apply_nary(lambda d: d.astype(dt), [data], name="cast")


Cast = cast


@_register
def clip(data, a_min, a_max):
    return apply_nary(lambda d: jnp.clip(d, a_min, a_max), [data], name="clip")


# ======================================================================
# elementwise binary + broadcast (reference: elemwise_binary_broadcast_op*)
# ======================================================================

def _binary_factory(name, jfn):
    def op(lhs, rhs, **kwargs):
        lhs = _nd(lhs, rhs if isinstance(rhs, NDArray) else None)
        if isinstance(rhs, NDArray):
            return apply_nary(jfn, [lhs, rhs], name=name)
        return apply_nary(lambda a: jfn(a, rhs), [lhs], name=name)
    op.__name__ = name
    op.__doc__ = f"Broadcasting binary {name}. Reference: src/operator/tensor/elemwise_binary_broadcast_op_basic.cc."
    return _register(op)


add = _binary_factory("add", jnp.add)
subtract = _binary_factory("subtract", jnp.subtract)
multiply = _binary_factory("multiply", jnp.multiply)
divide = _binary_factory("divide", jnp.divide)
# reference elemwise_binary_op_basic.cc mod is C fmod semantics: the result
# takes the sign of the dividend (unlike numpy/Python mod).
modulo = _binary_factory("modulo", jnp.fmod)
power = _binary_factory("power", jnp.power)
maximum = _binary_factory("maximum", jnp.maximum)
minimum = _binary_factory("minimum", jnp.minimum)
hypot = _binary_factory("hypot", jnp.hypot)
arctan2 = _binary_factory("arctan2", jnp.arctan2)
equal = _binary_factory("equal", lambda a, b: (a == b).astype(jnp.float32))
not_equal = _binary_factory("not_equal",
                            lambda a, b: (a != b).astype(jnp.float32))
greater = _binary_factory("greater", lambda a, b: (a > b).astype(jnp.float32))
greater_equal = _binary_factory("greater_equal",
                                lambda a, b: (a >= b).astype(jnp.float32))
lesser = _binary_factory("lesser", lambda a, b: (a < b).astype(jnp.float32))
lesser_equal = _binary_factory("lesser_equal",
                               lambda a, b: (a <= b).astype(jnp.float32))
logical_and = _binary_factory(
    "logical_and", lambda a, b: ((a != 0) & (b != 0)).astype(jnp.float32))
logical_or = _binary_factory(
    "logical_or", lambda a, b: ((a != 0) | (b != 0)).astype(jnp.float32))
logical_xor = _binary_factory(
    "logical_xor", lambda a, b: ((a != 0) ^ (b != 0)).astype(jnp.float32))

# broadcast_* aliases: in mx.nd elemwise add/sub/... were strict-shape and the
# broadcast_ variants broadcast; jax broadcasts everywhere, so both names map
# to the broadcasting kernel.
for _n in ("add", "sub", "mul", "div", "mod", "power", "maximum", "minimum",
           "hypot", "equal", "not_equal", "greater", "greater_equal",
           "lesser", "lesser_equal", "logical_and", "logical_or",
           "logical_xor"):
    _base = {"sub": subtract, "mul": multiply, "div": divide,
             "mod": modulo}.get(_n) or globals()[_n]
    globals()["broadcast_" + _n] = _base
    __all__.append("broadcast_" + _n)
elemwise_add = add
elemwise_sub = subtract
elemwise_mul = multiply
elemwise_div = divide
__all__ += ["elemwise_add", "elemwise_sub", "elemwise_mul", "elemwise_div"]


@_register
def add_n(*args):
    """Reference: src/operator/tensor/elemwise_sum.cc (add_n / ElementwiseSum)."""
    if len(args) == 1 and isinstance(args[0], (list, tuple)):
        args = tuple(args[0])
    return apply_nary(lambda *xs: functools_reduce(xs), list(args), name="add_n")


def functools_reduce(xs):
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return out


ElementWiseSum = add_n
__all__.append("ElementWiseSum")


@_register
def where(condition, x, y):
    return apply_nary(lambda c, a, b: jnp.where(c != 0, a, b),
                      [_nd(condition), _nd(x), _nd(y)], name="where")


# ======================================================================
# reductions (reference: src/operator/tensor/broadcast_reduce_op*)
# ======================================================================

def _reduce_factory(name, jfn, exclude_support=True):
    def op(data, axis=None, keepdims=False, exclude=False, **kwargs):
        ax = _ax(axis)
        if exclude and ax is not None:
            axes = (ax,) if isinstance(ax, int) else tuple(ax)
            ax = tuple(i for i in range(data.ndim) if i not in
                       tuple(a % data.ndim for a in axes))
        return apply_nary(lambda d: jfn(d, axis=ax, keepdims=keepdims),
                          [data], name=name)
    op.__name__ = name
    op.__doc__ = f"Reduction {name}. Reference: src/operator/tensor/broadcast_reduce_op_value.cc."
    return _register(op)


sum = _reduce_factory("sum", jnp.sum)
mean = _reduce_factory("mean", jnp.mean)
prod = _reduce_factory("prod", jnp.prod)
nansum = _reduce_factory("nansum", jnp.nansum)
nanprod = _reduce_factory("nanprod", jnp.nanprod)
max = _reduce_factory("max", jnp.max)
min = _reduce_factory("min", jnp.min)
@_register
def norm(data, ord=2, axis=None, keepdims=False, **kwargs):
    """Reference: src/operator/tensor/broadcast_reduce_op_value.cc (norm);
    supports ord=1 (sum of |x|) and ord=2 (L2)."""
    ax = _ax(axis)
    if ord == 1:
        jfn = lambda d: jnp.sum(jnp.abs(d), axis=ax, keepdims=keepdims)
    elif ord == 2:
        jfn = lambda d: jnp.sqrt(
            jnp.sum(jnp.square(d), axis=ax, keepdims=keepdims))
    else:
        raise MXNetError(f"norm only supports ord=1 or 2, got {ord}")
    return apply_nary(jfn, [data], name="norm")
sum_axis = sum
max_axis = max
min_axis = min
__all__ += ["sum_axis", "max_axis", "min_axis"]


def _arg_index_dtype():
    """Reference argmax/argmin return FLOAT indices; float32 cannot
    represent indices past 2^24 exactly (and rounds 2^31+k to 2^31), so
    the int64 build widens to float64."""
    import jax
    return jnp.float64 if jax.config.jax_enable_x64 else jnp.float32


@_register
def argmax(data, axis=None, keepdims=False):
    return apply_nary(
        lambda d: jnp.argmax(d, axis=axis, keepdims=keepdims)
        .astype(_arg_index_dtype()), [data], name="argmax")


@_register
def argmin(data, axis=None, keepdims=False):
    return apply_nary(
        lambda d: jnp.argmin(d, axis=axis, keepdims=keepdims)
        .astype(_arg_index_dtype()), [data], name="argmin")


@_register
def mp_sum(*a, **k):  # pragma: no cover - alias
    return sum(*a, **k)


# ======================================================================
# linalg: dot / batch_dot (the MXU path)
# ======================================================================

@_register
def dot(lhs, rhs, transpose_a=False, transpose_b=False):
    """mx.nd.dot semantics: reduce last axis of lhs with first axis of rhs
    (tensordot over 1 axis), NOT numpy matmul batching.
    Reference: src/operator/tensor/dot-inl.h."""
    def fn(a, b):
        if transpose_a:
            a = jnp.transpose(a)
        if transpose_b:
            b = jnp.transpose(b)
        if a.ndim == 1 and b.ndim == 1:
            return jnp.dot(a, b)
        return jnp.tensordot(a, b, axes=1)
    return apply_nary(fn, [lhs, rhs], name="dot")


@_register
def batch_dot(lhs, rhs, transpose_a=False, transpose_b=False):
    """Reference: src/operator/tensor/dot-inl.h (batch_dot): (B, M, K)x(B, K, N)."""
    def fn(a, b):
        if transpose_a:
            a = jnp.swapaxes(a, -1, -2)
        if transpose_b:
            b = jnp.swapaxes(b, -1, -2)
        return jnp.matmul(a, b)
    return apply_nary(fn, [lhs, rhs], name="batch_dot")


@_register
def linalg_gemm2(a, b, transpose_a=False, transpose_b=False, alpha=1.0):
    def fn(x, y):
        if transpose_a:
            x = jnp.swapaxes(x, -1, -2)
        if transpose_b:
            y = jnp.swapaxes(y, -1, -2)
        return alpha * jnp.matmul(x, y)
    return apply_nary(fn, [a, b], name="linalg_gemm2")


# ======================================================================
# shape / matrix ops (reference: src/operator/tensor/matrix_op.cc)
# ======================================================================

@_register
def reshape(data, shape, reverse=False):
    """MXNet reshape incl. codes 0/-1/-2/-3/-4 (matrix_op-inl.h
    InferReshapeShape); ``reverse=True`` matches codes from the right."""
    if reverse:
        from .ndarray import _resolve_reshape
        spec = tuple(int(s) for s in shape)
        if -4 in spec:
            raise MXNetError("reshape(reverse=True) with -4 split is not "
                             "supported; write the split explicitly")
        new_shape = _resolve_reshape(tuple(data.shape)[::-1],
                                     spec[::-1])[::-1]
        return data.reshape(new_shape)
    return data.reshape(shape)


Reshape = reshape


@_register
def flatten(data):
    return data.flatten()


Flatten = flatten
__all__ += ["Reshape", "Flatten"]


@_register
def transpose(data, axes=None):
    return data.transpose(axes) if axes else data.transpose()


@_register
def expand_dims(data, axis):
    return data.expand_dims(axis)


@_register
def squeeze(data, axis=None):
    return data.squeeze(axis)


@_register
def broadcast_axis(data, axis, size):
    """Broadcast size-1 axes to the given sizes (reference broadcast_axis /
    broadcast_axes in src/operator/tensor/broadcast_reduce_op_value.cc)."""
    axes = (axis,) if isinstance(axis, int) else tuple(axis)
    sizes = (size,) if isinstance(size, int) else tuple(size)
    tgt = list(data.shape)
    for a, s in zip(axes, sizes):
        if tgt[a] != 1:
            raise MXNetError(
                f"broadcast_axis: axis {a} has size {tgt[a]} != 1")
        tgt[a] = s
    return data.broadcast_to(tuple(tgt))


broadcast_axes = broadcast_axis
__all__.append("broadcast_axes")


@_register
def broadcast_to(data, shape):
    return data.broadcast_to(shape)


@_register
def broadcast_like(lhs, rhs):
    return lhs.broadcast_to(rhs.shape)


@_register
def concat(*data, dim=1):
    if len(data) == 1 and isinstance(data[0], (list, tuple)):
        data = tuple(data[0])
    return apply_nary(lambda *xs: jnp.concatenate(xs, axis=dim), list(data),
                      name="concat")


Concat = concat
__all__.append("Concat")


@_register
def stack(*data, axis=0):
    if len(data) == 1 and isinstance(data[0], (list, tuple)):
        data = tuple(data[0])
    return apply_nary(lambda *xs: jnp.stack(xs, axis=axis), list(data),
                      name="stack")


@_register
def split(data, num_outputs, axis=1, squeeze_axis=False):
    """Reference: src/operator/slice_channel.cc (SliceChannel/split)."""
    def fn(d):
        parts = jnp.split(d, num_outputs, axis=axis)
        if squeeze_axis:
            parts = [jnp.squeeze(p, axis=axis) for p in parts]
        return tuple(parts)
    return apply_nary(fn, [data], n_out=num_outputs, name="split")


SliceChannel = split
__all__.append("SliceChannel")


@_register
def slice(data, begin, end, step=None):
    """Reference: src/operator/tensor/matrix_op.cc (slice)."""
    begin = tuple(begin)
    end = tuple(end)
    step = tuple(step) if step is not None else (1,) * len(begin)
    def fn(d):
        idx = tuple(_pyslice(b, e, s)
                    for b, e, s in zip(begin, end, step))
        return d[idx + (Ellipsis,)]
    return apply_nary(fn, [data], name="slice")


def _pyslice(b, e, s):
    return _builtins.slice(b, e, s)


@_register
def slice_axis(data, axis, begin, end):
    def fn(d):
        sl = [_pyslice(None, None, None)] * d.ndim
        sl[axis] = _pyslice(begin, end if end is not None else d.shape[axis], None)
        return d[tuple(sl)]
    return apply_nary(fn, [data], name="slice_axis")


@_register
def slice_like(data, shape_like, axes=None):
    def fn(d, ref):
        sl = [_pyslice(None, None, None)] * d.ndim
        dims = axes if axes is not None else range(d.ndim)
        for a in dims:
            sl[a] = _pyslice(0, ref.shape[a], None)
        return d[tuple(sl)]
    return apply_nary(fn, [data, shape_like], name="slice_like")


@_register
def flip(data, axis):
    return apply_nary(lambda d: jnp.flip(d, axis), [data], name="flip")


reverse = flip
__all__.append("reverse")


@_register
def tile(data, reps):
    return data.tile(reps)


@_register
def repeat(data, repeats, axis=None):
    return data.repeat(repeats, axis)


@_register
def pad(data, mode="constant", pad_width=None, constant_value=0.0):
    """Reference: src/operator/pad.cc. pad_width is the flat MXNet layout
    (before_1, after_1, before_2, after_2, ...)."""
    pw = list(pad_width)
    pairs = [(pw[i], pw[i + 1]) for i in range(0, len(pw), 2)]
    jmode = {"constant": "constant", "edge": "edge", "reflect": "reflect"}[mode]
    kwargs = {"constant_values": constant_value} if mode == "constant" else {}
    return apply_nary(lambda d: jnp.pad(d, pairs, mode=jmode, **kwargs),
                      [data], name="pad")


@_register
def swapaxes(data, dim1, dim2):
    return data.swapaxes(dim1, dim2)


SwapAxis = swapaxes
__all__.append("SwapAxis")


@_register
def space_to_depth(data, block_size):
    b = block_size
    def fn(d):
        n, c, h, w = d.shape
        d = d.reshape(n, c, h // b, b, w // b, b)
        d = jnp.transpose(d, (0, 3, 5, 1, 2, 4))
        return d.reshape(n, c * b * b, h // b, w // b)
    return apply_nary(fn, [data], name="space_to_depth")


@_register
def depth_to_space(data, block_size):
    b = block_size
    def fn(d):
        n, c, h, w = d.shape
        d = d.reshape(n, b, b, c // (b * b), h, w)
        d = jnp.transpose(d, (0, 3, 4, 1, 5, 2))
        return d.reshape(n, c // (b * b), h * b, w * b)
    return apply_nary(fn, [data], name="depth_to_space")


# ======================================================================
# indexing ops (reference: src/operator/tensor/indexing_op.cc)
# ======================================================================

@_register
def take(a, indices, axis=0, mode="clip"):
    idx = _nd(indices, a)
    def fn(d, i):
        ii = i.astype(jnp.int32)
        if mode == "wrap":
            ii = jnp.mod(ii, d.shape[axis])
        else:
            ii = jnp.clip(ii, 0, d.shape[axis] - 1)
        return jnp.take(d, ii, axis=axis)
    return apply_nary(fn, [a, idx], name="take")


@_register
def pick(data, index, axis=-1, keepdims=False, mode="clip"):
    idx = _nd(index, data)
    def fn(d, i):
        ii = jnp.clip(i.astype(jnp.int32), 0, d.shape[axis] - 1)
        out = jnp.take_along_axis(d, jnp.expand_dims(ii, axis % d.ndim if axis >= 0 else axis),
                                  axis=axis)
        return out if keepdims else jnp.squeeze(out, axis=axis)
    return apply_nary(fn, [data, idx], name="pick")


@_register
def gather_nd(data, indices):
    def fn(d, i):
        ii = i.astype(jnp.int32)
        return d[tuple(ii[k] for k in range(ii.shape[0]))]
    return apply_nary(fn, [data, _nd(indices, data)], name="gather_nd")


@_register
def scatter_nd(data, indices, shape):
    def fn(d, i):
        ii = i.astype(jnp.int32)
        out = jnp.zeros(tuple(shape), d.dtype)
        return out.at[tuple(ii[k] for k in range(ii.shape[0]))].add(d)
    return apply_nary(fn, [data, _nd(indices, data)], name="scatter_nd")


@_register
def one_hot(indices, depth, on_value=1.0, off_value=0.0, dtype="float32"):
    dt = _dtype_of(dtype)
    def fn(i):
        oh = jax.nn.one_hot(i.astype(jnp.int32), depth, dtype=dt)
        return oh * (on_value - off_value) + off_value
    return apply_nary(fn, [_nd(indices)], name="one_hot")


@_register
def Embedding(data, weight, input_dim=None, output_dim=None, dtype="float32",
              sparse_grad=False):
    """Reference: src/operator/tensor/indexing_op.cc (Embedding).

    ``sparse_grad=True`` installs a row-sparse pullback: the weight
    cotangent is (unique touched rows, segment-summed values) — memory and
    compute O(nnz), never O(vocab) (reference kRowSparseStorage grad)."""
    def fn(i, w):
        return jnp.take(w, i.astype(jnp.int32), axis=0)
    data_nd, weight_nd = _nd(data), _nd(weight)
    if not sparse_grad:
        return apply_nary(fn, [data_nd, weight_nd], name="Embedding")

    from .ndarray import NDArray as _ND
    from .. import _tape
    outs, node = _tape.apply_op(fn, [data_nd, weight_nd], n_out=1,
                                name="Embedding(sparse_grad)")
    if node is not None:
        # Fully device-side pullback (r2 weak #6 fixed): the cotangent
        # carries the RAW batch ids (duplicates included) — no host
        # np.unique on the forward hot path, nnz bounded by the batch.
        # Dedup is deferred to SparseCotangent.dedup() at leaf
        # materialization (all consumers sum duplicates).
        ids_j = data_nd.data.astype(jnp.int32).ravel()
        vocab_shape = weight_nd.shape

        def sparse_vjp(cot):
            flat = cot.reshape(-1, cot.shape[-1])
            return (None, _tape.SparseCotangent(ids_j, flat, vocab_shape))
        node.vjp_fn = sparse_vjp
    out = _ND(outs[0], data_nd._ctx)
    if node is not None:
        out._node = node
        out._out_index = 0
    return out


embedding = Embedding
__all__.append("embedding")


@_register
def sequence_mask(data, sequence_length=None, use_sequence_length=False,
                  value=0.0, axis=0):
    if not use_sequence_length or sequence_length is None:
        return identity(data)
    def fn(d, sl):
        steps = jnp.arange(d.shape[axis])
        bshape = [1] * d.ndim
        bshape[axis] = d.shape[axis]
        batch_axis = 1 - axis  # mx convention: (T, B, ...) ax0 or (B, T) ax1
        sshape = [1] * d.ndim
        sshape[batch_axis] = d.shape[batch_axis]
        mask = steps.reshape(bshape) < sl.reshape(sshape)
        return jnp.where(mask, d, jnp.asarray(value, d.dtype))
    return apply_nary(fn, [data, _nd(sequence_length, data)],
                      name="sequence_mask")


SequenceMask = sequence_mask
__all__.append("SequenceMask")


@_register
def sequence_last(data, sequence_length=None, use_sequence_length=False, axis=0):
    if not use_sequence_length or sequence_length is None:
        return slice_axis(data, axis=axis, begin=-1, end=None).squeeze(axis)
    def fn(d, sl):
        idx = (sl.astype(jnp.int32) - 1)
        # index layout depends on the time axis: batch sits on the other of
        # axes {0,1} (reference src/operator/sequence_last.cc supports both)
        batch_axis = 1 - axis
        ishape = [1] * d.ndim
        ishape[batch_axis] = d.shape[batch_axis]
        return jnp.take_along_axis(d, idx.reshape(ishape), axis=axis) \
            .squeeze(axis)
    return apply_nary(fn, [data, _nd(sequence_length, data)],
                      name="sequence_last")


@_register
def sequence_reverse(data, sequence_length=None, use_sequence_length=False,
                     axis=0):
    if not use_sequence_length or sequence_length is None:
        return flip(data, axis)
    def fn(d, sl):
        T = d.shape[axis]
        steps = jnp.arange(T).reshape((-1,) + (1,) * (d.ndim - 1))
        sl_b = sl.astype(jnp.int32).reshape((1, -1) + (1,) * (d.ndim - 2))
        rev_idx = jnp.where(steps < sl_b, sl_b - 1 - steps, steps)
        return jnp.take_along_axis(d, jnp.broadcast_to(rev_idx, d.shape),
                                   axis=0)
    return apply_nary(fn, [data, _nd(sequence_length, data)],
                      name="sequence_reverse")


SequenceReverse = sequence_reverse
SequenceLast = sequence_last
__all__ += ["SequenceReverse", "SequenceLast"]


# ======================================================================
# ordering (reference: src/operator/tensor/ordering_op.cc)
# ======================================================================

@_register
def sort(data, axis=-1, is_ascend=True):
    def fn(d):
        out = jnp.sort(d, axis=axis)
        return out if is_ascend else jnp.flip(out, axis=axis)
    return apply_nary(fn, [data], name="sort")


@_register
def argsort(data, axis=-1, is_ascend=True, dtype="float32"):
    dt = _dtype_of(dtype)
    def fn(d):
        out = jnp.argsort(d, axis=axis)
        if not is_ascend:
            out = jnp.flip(out, axis=axis)
        return out.astype(dt)
    return apply_nary(fn, [data], name="argsort")


@_register
def topk(data, axis=-1, k=1, ret_typ="indices", is_ascend=False,
         dtype="float32"):
    dt = _dtype_of(dtype)
    def fn(d):
        dd = jnp.swapaxes(d, axis, -1) if axis not in (-1, d.ndim - 1) else d
        vals, idx = lax.top_k(-dd if is_ascend else dd, k)
        if is_ascend:
            vals = -vals
        if axis not in (-1, d.ndim - 1):
            vals = jnp.swapaxes(vals, axis, -1)
            idx = jnp.swapaxes(idx, axis, -1)
        if ret_typ == "value":
            return vals
        if ret_typ == "both":
            return (vals, idx.astype(dt))
        return idx.astype(dt)
    n_out = 2 if ret_typ == "both" else 1
    return apply_nary(fn, [data], n_out=n_out, name="topk")


# ======================================================================
# neural-net ops (reference: src/operator/nn/*)
# ======================================================================

@_register
def FullyConnected(data, weight, bias=None, num_hidden=None, no_bias=False,
                   flatten=True):
    """Reference: src/operator/nn/fully_connected.cc. weight is (out, in) —
    MXNet layout; the matmul hits the MXU as data @ weight.T.

    MXTPU_COMPUTE_DTYPE=int8|fp8 (ISSUE 20) reroutes the matmul through
    ops.quant_matmul — amax-scaled low-precision operands, f32
    accumulation, custom VJP with quantized grad-side matmuls — making
    this the single seam every Dense/projection in the trainer crosses.
    Resolved at trace time: unset, the op is BITWISE the plain matmul."""
    inputs = [data, weight] + ([] if no_bias or bias is None else [bias])
    from ..ops.quant_matmul import quant_matmul, resolve_compute_dtype
    cd = resolve_compute_dtype()
    def fn(d, w, *b):
        x = d.reshape(d.shape[0], -1) if flatten and d.ndim > 2 else d
        if cd is not None:
            y = quant_matmul(x, w.T, compute_dtype=cd, tag="fc")
        else:
            y = jnp.matmul(x, w.T)
        if b:
            y = y + b[0]
        return y
    return apply_nary(fn, inputs, name="FullyConnected")


fully_connected = FullyConnected
__all__.append("fully_connected")


@_register
def Activation(data, act_type="relu"):
    """Reference: src/operator/nn/activation.cc."""
    fns = {"relu": jax.nn.relu, "sigmoid": jax.nn.sigmoid,
           "tanh": jnp.tanh, "softrelu": jax.nn.softplus,
           "softsign": jax.nn.soft_sign}
    if act_type not in fns:
        raise MXNetError(f"unknown act_type {act_type}")
    return apply_nary(fns[act_type], [data], name="Activation")


@_register
def LeakyReLU(data, gamma=None, act_type="leaky", slope=0.25,
              lower_bound=0.125, upper_bound=0.334):
    """Reference: src/operator/leaky_relu.cc (leaky/prelu/elu/selu/gelu)."""
    if act_type == "leaky":
        return apply_nary(lambda d: jax.nn.leaky_relu(d, slope), [data],
                          name="LeakyReLU")
    if act_type == "elu":
        return apply_nary(lambda d: jax.nn.elu(d, slope), [data])
    if act_type == "selu":
        return apply_nary(jax.nn.selu, [data])
    if act_type == "gelu":
        return apply_nary(lambda d: jax.nn.gelu(d, approximate=False), [data])
    if act_type == "prelu":
        def fn(d, g):
            return jnp.where(d >= 0, d, _reshape_gamma(g, d) * d)
        return apply_nary(fn, [data, gamma], name="prelu")
    raise MXNetError(f"unknown LeakyReLU act_type {act_type}")


def _reshape_gamma(g, d):
    if g.ndim == 1 and d.ndim > 1:
        return g.reshape((1, -1) + (1,) * (d.ndim - 2))
    return g


@_register
def softmax(data, axis=-1, temperature=None, length=None):
    def fn(d):
        x = d / temperature if temperature else d
        return jax.nn.softmax(x, axis=axis)
    return apply_nary(fn, [data], name="softmax")


@_register
def log_softmax(data, axis=-1, temperature=None):
    def fn(d):
        x = d / temperature if temperature else d
        return jax.nn.log_softmax(x, axis=axis)
    return apply_nary(fn, [data], name="log_softmax")


@_register
def softmin(data, axis=-1):
    return apply_nary(lambda d: jax.nn.softmax(-d, axis=axis), [data])


@_register
def SoftmaxActivation(data, mode="instance"):
    axis = 1 if mode == "channel" else -1
    return softmax(data, axis=axis)


@_register
def SoftmaxOutput(data, label, grad_scale=1.0, ignore_label=-1,
                  use_ignore=False, multi_output=False, normalization="null",
                  out_grad=False, smooth_alpha=0.0):
    """Forward = softmax; backward = (p - onehot(label)) — the classic fused
    op. Reference: src/operator/softmax_output.cc. Implemented with a custom
    vjp so the Module/Symbol path trains identically."""
    @jax.custom_vjp
    def _so(d, l):
        return jax.nn.softmax(d, axis=-1)

    def _fwd(d, l):
        p = jax.nn.softmax(d, axis=-1)
        return p, (p, l)

    def _bwd(res, g):
        p, l = res
        oh = jax.nn.one_hot(l.astype(jnp.int32), p.shape[-1], dtype=p.dtype)
        grad = (p - oh) * grad_scale
        if use_ignore:
            mask = (l != ignore_label).astype(p.dtype)
            grad = grad * mask[..., None]
        if normalization == "batch":
            grad = grad / p.shape[0]
        elif normalization == "valid" and use_ignore:
            denom = jnp.maximum(jnp.sum(l != ignore_label), 1).astype(p.dtype)
            grad = grad / denom
        return grad, None

    _so.defvjp(_fwd, _bwd)
    return apply_nary(_so, [data, _nd(label, data)], name="SoftmaxOutput")


@_register
def Dropout(data, p=0.5, mode="training", axes=None, cudnn_off=False):
    """Reference: src/operator/nn/dropout.cc. Uses the framework PRNG stream
    (mx.random) — explicit-key JAX PRNG behind a stateful facade."""
    from . import random as _rnd
    from .. import _tape as _t
    if not _t.is_training() or p <= 0:
        return identity(data)
    key = _rnd.next_key()
    def fn(d):
        shape = d.shape
        if axes:
            shape = tuple(1 if i in axes else s for i, s in enumerate(d.shape))
        keep = jax.random.bernoulli(key, 1.0 - p, shape)
        return jnp.where(keep, d / (1.0 - p), jnp.zeros((), d.dtype))
    return apply_nary(fn, [data], name="Dropout")


# ---- convolution / pooling ----

def _conv_dn(ndim):
    # data NC[D]HW, kernel OI[D]HW — MXNet layout throughout
    spec = {1: ("NCH", "OIH", "NCH"), 2: ("NCHW", "OIHW", "NCHW"),
            3: ("NCDHW", "OIDHW", "NCDHW")}[ndim]
    return lax.conv_dimension_numbers((1, 1) + (1,) * ndim,
                                      (1, 1) + (1,) * ndim, spec)


@_register
def Convolution(data, weight, bias=None, kernel=None, stride=None, dilate=None,
                pad=None, num_filter=None, num_group=1, no_bias=False,
                workspace=None, layout=None, cudnn_off=False,
                cudnn_tune=None):
    """Reference: src/operator/nn/convolution.cc. Lowered to lax.conv_general_dilated
    so XLA:TPU picks MXU tiling (the reference dispatched to cuDNN)."""
    nd = len(kernel)
    stride = tuple(stride) if stride else (1,) * nd
    dilate = tuple(dilate) if dilate else (1,) * nd
    pad_ = tuple(pad) if pad else (0,) * nd
    padding = [(p, p) for p in pad_]
    # MXTPU_CONV_LAYOUT=NHWC runs the 2D conv internally channels-last
    # (TPU-native lane layout); boundary transposes between consecutive
    # convs cancel in XLA. User-facing semantics stay NCHW.
    nhwc = nd == 2 and _os.environ.get("MXTPU_CONV_LAYOUT", "") == "NHWC"
    dn = lax.conv_dimension_numbers(
        (1, 1, 1, 1), (1, 1, 1, 1), ("NHWC", "HWIO", "NHWC")) if nhwc \
        else _conv_dn(nd)
    inputs = [data, weight] + ([] if no_bias or bias is None else [bias])
    def fn(d, w, *b):
        # no preferred_element_type: XLA:TPU already accumulates bf16 convs
        # in fp32, and an explicit fp32 hint breaks jax's conv transpose
        # rule (fp32 cotangent x bf16 operand mismatch) under grad
        if nhwc:
            d = jnp.transpose(d, (0, 2, 3, 1))
            w = jnp.transpose(w, (2, 3, 1, 0))
        y = lax.conv_general_dilated(
            d, w, window_strides=stride, padding=padding,
            rhs_dilation=dilate, dimension_numbers=dn,
            feature_group_count=num_group)
        if nhwc:
            y = jnp.transpose(y, (0, 3, 1, 2))
        if b:
            y = y + b[0].reshape((1, -1) + (1,) * nd).astype(y.dtype)
        return y.astype(d.dtype)
    return apply_nary(fn, inputs, name="Convolution")


@_register
def Deconvolution(data, weight, bias=None, kernel=None, stride=None,
                  dilate=None, pad=None, adj=None, target_shape=None,
                  num_filter=None, num_group=1, no_bias=True, workspace=None,
                  layout=None, cudnn_off=False, cudnn_tune=None):
    """Transposed conv. Reference: src/operator/nn/deconvolution.cc.

    Lowered as ONE grouped ``lax.conv_general_dilated`` (lhs-dilated by
    stride — the textbook transposed-conv-as-conv identity), so groups,
    stride, dilation and adj all compose in a single XLA conv the MXU
    tiles directly."""
    nd = len(kernel)
    stride = tuple(stride) if stride else (1,) * nd
    dilate_ = tuple(dilate) if dilate else (1,) * nd
    pad_ = tuple(pad) if pad else (0,) * nd
    keff = [dilate_[i] * (kernel[i] - 1) + 1 for i in range(nd)]
    if target_shape is not None:
        # reference: target_shape overrides adj to hit the exact size
        ts = tuple(target_shape)
        in_sp = data.shape[2:]
        adj_ = tuple(
            ts[i] - ((in_sp[i] - 1) * stride[i] - 2 * pad_[i] + keff[i])
            for i in range(nd))
        if any(a < 0 or a >= stride[i] for i, a in enumerate(adj_)):
            raise MXNetError(
                f"Deconvolution: target_shape {ts} unreachable from input "
                f"{tuple(in_sp)} with kernel/stride/pad/dilate given")
    else:
        adj_ = tuple(adj) if adj else (0,) * nd
    inputs = [data, weight] + ([] if no_bias or bias is None else [bias])

    def fn(d, w, *b):
        # deconv forward == gradient of conv wrt input: lhs-dilate by
        # stride, pad with (k_eff-1-p), spatially flip the kernel and swap
        # its (in, out/g) dims per group. Output size:
        # (in-1)*s - 2p + k_eff + adj
        g = num_group
        in_g = w.shape[0] // g
        out_g = w.shape[1]
        wk = w.reshape((g, in_g, out_g) + w.shape[2:])
        wk = jnp.swapaxes(wk, 1, 2)
        wk = wk.reshape((g * out_g, in_g) + w.shape[2:])
        wk = jnp.flip(wk, axis=tuple(range(2, 2 + nd)))
        padding = [(keff[i] - 1 - pad_[i],
                    keff[i] - 1 - pad_[i] + adj_[i]) for i in range(nd)]
        y = lax.conv_general_dilated(
            d, wk, window_strides=(1,) * nd, padding=padding,
            lhs_dilation=stride, rhs_dilation=dilate_,
            dimension_numbers=_conv_dn(nd), feature_group_count=g)
        if b:
            y = y + b[0].reshape((1, -1) + (1,) * nd).astype(y.dtype)
        return y
    return apply_nary(fn, inputs, name="Deconvolution")


@_register
def Pooling(data, kernel=None, pool_type="max", global_pool=False,
            stride=None, pad=None, pooling_convention="valid",
            cudnn_off=False, count_include_pad=True, layout=None,
            p_value=2):
    """Reference: src/operator/nn/pooling.cc. Supports max/avg/sum/lp
    (p_value in the reference's {1,2,3}) and the 'valid'|'full'
    pooling_convention quirk (full = ceil division)."""
    def fn(d):
        nd = d.ndim - 2
        if global_pool:
            axes = tuple(range(2, d.ndim))
            if pool_type == "max":
                return jnp.max(d, axis=axes, keepdims=True)
            if pool_type == "sum":
                return jnp.sum(d, axis=axes, keepdims=True)
            if pool_type == "lp":
                # reference pool_utils.h a_pow_p: x^p with NO abs (odd p
                # keeps sign; negative window sums then root to NaN,
                # reference behavior)
                return jnp.sum(d ** p_value, axis=axes,
                               keepdims=True) ** (1.0 / p_value)
            return jnp.mean(d, axis=axes, keepdims=True)
        k = tuple(kernel)
        s = tuple(stride) if stride else (1,) * nd
        p = tuple(pad) if pad else (0,) * nd
        window = (1, 1) + k
        strides = (1, 1) + s
        if pooling_convention == "full":
            # ceil mode: pad right enough so ceil((x+2p-k)/s)+1 windows fit
            extra = []
            for i in range(nd):
                x = d.shape[2 + i] + 2 * p[i]
                out = -(-(x - k[i]) // s[i]) + 1
                need = (out - 1) * s[i] + k[i] - x
                extra.append(builtins_max(need, 0))
            padding = [(0, 0), (0, 0)] + [(p[i], p[i] + extra[i])
                                          for i in range(nd)]
        else:
            padding = [(0, 0), (0, 0)] + [(p[i], p[i]) for i in range(nd)]
        if pool_type == "max":
            init = -jnp.inf if jnp.issubdtype(d.dtype, jnp.floating) else \
                jnp.iinfo(d.dtype).min
            return lax.reduce_window(d, init, lax.max, window, strides,
                                     padding)
        # init must be a CONCRETE zero: lax.reduce_window only dispatches to
        # the differentiable reduce_window_sum monoid when it can see the
        # identity; a traced jnp zero falls back to a generic reduce_window
        # whose linearization fails under vjp-of-jit (hybridize + record)
        zero = _np.zeros((), d.dtype)
        if pool_type == "lp":
            # reference lp pooling: (sum x^p)^(1/p), no abs (see above)
            sp = lax.reduce_window(d ** p_value, zero, lax.add,
                                   window, strides, padding)
            return (sp ** (1.0 / p_value)).astype(d.dtype)
        ssum = lax.reduce_window(d, zero, lax.add, window, strides, padding)
        if pool_type == "sum":
            return ssum
        if count_include_pad:
            return (ssum / _np.prod(k)).astype(d.dtype)
        ones_ = jnp.ones_like(d)
        cnt = lax.reduce_window(ones_, zero, lax.add, window, strides, padding)
        return (ssum / cnt).astype(d.dtype)
    return apply_nary(fn, [data], name="Pooling")


def builtins_max(a, b):
    return a if a > b else b


@_register
def BatchNorm(data, gamma, beta, moving_mean, moving_var, eps=1e-3,
              momentum=0.9, fix_gamma=True, use_global_stats=False,
              output_mean_var=False, axis=1, cudnn_off=False):
    """Stateless op-level BatchNorm (normalizes with given stats in eval, batch
    stats in train). Running-stat *updates* are handled by gluon.nn.BatchNorm,
    which threads aux state explicitly (SURVEY.md §7 hard parts).
    Reference: src/operator/nn/batch_norm.cc."""
    from .. import _tape as _t
    training = _t.is_training() and not use_global_stats
    def fn(d, g, b, mm, mv):
        shape = [1] * d.ndim
        shape[axis] = d.shape[axis]
        g_ = jnp.ones_like(g) if fix_gamma else g
        if training:
            axes = tuple(i for i in range(d.ndim) if i != axis)
            m = jnp.mean(d, axis=axes)
            v = jnp.var(d, axis=axes)
        else:
            m, v = mm, mv
        inv = lax.rsqrt(v + eps).reshape(shape)
        return (d - m.reshape(shape)) * inv * g_.reshape(shape) + b.reshape(shape)
    return apply_nary(fn, [data, gamma, beta, moving_mean, moving_var],
                      name="BatchNorm")


@_register
def LayerNorm(data, gamma, beta, axis=-1, eps=1e-5, output_mean_var=False):
    """Reference: src/operator/nn/layer_norm.cc."""
    def fn(d, g, b):
        m = jnp.mean(d, axis=axis, keepdims=True)
        v = jnp.var(d, axis=axis, keepdims=True)
        shape = [1] * d.ndim
        shape[axis] = d.shape[axis]
        return (d - m) * lax.rsqrt(v + eps) * g.reshape(shape) + b.reshape(shape)
    return apply_nary(fn, [data, gamma, beta], name="LayerNorm")


@_register
def InstanceNorm(data, gamma, beta, eps=1e-3):
    def fn(d, g, b):
        axes = tuple(range(2, d.ndim))
        m = jnp.mean(d, axis=axes, keepdims=True)
        v = jnp.var(d, axis=axes, keepdims=True)
        shape = (1, -1) + (1,) * (d.ndim - 2)
        return (d - m) * lax.rsqrt(v + eps) * g.reshape(shape) + b.reshape(shape)
    return apply_nary(fn, [data, gamma, beta], name="InstanceNorm")


@_register
def L2Normalization(data, eps=1e-10, mode="instance"):
    def fn(d):
        if mode == "instance":
            axes = tuple(range(1, d.ndim))
        elif mode == "channel":
            axes = (1,)
        else:
            axes = tuple(range(1, d.ndim))
        nrm = jnp.sqrt(jnp.sum(jnp.square(d), axis=axes, keepdims=True) + eps)
        return d / nrm
    return apply_nary(fn, [data], name="L2Normalization")


@_register
def RNN(data, parameters, state, state_cell=None, state_size=None,
        num_layers=1, mode="lstm", bidirectional=False, p=0.0,
        state_outputs=False, projection_size=None, sequence_length=None,
        use_sequence_length=False):
    """Fused multi-layer (bi)directional RNN over a FLAT parameter vector
    (reference src/operator/rnn.cc / cuDNN RNN).

    data: (T, B, I) sequence-major. parameters: the reference's packed
    1-D vector — all weights first (per layer, per direction: W_i2h
    [G*H, in], W_h2h [G*H, H]), then all biases in the same order
    (b_i2h, b_h2h each [G*H]). state: (L*dir, B, H); state_cell for
    lstm. Returns out (T, B, H*dir), plus final states when
    state_outputs=True. The recurrence is ONE lax.scan per direction —
    the same compiled shape the gluon fused layer uses (identical
    _cell_step gate order, so gluon weights flattened into this layout
    reproduce gluon outputs bit-for-bit)."""
    if projection_size is not None or use_sequence_length:
        raise MXNetError("nd.RNN: projection_size/use_sequence_length "
                         "are not supported (reference cuDNN-only paths)")
    from ..gluon.rnn.rnn_layer import run_fused_rnn
    from .. import _tape
    gates = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}.get(mode)
    if gates is None:
        raise MXNetError(f"nd.RNN: unknown mode {mode!r}")
    if mode == "lstm" and state_cell is None:
        raise MXNetError("nd.RNN: lstm mode requires state_cell")
    dirs = 2 if bidirectional else 1
    T, B, I = data.shape
    H = int(state_size) if state_size else state.shape[-1]
    if state.shape[0] != num_layers * dirs:
        raise MXNetError(
            f"nd.RNN: state has {state.shape[0]} layer slots, need "
            f"num_layers*dirs = {num_layers * dirs}")
    expected = _builtins.sum(          # `sum` is the reduction op here
        gates * H * (I if layer == 0 else H * dirs) + gates * H * H
        + 2 * gates * H
        for layer in range(num_layers) for _ in range(dirs))
    n_given = int(_np.prod(getattr(parameters, "shape", (len(parameters),))))
    if n_given != expected:
        raise MXNetError(
            f"nd.RNN: packed parameter vector has {n_given} values, "
            f"layout needs {expected} (mode={mode}, num_layers="
            f"{num_layers}, bidirectional={bidirectional}, I={I}, H={H})")
    training = _tape.is_training()
    # hoist the dropout key OUT of the traced fn: tape replay re-executes
    # fn, and a fresh next_key() there would regenerate different masks
    drop_key = None
    if p and training and num_layers > 1:
        from . import random as _rnd
        drop_key = _rnd.next_key()

    def fn(x, w, *state_arrs):
        # unpack the packed vector with static python offsets
        offs = 0
        weights, biases = [], []
        for layer in range(num_layers):
            in_sz = I if layer == 0 else H * dirs
            for _ in range(dirs):
                wih = w[offs:offs + gates * H * in_sz] \
                    .reshape(gates * H, in_sz)
                offs += gates * H * in_sz
                whh = w[offs:offs + gates * H * H].reshape(gates * H, H)
                offs += gates * H * H
                weights.append((wih, whh))
        for layer in range(num_layers):
            for _ in range(dirs):
                bih = w[offs:offs + gates * H]
                offs += gates * H
                bhh = w[offs:offs + gates * H]
                offs += gates * H
                biases.append((bih, bhh))
        return run_fused_rnn(mode, x, state_arrs, weights, biases,
                             num_layers, dirs, p, training, drop_key)

    inputs = [data, _nd(parameters, data), state]
    if mode == "lstm":
        inputs.append(state_cell)
    n_out = 3 if mode == "lstm" else 2
    results = apply_nary(fn, inputs, n_out=n_out, name="RNN")
    if state_outputs:
        return results
    return results[0]


# ======================================================================
# losses at op level (reference: src/operator/loss_binary_op.cc etc.)
# ======================================================================

@_register
def softmax_cross_entropy(data, label):
    def fn(d, l):
        logp = jax.nn.log_softmax(d, axis=-1)
        oh = jax.nn.one_hot(l.astype(jnp.int32), d.shape[-1], dtype=d.dtype)
        return -jnp.sum(oh * logp)
    return apply_nary(fn, [data, _nd(label, data)],
                      name="softmax_cross_entropy")


@_register
def smooth_l1(data, scalar=1.0):
    s2 = scalar * scalar
    def fn(d):
        a = jnp.abs(d)
        return jnp.where(a < 1.0 / s2, 0.5 * s2 * jnp.square(d), a - 0.5 / s2)
    return apply_nary(fn, [data], name="smooth_l1")


@_register
def MakeLoss(data, grad_scale=1.0, valid_thresh=0.0, normalization="null"):
    return apply_nary(lambda d: d * grad_scale, [data], name="MakeLoss")


@_register
def BlockGrad(data):
    """Reference: src/operator/tensor/elemwise_unary_op_basic.cc (BlockGrad)."""
    return apply_nary(lambda d: lax.stop_gradient(d), [data], name="BlockGrad")


stop_gradient = BlockGrad
__all__.append("stop_gradient")


# ======================================================================
# control flow (reference: src/operator/control_flow.cc — foreach/while/cond)
# ======================================================================

@_register
def foreach(body, data, init_states):
    """lax.scan-backed foreach. body(elem, states) -> (out, new_states).
    Works on NDArrays imperatively (not differentiable through the tape in
    v1 — use inside HybridBlock/jit for the differentiable path)."""
    single = not isinstance(data, (list, tuple))
    datas = [data] if single else list(data)
    states_single = not isinstance(init_states, (list, tuple))
    states = [init_states] if states_single else list(init_states)

    def step(carry, xs):
        c_nd = [NDArray(c) for c in carry]
        x_nd = [NDArray(x) for x in xs]
        out, new_states = body(x_nd[0] if single else x_nd,
                               c_nd[0] if states_single else c_nd)
        outs = [out] if not isinstance(out, (list, tuple)) else list(out)
        ns = [new_states] if not isinstance(new_states, (list, tuple)) \
            else list(new_states)
        return tuple(s._data for s in ns), tuple(o._data for o in outs)

    from .. import _tape as _t
    with _t.trace_scope():
        final, stacked = lax.scan(step, tuple(s._data for s in states),
                                  tuple(d._data for d in datas))
    outs = [NDArray(s) for s in stacked]
    fstates = [NDArray(f) for f in final]
    return (outs[0] if len(outs) == 1 else outs,
            fstates[0] if states_single else fstates)


@_register
def cond(pred, then_func, else_func):
    p = pred.asscalar() if isinstance(pred, NDArray) else pred
    return then_func() if p else else_func()


@_register
def while_loop(cond_fn, func, loop_vars, max_iterations=None):
    steps = 0
    outputs = []
    lv = list(loop_vars)
    while cond_fn(*lv) and (max_iterations is None or steps < max_iterations):
        out, lv = func(*lv)
        lv = list(lv) if isinstance(lv, (list, tuple)) else [lv]
        if out is not None:     # step functions may carry state only
            outputs.append(out)
        steps += 1
    if outputs and isinstance(outputs[0], (list, tuple)):
        outs = [stack(*[o[i] for o in outputs], axis=0)
                for i in range(len(outputs[0]))]
    elif outputs:
        outs = stack(*outputs, axis=0)
    else:
        outs = []
    return outs, lv


# ======================================================================
# optimizer update ops (reference: src/operator/optimizer_op.cc) —
# these are the fused kernels Trainer/Optimizer call per parameter.
# ======================================================================

@_register
def sgd_update(weight, grad, lr, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
               lazy_update=True, out=None):
    def fn(w, g):
        g = g * rescale_grad
        if clip_gradient >= 0:
            g = jnp.clip(g, -clip_gradient, clip_gradient)
        g = g + wd * w
        return w - lr * g
    new_w = apply_nary(fn, [weight, grad], name="sgd_update")
    target = out if out is not None else weight
    target._set_data(new_w._data)
    return target


@_register
def sgd_mom_update(weight, grad, mom, lr, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, lazy_update=True,
                   out=None):
    def fn(w, g, m):
        g = g * rescale_grad
        if clip_gradient >= 0:
            g = jnp.clip(g, -clip_gradient, clip_gradient)
        g = g + wd * w
        m_new = momentum * m - lr * g
        return (w + m_new, m_new)
    new_w, new_m = apply_nary(fn, [weight, grad, mom], n_out=2,
                              name="sgd_mom_update")
    mom._set_data(new_m._data)
    target = out if out is not None else weight
    target._set_data(new_w._data)
    return target


@_register
def adam_update(weight, grad, mean, var, lr, beta1=0.9, beta2=0.999,
                epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                lazy_update=True, out=None):
    def fn(w, g, m, v):
        g = g * rescale_grad
        if clip_gradient >= 0:
            g = jnp.clip(g, -clip_gradient, clip_gradient)
        g = g + wd * w
        m_new = beta1 * m + (1 - beta1) * g
        v_new = beta2 * v + (1 - beta2) * jnp.square(g)
        return (w - lr * m_new / (jnp.sqrt(v_new) + epsilon), m_new, v_new)
    new_w, new_m, new_v = apply_nary(fn, [weight, grad, mean, var], n_out=3,
                                     name="adam_update")
    mean._set_data(new_m._data)
    var._set_data(new_v._data)
    target = out if out is not None else weight
    target._set_data(new_w._data)
    return target


# ======================================================================
# misc
# ======================================================================

@_register
def shape_array(data):
    return apply_nary(lambda d: jnp.asarray(d.shape, jnp.int64), [data])


@_register
def size_array(data):
    return apply_nary(lambda d: jnp.asarray([d.size], jnp.int64), [data])


@_register
def diag(data, k=0):
    return apply_nary(lambda d: jnp.diag(d, k) if d.ndim <= 2
                      else jnp.diagonal(d, k), [data], name="diag")


@_register
def batch_take(a, indices):
    def fn(d, i):
        return jnp.take_along_axis(
            d, i.astype(jnp.int32).reshape(-1, 1), axis=1).squeeze(1)
    return apply_nary(fn, [a, _nd(indices, a)], name="batch_take")


@_register
def gather_positions(data, positions):
    """Pick rows at per-batch positions: data (B, L, C), positions (B, M)
    -> (B, M, C). The MLM-head gather (reference: gluonnlp BERT decoder
    uses gather_nd for this)."""
    def fn(d, p):
        return jnp.take_along_axis(
            d, p.astype(jnp.int32)[..., None], axis=1)
    return apply_nary(fn, [data, _nd(positions, data)],
                      name="gather_positions")


# ======================================================================
# index raveling (reference: src/operator/tensor/ravel.cc)
# ======================================================================

@_register
def ravel_multi_index(data, shape):
    """(ndim, n) coordinate rows -> flat indices for ``shape``
    (ravel.cc ravel_multi_index)."""
    shape = tuple(int(s) for s in shape)
    def fn(d):
        # index arithmetic in the widest available int: under MXTPU_INT64
        # (jax_enable_x64) flat indices past 2^31 stay exact — the
        # large-tensor mode's reason to exist
        idt = jnp.int64 if jax.config.jax_enable_x64 else jnp.int32
        strides = _np.cumprod((1,) + shape[:0:-1],
                              dtype=_np.int64)[::-1].copy()
        return jnp.sum(d.astype(idt) *
                       jnp.asarray(strides, idt)[:, None], axis=0)
    return apply_nary(fn, [data], name="ravel_multi_index")


@_register
def unravel_index(data, shape):
    """Flat indices -> (ndim, n) coordinate rows (ravel.cc
    unravel_index)."""
    shape = tuple(int(s) for s in shape)
    def fn(d):
        idt = jnp.int64 if jax.config.jax_enable_x64 else jnp.int32
        coords = jnp.unravel_index(d.astype(idt), shape)
        return jnp.stack(coords, axis=0)
    return apply_nary(fn, [data], name="unravel_index")


@_register
def khatri_rao(*args):
    """Column-wise Khatri-Rao product: (m,k) x (n,k) -> (m*n, k)
    (reference src/operator/contrib/krprod.cc)."""
    if not args:
        raise MXNetError("khatri_rao needs at least one matrix")
    def fn(*ms):
        out = ms[0]
        for m in ms[1:]:
            k = out.shape[1]
            out = jnp.einsum("ik,jk->ijk", out, m).reshape(-1, k)
        return out
    return apply_nary(fn, [_nd(a) for a in args], name="khatri_rao")


# ======================================================================
# spatial sampling (reference: src/operator/grid_generator.cc,
# bilinear_sampler.cc — the SpatialTransformer pair)
# ======================================================================

@_register
def GridGenerator(data, transform_type="affine", target_shape=None):
    """affine: (B, 6) thetas -> (B, 2, H, W) sampling grid in [-1, 1];
    warp: (B, 2, H, W) flow field -> grid. Reference grid_generator.cc."""
    if transform_type == "affine":
        if target_shape is None:
            raise MXNetError("GridGenerator(affine) needs target_shape")
        h, w = int(target_shape[0]), int(target_shape[1])
        def fn(theta):
            b = theta.shape[0]
            ys = jnp.linspace(-1.0, 1.0, h)
            xs = jnp.linspace(-1.0, 1.0, w)
            gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
            base = jnp.stack([gx.ravel(), gy.ravel(),
                              jnp.ones(h * w)])            # (3, H*W)
            t = theta.reshape(b, 2, 3).astype(jnp.float32)
            grid = jnp.einsum("bij,jn->bin", t, base)      # (B, 2, H*W)
            return grid.reshape(b, 2, h, w)
        return apply_nary(fn, [data], name="GridGenerator")
    if transform_type == "warp":
        def fn(flow):
            b, _, h, w = flow.shape
            ys = jnp.arange(h, dtype=jnp.float32)
            xs = jnp.arange(w, dtype=jnp.float32)
            gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
            x = (gx[None] + flow[:, 0]) * 2.0 / max(w - 1, 1) - 1.0
            y = (gy[None] + flow[:, 1]) * 2.0 / max(h - 1, 1) - 1.0
            return jnp.stack([x, y], axis=1)
        return apply_nary(fn, [data], name="GridGenerator")
    raise MXNetError(f"unknown transform_type {transform_type!r}")


@_register
def BilinearSampler(data, grid, cudnn_off=None):
    """Sample data (B, C, H, W) at grid (B, 2, Ho, Wo) ([-1,1] x/y),
    zero padding outside — reference bilinear_sampler.cc. Differentiable
    in both data and grid (jax.vjp through the gather)."""
    def fn(d, g):
        b, c, h, w = d.shape
        x = (g[:, 0] + 1.0) * (w - 1) / 2.0          # (B, Ho, Wo)
        y = (g[:, 1] + 1.0) * (h - 1) / 2.0
        x0 = jnp.floor(x); y0 = jnp.floor(y)
        # per-batch gather, vectorized with vmap
        def sample_one(dd, yy, xx):
            yi = jnp.clip(yy.astype(jnp.int32), 0, h - 1)
            xi = jnp.clip(xx.astype(jnp.int32), 0, w - 1)
            valid = ((yy >= 0) & (yy <= h - 1) &
                     (xx >= 0) & (xx <= w - 1)).astype(dd.dtype)
            return dd[:, yi, xi] * valid[None]        # (C, Ho, Wo)
        def one(dd, xx, yy, xx0, yy0):
            wx = xx - xx0
            wy = yy - yy0
            v00 = sample_one(dd, yy0, xx0)
            v01 = sample_one(dd, yy0, xx0 + 1)
            v10 = sample_one(dd, yy0 + 1, xx0)
            v11 = sample_one(dd, yy0 + 1, xx0 + 1)
            return (v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx +
                    v10 * wy * (1 - wx) + v11 * wy * wx)
        return jax.vmap(one)(d, x, y, x0, y0)
    return apply_nary(fn, [data, _nd(grid, data)], name="BilinearSampler")


# ======================================================================
# CTC loss (reference: src/operator/nn/ctc_loss.cc)
# ======================================================================

@_register
def ctc_loss(data, label, data_lengths=None, label_lengths=None,
             use_data_lengths=False, use_label_lengths=False,
             blank_label="first"):
    """Connectionist temporal classification loss.

    data: (T, B, C) pre-softmax activations; label: (B, L) padded with -1
    (or 0s beyond label_lengths). Returns per-example forward loss (B,).
    The alpha recursion (extended blank-interleaved label sequence, log
    space) runs as a lax.scan and is fully differentiable through jax
    autodiff — reference src/operator/nn/ctc_loss.cc (warpctc-free).
    """
    if blank_label not in ("first", "last"):
        raise MXNetError("blank_label must be 'first' or 'last'")
    NEG = -1e30

    def _one(logp, ext, skip_ok, t_len, l_len):
        """One example: logp (T, C) log-softmax, ext (S,) extended labels."""
        T = logp.shape[0]
        alpha0 = jnp.full(ext.shape, NEG, jnp.float32)
        alpha0 = alpha0.at[0].set(logp[0, ext[0]])
        alpha0 = alpha0.at[1].set(
            jnp.where(l_len > 0, logp[0, ext[1]], NEG))

        def step(alpha, xs):
            lp_t, t = xs
            a_prev = jnp.concatenate([jnp.full((1,), NEG), alpha[:-1]])
            a_prev2 = jnp.concatenate([jnp.full((2,), NEG), alpha[:-2]])
            a = jnp.logaddexp(alpha, a_prev)
            a = jnp.where(skip_ok, jnp.logaddexp(a, a_prev2), a)
            new = a + lp_t[ext]
            return jnp.where(t < t_len, new, alpha), None

        alpha, _ = lax.scan(step, alpha0,
                            (logp[1:], jnp.arange(1, T)))
        end = 2 * l_len                      # last blank of the used prefix
        a_last = jnp.take(alpha, end)
        a_last2 = jnp.where(l_len > 0,
                            jnp.take(alpha, jnp.maximum(end - 1, 0)), NEG)
        return -jnp.logaddexp(a_last, a_last2)

    def fn(d, lab, *lens):
        t, b, c = d.shape
        blank = 0 if blank_label == "first" else c - 1
        logp = jax.nn.log_softmax(
            jnp.transpose(d, (1, 0, 2)).astype(jnp.float32), axis=-1)
        lab = lab.astype(jnp.int32)
        # lens layout strictly follows the use_* flags (inputs are built
        # the same way below — a None length with the flag set raises)
        if use_label_lengths:
            l_len = lens[1 if use_data_lengths else 0].astype(jnp.int32)
        else:
            l_len = jnp.sum((lab > 0) if blank == 0 else (lab >= 0),
                            axis=1).astype(jnp.int32)
        if use_data_lengths:
            t_len = lens[0].astype(jnp.int32)
        else:
            t_len = jnp.full((b,), t, jnp.int32)
        lab = jnp.maximum(lab, 0)
        L = lab.shape[1]
        ext = jnp.full((b, 2 * L + 1), blank, jnp.int32)
        ext = ext.at[:, 1::2].set(lab)
        skip = jnp.zeros((b, 2 * L + 1), bool)
        skip = skip.at[:, 2:].set((ext[:, 2:] != blank) &
                                  (ext[:, 2:] != ext[:, :-2]))
        return jax.vmap(_one)(logp, ext, skip, t_len, l_len)

    inputs = [data, _nd(label, data)]
    if use_data_lengths:
        if data_lengths is None:
            raise MXNetError("use_data_lengths=True requires data_lengths")
        inputs.append(_nd(data_lengths, data))
    if use_label_lengths:
        if label_lengths is None:
            raise MXNetError(
                "use_label_lengths=True requires label_lengths")
        inputs.append(_nd(label_lengths, data))
    return apply_nary(fn, inputs, name="ctc_loss")


CTCLoss = ctc_loss
__all__.append("CTCLoss")


# ======================================================================
# fused multi-tensor optimizer ops (reference:
# src/operator/optimizer_op.cc multi_sgd_update / multi_sgd_mom_update,
# src/operator/contrib/multi_lamb.cc)
# ======================================================================

def _group_pairs(arrays, per_weight):
    n = len(arrays) // per_weight
    return [arrays[i * per_weight:(i + 1) * per_weight] for i in range(n)]


def _check_num_weights(name, groups, num_weights):
    """Validate the reference API's num_weights kwarg against the group
    count implied by the flat array list."""
    if num_weights is not None and num_weights != len(groups):
        raise MXNetError(f"{name}: num_weights {num_weights} != "
                         f"{len(groups)} weight groups passed")


@_register
def multi_sgd_update(*arrays, lrs, wds, rescale_grad=1.0,
                     clip_gradient=None, num_weights=None, out=None):
    """Fused group SGD: arrays = (w0, g0, w1, g1, ...). ONE dispatch /
    XLA program updates every weight (the reference's multi-tensor-apply);
    weights are updated in place on their handles and returned."""
    groups = _group_pairs(list(arrays), 2)
    _check_num_weights("multi_sgd_update", groups, num_weights)
    def fn(*flat):
        outs = []
        for i in range(0, len(flat), 2):
            w, g = flat[i], flat[i + 1]
            lr, wd = lrs[i // 2], wds[i // 2]
            g = g * rescale_grad
            if clip_gradient is not None and clip_gradient >= 0:
                g = jnp.clip(g, -clip_gradient, clip_gradient)
            outs.append(w - lr * (g + wd * w))
        # apply_nary with n_out=1 expects a bare array, not a 1-tuple
        return tuple(outs) if len(outs) > 1 else outs[0]
    updated = apply_nary(fn, list(arrays), n_out=len(groups),
                         name="multi_sgd_update")
    updated = updated if isinstance(updated, list) else [updated]
    for (w, _), nw in zip(groups, updated):
        w._set_data(nw.data)
    return updated


@_register
def multi_sgd_mom_update(*arrays, lrs, wds, momentum=0.9, rescale_grad=1.0,
                         clip_gradient=None, num_weights=None, out=None):
    """Fused group SGD+momentum: arrays = (w0, g0, m0, w1, g1, m1, ...);
    weights AND momenta update in place (optimizer_op.cc
    multi_sgd_mom_update)."""
    groups = _group_pairs(list(arrays), 3)
    _check_num_weights("multi_sgd_mom_update", groups, num_weights)
    def fn(*flat):
        outs = []
        for i in range(0, len(flat), 3):
            w, g, m = flat[i], flat[i + 1], flat[i + 2]
            lr, wd = lrs[i // 3], wds[i // 3]
            g = g * rescale_grad
            if clip_gradient is not None and clip_gradient >= 0:
                g = jnp.clip(g, -clip_gradient, clip_gradient)
            new_m = momentum * m - lr * (g + wd * w)
            outs.append(w + new_m)
            outs.append(new_m)
        return tuple(outs)
    updated = apply_nary(fn, list(arrays), n_out=2 * len(groups),
                         name="multi_sgd_mom_update")
    for gi, (w, _, m) in enumerate(groups):
        w._set_data(updated[2 * gi].data)
        m._set_data(updated[2 * gi + 1].data)
    return [updated[2 * i] for i in range(len(groups))]


@_register
def multi_lamb_update(*arrays, lrs, wds, beta1=0.9, beta2=0.999,
                      epsilon=1e-6, rescale_grad=1.0, clip_gradient=None,
                      step=1, lower_bound=None, upper_bound=None, out=None):
    """Fused group LAMB: arrays = (w0, g0, mean0, var0, ...); one XLA
    program for the whole group (contrib/multi_lamb.cc)."""
    groups = _group_pairs(list(arrays), 4)
    def fn(*flat):
        outs = []
        for i in range(0, len(flat), 4):
            w, g, mean, var = flat[i:i + 4]
            lr, wd = lrs[i // 4], wds[i // 4]
            g = g * rescale_grad
            if clip_gradient is not None and clip_gradient >= 0:
                g = jnp.clip(g, -clip_gradient, clip_gradient)
            new_mean = beta1 * mean + (1 - beta1) * g
            new_var = beta2 * var + (1 - beta2) * jnp.square(g)
            mhat = new_mean / (1 - beta1 ** step)
            vhat = new_var / (1 - beta2 ** step)
            upd = mhat / (jnp.sqrt(vhat) + epsilon) + wd * w
            wnorm = jnp.linalg.norm(w)
            unorm = jnp.linalg.norm(upd)
            ratio = jnp.where(
                (wnorm > 0) & (unorm > 0),
                wnorm / jnp.maximum(unorm, 1e-12), 1.0)
            if lower_bound is not None:
                ratio = jnp.maximum(ratio, lower_bound)
            if upper_bound is not None:
                ratio = jnp.minimum(ratio, upper_bound)
            outs.extend([w - lr * ratio * upd, new_mean, new_var])
        return tuple(outs)
    updated = apply_nary(fn, list(arrays), n_out=3 * len(groups),
                         name="multi_lamb_update")
    for gi, (w, _, mean, var) in enumerate(groups):
        w._set_data(updated[3 * gi].data)
        mean._set_data(updated[3 * gi + 1].data)
        var._set_data(updated[3 * gi + 2].data)
    return [updated[3 * i] for i in range(len(groups))]


@_register
def arange_like(data, start=0.0, step=1.0, repeat=1, ctx=None, axis=None):
    """arange shaped like ``data`` (or its ``axis`` length) — reference
    src/operator/tensor/init_op.cc (arange_like). ``repeat`` repeats each
    value WITHIN the same element count (the output always has data's
    shape / the axis length)."""
    def fn(d):
        n = d.shape[axis] if axis is not None else d.size
        dt = d.dtype if jnp.issubdtype(d.dtype, jnp.floating) or \
            jnp.issubdtype(d.dtype, jnp.integer) else jnp.float32
        vals = (start + step * (jnp.arange(n) // repeat)).astype(dt)
        return vals if axis is not None else vals.reshape(d.shape)
    return apply_nary(fn, [data], name="arange_like")


# ======================================================================
# remaining classic nn ops (reference: src/operator/{pad,lrn,correlation,
# upsampling,crop}.cc, nn/group_norm, tensor/broadcast_reduce_op)
# ======================================================================

@_register
def Pad(data, mode="constant", pad_width=(), constant_value=0.0):
    """N-d padding (reference src/operator/pad.cc): pad_width is a flat
    (before, after) pair per axis; mode constant|edge|reflect."""
    pw = tuple(int(p) for p in pad_width)
    if len(pw) != 2 * len(data.shape):
        raise MXNetError(f"pad_width needs 2 entries per axis, got "
                         f"{len(pw)} for ndim {len(data.shape)}")
    pairs = tuple((pw[2 * i], pw[2 * i + 1]) for i in range(len(pw) // 2))
    jmode = {"constant": "constant", "edge": "edge",
             "reflect": "reflect"}.get(mode)
    if jmode is None:
        raise MXNetError(f"unknown pad mode {mode!r}")
    def fn(d):
        if jmode == "constant":
            return jnp.pad(d, pairs, mode="constant",
                           constant_values=constant_value)
        return jnp.pad(d, pairs, mode=jmode)
    return apply_nary(fn, [data], name="Pad")


pad = Pad
__all__.append("pad")


@_register
def argmax_channel(data):
    """argmax over the channel axis (axis 1), float output like the
    reference (broadcast_reduce_op_index.cc argmax_channel)."""
    return apply_nary(lambda d: jnp.argmax(d, axis=1).astype(jnp.float32),
                      [data], name="argmax_channel")


@_register
def GroupNorm(data, gamma, beta, num_groups=1, eps=1e-5):
    """Group normalization over (C//G)-channel groups of NCHW input
    (reference src/operator/nn/group_norm.cc)."""
    def fn(d, g, b):
        n, c = d.shape[0], d.shape[1]
        rest = d.shape[2:]
        x = d.reshape(n, num_groups, c // num_groups, *rest)
        axes = tuple(range(2, x.ndim))
        mean = jnp.mean(x, axis=axes, keepdims=True)
        var = jnp.var(x, axis=axes, keepdims=True)
        x = (x - mean) / jnp.sqrt(var + eps)
        x = x.reshape(d.shape)
        shape = (1, c) + (1,) * len(rest)
        return x * g.reshape(shape) + b.reshape(shape)
    return apply_nary(fn, [data, _nd(gamma, data), _nd(beta, data)],
                      name="GroupNorm")


@_register
def LRN(data, alpha=1e-4, beta=0.75, knorm=2.0, nsize=5):
    """Local response normalization across channels (reference
    src/operator/lrn.cc — the AlexNet-era op)."""
    def fn(d):
        sq = jnp.square(d)
        half = nsize // 2
        padded = jnp.pad(sq, ((0, 0), (half, half)) +
                         ((0, 0),) * (d.ndim - 2))
        acc = jnp.zeros_like(d)
        for i in range(nsize):
            acc = acc + lax.slice_in_dim(padded, i, i + d.shape[1], axis=1)
        return d / jnp.power(knorm + alpha * acc / nsize, beta)
    return apply_nary(fn, [data], name="LRN")


@_register
def UpSampling(*data, scale=2, sample_type="nearest", num_filter=0,
               num_args=1):
    """Spatial upsampling of NCHW inputs (reference
    src/operator/upsampling.cc): nearest or bilinear; multiple inputs
    are each upsampled to the FIRST input's target size and concatenated
    along channels (the FCN skip-connection pattern)."""
    def one(d, th, tw):
        n, c, h, w = d.shape
        if sample_type == "nearest" and th == h * scale and tw == w * scale:
            return jnp.repeat(jnp.repeat(d, scale, axis=2), scale, axis=3)
        import jax.image
        method = "nearest" if sample_type == "nearest" else "bilinear"
        return jax.image.resize(d, (n, c, th, tw), method=method)

    def fn(*ds):
        th = ds[0].shape[2] * scale
        tw = ds[0].shape[3] * scale
        outs = [one(d, th, tw) for d in ds]
        return outs[0] if len(outs) == 1 else \
            jnp.concatenate(outs, axis=1)
    return apply_nary(fn, [_nd(d) for d in data], name="UpSampling")


@_register
def Crop(*data, offset=(0, 0), h_w=(0, 0), num_args=1, center_crop=False):
    """Crop the first NCHW input to the size of the second (or to h_w)
    (reference src/operator/crop.cc)."""
    x = data[0]
    if num_args == 2 and len(data) > 1:
        th, tw = data[1].shape[2], data[1].shape[3]
    else:
        th, tw = h_w
    if th <= 0 or tw <= 0:
        raise MXNetError("Crop needs a reference input (num_args=2) or a "
                         f"positive h_w, got {(th, tw)}")
    h, w = x.shape[2], x.shape[3]
    oy, ox = ((h - th) // 2, (w - tw) // 2) if center_crop else offset
    if oy < 0 or ox < 0 or oy + th > h or ox + tw > w:
        raise MXNetError(f"Crop window {(th, tw)} at offset {(oy, ox)} "
                         f"exceeds input {(h, w)}")
    def fn(d):
        return d[:, :, oy:oy + th, ox:ox + tw]
    return apply_nary(fn, [x], name="Crop")


@_register
def Correlation(data1, data2, kernel_size=1, max_displacement=4, stride1=1,
                stride2=1, pad_size=4, is_multiply=True):
    """Correlation layer (reference src/operator/correlation.cc, the
    FlowNet op): per-displacement mean inner product of two feature maps.
    Vectorized as one shifted-multiply per displacement — XLA fuses the
    window sums; no per-pixel loops."""
    if kernel_size != 1:
        raise MXNetError("Correlation: only kernel_size=1 is supported")
    def fn(a, b):
        n, c, h, w = a.shape
        bp = jnp.pad(b, ((0, 0), (0, 0), (pad_size, pad_size),
                         (pad_size, pad_size)))
        d = max_displacement
        outs = []
        for dy in range(-d, d + 1, stride2):
            for dx in range(-d, d + 1, stride2):
                oy, ox = dy + pad_size, dx + pad_size
                shifted = lax.dynamic_slice(
                    bp, (0, 0, oy, ox), (n, c, h, w))
                if is_multiply:
                    prod = a * shifted
                else:
                    prod = jnp.abs(a - shifted)
                outs.append(jnp.mean(prod, axis=1))
        out = jnp.stack(outs, axis=1)           # (N, D*D, H, W)
        if stride1 > 1:
            out = out[:, :, ::stride1, ::stride1]
        return out
    return apply_nary(fn, [data1, _nd(data2, data1)], name="Correlation")


# ======================================================================
# round-3 op tail: activations, numpy-parity, sample_*, legacy outputs
# (reference: src/operator/tensor/elemwise_unary_op*.cc, matrix_op.cc,
#  src/operator/random/sample_op.cc, src/operator/regression_output*.cc)
# ======================================================================

mish = _unary_factory("mish", lambda d: d * jnp.tanh(jax.nn.softplus(d)))
# erf-based (exact) gelu to match the reference and LeakyReLU(act_type=gelu)
gelu = _unary_factory("gelu", lambda d: jax.nn.gelu(d, approximate=False))
rcbrt = _unary_factory("rcbrt", lambda d: 1.0 / jnp.cbrt(d))
relu6 = _unary_factory("relu6", lambda d: jnp.clip(d, 0.0, 6.0))
selu = _unary_factory("selu", jax.nn.selu)
softrelu = _unary_factory("softrelu", jax.nn.softplus)
log_sigmoid = _unary_factory("log_sigmoid", jax.nn.log_sigmoid)
silu = _unary_factory("silu", jax.nn.silu)
swish = _unary_factory("swish", jax.nn.silu)
isnan = _unary_factory("isnan", jnp.isnan)
isinf = _unary_factory("isinf", jnp.isinf)
isfinite = _unary_factory("isfinite", jnp.isfinite)


@_register
def elu(data, alpha=1.0):
    """ELU (reference LeakyReLU act_type='elu')."""
    return apply_nary(lambda d: jnp.where(d > 0, d, alpha * jnp.expm1(d)),
                      [data], name="elu")


def _binary_factory(name, jfn):
    def op(lhs, rhs, **kwargs):
        return apply_nary(jfn, [lhs, _nd(rhs, lhs)], name=name)
    op.__name__ = name
    op.__doc__ = f"Elementwise {name}. Reference: src/operator/tensor/elemwise_binary_op_basic.cc."
    return _register(op)


fmod = _binary_factory("fmod", jnp.fmod)
mod = _binary_factory("mod", jnp.fmod)   # C fmod semantics, see `modulo`
floor_divide = _binary_factory("floor_divide", jnp.floor_divide)
true_divide = _binary_factory("true_divide", jnp.true_divide)
outer = _binary_factory("outer", jnp.outer)
inner = _binary_factory("inner", jnp.inner)
vdot = _binary_factory("vdot", jnp.vdot)
kron = _binary_factory("kron", jnp.kron)
matmul = _binary_factory("matmul", jnp.matmul)


@_register
def tensordot(a, b, axes=2):
    return apply_nary(lambda x, y: jnp.tensordot(x, y, axes=axes),
                      [a, _nd(b, a)], name="tensordot")


@_register
def cumsum(a, axis=None, dtype=None):
    return apply_nary(
        lambda d: jnp.cumsum(d, axis=axis,
                             dtype=_dtype_of(dtype) if dtype else None),
        [a], name="cumsum")


@_register
def cumprod(a, axis=None):
    return apply_nary(lambda d: jnp.cumprod(d, axis=axis), [a],
                      name="cumprod")


@_register
def trace(data, offset=0, axis1=0, axis2=1):
    return apply_nary(lambda d: jnp.trace(d, offset, axis1, axis2), [data],
                      name="trace")


@_register
def rot90(data, k=1, axes=(0, 1)):
    return apply_nary(lambda d: jnp.rot90(d, k, axes), [data], name="rot90")


@_register
def tril(data, k=0):
    return apply_nary(lambda d: jnp.tril(d, k), [data], name="tril")


@_register
def triu(data, k=0):
    return apply_nary(lambda d: jnp.triu(d, k), [data], name="triu")


@_register
def full_like(data, fill_value, dtype=None):
    return apply_nary(
        lambda d: jnp.full_like(d, fill_value,
                                dtype=_dtype_of(dtype) if dtype else None),
        [data], name="full_like")


@_register
def masked_softmax(data, mask, axis=-1, temperature=1.0):
    """Softmax over positions where mask is true; masked positions get 0
    probability (reference src/operator/nn/softmax.cc masked_softmax)."""
    def fn(d, m):
        neg = jnp.finfo(d.dtype if jnp.issubdtype(d.dtype, jnp.floating)
                        else jnp.float32).min
        z = jnp.where(m.astype(bool), d / temperature, neg)
        p = jax.nn.softmax(z, axis=axis)
        return jnp.where(m.astype(bool), p, jnp.zeros((), p.dtype))
    return apply_nary(fn, [data, _nd(mask, data)], name="masked_softmax")


@_register
def meshgrid(*arrays, indexing="xy"):
    arrs = [_nd(a) for a in arrays]
    if len(arrs) == 1:   # numpy semantics: always a list, even for one input
        return [apply_nary(
            lambda d: jnp.meshgrid(d, indexing=indexing)[0], arrs,
            name="meshgrid")]
    return apply_nary(lambda *ds: tuple(jnp.meshgrid(*ds, indexing=indexing)),
                      arrs, n_out=len(arrs), name="meshgrid")


def _stack_factory(name, jfn):
    def op(*arrays, **kwargs):
        if len(arrays) == 1 and isinstance(arrays[0], (list, tuple)):
            arrays = tuple(arrays[0])
        arrs = [_nd(a) for a in arrays]
        return apply_nary(lambda *ds: jfn(ds), arrs, name=name)
    op.__name__ = name
    op.__doc__ = f"numpy-style {name}."
    return _register(op)


hstack = _stack_factory("hstack", jnp.hstack)
vstack = _stack_factory("vstack", jnp.vstack)
dstack = _stack_factory("dstack", jnp.dstack)


def _np_split_factory(name, jfn):
    def op(data, indices_or_sections):
        n = indices_or_sections if isinstance(indices_or_sections, int) \
            else len(indices_or_sections) + 1
        if n == 1:   # numpy semantics: a one-element list
            return [apply_nary(lambda d: jfn(d, indices_or_sections)[0],
                               [data], name=name)]
        return apply_nary(lambda d: tuple(jfn(d, indices_or_sections)),
                          [data], n_out=n, name=name)
    op.__name__ = name
    op.__doc__ = f"numpy-style {name}."
    return _register(op)


hsplit = _np_split_factory("hsplit", jnp.hsplit)
vsplit = _np_split_factory("vsplit", jnp.vsplit)


@_register
def histogram(data, bins=10, range=None):
    """Histogram counts + bin edges. Not differentiable (counts are
    integer, so the input is detached); runs eagerly on device."""
    data = _nd(data).detach()
    rng = range
    def fn(d):
        return jnp.histogram(d, bins=bins, range=rng)
    return apply_nary(fn, [data], n_out=2, name="histogram")


@_register
def bincount(data, weights=None, minlength=0):
    """Integer-count op: data-dependent output size, eager only; inputs are
    detached (counts are not differentiable w.r.t. indices)."""
    data = _nd(data).detach()
    if weights is None:
        return apply_nary(
            lambda d: jnp.bincount(d.astype(jnp.int32), minlength=minlength,
                                   length=None),
            [data], name="bincount")
    return apply_nary(
        lambda d, w: jnp.bincount(d.astype(jnp.int32), w,
                                  minlength=minlength),
        [data, _nd(weights, data)], name="bincount")


@_register
def unique(data):
    """Sorted unique values. Output size is data-dependent — eager only
    (inside jit/hybridize the size cannot be static); not differentiable, so
    the input is detached from any open tape; reference mx.np.unique."""
    return apply_nary(lambda d: jnp.unique(d), [_nd(data).detach()],
                      name="unique")


# ---- sample_* family: per-element distribution parameters ----
# reference src/operator/random/sample_op.cc: output shape = params.shape
# + shape; each output element drawn from its own parameterization

def _sample_shape(pshape, shape):
    if shape is None:
        return tuple(pshape)
    extra = (shape,) if isinstance(shape, int) else tuple(shape)
    return tuple(pshape) + extra


@_register
def sample_uniform(low, high, shape=None, dtype=None, ctx=None):
    from . import random as _rnd
    low = _nd(low)
    high = _nd(high, low)
    out_shape = _sample_shape(low.shape, shape)
    def fn(lo, hi):
        u = jax.random.uniform(_rnd.next_key(), out_shape,
                               _dtype_of(dtype) if dtype else jnp.float32)
        nd_ = lo.ndim
        bshape = lo.shape + (1,) * (len(out_shape) - nd_)
        return lo.reshape(bshape) + u * (hi - lo).reshape(bshape)
    return apply_nary(fn, [low, high], name="sample_uniform")


@_register
def sample_normal(mu, sigma, shape=None, dtype=None, ctx=None):
    from . import random as _rnd
    mu = _nd(mu)
    sigma = _nd(sigma, mu)
    out_shape = _sample_shape(mu.shape, shape)
    def fn(m, s):
        z = jax.random.normal(_rnd.next_key(), out_shape,
                              _dtype_of(dtype) if dtype else jnp.float32)
        bshape = m.shape + (1,) * (len(out_shape) - m.ndim)
        return m.reshape(bshape) + z * s.reshape(bshape)
    return apply_nary(fn, [mu, sigma], name="sample_normal")


@_register
def sample_gamma(alpha, beta, shape=None, dtype=None, ctx=None):
    from . import random as _rnd
    alpha = _nd(alpha)
    beta = _nd(beta, alpha)
    out_shape = _sample_shape(alpha.shape, shape)
    def fn(a, b):
        bshape = a.shape + (1,) * (len(out_shape) - a.ndim)
        g = jax.random.gamma(_rnd.next_key(),
                             jnp.broadcast_to(a.reshape(bshape), out_shape),
                             dtype=_dtype_of(dtype) if dtype else jnp.float32)
        return g * b.reshape(bshape)
    return apply_nary(fn, [alpha, beta], name="sample_gamma")


@_register
def sample_exponential(lam, shape=None, dtype=None, ctx=None):
    from . import random as _rnd
    lam = _nd(lam)
    out_shape = _sample_shape(lam.shape, shape)
    def fn(l):
        e = jax.random.exponential(
            _rnd.next_key(), out_shape,
            _dtype_of(dtype) if dtype else jnp.float32)
        return e / l.reshape(l.shape + (1,) * (len(out_shape) - l.ndim))
    return apply_nary(fn, [lam], name="sample_exponential")


@_register
def sample_poisson(lam, shape=None, dtype=None, ctx=None):
    from . import random as _rnd
    lam = _nd(lam)
    out_shape = _sample_shape(lam.shape, shape)
    def fn(l):
        lb = jnp.broadcast_to(
            l.reshape(l.shape + (1,) * (len(out_shape) - l.ndim)), out_shape)
        p = jax.random.poisson(_rnd.next_key(), lb, shape=out_shape)
        return p.astype(_dtype_of(dtype) if dtype else jnp.float32)
    return apply_nary(fn, [lam], name="sample_poisson")


@_register
def sample_multinomial(data, shape=None, get_prob=False, dtype="int32"):
    """Draw from rows of probabilities; with get_prob=True also return the
    log-likelihood of each draw for REINFORCE-style training (reference
    src/operator/random/sample_op.cc sample_multinomial: output shape is
    data.shape[:-1] + shape)."""
    from . import random as _rnd
    data = _nd(data)
    extra = () if shape is None else (
        (shape,) if isinstance(shape, int) else tuple(shape))
    n = int(_np.prod(extra)) if extra else 1
    def fn(p):
        logits = jnp.log(jnp.maximum(p, 1e-30))
        draws = jax.random.categorical(
            _rnd.next_key(), logits, axis=-1, shape=(n,) + p.shape[:-1])
        draws = jnp.moveaxis(draws, 0, -1)          # (..., n)
        out_shape = p.shape[:-1] + extra
        out = draws.reshape(out_shape).astype(_dtype_of(dtype))
        if not get_prob:
            return out
        logp = jnp.take_along_axis(
            jnp.broadcast_to(logits[..., None, :],
                             p.shape[:-1] + (n, p.shape[-1])),
            draws[..., :, None].astype(jnp.int32), axis=-1)
        return out, logp[..., 0].reshape(out_shape).astype(p.dtype)
    return apply_nary(fn, [data], n_out=2 if get_prob else 1,
                      name="sample_multinomial")


def random_uniform(low=0.0, high=1.0, shape=None, dtype=None, ctx=None):
    """Alias of mx.nd.random.uniform (reference _random_uniform)."""
    from . import random as _rnd
    return _rnd.uniform(low, high, shape, dtype, ctx)


def random_normal(loc=0.0, scale=1.0, shape=None, dtype=None, ctx=None):
    """Alias of mx.nd.random.normal (reference _random_normal)."""
    from . import random as _rnd
    return _rnd.normal(loc, scale, shape, dtype, ctx)


__all__ += ["random_uniform", "random_normal"]


# ---- legacy Module-era output ops: forward=identity, custom backward ----
# reference src/operator/regression_output{,-inl}.h, svm_output.cc,
# make_loss.cc: backward IGNORES the incoming cotangent and emits the
# op-defined gradient scaled by grad_scale

def _output_op(name, grad_fn):
    def op(data, label, grad_scale=1.0):
        label = _nd(label, data)

        @jax.custom_vjp
        def fwd(d, l):
            return d

        def fwd_fwd(d, l):
            return d, (d, l)

        def fwd_bwd(res, g):
            d, l = res
            return (grad_fn(d, l, grad_scale).astype(d.dtype),
                    jnp.zeros_like(l))

        fwd.defvjp(fwd_fwd, fwd_bwd)
        return apply_nary(fwd, [data, label], name=name)
    op.__name__ = name
    op.__doc__ = (f"{name} (reference src/operator/): identity forward; "
                  "backward is the op-defined gradient, replacing the "
                  "incoming cotangent (legacy Module-era loss op).")
    return _register(op)


def _linreg_grad(d, l, scale):
    return (d - l.reshape(d.shape)) * scale


def _maereg_grad(d, l, scale):
    return jnp.sign(d - l.reshape(d.shape)) * scale


LinearRegressionOutput = _output_op("LinearRegressionOutput", _linreg_grad)
MAERegressionOutput = _output_op("MAERegressionOutput", _maereg_grad)


@_register
def LogisticRegressionOutput(data, label, grad_scale=1.0):
    """Reference src/operator/regression_output.cc (LogisticRegressionOutput):
    forward = sigmoid(data); backward w.r.t. data = (out - label)*grad_scale,
    replacing the incoming cotangent (legacy Module-era loss op)."""
    label = _nd(label, data)

    @jax.custom_vjp
    def fwd(d, l):
        return jax.nn.sigmoid(d)

    def fwd_fwd(d, l):
        out = jax.nn.sigmoid(d)
        return out, (out, l)

    def fwd_bwd(res, g):
        out, l = res
        return (((out - l.reshape(out.shape)) * grad_scale).astype(out.dtype),
                jnp.zeros_like(l))

    fwd.defvjp(fwd_fwd, fwd_bwd)
    return apply_nary(fwd, [data, label], name="LogisticRegressionOutput")


def _svm_grad(d, l, scale, margin=1.0, regularization_coefficient=1.0,
              use_linear=False):
    lab = l.astype(jnp.int32)
    onehot = jax.nn.one_hot(lab, d.shape[-1], dtype=d.dtype)
    signed = jnp.where(onehot > 0, -d, d)
    viol = (margin + signed) > 0
    if use_linear:
        g = jnp.where(viol, jnp.where(onehot > 0, -1.0, 1.0), 0.0)
    else:
        g = jnp.where(viol, 2.0 * (margin + signed) *
                      jnp.where(onehot > 0, -1.0, 1.0), 0.0)
    return g * scale * regularization_coefficient


@_register
def SVMOutput(data, label, margin=1.0, regularization_coefficient=1.0,
              use_linear=False, grad_scale=1.0):
    """Hinge-loss output op (reference src/operator/svm_output.cc):
    identity forward, margin-violation gradient backward."""
    label = _nd(label, data)

    @jax.custom_vjp
    def fwd(d, l):
        return d

    def fwd_fwd(d, l):
        return d, (d, l)

    def fwd_bwd(res, g):
        d, l = res
        return (_svm_grad(d, l, grad_scale, margin,
                          regularization_coefficient,
                          use_linear).astype(d.dtype),
                jnp.zeros_like(l))

    fwd.defvjp(fwd_fwd, fwd_bwd)
    return apply_nary(fwd, [data, label], name="SVMOutput")


@_register
def im2col(data, kernel, stride=None, dilate=None, pad=None):
    """Unfold conv patches to a (N, C*prod(kernel), L) matrix (reference
    src/operator/nn/im2col.h via the im2col op). Lowered to
    lax.conv_general_dilated_patches so XLA emits one gather-free windowed
    read; column order matches the reference (channel-major, then kernel
    positions row-major, spatial L last)."""
    ndim = len(kernel)
    stride = tuple(stride) if stride else (1,) * ndim
    dilate = tuple(dilate) if dilate else (1,) * ndim
    pad_ = tuple(pad) if pad else (0,) * ndim
    def fn(d):
        patches = lax.conv_general_dilated_patches(
            d, filter_shape=tuple(kernel), window_strides=stride,
            padding=[(p, p) for p in pad_], rhs_dilation=dilate)
        # patches: (N, C*prod(k), *out_spatial) already channel-major
        n = patches.shape[0]
        c = patches.shape[1]
        return patches.reshape(n, c, -1)
    return apply_nary(fn, [data], name="im2col")


@_register
def col2im(data, output_size, kernel, stride=None, dilate=None, pad=None):
    """Fold a (N, C*prod(kernel), L) matrix back to an image, summing
    overlapping patches (reference col2im op) — implemented as the exact
    linear transpose of im2col via jax.linear_transpose, so the pair is
    adjoint by construction."""
    ndim = len(kernel)
    stride_ = tuple(stride) if stride else (1,) * ndim
    dilate_ = tuple(dilate) if dilate else (1,) * ndim
    pad_ = tuple(pad) if pad else (0,) * ndim
    out_sp = (output_size,) * ndim if isinstance(output_size, int) \
        else tuple(output_size)
    def fn(cols):
        n = cols.shape[0]
        ck = cols.shape[1]
        c = ck // int(_np.prod(kernel))
        img_shape = (n, c) + out_sp
        def unfold(img):
            p = lax.conv_general_dilated_patches(
                img, filter_shape=tuple(kernel), window_strides=stride_,
                padding=[(p_, p_) for p_ in pad_], rhs_dilation=dilate_)
            return p.reshape(n, ck, -1)
        img0 = jnp.zeros(img_shape, cols.dtype)
        transpose = jax.linear_transpose(unfold, img0)
        (img,) = transpose(cols)
        return img
    return apply_nary(fn, [data], name="col2im")


# ======================================================================
# bitwise / integer elementwise (reference: mx.np bitwise ops +
# src/operator/tensor/elemwise_binary_op_logic.cc family)
# ======================================================================

def _int_binary_factory(name, jfn):
    """Integer-only binary ops: a Python-scalar rhs must NOT go through
    _nd (which builds a float32 NDArray jax would reject) — pass it raw
    so jax weak-types it to the lhs integer dtype."""
    def op(lhs, rhs, **kwargs):
        if isinstance(rhs, NDArray):
            return apply_nary(jfn, [lhs, rhs], name=name)
        return apply_nary(lambda a: jfn(a, rhs), [lhs], name=name)
    op.__name__ = name
    op.__doc__ = (f"Elementwise {name}. Reference: mx.np bitwise/int ops "
                  "(src/operator/tensor/elemwise_binary_op_logic.cc "
                  "family).")
    return _register(op)


bitwise_and = _int_binary_factory("bitwise_and", jnp.bitwise_and)
bitwise_or = _int_binary_factory("bitwise_or", jnp.bitwise_or)
bitwise_xor = _int_binary_factory("bitwise_xor", jnp.bitwise_xor)
left_shift = _int_binary_factory("left_shift", jnp.left_shift)
right_shift = _int_binary_factory("right_shift", jnp.right_shift)
lcm = _int_binary_factory("lcm", jnp.lcm)
gcd = _int_binary_factory("gcd", jnp.gcd)


@_register
def bitwise_not(data):
    return apply_nary(jnp.bitwise_not, [data], name="bitwise_not")


invert = bitwise_not
__all__.append("invert")


@_register
def isposinf(data):
    return apply_nary(lambda d: jnp.isposinf(d).astype(jnp.float32), [data],
                      name="isposinf")


@_register
def isneginf(data):
    return apply_nary(lambda d: jnp.isneginf(d).astype(jnp.float32), [data],
                      name="isneginf")


@_register
def nan_to_num(data, copy=True, nan=0.0, posinf=None, neginf=None):
    out = apply_nary(
        lambda d: jnp.nan_to_num(d, nan=nan, posinf=posinf, neginf=neginf),
        [data], name="nan_to_num")
    if not copy:
        # reference copy=False mutates the input in place
        data._set_data(out._data)
        return data
    return out


@_register
def ediff1d(data, to_end=None, to_begin=None):
    def fn(d):
        out = jnp.diff(d.ravel())
        parts = []
        if to_begin is not None:
            parts.append(jnp.atleast_1d(jnp.asarray(to_begin, out.dtype))
                         .ravel())
        parts.append(out)
        if to_end is not None:
            parts.append(jnp.atleast_1d(jnp.asarray(to_end, out.dtype))
                         .ravel())
        return jnp.concatenate(parts) if len(parts) > 1 else out
    return apply_nary(fn, [data], name="ediff1d")


@_register
def interp(x, xp, fp, left=None, right=None):
    def fn(a, b, c):
        return jnp.interp(a, b, c, left=left, right=right)
    return apply_nary(fn, [x, _nd(xp, x), _nd(fp, x)], name="interp")


@_register
def polyval(p, x):
    def fn(pp, xx):
        return jnp.polyval(pp, xx)
    return apply_nary(fn, [_nd(p, x), x], name="polyval")


@_register
def divmod(lhs, rhs):   # noqa: A001 — reference op name
    def fn(a, b):
        q = jnp.floor_divide(a, b)
        return q, a - q * b
    return apply_nary(fn, [lhs, _nd(rhs, lhs)], n_out=2, name="divmod")


@_register
def digitize(data, bins, right=False):
    def fn(d, b):
        return jnp.digitize(d, b, right=right).astype(jnp.int64)
    return apply_nary(fn, [data, _nd(bins, data)], name="digitize")


@_register
def searchsorted(a, v, side="left", sorter=None):
    if sorter is not None:
        raise MXNetError("searchsorted: sorter is not supported; "
                         "pre-sort the input")
    def fn(aa, vv):
        return jnp.searchsorted(aa, vv, side=side).astype(jnp.int64)
    return apply_nary(fn, [a, _nd(v, a)], name="searchsorted")


# ======================================================================
# random_pdf_* family (reference: src/operator/random/pdf_op.cc) —
# pdf of `sample` under per-row distribution parameters. Parameter
# arrays have shape S; samples have shape S + (n,) (dirichlet:
# alpha S + (k,), sample S + (n, k)). All support is_log.
# ======================================================================

def _pdf_op(name, logpdf_fn, n_params, event_dims=0):
    def op(sample, *params, is_log=False):
        if len(params) != n_params:
            raise MXNetError(f"{name} expects {n_params} parameter "
                             f"array(s), got {len(params)}")

        def fn(s, *ps):
            # parameters broadcast over the trailing sample axis (for
            # dirichlet the event axis stays rightmost: insert before it)
            axis = -1 - event_dims
            ps = [jnp.expand_dims(p, axis) for p in ps]
            lp = logpdf_fn(s, *ps)
            return lp if is_log else jnp.exp(lp)
        return apply_nary(fn, [sample] + [_nd(p, sample) for p in params],
                          name=name)
    op.__name__ = name
    op.__doc__ = (f"{name}(sample, params..., is_log=False) — reference "
                  "src/operator/random/pdf_op.cc; grads via jax.vjp.")
    return _register(op)


def _lgamma(x):
    return lax.lgamma(x.astype(jnp.float32))


random_pdf_uniform = _pdf_op(
    "random_pdf_uniform",
    lambda s, low, high: jnp.where(
        (s >= low) & (s <= high), -jnp.log(high - low), -jnp.inf), 2)

random_pdf_normal = _pdf_op(
    "random_pdf_normal",
    lambda s, mu, sigma: -0.5 * jnp.square((s - mu) / sigma)
    - jnp.log(sigma) - 0.5 * math.log(2 * math.pi), 2)

random_pdf_gamma = _pdf_op(
    "random_pdf_gamma",
    lambda s, alpha, beta: (alpha - 1) * jnp.log(s) - s * beta
    + alpha * jnp.log(beta) - _lgamma(alpha), 2)

random_pdf_exponential = _pdf_op(
    "random_pdf_exponential",
    lambda s, lam: jnp.log(lam) - lam * s, 1)

random_pdf_poisson = _pdf_op(
    "random_pdf_poisson",
    lambda s, lam: s * jnp.log(lam) - lam - _lgamma(s + 1), 1)

random_pdf_negative_binomial = _pdf_op(
    "random_pdf_negative_binomial",
    lambda s, k, p: _lgamma(s + k) - _lgamma(s + 1) - _lgamma(k)
    + k * jnp.log(p) + s * jnp.log1p(-p), 2)


def _gnb_logpdf(s, mu, alpha):
    # generalized negative binomial in (mu, alpha) parametrization
    # (reference pdf_op.cc): r = 1/alpha, p = r/(r+mu)
    r = 1.0 / alpha
    p = r / (r + mu)
    return (_lgamma(s + r) - _lgamma(s + 1) - _lgamma(r)
            + r * jnp.log(p) + s * jnp.log1p(-p))


random_pdf_generalized_negative_binomial = _pdf_op(
    "random_pdf_generalized_negative_binomial", _gnb_logpdf, 2)


def _dirichlet_logpdf(s, alpha):
    # s: (..., n, k), alpha broadcast (..., 1, k)
    return (jnp.sum((alpha - 1) * jnp.log(s), axis=-1)
            + _lgamma(jnp.sum(alpha, axis=-1))
            - jnp.sum(_lgamma(alpha), axis=-1))


random_pdf_dirichlet = _pdf_op(
    "random_pdf_dirichlet", _dirichlet_logpdf, 1, event_dims=1)


# ======================================================================
# optimizer update-op tail (reference: src/operator/optimizer_op.cc) —
# raw op-level entry points mirroring the fused kernels Optimizer uses.
# All mutate `weight` (and state) in place and return the weight handle,
# matching the reference's out=weight convention.
# ======================================================================

def _prep_grad(g, w, wd, rescale_grad, clip_gradient):
    g = g * rescale_grad
    if clip_gradient is not None and clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    return g + wd * w


@_register
def signsgd_update(weight, grad, lr, wd=0.0, rescale_grad=1.0,
                   clip_gradient=-1.0, out=None):
    def fn(w, g):
        g = g * rescale_grad
        if clip_gradient >= 0:
            g = jnp.clip(g, -clip_gradient, clip_gradient)
        return (1 - lr * wd) * w - lr * jnp.sign(g)
    new_w = apply_nary(fn, [weight, grad], name="signsgd_update")
    target = out if out is not None else weight
    target._set_data(new_w._data)
    return target


@_register
def signum_update(weight, grad, mom, lr, momentum=0.0, wd=0.0,
                  rescale_grad=1.0, clip_gradient=-1.0, wd_lh=0.0, out=None):
    def fn(w, g, m):
        g = _prep_grad(g, w, wd, rescale_grad, clip_gradient)
        m_new = momentum * m - (1 - momentum) * g
        return ((1 - lr * wd_lh) * w + lr * jnp.sign(m_new), m_new)
    new_w, new_m = apply_nary(fn, [weight, grad, mom], n_out=2,
                              name="signum_update")
    mom._set_data(new_m._data)
    target = out if out is not None else weight
    target._set_data(new_w._data)
    return target


@_register
def rmsprop_update(weight, grad, n, lr, gamma1=0.95, epsilon=1e-8, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, clip_weights=-1.0,
                   out=None):
    def fn(w, g, nn_):
        g = _prep_grad(g, w, wd, rescale_grad, clip_gradient)
        n_new = gamma1 * nn_ + (1 - gamma1) * jnp.square(g)
        w_new = w - lr * g / (jnp.sqrt(n_new) + epsilon)
        if clip_weights > 0:
            w_new = jnp.clip(w_new, -clip_weights, clip_weights)
        return (w_new, n_new)
    new_w, new_n = apply_nary(fn, [weight, grad, n], n_out=2,
                              name="rmsprop_update")
    n._set_data(new_n._data)
    target = out if out is not None else weight
    target._set_data(new_w._data)
    return target


@_register
def rmspropalex_update(weight, grad, n, g, delta, lr, gamma1=0.95,
                       gamma2=0.9, epsilon=1e-8, wd=0.0, rescale_grad=1.0,
                       clip_gradient=-1.0, clip_weights=-1.0, out=None):
    """RMSProp with the Alex Graves centered variant + momentum delta."""
    def fn(w, gr, nn_, gm, dl):
        gr = _prep_grad(gr, w, wd, rescale_grad, clip_gradient)
        n_new = gamma1 * nn_ + (1 - gamma1) * jnp.square(gr)
        g_new = gamma1 * gm + (1 - gamma1) * gr
        d_new = gamma2 * dl - lr * gr / jnp.sqrt(
            n_new - jnp.square(g_new) + epsilon)
        w_new = w + d_new
        if clip_weights > 0:
            w_new = jnp.clip(w_new, -clip_weights, clip_weights)
        return (w_new, n_new, g_new, d_new)
    new_w, new_n, new_g, new_d = apply_nary(
        fn, [weight, grad, n, g, delta], n_out=4, name="rmspropalex_update")
    n._set_data(new_n._data)
    g._set_data(new_g._data)
    delta._set_data(new_d._data)
    target = out if out is not None else weight
    target._set_data(new_w._data)
    return target


@_register
def ftrl_update(weight, grad, z, n, lr, lamda1=0.01, beta=1.0, wd=0.0,
                rescale_grad=1.0, clip_gradient=-1.0, out=None):
    def fn(w, g, zz, nn_):
        g = g * rescale_grad
        if clip_gradient >= 0:
            g = jnp.clip(g, -clip_gradient, clip_gradient)
        n_new = nn_ + jnp.square(g)
        sigma = (jnp.sqrt(n_new) - jnp.sqrt(nn_)) / lr
        z_new = zz + g - sigma * w
        w_new = -(z_new - jnp.sign(z_new) * lamda1) / \
            ((beta + jnp.sqrt(n_new)) / lr + wd)
        w_new = jnp.where(jnp.abs(z_new) <= lamda1,
                          jnp.zeros_like(w_new), w_new)
        return (w_new, z_new, n_new)
    new_w, new_z, new_n = apply_nary(fn, [weight, grad, z, n], n_out=3,
                                     name="ftrl_update")
    z._set_data(new_z._data)
    n._set_data(new_n._data)
    target = out if out is not None else weight
    target._set_data(new_w._data)
    return target


@_register
def adagrad_update(weight, grad, history, lr, epsilon=1e-7, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, out=None):
    def fn(w, g, h):
        g = _prep_grad(g, w, wd, rescale_grad, clip_gradient)
        h_new = h + jnp.square(g)
        return (w - lr * g / (jnp.sqrt(h_new) + epsilon), h_new)
    new_w, new_h = apply_nary(fn, [weight, grad, history], n_out=2,
                              name="adagrad_update")
    history._set_data(new_h._data)
    target = out if out is not None else weight
    target._set_data(new_w._data)
    return target


@_register
def nag_mom_update(weight, grad, mom, lr, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, out=None):
    def fn(w, g, m):
        g = _prep_grad(g, w, wd, rescale_grad, clip_gradient)
        m_new = momentum * m + g
        return (w - lr * (g + momentum * m_new), m_new)
    new_w, new_m = apply_nary(fn, [weight, grad, mom], n_out=2,
                              name="nag_mom_update")
    mom._set_data(new_m._data)
    target = out if out is not None else weight
    target._set_data(new_w._data)
    return target


@_register
def ftml_update(weight, grad, d, v, z, lr, t, beta1=0.6, beta2=0.999,
                epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_grad=-1.0,
                out=None):
    def fn(w, g, dd, vv, zz):
        g = _prep_grad(g, w, wd, rescale_grad, clip_grad)
        v_new = beta2 * vv + (1 - beta2) * jnp.square(g)
        d_new = (1 - beta1 ** t) / lr * (
            jnp.sqrt(v_new / (1 - beta2 ** t)) + epsilon)
        sigma = d_new - beta1 * dd
        z_new = beta1 * zz + (1 - beta1) * g - sigma * w
        return (-z_new / d_new, d_new, v_new, z_new)
    new_w, new_d, new_v, new_z = apply_nary(
        fn, [weight, grad, d, v, z], n_out=4, name="ftml_update")
    d._set_data(new_d._data)
    v._set_data(new_v._data)
    z._set_data(new_z._data)
    target = out if out is not None else weight
    target._set_data(new_w._data)
    return target


@_register
def adamax_update(weight, grad, mean, var, lr, beta1=0.9, beta2=0.999,
                  epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                  out=None):
    """lr is expected pre-bias-corrected (lr_t = lr / (1 - beta1^t)),
    matching the reference op contract."""
    def fn(w, g, m, u):
        g = _prep_grad(g, w, wd, rescale_grad, clip_gradient)
        m_new = beta1 * m + (1 - beta1) * g
        u_new = jnp.maximum(beta2 * u, jnp.abs(g))
        return (w - lr * m_new / (u_new + epsilon), m_new, u_new)
    new_w, new_m, new_u = apply_nary(fn, [weight, grad, mean, var], n_out=3,
                                     name="adamax_update")
    mean._set_data(new_m._data)
    var._set_data(new_u._data)
    target = out if out is not None else weight
    target._set_data(new_w._data)
    return target


_NADAM_SCHED = {}   # (beta1, schedule_decay) -> (mus, cumprods); cumprods[i]
                    # = prod mu_1..mu_i, extended lazily as t grows


def _nadam_schedule(beta1, schedule_decay, t):
    mus, cum = _NADAM_SCHED.setdefault((beta1, schedule_decay),
                                       ([None], [1.0]))
    while len(mus) <= t + 1:
        i = len(mus)
        mu = beta1 * (1 - 0.5 * 0.96 ** (i * schedule_decay))
        mus.append(mu)
        cum.append(cum[-1] * mu)
    return mus[t], mus[t + 1], cum[t], cum[t] * mus[t + 1]


@_register
def nadam_update(weight, grad, mean, var, lr, t, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, schedule_decay=0.004, wd=0.0,
                 rescale_grad=1.0, clip_gradient=-1.0, out=None):
    """Nesterov Adam (reference python optimizer.Nadam semantics). The
    bias correction uses the CUMULATIVE momentum-schedule product
    m_schedule = prod_i mu_i, not just the current step's mu_t; the
    products are cached per (beta1, schedule_decay) and extended
    incrementally, so step t costs O(1) host work in a training loop."""
    mu_t, mu_tp1, m_schedule, m_schedule_next = _nadam_schedule(
        beta1, schedule_decay, t)

    def fn(w, g, m, v):
        g = _prep_grad(g, w, wd, rescale_grad, clip_gradient)
        m_new = beta1 * m + (1 - beta1) * g
        v_new = beta2 * v + (1 - beta2) * jnp.square(g)
        g_hat = g / (1 - m_schedule)
        m_hat = m_new / (1 - m_schedule_next)
        v_hat = v_new / (1 - beta2 ** t)
        m_bar = (1 - mu_t) * g_hat + mu_tp1 * m_hat
        return (w - lr * m_bar / (jnp.sqrt(v_hat) + epsilon), m_new, v_new)
    new_w, new_m, new_v = apply_nary(fn, [weight, grad, mean, var], n_out=3,
                                     name="nadam_update")
    mean._set_data(new_m._data)
    var._set_data(new_v._data)
    target = out if out is not None else weight
    target._set_data(new_w._data)
    return target


@_register
def lamb_update_phase1(weight, grad, mean, var, t, beta1=0.9, beta2=0.999,
                       epsilon=1e-6, bias_correction=True, wd=0.0,
                       rescale_grad=1.0, clip_gradient=-1.0):
    """Phase 1 of the two-phase LAMB update: returns the raw layer update
    direction g' (the trust-ratio scaling happens in phase 2). Mutates
    mean/var in place like the reference op."""
    def fn(w, g, m, v):
        g = g * rescale_grad
        if clip_gradient >= 0:
            g = jnp.clip(g, -clip_gradient, clip_gradient)
        m_new = beta1 * m + (1 - beta1) * g
        v_new = beta2 * v + (1 - beta2) * jnp.square(g)
        if bias_correction:
            m_hat = m_new / (1 - beta1 ** t)
            v_hat = v_new / (1 - beta2 ** t)
        else:
            m_hat, v_hat = m_new, v_new
        return (m_hat / (jnp.sqrt(v_hat) + epsilon) + wd * w, m_new, v_new)
    g_out, new_m, new_v = apply_nary(fn, [weight, grad, mean, var], n_out=3,
                                     name="lamb_update_phase1")
    mean._set_data(new_m._data)
    var._set_data(new_v._data)
    return g_out


@_register
def lamb_update_phase2(weight, g, r1, r2, lr, lower_bound=-1.0,
                       upper_bound=-1.0, out=None):
    """Phase 2: apply the trust ratio r1/r2 (weight norm / update norm);
    a zero norm on either side means ratio 1 (reference semantics)."""
    def fn(w, gg, rr1, rr2):
        rr1 = rr1.reshape(())
        rr2 = rr2.reshape(())
        if lower_bound > 0:
            rr1 = jnp.maximum(rr1, lower_bound)
        if upper_bound > 0:
            rr1 = jnp.minimum(rr1, upper_bound)
        ratio = jnp.where((rr1 > 0) & (rr2 > 0), rr1 / rr2, 1.0)
        return w - lr * ratio * gg
    new_w = apply_nary(fn, [weight, g, _nd(r1, weight), _nd(r2, weight)],
                       name="lamb_update_phase2")
    target = out if out is not None else weight
    target._set_data(new_w._data)
    return target


@_register
def mp_sgd_update(weight, grad, weight32, lr, wd=0.0, rescale_grad=1.0,
                  clip_gradient=-1.0, out=None):
    """Mixed-precision SGD: the master fp32 copy carries the update; the
    low-precision weight is the cast of it (reference mp_sgd_update)."""
    def fn(w, g, w32):
        g = _prep_grad(g.astype(jnp.float32), w32, wd, rescale_grad,
                       clip_gradient)
        w32_new = w32 - lr * g
        return (w32_new.astype(w.dtype), w32_new)
    new_w, new_w32 = apply_nary(fn, [weight, grad, weight32], n_out=2,
                                name="mp_sgd_update")
    weight32._set_data(new_w32._data)
    target = out if out is not None else weight
    target._set_data(new_w._data)
    return target


@_register
def mp_sgd_mom_update(weight, grad, mom, weight32, lr, momentum=0.0, wd=0.0,
                      rescale_grad=1.0, clip_gradient=-1.0, out=None):
    def fn(w, g, m, w32):
        g = _prep_grad(g.astype(jnp.float32), w32, wd, rescale_grad,
                       clip_gradient)
        m_new = momentum * m - lr * g
        w32_new = w32 + m_new
        return (w32_new.astype(w.dtype), m_new, w32_new)
    new_w, new_m, new_w32 = apply_nary(fn, [weight, grad, mom, weight32],
                                       n_out=3, name="mp_sgd_mom_update")
    mom._set_data(new_m._data)
    weight32._set_data(new_w32._data)
    target = out if out is not None else weight
    target._set_data(new_w._data)
    return target


@_register
def mp_nag_mom_update(weight, grad, mom, weight32, lr, momentum=0.0, wd=0.0,
                      rescale_grad=1.0, clip_gradient=-1.0, out=None):
    def fn(w, g, m, w32):
        g = _prep_grad(g.astype(jnp.float32), w32, wd, rescale_grad,
                       clip_gradient)
        m_new = momentum * m + g
        w32_new = w32 - lr * (g + momentum * m_new)
        return (w32_new.astype(w.dtype), m_new, w32_new)
    new_w, new_m, new_w32 = apply_nary(fn, [weight, grad, mom, weight32],
                                       n_out=3, name="mp_nag_mom_update")
    mom._set_data(new_m._data)
    weight32._set_data(new_w32._data)
    target = out if out is not None else weight
    target._set_data(new_w._data)
    return target


@_register
def mp_lamb_update_phase1(weight, grad, mean, var, weight32, t, beta1=0.9,
                          beta2=0.999, epsilon=1e-6, bias_correction=True,
                          wd=0.0, rescale_grad=1.0, clip_gradient=-1.0):
    """fp32-master LAMB phase 1: statistics and direction in fp32."""
    def fn(w, g, m, v, w32):
        g = g.astype(jnp.float32) * rescale_grad
        if clip_gradient >= 0:
            g = jnp.clip(g, -clip_gradient, clip_gradient)
        m_new = beta1 * m + (1 - beta1) * g
        v_new = beta2 * v + (1 - beta2) * jnp.square(g)
        if bias_correction:
            m_hat = m_new / (1 - beta1 ** t)
            v_hat = v_new / (1 - beta2 ** t)
        else:
            m_hat, v_hat = m_new, v_new
        return (m_hat / (jnp.sqrt(v_hat) + epsilon) + wd * w32,
                m_new, v_new)
    g_out, new_m, new_v = apply_nary(
        fn, [weight, grad, mean, var, weight32], n_out=3,
        name="mp_lamb_update_phase1")
    mean._set_data(new_m._data)
    var._set_data(new_v._data)
    return g_out


@_register
def mp_lamb_update_phase2(weight, g, r1, r2, weight32, lr, lower_bound=-1.0,
                          upper_bound=-1.0, out=None):
    def fn(w, gg, rr1, rr2, w32):
        rr1 = rr1.reshape(())
        rr2 = rr2.reshape(())
        if lower_bound > 0:
            rr1 = jnp.maximum(rr1, lower_bound)
        if upper_bound > 0:
            rr1 = jnp.minimum(rr1, upper_bound)
        ratio = jnp.where((rr1 > 0) & (rr2 > 0), rr1 / rr2, 1.0)
        w32_new = w32 - lr * ratio * gg
        return (w32_new.astype(w.dtype), w32_new)
    new_w, new_w32 = apply_nary(
        fn, [weight, g, _nd(r1, weight), _nd(r2, weight), weight32],
        n_out=2, name="mp_lamb_update_phase2")
    weight32._set_data(new_w32._data)
    target = out if out is not None else weight
    target._set_data(new_w._data)
    return target


# ======================================================================
# multi-tensor utility ops (reference: src/operator/contrib/multi_*.cc,
# all_finite.cc — the LARS/AMP support kernels)
# ======================================================================

@_register
def all_finite(data, init_output=True):
    """1.0 if every element is finite (reference all_finite.cc; the AMP
    dynamic-loss-scaler check)."""
    return apply_nary(
        lambda d: jnp.all(jnp.isfinite(d)).astype(jnp.float32).reshape(1),
        [data], name="all_finite")


@_register
def multi_all_finite(*arrays, num_arrays=None, init_output=True):
    if num_arrays is not None and num_arrays != len(arrays):
        raise MXNetError(f"multi_all_finite: num_arrays {num_arrays} != "
                         f"{len(arrays)} inputs")
    def fn(*ds):
        ok = jnp.ones((), jnp.bool_)
        for d in ds:
            ok = ok & jnp.all(jnp.isfinite(d))
        return ok.astype(jnp.float32).reshape(1)
    return apply_nary(fn, list(arrays), name="multi_all_finite")


@_register
def multi_sum_sq(*arrays, num_arrays=None):
    """Per-array sum of squares, one fused launch (reference
    multi_sum_sq.cc — feeds multi_lars). Returns shape (n,)."""
    if num_arrays is not None and num_arrays != len(arrays):
        raise MXNetError(f"multi_sum_sq: num_arrays {num_arrays} != "
                         f"{len(arrays)} inputs")
    def fn(*ds):
        return jnp.stack([jnp.sum(jnp.square(d.astype(jnp.float32)))
                          for d in ds])
    return apply_nary(fn, list(arrays), name="multi_sum_sq")


@_register
def multi_lars(lrs, weights_sum_sq, grads_sum_sq, wds, eta=0.001,
               eps=1e-8, rescale_grad=1.0):
    """LARS trust-ratio layer-wise lr scaling (reference multi_lars.cc):
    lr_i *= eta*||w||/(||g||*rescale + wd*||w|| + eps), identity when
    either norm is zero."""
    def fn(lr, wss, gss, wd):
        wn = jnp.sqrt(wss)
        gn = jnp.sqrt(gss) * rescale_grad
        ratio = eta * wn / (gn + wd * wn + eps)
        return jnp.where((wn > 0) & (gn > 0), lr * ratio, lr)
    return apply_nary(fn, [lrs, _nd(weights_sum_sq, lrs),
                           _nd(grads_sum_sq, lrs), _nd(wds, lrs)],
                      name="multi_lars")


@_register
def amp_cast(data, dtype):
    """AMP-inserted cast (reference src/operator/tensor/amp_cast.cc)."""
    dt = _dtype_of(dtype)
    return apply_nary(lambda d: d.astype(dt), [data], name="amp_cast")


@_register
def amp_multicast(*data, num_outputs=None, cast_narrow=False):
    """Cast all inputs to their widest (or narrowest) floating dtype."""
    if num_outputs is not None and num_outputs != len(data):
        raise MXNetError(f"amp_multicast: num_outputs {num_outputs} != "
                         f"{len(data)} inputs")
    dts = [d.data.dtype for d in data]
    key = (lambda t: jnp.finfo(t).bits) if not cast_narrow else \
        (lambda t: -jnp.finfo(t).bits)
    target = _builtins.max(dts, key=key)   # `max` is the reduction op here
    def fn(*ds):
        return tuple(d.astype(target) for d in ds)
    return apply_nary(fn, list(data), n_out=len(data),
                      name="amp_multicast")


@_register
def moments(data, axes=None, keepdims=False):
    """(mean, variance) in one op (reference src/operator/nn/moments.cc)."""
    ax = tuple(axes) if isinstance(axes, (list, tuple)) else axes
    def fn(d):
        mu = jnp.mean(d, axis=ax, keepdims=keepdims)
        var = jnp.var(d, axis=ax, keepdims=keepdims)
        return (mu, var)
    return apply_nary(fn, [data], n_out=2, name="moments")


# ======================================================================
# preloaded multi-sgd (reference src/operator/contrib/preloaded_multi_sgd.cc
# — lrs/wds live on device as tensors, one launch updates many weights)
# ======================================================================

def _preloaded_multi(name, step, n_per_weight, mutated_idx):
    """Build a preloaded_multi_* op. All n weight-groups update in ONE
    apply_nary dispatch (one traced graph XLA fuses into one launch) with
    lrs/wds consumed in-graph — no per-weight host indexing or sync.
    ``step`` maps one group's raw arrays to the new values of the arrays
    at ``mutated_idx`` within the group."""
    def op(*data, rescale_grad=1.0, clip_gradient=-1.0, momentum=0.0,
           num_weights=None):
        n = num_weights if num_weights is not None else \
            (len(data) - 2) // n_per_weight
        if len(data) != n * n_per_weight + 2:
            raise MXNetError(
                f"{name}: expected {n}*{n_per_weight}+2 arrays "
                f"(groups + lrs + wds), got {len(data)}")
        groups = [data[i * n_per_weight:(i + 1) * n_per_weight]
                  for i in range(n)]
        lrs, wds = data[-2], data[-1]

        def fn(*arrs):
            flat, lr_a, wd_a = arrs[:-2], arrs[-2], arrs[-1]
            outs = []
            for i in range(n):
                grp = flat[i * n_per_weight:(i + 1) * n_per_weight]
                outs.extend(step(grp, lr_a[i], wd_a[i], rescale_grad,
                                 clip_gradient, momentum))
            return tuple(outs)

        flat_in = [a for grp in groups for a in grp] + [lrs, wds]
        n_out = n * len(mutated_idx)
        results = apply_nary(fn, flat_in, n_out=n_out, name=name)
        if n_out == 1:
            results = [results]
        k = 0
        for grp in groups:
            for j in mutated_idx:
                grp[j]._set_data(results[k]._data)
                k += 1
        return [grp[0] for grp in groups]
    op.__name__ = name
    op.__doc__ = (f"{name} — reference contrib/preloaded_multi_sgd.cc; "
                  "lrs/wds are device tensors indexed per weight, the "
                  "whole update is one fused dispatch.")
    return _register(op)


def _plain_sgd_step(grp, lr, wd, rescale, clip, momentum):
    w, g = grp
    g = _prep_grad(g, w, wd, rescale, clip)
    return (w - lr * g,)


def _mom_sgd_step(grp, lr, wd, rescale, clip, momentum):
    w, g, m = grp
    g = _prep_grad(g, w, wd, rescale, clip)
    m_new = momentum * m - lr * g
    return (w + m_new, m_new)


def _mp_sgd_step(grp, lr, wd, rescale, clip, momentum):
    w, g, w32 = grp
    g = _prep_grad(g.astype(jnp.float32), w32, wd, rescale, clip)
    w32_new = w32 - lr * g
    return (w32_new.astype(w.dtype), w32_new)


def _mp_mom_sgd_step(grp, lr, wd, rescale, clip, momentum):
    w, g, m, w32 = grp
    g = _prep_grad(g.astype(jnp.float32), w32, wd, rescale, clip)
    m_new = momentum * m - lr * g
    w32_new = w32 + m_new
    return (w32_new.astype(w.dtype), m_new, w32_new)


preloaded_multi_sgd_update = _preloaded_multi(
    "preloaded_multi_sgd_update", _plain_sgd_step, 2, (0,))
preloaded_multi_sgd_mom_update = _preloaded_multi(
    "preloaded_multi_sgd_mom_update", _mom_sgd_step, 3, (0, 2))
preloaded_multi_mp_sgd_update = _preloaded_multi(
    "preloaded_multi_mp_sgd_update", _mp_sgd_step, 3, (0, 2))
preloaded_multi_mp_sgd_mom_update = _preloaded_multi(
    "preloaded_multi_mp_sgd_mom_update", _mp_mom_sgd_step, 4, (0, 2, 3))


# ======================================================================
# legacy structured ops
# ======================================================================

@_register
def choose_element_0index(data, index, axis=1, keepdims=False):
    """Pick one element per row by index (reference legacy op; alias of
    pick with the row axis)."""
    return pick(data, index, axis=axis, keepdims=keepdims)


@_register
def fill_element_0index(lhs, mhs, rhs):
    """lhs[i, rhs[i]] = mhs[i] per row (reference legacy op)."""
    def fn(l, m, r):
        rows = jnp.arange(l.shape[0])
        return l.at[rows, r.astype(jnp.int32)].set(m)
    return apply_nary(fn, [lhs, _nd(mhs, lhs), _nd(rhs, lhs)],
                      name="fill_element_0index")


@_register
def SpatialTransformer(data, loc, target_shape=None,
                       transform_type="affine", sampler_type="bilinear",
                       cudnn_off=None):
    """Affine spatial transformer = GridGenerator + BilinearSampler
    (reference src/operator/spatial_transformer.cc)."""
    if transform_type != "affine" or sampler_type != "bilinear":
        raise MXNetError("SpatialTransformer supports affine/bilinear "
                         "(reference supports exactly these too)")
    grid = GridGenerator(loc, transform_type="affine",
                         target_shape=target_shape)
    return BilinearSampler(data, grid)


@_register
def IdentityAttachKLSparseReg(data, sparseness_target=0.1, penalty=0.001,
                              momentum=0.9):
    """Identity forward; backward adds the KL sparsity penalty gradient
    pushing mean activation toward sparseness_target (reference
    src/operator/identity_attach_KL_sparse_reg.cc).

    ``momentum`` is accepted for API compatibility and has no effect: the
    reference keeps a momentum-smoothed moving average of the activation
    in auxiliary op state; this functional op has no cross-call state, so
    rho is the current batch mean (equivalent to momentum=0)."""
    t = sparseness_target

    @jax.custom_vjp
    def fwd(d):
        return d

    def fwd_fwd(d):
        return d, d

    def fwd_bwd(d, g):
        rho = jnp.clip(jnp.mean(d, axis=0, keepdims=True), 1e-6, 1 - 1e-6)
        kl_grad = penalty * (-t / rho + (1 - t) / (1 - rho))
        return (g + jnp.broadcast_to(kl_grad, g.shape) / d.shape[0],)

    fwd.defvjp(fwd_fwd, fwd_bwd)
    return apply_nary(fwd, [data], name="IdentityAttachKLSparseReg")


# ======================================================================
# Round-4 registry tail: remaining sample_* distributions, multi-tensor
# mixed-precision updates, legacy utility ops
# ======================================================================

def _gamma_poisson(key_gamma, key_poisson, gshape, gscale, out_shape, dtype):
    """NB sampling via the Gamma-Poisson mixture: lam ~ Gamma(shape, scale)
    then x ~ Poisson(lam) — the standard reparameterization (reference
    draws NB directly in src/operator/random/sampler.h; the mixture is
    exactly the same marginal and maps onto jax primitives)."""
    lam = jax.random.gamma(key_gamma, gshape, out_shape) * gscale
    draws = jax.random.poisson(key_poisson, lam, shape=out_shape)
    return draws.astype(_dtype_of(dtype) if dtype else jnp.float32)


@_register
def sample_negative_binomial(k, p, shape=None, dtype=None, ctx=None):
    """Per-element NB(k successes, success prob p) draws (reference
    sample_negative_binomial in src/operator/random/multisample_op.cc);
    counts failures before the k-th success, mean k*(1-p)/p."""
    from . import random as _rnd
    k = _nd(k)
    p = _nd(p, k)
    out_shape = _sample_shape(k.shape, shape)

    def fn(kk, pp):
        bshape = kk.shape + (1,) * (len(out_shape) - kk.ndim)
        kb = jnp.broadcast_to(kk.reshape(bshape), out_shape)
        pb = jnp.broadcast_to(pp.reshape(bshape), out_shape)
        return _gamma_poisson(_rnd.next_key(), _rnd.next_key(),
                              kb, (1.0 - pb) / jnp.maximum(pb, 1e-12),
                              out_shape, dtype)

    return apply_nary(fn, [k, p], name="sample_negative_binomial")


@_register
def sample_generalized_negative_binomial(mu, alpha, shape=None, dtype=None,
                                         ctx=None):
    """Per-element generalized NB(mean mu, dispersion alpha) draws
    (reference sample_generalized_negative_binomial): equivalent to
    NB with k = 1/alpha, p = 1/(1 + mu*alpha)."""
    from . import random as _rnd
    mu = _nd(mu)
    alpha = _nd(alpha, mu)
    out_shape = _sample_shape(mu.shape, shape)

    def fn(m, a):
        bshape = m.shape + (1,) * (len(out_shape) - m.ndim)
        mb = jnp.broadcast_to(m.reshape(bshape), out_shape)
        ab = jnp.broadcast_to(a.reshape(bshape), out_shape)
        ab = jnp.maximum(ab, 1e-12)
        return _gamma_poisson(_rnd.next_key(), _rnd.next_key(),
                              1.0 / ab, mb * ab, out_shape, dtype)

    return apply_nary(fn, [mu, alpha], name="sample_generalized_"
                                            "negative_binomial")


@_register
def multi_mp_sgd_update(*arrays, lrs, wds, rescale_grad=1.0,
                        clip_gradient=None, num_weights=None, out=None):
    """Fused group mixed-precision SGD: arrays = (w0, g0, w32_0, ...).
    The fp32 master weight carries the update; the low-precision weight
    is its cast (reference optimizer_op.cc multi_mp_sgd_update)."""
    groups = _group_pairs(list(arrays), 3)
    _check_num_weights("multi_mp_sgd_update", groups, num_weights)

    def fn(*flat):
        outs = []
        for i in range(0, len(flat), 3):
            w, g, w32 = flat[i], flat[i + 1], flat[i + 2]
            lr, wd = lrs[i // 3], wds[i // 3]
            g32 = g.astype(jnp.float32) * rescale_grad
            if clip_gradient is not None and clip_gradient >= 0:
                g32 = jnp.clip(g32, -clip_gradient, clip_gradient)
            new32 = w32 - lr * (g32 + wd * w32)
            outs.append(new32.astype(w.dtype))
            outs.append(new32)
        return tuple(outs)

    updated = apply_nary(fn, list(arrays), n_out=2 * len(groups),
                         name="multi_mp_sgd_update")
    for gi, (w, _, w32) in enumerate(groups):
        w._set_data(updated[2 * gi].data)
        w32._set_data(updated[2 * gi + 1].data)
    return [updated[2 * i] for i in range(len(groups))]


@_register
def multi_mp_sgd_mom_update(*arrays, lrs, wds, momentum=0.9,
                            rescale_grad=1.0, clip_gradient=None,
                            num_weights=None, out=None):
    """Fused group mixed-precision SGD+momentum: arrays =
    (w0, g0, mom0, w32_0, ...); momentum and master weight stay fp32
    (reference multi_mp_sgd_mom_update)."""
    groups = _group_pairs(list(arrays), 4)
    _check_num_weights("multi_mp_sgd_mom_update", groups, num_weights)

    def fn(*flat):
        outs = []
        for i in range(0, len(flat), 4):
            w, g, m, w32 = flat[i], flat[i + 1], flat[i + 2], flat[i + 3]
            lr, wd = lrs[i // 4], wds[i // 4]
            g32 = g.astype(jnp.float32) * rescale_grad
            if clip_gradient is not None and clip_gradient >= 0:
                g32 = jnp.clip(g32, -clip_gradient, clip_gradient)
            new_m = momentum * m - lr * (g32 + wd * w32)
            new32 = w32 + new_m
            outs.append(new32.astype(w.dtype))
            outs.append(new_m)
            outs.append(new32)
        return tuple(outs)

    updated = apply_nary(fn, list(arrays), n_out=3 * len(groups),
                         name="multi_mp_sgd_mom_update")
    for gi, (w, _, m, w32) in enumerate(groups):
        w._set_data(updated[3 * gi].data)
        m._set_data(updated[3 * gi + 1].data)
        w32._set_data(updated[3 * gi + 2].data)
    return [updated[3 * i] for i in range(len(groups))]


@_register
def reset_arrays(*arrays, num_arrays=None):
    """Zero every array in place in one dispatch (reference
    contrib/reset_arrays.cc — gradient clearing for grad_req='add')."""
    if num_arrays is not None and num_arrays != len(arrays):
        raise MXNetError(f"reset_arrays: num_arrays {num_arrays} != "
                         f"{len(arrays)} arrays passed")
    for a in arrays:
        a._set_data(jnp.zeros_like(a.data))
    return None


@_register
def one_hot_encode(indices, out):
    """Legacy one-hot writer: out[i, indices[i]] = 1, everything else 0
    (reference mx.nd.onehot_encode / ndarray_function.cc OnehotEncode).
    ``out`` supplies the class count and receives the result in place."""
    if out.ndim != 2 or indices.ndim != 1:
        raise MXNetError("one_hot_encode expects indices (N,), out (N, C)")
    n, c = out.shape
    if indices.shape[0] != n:
        raise MXNetError(f"one_hot_encode: indices length "
                         f"{indices.shape[0]} != out rows {n}")

    def fn(idx):
        return jax.nn.one_hot(idx.astype(jnp.int32), c,
                              dtype=_dtype_of(out.dtype))

    res = apply_nary(fn, [indices], name="one_hot_encode")
    out._set_data(res.data)
    return out


onehot_encode = one_hot_encode
__all__.append("onehot_encode")
