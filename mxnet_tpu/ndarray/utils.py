"""NDArray serialization: ``mx.nd.save`` / ``mx.nd.load``.

Reference: ``NDArray::Save/Load`` in src/ndarray/ndarray.cc (dmlc binary blob,
magic ``NDARRAY_V2``) exposed via python/mxnet/ndarray/utils.py.

The TPU rebuild's native format is a single-file container with a small JSON
header + raw little-endian tensor payloads (alignment-friendly, mmap-able —
the role dmlc-core's stream played). A reader for the legacy MXNet binary
format is provided so pretrained reference-zoo checkpoints load directly
(SURVEY.md §5.4: ".params binary compatibility").
"""
from __future__ import annotations

import json
import struct

import numpy as _np

from ..base import MXNetError
from .ndarray import NDArray, array

__all__ = ["save", "load", "load_frombuffer", "save_legacy"]

_MAGIC = b"MXTPU001"

# legacy constants (reference: src/ndarray/ndarray.cc)
_LEGACY_FILE_MAGIC = 0x112
_LEGACY_ND_MAGIC = 0xF993FAC9  # NDARRAY_V2
_LEGACY_ND_MAGIC_V3 = 0xF993FAC8
_LEGACY_DTYPES = {0: "float32", 1: "float64", 2: "float16", 3: "uint8",
                  4: "int32", 5: "int8", 6: "int64"}


def save(fname, data):
    """Save a list or str->NDArray dict."""
    if isinstance(data, NDArray):
        data = [data]
    if isinstance(data, dict):
        names = list(data.keys())
        arrays = [data[k] for k in names]
    else:
        names = [""] * len(data)
        arrays = list(data)
    metas = []
    payloads = []
    for name, arr in zip(names, arrays):
        segs = _sparse_segments(arr)
        if segs is not None:
            stype, dtype_name, parts = segs
            seg_meta, raw = [], b""
            for part in parts:
                p = _np.ascontiguousarray(part)
                seg_meta.append({"shape": list(p.shape),
                                 "dtype": str(p.dtype),
                                 "nbytes": p.nbytes})
                raw += p.tobytes()
            # stype + segments: sparse arrays round-trip their COMPRESSED
            # representation (reference NDARRAY_V2 stores stype per
            # record, src/ndarray/ndarray.cc).  NOTE: containers holding
            # sparse records need this reader version or newer — an
            # older _load_native would error on the short payload
            metas.append({"name": name, "shape": list(arr.shape),
                          "dtype": dtype_name, "stype": stype,
                          "segments": seg_meta, "nbytes": len(raw)})
            payloads.append(raw)
            continue
        np_arr = _np.ascontiguousarray(_to_numpy_raw(arr))
        metas.append({"name": name, "shape": list(np_arr.shape),
                      "dtype": _dtype_name(arr), "nbytes": np_arr.nbytes})
        payloads.append(np_arr.tobytes())
    header = json.dumps(metas).encode()
    with open(fname, "wb") as f:
        f.write(_MAGIC)
        f.write(struct.pack("<Q", len(header)))
        f.write(header)
        for p in payloads:
            f.write(p)


def _dtype_name(arr):
    d = arr.data.dtype
    return str(d)


def _sparse_segments(arr):
    """(stype, dtype, [numpy parts]) for sparse arrays, None for dense.

    Goes through the .values/.indices PROPERTIES (not the private
    slots): they refresh the compressed pair after dense-path writes,
    and .dtype never materializes the dense view."""
    from .sparse import RowSparseNDArray, CSRNDArray
    if isinstance(arr, RowSparseNDArray):
        return ("row_sparse", str(arr.dtype),
                [_np.asarray(arr.values.data),
                 _np.asarray(arr.indices.data)])
    if isinstance(arr, CSRNDArray):
        return ("csr", str(arr.dtype),
                [_np.asarray(arr.values.data),
                 _np.asarray(arr.indptr.data),
                 _np.asarray(arr.indices.data)])
    return None


def _from_sparse_segments(m, parts):
    # same reconstruction the pickle path uses — one home for it
    from .sparse import _row_sparse_from_host, _csr_from_host
    shape = tuple(m["shape"])
    if m["stype"] == "row_sparse":
        return _row_sparse_from_host(parts[0], parts[1], shape)
    if m["stype"] == "csr":
        return _csr_from_host(parts[0], parts[1], parts[2], shape)
    raise MXNetError(f"unknown stype {m['stype']!r} in container")


def _to_numpy_raw(arr):
    np_arr = _np.asarray(arr.asnumpy()) if str(arr.data.dtype) != "bfloat16" \
        else _np.asarray(arr.astype("float32").asnumpy())
    return np_arr


def load(fname):
    with open(fname, "rb") as f:
        blob = f.read()
    return load_frombuffer(blob)


def load_frombuffer(blob):
    if blob[:8] == _MAGIC:
        return _load_native(blob)
    return _load_legacy(blob)


def _load_native(blob):
    (hlen,) = struct.unpack("<Q", blob[8:16])
    metas = json.loads(blob[16:16 + hlen].decode())
    off = 16 + hlen
    out_list, out_dict, named = [], {}, False
    for m in metas:
        if m.get("stype"):
            parts = []
            seg_off = off
            for seg in m["segments"]:
                cnt = int(_np.prod(seg["shape"])) if seg["shape"] else 1
                parts.append(_np.frombuffer(
                    blob, dtype=seg["dtype"], count=cnt,
                    offset=seg_off).reshape(seg["shape"]))
                seg_off += seg["nbytes"]
            off += m["nbytes"]
            arr = _from_sparse_segments(m, parts)
            if m["name"]:
                named = True
                out_dict[m["name"]] = arr
            out_list.append(arr)
            continue
        dtype = m["dtype"] if m["dtype"] != "bfloat16" else "float32"
        np_arr = _np.frombuffer(blob, dtype=dtype, count=int(_np.prod(m["shape"])) if m["shape"] else 1,
                                offset=off).reshape(m["shape"])
        off += m["nbytes"]
        arr = array(np_arr, dtype=m["dtype"] if m["dtype"] != "bfloat16" else "bfloat16")
        if m["name"]:
            named = True
            out_dict[m["name"]] = arr
        out_list.append(arr)
    return out_dict if named else out_list


def _load_legacy(blob):
    """Parse the reference dmlc NDArray container (NDARRAY_V2 records).

    Layout (src/ndarray/ndarray.cc Save): uint64 file_magic(0x112),
    uint64 reserved, uint64 ndarray_count -> [each: magic, stype?, shape,
    ctx, dtype, payload], then names vector<string>.
    """
    off = 0
    def u64():
        nonlocal off
        (v,) = struct.unpack_from("<Q", blob, off)
        off += 8
        return v
    def u32():
        nonlocal off
        (v,) = struct.unpack_from("<I", blob, off)
        off += 4
        return v

    if u64() != _LEGACY_FILE_MAGIC:
        raise MXNetError("unrecognized NDArray file format")
    u64()  # reserved
    count = u64()
    arrays = []
    for _ in range(count):
        magic = u32()
        if magic not in (_LEGACY_ND_MAGIC, _LEGACY_ND_MAGIC_V3):
            raise MXNetError(f"bad ndarray record magic {magic:#x}")
        stype = 0
        if magic == _LEGACY_ND_MAGIC:
            stype = struct.unpack_from("<i", blob, off)[0]
            off += 4
            # reference NDArrayStorageType: dense (kDefaultStorage) is 0;
            # tolerate -1 (kUndefinedStorage) from early files of ours
            if stype not in (0, -1):
                raise MXNetError("sparse legacy checkpoints not supported yet")
        ndim = u32()
        shape = [struct.unpack_from("<q", blob, off + 8 * i)[0]
                 for i in range(ndim)]
        off += 8 * ndim
        u32()  # ctx dev_type
        u32()  # ctx dev_id
        dtype_flag = u32()
        dtype = _LEGACY_DTYPES.get(dtype_flag)
        if dtype is None:
            raise MXNetError(f"unknown legacy dtype flag {dtype_flag}")
        nbytes = int(_np.prod(shape)) * _np.dtype(dtype).itemsize if ndim else \
            _np.dtype(dtype).itemsize
        np_arr = _np.frombuffer(blob, dtype=dtype,
                                count=nbytes // _np.dtype(dtype).itemsize,
                                offset=off).reshape(shape)
        off += nbytes
        arrays.append(array(np_arr, dtype=dtype))
    # names
    n_names = u64()
    names = []
    for _ in range(n_names):
        ln = u64()
        names.append(blob[off:off + ln].decode())
        off += ln
    if names:
        return dict(zip(names, arrays))
    return arrays


_LEGACY_DTYPE_FLAGS = {v: k for k, v in _LEGACY_DTYPES.items()}


def save_legacy(fname, data):
    """Write the reference's dmlc NDArray container (NDARRAY_V2 records,
    src/ndarray/ndarray.cc Save) so checkpoints produced here load in
    reference MXNet — the migration path in the other direction, and the
    generator for byte-genuine ``.params`` fixtures. Dense only."""
    if isinstance(data, NDArray):
        data = [data]
    if isinstance(data, dict):
        names = list(data.keys())
        arrays = [data[k] for k in names]
    else:
        names = []
        arrays = list(data)
    out = bytearray()
    out += struct.pack("<Q", _LEGACY_FILE_MAGIC)
    out += struct.pack("<Q", 0)                    # reserved
    out += struct.pack("<Q", len(arrays))
    for arr in arrays:
        np_arr = _np.ascontiguousarray(arr.asnumpy())
        dname = str(np_arr.dtype)
        if dname not in _LEGACY_DTYPE_FLAGS:
            raise MXNetError(
                f"dtype {dname} has no legacy NDARRAY_V2 encoding; cast "
                f"to one of {sorted(_LEGACY_DTYPE_FLAGS)} first")
        out += struct.pack("<I", _LEGACY_ND_MAGIC)
        out += struct.pack("<i", 0)    # stype: dense (kDefaultStorage)
        out += struct.pack("<I", np_arr.ndim)
        for s in np_arr.shape:
            out += struct.pack("<q", s)
        out += struct.pack("<I", 1)                # ctx dev_type: cpu
        out += struct.pack("<I", 0)                # ctx dev_id
        out += struct.pack("<I", _LEGACY_DTYPE_FLAGS[dname])
        out += np_arr.tobytes()
    out += struct.pack("<Q", len(names))
    for name in names:
        b = name.encode()
        out += struct.pack("<Q", len(b))
        out += b
    with open(fname, "wb") as f:
        f.write(bytes(out))
