"""``mx.nd.contrib`` — detection / misc contrib operators, TPU-first.

Reference surface: src/operator/contrib/ (bounding_box.cc: box_nms, box_iou,
bipartite_matching, box_encode/decode; roi_align.cc; multibox_prior.cc,
multibox_target.cc, multibox_detection.cc for the legacy SSD path).

Design notes (TPU): all ops are static-shape and branch-free so they jit onto
the VPU/MXU — NMS is a fixed-trip `lax.fori_loop` over the top-k scored boxes
(suppressed entries are masked, never dropped), ROIAlign is vectorised
bilinear gather, matching is an argmax sweep. No dynamic shapes anywhere.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..base import MXNetError
from .ndarray import NDArray, apply_nary

__all__ = ["box_iou", "box_nms", "box_encode", "box_decode",
           "bipartite_matching", "ROIAlign", "ROIPooling",
           "MultiBoxPrior", "MultiBoxTarget", "MultiBoxDetection",
           "getnnz", "quantize", "arange_like", "fused_gelu",
           "BilinearResize2D", "AdaptiveAvgPooling2D",
           "DeformableConvolution",
           "boolean_mask", "index_copy", "index_array", "allclose",
           "gradientmultiplier", "fft", "ifft", "count_sketch",
           "quadratic", "div_sqrt_dim", "edge_id",
           "Proposal", "MultiProposal", "fused_linear_cross_entropy"]


def _corner(box, fmt):
    """Convert [..., 4] boxes to corner (xmin, ymin, xmax, ymax)."""
    if fmt == "corner":
        return box
    if fmt == "center":
        x, y, w, h = jnp.split(box, 4, axis=-1)
        return jnp.concatenate(
            [x - w / 2, y - h / 2, x + w / 2, y + h / 2], axis=-1)
    raise MXNetError(f"unknown box format {fmt!r}")


def _pairwise_iou(lhs, rhs):
    """IoU of [..., N, 4] x [..., M, 4] corner boxes -> [..., N, M]."""
    l = lhs[..., :, None, :]
    r = rhs[..., None, :, :]
    tl = jnp.maximum(l[..., :2], r[..., :2])
    br = jnp.minimum(l[..., 2:], r[..., 2:])
    wh = jnp.maximum(br - tl, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    area_l = jnp.maximum(l[..., 2] - l[..., 0], 0.0) * \
        jnp.maximum(l[..., 3] - l[..., 1], 0.0)
    area_r = jnp.maximum(r[..., 2] - r[..., 0], 0.0) * \
        jnp.maximum(r[..., 3] - r[..., 1], 0.0)
    return inter / jnp.maximum(area_l + area_r - inter, 1e-12)


def box_iou(lhs, rhs, format="corner"):
    """Pairwise IoU (reference: src/operator/contrib/bounding_box.cc)."""
    def fn(a, b):
        return _pairwise_iou(_corner(a, format), _corner(b, format))
    return apply_nary(fn, [lhs, rhs], name="box_iou")


def box_nms(data, overlap_thresh=0.5, valid_thresh=0.0, topk=-1,
            coord_start=2, score_index=1, id_index=-1, force_suppress=False,
            in_format="corner", out_format="corner", background_id=-1):
    """Non-maximum suppression, MXNet semantics.

    data: (..., N, K) with K >= coord_start+4; suppressed boxes get score -1
    (all other fields preserved), output sorted by score descending. The
    suppression sweep is a fixed-trip ``lax.fori_loop`` over candidates so the
    whole op compiles to one static XLA program (no data-dependent shapes).
    """
    def fn(d):
        shape = d.shape
        d2 = d.reshape((-1,) + shape[-2:])
        n = d2.shape[1]

        # suppression sweep runs only on the top-k candidates (k x k IoU,
        # k-trip loop) — the O(N^2) full matrix would not fit the VPU
        # budget for SSD-sized anchor sets (N ~ 10k)
        k = n if topk < 0 else min(int(topk), n)

        def one(batch):
            scores = batch[:, score_index]
            ids = batch[:, id_index] if id_index >= 0 else jnp.zeros(n)
            valid = scores > valid_thresh
            if background_id >= 0 and id_index >= 0:
                valid = valid & (ids != background_id)
            order = jnp.argsort(-jnp.where(valid, scores, -jnp.inf))
            boxes = _corner(batch[:, coord_start:coord_start + 4], in_format)
            cand = order[:k]
            sboxes = boxes[cand]
            svalid = valid[cand]
            sids = ids[cand]
            iou = _pairwise_iou(sboxes, sboxes)
            if not force_suppress and id_index >= 0:
                same = sids[:, None] == sids[None, :]
                iou = jnp.where(same, iou, 0.0)

            def body(i, keep):
                alive = keep[i] & svalid[i]
                sup = (iou[i] > overlap_thresh) & (jnp.arange(k) > i)
                return jnp.where(alive, keep & ~sup, keep)

            keep_k = lax.fori_loop(0, k, body, jnp.ones(k, bool))
            keep = jnp.zeros(n, bool).at[:k].set(keep_k & svalid)
            out = batch[order]
            out = out.at[:, score_index].set(
                jnp.where(keep, out[:, score_index], -1.0))
            if out_format != in_format:
                cs = coord_start
                box_out = _corner(out[:, cs:cs + 4], in_format)
                if out_format == "center":
                    xmin, ymin, xmax, ymax = jnp.split(box_out, 4, axis=-1)
                    box_out = jnp.concatenate(
                        [(xmin + xmax) / 2, (ymin + ymax) / 2,
                         xmax - xmin, ymax - ymin], axis=-1)
                out = out.at[:, cs:cs + 4].set(box_out)
            return out

        return jax.vmap(one)(d2).reshape(shape)

    return apply_nary(fn, [data], name="box_nms")


def box_encode(samples, matches, anchors, refs, means=(0., 0., 0., 0.),
               stds=(0.1, 0.1, 0.2, 0.2)):
    """Encode matched gt boxes against anchors as normalized offsets.

    samples: (B, N) in {-1, 0, 1} (1 = positive); matches: (B, N) gt index;
    anchors/refs: (B, N, 4)/(B, M, 4) corner boxes. Returns (targets, masks).
    Reference: src/operator/contrib/bounding_box.cc (BoxEncode).
    """
    means = jnp.asarray(means)
    stds = jnp.asarray(stds)

    def fn(s, m, a, r):
        g = jnp.take_along_axis(r, m[..., None].astype(jnp.int32).clip(0)
                                .repeat(4, -1), axis=1)
        aw = a[..., 2] - a[..., 0]
        ah = a[..., 3] - a[..., 1]
        ax = (a[..., 0] + a[..., 2]) / 2
        ay = (a[..., 1] + a[..., 3]) / 2
        gw = g[..., 2] - g[..., 0]
        gh = g[..., 3] - g[..., 1]
        gx = (g[..., 0] + g[..., 2]) / 2
        gy = (g[..., 1] + g[..., 3]) / 2
        t = jnp.stack([(gx - ax) / jnp.maximum(aw, 1e-12),
                       (gy - ay) / jnp.maximum(ah, 1e-12),
                       jnp.log(jnp.maximum(gw, 1e-12) /
                               jnp.maximum(aw, 1e-12)),
                       jnp.log(jnp.maximum(gh, 1e-12) /
                               jnp.maximum(ah, 1e-12))], axis=-1)
        t = (t - means) / stds
        mask = (s > 0.5)[..., None].astype(t.dtype)
        return t * mask, mask.repeat(4, -1) * 0 + mask

    out = apply_nary(fn, [samples, matches, anchors, refs], n_out=2,
                     name="box_encode")
    return out


def box_decode(data, anchors, std0=0.1, std1=0.1, std2=0.2, std3=0.2,
               clip=-1.0, format="center"):
    """Decode offsets back to corner boxes (inverse of box_encode)."""
    stds = jnp.asarray([std0, std1, std2, std3])

    def fn(d, a):
        if format == "corner":
            ac = a
            aw = ac[..., 2] - ac[..., 0]
            ah = ac[..., 3] - ac[..., 1]
            ax = (ac[..., 0] + ac[..., 2]) / 2
            ay = (ac[..., 1] + ac[..., 3]) / 2
        else:
            ax, ay, aw, ah = (a[..., 0], a[..., 1], a[..., 2], a[..., 3])
        t = d * stds
        ox = t[..., 0] * aw + ax
        oy = t[..., 1] * ah + ay
        tw = t[..., 2]
        th = t[..., 3]
        if clip > 0:
            tw = jnp.minimum(tw, clip)
            th = jnp.minimum(th, clip)
        ow = jnp.exp(tw) * aw / 2
        oh = jnp.exp(th) * ah / 2
        return jnp.stack([ox - ow, oy - oh, ox + ow, oy + oh], axis=-1)

    return apply_nary(fn, [data, anchors], name="box_decode")


def bipartite_matching(data, threshold=1e-12, is_ascend=False, topk=-1):
    """Greedy bipartite matching on a (B, N, M) score matrix.

    Returns (row_match, col_match): for each row the matched column (or -1),
    and for each column the matched row (or -1). Fixed-trip argmax sweep.
    Reference: src/operator/contrib/bounding_box.cc (BipartiteMatching).
    """
    def fn(d):
        sign = 1.0 if not is_ascend else -1.0

        def one(mat):
            n, m = mat.shape
            k = min(n, m) if topk < 0 else min(int(topk), n, m)
            s = mat * sign

            def body(_, carry):
                s_cur, row, col = carry
                flat = jnp.argmax(s_cur)
                i, j = flat // m, flat % m
                ok = s_cur[i, j] > (threshold * sign if not is_ascend
                                    else -jnp.inf)
                row = jnp.where(ok, row.at[i].set(j), row)
                col = jnp.where(ok, col.at[j].set(i), col)
                s_cur = jnp.where(ok, s_cur.at[i, :].set(-jnp.inf)
                                  .at[:, j].set(-jnp.inf), s_cur)
                return s_cur, row, col

            _, row, col = lax.fori_loop(
                0, k, body, (s, -jnp.ones(n, jnp.float32),
                             -jnp.ones(m, jnp.float32)))
            return row, col

        rows, cols = jax.vmap(one)(d)
        return rows, cols

    return apply_nary(fn, [data], n_out=2, name="bipartite_matching")


def _roi_align_one(feat, roi, pooled_h, pooled_w, spatial_scale, ratio):
    """feat: (C, H, W); roi: (4,) corner in image coords -> (C, ph, pw)."""
    c, h, w = feat.shape
    x0, y0, x1, y1 = roi * spatial_scale
    rw = jnp.maximum(x1 - x0, 1.0)
    rh = jnp.maximum(y1 - y0, 1.0)
    bin_w = rw / pooled_w
    bin_h = rh / pooled_h
    sr = ratio if ratio > 0 else 2
    # sample grid: (ph, pw, sr, sr) bilinear sample points
    iy = jnp.arange(sr) + 0.5
    ix = jnp.arange(sr) + 0.5
    py = jnp.arange(pooled_h)
    px = jnp.arange(pooled_w)
    ys2 = jnp.broadcast_to(
        (y0 + py[:, None] * bin_h + iy[None, :] / sr * bin_h)[:, None, :, None],
        (pooled_h, pooled_w, sr, sr))
    xs2 = jnp.broadcast_to(
        (x0 + px[:, None] * bin_w + ix[None, :] / sr * bin_w)[None, :, None, :],
        (pooled_h, pooled_w, sr, sr))

    def bilinear(yy, xx):
        yy = jnp.clip(yy, 0.0, h - 1.0)
        xx = jnp.clip(xx, 0.0, w - 1.0)
        y0i = jnp.floor(yy).astype(jnp.int32)
        x0i = jnp.floor(xx).astype(jnp.int32)
        y1i = jnp.minimum(y0i + 1, h - 1)
        x1i = jnp.minimum(x0i + 1, w - 1)
        wy = yy - y0i
        wx = xx - x0i
        v00 = feat[:, y0i, x0i]
        v01 = feat[:, y0i, x1i]
        v10 = feat[:, y1i, x0i]
        v11 = feat[:, y1i, x1i]
        return (v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx +
                v10 * wy * (1 - wx) + v11 * wy * wx)

    vals = bilinear(ys2, xs2)            # (C, ph, pw, sr, sr)
    return vals.mean(axis=(-1, -2))


def ROIAlign(data, rois, pooled_size=(7, 7), spatial_scale=1.0,
             sample_ratio=-1, position_sensitive=False, aligned=False):
    """ROI Align (reference: src/operator/contrib/roi_align.cc).

    data: (B, C, H, W); rois: (R, 5) rows [batch_idx, x0, y0, x1, y1].
    Returns (R, C, ph, pw). Vectorised bilinear gather — XLA lowers the
    gathers; sample grid is static (sample_ratio<=0 -> 2x2).
    """
    if position_sensitive:
        raise MXNetError("position_sensitive ROIAlign not supported")
    ph, pw = (pooled_size if isinstance(pooled_size, (tuple, list))
              else (pooled_size, pooled_size))

    def fn(d, r):
        off = 0.5 if aligned else 0.0

        def one(roi):
            b = roi[0].astype(jnp.int32).clip(0, d.shape[0] - 1)
            feat = d[b]
            return _roi_align_one(feat, roi[1:5] - off / spatial_scale,
                                  ph, pw, spatial_scale, sample_ratio)

        return jax.vmap(one)(r)

    return apply_nary(fn, [data, rois], name="ROIAlign")


def ROIPooling(data, rois, pooled_size=(7, 7), spatial_scale=1.0):
    """Max ROI pooling (reference: src/operator/roi_pooling.cc)."""
    ph, pw = (pooled_size if isinstance(pooled_size, (tuple, list))
              else (pooled_size, pooled_size))

    def fn(d, r):
        _, _, h, w = d.shape

        def one(roi):
            b = roi[0].astype(jnp.int32).clip(0, d.shape[0] - 1)
            feat = d[b]
            x0 = jnp.round(roi[1] * spatial_scale)
            y0 = jnp.round(roi[2] * spatial_scale)
            x1 = jnp.round(roi[3] * spatial_scale)
            y1 = jnp.round(roi[4] * spatial_scale)
            rw = jnp.maximum(x1 - x0 + 1, 1.0)
            rh = jnp.maximum(y1 - y0 + 1, 1.0)
            ys = jnp.arange(h)
            xs = jnp.arange(w)
            py = jnp.floor((ys - y0) / (rh / ph))
            px = jnp.floor((xs - x0) / (rw / pw))
            inside_y = (ys >= y0) & (ys <= y1)
            inside_x = (xs >= x0) & (xs <= x1)
            bins_y = jnp.where(inside_y, py, -1).clip(-1, ph - 1)
            bins_x = jnp.where(inside_x, px, -1).clip(-1, pw - 1)
            onehot_y = bins_y[:, None] == jnp.arange(ph)[None, :]
            onehot_x = bins_x[:, None] == jnp.arange(pw)[None, :]
            masked = jnp.where(
                onehot_y[None, :, None, :, None] &
                onehot_x[None, None, :, None, :],
                feat[:, :, :, None, None], -jnp.inf)
            out = masked.max(axis=(1, 2))
            return jnp.where(jnp.isfinite(out), out, 0.0)

        return jax.vmap(one)(r)

    return apply_nary(fn, [data, rois], name="ROIPooling")


def MultiBoxPrior(data, sizes=(1.0,), ratios=(1.0,), clip=False,
                  steps=(-1.0, -1.0), offsets=(0.5, 0.5)):
    """Anchor generation (reference: src/operator/contrib/multibox_prior.cc).

    data: (B, C, H, W) feature map -> (1, H*W*(S+R-1), 4) corner anchors in
    [0,1] image coords.
    """
    sizes = tuple(float(s) for s in sizes)
    ratios = tuple(float(r) for r in ratios)

    def fn(d):
        h, w = d.shape[-2], d.shape[-1]
        step_y = steps[0] if steps[0] > 0 else 1.0 / h
        step_x = steps[1] if steps[1] > 0 else 1.0 / w
        cy = (jnp.arange(h) + offsets[0]) * step_y
        cx = (jnp.arange(w) + offsets[1]) * step_x
        # anchor shapes: sizes with ratio[0], plus ratios[1:] with size[0]
        ws, hs = [], []
        for s in sizes:
            ws.append(s * math_sqrt(ratios[0]))
            hs.append(s / math_sqrt(ratios[0]))
        for r in ratios[1:]:
            ws.append(sizes[0] * math_sqrt(r))
            hs.append(sizes[0] / math_sqrt(r))
        ws = jnp.asarray(ws)
        hs = jnp.asarray(hs)
        cyg, cxg = jnp.meshgrid(cy, cx, indexing="ij")
        cyg = cyg[..., None]
        cxg = cxg[..., None]
        out = jnp.stack([cxg - ws / 2, cyg - hs / 2,
                         cxg + ws / 2, cyg + hs / 2], axis=-1)
        out = out.reshape(1, -1, 4)
        if clip:
            out = out.clip(0.0, 1.0)
        return out

    return apply_nary(fn, [data], name="MultiBoxPrior")


def math_sqrt(x):
    return float(x) ** 0.5


def MultiBoxTarget(anchor, label, cls_pred, overlap_threshold=0.5,
                   ignore_label=-1.0, negative_mining_ratio=3.0,
                   negative_mining_thresh=0.5, minimum_negative_samples=0,
                   variances=(0.1, 0.1, 0.2, 0.2)):
    """SSD training targets (reference: multibox_target.cc).

    anchor: (1, N, 4) corner; label: (B, M, 5) rows [cls, x0, y0, x1, y1]
    with cls=-1 padding; cls_pred: (B, num_cls+1, N).
    Returns (box_target (B, N*4), box_mask (B, N*4), cls_target (B, N)).
    """
    variances = jnp.asarray(variances)

    def fn(anc, lab, pred):
        anc = anc[0]
        n = anc.shape[0]

        def one(lb, pr):
            gt_valid = lb[:, 0] >= 0
            iou = _pairwise_iou(anc, lb[:, 1:5])     # (N, M)
            iou = jnp.where(gt_valid[None, :], iou, 0.0)
            best_gt = jnp.argmax(iou, axis=1)
            best_iou = jnp.max(iou, axis=1)
            pos = best_iou >= overlap_threshold
            # force-match: each VALID gt claims its best anchor; padded
            # rows scatter to a dropped slot n so they can't clobber
            # anchor 0 (their zeroed iou column argmaxes to 0)
            best_anchor = jnp.argmax(iou, axis=0)    # (M,)
            m = lb.shape[0]
            safe_anchor = jnp.where(gt_valid, best_anchor, n)
            forced = jnp.zeros(n + 1, bool).at[safe_anchor] \
                .set(True)[:n]
            pos = pos | forced
            forced_gt = jnp.zeros(n + 1, best_gt.dtype) \
                .at[safe_anchor].set(jnp.arange(m))[:n]
            best_gt = jnp.where(forced, forced_gt, best_gt)
            g = lb[best_gt.clip(0), 1:5]
            aw = anc[:, 2] - anc[:, 0]
            ah = anc[:, 3] - anc[:, 1]
            ax = (anc[:, 0] + anc[:, 2]) / 2
            ay = (anc[:, 1] + anc[:, 3]) / 2
            gw = g[:, 2] - g[:, 0]
            gh = g[:, 3] - g[:, 1]
            gx = (g[:, 0] + g[:, 2]) / 2
            gy = (g[:, 1] + g[:, 3]) / 2
            t = jnp.stack([(gx - ax) / jnp.maximum(aw, 1e-12) / variances[0],
                           (gy - ay) / jnp.maximum(ah, 1e-12) / variances[1],
                           jnp.log(jnp.maximum(gw, 1e-12) /
                                   jnp.maximum(aw, 1e-12)) / variances[2],
                           jnp.log(jnp.maximum(gh, 1e-12) /
                                   jnp.maximum(ah, 1e-12)) / variances[3]],
                          axis=-1)
            box_target = jnp.where(pos[:, None], t, 0.0).reshape(-1)
            box_mask = jnp.where(pos[:, None],
                                 jnp.ones_like(t), 0.0).reshape(-1)
            cls_target = jnp.where(pos, lb[best_gt.clip(0), 0] + 1, 0.0)
            # hard negative mining: keep top (ratio * num_pos) background by
            # max non-background confidence
            if negative_mining_ratio > 0:
                bg_conf = 1.0 - jax.nn.softmax(pr, axis=0)[0]
                neg_score = jnp.where(pos, -jnp.inf, bg_conf)
                num_pos = jnp.sum(pos)
                max_neg = jnp.maximum(
                    (negative_mining_ratio * num_pos).astype(jnp.int32),
                    minimum_negative_samples)
                rank = jnp.argsort(jnp.argsort(-neg_score))
                keep_neg = (rank < max_neg) & ~pos
                cls_target = jnp.where(pos | keep_neg, cls_target,
                                       ignore_label)
            return box_target, box_mask, cls_target

        bt, bm, ct = jax.vmap(one)(lab, pred)
        return bt, bm, ct

    return apply_nary(fn, [anchor, label, cls_pred], n_out=3,
                      name="MultiBoxTarget")


def MultiBoxDetection(cls_prob, loc_pred, anchor, clip=True, threshold=0.01,
                      background_id=0, nms_threshold=0.5, force_suppress=False,
                      variances=(0.1, 0.1, 0.2, 0.2), nms_topk=-1):
    """SSD decode + NMS (reference: multibox_detection.cc).

    cls_prob: (B, num_cls+1, N); loc_pred: (B, N*4); anchor: (1, N, 4).
    Returns (B, N, 6) rows [cls_id, score, x0, y0, x1, y1]; invalid rows have
    cls_id = -1.
    """
    variances = jnp.asarray(variances)

    def fn(cp, lp, anc):
        anc = anc[0]
        n = anc.shape[0]

        def one(p, loc):
            t = loc.reshape(n, 4) * variances
            aw = anc[:, 2] - anc[:, 0]
            ah = anc[:, 3] - anc[:, 1]
            ax = (anc[:, 0] + anc[:, 2]) / 2
            ay = (anc[:, 1] + anc[:, 3]) / 2
            ox = t[:, 0] * aw + ax
            oy = t[:, 1] * ah + ay
            ow = jnp.exp(t[:, 2]) * aw / 2
            oh = jnp.exp(t[:, 3]) * ah / 2
            boxes = jnp.stack([ox - ow, oy - oh, ox + ow, oy + oh], axis=-1)
            if clip:
                boxes = boxes.clip(0.0, 1.0)
            score = jnp.max(
                jnp.where(jnp.arange(p.shape[0])[:, None] == background_id,
                          -jnp.inf, p), axis=0)
            cls_id = jnp.argmax(
                jnp.where(jnp.arange(p.shape[0])[:, None] == background_id,
                          -jnp.inf, p), axis=0).astype(boxes.dtype) - \
                (1.0 if background_id == 0 else 0.0)
            cls_id = jnp.where(score > threshold, cls_id, -1.0)
            return jnp.concatenate(
                [cls_id[:, None], score[:, None], boxes], axis=-1)

        dets = jax.vmap(one)(cp, lp)
        return dets

    out = apply_nary(fn, [cls_prob, loc_pred, anchor], name="MultiBoxDecode")
    out = box_nms(out, overlap_thresh=nms_threshold, valid_thresh=threshold,
                  topk=nms_topk, coord_start=2, score_index=1, id_index=0,
                  force_suppress=force_suppress)
    return out


def getnnz(data, axis=None):
    """Count non-zeros (reference: contrib nnz for CSR)."""
    def fn(d):
        return jnp.sum(d != 0, axis=axis).astype(jnp.int64)
    return apply_nary(fn, [data], name="getnnz")


def quantize(data, min_range, max_range, out_type="uint8"):
    """Affine-quantize a tensor (reference: src/operator/quantization/)."""
    def fn(d, lo, hi):
        if out_type == "uint8":
            qmin, qmax = 0.0, 255.0
        else:
            qmin, qmax = -127.0, 127.0
        scale = (qmax - qmin) / jnp.maximum(hi - lo, 1e-12)
        q = jnp.clip(jnp.round((d - lo) * scale + qmin), qmin, qmax)
        # affine (min/max-range) cast: the scale was applied the line
        # above; the symmetric ops.quant_matmul helpers don't fit
        return q.astype(  # mxlint: disable=HB21
            jnp.uint8 if out_type == "uint8" else jnp.int8)
    return apply_nary(fn, [data, min_range, max_range], name="quantize")


def arange_like(data, start=0.0, step=1.0, axis=None, repeat=1):
    """Delegates to the single implementation in ops.py (reference
    init_op.cc arange_like; contrib exports the same op)."""
    from .ops import arange_like as _al
    return _al(data, start=start, step=step, repeat=repeat, axis=axis)


def fused_gelu(data):
    def fn(d):
        return jax.nn.gelu(d, approximate=False)
    return apply_nary(fn, [data], name="fused_gelu")


def BilinearResize2D(data, height=None, width=None, scale_height=None,
                     scale_width=None, like=None, mode="size",
                     align_corners=True):
    """Bilinear resize on NCHW (reference: src/operator/contrib/
    bilinear_resize.cc, whose coordinate map is (in-1)/(out-1), i.e.
    align_corners=True — the torch interpolate convention segmentation
    models were built against). align_corners=False falls back to the
    half-pixel mapping (jax.image.resize)."""
    if like is not None:
        height, width = like.shape[2], like.shape[3]

    def fn(d):
        h = height if height is not None else int(d.shape[2] * scale_height)
        w = width if width is not None else int(d.shape[3] * scale_width)
        if not align_corners:
            return jax.image.resize(d, d.shape[:2] + (h, w),
                                    method="bilinear")
        hi, wi = d.shape[2], d.shape[3]
        # out==1 on an axis: the (in-1)/(out-1) map degenerates; the
        # convention (torch/MXNet scale=0) samples the FIRST pixel
        rows = jnp.linspace(0.0, hi - 1.0, h) if h > 1 else \
            jnp.zeros((1,))
        cols = jnp.linspace(0.0, wi - 1.0, w) if w > 1 else \
            jnp.zeros((1,))
        r0 = jnp.clip(jnp.floor(rows).astype(jnp.int32), 0, hi - 1)
        r1 = jnp.clip(r0 + 1, 0, hi - 1)
        fr = (rows - r0).astype(d.dtype)[None, None, :, None]
        c0 = jnp.clip(jnp.floor(cols).astype(jnp.int32), 0, wi - 1)
        c1 = jnp.clip(c0 + 1, 0, wi - 1)
        fc = (cols - c0).astype(d.dtype)[None, None, None, :]
        top = d[:, :, r0, :] * (1 - fr) + d[:, :, r1, :] * fr
        return top[:, :, :, c0] * (1 - fc) + top[:, :, :, c1] * fc

    return apply_nary(fn, [data], name="BilinearResize2D")


def AdaptiveAvgPooling2D(data, output_size=1):
    """Adaptive average pool to a target (H, W) (reference:
    src/operator/contrib/adaptive_avg_pooling.cc)."""
    oh, ow = (output_size if isinstance(output_size, (tuple, list))
              else (output_size, output_size))

    def fn(d):
        b, c, h, w = d.shape
        # split H/W into oh/ow nearly-equal bins (static python loop)
        rows = [d[:, :, (i * h) // oh:((i + 1) * h + oh - 1) // oh or 1, :]
                .mean(axis=2, keepdims=True) for i in range(oh)]
        col = jnp.concatenate(rows, axis=2)
        cols = [col[:, :, :, (j * w) // ow:((j + 1) * w + ow - 1) // ow or 1]
                .mean(axis=3, keepdims=True) for j in range(ow)]
        return jnp.concatenate(cols, axis=3)

    return apply_nary(fn, [data], name="AdaptiveAvgPooling2D")


def DeformableConvolution(data, offset, weight, bias=None, kernel=(3, 3),
                          stride=(1, 1), pad=(0, 0), dilate=(1, 1),
                          num_filter=1, num_deformable_group=1,
                          no_bias=False, **kwargs):
    """Deformable convolution v1 (reference:
    src/operator/contrib/deformable_convolution.cc — Dai et al. 2017).

    data (B, C, H, W); offset (B, dg*2*kh*kw, Ho, Wo) with per-tap (y, x)
    offset pairs; weight (O, C, kh, kw). TPU-native: the deformable im2col
    is a vmapped bilinear gather (VPU) feeding ONE big (O, C*kh*kw) x
    (C*kh*kw, Ho*Wo) matmul (MXU) — no per-pixel scalar loops.
    """
    from .ndarray import NDArray, apply_nary
    kh, kw = kernel
    sh, sw = stride
    ph, pw = pad
    dh, dw = dilate
    dg = num_deformable_group

    def fn(*arrs):
        d, off, w = arrs[0], arrs[1], arrs[2]
        b = arrs[3] if len(arrs) > 3 else None
        B, C, H, W = d.shape
        O = w.shape[0]
        Ho = (H + 2 * ph - dh * (kh - 1) - 1) // sh + 1
        Wo = (W + 2 * pw - dw * (kw - 1) - 1) // sw + 1
        base_y = jnp.arange(Ho) * sh - ph          # (Ho,)
        base_x = jnp.arange(Wo) * sw - pw
        off = off.reshape(B, dg, kh * kw, 2, Ho, Wo)
        d_grp = d.reshape(B, dg, C // dg, H, W)

        def sample(img, py, px):
            # img (Cg, H, W); py/px (Ho, Wo) absolute float coords
            y0 = jnp.floor(py)
            x0 = jnp.floor(px)
            wy = py - y0
            wx = px - x0

            def at(yy, xx):
                yi = jnp.clip(yy.astype(jnp.int32), 0, H - 1)
                xi = jnp.clip(xx.astype(jnp.int32), 0, W - 1)
                valid = ((yy >= 0) & (yy <= H - 1) &
                         (xx >= 0) & (xx <= W - 1)).astype(img.dtype)
                return img[:, yi, xi] * valid[None]
            return (at(y0, x0) * (1 - wy) * (1 - wx) +
                    at(y0, x0 + 1) * (1 - wy) * wx +
                    at(y0 + 1, x0) * wy * (1 - wx) +
                    at(y0 + 1, x0 + 1) * wy * wx)     # (Cg, Ho, Wo)

        def one_image(img_g, off_g):
            # img_g (dg, Cg, H, W); off_g (dg, kh*kw, 2, Ho, Wo)
            def one_group(img, offs):
                def one_tap(t):
                    i, j = t // kw, t % kw
                    py = base_y[:, None] + i * dh + offs[t, 0]
                    px = base_x[None, :] + j * dw + offs[t, 1]
                    return sample(img, py, px)        # (Cg, Ho, Wo)
                taps = jax.vmap(one_tap)(jnp.arange(kh * kw))
                return taps                            # (K, Cg, Ho, Wo)
            cols = jax.vmap(one_group)(img_g, off_g)   # (dg, K, Cg, Ho, Wo)
            # -> (C*kh*kw, Ho*Wo) with channel-major layout matching the
            # (O, C, kh, kw) weight flatten
            cols = jnp.transpose(cols, (0, 2, 1, 3, 4))   # (dg, Cg, K, ...)
            return cols.reshape(C * kh * kw, Ho * Wo)

        cols = jax.vmap(one_image)(d_grp, off)         # (B, C*K, Ho*Wo)
        wm = w.reshape(O, C * kh * kw)
        out = jnp.einsum("ok,bkn->bon", wm, cols,
                         preferred_element_type=jnp.float32)
        out = out.reshape(B, O, Ho, Wo).astype(d.dtype)
        if b is not None:
            out = out + b.reshape(1, O, 1, 1)
        return out

    inputs = [data, offset, weight]
    if bias is not None and not no_bias:
        inputs.append(bias)
    return apply_nary(fn, inputs, name="DeformableConvolution")


# ----------------------------------------------------------------------
# round-3 contrib tail (reference: src/operator/contrib/{boolean_mask,
# index_copy,index_array,allclose,gradient_multiplier_op,fft,count_sketch}.cc)
# ----------------------------------------------------------------------

def _as_nd(x, like=None):
    if isinstance(x, NDArray):
        return x
    from .ndarray import array
    return array(x, ctx=like._ctx if like is not None else None)


def boolean_mask(data, index, axis=0):
    """Select rows where index!=0. Output size is data-dependent — eager
    only (reference boolean_mask has the same dynamic-shape nature; its
    CachedOp path also bails to imperative)."""
    def fn(d, idx):
        keep = jnp.nonzero(idx.astype(bool))[0]
        return jnp.take(d, keep, axis=axis)
    return apply_nary(fn, [data, _as_nd(index, data)], name="boolean_mask")


def index_copy(old_tensor, index_vector, new_tensor):
    """Copy new_tensor rows into old_tensor at index_vector (reference
    index_copy: out-of-place, differentiable w.r.t. both tensors)."""
    def fn(old, idx, new):
        return old.at[idx.astype(jnp.int32)].set(new)
    return apply_nary(fn, [old_tensor, _as_nd(index_vector, old_tensor),
                           _as_nd(new_tensor, old_tensor)],
                      name="index_copy")


def index_array(data, axes=None):
    """Return an int64 array of index coordinates of data's shape
    (reference index_array): out[i_0,..,i_{n-1}] = (i_0,..,i_{n-1}),
    optionally restricted to `axes`."""
    def fn(d):
        sel = range(d.ndim) if axes is None else axes
        dt = jnp.int64 if jax.config.jax_enable_x64 else jnp.int32
        # build only the selected axes: arange along axis a broadcast to
        # the full shape (no O(ndim * numel) meshgrid materialization)
        grids = [jnp.broadcast_to(
            jnp.arange(d.shape[a], dtype=dt).reshape(
                tuple(d.shape[a] if i == a else 1
                      for i in range(d.ndim))), d.shape) for a in sel]
        return jnp.stack(grids, axis=-1)
    return apply_nary(fn, [data], name="index_array")


def allclose(a, b, rtol=1e-5, atol=1e-8, equal_nan=True):
    """Scalar 1.0/0.0 allclose (reference contrib/allclose_op.cc)."""
    def fn(x, y):
        return jnp.allclose(x, y, rtol=rtol, atol=atol,
                            equal_nan=equal_nan).astype(jnp.float32)
    return apply_nary(fn, [a, _as_nd(b, a)], name="allclose")


def gradientmultiplier(data, scalar=1.0):
    """Identity forward, gradient scaled by `scalar` (reference
    gradient_multiplier_op.cc — the gradient-reversal-layer primitive when
    scalar is negative)."""
    @jax.custom_vjp
    def fwd(d):
        return d

    def fwd_fwd(d):
        return d, None

    def fwd_bwd(_, g):
        return (g * scalar,)

    fwd.defvjp(fwd_fwd, fwd_bwd)
    return apply_nary(fwd, [data], name="gradientmultiplier")


def fft(data, compute_size=128):
    """FFT along the last axis, complex output interleaved as
    (..., 2*n) real/imag pairs (reference contrib/fft.cc layout).

    `compute_size` (the reference's cuFFT batching knob) is accepted for API
    compatibility and has no effect: XLA schedules the whole batch itself."""
    def fn(d):
        c = jnp.fft.fft(d, axis=-1)
        out = jnp.stack([c.real, c.imag], axis=-1)
        return out.reshape(d.shape[:-1] + (2 * d.shape[-1],)) \
            .astype(jnp.float32)
    return apply_nary(fn, [data], name="fft")


def ifft(data, compute_size=128):
    """Inverse of contrib.fft: input (..., 2*n) interleaved real/imag,
    output (..., n) real part, scaled by n like the reference (which
    does not normalize, leaving the caller to divide).

    `compute_size` is accepted for API compatibility and has no effect
    under XLA (see contrib.fft)."""
    def fn(d):
        n = d.shape[-1] // 2
        pairs = d.reshape(d.shape[:-1] + (n, 2))
        c = lax.complex(pairs[..., 0], pairs[..., 1])
        return jnp.fft.ifft(c, axis=-1).real.astype(jnp.float32) * n
    return apply_nary(fn, [data], name="ifft")


def count_sketch(data, h, s, out_dim=None, processing_batch_size=32):
    """Count sketch projection (reference contrib/count_sketch.cc):
    out[..., h[j]] += s[j] * data[..., j]; h in [0, out_dim), s in ±1.

    `processing_batch_size` (the reference's CUDA batching knob) is accepted
    for API compatibility and has no effect: XLA tiles the scatter itself."""
    if out_dim is None:
        raise MXNetError("count_sketch requires out_dim")
    def fn(d, hh, ss):
        idx = hh.astype(jnp.int32).reshape(-1)
        sign = ss.reshape(-1).astype(d.dtype)
        flat = d.reshape(-1, d.shape[-1])
        out = jnp.zeros((flat.shape[0], out_dim), d.dtype)
        out = out.at[:, idx].add(flat * sign[None, :])
        return out.reshape(d.shape[:-1] + (out_dim,))
    return apply_nary(fn, [data, _as_nd(h, data), _as_nd(s, data)],
                      name="count_sketch")


def quadratic(data, a=0.0, b=0.0, c=0.0):
    """a*x^2 + b*x + c elementwise (reference contrib/quadratic_op.cc —
    the tutorial op; kept for API parity and example code)."""
    def fn(d):
        return a * d * d + b * d + c
    return apply_nary(fn, [data], name="quadratic")


def div_sqrt_dim(data):
    """data / sqrt(last_dim) — attention-logit scaling helper (reference
    contrib/transformer.cc div_sqrt_dim)."""
    def fn(d):
        return d / jnp.sqrt(jnp.asarray(d.shape[-1], d.dtype))
    return apply_nary(fn, [data], name="div_sqrt_dim")


def edge_id(data, u, v):
    """Edge ids of (u[i], v[i]) pairs in a CSR adjacency matrix, -1 when
    absent (reference contrib/dgl_graph.cc EdgeID).  Host-side numpy —
    graph bookkeeping is data-prep, not device compute, here exactly as
    in the reference (CPU-only op there too)."""
    import numpy as np
    from .sparse import CSRNDArray
    if not isinstance(data, CSRNDArray):
        raise MXNetError("edge_id expects a CSRNDArray adjacency")
    indptr = np.asarray(data._indptr)
    cols = np.asarray(data._indices_csr)
    uu = np.asarray(getattr(u, "asnumpy", lambda: u)()).astype(np.int64)
    vv = np.asarray(getattr(v, "asnumpy", lambda: v)()).astype(np.int64)
    out = np.full(uu.shape, -1.0, np.float32)
    for i, (ru, cv) in enumerate(zip(uu.ravel(), vv.ravel())):
        lo, hi = indptr[ru], indptr[ru + 1]
        hits = np.nonzero(cols[lo:hi] == cv)[0]
        if hits.size:
            out.ravel()[i] = float(lo + hits[0])
    from .ndarray import array as _array
    return _array(out)


def _generate_anchors(stride, scales, ratios):
    """Base anchors for one feature cell (reference
    contrib/proposal.cc GenerateAnchors): base box [0,0,stride-1,stride-1]
    enumerated over ratios then scales, centered on the cell."""
    base = jnp.asarray([0.0, 0.0, stride - 1.0, stride - 1.0])
    w = base[2] - base[0] + 1.0
    h = base[3] - base[1] + 1.0
    cx = base[0] + 0.5 * (w - 1.0)
    cy = base[1] + 0.5 * (h - 1.0)
    anchors = []
    for r in ratios:
        size = w * h
        ws = jnp.round(jnp.sqrt(size / r))
        hs = jnp.round(ws * r)
        for s in scales:
            wss, hss = ws * s, hs * s
            anchors.append(jnp.stack([cx - 0.5 * (wss - 1.0),
                                      cy - 0.5 * (hss - 1.0),
                                      cx + 0.5 * (wss - 1.0),
                                      cy + 0.5 * (hss - 1.0)]))
    return jnp.stack(anchors)          # (A, 4)


def _proposal_one(scores, deltas, im_info, anchors, stride,
                  pre_nms, post_nms, thresh, min_size):
    """Static-shape RPN proposal for ONE image: shift anchors over the
    grid, apply deltas, clip, min-size filter, top-k + fixed-trip NMS."""
    A = anchors.shape[0]
    H, W = scores.shape[-2:]
    shift_x = jnp.arange(W) * stride
    shift_y = jnp.arange(H) * stride
    sx, sy = jnp.meshgrid(shift_x, shift_y)            # (H, W)
    shifts = jnp.stack([sx, sy, sx, sy], axis=-1).reshape(-1, 1, 4)
    boxes = (anchors[None] + shifts).reshape(-1, 4)     # (H*W*A, 4)
    # deltas (4A, H, W) -> (H*W*A, 4); scores (A, H, W) -> (H*W*A,)
    d = deltas.reshape(A, 4, H, W).transpose(2, 3, 0, 1).reshape(-1, 4)
    s = scores.reshape(A, H, W).transpose(1, 2, 0).reshape(-1)
    # bbox transform inv (center-offset parameterization)
    widths = boxes[:, 2] - boxes[:, 0] + 1.0
    heights = boxes[:, 3] - boxes[:, 1] + 1.0
    ctr_x = boxes[:, 0] + 0.5 * (widths - 1.0)
    ctr_y = boxes[:, 1] + 0.5 * (heights - 1.0)
    px = d[:, 0] * widths + ctr_x
    py = d[:, 1] * heights + ctr_y
    pw = jnp.exp(jnp.clip(d[:, 2], -10.0, 10.0)) * widths
    ph = jnp.exp(jnp.clip(d[:, 3], -10.0, 10.0)) * heights
    prop = jnp.stack([px - 0.5 * (pw - 1.0), py - 0.5 * (ph - 1.0),
                      px + 0.5 * (pw - 1.0), py + 0.5 * (ph - 1.0)],
                     axis=-1)
    # clip to image, drop boxes under the scaled min size
    hlim, wlim = im_info[0] - 1.0, im_info[1] - 1.0
    prop = jnp.stack([jnp.clip(prop[:, 0], 0.0, wlim),
                      jnp.clip(prop[:, 1], 0.0, hlim),
                      jnp.clip(prop[:, 2], 0.0, wlim),
                      jnp.clip(prop[:, 3], 0.0, hlim)], axis=-1)
    ms = min_size * im_info[2]
    keepable = ((prop[:, 2] - prop[:, 0] + 1.0 >= ms) &
                (prop[:, 3] - prop[:, 1] + 1.0 >= ms))
    s = jnp.where(keepable, s, -jnp.inf)

    k = min(pre_nms, prop.shape[0])
    top_s, top_i = jax.lax.top_k(s, k)
    top_b = prop[top_i]
    # IoU in the op's own +1-pixel area convention (matches the widths/
    # min-size math above; _pairwise_iou's x2-x1 areas would zero out
    # 1-pixel boxes and flip borderline suppression decisions)
    l, r = top_b[:, None, :], top_b[None, :, :]
    tl = jnp.maximum(l[..., :2], r[..., :2])
    br = jnp.minimum(l[..., 2:], r[..., 2:])
    wh = jnp.maximum(br - tl + 1.0, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    area = ((top_b[:, 2] - top_b[:, 0] + 1.0) *
            (top_b[:, 3] - top_b[:, 1] + 1.0))
    iou = inter / jnp.maximum(area[:, None] + area[None, :] - inter, 1e-12)

    def body(i, keep):
        sup = (iou[i] > thresh) & (jnp.arange(k) > i)
        return jnp.where(keep[i] & jnp.isfinite(top_s[i]),
                         keep & ~sup, keep)

    keep = lax.fori_loop(0, k, body, jnp.ones(k, bool))
    keep = keep & jnp.isfinite(top_s)
    # stable selection of the first post_nms kept boxes, zero-padded
    rank = jnp.cumsum(keep) - 1
    sel = jnp.where(keep & (rank < post_nms), rank, post_nms)
    out_b = jnp.zeros((post_nms + 1, 4)).at[sel].set(top_b)[:post_nms]
    out_s = jnp.zeros((post_nms + 1,)).at[sel].set(top_s)[:post_nms]
    return out_b, out_s


def MultiProposal(cls_prob, bbox_pred, im_info, rpn_pre_nms_top_n=6000,
                  rpn_post_nms_top_n=300, threshold=0.7, rpn_min_size=16,
                  scales=(4, 8, 16, 32), ratios=(0.5, 1, 2),
                  feature_stride=16, output_score=False, iou_loss=False):
    """Batched RPN proposal generation (reference
    contrib/multi_proposal.cc): anchors + deltas -> clipped, NMS-pruned
    rois (B*post_n, 5) with batch index in column 0.  Static shapes
    throughout (top_k + fixed-trip NMS) so the op jits on TPU.
    ``iou_loss`` is not supported (niche IoU-parameterized variant)."""
    if iou_loss:
        raise MXNetError("MultiProposal: iou_loss=True is not supported; "
                         "use the default bbox-delta parameterization")
    A = len(scales) * len(ratios)
    anchors = _generate_anchors(float(feature_stride),
                                [float(s) for s in scales],
                                [float(r) for r in ratios])

    def fn(cp, bp, info):
        B = cp.shape[0]
        fg = cp[:, A:, :, :]        # (B, A, H, W) foreground scores

        def one(args):
            return _proposal_one(args[0], args[1], args[2], anchors,
                                 float(feature_stride),
                                 int(rpn_pre_nms_top_n),
                                 int(rpn_post_nms_top_n),
                                 float(threshold), float(rpn_min_size))

        boxes, scores = jax.vmap(one)((fg, bp, info))
        bidx = jnp.repeat(jnp.arange(B, dtype=boxes.dtype),
                          int(rpn_post_nms_top_n))
        rois = jnp.concatenate(
            [bidx[:, None], boxes.reshape(-1, 4)], axis=-1)
        if output_score:
            return rois, scores.reshape(-1, 1)
        return rois

    n_out = 2 if output_score else 1
    return apply_nary(fn, [cls_prob, bbox_pred, im_info], n_out=n_out,
                      name="MultiProposal")


def Proposal(cls_prob, bbox_pred, im_info, **kwargs):
    """Single-image RPN proposal op (reference contrib/proposal.cc);
    batch must be 1 — use MultiProposal for batched inputs."""
    if cls_prob.shape[0] != 1:
        raise MXNetError("Proposal expects batch size 1; "
                         "use MultiProposal for batched inputs")
    return MultiProposal(cls_prob, bbox_pred, im_info, **kwargs)


def fused_linear_cross_entropy(data, weight, targets, block=2048,
                               ignore_index=None):
    """Fused LM-head + CE with blocked vocabulary: per-token loss of
    ``softmax(data @ weight)`` without ever materializing the (N, V)
    logits (O(N*block) peak memory, backward recomputes block softmax).
    See mxnet_tpu/ops/blocked_cross_entropy.py; the reference computes CE
    on materialized logits (src/operator/nn/softmax.cc) — this is the
    TPU-first large-vocab/long-context replacement."""
    from ..ops.blocked_cross_entropy import fused_linear_cross_entropy as f

    def fn(x, w, t):
        return f(x, w, t.astype(jnp.int32), block=block,
                 ignore_index=ignore_index)

    return apply_nary(fn, [data, weight, _as_nd(targets, data)],
                      name="fused_linear_cross_entropy")
