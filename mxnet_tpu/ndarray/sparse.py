"""Sparse NDArray: ``row_sparse`` and ``csr`` storage types.

Reference: python/mxnet/ndarray/sparse.py + src/operator/tensor/cast_storage*,
dot(csr,dense), sparse_retain (SURVEY.md §2.1 "Sparse ops"). TPU disposition:
both stypes keep their native compressed representation — densification is
LAZY and happens only when a dense-only op touches ``.data`` (VERDICT r1 #5:
the previous version densified on construction, erasing the memory benefit).
Sparse-aware paths (``retain``, ``dot(csr, dense)``, kvstore
``row_sparse_pull``, the optimizers' lazy updates) work on the compressed
pair directly and never materialize the dense array.
"""
from __future__ import annotations

import numpy as _np
import jax
import jax.numpy as jnp

from ..base import MXNetError
from ..context import current_context
from .ndarray import NDArray, array, _dtype_of

__all__ = ["RowSparseNDArray", "CSRNDArray", "row_sparse_array", "csr_matrix",
           "zeros", "retain", "dot", "sum_duplicate_rows"]


def sum_duplicate_rows(indices, values):
    """Sum values whose row index repeats: the one shared 'merge row-sparse
    pairs' kernel (used by the tape's SparseCotangent, the kvstore reduce,
    and retain). indices: int array (n,); values: (n, ...) — returns
    (unique_sorted_indices, summed_values)."""
    idx = _np.asarray(indices)
    uniq, inv = _np.unique(idx, return_inverse=True)
    if len(uniq) == len(idx) and (idx == uniq).all():
        return jnp.asarray(idx), values
    summed = jax.ops.segment_sum(values, jnp.asarray(inv),
                                 num_segments=len(uniq))
    return jnp.asarray(uniq, jnp.asarray(indices).dtype), summed

_LAZY = object()   # sentinel: "dense view not materialized"


def _index_dtype():
    """Row-index dtype: int64 under MXTPU_INT64/x64, else int32 (no
    truncation warning — the narrowing is part of the storage design)."""
    return jnp.int64 if jax.config.jax_enable_x64 else jnp.int32


class BaseSparseNDArray(NDArray):
    __slots__ = ()


class RowSparseNDArray(BaseSparseNDArray):
    """indices (int rows) + values (rows x trailing dims) — no dense array
    is stored until a dense-only op asks for one.

    ``.data`` densifies lazily (scatter on device); kvstore row_sparse
    push/pull, ``retain`` and the sparse optimizer paths use
    ``.indices``/``.values`` directly.
    """

    __slots__ = ("_indices", "_values", "_dense_shape", "_dense_cache",
                 "_sparse_stale")

    def __init__(self, values, indices, shape, ctx=None):
        self._indices = indices
        self._values = values
        self._dense_shape = tuple(int(s) for s in shape)
        self._dense_cache = None
        self._sparse_stale = False
        super().__init__(_LAZY, ctx or current_context())

    def __reduce__(self):
        """Pickle the COMPRESSED representation (base NDArray.__reduce__
        would densify and come back dense, losing stype)."""
        return (_row_sparse_from_host,
                (_np.asarray(self._values), _np.asarray(self._indices),
                 self._dense_shape))

    # -- lazy dense view ------------------------------------------------
    @property
    def _data(self):
        if self._dense_cache is None:
            self._dense_cache = jnp.zeros(
                self._dense_shape, self._values.dtype
            ).at[self._indices].set(self._values)
        return self._dense_cache

    @_data.setter
    def _data(self, v):
        if v is _LAZY:
            return
        # a dense write (e.g. an optimizer dense update) invalidates the
        # compressed pair; it is recomputed on next .indices/.values access
        self._dense_cache = v
        self._sparse_stale = True

    def _sync_handles(self):
        if self._sparse_stale or self._dense_cache is not None:
            return (self._dense_cache,)
        return (self._indices, self._values)

    def _refresh_sparse(self):
        if self._sparse_stale:
            d = self._dense_cache
            # device-side recovery (r2 weak #7): row mask + gather stay on
            # device; only the O(rows) mask syncs to size the result —
            # never the O(rows x dim) dense payload
            mask = jnp.any(d != 0, axis=tuple(range(1, d.ndim)))
            nz = jnp.nonzero(mask)[0]
            self._indices = nz.astype(self._indices.dtype)
            self._values = jnp.take(d, nz, axis=0)
            self._sparse_stale = False

    # -- shape/dtype without densifying ---------------------------------
    @property
    def shape(self):
        return self._dense_shape

    @property
    def ndim(self):
        return len(self._dense_shape)

    @property
    def dtype(self):
        dt = (self._dense_cache.dtype if self._sparse_stale
              else self._values.dtype)
        return _np.dtype(dt) if dt != jnp.bfloat16 else dt

    @property
    def stype(self):
        return "row_sparse"

    @property
    def indices(self):
        self._refresh_sparse()
        return NDArray(self._indices, self._ctx)

    @property
    def values(self):
        self._refresh_sparse()
        return NDArray(self._values, self._ctx)

    data_nd = values

    def tostype(self, stype):
        if stype == "default":
            return NDArray(self._data, self._ctx)
        if stype == "row_sparse":
            return self
        raise MXNetError(f"cannot convert row_sparse to {stype}")

    def retain(self, indices):
        return retain(self, indices)

    def copyto(self, other):
        if isinstance(other, RowSparseNDArray):
            self._refresh_sparse()
            other._indices = self._indices
            other._values = self._values
            other._dense_shape = self._dense_shape
            other._dense_cache = None
            other._sparse_stale = False
            return other
        return super().copyto(other)

    def __repr__(self):
        self._refresh_sparse()
        return (f"\n<RowSparseNDArray {self._dense_shape} "
                f"({len(_np.asarray(self._indices))} rows stored) "
                f"@{self._ctx}>")


class CSRNDArray(BaseSparseNDArray):
    __slots__ = ("_indptr", "_indices_csr", "_values_csr", "_dense_shape",
                 "_dense_cache")

    def __init__(self, data_vals, indptr, indices, shape, ctx=None):
        self._indptr = _np.asarray(indptr)
        self._indices_csr = _np.asarray(indices)
        self._values_csr = data_vals
        self._dense_shape = tuple(int(s) for s in shape)
        self._dense_cache = None
        super().__init__(_LAZY, ctx or current_context())

    def __reduce__(self):
        """Pickle the compressed CSR triple, not the dense view."""
        return (_csr_from_host,
                (_np.asarray(self._values_csr), self._indptr.copy(),
                 self._indices_csr.copy(), self._dense_shape))

    @property
    def _data(self):
        if self._dense_cache is None:
            rows = _np.repeat(_np.arange(self._dense_shape[0]),
                              _np.diff(self._indptr))
            self._dense_cache = jnp.zeros(
                self._dense_shape, _np.asarray(self._values_csr).dtype
            ).at[jnp.asarray(rows), jnp.asarray(self._indices_csr)].set(
                jnp.asarray(self._values_csr))
        return self._dense_cache

    @_data.setter
    def _data(self, v):
        if v is _LAZY:
            return
        raise MXNetError("CSRNDArray is read-only; convert with "
                         "tostype('default') first")

    @property
    def shape(self):
        return self._dense_shape

    @property
    def ndim(self):
        return len(self._dense_shape)

    @property
    def dtype(self):
        dt = _np.asarray(self._values_csr).dtype
        return _np.dtype(dt)

    @property
    def stype(self):
        return "csr"

    def _sync_handles(self):
        if self._dense_cache is not None:
            return (self._dense_cache,)
        v = self._values_csr
        return (v,) if hasattr(v, "block_until_ready") else ()

    @property
    def indptr(self):
        return NDArray(jnp.asarray(self._indptr), self._ctx)

    @property
    def indices(self):
        return NDArray(jnp.asarray(self._indices_csr), self._ctx)

    @property
    def values(self):
        return NDArray(jnp.asarray(self._values_csr), self._ctx)

    def tostype(self, stype):
        if stype == "default":
            return NDArray(self._data, self._ctx)
        if stype == "csr":
            return self
        raise MXNetError(f"cannot convert csr to {stype}")


def row_sparse_array(arg1, shape=None, ctx=None, dtype=None):
    if isinstance(arg1, tuple) and len(arg1) == 2:
        values, indices = arg1
        values = jnp.asarray(getattr(values, "data", values),
                             dtype=_dtype_of(dtype))
        indices = jnp.asarray(getattr(indices, "data", indices),
                              _index_dtype())
        return RowSparseNDArray(values, indices, shape, ctx)
    dense = array(arg1, ctx=ctx, dtype=dtype)
    np_d = dense.asnumpy()
    nz_rows = _np.where(_np.any(np_d != 0, axis=tuple(range(1, np_d.ndim))))[0]
    return RowSparseNDArray(jnp.asarray(np_d[nz_rows]),
                            jnp.asarray(nz_rows, _index_dtype()),
                            np_d.shape, ctx)


def csr_matrix(arg1, shape=None, ctx=None, dtype=None):
    if isinstance(arg1, tuple) and len(arg1) == 3:
        data_vals, indices, indptr = arg1
        return CSRNDArray(_np.asarray(getattr(data_vals, "data", data_vals)),
                          _np.asarray(getattr(indptr, "data", indptr)),
                          _np.asarray(getattr(indices, "data", indices)),
                          shape, ctx)
    dense = _np.asarray(array(arg1, ctx=ctx, dtype=dtype).asnumpy())
    nz_r, nz_c = _np.nonzero(dense)
    vals = dense[nz_r, nz_c]
    indptr = _np.zeros(dense.shape[0] + 1, _np.int64)
    _np.add.at(indptr, nz_r + 1, 1)
    indptr = _np.cumsum(indptr)
    return CSRNDArray(vals, indptr, nz_c, dense.shape, ctx)


def zeros(stype, shape, ctx=None, dtype=None):
    dt = _dtype_of(dtype)
    if stype == "row_sparse":
        return RowSparseNDArray(jnp.zeros((0,) + tuple(shape[1:]), dt),
                                jnp.zeros((0,), _index_dtype()),
                                shape, ctx)
    if stype == "csr":
        return CSRNDArray(
            _np.zeros((0,), _np.dtype("float32") if dtype is None else dtype),
            _np.zeros(shape[0] + 1, _np.int64),
            _np.zeros((0,), _np.int64), shape, ctx)
    from .ndarray import zeros as dzeros
    return dzeros(shape, ctx, dtype)


def retain(data, indices):
    """sparse_retain: keep only the given rows — works on the compressed
    pair, never densifies. Reference: src/operator/tensor/sparse_retain.cc."""
    if not isinstance(data, RowSparseNDArray):
        raise MXNetError("retain expects a RowSparseNDArray")
    data._refresh_sparse()
    stored = _np.asarray(data._indices)
    req = _np.asarray(getattr(indices, "data", indices)).astype(stored.dtype)
    keep = _np.isin(stored, req)
    pos = _np.where(keep)[0]
    new_vals = jnp.take(data._values, jnp.asarray(pos), axis=0) \
        if len(pos) else jnp.zeros((0,) + data._dense_shape[1:],
                                   data._values.dtype)
    return RowSparseNDArray(new_vals, jnp.asarray(stored[keep]),
                            data._dense_shape, data._ctx)


def dot(lhs, rhs, transpose_a=False, transpose_b=False):
    """dot with a sparse lhs. csr x dense runs as an nnz-proportional
    gather + segment_sum (no densification); everything else falls back to
    the dense op. Reference: src/operator/tensor/dot.cc DotCsrDnsDns."""
    if isinstance(lhs, CSRNDArray) and not transpose_a and \
            isinstance(rhs, NDArray) and not isinstance(rhs, BaseSparseNDArray):
        b = rhs.data
        if transpose_b:
            b = b.T
        nrows = lhs._dense_shape[0]
        rows = _np.repeat(_np.arange(nrows), _np.diff(lhs._indptr))
        vals = jnp.asarray(lhs._values_csr)
        cols = jnp.asarray(lhs._indices_csr)
        if vals.shape[0] == 0:
            out = jnp.zeros((nrows, b.shape[1]), b.dtype)
        else:
            contrib = vals[:, None] * jnp.take(b, cols, axis=0)
            out = jax.ops.segment_sum(contrib, jnp.asarray(rows),
                                      num_segments=nrows)
        return NDArray(out, lhs._ctx)
    from . import ops as _ops
    return _ops.dot(lhs, rhs, transpose_a=transpose_a,
                    transpose_b=transpose_b)

def _row_sparse_from_host(values, indices, shape):
    """Unpickle target: re-materialize on the unpickler's default device."""
    return RowSparseNDArray(jnp.asarray(values), jnp.asarray(indices), shape)


def _csr_from_host(values, indptr, indices, shape):
    return CSRNDArray(jnp.asarray(values), indptr, indices, shape)
