"""Sparse NDArray: ``row_sparse`` and ``csr`` storage types.

Reference: python/mxnet/ndarray/sparse.py + src/operator/tensor/cast_storage*,
dot(csr,dense), sparse_retain (SURVEY.md §2.1 "Sparse ops"). TPU disposition:
row_sparse keeps its native (indices, values) pair — it is essentially a
gather/scatter representation that maps well to TPU dynamic-slice — while csr
is backed by jax.experimental.sparse BCSR when available, dense fallback
otherwise (XLA:TPU has no sparse codegen; honesty over pretense).
"""
from __future__ import annotations

import numpy as _np
import jax
import jax.numpy as jnp

from ..base import MXNetError
from ..context import current_context
from .ndarray import NDArray, array, _dtype_of

__all__ = ["RowSparseNDArray", "CSRNDArray", "row_sparse_array", "csr_matrix",
           "zeros", "retain", "dot"]


class BaseSparseNDArray(NDArray):
    __slots__ = ()


class RowSparseNDArray(BaseSparseNDArray):
    """indices (int64 rows) + values (rows x trailing dims).

    ``.data`` densifies lazily; kvstore row_sparse push/pull and the sparse
    optimizer paths use ``.indices``/``.values`` directly.
    """

    __slots__ = ("_indices", "_values", "_dense_shape")

    def __init__(self, values, indices, shape, ctx=None):
        self._indices = indices
        self._values = values
        self._dense_shape = tuple(shape)
        dense = jnp.zeros(shape, values.dtype).at[indices].set(values)
        super().__init__(dense, ctx or current_context())

    @property
    def stype(self):
        return "row_sparse"

    @property
    def indices(self):
        return NDArray(self._indices, self._ctx)

    @property
    def values(self):
        return NDArray(self._values, self._ctx)

    data_nd = values

    def tostype(self, stype):
        if stype == "default":
            return NDArray(self._data, self._ctx)
        if stype == "row_sparse":
            return self
        raise MXNetError(f"cannot convert row_sparse to {stype}")

    def retain(self, indices):
        return retain(self, indices)

    def __repr__(self):
        return (f"\n<RowSparseNDArray {self._dense_shape} "
                f"({len(_np.asarray(self._indices))} rows stored) @{self._ctx}>")


class CSRNDArray(BaseSparseNDArray):
    __slots__ = ("_indptr", "_indices_csr", "_values_csr", "_dense_shape")

    def __init__(self, data_vals, indptr, indices, shape, ctx=None):
        self._indptr = indptr
        self._indices_csr = indices
        self._values_csr = data_vals
        self._dense_shape = tuple(shape)
        dense = _np.zeros(shape, dtype=_np.asarray(data_vals).dtype)
        ip = _np.asarray(indptr)
        ix = _np.asarray(indices)
        vals = _np.asarray(data_vals)
        for r in range(shape[0]):
            dense[r, ix[ip[r]:ip[r + 1]]] = vals[ip[r]:ip[r + 1]]
        super().__init__(jnp.asarray(dense), ctx or current_context())

    @property
    def stype(self):
        return "csr"

    @property
    def indptr(self):
        return NDArray(jnp.asarray(self._indptr), self._ctx)

    @property
    def indices(self):
        return NDArray(jnp.asarray(self._indices_csr), self._ctx)

    def tostype(self, stype):
        if stype == "default":
            return NDArray(self._data, self._ctx)
        if stype == "csr":
            return self
        raise MXNetError(f"cannot convert csr to {stype}")


def row_sparse_array(arg1, shape=None, ctx=None, dtype=None):
    if isinstance(arg1, tuple) and len(arg1) == 2:
        values, indices = arg1
        values = jnp.asarray(getattr(values, "data", values),
                             dtype=_dtype_of(dtype))
        indices = jnp.asarray(getattr(indices, "data", indices), jnp.int64)
        return RowSparseNDArray(values, indices, shape, ctx)
    dense = array(arg1, ctx=ctx, dtype=dtype)
    np_d = dense.asnumpy()
    nz_rows = _np.where(_np.any(np_d != 0, axis=tuple(range(1, np_d.ndim))))[0]
    return RowSparseNDArray(jnp.asarray(np_d[nz_rows]),
                            jnp.asarray(nz_rows, jnp.int64),
                            np_d.shape, ctx)


def csr_matrix(arg1, shape=None, ctx=None, dtype=None):
    if isinstance(arg1, tuple) and len(arg1) == 3:
        data_vals, indices, indptr = arg1
        return CSRNDArray(_np.asarray(getattr(data_vals, "data", data_vals)),
                          _np.asarray(getattr(indptr, "data", indptr)),
                          _np.asarray(getattr(indices, "data", indices)),
                          shape, ctx)
    dense = _np.asarray(array(arg1, ctx=ctx, dtype=dtype).asnumpy())
    indptr = [0]
    indices, vals = [], []
    for r in range(dense.shape[0]):
        nz = _np.where(dense[r] != 0)[0]
        indices.extend(nz.tolist())
        vals.extend(dense[r, nz].tolist())
        indptr.append(len(indices))
    return CSRNDArray(_np.asarray(vals, dense.dtype), _np.asarray(indptr),
                      _np.asarray(indices), dense.shape, ctx)


def zeros(stype, shape, ctx=None, dtype=None):
    dt = _dtype_of(dtype)
    if stype == "row_sparse":
        return RowSparseNDArray(jnp.zeros((0,) + tuple(shape[1:]), dt),
                                jnp.zeros((0,), jnp.int64), shape, ctx)
    if stype == "csr":
        return CSRNDArray(_np.zeros((0,), _np.dtype("float32") if dtype is None else dtype),
                          _np.zeros(shape[0] + 1, _np.int64),
                          _np.zeros((0,), _np.int64), shape, ctx)
    from .ndarray import zeros as dzeros
    return dzeros(shape, ctx, dtype)


def retain(data, indices):
    """sparse_retain: keep only the given rows.
    Reference: src/operator/tensor/sparse_retain.cc."""
    if not isinstance(data, RowSparseNDArray):
        raise MXNetError("retain expects a RowSparseNDArray")
    idx = jnp.asarray(getattr(indices, "data", indices), jnp.int64)
    vals = jnp.take(data._data, idx, axis=0)
    return RowSparseNDArray(vals, idx, data._dense_shape, data._ctx)


def dot(lhs, rhs, transpose_a=False, transpose_b=False):
    from . import ops as _ops
    return _ops.dot(lhs, rhs, transpose_a=transpose_a, transpose_b=transpose_b)
