"""``mx.nd`` — the imperative NDArray API.

Reference surface: python/mxnet/ndarray/ (SURVEY.md §2.2). Op wrappers that
the reference autogenerates from the NNVM registry are here plain Python
functions in ``ops.py``.
"""
from .ndarray import (NDArray, array, zeros, ones, full, empty, arange,
                      concatenate, from_jax, waitall, eye, linspace)
from .ops import *  # noqa: F401,F403
from .ops import concat, stack
from .linalg import *  # noqa: F401,F403
from . import random
from .random import shuffle  # reference aliases mx.nd.shuffle -> _shuffle op
from .utils import save, load, load_frombuffer
from . import sparse
from . import contrib


def Custom(*inputs, op_type=None, **kwargs):
    """Invoke a registered custom python op (reference mx.nd.Custom ->
    src/operator/custom/custom.cc; see mxnet_tpu.operator)."""
    from ..operator import Custom as _custom
    return _custom(*inputs, op_type=op_type, **kwargs)

zeros_like_fn = None  # avoid accidental shadowing confusion


def moveaxis(data, source, destination):
    import jax.numpy as jnp
    from .ndarray import _apply1
    return _apply1(data, lambda d: jnp.moveaxis(d, source, destination))
