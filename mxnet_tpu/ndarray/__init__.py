"""``mx.nd`` — the imperative NDArray API.

Reference surface: python/mxnet/ndarray/ (SURVEY.md §2.2). Op wrappers that
the reference autogenerates from the NNVM registry are here plain Python
functions in ``ops.py``.
"""
from .ndarray import (NDArray, array, zeros, ones, full, empty, arange,
                      concatenate, from_jax, waitall, eye, linspace)
from .ops import *  # noqa: F401,F403
from .ops import concat, stack
from .linalg import *  # noqa: F401,F403
from . import random
from .random import shuffle  # reference aliases mx.nd.shuffle -> _shuffle op
from .utils import save, load, load_frombuffer
from . import sparse
from . import contrib


def Custom(*inputs, op_type=None, **kwargs):
    """Invoke a registered custom python op (reference mx.nd.Custom ->
    src/operator/custom/custom.cc; see mxnet_tpu.operator)."""
    from ..operator import Custom as _custom
    return _custom(*inputs, op_type=op_type, **kwargs)

zeros_like_fn = None  # avoid accidental shadowing confusion


def moveaxis(data, source, destination):
    import jax.numpy as jnp
    from .ndarray import _apply1
    return _apply1(data, lambda d: jnp.moveaxis(d, source, destination))


def _dense_tostype(self, stype):
    """Dense -> requested storage (reference NDArray.tostype over
    cast_storage, src/operator/tensor/cast_storage.cc; sparse classes
    override with their own conversions)."""
    if stype == "default":
        # reference cast_storage always returns a NEW array
        return self.copy()
    from .sparse import row_sparse_array, csr_matrix
    if stype == "row_sparse":
        return row_sparse_array(self)
    if stype == "csr":
        return csr_matrix(self)
    from ..base import MXNetError
    raise MXNetError(f"unknown storage type {stype!r}")


from .ndarray import NDArray as _NDArrayCls

if not hasattr(_NDArrayCls, "tostype"):
    _NDArrayCls.tostype = _dense_tostype


# ----------------------------------------------------------------------
# Registry-driven method surface: the reference autogenerates NDArray
# methods from the op registry (python/mxnet/ndarray/ndarray.py autogen
# block); same idea here — every listed op whose first positional arg is
# the array becomes a method, forwarding to the tape-integrated op (NOT
# a raw jnp call, so autograd/vjp semantics are identical either way).
# ----------------------------------------------------------------------

_METHOD_FORWARD_OPS = [
    "flip", "diag", "sort", "argsort", "sign", "round", "rint", "ceil",
    "floor", "trunc", "fix", "square", "rsqrt", "cbrt", "log2", "log10",
    "log1p", "expm1", "sin", "cos", "tan", "arcsin", "arccos", "arctan",
    "degrees", "radians", "sinh", "cosh", "arcsinh", "arccosh", "arctanh",
    "slice", "slice_like", "pad", "batch_dot", "nansum", "nanprod",
    "moments", "shape_array", "size_array", "split", "one_hot", "take",
    "pick", "repeat", "tile", "norm", "erf", "erfinv", "gamma",
    "gammaln", "reciprocal",
]


def _make_op_method(_op, _name):
    def method(self, *args, **kwargs):
        return _op(self, *args, **kwargs)
    method.__name__ = _name
    method.__doc__ = (f"Method form of ``mx.nd.{_name}`` (reference "
                      f"autogen NDArray method surface).")
    return method


import sys as _sys
_this = _sys.modules[__name__]
for _name in _METHOD_FORWARD_OPS:
    if not hasattr(_this, _name):
        # fail CLOSED: a renamed/misspelled op must break the import,
        # not silently drop the method
        raise ImportError(f"_METHOD_FORWARD_OPS lists unknown op {_name!r}")
    if not hasattr(_NDArrayCls, _name):
        setattr(_NDArrayCls, _name, _make_op_method(getattr(_this, _name),
                                                    _name))
del _sys, _this, _name
