"""``mx.nd.linalg_*`` — the linear-algebra op family.

Reference: src/operator/tensor/la_op.cc / la_op-inl.h (LAPACK/cuSolver
wrappers). On TPU these map to jax.numpy.linalg / jax.lax.linalg, which
XLA lowers to MXU-friendly blocked kernels; every op routes through
``apply_nary`` so the imperative tape records it and jax.vjp supplies the
(well-known) matrix-calculus gradients — no hand-written backward kernels.

Batch semantics match the reference: all ops accept (..., m, n) stacks.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..base import MXNetError
from .ndarray import NDArray, apply_nary

__all__ = []


def _register(fn):
    __all__.append(fn.__name__)
    return fn


@_register
def linalg_gemm(A, B, C, transpose_a=False, transpose_b=False, alpha=1.0,
                beta=1.0, axis=-2):
    """alpha * op(A) @ op(B) + beta * C (la_op.cc linalg_gemm). ``axis``
    names the matrix-row axis (reference default -2); other values move
    the batch dims accordingly."""
    def fn(a, b, c):
        if axis != -2:
            a = jnp.moveaxis(a, axis, -2)
            b = jnp.moveaxis(b, axis, -2)
            c = jnp.moveaxis(c, axis, -2)
        if transpose_a:
            a = jnp.swapaxes(a, -1, -2)
        if transpose_b:
            b = jnp.swapaxes(b, -1, -2)
        r = alpha * jnp.matmul(a, b) + beta * c
        if axis != -2:
            r = jnp.moveaxis(r, -2, axis)
        return r
    return apply_nary(fn, [A, B, C], name="linalg_gemm")


@_register
def linalg_potrf(A):
    """Cholesky factor L of a PSD matrix: A = L @ L.T (la_op.cc
    linalg_potrf). Returns the lower triangle like the reference."""
    return apply_nary(jnp.linalg.cholesky, [A], name="linalg_potrf")


@_register
def linalg_potri(A):
    """Inverse of the PSD matrix whose Cholesky factor is ``A``:
    (A @ A.T)^-1 (la_op.cc linalg_potri)."""
    def fn(l):
        eye = jnp.broadcast_to(
            jnp.eye(l.shape[-1], dtype=l.dtype), l.shape)
        linv = lax.linalg.triangular_solve(
            l, eye, left_side=True, lower=True)
        return jnp.swapaxes(linv, -1, -2) @ linv
    return apply_nary(fn, [A], name="linalg_potri")


@_register
def linalg_trsm(A, B, transpose=False, rightside=False, lower=True,
                alpha=1.0):
    """Solve op(A) X = alpha B (or X op(A) = alpha B) with triangular A
    (la_op.cc linalg_trsm)."""
    def fn(a, b):
        return lax.linalg.triangular_solve(
            a, alpha * b, left_side=not rightside, lower=lower,
            transpose_a=transpose)
    return apply_nary(fn, [A, B], name="linalg_trsm")


@_register
def linalg_trmm(A, B, transpose=False, rightside=False, lower=True,
                alpha=1.0):
    """Multiply by a triangular matrix: alpha op(tri(A)) @ B
    (la_op.cc linalg_trmm)."""
    def fn(a, b):
        t = jnp.tril(a) if lower else jnp.triu(a)
        if transpose:
            t = jnp.swapaxes(t, -1, -2)
        return alpha * (jnp.matmul(b, t) if rightside else jnp.matmul(t, b))
    return apply_nary(fn, [A, B], name="linalg_trmm")


@_register
def linalg_syrk(A, transpose=False, alpha=1.0):
    """alpha * A @ A.T (or A.T @ A when transpose) — la_op.cc
    linalg_syrk."""
    def fn(a):
        at = jnp.swapaxes(a, -1, -2)
        return alpha * (jnp.matmul(at, a) if transpose
                        else jnp.matmul(a, at))
    return apply_nary(fn, [A], name="linalg_syrk")


@_register
def linalg_sumlogdiag(A):
    """sum(log(diag(A))) per matrix (la_op.cc linalg_sumlogdiag)."""
    def fn(a):
        d = jnp.diagonal(a, axis1=-2, axis2=-1)
        return jnp.sum(jnp.log(d), axis=-1)
    return apply_nary(fn, [A], name="linalg_sumlogdiag")


@_register
def linalg_extractdiag(A, offset=0):
    """Diagonal of each matrix in the stack (la_op.cc
    linalg_extractdiag)."""
    def fn(a):
        return jnp.diagonal(a, offset=offset, axis1=-2, axis2=-1)
    return apply_nary(fn, [A], name="linalg_extractdiag")


@_register
def linalg_makediag(A, offset=0):
    """Embed vectors as diagonal matrices (la_op.cc linalg_makediag)."""
    def fn(a):
        n = a.shape[-1] + abs(offset)
        base = jnp.zeros(a.shape[:-1] + (n, n), a.dtype)
        idx = jnp.arange(a.shape[-1])
        rows = idx + max(0, -offset)
        cols = idx + max(0, offset)
        return base.at[..., rows, cols].set(a)
    return apply_nary(fn, [A], name="linalg_makediag")


def _trian_indices(n, offset, lower):
    """Index pairs of the offset-SHIFTED triangle (the reference
    semantics, la_op-inl.h CopyTriangle): the lower/upper triangle of the
    (n-|offset|)-dim submatrix shifted by offset, (q)(q+1)/2 entries —
    NOT the half-plane that numpy's tril/triu_indices(k=offset) gives."""
    import numpy as _onp
    q = n - abs(offset)
    ri, ci = (_onp.tril_indices(q) if lower else _onp.triu_indices(q))
    if offset >= 0:
        return ri, ci + offset
    return ri - offset, ci


@_register
def linalg_extracttrian(A, offset=0, lower=True):
    """Flatten the (offset-shifted) triangle of each matrix into a
    vector of (n-|offset|)(n-|offset|+1)/2 entries (la_op.cc
    linalg_extracttrian)."""
    def fn(a):
        rows, cols = _trian_indices(a.shape[-1], offset, lower)
        return a[..., rows, cols]
    return apply_nary(fn, [A], name="linalg_extracttrian")


@_register
def linalg_maketrian(A, offset=0, lower=True):
    """Inverse of extracttrian: vector -> triangular matrix (la_op.cc
    linalg_maketrian)."""
    def fn(a):
        import math as _math
        m = a.shape[-1]
        # vector holds q(q+1)/2 entries of a triangle q = n - |offset|
        q = (_math.isqrt(8 * m + 1) - 1) // 2
        n = q + abs(offset)
        rows, cols = _trian_indices(n, offset, lower)
        base = jnp.zeros(a.shape[:-1] + (n, n), a.dtype)
        return base.at[..., rows, cols].set(a)
    return apply_nary(fn, [A], name="linalg_maketrian")


@_register
def linalg_gelqf(A):
    """LQ factorization A = L @ Q with Q orthonormal rows (la_op.cc
    linalg_gelqf). Returns (L, Q)."""
    def fn(a):
        q, r = jnp.linalg.qr(jnp.swapaxes(a, -1, -2))
        return jnp.swapaxes(r, -1, -2), jnp.swapaxes(q, -1, -2)
    return apply_nary(fn, [A], name="linalg_gelqf", n_out=2)


@_register
def linalg_syevd(A):
    """Symmetric eigendecomposition: A = U.T diag(L) U (la_op.cc
    linalg_syevd). Returns (U, L) with eigenvectors as ROWS of U like the
    reference."""
    def fn(a):
        w, v = jnp.linalg.eigh(a)
        return jnp.swapaxes(v, -1, -2), w
    return apply_nary(fn, [A], name="linalg_syevd", n_out=2)


@_register
def linalg_inverse(A):
    """Matrix inverse (la_op.cc linalg_inverse)."""
    return apply_nary(jnp.linalg.inv, [A], name="linalg_inverse")


@_register
def linalg_det(A):
    """Determinant (la_op.cc linalg_det)."""
    return apply_nary(jnp.linalg.det, [A], name="linalg_det")


@_register
def linalg_slogdet(A):
    """(sign, log|det|) (la_op.cc linalg_slogdet)."""
    def fn(a):
        sign, logdet = jnp.linalg.slogdet(a)
        return sign, logdet
    return apply_nary(fn, [A], name="linalg_slogdet", n_out=2)


# reference exposes the family BOTH as nd.linalg_potrf (flat) and
# nd.linalg.potrf (short name inside the submodule); mirror the aliases.
# linalg_gemm2 lives in ops.py (it predates this module) — pull it in so
# the short-name surface is complete.
from .ops import linalg_gemm2  # noqa: E402

for _n in list(globals()):
    if _n.startswith("linalg_"):
        globals()[_n[len("linalg_"):]] = globals()[_n]
del _n
