"""``mx.monitor.Monitor`` — periodic tensor statistics during training.

Reference: python/mxnet/monitor.py — hooked every executor op output via
the C++ monitor callback and printed ``stat_func`` per tensor every
``interval`` batches (the classic exploding-gradient hunt).

TPU-native scope: XLA fuses op internals away, so the observable surface
is the executor boundary — arguments (weights), gradients, auxiliary
states, and outputs. That covers the reference Monitor's dominant uses
(weight/grad scale tracking); per-internal-op activations need
``MXTPU_EAGER=1`` (every op dispatches eagerly) + ``mx.profiler``
instead, which is the documented NaN/blowup workflow (docs/API.md env
table, MXTPU_DEBUG_NANS).
"""
from __future__ import annotations

import logging
import re

import numpy as _np

from .base import MXNetError

__all__ = ["Monitor"]


def _default_stat(arr):
    return _np.abs(arr).mean()


class Monitor:
    """Collect statistics of params/grads/aux/outputs every N batches.

    Usage (reference pattern)::

        mon = mx.monitor.Monitor(interval=10, pattern=".*weight.*")
        mod.install_monitor(mon)
        ...
        mon.tic()
        mod.forward_backward(batch)
        for name, stat in mon.toc():
            ...
        # or mon.toc_print()
    """

    def __init__(self, interval, stat_func=None, pattern=".*", sort=False):
        self.interval = int(interval)
        self.stat_func = stat_func or _default_stat
        self.re_pattern = re.compile(pattern)
        self.sort = sort
        self.step = 0
        self.activated = False
        self._module = None
        self.queue = []

    def install(self, module):
        """Wired by Module.install_monitor."""
        self._module = module

    def tic(self):
        """Arm collection for this batch if the interval says so."""
        if self.step % self.interval == 0:
            self.activated = True
            self.queue = []
        self.step += 1

    def _collect(self):
        if self._module is None:
            raise MXNetError("Monitor not installed; call "
                             "module.install_monitor(monitor) first")
        mod = self._module
        # BucketingModule: the live executor belongs to the current bucket
        mod = getattr(mod, "_curr_module", None) or mod
        exe = getattr(mod, "_exec", None)
        if exe is None:
            raise MXNetError("Monitor: module is not bound yet")
        sources = [("", exe.arg_dict),
                   ("_grad", getattr(exe, "grad_dict", {}) or {}),
                   ("_aux", getattr(exe, "aux_dict", {}) or {})]
        for suffix, d in sources:
            for name, arr in d.items():
                full = name + suffix
                if arr is not None and self.re_pattern.match(full):
                    self.queue.append(
                        (self.step, full,
                         self.stat_func(_np.asarray(arr.asnumpy()))))
        for i, out in enumerate(mod.get_outputs()):
            full = f"output{i}"
            if self.re_pattern.match(full):
                self.queue.append(
                    (self.step, full,
                     self.stat_func(_np.asarray(out.asnumpy()))))

    def toc(self):
        """Return [(step, name, stat)] for an armed batch, else []."""
        if not self.activated:
            return []
        self._collect()
        self.activated = False
        res = self.queue
        self.queue = []
        if self.sort:
            res = sorted(res, key=lambda x: x[1])
        return res

    def toc_print(self):
        for step, name, stat in self.toc():
            logging.info("Batch: %7d %30s %s", step, name, stat)
