"""Interprocedural concurrency pass — rules HB14/HB15/HB16.

Unlike the per-function taint walk (analyzer.py), this pass builds a
per-class model of the whole module before judging anything:

1. **Lock inventory**: fields assigned from a lock factory
   (``threading.Lock/RLock/Condition``, ``racecheck.make_lock/
   make_rlock/make_condition``) become the class's lock set; module- and
   function-level lock bindings are tracked by name.  A lock is
   identified by a *token* (``ClassName.attr`` / bare name), so two
   methods taking ``self._lock`` share one graph node.
2. **Field-access model**: every ``self.<field>`` read/write in every
   method is recorded together with the stack of locks lexically held
   (``with <lock>:`` nesting) at the access.
3. **Call graph**: ``self.m(...)`` and same-module ``fn(...)`` calls are
   resolved one level, so a lock acquired (or a blocking call made)
   inside a helper is charged to the call site that holds the lock.

Annotations (see docs/LINT.md):

- ``self._table = {}   # guarded-by: _lock`` — the field must ALWAYS be
  accessed with ``self._lock`` held; any bare access is HB14 regardless
  of thread reachability.
- ``def _emit(self, ...):   # guarded-by: _lock`` — the method runs with
  ``self._lock`` already held by its callers (the
  ``Membership._emit`` shape); its body is analyzed under that lock.

Rules:

**HB14 unguarded-shared-state** — in a threading module, a mutable field
(written outside ``__init__``) of a lock-owning class that is accessed
under a lock in one method and with NO guard lock held in another.
Construction-time methods (``__init__``/``__del__``/pickle hooks) are
exempt: they happen-before/after the threads.

**HB15 lock-order-inversion** — a cycle in the statically derived lock
acquisition graph (edge A→B when B is acquired — directly or through a
one-level call — while A is held).  ``api.lint_paths`` merges the edge
lists of every linted file before cycle-checking, so an inversion split
across modules is still caught.

**HB16 blocking-call-under-lock** — a blocking operation lexically
inside a ``with <lock>:`` body: ``time.sleep``, queue ``get/put``
(queue-named receivers), socket sends/recvs (RPC), file I/O
(``open``/``read``/``write``/``flush``/``os.replace``/``os.fsync``/
``print``), device syncs (``block_until_ready``/``asnumpy``/
``wait_to_read``/...), thread joins, and dispatch of a jit-compiled
callable bound in the same scope.  ``cv.wait()`` on the HELD condition
is exempt — releasing while waiting is the point of a condition
variable.
"""
from __future__ import annotations

import ast
import re

from .report import Violation

__all__ = ["run_concurrency_pass", "collect_lock_edges",
           "cross_module_cycles"]

# lock factory call forms
_LOCK_FACTORIES = {"Lock", "RLock", "Condition"}
_RACECHECK_FACTORIES = {"make_lock", "make_rlock", "make_condition"}
_LOCKISH_NAME = re.compile(r"(?:^|_)(?:lock|mutex|rlock|cv|cond)",
                           re.IGNORECASE)

_GUARDED_BY_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_][A-Za-z0-9_.]*)")

_INIT_METHODS = {"__init__", "__new__", "__del__", "__getstate__",
                 "__setstate__", "__repr__", "__reduce__"}

# container-mutator method names: `self.f.append(...)` counts as a WRITE
# to field f's contents
_MUTATORS = {"append", "extend", "insert", "remove", "pop", "popleft",
             "appendleft", "clear", "update", "add", "discard",
             "setdefault", "sort"}

# -- HB16 blocking-call catalogs ----------------------------------------
_SLEEP_CALLS = {"time.sleep"}
_SOCKET_ATTRS = {"sendall", "recv", "recvfrom", "sendto", "accept",
                 "connect", "makefile"}
_SOCKET_CALLS = {"socket.create_connection"}
_FILE_ATTRS = {"flush", "fsync", "readline", "readinto"}
_OS_IO_CALLS = {"os.replace", "os.fsync", "os.rename"}
_DEVICE_SYNC_ATTRS = {"block_until_ready", "wait_to_read", "waitall",
                      "asnumpy", "asscalar", "item", "tolist"}
_DEVICE_SYNC_CALLS = {"jax.block_until_ready"}
_JIT_FACTORY_CALLS = {"jax.jit", "jit", "jax.pmap", "pmap"}


def _dotted(node):
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_lock_factory(node):
    """True for ``threading.Lock()`` / ``Lock()`` /
    ``racecheck.make_lock(...)`` / ``_racecheck.make_condition(...)``."""
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    name = f.attr if isinstance(f, ast.Attribute) else \
        f.id if isinstance(f, ast.Name) else None
    return name in _LOCK_FACTORIES or name in _RACECHECK_FACTORIES


def _queueish(dotted):
    if not dotted:
        return False
    last = dotted.split(".")[-1]
    return "queue" in dotted.lower() or last == "q" or last.endswith("_q")


class _Access:
    __slots__ = ("field", "write", "locks", "node", "method")

    def __init__(self, field, write, locks, node, method):
        self.field = field
        self.write = write
        self.locks = locks           # frozenset of lock tokens held
        self.node = node
        self.method = method


class _MethodInfo:
    def __init__(self, name):
        self.name = name
        self.accesses = []           # [_Access]
        self.acquired = set()        # every lock token this method takes
        self.blocking = []           # [(node, what)] direct blocking ops
        self.calls = []              # [(callee_name, kind, locks, node)]
                                     # kind: "self" | "module"
        self.edges = []              # [(held, taken, node)]


class _ClassModel:
    def __init__(self, name):
        self.name = name
        self.locks = set()           # lock field names (attr, no "self.")
        self.guarded_by = {}         # field -> lock token (annotation)
        self.methods = {}            # name -> _MethodInfo


class _MethodWalker(ast.NodeVisitor):
    """One pass over a function body tracking the lexical lock stack."""

    def __init__(self, model, cls, info, module, initial_locks=()):
        self.model = model           # _ModuleModel
        self.cls = cls               # _ClassModel or None
        self.info = info             # _MethodInfo
        self.module = module
        self.stack = list(initial_locks)
        self.local_locks = set()     # names bound to lock factories here
        self.local_jitted = set()    # names bound to jit factories here

    # -- token resolution ------------------------------------------------
    def _token(self, expr):
        """Lock token for a with-item / receiver, or None."""
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and \
                expr.value.id == "self" and self.cls is not None:
            attr = expr.attr
            if attr in self.cls.locks or _LOCKISH_NAME.search(attr):
                return f"{self.cls.name}.{attr}"
            return None
        if isinstance(expr, ast.Name):
            n = expr.id
            if n in self.local_locks:
                return f"<local>.{n}"
            if n in self.module.module_locks or _LOCKISH_NAME.search(n):
                return n
            return None
        dotted = _dotted(expr)
        if dotted and _LOCKISH_NAME.search(dotted.split(".")[-1]):
            return dotted
        return None

    def _self_token(self, lockname):
        """Normalize an annotation lock name to a token."""
        lockname = lockname.split(".")[-1]
        if self.cls is not None:
            return f"{self.cls.name}.{lockname}"
        return lockname

    # -- statements ------------------------------------------------------
    def visit_With(self, node):
        tokens = []
        for item in node.items:
            self._scan_expr(item.context_expr)
            tok = self._token(item.context_expr)
            if tok is not None:
                for held in self.stack:
                    if held != tok:
                        self.info.edges.append((held, tok,
                                                item.context_expr))
                self.info.acquired.add(tok)
                tokens.append(tok)
                self.stack.append(tok)
        for stmt in node.body:
            self.visit(stmt)
        for _ in tokens:
            self.stack.pop()

    visit_AsyncWith = visit_With

    def visit_Assign(self, node):
        if _is_lock_factory(node.value):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    self.local_locks.add(t.id)
                elif isinstance(t, ast.Attribute) and \
                        isinstance(t.value, ast.Name) and \
                        t.value.id == "self" and self.cls is not None:
                    self.cls.locks.add(t.attr)
            return
        if self._is_jit_factory(node.value):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    self.local_jitted.add(t.id)
        for t in node.targets:
            self._record_target(t)
        self._scan_expr(node.value)

    def visit_AugAssign(self, node):
        self._record_target(node.target)
        self._scan_expr(node.value)

    def visit_AnnAssign(self, node):
        if node.value is not None and _is_lock_factory(node.value):
            if isinstance(node.target, ast.Name):
                self.local_locks.add(node.target.id)
            return
        self._record_target(node.target)
        if node.value is not None:
            self._scan_expr(node.value)

    def visit_Delete(self, node):
        for t in node.targets:
            self._record_target(t)

    def visit_FunctionDef(self, node):
        # nested function (worker closures): analyzed in the same
        # method's model — closures share the enclosing lock discipline
        for stmt in node.body:
            self.visit(stmt)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Expr(self, node):
        self._scan_expr(node.value)

    def generic_visit(self, node):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.stmt):
                self.visit(child)
            elif isinstance(child, ast.expr):
                self._scan_expr(child)

    # -- field access recording ------------------------------------------
    def _field_of(self, expr):
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and expr.value.id == "self":
            return expr.attr
        return None

    def _record_access(self, field, write, node):
        if self.cls is None or field in self.cls.locks:
            return
        self.info.accesses.append(_Access(
            field, write, frozenset(self.stack), node, self.info.name))

    def _record_target(self, target):
        f = self._field_of(target)
        if f is not None:
            self._record_access(f, True, target)
            return
        if isinstance(target, ast.Subscript):
            f = self._field_of(target.value)
            if f is not None:
                self._record_access(f, True, target)
                return
            self._scan_expr(target.value)
            self._scan_expr(target.slice)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._record_target(elt)
        elif isinstance(target, ast.Starred):
            self._record_target(target.value)
        else:
            self._scan_expr(target)

    # -- expressions (calls, reads) --------------------------------------
    def _scan_expr(self, node):
        if node is None or isinstance(node, (ast.Constant, ast.Name)):
            return
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                self._handle_call(sub)
            elif isinstance(sub, ast.Attribute):
                f = self._field_of(sub)
                if f is not None:
                    self._record_access(f, False, sub)

    def _is_jit_factory(self, node):
        if not isinstance(node, ast.Call):
            return False
        if _dotted(node.func) in _JIT_FACTORY_CALLS:
            return True
        return isinstance(node.func, ast.Attribute) and \
            node.func.attr == "compile"

    def _handle_call(self, node):
        f = node.func
        dotted = _dotted(f)
        attr = f.attr if isinstance(f, ast.Attribute) else None
        # mutator method on a self field: a WRITE to that field
        if attr in _MUTATORS and isinstance(f, ast.Attribute):
            fld = self._field_of(f.value)
            if fld is not None:
                self._record_access(fld, True, node)
        # call-graph edges for one-level resolution
        if self.stack:
            if isinstance(f, ast.Attribute) and \
                    isinstance(f.value, ast.Name) and f.value.id == "self":
                self.info.calls.append((attr, "self",
                                        tuple(self.stack), node))
            elif isinstance(f, ast.Name):
                self.info.calls.append((f.id, "module",
                                        tuple(self.stack), node))
            b = self._blocking_kind(node, dotted, attr, f)
            if b is not None:
                self.info.blocking.append((node, b, tuple(self.stack)))
        else:
            b = self._blocking_kind(node, dotted, attr, f,
                                    under_lock=False)
            if b is not None:
                self.info.blocking.append((node, b, ()))

    def _blocking_kind(self, node, dotted, attr, f, under_lock=True):
        """Classify a call as blocking; returns a description or None.
        ``under_lock=False`` records are used only for one-level call
        resolution (a helper that blocks, called under a lock)."""
        if dotted in _SLEEP_CALLS:
            return f"`{dotted}()` (sleep)"
        if dotted in _OS_IO_CALLS:
            return f"`{dotted}()` (file I/O)"
        if dotted in _SOCKET_CALLS:
            return f"`{dotted}()` (RPC/socket)"
        if dotted in _DEVICE_SYNC_CALLS:
            return f"`{dotted}()` (device sync)"
        if isinstance(f, ast.Name):
            if f.id == "open":
                return "`open()` (file I/O)"
            if f.id == "print":
                return "`print()` (console I/O)"
            if f.id in self.local_jitted:
                return f"`{f.id}()` (jit-compiled dispatch)"
            return None
        if attr is None:
            return None
        recv = f.value
        recv_dotted = _dotted(recv)
        if attr in _DEVICE_SYNC_ATTRS:
            return f"`.{attr}()` (device sync)"
        if attr in _SOCKET_ATTRS:
            return f"`.{attr}()` (RPC/socket)"
        if attr in _FILE_ATTRS:
            return f"`.{attr}()` (file I/O)"
        if attr in ("get", "put") and _queueish(recv_dotted):
            return f"`.{attr}()` (queue wait)"
        if attr == "join" and recv_dotted and \
                "thread" in recv_dotted.lower():
            return f"`.{attr}()` (thread join)"
        if attr == "wait":
            tok = self._token(recv) if under_lock else None
            if under_lock and tok is not None and tok in self.stack:
                return None       # cv.wait on the HELD condition: fine
            if recv_dotted and not isinstance(recv, ast.Constant):
                return f"`.{attr}()` (event/thread wait)"
        return None


class _ModuleModel:
    def __init__(self, tree, path, src_lines):
        self.path = path
        self.src_lines = src_lines
        self.classes = {}            # name -> _ClassModel
        self.functions = {}          # name -> _MethodInfo (module funcs)
        self.module_locks = set()
        self.uses_threading = False
        self.spawns_threads = False
        self._scan_module(tree)

    def _line(self, node):
        i = getattr(node, "lineno", 0)
        return self.src_lines[i - 1] if 0 < i <= len(self.src_lines) \
            else ""

    def _guarded_by_on(self, node):
        m = _GUARDED_BY_RE.search(self._line(node))
        return m.group(1) if m else None

    def _scan_module(self, tree):
        src = "\n".join(self.src_lines)
        if re.search(r"\b(?:import\s+threading|from\s+threading\s+import"
                     r"|concurrent\.futures|ThreadPoolExecutor"
                     r"|make_lock|make_rlock|make_condition)", src):
            self.uses_threading = True
        if re.search(r"\bThread\s*\(|ThreadPoolExecutor\s*\(", src):
            self.spawns_threads = True
        for node in tree.body:
            if isinstance(node, ast.Assign) and _is_lock_factory(node.value):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        self.module_locks.add(t.id)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions[node.name] = self._walk_function(
                    node, None)
            elif isinstance(node, ast.ClassDef):
                self._scan_class(node)

    def _scan_class(self, cd):
        cls = _ClassModel(cd.name)
        self.classes[cd.name] = cls
        methods = [i for i in cd.body
                   if isinstance(i, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))]
        # pass 1: lock fields + guarded-by field annotations (any method)
        for m in methods:
            for sub in ast.walk(m):
                if isinstance(sub, ast.Assign):
                    for t in sub.targets:
                        if isinstance(t, ast.Attribute) and \
                                isinstance(t.value, ast.Name) and \
                                t.value.id == "self":
                            if _is_lock_factory(sub.value):
                                cls.locks.add(t.attr)
                            else:
                                g = self._guarded_by_on(sub)
                                if g:
                                    cls.guarded_by[t.attr] = \
                                        f"{cls.name}.{g.split('.')[-1]}"
        # pass 2: per-method access/edge/blocking model
        for m in methods:
            initial = ()
            g = self._guarded_by_on(m)
            if g:
                initial = (f"{cls.name}.{g.split('.')[-1]}",)
            cls.methods[m.name] = self._walk_function(m, cls, initial)

    def _walk_function(self, fn, cls, initial_locks=()):
        info = _MethodInfo(fn.name)
        w = _MethodWalker(self, cls, info, self, initial_locks)
        for stmt in fn.body:
            w.visit(stmt)
        return info


# ----------------------------------------------------------------------
# rule evaluation
# ----------------------------------------------------------------------

def _check_hb14(model, collector):
    if not model.uses_threading:
        return
    for cls in model.classes.values():
        if not cls.locks and not cls.guarded_by:
            continue
        # field -> guard lock set (locks it is EVER accessed under,
        # outside construction)
        guards = {}
        mutable = set(cls.guarded_by)     # annotated fields: always live
        for info in cls.methods.values():
            construction = info.name in _INIT_METHODS
            for a in info.accesses:
                if a.write and not construction:
                    mutable.add(a.field)
                if construction:
                    continue
                if a.locks:
                    guards.setdefault(a.field, set()).update(a.locks)
        for field, tok in cls.guarded_by.items():
            guards.setdefault(field, set()).add(tok)
        for info in cls.methods.values():
            if info.name in _INIT_METHODS:
                continue
            for a in info.accesses:
                g = guards.get(a.field)
                if not g or a.field not in mutable:
                    continue
                if a.locks & g:
                    continue
                annotated = a.field in cls.guarded_by
                lock_desc = " / ".join(sorted(g))
                collector.add(Violation(
                    rule="HB14", path=model.path, line=a.node.lineno,
                    col=a.node.col_offset,
                    message=(
                        f"shared field `self.{a.field}` accessed without "
                        f"{lock_desc} held"
                        + (" (declared `# guarded-by`)" if annotated
                           else f", but other methods access it under "
                                f"{lock_desc}")
                        + ": a concurrent locked writer races this "
                        "access (torn reads, lost updates); take the "
                        "lock here, or document the invariant with "
                        "`# guarded-by:` / a justified "
                        "`# mxlint: disable=HB14`"),
                    block=cls.name, func=info.name))


def _one_level_edges(model, cls, info):
    """Edges through a single call hop: a call made while holding locks
    to a method/function that itself acquires locks."""
    out = []
    for callee, kind, held, node in info.calls:
        target = None
        if kind == "self" and cls is not None:
            target = cls.methods.get(callee)
        elif kind == "module":
            target = model.functions.get(callee)
        if target is None:
            continue
        for tok in target.acquired:
            for h in held:
                if h != tok:
                    out.append((h, tok, node))
    return out


def _all_edges(model):
    """Every lock-order edge in the module, with the site node and
    owning (class, method) for reporting."""
    edges = []
    for cls in model.classes.values():
        for info in cls.methods.values():
            for h, t, node in info.edges:
                edges.append((h, t, node, cls.name, info.name))
            for h, t, node in _one_level_edges(model, cls, info):
                edges.append((h, t, node, cls.name, info.name))
    for info in model.functions.values():
        for h, t, node in info.edges:
            edges.append((h, t, node, "", info.name))
        for h, t, node in _one_level_edges(model, None, info):
            edges.append((h, t, node, "", info.name))
    return edges


def _cycle_violations(edges, path_of=None):
    """Report each edge that participates in a cycle, once per (A, B).
    ``edges``: [(held, taken, node, block, func)] or the cross-module
    form [(held, taken, path, line, col, block, func)]."""
    graph = {}
    for e in edges:
        graph.setdefault(e[0], set()).add(e[1])

    def reachable(src, dst):
        stack, seen = [src], set()
        while stack:
            n = stack.pop()
            if n == dst:
                return True
            if n in seen:
                continue
            seen.add(n)
            stack.extend(graph.get(n, ()))
        return False

    out = []
    reported = set()
    for e in edges:
        h, t = e[0], e[1]
        if (h, t) in reported:
            continue
        if not reachable(t, h):
            continue
        reported.add((h, t))
        if len(e) == 5:
            _h, _t, node, block, func = e
            path, line, col = path_of, node.lineno, node.col_offset
        else:
            _h, _t, path, line, col, block, func = e
        out.append(Violation(
            rule="HB15", path=path, line=line, col=col,
            message=(
                f"lock-order inversion: {t} is acquired here while "
                f"{h} is held, but elsewhere {h} is (transitively) "
                f"acquired while {t} is held — two threads interleaving "
                f"these orders deadlock; pick ONE global order (document "
                f"it) or release {h} first"),
            block=block, func=func))
    return out


def _check_hb16(model, collector):
    for cls in model.classes.values():
        for info in cls.methods.values():
            _hb16_for(model, cls, info, collector)
    for info in model.functions.values():
        _hb16_for(model, None, info, collector)


def _hb16_for(model, cls, info, collector):
    cname = cls.name if cls is not None else ""
    for node, what, held in info.blocking:
        if not held:
            continue
        collector.add(Violation(
            rule="HB16", path=model.path, line=node.lineno,
            col=node.col_offset,
            message=(
                f"blocking call {what} while holding {held[-1]}: every "
                f"other thread needing the lock stalls behind this "
                f"wait — on the step path that is a host-side stall "
                f"that caps throughput (arXiv:2011.03641); move the "
                f"blocking work outside the critical section (snapshot "
                f"under the lock, act after release)"),
            block=cname, func=info.name))
    # one-level: call under lock to a helper that blocks
    for callee, kind, held, node in info.calls:
        if not held:
            continue
        target = None
        if kind == "self" and cls is not None:
            target = cls.methods.get(callee)
        elif kind == "module":
            target = model.functions.get(callee)
        if target is None or target is info:
            continue
        blocked = [b for b in target.blocking]
        if not blocked:
            continue
        _n, what, _h = blocked[0]
        collector.add(Violation(
            rule="HB16", path=model.path, line=node.lineno,
            col=node.col_offset,
            message=(
                f"blocking call reached while holding {held[-1]}: "
                f"`{callee}()` performs {what} — every other thread "
                f"needing the lock stalls behind it; move the call "
                f"outside the critical section or shrink the helper"),
            block=cname, func=info.name))


def run_concurrency_pass(collector, tree, path, src_lines):
    """Run HB14/HB15/HB16 over one module; violations go into the
    shared collector (suppressions applied downstream)."""
    model = _ModuleModel(tree, path, src_lines)
    _check_hb14(model, collector)
    for v in _cycle_violations(_all_edges(model), path_of=path):
        collector.add(v)
    _check_hb16(model, collector)


# ----------------------------------------------------------------------
# cross-module HB15 (api.lint_paths merges every file's edges)
# ----------------------------------------------------------------------

def collect_lock_edges(source, path):
    """The module's lock-order edges as JSON-able tuples
    ``(held, taken, path, line, col, block, func)``, with HB15
    suppressions already applied (a suppressed edge never feeds the
    cross-module cycle check)."""
    from .suppressions import parse_suppressions, is_suppressed
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError:
        return []
    src_lines = source.splitlines()
    model = _ModuleModel(tree, path, src_lines)
    suppressed, _ = parse_suppressions(source)
    out = []
    for h, t, node, block, func in _all_edges(model):
        if is_suppressed(suppressed, node.lineno, "HB15"):
            continue
        out.append((h, t, path, node.lineno, node.col_offset, block,
                    func))
    return out


def cross_module_cycles(edges):
    """Cycle-check a merged multi-file edge list; returns Violations."""
    return _cycle_violations(edges)
