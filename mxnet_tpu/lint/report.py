"""Violation record + text/JSON rendering for ``mx.lint``.

Kept stdlib-only: ``tools/mxlint.py`` loads the lint package standalone
(no jax import) so it can run in CI images without an accelerator.
"""
from __future__ import annotations

import json
from dataclasses import dataclass, field, asdict

from .rules import RULES


@dataclass(frozen=True)
class Violation:
    """One rule hit, anchored to source. ``block``/``func`` locate the
    HybridBlock class and the forward/helper the hit was found in."""
    rule: str
    path: str
    line: int
    col: int
    message: str
    block: str = ""
    func: str = ""
    source_line: str = field(default="", compare=False)

    @property
    def title(self):
        return RULES[self.rule].title if self.rule in RULES else self.rule

    def format_text(self):
        where = self.block and f" [in {self.block}.{self.func}]" or ""
        return (f"{self.path}:{self.line}:{self.col}: {self.rule} "
                f"({self.title}) {self.message}{where}")


def render_text(violations):
    lines = [v.format_text() for v in violations]
    n = len(violations)
    lines.append(f"{n} violation{'s' if n != 1 else ''} found"
                 if n else "clean: no trace-safety violations")
    return "\n".join(lines)


#: SARIF 2.1.0 — the minimal profile GitHub code scanning and most CI
#: viewers accept: tool.driver with a rule index, one result per
#: violation with ruleId/ruleIndex/level/message/locations.
_SARIF_VERSION = "2.1.0"
_SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/"
                 "sarif-spec/master/Schemas/sarif-schema-2.1.0.json")


def render_sarif(violations, files_checked=None):
    """Render violations as a SARIF 2.1.0 log (single run).

    The driver carries the full rule catalog (not just the rules that
    fired) so viewers can resolve ``ruleIndex`` and show the help text;
    ``fullDescription`` is the catalog summary from ``rules.py``.
    """
    rule_ids = sorted(RULES)
    rule_index = {rid: i for i, rid in enumerate(rule_ids)}
    rules = [
        {
            "id": rid,
            "name": RULES[rid].title,
            "shortDescription": {"text": RULES[rid].title},
            "fullDescription": {"text": RULES[rid].summary},
            "defaultConfiguration": {"level": "error"},
        }
        for rid in rule_ids
    ]
    results = []
    for v in violations:
        result = {
            "ruleId": v.rule,
            "level": "error",
            "message": {"text": v.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": v.path},
                    "region": {"startLine": v.line,
                               "startColumn": v.col + 1},
                },
            }],
        }
        if v.rule in rule_index:
            result["ruleIndex"] = rule_index[v.rule]
        if v.block:
            result["locations"][0]["logicalLocations"] = [{
                "fullyQualifiedName": f"{v.block}.{v.func}",
                "kind": "function",
            }]
        results.append(result)
    run = {
        "tool": {"driver": {"name": "mxlint",
                            "informationUri":
                                "https://example.invalid/mxnet_tpu",
                            "rules": rules}},
        "results": results,
    }
    if files_checked is not None:
        run["properties"] = {"filesChecked": files_checked}
    return json.dumps({"$schema": _SARIF_SCHEMA,
                       "version": _SARIF_VERSION,
                       "runs": [run]}, indent=2)


def render_json(violations, files_checked=None):
    by_rule = {}
    for v in violations:
        by_rule[v.rule] = by_rule.get(v.rule, 0) + 1
    payload = {
        "violations": [asdict(v) for v in violations],
        "count": len(violations),
        "by_rule": by_rule,
    }
    if files_checked is not None:
        payload["files_checked"] = files_checked
    return json.dumps(payload, indent=2)
