"""Violation record + text/JSON rendering for ``mx.lint``.

Kept stdlib-only: ``tools/mxlint.py`` loads the lint package standalone
(no jax import) so it can run in CI images without an accelerator.
"""
from __future__ import annotations

import json
from dataclasses import dataclass, field, asdict

from .rules import RULES


@dataclass(frozen=True)
class Violation:
    """One rule hit, anchored to source. ``block``/``func`` locate the
    HybridBlock class and the forward/helper the hit was found in."""
    rule: str
    path: str
    line: int
    col: int
    message: str
    block: str = ""
    func: str = ""
    source_line: str = field(default="", compare=False)

    @property
    def title(self):
        return RULES[self.rule].title if self.rule in RULES else self.rule

    def format_text(self):
        where = self.block and f" [in {self.block}.{self.func}]" or ""
        return (f"{self.path}:{self.line}:{self.col}: {self.rule} "
                f"({self.title}) {self.message}{where}")


def render_text(violations):
    lines = [v.format_text() for v in violations]
    n = len(violations)
    lines.append(f"{n} violation{'s' if n != 1 else ''} found"
                 if n else "clean: no trace-safety violations")
    return "\n".join(lines)


def render_json(violations, files_checked=None):
    by_rule = {}
    for v in violations:
        by_rule[v.rule] = by_rule.get(v.rule, 0) + 1
    payload = {
        "violations": [asdict(v) for v in violations],
        "count": len(violations),
        "by_rule": by_rule,
    }
    if files_checked is not None:
        payload["files_checked"] = files_checked
    return json.dumps(payload, indent=2)
