"""Runtime race / lock-order detector — the dynamic half of HB14-HB16.

The static concurrency pass (``concurrency.py``) reasons about lock
discipline it can SEE in the source; this module watches the locks a
live process actually takes.  With ``MXTPU_RACECHECK=1`` the threaded
subsystems (``io.DevicePrefetcher``, ``AsyncCheckpointer``, the PS
server/heartbeat threads, elastic ``Membership``, the telemetry
registry/event log, ``recordio`` readers) create their locks through
:func:`make_lock` / :func:`make_rlock` / :func:`make_condition`, which
hand back instrumented wrappers that

- record, per thread, the stack of locks currently held plus the
  acquisition call stack;
- maintain the process-wide **lock-order graph** (edge A -> B when a
  thread acquires B while holding A, keyed by lock *name* so two
  instances of the same role share a node — the lockdep "lock class"
  idea) and flag a cycle the moment an edge closes one: the static
  HB15 inversion, caught at runtime even when the two orders live in
  different modules;
- check **registered guarded structures** (:func:`guard`): a dict
  registered against a lock that is mutated (or read) by a thread NOT
  holding that lock is an HB14 race observed live.

Findings are recorded in-process (:func:`findings`), emitted as
``racecheck.*`` telemetry events, and dumped through the PR 9 flight
recorder (``reason="racecheck:<kind>"``) so a chaos run that races
leaves the same post-mortem a kill does.  The chaos suites
(``testing/chaos.py``, ``tools/tpu_queue_runner.py --chaos``) run under
the detector and assert an empty findings list after every scenario.

Zero overhead when off (the default): :func:`make_lock` returns a plain
``threading.Lock`` — no wrapper allocation, no graph, no thread-local —
and :func:`guard` returns the structure unchanged.  Enabling mid-process
(``configure(enabled=True)``) instruments locks created AFTER the call;
locks built while disabled stay plain.

Stdlib-only at import (the ``mx.lint`` contract): telemetry is imported
lazily and only when a finding fires.
"""
from __future__ import annotations

import os
import threading
import traceback

__all__ = ["enabled", "configure", "configure_from_env", "make_lock",
           "make_rlock", "make_condition", "guard", "findings",
           "assert_clean", "reset", "TrackedLock", "GuardedDict",
           "RaceCheckError"]


class RaceCheckError(AssertionError):
    """:func:`assert_clean` failed — the run produced findings."""


def _env_enabled():
    return os.environ.get("MXTPU_RACECHECK", "0") not in ("", "0")


_ENABLED = _env_enabled()

# internal bookkeeping lock: a PLAIN lock, never tracked — the detector
# must not observe (or deadlock on) its own state
_STATE_LOCK = threading.Lock()
_EDGES = {}        # name -> {name}: the live lock-order graph
_EDGE_SITES = {}   # (a, b) -> (thread_name, short_stack)
_CYCLES_SEEN = set()
_FINDINGS = []
_HELD = threading.local()   # per-thread list of lock names (stack order)


def enabled():
    """Whether the detector is live (``MXTPU_RACECHECK=1``)."""
    return _ENABLED


def configure(enabled=None):
    """Flip the detector (tests / chaos harness).  Only locks created
    AFTER enabling are tracked — the zero-overhead contract means
    disabled-mode locks carry no wrapper to retrofit."""
    global _ENABLED
    if enabled is not None:
        _ENABLED = bool(enabled)
    return _ENABLED


def configure_from_env():
    """Re-read ``MXTPU_RACECHECK`` (subprocess harnesses that mutate the
    env after import)."""
    return configure(enabled=_env_enabled())


def reset():
    """Clear the graph, findings, and edge sites, and re-read the env
    (the conftest per-test hook, alongside telemetry/profiler reset)."""
    global _ENABLED
    with _STATE_LOCK:
        _EDGES.clear()
        _EDGE_SITES.clear()
        _CYCLES_SEEN.clear()
        del _FINDINGS[:]
    _ENABLED = _env_enabled()


def findings():
    """All findings so far, oldest first (list of dicts:
    ``{"kind", "detail", "locks", "thread", "stack"}``)."""
    with _STATE_LOCK:
        return [dict(f) for f in _FINDINGS]


def assert_clean(context=""):
    """Raise :class:`RaceCheckError` when any finding was recorded —
    the chaos suites' post-scenario gate."""
    found = findings()
    if found:
        lines = [f"  [{f['kind']}] {f['detail']}" for f in found]
        raise RaceCheckError(
            f"racecheck: {len(found)} finding(s)"
            + (f" after {context}" if context else "") + ":\n"
            + "\n".join(lines))


def _short_stack(skip=3, limit=6):
    """Compact acquisition stack: the frames above the wrapper."""
    frames = traceback.extract_stack()[:-skip]
    return [f"{os.path.basename(f.filename)}:{f.lineno}:{f.name}"
            for f in frames[-limit:]]


def _record(kind, detail, locks=(), stack=None):
    rec = {"kind": kind, "detail": detail, "locks": list(locks),
           "thread": threading.current_thread().name,
           "stack": list(stack or _short_stack())}
    with _STATE_LOCK:
        _FINDINGS.append(rec)
    _dump(kind, rec)
    return rec


def _dump(kind, rec):
    """Emit the finding as a telemetry event and dump the flight
    recorder (the PR 9 post-mortem path).  Lazy absolute import: this
    module must stay stdlib-importable (tools/mxlint.py loads lint/
    standalone), and a finding in a process without mxnet_tpu loaded
    just stays in-process."""
    try:
        import sys
        mx = sys.modules.get("mxnet_tpu")
        if mx is None:
            return
        telemetry = mx.telemetry
    except (ImportError, AttributeError):
        return
    try:
        telemetry.event(f"racecheck.{kind}", detail=rec["detail"],
                        locks=",".join(rec["locks"]),
                        thread=rec["thread"])
        telemetry.inc("racecheck.findings")
        telemetry.dump_flight(f"racecheck:{kind}")
    except Exception:  # noqa: BLE001 — reporting must never take the run down
        pass


# -- lock-order graph ---------------------------------------------------

def _held_list():
    lst = getattr(_HELD, "names", None)
    if lst is None:
        lst = _HELD.names = []
    return lst


def _reachable(graph, src, dst):
    stack, seen = [src], set()
    while stack:
        n = stack.pop()
        if n == dst:
            return True
        if n in seen:
            continue
        seen.add(n)
        stack.extend(graph.get(n, ()))
    return False


def _on_acquire(name):
    held = _held_list()
    new_edges = []
    with _STATE_LOCK:
        for h in held:
            if h == name:
                continue              # re-entrant RLock: no self-edge
            if name not in _EDGES.get(h, ()):
                new_edges.append(h)
    cycle_hits = []
    if new_edges:
        stack = _short_stack(skip=4)
        tname = threading.current_thread().name
        with _STATE_LOCK:
            for h in new_edges:
                # cycle check BEFORE inserting: does name already reach h?
                if _reachable(_EDGES, name, h):
                    key = frozenset((h, name))
                    if key not in _CYCLES_SEEN:
                        _CYCLES_SEEN.add(key)
                        other = _EDGE_SITES.get((name, h))
                        cycle_hits.append((h, name, stack, other))
                _EDGES.setdefault(h, set()).add(name)
                _EDGE_SITES.setdefault((h, name), (tname, stack))
    held.append(name)
    for h, n, stack, other in cycle_hits:
        where = (f"; reverse order taken by thread {other[0]!r} at "
                 f"{' < '.join(other[1])}" if other else "")
        _record(
            "lock-order",
            f"lock-order inversion: acquired {n!r} while holding {h!r}, "
            f"but {n!r} is (transitively) acquired before {h!r} "
            f"elsewhere — two threads interleaving these orders "
            f"deadlock{where}",
            locks=(h, n), stack=stack)


def _on_release(name):
    held = _held_list()
    # remove by identity of name, newest first (cv.wait releases out of
    # strict LIFO order when the waiter holds other locks)
    for i in range(len(held) - 1, -1, -1):
        if held[i] == name:
            del held[i]
            return


class TrackedLock:
    """Instrumented Lock/RLock: same blocking semantics (delegates to a
    real primitive), plus held-stack and lock-order bookkeeping."""

    __slots__ = ("name", "_lock")

    def __init__(self, name, rlock=False):
        self.name = str(name)
        self._lock = threading.RLock() if rlock else threading.Lock()

    def acquire(self, blocking=True, timeout=-1):
        ok = self._lock.acquire(blocking, timeout)
        if ok:
            _on_acquire(self.name)
        return ok

    def release(self):
        _on_release(self.name)
        self._lock.release()

    def locked(self):
        return self._lock.locked()

    def held_by_current_thread(self):
        return self.name in _held_list()

    # threading.Condition uses _is_owned when the wrapped lock offers it
    def _is_owned(self):
        return self.held_by_current_thread()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return f"TrackedLock({self.name!r})"


def make_lock(name):
    """A mutex for ``name`` (a stable role string like
    ``"PSServer._lock"`` — instances of the same role share one graph
    node).  Disabled: a plain ``threading.Lock`` — NO wrapper."""
    if not _ENABLED:
        return threading.Lock()
    return TrackedLock(name)


def make_rlock(name):
    if not _ENABLED:
        return threading.RLock()
    return TrackedLock(name, rlock=True)


def make_condition(name):
    """A condition variable whose underlying mutex is tracked (the
    ``PSServer._barrier_cv`` shape)."""
    if not _ENABLED:
        return threading.Condition()
    return threading.Condition(lock=TrackedLock(name))


# -- guarded structures -------------------------------------------------

def _holds(lock):
    if isinstance(lock, TrackedLock):
        return lock.held_by_current_thread()
    inner = getattr(lock, "_lock", None)       # Condition wrapping one
    if isinstance(inner, TrackedLock):
        return inner.held_by_current_thread()
    # plain lock: best effort — held by SOMEONE counts (cannot attribute
    # to this thread without the wrapper)
    try:
        return lock.locked()
    except AttributeError:
        return False


class GuardedDict(dict):
    """A dict whose every access must happen with the registered lock
    held by the CURRENT thread; violations are recorded, never raised —
    the detector observes, the chaos gate fails the run."""

    def __init__(self, data, lock, name):
        super().__init__(data)
        self._rc_lock = lock
        self._rc_name = str(name)

    def _rc_check(self, op):
        if not _holds(self._rc_lock):
            _record(
                "unguarded-access",
                f"guarded structure {self._rc_name!r} {op} without its "
                f"lock held by thread "
                f"{threading.current_thread().name!r}",
                locks=(getattr(self._rc_lock, "name", "<lock>"),))

    def __getitem__(self, k):
        self._rc_check(f"read [{k!r}]")
        return super().__getitem__(k)

    def __setitem__(self, k, v):
        self._rc_check(f"write [{k!r}]")
        super().__setitem__(k, v)

    def __delitem__(self, k):
        self._rc_check(f"del [{k!r}]")
        super().__delitem__(k)

    def __contains__(self, k):
        self._rc_check(f"contains [{k!r}]")
        return super().__contains__(k)

    def get(self, k, default=None):
        self._rc_check(f"get [{k!r}]")
        return super().get(k, default)

    def pop(self, k, *default):
        self._rc_check(f"pop [{k!r}]")
        return super().pop(k, *default)

    def update(self, *a, **kw):
        self._rc_check("update")
        super().update(*a, **kw)

    def clear(self):
        self._rc_check("clear")
        super().clear()

    def setdefault(self, k, default=None):
        self._rc_check(f"setdefault [{k!r}]")
        return super().setdefault(k, default)


def guard(mapping, lock, name):
    """Register ``mapping`` (a dict) as guarded by ``lock``: every
    access from a thread not holding the lock is a finding.  Disabled:
    returns ``mapping`` unchanged (zero overhead)."""
    if not _ENABLED:
        return mapping
    return GuardedDict(mapping, lock, name)
