"""``mxlint`` CLI entry point (see tools/mxlint.py).

    python tools/mxlint.py <paths...> [--format=text|json] [--rules=HB01,..]

Exit codes: 0 clean, 1 violations found, 2 usage/IO error. The tool is
pure AST analysis — it never imports the linted code (and never imports
jax), so it is safe on any tree and in minimal CI images.
"""
from __future__ import annotations

import argparse
import sys

from .api import lint_paths
from .report import render_json, render_text
from .rules import ALL_RULE_IDS, RULES
from .suppressions import parse_suppressions


def _parse_rules(spec):
    if not spec:
        return None
    rules = set()
    for raw in spec.split(","):
        rid = raw.strip().upper()
        if rid not in RULES:
            raise SystemExit(
                f"mxlint: unknown rule {raw!r} (known: "
                f"{', '.join(ALL_RULE_IDS)})")
        rules.add(rid)
    return rules


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="mxlint",
        description="Trace-safety static analyzer for HybridBlocks "
                    "(rules HB01-HB06; see docs/LINT.md)")
    ap.add_argument("paths", nargs="+",
                    help="python files or directories to lint")
    ap.add_argument("--format", choices=("text", "json"), default="text",
                    help="diagnostic output format (default: text)")
    ap.add_argument("--rules", default=None, metavar="HB0x,HB0y",
                    help="only check these rule IDs")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rid in ALL_RULE_IDS:
            r = RULES[rid]
            print(f"{rid} ({r.title}): {r.summary}\n")
        return 0

    rules = _parse_rules(args.rules)
    try:
        violations, n_files = lint_paths(args.paths, rules=rules)
    except OSError as e:
        print(f"mxlint: {e}", file=sys.stderr)
        return 2
    except SyntaxError as e:
        print(f"mxlint: syntax error: {e}", file=sys.stderr)
        return 2

    # surface suppression typos (a misspelled ID must not hide a rule)
    for p in _iter_files(args.paths):
        try:
            with open(p, encoding="utf-8") as f:
                _, unknown = parse_suppressions(f.read())
        except OSError:
            continue
        for line, bad in unknown:
            print(f"mxlint: warning: {p}:{line}: unknown rule {bad!r} in "
                  f"suppression comment", file=sys.stderr)

    if args.format == "json":
        print(render_json(violations, files_checked=n_files))
    else:
        print(render_text(violations))
    return 1 if violations else 0


def _iter_files(paths):
    import os
    for p in paths:
        if os.path.isdir(p):
            for root, _dirs, names in os.walk(p):
                for n in sorted(names):
                    if n.endswith(".py"):
                        yield os.path.join(root, n)
        else:
            yield p


if __name__ == "__main__":
    sys.exit(main())
