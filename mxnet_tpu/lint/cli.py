"""``mxlint`` CLI entry point (see tools/mxlint.py).

    python tools/mxlint.py <paths...> [--format=text|json|sarif] [--rules=..]
    python tools/mxlint.py <paths...> --write-baseline base.json
    python tools/mxlint.py <paths...> --baseline base.json --fail-on-new

Exit codes: 0 clean, 1 violations found, 2 usage/IO error. The tool is
pure AST analysis — it never imports the linted code (and never imports
jax), so it is safe on any tree and in minimal CI images.  Baselines
grandfather a tree's existing debt by (rule, file) violation COUNTS so
new strict rules can land on ``mxnet_tpu/`` without blocking
``examples/`` — only regressions beyond the snapshot gate CI.
``--baseline`` accepts either the native counts snapshot or a SARIF
log (``--format=sarif`` output, or one produced by another tool): a
SARIF baseline is folded down to the same (rule, file) counts.
"""
from __future__ import annotations

import argparse
import json
import sys

from .api import lint_paths
from .report import render_json, render_sarif, render_text
from .rules import ALL_RULE_IDS, RULES
from .suppressions import parse_suppressions

_BASELINE_VERSION = 1


def _group_key(v):
    """Baseline grouping key: (rule, path).  Line numbers drift with
    every edit, so the baseline stores violation COUNTS per group — a
    group is \"new\" only when its count grows."""
    return f"{v.rule}|{v.path}"


def write_baseline(violations, path):
    counts = {}
    for v in violations:
        k = _group_key(v)
        counts[k] = counts.get(k, 0) + 1
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"version": _BASELINE_VERSION, "counts": counts}, f,
                  indent=1, sort_keys=True)
    return counts


def _load_baseline_counts(baseline_path):
    """Read a baseline into (rule, path) counts.  Two formats:

    - native ``--write-baseline`` snapshot: ``{"version", "counts"}``
    - a SARIF 2.1.0 log (``--format=sarif`` output): each result's
      ``ruleId`` + first physical location URI is folded into the same
      count keys, so a stored CI scan doubles as the grandfather list
    """
    with open(baseline_path, encoding="utf-8") as f:
        base = json.load(f)
    if not isinstance(base, dict):
        raise ValueError("baseline is not a JSON object")
    if "runs" in base:  # SARIF log
        counts = {}
        for run in base.get("runs") or []:
            for result in run.get("results") or []:
                rule = result.get("ruleId", "")
                uri = ""
                locs = result.get("locations") or []
                if locs:
                    uri = (locs[0].get("physicalLocation", {})
                           .get("artifactLocation", {}).get("uri", ""))
                if rule and uri:
                    k = f"{rule}|{uri}"
                    counts[k] = counts.get(k, 0) + 1
        return counts
    return dict(base.get("counts", {}))


def filter_new(violations, baseline_path):
    """Keep only violations beyond the baseline: within each
    (rule, path) group, the first ``baseline_count`` hits (in line
    order) are grandfathered; anything past that is a regression."""
    counts = _load_baseline_counts(baseline_path)
    grandfathered = 0
    out = []
    for v in sorted(violations, key=lambda v: (v.path, v.line, v.col,
                                               v.rule)):
        k = _group_key(v)
        if counts.get(k, 0) > 0:
            counts[k] -= 1
            grandfathered += 1
        else:
            out.append(v)
    return out, grandfathered


def _parse_rules(spec):
    if not spec:
        return None
    rules = set()
    for raw in spec.split(","):
        rid = raw.strip().upper()
        if rid not in RULES:
            raise SystemExit(
                f"mxlint: unknown rule {raw!r} (known: "
                f"{', '.join(ALL_RULE_IDS)})")
        rules.add(rid)
    return rules


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="mxlint",
        description="Trace-safety + concurrency + donation-dataflow "
                    "static analyzer (rules HB01-HB20; see "
                    "docs/LINT.md)")
    ap.add_argument("paths", nargs="+",
                    help="python files or directories to lint")
    ap.add_argument("--format", choices=("text", "json", "sarif"),
                    default="text",
                    help="diagnostic output format (default: text)")
    ap.add_argument("--rules", default=None, metavar="HB0x,HB0y",
                    help="only check these rule IDs")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    ap.add_argument("--write-baseline", default=None, metavar="FILE",
                    help="snapshot the current violations (counts per "
                         "rule+file) to FILE and exit 0 — the CI "
                         "grandfather list new strict rules land "
                         "against")
    ap.add_argument("--baseline", default=None, metavar="FILE",
                    help="compare against a --write-baseline snapshot: "
                         "only violations BEYOND the baselined counts "
                         "are reported and gate the exit code")
    ap.add_argument("--fail-on-new", action="store_true",
                    help="with --baseline: exit 1 only on regressions "
                         "(implied by --baseline; kept for explicit CI "
                         "invocations)")
    args = ap.parse_args(argv)
    if args.fail_on_new and not args.baseline:
        print("mxlint: --fail-on-new requires --baseline",
              file=sys.stderr)
        return 2

    if args.list_rules:
        for rid in ALL_RULE_IDS:
            r = RULES[rid]
            print(f"{rid} ({r.title}): {r.summary}\n")
        return 0

    rules = _parse_rules(args.rules)
    try:
        violations, n_files = lint_paths(args.paths, rules=rules)
    except OSError as e:
        print(f"mxlint: {e}", file=sys.stderr)
        return 2
    except SyntaxError as e:
        print(f"mxlint: syntax error: {e}", file=sys.stderr)
        return 2

    # surface suppression typos (a misspelled ID must not hide a rule)
    for p in _iter_files(args.paths):
        try:
            with open(p, encoding="utf-8") as f:
                _, unknown = parse_suppressions(f.read())
        except OSError:
            continue
        for line, bad in unknown:
            print(f"mxlint: warning: {p}:{line}: unknown rule {bad!r} in "
                  f"suppression comment", file=sys.stderr)

    if args.write_baseline:
        counts = write_baseline(violations, args.write_baseline)
        print(f"mxlint: baseline written to {args.write_baseline}: "
              f"{len(violations)} violation(s) across {len(counts)} "
              f"group(s)")
        return 0

    grandfathered = 0
    if args.baseline:
        try:
            violations, grandfathered = filter_new(violations,
                                                   args.baseline)
        except (OSError, ValueError) as e:
            print(f"mxlint: cannot read baseline {args.baseline!r}: {e}",
                  file=sys.stderr)
            return 2

    if args.format == "json":
        print(render_json(violations, files_checked=n_files))
    elif args.format == "sarif":
        print(render_sarif(violations, files_checked=n_files))
    else:
        print(render_text(violations))
        if grandfathered:
            print(f"({grandfathered} pre-existing violation(s) "
                  f"grandfathered by {args.baseline})")
    return 1 if violations else 0


def _iter_files(paths):
    import os
    for p in paths:
        if os.path.isdir(p):
            for root, _dirs, names in os.walk(p):
                for n in sorted(names):
                    if n.endswith(".py"):
                        yield os.path.join(root, n)
        else:
            yield p


if __name__ == "__main__":
    sys.exit(main())
