"""Runtime use-after-donate sentinel — the dynamic half of HB18/HB20.

The static dataflow pass (``dataflow.py``) reasons about donation it
can SEE in one function; this module watches the buffers a live process
actually donates.  CPU XLA silently ignores ``donate_argnums``, so a
use-after-donate is invisible to tier-1 — the read returns perfectly
good data on CPU and crashes (or silently corrupts, if the buffer was
reused) on the first real TPU round.  With ``MXTPU_DONATION_CHECK=1``
the dispatch seams that donate — ``DataParallelTrainer._dispatch``
(params + optimizer state), the serving engine's pool swap
(``KVCache.update_pools`` after every prefill/decode/chunk/cow
executable) — call :func:`poison` on the donor buffers AFTER dispatch,
and the NDArray host-access points (``.asnumpy()``, ``__getitem__``,
``.shape``) call :func:`touch`: any touch of a poisoned buffer raises
a typed :class:`UseAfterDonateError` naming the dispatch site — the
TPU crash, reproduced on CPU, with a source-level culprit.

Findings are recorded in-process (:func:`findings`), emitted as
``donation.*`` telemetry events, and dumped through the flight recorder
(``reason="donation:<site>"``) so a chaos run that trips leaves the
same post-mortem a kill does.  The chaos suites arm the sentinel and
assert an empty findings list after every scenario
(:func:`assert_clean`).

Zero overhead when off (the default): the instrumented seams gate on
the module-level ``_ENABLED`` bool — one attribute read, no wrapper, no
registry — so ``MXTPU_DONATION_CHECK=0`` is bitwise-inert.  Poisoned
entries hold a STRONG reference to the donor buffer: on CPU the buffer
outlives donation anyway, and pinning it prevents ``id()`` reuse from
mis-attributing a fresh allocation to an old dispatch.  The registry is
FIFO-capped so a long run cannot grow it unboundedly.

Stdlib-only at import (the ``mx.lint`` contract): telemetry is imported
lazily and only when a finding fires.
"""
from __future__ import annotations

import os
import threading
import traceback

__all__ = ["enabled", "configure", "configure_from_env", "reset",
           "poison", "touch", "findings", "assert_clean",
           "UseAfterDonateError", "DonationCheckError"]


class UseAfterDonateError(RuntimeError):
    """A host access touched a buffer that was donated to a compiled
    call — ``site`` names the dispatch that consumed it."""

    def __init__(self, message, site=""):
        super().__init__(message)
        self.site = site


class DonationCheckError(AssertionError):
    """:func:`assert_clean` failed — the run produced findings."""


def _env_enabled():
    return os.environ.get("MXTPU_DONATION_CHECK", "0") not in ("", "0")


_ENABLED = _env_enabled()

# internal bookkeeping lock — the sentinel must not race itself when
# trainer threads and serving pools poison concurrently
_STATE_LOCK = threading.Lock()
_MAX_POISONED = 512
_POISONED = {}     # id(buffer) -> {"site", "obj", "line"}
_ORDER = []        # FIFO of ids for the cap
_FINDINGS = []


def enabled():
    """Whether the sentinel is live (``MXTPU_DONATION_CHECK=1``)."""
    return _ENABLED


def configure(enabled=None):
    """Flip the sentinel (tests / chaos harness)."""
    global _ENABLED
    if enabled is not None:
        _ENABLED = bool(enabled)
    return _ENABLED


def configure_from_env():
    """Re-read ``MXTPU_DONATION_CHECK`` (subprocess harnesses that
    mutate the env after import)."""
    return configure(enabled=_env_enabled())


def reset():
    """Drop the poison registry and findings, and re-read the env (the
    conftest per-test hook, alongside telemetry/racecheck reset)."""
    global _ENABLED
    with _STATE_LOCK:
        _POISONED.clear()
        del _ORDER[:]
        del _FINDINGS[:]
    _ENABLED = _env_enabled()


def findings():
    """All findings so far, oldest first (list of dicts:
    ``{"kind", "site", "op", "detail", "thread", "stack"}``)."""
    with _STATE_LOCK:
        return [dict(f) for f in _FINDINGS]


def assert_clean(context=""):
    """Raise :class:`DonationCheckError` when any finding was recorded
    — the chaos suites' post-scenario gate."""
    found = findings()
    if found:
        lines = [f"  [{f['kind']}] {f['detail']}" for f in found]
        raise DonationCheckError(
            f"donation: {len(found)} finding(s)"
            + (f" after {context}" if context else "") + ":\n"
            + "\n".join(lines))


def _short_stack(skip=3, limit=6):
    frames = traceback.extract_stack()[:-skip]
    return [f"{os.path.basename(f.filename)}:{f.lineno}:{f.name}"
            for f in frames[-limit:]]


def _leaves(value):
    """Flatten one poison argument into buffer leaves: lists/tuples/
    dicts one level at a time, NDArray-likes unwrapped to their backing
    array (``._data``).  ``None`` and python scalars are skipped."""
    stack = [value]
    while stack:
        v = stack.pop()
        if v is None or isinstance(v, (bool, int, float, complex, str)):
            continue
        if isinstance(v, (list, tuple)):
            stack.extend(v)
            continue
        if isinstance(v, dict):
            stack.extend(v.values())
            continue
        inner = getattr(v, "_data", None)
        if inner is not None and not isinstance(
                inner, (list, tuple, dict)):
            yield inner
        yield v


def poison(values, site):
    """Mark every buffer leaf in ``values`` as donated by ``site``.
    Called by the dispatch seams AFTER a donating call returns — from
    that point the donor buffers are dead on TPU, so any later host
    touch is a latent crash.  No-op when the sentinel is off."""
    if not _ENABLED:
        return
    with _STATE_LOCK:
        for leaf in _leaves(values):
            key = id(leaf)
            if key in _POISONED:
                continue
            # strong ref on purpose: prevents id() reuse (see module
            # docstring); FIFO cap bounds the pin
            _POISONED[key] = {"site": str(site), "obj": leaf}
            _ORDER.append(key)
        while len(_ORDER) > _MAX_POISONED:
            _POISONED.pop(_ORDER.pop(0), None)


def touch(buffer, op):
    """Check a host access (``op`` names it: "asnumpy", "getitem",
    "shape") against the poison registry; a hit records a finding,
    emits telemetry + a flight dump, and raises
    :class:`UseAfterDonateError` naming the dispatch site.  The
    instrumented access points gate on ``_ENABLED`` before calling, so
    this body only ever runs with the sentinel armed."""
    if not _ENABLED:
        return
    with _STATE_LOCK:
        rec = _POISONED.get(id(buffer))
    if rec is None:
        return
    site = rec["site"]
    detail = (f"use-after-donate: .{op} touched a buffer donated to "
              f"{site} — on TPU this buffer no longer exists (CPU XLA "
              f"ignores donation); rebind from the dispatch result "
              f"instead of holding the donor")
    finding = {"kind": "use-after-donate", "site": site, "op": op,
               "detail": detail,
               "thread": threading.current_thread().name,
               "stack": _short_stack()}
    with _STATE_LOCK:
        _FINDINGS.append(finding)
    _dump(site, finding)
    raise UseAfterDonateError(detail, site=site)


def _dump(site, rec):
    """Emit the finding as a telemetry event and dump the flight
    recorder.  Lazy lookup through ``sys.modules`` — this module must
    stay stdlib-importable (tools/mxlint.py loads lint/ standalone),
    and a finding in a process without mxnet_tpu just stays
    in-process."""
    try:
        import sys
        mx = sys.modules.get("mxnet_tpu")
        if mx is None:
            return
        telemetry = mx.telemetry
    except (ImportError, AttributeError):
        return
    try:
        telemetry.event("donation.use_after_donate", site=site,
                        op=rec["op"], thread=rec["thread"])
        telemetry.inc("donation.findings")
        telemetry.dump_flight(f"donation:{site}")
    except Exception:  # noqa: BLE001 — reporting must never take the run down
        pass
