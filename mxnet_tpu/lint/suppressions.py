"""Per-line ``# mxlint: disable=HB0x`` suppression comments.

Syntax (on the offending line, after the code):

    y = x.asnumpy()          # mxlint: disable=HB02
    k = int(F.sum(m))        # mxlint: disable=HB02,HB03  -- justification
    if x > 0: ...            # mxlint: disable            (all rules)

A bare ``disable`` (or ``disable=all``) suppresses every rule on that
line. Unknown rule IDs in a suppression are reported as a warning by the
CLI rather than silently ignored, so typos don't hide real violations.
"""
from __future__ import annotations

import re

from .rules import is_valid_rule

_SUPPRESS_RE = re.compile(
    r"#\s*mxlint:\s*disable(?:\s*=\s*(?P<ids>[A-Za-z0-9_,\s]+?))?\s*(?:--|#|$)")


def parse_suppressions(source):
    """Map line number (1-based) -> set of suppressed rule IDs, where
    ``{"all"}`` means every rule. Also returns a list of
    (line, bad_id) for unknown rule IDs."""
    suppressed = {}
    unknown = []
    for lineno, text in enumerate(source.splitlines(), start=1):
        m = _SUPPRESS_RE.search(text)
        if not m:
            continue
        ids = m.group("ids")
        if not ids or ids.strip().lower() == "all":
            suppressed[lineno] = {"all"}
            continue
        rules = set()
        for raw in ids.split(","):
            rid = raw.strip().upper()
            if not rid:
                continue
            if is_valid_rule(rid):
                rules.add(rid)
            else:
                unknown.append((lineno, raw.strip()))
        if rules:
            suppressed[lineno] = rules
    return suppressed, unknown


def is_suppressed(suppressed, line, rule):
    rules = suppressed.get(line)
    return bool(rules) and ("all" in rules or rule in rules)
