"""Intraprocedural dataflow pass — HB18/HB19/HB20 (ISSUE 16).

Grows the linter from per-statement pattern matching into per-function
**def-use chains** over local names and ``self.*`` attribute paths, then
ships three rule families on top:

HB18  use-after-donate: a name passed in a donated position of a
      jitted/AOT call (``donate_argnums``, including executables built
      in another method of the same class and dispatch-through helpers
      like the trainer's ``self._dispatch(jitted, *args)``) and then
      read / returned / stored afterwards without rebinding.  Rebinding
      the name from the call's result — ``p, s = f(p, s)`` — is the
      clean pattern: the RHS is evaluated before the targets are
      stored, so same-statement rebinds never poison.
HB19  mesh-axis consistency: axis names reaching ``P(...)``,
      ``shard_map(..., in_specs/out_specs)`` or a collective
      (``psum``/``all_gather``/... ``axis_name=``) must be drawn from
      the ``parallel/mesh.py`` AXIS_* constants AND be constructible on
      the declared ``MeshConfig`` of the enclosing scope — catching an
      ``"sp"``/``"ep"`` axis before it exists on any mesh, and an
      ``AXIS_TP`` collective inside a function whose only declared mesh
      is dp-only.
HB20  donation-aliasing: the same array object passed twice into one
      donated call, or a donated buffer that was first stored into a
      ``self.*`` field / captured by a closure — an alias that outlives
      the call and dangles the moment the donor buffer is reused.

Why a dedicated pass: CPU XLA silently ignores ``donate_argnums``, so
tier-1 (CPU parity) structurally cannot catch a use-after-donate — it
is a latent crash that fires only on the first real TPU round
(arXiv:1909.09756's device-resident-step discipline makes donation the
default on every hot path here).  The dataflow pass makes the bug class
visible at lint time; ``lint/donation.py`` is the runtime half.

Analysis model (deliberately simple, documented so the limits are
contractual):

- **Linear walk with branch forking.**  Statements are processed in
  order; ``if``/``try`` branches are analyzed on forked copies of the
  poison state and merged as a UNION (poisoned on any path counts —
  a "may" analysis).  Loop bodies are processed twice so a donation at
  the bottom of iteration N is seen by a read at the top of iteration
  N+1 (the wraparound case); the collector dedups repeat reports.
- **Donating callables** are names or ``self.X`` attributes bound from
  ``jax.jit(..., donate_argnums=...)`` (``.lower(...).compile()`` AOT
  chains included), resolved across the methods of the enclosing class.
  A call whose FIRST argument is itself a known donating callable is a
  dispatch-through (the trainer's ``self._dispatch(jitted, p, s, ...)``
  seam): donated positions shift right by one.
- **Kill set.**  Poison dies on rebind (assign / for-target / with-as),
  and on a method call THROUGH the owner prefix of a poisoned dotted
  path (``self.cache.update_pools(...)`` may rebind
  ``self.cache.k_pool`` — the engine's clean pattern), because an
  intraprocedural pass cannot see the callee's stores.

Stdlib-only (the ``mx.lint`` contract): pure ``ast``, no jax import.
"""
from __future__ import annotations

import ast

from .report import Violation

__all__ = ["run_dataflow_pass"]

# The canonical mesh axes — parallel/mesh.py's MeshConfig contract.
# Deliberately duplicated here as data (the linter never imports the
# framework): adding an axis (the ROADMAP's "sp"/"ep" items) means
# touching mesh.py AND this contract in the same PR, which is exactly
# the single-source ceremony HB19 exists to enforce.
_CANONICAL_AXES = ("dp", "tp", "pp")
_CANONICAL_AXIS_CONSTS = ("AXIS_DP", "AXIS_TP", "AXIS_PP")
_CONST_TO_AXIS = dict(zip(_CANONICAL_AXIS_CONSTS, _CANONICAL_AXES))

_SPEC_CALLEES = {"P", "PartitionSpec"}
_COLLECTIVE_CALLEES = {
    "psum", "pmean", "pmax", "pmin", "all_gather", "psum_scatter",
    "all_to_all", "ppermute", "pshuffle", "pcast",
    "reduce_scatter_bucket"}
_SHARD_MAP_CALLEES = {"shard_map"}


def _path_of(node):
    """A hashable dotted path for a Name/Attribute chain:
    ``("self", "cache", "k_pool")`` — or None for anything else."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return tuple(reversed(parts))
    return None


def _fmt_path(path):
    return ".".join(path)


def _positions_value(node, env=None):
    """Resolve a ``donate_argnums`` expression to a position tuple:
    constant ints/tuples, a local name bound to one (``env``), or an
    ``(0, 1) if self._donate else ()`` conditional — conditionals
    resolve to the UNION of their branches, because a position donated
    on any configuration is a "may" bug on that configuration."""
    if isinstance(node, ast.IfExp):
        merged = set()
        for branch in (node.body, node.orelse):
            merged |= set(_positions_value(branch, env) or ())
        return tuple(sorted(merged)) or None
    if isinstance(node, ast.Name) and env:
        return env.get(node.id)
    return _const_positions(node)


def _donate_positions(call, env=None):
    """The statically-known donated positions of a ``jax.jit`` call, or
    None when the call does not donate / cannot be resolved."""
    for kw in call.keywords:
        if kw.arg not in ("donate_argnums", "donate_argnames"):
            continue
        return _positions_value(kw.value, env)
    return None


def _const_positions(node):
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, int):
                out.append(e.value)
            else:
                return None
        return tuple(out) or None
    return None


def _unwrap_aot(node):
    """Peel ``.lower(...).compile()`` / ``.compile()`` AOT chains off a
    call expression, returning the innermost Call."""
    while isinstance(node, ast.Call) and \
            isinstance(node.func, ast.Attribute) and \
            node.func.attr in ("lower", "compile"):
        node = node.func.value
    return node if isinstance(node, ast.Call) else None


def _donating_expr(node, env=None):
    """Donated positions when ``node`` is a donating ``jax.jit(...)``
    expression (AOT chains included), else None."""
    call = _unwrap_aot(node) if isinstance(node, ast.Call) else None
    if call is None:
        return None
    f = call.func
    name = f.attr if isinstance(f, ast.Attribute) else \
        getattr(f, "id", None)
    if name != "jit":
        return None
    return _donate_positions(call, env)


def _local_pos_env(fn):
    """Local names bound to constant position tuples within ``fn`` —
    the ``donate = (0, 1) if self._donate else ()`` idiom that then
    feeds ``donate_argnums=donate``."""
    env = {}
    for node in ast.walk(fn):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node is not fn:
            continue
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            pos = _positions_value(node.value)
            if pos:
                env[node.targets[0].id] = pos
    return env


class _ClassDonations:
    """Pre-pass over a ClassDef: resolve every donating executable the
    class builds, across methods —

    - ``self.X = jax.jit(..., donate_argnums=...)`` (AOT chains and the
      ``donate = (0, 1) if ... else ()`` local-name idiom included), so
      a step executable built in ``_build`` is recognized when
      dispatched from ``step``;
    - methods that RETURN a donating executable (the engine's
      ``_get``-style factory), recorded in ``method_returns`` so
      ``fn = self._get(...)`` call sites inherit the positions;
    - ``self.X = self._build_accum(...)`` resolved through
      ``method_returns``."""

    def __init__(self, classdef):
        self.attrs = {}            # attr name -> donated positions
        self.method_returns = {}   # method name -> donated positions
        methods = [n for n in classdef.body
                   if isinstance(n, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))]
        pending = []               # (attr, factory method name)
        for m in methods:
            env = _local_pos_env(m)
            local_don = {}         # local name -> positions (this method)
            # assigns first, returns second: ast.walk is breadth-first,
            # so `return fn` sits shallower than the `fn = jax.jit(...)`
            # it refers to (the compile-cache-miss nesting)
            for node in ast.walk(m):
                if not isinstance(node, ast.Assign):
                    continue
                pos = _donating_expr(node.value, env)
                for t in node.targets:
                    if pos and isinstance(t, ast.Name):
                        local_don[t.id] = pos
                    if not isinstance(t, ast.Attribute) or \
                            not isinstance(t.value, ast.Name) or \
                            t.value.id != "self":
                        continue
                    if pos:
                        self.attrs[t.attr] = pos
                    elif isinstance(node.value, ast.Call):
                        vf = _path_of(node.value.func)
                        if vf and len(vf) == 2 and vf[0] == "self":
                            pending.append((t.attr, vf[1]))
            for node in ast.walk(m):
                if isinstance(node, ast.Return) and \
                        node.value is not None:
                    pos = _donating_expr(node.value, env)
                    if pos is None and isinstance(node.value, ast.Name):
                        pos = local_don.get(node.value.id)
                    if pos:
                        self.method_returns[m.name] = pos
        for attr, meth in pending:
            if meth in self.method_returns:
                self.attrs[attr] = self.method_returns[meth]


class _FunctionDataflow:
    """One function's linear def-use walk (HB18 + HB20)."""

    def __init__(self, pass_, fn, class_name, class_don, method_returns):
        self.p = pass_
        self.fn = fn
        self.cls = class_name or ""
        self.cls_don = class_don             # self attr -> positions
        self.cls_returns = method_returns    # factory method -> positions
        self.env = _local_pos_env(fn)        # donate-tuple local names
        self.donating = {}     # local path -> positions
        self.poisoned = {}     # path -> site string
        self.aliases = {}      # name -> list of alias descriptions

    # -- driving ---------------------------------------------------------

    def run(self):
        for stmt in self.fn.body:
            self._stmt(stmt)

    def _stmt(self, stmt):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            self._note_closure(stmt)
            return
        if isinstance(stmt, ast.ClassDef):
            return
        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            self._assign(stmt)
            return
        if isinstance(stmt, ast.If):
            self._check_expr(stmt.test)
            self._fork_branches([stmt.body, stmt.orelse])
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._check_expr(stmt.iter)
            self._kill_target(stmt.target)
            # two passes: catch donation-at-bottom / read-at-top
            for _ in range(2):
                for s in stmt.body:
                    self._stmt(s)
                self._kill_target(stmt.target)
            for s in stmt.orelse:
                self._stmt(s)
            return
        if isinstance(stmt, ast.While):
            self._check_expr(stmt.test)
            for _ in range(2):
                for s in stmt.body:
                    self._stmt(s)
                self._check_expr(stmt.test)
            for s in stmt.orelse:
                self._stmt(s)
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._check_expr(item.context_expr)
                if item.optional_vars is not None:
                    self._kill_target(item.optional_vars)
            for s in stmt.body:
                self._stmt(s)
            return
        if isinstance(stmt, ast.Try):
            self._fork_branches(
                [stmt.body] + [h.body for h in stmt.handlers]
                + ([stmt.orelse] if stmt.orelse else []))
            for s in stmt.finalbody:
                self._stmt(s)
            return
        if isinstance(stmt, ast.Return):
            if stmt.value is not None:
                for call in self._calls_in(stmt.value):
                    self._handle_call(call)   # HB20 still applies; the
                    # pending poison is moot — nothing runs after return
                self._check_expr(stmt.value, reading="returned")
            return
        if isinstance(stmt, ast.Expr):
            self._expr_stmt(stmt.value)
            return
        if isinstance(stmt, (ast.Delete,)):
            for t in stmt.targets:
                self._kill_target(t)
            return
        # raise/assert/global/pass/...: check embedded expressions
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.expr):
                self._check_expr(child)

    def _fork_branches(self, bodies):
        base_poison = dict(self.poisoned)
        base_don = dict(self.donating)
        merged = dict(base_poison)
        merged_don = dict(base_don)
        for body in bodies:
            self.poisoned = dict(base_poison)
            self.donating = dict(base_don)
            for s in body:
                self._stmt(s)
            merged.update(self.poisoned)      # union: "may" analysis
            merged_don.update(self.donating)  # `jitted = ...` chosen in
            # a branch (the step-variant selection idiom) stays known
        self.poisoned = merged
        self.donating = merged_don

    # -- assignments -----------------------------------------------------

    def _assign(self, stmt):
        if isinstance(stmt, ast.AugAssign):
            self._check_expr(stmt.value)
            self._check_expr(stmt.target)   # aug target is read first
            self._kill_target(stmt.target)
            return
        value = stmt.value
        targets = stmt.targets if isinstance(stmt, ast.Assign) \
            else ([stmt.target] if stmt.value is not None else [])
        if value is None:
            return
        # donating-callable binding? (f = jax.jit(...); AOT chains)
        pos = _donating_expr(value, self.env)
        if pos:
            for t in targets:
                tp = _path_of(t)
                if tp:
                    self.donating[tp] = pos
            # still check the jit args themselves for poisoned reads
            self._check_expr(value)
            return
        # factory binding: fn = self._get(...) where _get returns a
        # donating executable (resolved by the class pre-pass); a
        # literal donate=False / donate_argnums=() at the call site is
        # an explicit opt-out (the overlap-probe idiom)
        if isinstance(value, ast.Call):
            vf = _path_of(value.func)
            opted_out = any(
                kw.arg in ("donate", "donate_argnums") and
                ((isinstance(kw.value, ast.Constant) and
                  not kw.value.value) or
                 (isinstance(kw.value, (ast.Tuple, ast.List)) and
                  not kw.value.elts))
                for kw in value.keywords)
            if vf and len(vf) == 2 and vf[0] == "self" and \
                    vf[1] in self.cls_returns and not opted_out:
                for t in targets:
                    tp = _path_of(t)
                    if tp:
                        self.donating[tp] = self.cls_returns[vf[1]]
        # plain alias of a donating callable: g = self._step
        vp = _path_of(value)
        if vp is not None:
            dpos = self._donation_of(vp)
            if dpos:
                for t in targets:
                    tp = _path_of(t)
                    if tp:
                        self.donating[tp] = dpos
        # a lambda on the RHS (metrics = lambda: params.sum()) captures
        # its free names just like a nested def — record the aliases
        for n in ast.walk(value):
            if isinstance(n, ast.Lambda):
                self._note_closure(n)
        # self.X = name  — record the alias BEFORE any later donation
        for t in targets:
            if isinstance(t, ast.Attribute) and \
                    isinstance(t.value, ast.Name) and \
                    t.value.id == "self" and isinstance(value, ast.Name):
                self.aliases.setdefault(value.id, []).append(
                    f"stored into self.{t.attr} at line {stmt.lineno}")
        # RHS first (a donating call poisons its donated args, and
        # poisoned reads inside the RHS are violations) ...
        to_poison = self._expr_stmt(value, collect=True)
        # ... then the targets rebind: same-statement rebinding from the
        # result is the CLEAN pattern, so targets cancel pending poison
        killed = set()
        for t in targets:
            killed |= self._kill_target(t)
        for path, site in to_poison:
            if path not in killed:
                self.poisoned[path] = site

    def _kill_target(self, target):
        """Rebinding kills poison; returns the set of killed paths."""
        killed = set()
        if isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                killed |= self._kill_target(e)
            return killed
        if isinstance(target, ast.Starred):
            return self._kill_target(target.value)
        tp = _path_of(target)
        if tp is not None:
            killed.add(tp)
            self.poisoned.pop(tp, None)
            # rebinding a prefix (self.cache = ...) kills everything
            # under it
            for p in [p for p in self.poisoned
                      if p[:len(tp)] == tp and len(p) > len(tp)]:
                self.poisoned.pop(p, None)
                killed.add(p)
        elif isinstance(target, ast.Subscript):
            self._check_expr(target.value)
            self._check_expr(target.slice)
        return killed

    # -- expressions -----------------------------------------------------

    def _expr_stmt(self, expr, collect=False):
        """Process an expression statement / assignment RHS.  Donating
        calls poison their donated args AFTER the statement; with
        ``collect=True`` the pending poisons are returned instead of
        applied (assignment targets get a chance to cancel them)."""
        pending = []
        for call in self._calls_in(expr):
            pending.extend(self._handle_call(call))
        self._check_expr(expr, skip_calls=True)
        if collect:
            return pending
        for path, site in pending:
            self.poisoned[path] = site
        return []

    def _calls_in(self, expr):
        return [n for n in ast.walk(expr) if isinstance(n, ast.Call)]

    def _donation_of(self, path):
        if path in self.donating:
            return self.donating[path]
        if len(path) == 2 and path[0] == "self" and \
                path[1] in self.cls_don:
            return self.cls_don[path[1]]
        return None

    def _handle_call(self, call):
        """HB18 poison + HB20 aliasing for one call; returns pending
        ``(path, site)`` poisons."""
        callee_path = _path_of(call.func)
        pos = self._donation_of(callee_path) if callee_path else None
        args = list(call.args)
        shift = 0
        if pos is None and args:
            # dispatch-through: self._dispatch(jitted, *args) where the
            # first argument is itself a known donating callable
            a0 = _path_of(args[0])
            if a0 is not None:
                inner = self._donation_of(a0)
                if inner is not None:
                    pos = inner
                    shift = 1
        # inline jax.jit(step, donate_argnums=..)(a, b) immediate call
        if pos is None:
            inner = _donating_expr(call.func, self.env)
            if inner:
                pos = inner
        # immediate factory dispatch: self._get(kind, size, args)(*args)
        if pos is None and isinstance(call.func, ast.Call):
            ff = _path_of(call.func.func)
            if ff and len(ff) == 2 and ff[0] == "self" and \
                    ff[1] in self.cls_returns:
                pos = self.cls_returns[ff[1]]
                callee_path = ff
        if not pos:
            # a method call through the owner prefix of a poisoned path
            # may rebind fields the pass cannot see: kill under the
            # receiver (the cache.update_pools(...) clean pattern).
            # len > 2 so bare `self.helper()` does NOT launder self.*
            # poison — only calls on the owning sub-object do
            if callee_path is not None and len(callee_path) > 2:
                owner = callee_path[:-1]
                for p in [p for p in self.poisoned
                          if p[:len(owner)] == owner and p != owner]:
                    self.poisoned.pop(p, None)
            return []
        site = (f"`{_fmt_path(callee_path) if callee_path else '<call>'}"
                f"(...)` at line {call.lineno}")
        donated_paths = []
        pending = []
        for i in pos:
            j = i + shift
            if j >= len(args):
                # `f(*args)`: a donated position folded into a starred
                # tuple poisons the tuple name itself — reading any
                # element after the call is the same bug
                if args and isinstance(args[-1], ast.Starred):
                    sp = _path_of(args[-1].value)
                    if sp is not None and (sp, site) not in pending:
                        donated_paths.append((len(args) - 1, sp))
                        pending.append((sp, site))
                continue
            a = args[j]
            if isinstance(a, ast.Starred):
                a = a.value
            ap = _path_of(a)
            if ap is None:
                continue
            donated_paths.append((j, ap))
            pending.append((ap, site))
        # HB20(a): same object in two positions, at least one donated
        all_paths = {}
        for j, a in enumerate(args):
            ap = _path_of(a)
            if ap is not None:
                all_paths.setdefault(ap, []).append(j)
        for j, ap in donated_paths:
            if len(all_paths.get(ap, ())) > 1:
                self._violation(
                    "HB20", call,
                    f"`{_fmt_path(ap)}` is passed twice into donated "
                    f"call {site} — XLA donates the buffer once, the "
                    f"second reference dangles the moment the donor "
                    f"memory is reused")
        # HB20(b): donated arg has a live alias (self.* store / closure)
        for j, ap in donated_paths:
            if len(ap) == 1 and ap[0] in self.aliases:
                where = "; ".join(self.aliases[ap[0]])
                self._violation(
                    "HB20", call,
                    f"`{_fmt_path(ap)}` is donated by {site} but an "
                    f"alias outlives the call ({where}) — the aliased "
                    f"reference dangles after donation")
        return pending

    def _note_closure(self, fndef):
        """A nested def/lambda capturing a local by name: every
        captured name gains a closure alias (HB20(b))."""
        bound = set()
        if hasattr(fndef, "args"):
            a = fndef.args
            bound = {x.arg for x in
                     a.posonlyargs + a.args + a.kwonlyargs}
            if a.vararg:
                bound.add(a.vararg.arg)
            if a.kwarg:
                bound.add(a.kwarg.arg)
        body = fndef.body if isinstance(fndef.body, list) else [fndef.body]
        for node in body:
            for n in ast.walk(node):
                if isinstance(n, ast.Name) and \
                        isinstance(n.ctx, ast.Load) and \
                        n.id not in bound:
                    name = getattr(fndef, "name", "<lambda>")
                    self.aliases.setdefault(n.id, []).append(
                        f"captured by closure `{name}` at line "
                        f"{fndef.lineno}")

    def _check_expr(self, expr, reading="read", skip_calls=False):
        """Flag loads of poisoned paths inside ``expr`` (HB18)."""
        if expr is None:
            return
        for node in ast.walk(expr):
            if skip_calls and isinstance(node, ast.Call):
                continue
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue
            path = None
            if isinstance(node, ast.Attribute) and \
                    isinstance(node.ctx, ast.Load):
                path = _path_of(node)
            elif isinstance(node, ast.Name) and \
                    isinstance(node.ctx, ast.Load):
                path = (node.id,)
            if path is None:
                continue
            # a load of a poisoned path OR of anything under it
            hit_key, hit = None, None
            if path in self.poisoned:
                hit_key, hit = path, self.poisoned[path]
            else:
                for p, s in self.poisoned.items():
                    if path[:len(p)] == p:
                        hit_key, hit = p, s
                        break
            if hit is not None:
                self._violation(
                    "HB18", node,
                    f"`{_fmt_path(path)}` was donated to {hit} and is "
                    f"{reading} afterwards without rebinding — on TPU "
                    f"the buffer is gone (CPU XLA ignores donation, so "
                    f"tier-1 can't see this); rebind it from the "
                    f"call's result or drop the donation")
                # one report per poisoning: further reads of the same
                # path repeat the same bug
                self.poisoned.pop(hit_key, None)

    def _violation(self, rule, node, message):
        self.p.collector.add(Violation(
            rule=rule, path=self.p.path, line=node.lineno,
            col=getattr(node, "col_offset", 0), message=message,
            block=self.cls, func=self.fn.name))


# ----------------------------------------------------------------------
# HB19 — mesh-axis consistency
# ----------------------------------------------------------------------

class _MeshAxisConsistency(ast.NodeVisitor):
    """Axis names reaching a PartitionSpec / shard_map spec / collective
    must be canonical (AXIS_DP/AXIS_TP/AXIS_PP, or their literals inside
    the exempt parallel/mesh.py) AND constructible on the MeshConfig
    declared in the enclosing function — ``MeshConfig(dp=8)`` followed
    by an ``AXIS_TP`` collective is flagged before it ever reaches a
    mesh."""

    def __init__(self, collector, path):
        self.c = collector
        self.path = path
        self.func_stack = ["<module>"]
        # axes declared by a MeshConfig(...) ctor per function scope;
        # None = no (or ambiguous) declaration -> scope check off
        self.declared_stack = [None]
        norm = path.replace("\\", "/")
        self.exempt_literals = norm.endswith("parallel/mesh.py")

    # -- scope tracking --------------------------------------------------

    def visit_FunctionDef(self, node):
        self.func_stack.append(node.name)
        self.declared_stack.append(self._declared_axes(node))
        try:
            self.generic_visit(node)
        finally:
            self.func_stack.pop()
            self.declared_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def _declared_axes(self, fn):
        """The axis set of the single ``MeshConfig(...)``/``from_spec``
        declaration in ``fn``'s own body, or None when there is none or
        more than one (ambiguous scopes don't gate)."""
        decls = []
        for node in ast.walk(fn):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node is not fn:
                continue
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            name = f.attr if isinstance(f, ast.Attribute) else \
                getattr(f, "id", None)
            if name != "MeshConfig":
                continue
            if not node.keywords or any(kw.arg is None
                                        for kw in node.keywords):
                return None          # positional / **kw: can't resolve
            axes = set()
            for kw in node.keywords:
                if kw.arg in _CANONICAL_AXES:
                    v = kw.value
                    if isinstance(v, ast.Constant) and v.value == 1:
                        continue     # size-1 axis: not collective-able
                    axes.add(kw.arg)
            decls.append(axes)
        if len(decls) != 1:
            return None
        return decls[0]

    # -- reporting -------------------------------------------------------

    def _add(self, node, message):
        self.c.add(Violation(
            rule="HB19", path=self.path, line=node.lineno,
            col=getattr(node, "col_offset", 0), message=message,
            block="", func=self.func_stack[-1]))

    # -- axis extraction -------------------------------------------------

    def _axis_nodes(self, callee, call):
        """(node, axis_token_or_None) pairs for every axis-position
        argument of ``call``.  axis_token is the resolved axis string
        for canonical names/constants, None for unknown."""
        out = []
        if callee in _SPEC_CALLEES:
            subs = list(call.args) + [kw.value for kw in call.keywords]
            for sub in subs:
                for n in ast.walk(sub):
                    out.extend(self._classify(n))
        elif callee in _COLLECTIVE_CALLEES:
            cand = []
            if len(call.args) > 1:
                cand.append(call.args[1])   # psum(x, axis_name) slot
            cand += [kw.value for kw in call.keywords
                     if kw.arg == "axis_name"]
            for sub in cand:
                targets = sub.elts if isinstance(sub, (ast.Tuple,
                                                       ast.List)) \
                    else [sub]
                for n in targets:
                    out.extend(self._classify(n))
        elif callee in _SHARD_MAP_CALLEES:
            for kw in call.keywords:
                if kw.arg in ("in_specs", "out_specs", "axis_names"):
                    for n in ast.walk(kw.value):
                        out.extend(self._classify(n))
        return out

    def _classify(self, n):
        if isinstance(n, ast.Constant) and isinstance(n.value, str):
            return [(n, n.value if n.value in _CANONICAL_AXES else None)]
        name = None
        if isinstance(n, ast.Name):
            name = n.id
        elif isinstance(n, ast.Attribute):
            name = n.attr
        if name is not None and name.startswith("AXIS_"):
            return [(n, _CONST_TO_AXIS.get(name))]
        return []

    # -- the check -------------------------------------------------------

    def visit_Call(self, node):
        f = node.func
        callee = f.attr if isinstance(f, ast.Attribute) else \
            getattr(f, "id", None)
        if callee in _SPEC_CALLEES or callee in _COLLECTIVE_CALLEES or \
                callee in _SHARD_MAP_CALLEES:
            declared = self.declared_stack[-1]
            for n, axis in self._axis_nodes(callee, node):
                if axis is None:
                    what = (f'"{n.value}"'
                            if isinstance(n, ast.Constant)
                            else f"`{getattr(n, 'attr', None) or getattr(n, 'id', '?')}`")
                    self._add(n, (
                        f"axis {what} in `{callee}(...)` is not a "
                        f"canonical mesh axis "
                        f"({'/'.join(_CANONICAL_AXES)}): no MeshConfig "
                        f"can construct it — add it to "
                        f"parallel/mesh.py (AXIS_* + this catalog) "
                        f"before sharding over it"))
                elif isinstance(n, ast.Constant) and \
                        not self.exempt_literals:
                    # canonical literal outside mesh.py: HB17 territory
                    continue
                elif declared is not None and axis not in declared and \
                        callee in _COLLECTIVE_CALLEES:
                    self._add(n, (
                        f"collective `{callee}(...)` reduces over "
                        f"'{axis}' but the MeshConfig declared in this "
                        f"scope has no '{axis}' axis (missing or "
                        f"size 1) — the axis name will not resolve on "
                        f"the built mesh"))
        self.generic_visit(node)


# ----------------------------------------------------------------------
# the pass driver
# ----------------------------------------------------------------------

class _DataflowPass:
    def __init__(self, collector, path):
        self.collector = collector
        self.path = path

    def run(self, tree):
        # HB19 is a straight scan
        _MeshAxisConsistency(self.collector, self.path).visit(tree)
        # HB18/HB20: every function, with class-level donation context
        self._walk(tree, class_name=None, class_don=None)

    def _walk(self, node, class_name, class_don):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                cd = _ClassDonations(child)
                self._walk(child, child.name, cd)
            elif isinstance(child, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                _FunctionDataflow(
                    self, child, class_name,
                    class_don.attrs if class_don else {},
                    class_don.method_returns if class_don else {}).run()
                # nested defs get their own (closure-free) analysis
                self._walk(child, class_name, class_don)
            else:
                self._walk(child, class_name, class_don)


def run_dataflow_pass(collector, tree, path):
    """Run HB18/HB19/HB20 over one module; violations land in the
    shared collector (suppressions applied downstream)."""
    _DataflowPass(collector, path).run(tree)
