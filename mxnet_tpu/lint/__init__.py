"""``mx.lint`` — trace-safety static analyzer for HybridBlocks, plus the
runtime retrace detector.

Static side: ``mx.lint.check(block_or_module)`` walks the source of
``hybrid_forward``/``forward`` (helpers included) and reports
framework-level diagnostics with stable rule IDs:

    HB01  Python if/while/assert branching on NDArray values
    HB02  host-sync (.asnumpy()/.item()/float(x)) inside a traced forward
    HB03  host-materialized values fed back into ops (data-dependent
          jit cache key -> retrace storms)
    HB04  Parameters / fresh constant ndarrays allocated per call
    HB05  np.random / stdlib random draws inside a traced region
    HB06  as_in_context / device transfers in a hot forward
    HB07  eager collectives (kvstore push/pull/pushpull, process_allgather)
          inside Python loops — module-wide, not just forwards
    HB14  unguarded shared state (locked in one method, bare in another;
          `# guarded-by:` annotations) — interprocedural, concurrency.py
    HB15  lock-order inversion (cycle in the acquisition graph, merged
          across every linted file)
    HB16  blocking call (device sync / RPC / file IO / queue.get /
          time.sleep / jitted dispatch) inside a `with lock:` body
    HB17  hardcoded mesh-axis literal ("dp"/"tp"/"pp" in P()/collective
          calls, mesh.shape["dp"]/[0]) outside parallel/mesh.py

CLI: ``python tools/mxlint.py <paths>`` (non-zero exit on violations,
``--format=json|text``, per-line ``# mxlint: disable=HB0x``,
``--write-baseline``/``--baseline``/``--fail-on-new`` to gate CI on
regressions only). Rule catalog with bad/good snippets:
``docs/LINT.md`` or ``--list-rules``.

Runtime side 2 (``racecheck``): with ``MXTPU_RACECHECK=1`` the threaded
subsystems create their locks through ``lint.racecheck.make_lock``,
which maintains a live lock-order graph (cycles flagged the moment an
edge closes one) and checks registered guarded structures; findings
dump through the telemetry flight recorder.  Zero overhead when off.

Runtime side: every ``hybridize()``'d block counts its jax.jit cache
misses (gluon/block.py CachedOp) and emits a :class:`RetraceWarning`
once when a block crosses ``MXTPU_RETRACE_WARN`` distinct input
signatures — catching the dynamic retrace storms the static rules
cannot see.

This package is stdlib-only at import time so the CLI can run without
jax; it is also re-exported as ``mxnet_tpu.lint``.
"""
from __future__ import annotations

from .analyzer import lint_file, lint_source
from .api import check, lint_paths
from .report import Violation, render_json, render_text
from .retrace import RetraceMonitor, RetraceWarning, default_threshold
from .rules import ALL_RULE_IDS, RULES, Rule
from . import racecheck

__all__ = [
    "check", "lint_paths", "lint_source", "lint_file",
    "Violation", "render_text", "render_json",
    "RULES", "Rule", "ALL_RULE_IDS",
    "RetraceMonitor", "RetraceWarning", "default_threshold",
    "racecheck",
]
