"""``mx.lint`` — trace-safety static analyzer for HybridBlocks, plus the
runtime retrace detector.

Static side: ``mx.lint.check(block_or_module)`` walks the source of
``hybrid_forward``/``forward`` (helpers included) and reports
framework-level diagnostics with stable rule IDs:

    HB01  Python if/while/assert branching on NDArray values
    HB02  host-sync (.asnumpy()/.item()/float(x)) inside a traced forward
    HB03  host-materialized values fed back into ops (data-dependent
          jit cache key -> retrace storms)
    HB04  Parameters / fresh constant ndarrays allocated per call
    HB05  np.random / stdlib random draws inside a traced region
    HB06  as_in_context / device transfers in a hot forward
    HB07  eager collectives (kvstore push/pull/pushpull, process_allgather)
          inside Python loops — module-wide, not just forwards
    HB14  unguarded shared state (locked in one method, bare in another;
          `# guarded-by:` annotations) — interprocedural, concurrency.py
    HB15  lock-order inversion (cycle in the acquisition graph, merged
          across every linted file)
    HB16  blocking call (device sync / RPC / file IO / queue.get /
          time.sleep / jitted dispatch) inside a `with lock:` body
    HB17  hardcoded mesh-axis literal ("dp"/"tp"/"pp" in P()/collective
          calls, mesh.shape["dp"]/[0]) outside parallel/mesh.py
    HB18  use-after-donate: a name passed in a donated position of a
          jitted/AOT call is read/returned/stored afterwards without
          rebinding — intraprocedural dataflow, dataflow.py
    HB19  mesh-axis consistency: axis names reaching P(...)/shard_map
          specs/collective axis_name= must be the canonical AXIS_*
          constants and constructible on the enclosing MeshConfig
    HB20  donation aliasing: the same array (or an alias of it) passed
          twice into one donated call, or a donated buffer captured by
          a closure/self-field that outlives the call

CLI: ``python tools/mxlint.py <paths>`` (non-zero exit on violations,
``--format=json|text|sarif``, per-line ``# mxlint: disable=HB0x``,
``--write-baseline``/``--baseline``/``--fail-on-new`` to gate CI on
regressions only; the baseline reader accepts both its native JSON and
SARIF files). Rule catalog with bad/good snippets:
``docs/LINT.md`` or ``--list-rules``.

Runtime side 2 (``racecheck``): with ``MXTPU_RACECHECK=1`` the threaded
subsystems create their locks through ``lint.racecheck.make_lock``,
which maintains a live lock-order graph (cycles flagged the moment an
edge closes one) and checks registered guarded structures; findings
dump through the telemetry flight recorder.  Zero overhead when off.

Runtime side 3 (``donation``): with ``MXTPU_DONATION_CHECK=1`` the
donating dispatch seams (trainer step, serving pool swap) poison their
donor buffers after dispatch, and any later NDArray host touch
(``.asnumpy()``/``__getitem__``/``.shape``) of a poisoned buffer raises
a typed :class:`donation.UseAfterDonateError` naming the dispatch site
— reproducing on CPU the crash TPU donation would cause.  Findings
emit ``donation.*`` telemetry and a flight dump.  Zero overhead when
off.

Runtime side: every ``hybridize()``'d block counts its jax.jit cache
misses (gluon/block.py CachedOp) and emits a :class:`RetraceWarning`
once when a block crosses ``MXTPU_RETRACE_WARN`` distinct input
signatures — catching the dynamic retrace storms the static rules
cannot see.

This package is stdlib-only at import time so the CLI can run without
jax; it is also re-exported as ``mxnet_tpu.lint``.
"""
from __future__ import annotations

from .analyzer import lint_file, lint_source
from .api import check, lint_paths
from .report import Violation, render_json, render_sarif, render_text
from .retrace import RetraceMonitor, RetraceWarning, default_threshold
from .rules import ALL_RULE_IDS, RULES, Rule
from . import donation, racecheck

__all__ = [
    "check", "lint_paths", "lint_source", "lint_file",
    "Violation", "render_text", "render_json", "render_sarif",
    "RULES", "Rule", "ALL_RULE_IDS",
    "RetraceMonitor", "RetraceWarning", "default_threshold",
    "racecheck", "donation",
]
