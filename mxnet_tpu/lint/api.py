"""``mx.lint.check`` — lint live Blocks, classes, and modules.

Resolves the source of the object with ``inspect`` and runs the AST
analyzer (analyzer.py) over it, so the result is identical to the CLI
run on the defining file. The gluon import is deferred so the lint
package stays importable standalone (tools/mxlint.py loads it without
importing mxnet_tpu or jax).
"""
from __future__ import annotations

import inspect
import os
import types

from .analyzer import lint_file, lint_source

__all__ = ["check", "lint_paths"]


def _lint_class(cls, seen_modules, out, rules):
    """Lint every class along ``cls``'s MRO that defines a forward,
    skipping the framework base classes themselves."""
    for klass in cls.__mro__:
        if klass.__module__.endswith("gluon.block") or klass is object:
            continue                   # Block/HybridBlock bases
        defines_fwd = any(m in vars(klass)
                          for m in ("hybrid_forward", "forward"))
        if not defines_fwd:
            continue
        mod = inspect.getmodule(klass)
        if mod is None:
            continue
        key = (mod.__name__, klass.__name__)
        if key in seen_modules:
            continue
        seen_modules.add(key)
        try:
            source = inspect.getsource(mod)
            path = inspect.getsourcefile(mod) or f"<{mod.__name__}>"
        except (OSError, TypeError):
            continue                   # dynamically defined: no source
        out.extend(lint_source(source, path=path,
                               only_classes={klass.__name__}, rules=rules))


def check(block_or_module, rules=None, recursive=True):
    """Statically check a HybridBlock instance, Block subclass, or a
    python module for trace-safety violations (rules HB01-HB07).

    Returns a list of :class:`mxnet_tpu.lint.Violation`, empty when the
    target is trace-clean. ``rules`` restricts checking to a subset of
    rule IDs; ``recursive`` (instances only) also checks the classes of
    all child blocks.

    Examples
    --------
    >>> net = model_zoo.vision.resnet18_v1()
    >>> assert not mx.lint.check(net)
    >>> mx.lint.check(mxnet_tpu.gluon.model_zoo.vision.yolo)
    """
    if rules is not None:
        rules = {r.upper() for r in rules}
    out = []
    seen = set()
    if isinstance(block_or_module, types.ModuleType):
        try:
            source = inspect.getsource(block_or_module)
            path = inspect.getsourcefile(block_or_module) \
                or f"<{block_or_module.__name__}>"
        except (OSError, TypeError):
            return []
        return lint_source(source, path=path, rules=rules)
    if isinstance(block_or_module, type):
        _lint_class(block_or_module, seen, out, rules)
        return _dedupe(out)
    # instance: its class, plus children when recursive
    cls = type(block_or_module)
    _lint_class(cls, seen, out, rules)
    if recursive:
        stack = list(getattr(block_or_module, "_children", {}).values())
        while stack:
            child = stack.pop()
            _lint_class(type(child), seen, out, rules)
            stack.extend(getattr(child, "_children", {}).values())
    return _dedupe(out)


def _dedupe(violations):
    seen = set()
    out = []
    for v in violations:
        key = (v.rule, v.path, v.line, v.col)
        if key not in seen:
            seen.add(key)
            out.append(v)
    return sorted(out, key=lambda v: (v.path, v.line, v.col, v.rule))


def lint_paths(paths, rules=None):
    """Lint files and directories (recursing into ``*.py``). Returns
    (violations, files_checked). Unreadable/unparsable files raise.

    HB15 runs twice: per file (intra-module cycles) and once over the
    MERGED lock-order edges of every linted file, so an inversion whose
    two orders live in different modules is still caught (the edges
    share nodes through class-qualified lock tokens)."""
    files = []
    for p in paths:
        if os.path.isdir(p):
            for root, _dirs, names in os.walk(p):
                files.extend(os.path.join(root, n)
                             for n in sorted(names) if n.endswith(".py"))
        else:
            files.append(p)
    out = []
    merged_edges = []
    want_hb15 = rules is None or "HB15" in rules
    for f in files:
        out.extend(lint_file(f, rules=rules))
        if want_hb15:
            from .concurrency import collect_lock_edges
            try:
                with open(f, encoding="utf-8") as fh:
                    merged_edges.extend(collect_lock_edges(fh.read(), f))
            except OSError:
                pass
    if want_hb15 and merged_edges:
        from .concurrency import cross_module_cycles
        out.extend(cross_module_cycles(merged_edges))
    return _dedupe(out), len(files)
