"""``mx.lint`` rule catalog — trace-safety rules for HybridBlocks.

Each rule has a stable ID (HB01..HB06) used in diagnostics and in
``# mxlint: disable=HB0x`` suppression comments. The catalog carries a
one-line summary plus a bad/good snippet pair; ``docs/LINT.md`` renders
the same catalog for humans.

Why these six: ``hybridize()`` compiles ``hybrid_forward`` with
``jax.jit`` (gluon/block.py CachedOp). Anything that forces the traced
values onto the host (HB01/HB02), makes the jit cache key depend on
tensor *data* rather than shapes (HB03), re-allocates constants or
parameters per trace (HB04), draws host randomness inside the trace
(HB05), or moves data across devices mid-forward (HB06) either throws a
``TracerBoolConversionError`` deep inside jax, silently serializes the
device, or triggers the retrace/recompile storms that dominate TPU-pod
utilization loss (arXiv:2011.03641 §4; ROADMAP north star).
"""
from __future__ import annotations

from collections import namedtuple

Rule = namedtuple("Rule", ["id", "title", "summary", "bad", "good"])

RULES = {
    "HB01": Rule(
        "HB01", "python-branch-on-tensor",
        "Python `if`/`while`/`assert`/`and`/`or` on an NDArray value: "
        "under jax.jit the value is an abstract tracer, so `bool()` "
        "raises TracerBoolConversionError (or forces a host sync in "
        "eager mode). Branch on static shapes, or compute both sides "
        "and select with F.where.",
        "if x > 0:\n    x = x * 2",
        "x = F.where(x > 0, x * 2, x)      # stays in-graph\n"
        "if x.shape[0] > 4: ...            # shapes are static: fine"),
    "HB02": Rule(
        "HB02", "host-sync-in-forward",
        "Host-sync conversion (`.asnumpy()`, `.asscalar()`, `.item()`, "
        "`.tolist()`, or `float()`/`int()`/`bool()` on a tensor) inside "
        "a traced forward: blocks the device pipeline and fails under "
        "jax.jit (TracerArrayConversionError).",
        "scale = float(F.max(x))           # device->host round-trip\n"
        "return x / scale",
        "return x / F.max(x)               # stays on device\n"
        "n = int(x.shape[1])               # shape metadata: fine"),
    "HB03": Rule(
        "HB03", "data-dependent-cache-key",
        "A host-materialized value (from `.item()`/`.asnumpy()`/`int()` "
        "on a tensor) fed back into an op argument or tensor slice: the "
        "jit cache key becomes data-dependent, so every new *value* "
        "compiles a new program (retrace storm).",
        "k = int(F.sum(mask))\n"
        "top = F.slice_axis(x, axis=0, begin=0, end=k)",
        "top = F.slice_axis(x, axis=0, begin=0,\n"
        "                   end=x.shape[0] // 2)   # shape-derived: one\n"
        "                                          # trace per shape"),
    "HB04": Rule(
        "HB04", "alloc-in-forward",
        "Allocating a `Parameter` (`self.params.get(...)`) or a fresh "
        "constant ndarray (`F.array([...])` on non-tensor data) inside "
        "forward: the constant is re-created and baked into every "
        "trace; parameters created per-call never train. Create them in "
        "`__init__` (Parameter/Constant) and close over them.",
        "def hybrid_forward(self, F, x):\n"
        "    w = F.array([0.299, 0.587, 0.114])\n"
        "    return F.dot(x, w)",
        "# __init__: self.w = self.params.get_constant('w', [...])\n"
        "def hybrid_forward(self, F, x, w):\n"
        "    return F.dot(x, w)\n"
        "y = F.zeros_like(x)               # shaped like an input: fine"),
    "HB05": Rule(
        "HB05", "host-rng-in-forward",
        "`np.random.*` / stdlib `random.*` draw inside a traced "
        "forward: the draw happens once at trace time and is baked into "
        "the compiled program as a constant — every call replays the "
        "same 'random' numbers. Use `F.random.*`, which threads the "
        "per-call PRNG key through the trace.",
        "noise = F.array(np.random.randn(4))\n"
        "return x + noise",
        "return x + F.random.normal(shape=(4,))   # fresh per call"),
    "HB06": Rule(
        "HB06", "device-transfer-in-forward",
        "`as_in_context`/`copyto` device transfer in a hot forward: "
        "inside a trace it pins placement against the mesh sharding "
        "(and eagerly it serializes H2D/D2H per call). Move data before "
        "the forward; let jit/shard_map place values.",
        "x = x.as_in_context(mx.cpu())\n"
        "return self.body(x)",
        "# transfer once, outside forward:\n"
        "# data = data.as_in_context(ctx)  (in the input pipeline)\n"
        "return self.body(x)"),
    "HB07": Rule(
        "HB07", "eager-collective-in-loop",
        "An eager collective (kvstore `push`/`pull`/`pushpull`/"
        "`broadcast`, `multihost_utils.process_allgather`) inside a "
        "Python `for`/`while` loop: each iteration pays a full dispatch "
        "+ wire round, so bandwidth craters O(n_keys) (SURVEY.md §7 "
        "perf cliff). Batch the keys into ONE call — the stores "
        "coalesce a key list into BIGARRAY_BOUND-sized buckets — or "
        "move the collective in-graph (traced push lowers to one "
        "psum).  Applies to any function, not just forwards.",
        "for i, p in enumerate(params):\n"
        "    kv.pushpull(i, p.grad(), out=p.grad())",
        "keys = list(range(len(params)))\n"
        "grads = [p.grad() for p in params]\n"
        "kv.pushpull(keys, grads, out=grads)   # one bucketed round"),
    "HB08": Rule(
        "HB08", "signal-in-forward",
        "`signal.signal` / `signal.raise_signal` / `os.kill` / "
        "`os.killpg` inside a HybridBlock forward: host-side process "
        "control is a side effect — under jax.jit it runs once at "
        "trace time (never again on replay), and signal handler "
        "registration is only legal on the main thread while traces "
        "may run anywhere. Install handlers at startup "
        "(mx.checkpoint.PreemptionHandler) and keep forwards pure.",
        "def hybrid_forward(self, F, x):\n"
        "    signal.signal(signal.SIGTERM, self._on_term)\n"
        "    return self.body(x)",
        "# startup, outside any forward:\n"
        "# with mx.checkpoint.PreemptionHandler() as h: ...\n"
        "def hybrid_forward(self, F, x):\n"
        "    return self.body(x)"),
    "HB09": Rule(
        "HB09", "host-sync-between-backward-and-step",
        "A host sync (`.asnumpy()`/`.asscalar()`/`.item()`/`.tolist()`/"
        "`.wait_to_read()`) between `backward()` and `trainer.step()` in "
        "a training loop: the sync blocks the host until the whole "
        "backward drains, so per-bucket gradient collectives dispatched "
        "from grad-ready hooks (parallel.OverlapScheduler) — and the "
        "async step dispatch itself — serialize behind it, defeating "
        "comm/compute overlap. Read the loss AFTER step() (the value is "
        "identical; the sync then overlaps the next dispatch).",
        "loss.backward()\n"
        "print(loss.asnumpy())          # host sync: backward drains,\n"
        "trainer.step(batch_size)       # bucket comm can't overlap",
        "loss.backward()\n"
        "trainer.step(batch_size)       # step dispatches async\n"
        "print(loss.asnumpy())          # sync AFTER the dispatches"),
    "HB11": Rule(
        "HB11", "per-token-host-sync-in-decode-loop",
        "A per-token host pull (`.item()`, `.asnumpy()`, `.asscalar()`, "
        "`.tolist()`, `float()`) inside a decode/generation loop (a loop "
        "driving a decoder step — `decoder(...)`/`.decode_step(...)`): "
        "autoregressive decode runs ONE small compiled step per token, "
        "so a host round-trip per token serializes the whole serving "
        "batch behind the slowest pull — the serving twin of HB10. Keep "
        "sampling/argmax in the compiled step (the engine returns the "
        "sampled token), batch EOS checks at chunk boundaries, and pull "
        "sequences once at the end.",
        "for t in range(max_new):\n"
        "    logits, st = decoder(tok, st)\n"
        "    tok = int(logits.asnumpy().argmax())  # sync per token",
        "for t in range(max_new):\n"
        "    tok, st = decoder(tok, st)      # token sampled in-graph\n"
        "out = seq.asnumpy()                 # ONE pull after the loop"),
    "HB10": Rule(
        "HB10", "per-step-host-pull-in-multi-step-loop",
        "A per-step host pull of loss/metrics (`float(loss)`, "
        "`.item()`, `.asnumpy()`, `.asscalar()`, `.tolist()`, "
        "`.wait_to_read()`) inside a training loop that drives the "
        "compiled multi-step path (`trainer.step_multi`, "
        "MXTPU_STEPS_PER_CALL>1): scanning K steps into one dispatch "
        "buys ONE host sync per window, and a pull inside a nested "
        "per-step loop pays K syncs per dispatch — the exact per-step "
        "host round-trip the scan exists to remove. Pull the (K,) loss "
        "vector ONCE at the scan boundary and slice it on the host.",
        "for window in prefetcher.windows(k):\n"
        "    losses = trainer.step_multi(window)\n"
        "    for l in losses:\n"
        "        total += float(l)      # K host syncs per dispatch",
        "for window in prefetcher.windows(k):\n"
        "    losses = trainer.step_multi(window)\n"
        "    total += losses.asnumpy().sum()  # ONE boundary sync"),
    "HB12": Rule(
        "HB12", "world-size-read-in-forward",
        "`jax.device_count()` / `jax.devices()` / mesh-size reads "
        "(`mesh.shape[...]`, `mesh.size`) inside a hybridized forward: "
        "the world size is a trace-time Python int, so it is BAKED into "
        "the compiled program — after an elastic reshard "
        "(mx.elastic, dp changed mid-run) every cached graph silently "
        "computes with the OLD world size (wrong loss scaling, wrong "
        "shard math) instead of failing. Capture the size in __init__ "
        "and let the controller rebuild the block on reshard, or "
        "derive it in-graph (lax.psum of ones over the axis).",
        "def hybrid_forward(self, F, x):\n"
        "    return x / jax.device_count()   # baked in; stale after\n"
        "                                    # an elastic reshard",
        "# __init__: self._dp = dp   (trainer.rebuild() re-creates\n"
        "#           the graph with the new size after a reshard)\n"
        "def hybrid_forward(self, F, x):\n"
        "    return x / self._dp"),
    "HB13": Rule(
        "HB13", "unsynced-device-timing",
        "A `time.time()`/`time.perf_counter()` delta wrapping a jitted/"
        "compiled call with no `block_until_ready`/`wait_to_read`/host "
        "read between the dispatch and the delta: jax dispatches "
        "asynchronously, so the measured span is the HOST DISPATCH "
        "time, not device compute — the classic way a benchmark (or a "
        "telemetry gauge) reports a 100x-too-fast step. Sync on the "
        "result inside the timed region, or name the metric dispatch_ms "
        "and measure compute via the profiler.",
        "f = jax.jit(step)\n"
        "t0 = time.perf_counter()\n"
        "y = f(x)                    # returns BEFORE the device runs\n"
        "dt = time.perf_counter() - t0   # dispatch, not compute",
        "f = jax.jit(step)\n"
        "t0 = time.perf_counter()\n"
        "y = f(x)\n"
        "jax.block_until_ready(y)    # drain the device first\n"
        "dt = time.perf_counter() - t0"),
    "HB14": Rule(
        "HB14", "unguarded-shared-state",
        "A mutable field of a lock-owning class accessed under the lock "
        "in one method but with NO lock held in another (in a module "
        "that runs threads): a locked writer races the bare access — "
        "torn reads, lost updates, the silent corruption chaos kills "
        "only catch by luck. Take the lock at every access, declare the "
        "invariant with `# guarded-by: _lock` (on the field assignment: "
        "every access must hold it; on a `def` line: the method runs "
        "with it already held), or justify a lock-free design with "
        "`# mxlint: disable=HB14`.",
        "class Stats:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.n = 0\n"
        "    def add(self):           # worker thread\n"
        "        with self._lock:\n"
        "            self.n += 1\n"
        "    def summary(self):\n"
        "        return self.n        # bare read races add()",
        "    def summary(self):\n"
        "        with self._lock:     # snapshot under the lock,\n"
        "            n = self.n       # compute after release\n"
        "        return n"),
    "HB15": Rule(
        "HB15", "lock-order-inversion",
        "A cycle in the statically derived lock acquisition graph: one "
        "code path takes lock A then B (directly or through a called "
        "method), another takes B then A — two threads interleaving "
        "those orders deadlock, and only under load. The edges are "
        "merged across every linted file, so an inversion split across "
        "modules is still caught. Pick ONE global order and document "
        "it, or restructure so the inner lock is released first.",
        "def transfer(src, dst):\n"
        "    with src.lock:\n"
        "        with dst.lock:       # order depends on caller:\n"
        "            ...              # transfer(a,b) || transfer(b,a)\n"
        "                             # deadlocks",
        "def transfer(src, dst):\n"
        "    first, second = sorted((src, dst), key=id)\n"
        "    with first.lock:         # ONE global order, any caller\n"
        "        with second.lock:\n"
        "            ..."),
    "HB16": Rule(
        "HB16", "blocking-call-under-lock",
        "A blocking operation inside a `with lock:` body — device sync "
        "(`.asnumpy()`/`block_until_ready`), RPC/socket I/O, file I/O "
        "(`open`/`.write`/`.flush`/`print`), `queue.get/put`, "
        "`time.sleep`, a thread join, or dispatch of a jit-compiled "
        "callable: every other thread needing the lock stalls behind "
        "the wait, and on the step path that host-side stall directly "
        "caps throughput (arXiv:2011.03641). Snapshot state under the "
        "lock, do the blocking work after release. (`cv.wait()` on the "
        "held condition is exempt — releasing while waiting is the "
        "point.)",
        "with self._lock:\n"
        "    arr = self._table[key]\n"
        "    sock.sendall(pack(arr))   # wire round under the lock:\n"
        "                              # every push/pull stalls",
        "with self._lock:\n"
        "    arr = self._table[key].copy()   # snapshot under the lock\n"
        "sock.sendall(pack(arr))             # blocking work outside"),
    "HB17": Rule(
        "HB17", "hardcoded-mesh-axis",
        "A literal \"dp\"/\"tp\"/\"pp\" string inside a PartitionSpec "
        "or collective call, or a literal index into a mesh's "
        "`.shape`/`.axis_names` (`mesh.shape[\"dp\"]`, `mesh.shape[0]`)"
        " outside parallel/mesh.py.  The axis names are MeshConfig's "
        "single-source contract (ISSUE 11): a hardcoded copy keeps "
        "compiling after the mesh layout changes — a 2x2x2 config, an "
        "elastic reshard, a reordered axis — and then shards or "
        "reduces over the WRONG axis silently.  Import "
        "AXIS_DP/AXIS_TP/AXIS_PP from parallel.mesh (or read sizes "
        "through MeshConfig) so the name has one owner.",
        "spec = P(\"dp\", None)          # literal axis name\n"
        "dp = self.mesh.shape[\"dp\"]    # literal shape index",
        "from mxnet_tpu.parallel.mesh import AXIS_DP\n"
        "spec = P(AXIS_DP, None)\n"
        "dp = self.mesh.shape[AXIS_DP]   # one owner for the name"),
    "HB18": Rule(
        "HB18", "use-after-donate",
        "A name passed in a donated position of a jitted/AOT call "
        "(`donate_argnums`, including executables built in another "
        "method and dispatch-through helpers) is read, returned, or "
        "stored afterwards without rebinding. CPU XLA silently ignores "
        "donation, so tier-1 cannot see this — it is a latent "
        "deleted-buffer crash that fires on the first real TPU round. "
        "Rebind the name from the call's result (the clean pattern) or "
        "drop the donation.",
        "step = jax.jit(f, donate_argnums=(0,))\n"
        "new = step(params)\n"
        "norm = params[0].sum()        # params was donated: gone on TPU",
        "step = jax.jit(f, donate_argnums=(0,))\n"
        "params = step(params)         # rebound from the result\n"
        "norm = params[0].sum()        # reads the NEW buffer"),
    "HB19": Rule(
        "HB19", "unknown-mesh-axis",
        "An axis name reaching `P(...)`, `shard_map(in_specs/"
        "out_specs)`, or a collective (`psum`/`all_gather`/... "
        "`axis_name=`) that is not a canonical mesh axis (dp/tp/pp "
        "via the parallel/mesh.py AXIS_* constants), or a collective "
        "over an axis the MeshConfig declared in the enclosing scope "
        "cannot construct (missing or size 1). The call compiles on "
        "CPU and then fails — or silently reduces over the wrong "
        "group — when the mesh is built. Add the axis to "
        "parallel/mesh.py first, and size it >1 on the config that "
        "reaches this call.",
        'g = lax.psum(x, "sp")            # no mesh has an "sp" axis\n'
        "cfg = MeshConfig(dp=8)\n"
        "y = lax.psum(x, AXIS_TP)         # dp-only mesh: tp won't "
        "resolve",
        "from mxnet_tpu.parallel.mesh import AXIS_DP\n"
        "cfg = MeshConfig(dp=4, tp=2)\n"
        "y = lax.psum(x, AXIS_DP)         # canonical axis, on this "
        "mesh"),
    "HB20": Rule(
        "HB20", "donation-aliasing",
        "The same array object passed twice into one donated call, or "
        "a donated buffer that was first stored into a `self.*` field "
        "or captured by a closure. XLA donates the buffer once; every "
        "other reference silently dangles the moment the donor memory "
        "is reused — corruption, not a crash, and only on TPU.",
        "self._snapshot = params          # alias created...\n"
        "new = step(params)               # ...then params donated:\n"
        "                                 # self._snapshot dangles",
        "new = step(params)\n"
        "self._snapshot = new             # alias the RESULT, which\n"
        "                                 # nobody donates"),
    "HB21": Rule(
        "HB21", "unscaled-lowp-cast",
        "A raw `.astype(...)` (or `lax.convert_element_type`) to a "
        "narrow format — int8, fp8 (float8_e4m3fn / float8_e5m2), or "
        "bf16 — outside the ops/quant_* scaled helpers.  Narrow "
        "formats clip: int8 saturates at ±127, fp8-e4m3 at ±448, so a "
        "cast whose operand was never divided by an amax-derived "
        "scale silently flushes the tensor's tails to the format "
        "ceiling.  CPU tier-1 runs the same cast on the same tame "
        "values and passes; the loss spike fires on the first real "
        "TPU round with production magnitudes (ISSUE 20).  Route the "
        "cast through ops.quant_matmul (quantize_rtn_int8 / "
        "quantize_sr_int8 / quant_matmul) or ops.quant_kv "
        "(kv_quantize_fp8 / kv_cast) so a scale always rides with the "
        "narrowed bits; genuinely scale-free casts (bf16 keeps f32's "
        "exponent range on a comms wire) carry a per-line disable "
        "with the justification.",
        "q = x.astype(jnp.int8)            # |x|>127 saturates\n"
        "k = keys.astype(jnp.float8_e4m3fn)  # tails flushed at 448",
        "from mxnet_tpu.ops.quant_matmul import quantize_rtn_int8\n"
        "q = quantize_rtn_int8(x, scale)   # scale rides with the cast\n"
        "codes, s = kv_quantize_fp8(keys)  # per-row amax scales"),
}

ALL_RULE_IDS = tuple(sorted(RULES))


def is_valid_rule(rule_id):
    return rule_id in RULES
