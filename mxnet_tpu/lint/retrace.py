"""Runtime retrace detector — the dynamic complement to the static rules.

The static analyzer can't see shapes that only exist at runtime: a
data loader that emits a different sequence length every batch defeats
HB03's static view entirely. This module counts jax.jit cache misses
per hybridized block (every distinct input shape/dtype signature is one
retrace + recompile) and warns ONCE per block when the count crosses a
threshold — the observable symptom of the retrace storms that dominate
TPU-pod utilization loss (arXiv:2011.03641 §4).

Wired into ``gluon/block.py`` ``CachedOp.__call__``; tune with
``MXTPU_RETRACE_WARN=<n>`` (default 3: the warning fires on the 4th
distinct signature; 0 disables). The fix is usually shape bucketing
(pad to a small set of shapes — see BucketingModule) or hoisting the
shape-varying prefix out of the hybridized block.
"""
from __future__ import annotations

import os
import warnings

__all__ = ["RetraceWarning", "RetraceMonitor", "default_threshold"]


class RetraceWarning(UserWarning):
    """A hybridized block is retracing/recompiling excessively."""


def default_threshold():
    """MXTPU_RETRACE_WARN env (distinct signatures tolerated before the
    warning; 0 disables the detector)."""
    try:
        return int(os.environ.get("MXTPU_RETRACE_WARN", "3"))
    except ValueError:
        return 3


class RetraceMonitor:
    """Tracks distinct (train, shapes, dtypes) signatures for one
    CachedOp. Each new signature is a jax.jit cache miss: a full
    retrace + XLA compile. ``record`` is O(1) per call (set lookup)."""

    def __init__(self, name, threshold=None):
        self.name = name
        self.threshold = default_threshold() if threshold is None \
            else threshold
        self.signatures = set()
        self.calls = 0
        self.warned = False

    @property
    def misses(self):
        return len(self.signatures)

    def record(self, signature):
        """Record one call; returns True when this signature is new
        (i.e. this call pays a retrace)."""
        self.calls += 1
        if signature in self.signatures:
            return False
        self.signatures.add(signature)
        if (not self.warned and self.threshold > 0
                and len(self.signatures) > self.threshold):
            self.warned = True
            warnings.warn(
                f"block '{self.name}' has retraced "
                f"{len(self.signatures)} times in {self.calls} calls "
                f"(every distinct input signature recompiles under "
                f"jax.jit); newest signature: {signature!r}. Pad inputs "
                f"to a fixed set of shapes (shape bucketing) or run "
                f"`mx.lint.check` on the block for data-dependent "
                f"patterns. Tune with MXTPU_RETRACE_WARN=<n> (0 "
                f"disables).", RetraceWarning, stacklevel=3)
        return True
