"""AST taint analyzer for HybridBlock trace-safety (rules HB01-HB06).

Works on *source*, not live objects, so ``tools/mxlint.py`` can lint a
tree without importing it (and without importing jax). The walk:

1. Index the module: top-level functions, classes, their methods and
   base-class names. A class is "blocky" when it (transitively, within
   the module) derives from a base whose name contains ``Block``, or
   when it defines ``hybrid_forward`` itself.
2. For every blocky class, analyze the entry points ``hybrid_forward``
   and ``forward`` with their tensor arguments seeded as tainted.
3. Propagate two taints through expressions and assignments:
   - *tensor*: the value is (or contains) an NDArray/tracer. Branching
     on it is HB01; converting it to a Python scalar/array is HB02.
   - *host*: a Python value materialized FROM tensor data (the result
     of an HB02 conversion). Feeding it back into an op argument or a
     tensor slice bound is HB03 — the jit cache key becomes
     data-dependent and every new value recompiles.
   ``.shape``/``.dtype``/metadata reads and ``len(tensor)`` yield
   *untainted* values: under jit, shapes are static per trace, so
   shape-derived control flow and slice bounds are the supported idiom.
4. Helper calls (``self._helper(...)`` methods and same-module
   functions) are resolved and analyzed at the call site with the
   caller's argument taints, so violations inside helpers reached from
   a traced forward are reported at the helper's own lines.

The analysis is deliberately framework-level: it flags ``.asnumpy()``
where jax would only name a primitive three stack frames deep.
"""
from __future__ import annotations

import ast

from .concurrency import run_concurrency_pass
from .dataflow import run_dataflow_pass
from .report import Violation
from .suppressions import parse_suppressions, is_suppressed

__all__ = ["lint_source", "lint_file"]

# tensor metadata reads that are static under a jit trace
_META_ATTRS = {"shape", "dtype", "ndim", "size", "context", "ctx",
               "stype", "grad_req", "name"}
# methods whose call forces tensor data onto the host (HB02)
_SYNC_METHODS = {"asnumpy", "asscalar", "item", "tolist"}
# builtins that force a host sync when applied to a tensor (HB02)
_SYNC_BUILTINS = {"float", "int", "bool", "complex"}
# device-transfer methods (HB06)
_TRANSFER_METHODS = {"as_in_context", "as_in_ctx", "copyto"}
# names conventionally bound to the op namespace inside forwards
_OP_NAMESPACE_NAMES = {"F", "nd", "npx"}
# module roots whose ``.random`` submodule is host RNG (HB05)
_HOST_RNG_ROOTS = {"np", "numpy", "_np", "onp"}
# host process-control calls that must never live in a forward (HB08)
_SIGNAL_CALLS = {"signal.signal", "signal.raise_signal", "signal.alarm",
                 "os.kill", "os.killpg"}
# world-size reads that bake the dp size into a trace (HB12): the call
# forms; the mesh-attribute forms are matched structurally in ev()
_WORLD_SIZE_CALLS = {"device_count", "local_device_count",
                     "process_count"}
_DEVICE_LIST_CALLS = {"jax.devices", "jax.local_devices"}


def _mesh_receiver(node):
    """True when an attribute chain's receiver names a mesh binding
    (``mesh``, ``self.mesh``, ``self._mesh``, ``tp_mesh`` ...) — the
    HB12 mesh-size-read heuristic."""
    dotted = _dotted(node)
    return bool(dotted) and any("mesh" in part.lower()
                                for part in dotted.split("."))


class _Taint:
    """tensor: the value IS a tensor/tracer (bool() on it is unsafe).
    host: a Python value materialized from tensor data (HB03 source).
    container: a Python tuple/list/dict possibly HOLDING tensors —
    truthiness is a safe len() check, but elements are tensors."""
    __slots__ = ("tensor", "host", "container")

    def __init__(self, tensor=False, host=False, container=False):
        self.tensor = tensor
        self.host = host
        self.container = container

    def __or__(self, other):
        return _Taint(self.tensor or other.tensor,
                      self.host or other.host,
                      self.container or other.container)

    @property
    def clean(self):
        return not (self.tensor or self.host or self.container)


_NONE = _Taint()
_TENSOR = _Taint(tensor=True)
_HOST = _Taint(host=True)
_CONTAINER = _Taint(container=True)

# predicates over python structure: static under a trace, return py bool
_STRUCTURE_BUILTINS = {"isinstance", "hasattr", "callable", "issubclass"}


def _base_names(classdef):
    names = []
    for b in classdef.bases:
        if isinstance(b, ast.Name):
            names.append(b.id)
        elif isinstance(b, ast.Attribute):
            names.append(b.attr)
    return names


def _dotted(node):
    """'np.random.uniform' for an Attribute chain of Names, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class _ModuleIndex:
    def __init__(self, tree):
        self.functions = {}
        self.classes = {}
        self.op_namespaces = set(_OP_NAMESPACE_NAMES)
        self.rng_names = set()      # `from random import randint` etc.
        self._blocky_cache = {}
        for node in tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions[node.name] = node
            elif isinstance(node, ast.ClassDef):
                self.classes[node.name] = node
            elif isinstance(node, ast.Import):
                for a in node.names:
                    alias = a.asname or a.name.split(".")[0]
                    if "ndarray" in a.name or a.name.startswith("jax.numpy"):
                        self.op_namespaces.add(alias)
            elif isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                if mod == "random" or mod.endswith(".random") and \
                        mod.split(".")[0] in _HOST_RNG_ROOTS:
                    for a in node.names:
                        self.rng_names.add(a.asname or a.name)
                if mod.endswith("ndarray"):
                    for a in node.names:
                        if a.name in ("ndarray", "ops"):
                            self.op_namespaces.add(a.asname or a.name)

    def methods_of(self, class_name):
        """Own + same-module-inherited methods, derived-most first."""
        out = {}
        seen = set()
        stack = [class_name]
        while stack:
            name = stack.pop(0)
            if name in seen or name not in self.classes:
                continue
            seen.add(name)
            cd = self.classes[name]
            for item in cd.body:
                if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    out.setdefault(item.name, (name, item))
            stack.extend(_base_names(cd))
        return out

    def is_blocky(self, class_name):
        if class_name in self._blocky_cache:
            return self._blocky_cache[class_name]
        self._blocky_cache[class_name] = False       # cycle guard
        cd = self.classes.get(class_name)
        result = False
        if cd is not None:
            if any(isinstance(i, (ast.FunctionDef, ast.AsyncFunctionDef))
                   and i.name == "hybrid_forward" for i in cd.body):
                result = True
            else:
                for base in _base_names(cd):
                    if "Block" in base or self.is_blocky(base):
                        result = True
                        break
        self._blocky_cache[class_name] = result
        return result


class _FunctionAnalyzer(ast.NodeVisitor):
    """Taint walk of one function body. ``env`` maps local names to
    _Taint; violations accumulate into the shared collector."""

    def __init__(self, collector, index, path, class_name, func_name,
                 env, op_names, depth):
        self.c = collector
        self.index = index
        self.path = path
        self.class_name = class_name
        self.func_name = func_name
        self.env = env
        self.op_names = op_names       # names bound to the op namespace
        self.depth = depth
        self.return_taint = _NONE

    # -- plumbing -------------------------------------------------------

    def _report(self, rule, node, message):
        self.c.add(Violation(rule=rule, path=self.path, line=node.lineno,
                             col=node.col_offset, message=message,
                             block=self.class_name, func=self.func_name))

    def _lookup(self, name):
        return self.env.get(name, _NONE)

    def _assign(self, target, taint):
        if isinstance(target, ast.Name):
            self.env[target.id] = taint
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._assign(elt, taint)
        elif isinstance(target, ast.Starred):
            # `x, *rest = ...`: rest is a python LIST of the remaining
            # elements — container semantics, not a bare tensor
            self._assign(target.value,
                         _CONTAINER if (taint.tensor or taint.container)
                         else taint)
        # attribute/subscript targets: no local binding to track

    # -- expression taint -----------------------------------------------

    def ev(self, node):  # noqa: C901 — one dispatch table, kept flat
        if node is None:
            return _NONE
        if isinstance(node, ast.Name):
            return self._lookup(node.id)
        if isinstance(node, ast.Constant):
            return _NONE
        if isinstance(node, ast.Attribute):
            base = self.ev(node.value)
            if base.tensor and node.attr in _META_ATTRS:
                return _NONE           # static shape/dtype metadata
            if base.tensor:
                return _TENSOR         # x.T and friends
            if node.attr == "size" and _mesh_receiver(node.value):
                self._report(
                    "HB12", node,
                    "mesh size read inside a traced forward: the world "
                    "size is baked into the compiled program and goes "
                    "silently stale after an elastic reshard "
                    "(mx.elastic); capture it in __init__ and rebuild "
                    "on reshard")
            return _Taint(host=base.host)
        if isinstance(node, ast.Subscript):
            if isinstance(node.value, ast.Attribute) and \
                    node.value.attr in ("shape", "axis_sizes") and \
                    _mesh_receiver(node.value.value):
                self._report(
                    "HB12", node,
                    "mesh axis size read (`mesh.shape[...]`) inside a "
                    "traced forward: the world size is baked into the "
                    "compiled program and goes silently stale after an "
                    "elastic reshard (mx.elastic); capture it in "
                    "__init__ and rebuild on reshard")
                self.ev(node.slice)
                return _NONE
            base = self.ev(node.value)
            idx = self.ev(node.slice)
            if base.tensor:
                if idx.host and not idx.tensor:
                    self._report(
                        "HB03", node,
                        "tensor sliced with a host-materialized value: "
                        "the slice bound is baked into the trace, so the "
                        "jit cache key becomes data-dependent")
                return _TENSOR
            if base.container:
                # args[1:] stays a container; args[0] is an element
                return _CONTAINER if isinstance(node.slice, ast.Slice) \
                    else _TENSOR
            return _Taint(host=base.host or idx.host)
        if isinstance(node, ast.Slice):
            return self.ev(node.lower) | self.ev(node.upper) | \
                self.ev(node.step)
        if isinstance(node, ast.BinOp):
            return self.ev(node.left) | self.ev(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.ev(node.operand)
        if isinstance(node, ast.Compare):
            t = self.ev(node.left)
            for cmp_ in node.comparators:
                t = t | self.ev(cmp_)
            if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
                return _NONE     # identity check: no bool() on the tracer
            return t
        if isinstance(node, ast.BoolOp):
            t = _NONE
            for v in node.values:
                t = t | self.ev(v)
            if t.tensor:
                self._report(
                    "HB01", node,
                    "`and`/`or` on an NDArray calls bool() on it: "
                    "TracerBoolConversionError under jax.jit; use "
                    "F.logical_and/F.logical_or or F.where")
            return t
        if isinstance(node, ast.IfExp):
            test = self.ev(node.test)
            if test.tensor or test.host:
                self._report(
                    "HB01", node,
                    "conditional expression branches on "
                    + ("an NDArray value" if test.tensor
                       else "a host-synced tensor value")
                    + "; use F.where to keep both branches in-graph")
            return self.ev(node.body) | self.ev(node.orelse)
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            t = _NONE
            for elt in node.elts:
                t = t | self.ev(elt)
            if t.tensor or t.container:
                # a python tuple OF tensors: truthiness is a len() check
                return _Taint(host=t.host, container=True)
            return t
        if isinstance(node, ast.Dict):
            t = _NONE
            for k, v in zip(node.keys, node.values):
                t = t | self.ev(k) | self.ev(v)
            if t.tensor or t.container:
                return _Taint(host=t.host, container=True)
            return t
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
            return self._ev_comp(node, node.elt)
        if isinstance(node, ast.DictComp):
            t1 = self._ev_comp(node, node.key)
            t2 = self._ev_comp(node, node.value)
            return t1 | t2
        if isinstance(node, ast.Call):
            return self._ev_call(node)
        if isinstance(node, ast.Starred):
            return self.ev(node.value)
        if isinstance(node, ast.JoinedStr):
            for v in node.values:
                self.ev(v)
            return _NONE
        if isinstance(node, ast.FormattedValue):
            return self.ev(node.value)
        if isinstance(node, ast.Lambda):
            return _NONE               # not called here; body unanalyzed
        if isinstance(node, ast.Await):
            return self.ev(node.value)
        # anything else: walk children conservatively, untainted result
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self.ev(child)
        return _NONE

    def _ev_comp(self, comp, *elts):
        saved = dict(self.env)
        try:
            for gen in comp.generators:
                self._assign(gen.target, self.ev(gen.iter))
                for cond in gen.ifs:
                    t = self.ev(cond)
                    if t.tensor:
                        self._report(
                            "HB01", cond,
                            "comprehension filter branches on an NDArray "
                            "value (bool() on a tracer)")
            t = _NONE
            for e in elts:
                t = t | self.ev(e)
            return t
        finally:
            self.env = saved

    # -- calls ----------------------------------------------------------

    def _check_op_args(self, node, op_desc):
        """HB03: host-materialized values fed into an op call."""
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            t = self.ev(arg)
            if t.host and not t.tensor:
                self._report(
                    "HB03", arg,
                    f"host-materialized value passed to {op_desc}: the "
                    "value is baked into the trace, so the jit cache key "
                    "becomes data-dependent (a retrace per distinct value)")

    def _arg_taints(self, node):
        pos = [self.ev(a) for a in node.args]
        kw = {k.arg: self.ev(k.value) for k in node.keywords
              if k.arg is not None}
        return pos, kw

    def _ev_call(self, node):  # noqa: C901
        func = node.func
        # ---- builtins --------------------------------------------------
        if isinstance(func, ast.Name):
            fname = func.id
            if fname in _SYNC_BUILTINS:
                t = _NONE
                for a in node.args:
                    t = t | self.ev(a)
                if t.tensor:
                    self._report(
                        "HB02", node,
                        f"`{fname}()` on an NDArray forces a device->host "
                        "sync (TracerArrayConversionError under jax.jit); "
                        "keep the value on device or derive it from .shape")
                    return _HOST
                return _Taint(host=t.host)
            if fname == "len" or fname in _STRUCTURE_BUILTINS:
                for a in node.args:
                    self.ev(a)
                return _NONE           # len/isinstance/...: static python
            if fname in ("tuple", "list", "set", "sorted", "reversed"):
                t = _NONE
                for a in node.args:
                    t = t | self.ev(a)
                return _CONTAINER if (t.tensor or t.container) else t
            if fname == "Parameter":
                self._report(
                    "HB04", node,
                    "Parameter created inside forward: it is re-allocated "
                    "every call and never registered for training; create "
                    "it in __init__")
                self._arg_taints(node)
                return _TENSOR
            if fname in _WORLD_SIZE_CALLS:
                self._report(
                    "HB12", node,
                    f"`{fname}()` inside a traced forward bakes the "
                    "world size into the compiled program — silently "
                    "stale after an elastic reshard (mx.elastic); "
                    "capture it in __init__ and rebuild on reshard")
                self._arg_taints(node)
                return _NONE
            if fname in self.index.rng_names:
                self._report(
                    "HB05", node,
                    f"host RNG `{fname}()` inside a traced forward is "
                    "drawn once at trace time and baked in as a constant; "
                    "use F.random.* (threads the per-call PRNG key)")
                self._arg_taints(node)
                return _HOST
            # same-module helper?
            helper = self.index.functions.get(fname)
            if helper is not None:
                pos, kw = self._arg_taints(node)
                return self.c.analyze_helper(
                    helper, None, fname, pos, kw, self.op_names,
                    self.depth + 1)
            # unknown plain call: tensor-in -> assume tensor-out
            pos, kw = self._arg_taints(node)
            t = _NONE
            for x in list(pos) + list(kw.values()):
                t = t | x
            return _TENSOR if t.tensor else _Taint(host=t.host)

        if not isinstance(func, ast.Attribute):
            # e.g. (lambda ...)(...) — evaluate args, untainted result
            self._arg_taints(node)
            return _NONE

        # ---- attribute calls ------------------------------------------
        attr = func.attr
        recv = func.value
        dotted = _dotted(func)

        # HB05: np.random.* / random.* draws
        if dotted:
            parts = dotted.split(".")
            root = parts[0]
            if (root == "random" and len(parts) == 2) or \
                    (root in _HOST_RNG_ROOTS and len(parts) >= 3
                     and parts[1] == "random"):
                self._report(
                    "HB05", node,
                    f"host RNG `{dotted}()` inside a traced forward is "
                    "drawn once at trace time and baked in as a constant; "
                    "use F.random.* (threads the per-call PRNG key)")
                self._arg_taints(node)
                return _HOST
            if dotted in _SIGNAL_CALLS:
                self._report(
                    "HB08", node,
                    f"`{dotted}()` inside a traced forward: host "
                    "process control runs once at trace time (never on "
                    "replay) and signal registration is main-thread-"
                    "only; install handlers at startup "
                    "(mx.checkpoint.PreemptionHandler), keep forwards "
                    "pure")
                self._arg_taints(node)
                return _NONE
            if parts[-1] in _WORLD_SIZE_CALLS or \
                    dotted in _DEVICE_LIST_CALLS or \
                    (parts[-1] == "devices" and _mesh_receiver(recv)):
                self._report(
                    "HB12", node,
                    f"`{dotted}()` inside a traced forward bakes the "
                    "world size into the compiled program — after an "
                    "elastic reshard (mx.elastic, dp changed mid-run) "
                    "every cached graph silently computes with the OLD "
                    "size; capture it in __init__ and rebuild on "
                    "reshard, or derive it in-graph (lax.psum over the "
                    "axis)")
                self._arg_taints(node)
                return _CONTAINER if parts[-1] in ("devices",
                                                   "local_devices") \
                    else _NONE

        recv_taint = self.ev(recv)

        # HB02: sync methods on tensors
        if attr in _SYNC_METHODS and (recv_taint.tensor or
                                      self._looks_tensorish(recv)):
            self._report(
                "HB02", node,
                f"`.{attr}()` forces a device->host sync inside a traced "
                "forward (blocks the pipeline; fails under jax.jit)")
            self._arg_taints(node)
            return _HOST

        # HB06: device transfers on tensors
        if attr in _TRANSFER_METHODS and recv_taint.tensor:
            self._report(
                "HB06", node,
                f"`.{attr}()` device transfer inside a hot forward: pins "
                "placement against the mesh and serializes the pipeline; "
                "move data before the forward")
            self._arg_taints(node)
            return _TENSOR

        # HB04: self.params.get(...) in forward
        if attr in ("get", "get_constant") and \
                isinstance(recv, ast.Attribute) and recv.attr == "params" \
                and isinstance(recv.value, ast.Name) \
                and recv.value.id == "self":
            self._report(
                "HB04", node,
                f"`self.params.{attr}(...)` inside forward allocates a "
                "parameter per call (baked into every trace, never "
                "trained); declare it in __init__")
            self._arg_taints(node)
            return _TENSOR

        # op-namespace calls: F.xxx(...), nd.xxx(...), F.random.xxx(...)
        ns_root = dotted.split(".")[0] if dotted else None
        if ns_root in self.op_names or ns_root in self.index.op_namespaces:
            if attr == "array":
                args_t = [self.ev(a) for a in node.args]
                if args_t and args_t[0].clean:
                    self._report(
                        "HB04", node,
                        f"`{dotted}([...])` creates a fresh constant "
                        "ndarray on every call — it is baked into every "
                        "trace; build it once in __init__ "
                        "(params.get_constant) or hoist it to module "
                        "level")
            self._check_op_args(node, f"op `{dotted}`")
            return _TENSOR

        # param.data() / param.grad() hand back the underlying NDArray
        if attr in ("data", "grad", "list_data") and not node.args \
                and not node.keywords and not recv_taint.host:
            return _TENSOR

        # method call on a tensor: x.reshape(...), x.sum() ...
        if recv_taint.tensor:
            self._check_op_args(node, f"tensor method `.{attr}`")
            return _TENSOR

        # self.helper(...) — same-class method or child-block call
        if isinstance(recv, ast.Name) and recv.id == "self":
            methods = self.index.methods_of(self.class_name)
            if attr in methods:
                owner, fn = methods[attr]
                pos, kw = self._arg_taints(node)
                return self.c.analyze_helper(
                    fn, owner, attr, pos, kw, self.op_names,
                    self.depth + 1)
            # child block: tensor-in -> tensor-out
            self._check_op_args(node, f"block `self.{attr}`")
            pos, kw = self._arg_taints(node)
            t = _NONE
            for x in list(pos) + list(kw.values()):
                t = t | x
            return _TENSOR if t.tensor else _NONE

        # anything else: evaluate args; propagate host taint
        pos, kw = self._arg_taints(node)
        t = recv_taint
        for x in list(pos) + list(kw.values()):
            t = t | x
        return _TENSOR if t.tensor else _Taint(host=t.host)

    def _looks_tensorish(self, node):
        """`.asnumpy()` on an untracked receiver (e.g. an attribute or a
        fresh call result) still syncs; only suppress for names we know
        are plain Python."""
        if isinstance(node, ast.Name):
            return False               # known-untainted local
        if isinstance(node, ast.Constant):
            return False
        if isinstance(node, ast.Call):
            return self.ev(node).tensor
        return False

    # -- statements ------------------------------------------------------

    def visit_Assign(self, node):
        taint = self.ev(node.value)
        for target in node.targets:
            # evaluate subscript/attribute targets for their own hits
            if isinstance(target, (ast.Subscript, ast.Attribute)):
                self.ev(target)
            self._assign(target, taint)

    def visit_AnnAssign(self, node):
        if node.value is not None:
            self._assign(node.target, self.ev(node.value))

    def visit_AugAssign(self, node):
        taint = self.ev(node.value)
        if isinstance(node.target, ast.Name):
            taint = taint | self._lookup(node.target.id)
        self._assign(node.target, taint)

    def _check_branch(self, test, kind):
        t = self.ev(test)
        if t.tensor:
            self._report(
                "HB01", test,
                f"Python `{kind}` on an NDArray value: bool() on a "
                "tracer raises under jax.jit; branch on static shapes or "
                "use F.where to keep both sides in-graph")
        elif t.host:
            self._report(
                "HB01", test,
                f"Python `{kind}` on a host-synced tensor value: the "
                "branch taken is baked into the trace, so the compiled "
                "program silently depends on this call's data")

    def visit_If(self, node):
        self._check_branch(node.test, "if")
        saved = dict(self.env)
        for stmt in node.body:
            self.visit(stmt)
        env_body = self.env
        self.env = dict(saved)
        for stmt in node.orelse:
            self.visit(stmt)
        # merge: a name tainted on either path stays tainted
        for k, v in env_body.items():
            self.env[k] = self.env.get(k, _NONE) | v

    def visit_While(self, node):
        self._check_branch(node.test, "while")
        for stmt in node.body + node.orelse:
            self.visit(stmt)

    def visit_Assert(self, node):
        self._check_branch(node.test, "assert")
        if node.msg is not None:
            self.ev(node.msg)

    def visit_For(self, node):
        self._assign_loop_target(node.target, node.iter)
        for stmt in node.body + node.orelse:
            self.visit(stmt)

    def _assign_loop_target(self, target, iter_node):
        """Element-wise taint for the `for i, (a, b) in enumerate(zip(..))`
        idiom: the enumerate counter is a plain int, and each zip slot
        carries only its own iterable's taint."""
        if isinstance(iter_node, ast.Call) and \
                isinstance(iter_node.func, ast.Name) and \
                isinstance(target, (ast.Tuple, ast.List)):
            fname = iter_node.func.id
            if fname == "enumerate" and len(target.elts) == 2 \
                    and iter_node.args:
                self._assign(target.elts[0], _NONE)
                self._assign_loop_target(target.elts[1], iter_node.args[0])
                return
            if fname == "zip" and len(target.elts) == len(iter_node.args):
                for elt, arg in zip(target.elts, iter_node.args):
                    t = self.ev(arg)
                    self._assign(elt, _TENSOR if t.container else t)
                return
        t = self.ev(iter_node)
        # iterating a container of tensors yields tensors
        self._assign(target, _TENSOR if t.container else t)

    def visit_Return(self, node):
        if node.value is not None:
            self.return_taint = self.return_taint | self.ev(node.value)

    def visit_Expr(self, node):
        self.ev(node.value)

    def visit_With(self, node):
        for item in node.items:
            self.ev(item.context_expr)
            if item.optional_vars is not None:
                self._assign(item.optional_vars, _NONE)
        for stmt in node.body:
            self.visit(stmt)

    def visit_Try(self, node):
        for stmt in (node.body + node.orelse + node.finalbody):
            self.visit(stmt)
        for handler in node.handlers:
            for stmt in handler.body:
                self.visit(stmt)

    def visit_FunctionDef(self, node):
        # closures defined inside forward usually run under the same
        # trace (branch fns, scan bodies): analyze the body in the
        # current environment
        for stmt in node.body:
            self.visit(stmt)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Raise(self, node):
        if node.exc is not None:
            self.ev(node.exc)

    def visit_Delete(self, node):
        for t in node.targets:
            if isinstance(t, ast.Name):
                self.env.pop(t.id, None)

    def generic_visit(self, node):
        # fall through for statements not handled above
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.stmt):
                self.visit(child)
            elif isinstance(child, ast.expr):
                self.ev(child)


_MAX_HELPER_DEPTH = 8

# -- HB07: eager collectives inside Python loops (module-wide pass) -----

# kvstore-style data-plane methods; receiver name must look like a
# kvstore binding (`kv`, `kvstore`, `self._kvstore`, ...) to fire
_EAGER_COLLECTIVE_METHODS = {"push", "pull", "pushpull", "broadcast"}


def _is_eager_collective(node):
    func = node.func
    if isinstance(func, ast.Name):
        return func.id == "process_allgather"
    if not isinstance(func, ast.Attribute):
        return False
    if func.attr == "process_allgather":
        return True
    if func.attr in _EAGER_COLLECTIVE_METHODS:
        dotted = _dotted(func.value)
        return bool(dotted) and any("kv" in part.lower()
                                    for part in dotted.split("."))
    return False


class _LoopCollectiveScanner(ast.NodeVisitor):
    """HB07 walks EVERY function in the module (training scripts and
    helpers, not just HybridBlock forwards): an eager collective
    dispatched once per loop iteration pays one wire round per key —
    the SURVEY §7 bandwidth cliff the batched/bucketed APIs exist to
    avoid.  Comprehensions are exempt only because the offending
    real-world shape is the per-parameter for-loop."""

    def __init__(self, collector, path):
        self.c = collector
        self.path = path
        self.loop_depth = 0
        self.func_stack = ["<module>"]

    def _loop(self, node):
        self.loop_depth += 1
        try:
            self.generic_visit(node)
        finally:
            self.loop_depth -= 1

    visit_For = visit_While = visit_AsyncFor = _loop

    def visit_FunctionDef(self, node):
        self.func_stack.append(node.name)
        try:
            self.generic_visit(node)
        finally:
            self.func_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Call(self, node):
        if self.loop_depth > 0 and _is_eager_collective(node):
            name = node.func.attr if isinstance(node.func, ast.Attribute) \
                else node.func.id
            self.c.add(Violation(
                rule="HB07", path=self.path, line=node.lineno,
                col=node.col_offset,
                message=f"eager collective `{name}` inside a Python "
                        "loop: one dispatch + wire round per iteration "
                        "(O(n_keys) bandwidth cliff); batch the keys "
                        "into one call (the store buckets them) or move "
                        "the collective in-graph",
                block="", func=self.func_stack[-1]))
        self.generic_visit(node)


# -- HB09: host sync between backward() and trainer.step() --------------

# method calls that force a host round-trip mid-training-loop
_HB09_SYNC_METHODS = _SYNC_METHODS | {"wait_to_read", "waitall"}


class _BackwardStepScanner(ast.NodeVisitor):
    """HB09: within any Python loop (the training loop), a host-sync
    call issued AFTER ``backward()`` but BEFORE the matching
    ``.step(...)`` serializes the step: the sync drains the whole
    backward, so overlapped per-bucket gradient communication
    (parallel.OverlapScheduler grad-ready hooks) and the async step
    dispatch both stall behind it.  Scans every loop in the module;
    nested scans dedup through the collector."""

    def __init__(self, collector, path):
        self.c = collector
        self.path = path
        self.func_stack = ["<module>"]

    def visit_FunctionDef(self, node):
        self.func_stack.append(node.name)
        try:
            self.generic_visit(node)
        finally:
            self.func_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def _scan_loop(self, node):
        calls = []
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call) and \
                    isinstance(sub.func, ast.Attribute):
                calls.append(sub)
        calls.sort(key=lambda c: (c.lineno, c.col_offset))
        armed = False
        for call in calls:
            attr = call.func.attr
            if attr == "backward":
                armed = True
            elif attr == "step" and armed:
                armed = False
            elif armed and attr in _HB09_SYNC_METHODS:
                self.c.add(Violation(
                    rule="HB09", path=self.path, line=call.lineno,
                    col=call.col_offset,
                    message=f"host sync `.{attr}()` between backward() "
                            "and trainer.step() in a training loop: the "
                            "sync drains the backward before step can "
                            "dispatch, serializing the step and "
                            "defeating backward-overlapped gradient "
                            "communication; move the read after step()",
                    block="", func=self.func_stack[-1]))
        self.generic_visit(node)

    visit_For = visit_While = visit_AsyncFor = _scan_loop


# -- HB10: per-step host pulls in a compiled multi-step loop -------------

_HB10_SYNC_METHODS = _SYNC_METHODS | {"wait_to_read", "waitall"}


class _MultiStepPullScanner(ast.NodeVisitor):
    """HB10: a loop that calls ``step_multi`` runs the compiled
    multi-step path — K steps, ONE dispatch, ONE intended host sync at
    the scan boundary.  A host pull (``.item()``/``.asnumpy()``/... or
    ``float()`` on a value) inside a loop NESTED in that window loop
    runs per scanned step: K host round-trips per dispatch, the exact
    tax the scan removes.  A single boundary pull directly in the
    window loop stays clean.  Multiply-nested loops dedup through the
    collector."""

    def __init__(self, collector, path):
        self.c = collector
        self.path = path
        self.func_stack = ["<module>"]

    def visit_FunctionDef(self, node):
        self.func_stack.append(node.name)
        try:
            self.generic_visit(node)
        finally:
            self.func_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    @staticmethod
    def _calls_step_multi(loop):
        for sub in ast.walk(loop):
            if isinstance(sub, ast.Call) and \
                    isinstance(sub.func, ast.Attribute) and \
                    sub.func.attr == "step_multi":
                return True
        return False

    def _flag(self, call, what):
        self.c.add(Violation(
            rule="HB10", path=self.path, line=call.lineno,
            col=call.col_offset,
            message=f"per-step host pull {what} inside a nested loop of "
                    "a compiled multi-step training loop (step_multi): "
                    "K host syncs per dispatch defeat the one-sync-per-"
                    "window scan; read the (K,) losses once at the scan "
                    "boundary and slice on the host",
            block="", func=self.func_stack[-1]))

    def _scan_window_loop(self, node):
        if self._calls_step_multi(node):
            inner_loops = [sub for sub in ast.walk(node)
                           if isinstance(sub, (ast.For, ast.While,
                                               ast.AsyncFor))
                           and sub is not node]
            for loop in inner_loops:
                for sub in ast.walk(loop):
                    if not isinstance(sub, ast.Call):
                        continue
                    f = sub.func
                    if isinstance(f, ast.Attribute) and \
                            f.attr in _HB10_SYNC_METHODS:
                        self._flag(sub, f"`.{f.attr}()`")
                    elif isinstance(f, ast.Name) and f.id == "float" \
                            and sub.args:
                        self._flag(sub, "`float()`")
        self.generic_visit(node)

    visit_For = visit_While = visit_AsyncFor = _scan_window_loop


# -- HB11: per-token host sync in a decode/generation loop ---------------

_HB11_SYNC_METHODS = _SYNC_METHODS | {"wait_to_read", "waitall"}
# callee names that mark a loop as an autoregressive decode loop: the
# per-token step call of samplers (self._decoder), serving engines
# (engine.decode_step) and hand-rolled generation loops.  Bare "decode"
# is deliberately absent — it collides with bytes.decode()
_HB11_DECODE_CALLEES = {"decoder", "_decoder", "decode_step",
                        "generate_step", "decode_token"}


class _DecodeLoopPullScanner(ast.NodeVisitor):
    """HB11: a loop that calls a decoder step runs ONE compiled step per
    token; a host pull (``.item()``/``.asnumpy()``/``float()``/...)
    in that loop pays a device->host round-trip PER TOKEN, serializing
    the whole serving batch behind it — the serving twin of HB10.  The
    compiled step should sample in-graph and hand back the token; reads
    of accumulated sequences belong after the loop (or at amortized
    chunk boundaries — a periodic ``bool(all(done))`` early-exit check
    is not flagged)."""

    def __init__(self, collector, path):
        self.c = collector
        self.path = path
        self.func_stack = ["<module>"]

    def visit_FunctionDef(self, node):
        self.func_stack.append(node.name)
        try:
            self.generic_visit(node)
        finally:
            self.func_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    @staticmethod
    def _calls_decoder(loop):
        for sub in ast.walk(loop):
            if isinstance(sub, ast.Call):
                f = sub.func
                name = f.attr if isinstance(f, ast.Attribute) else \
                    f.id if isinstance(f, ast.Name) else None
                if name in _HB11_DECODE_CALLEES:
                    return True
        return False

    def _flag(self, call, what):
        self.c.add(Violation(
            rule="HB11", path=self.path, line=call.lineno,
            col=call.col_offset,
            message=f"per-token host sync {what} inside a decode/"
                    "generation loop: one device->host round-trip per "
                    "token serializes the serving batch; sample in the "
                    "compiled step and read sequences once after the "
                    "loop", block="", func=self.func_stack[-1]))

    def _scan_decode_loop(self, node):
        if self._calls_decoder(node):
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Call):
                    continue
                f = sub.func
                if isinstance(f, ast.Attribute) and \
                        f.attr in _HB11_SYNC_METHODS:
                    self._flag(sub, f"`.{f.attr}()`")
                elif isinstance(f, ast.Name) and f.id == "float" \
                        and sub.args:
                    self._flag(sub, "`float()`")
        self.generic_visit(node)

    visit_For = visit_While = visit_AsyncFor = _scan_decode_loop


# -- HB13: wall-clock timing of device code without synchronization -----

# clock reads whose subtraction forms a wall-clock delta
_TIME_CALLS = {"time.time", "time.perf_counter", "time.monotonic",
               "perf_counter", "monotonic"}
# calls that drain the device inside the timed region (make the delta
# measure compute, not dispatch)
_HB13_SYNC_METHODS = {"block_until_ready", "wait_to_read", "waitall",
                      "asnumpy", "asscalar", "item", "tolist"}
# call forms that PRODUCE a compiled callable
_JIT_FACTORIES = {"jax.jit", "jit", "jax.pmap", "pmap"}


def _is_time_call(node):
    return isinstance(node, ast.Call) and _dotted(node.func) in _TIME_CALLS


def _is_jit_factory(node):
    """``jax.jit(...)`` / ``jit(...)`` / ``...lower(args).compile()`` —
    the value bound is a compiled callable whose invocation dispatches
    async device work."""
    if not isinstance(node, ast.Call):
        return False
    if _dotted(node.func) in _JIT_FACTORIES:
        return True
    return isinstance(node.func, ast.Attribute) and \
        node.func.attr == "compile"


class _UnsyncedTimingScanner(ast.NodeVisitor):
    """HB13: ``t0 = time.perf_counter(); y = f(x); dt =
    time.perf_counter() - t0`` where ``f`` is jitted/compiled and no
    ``block_until_ready``/``wait_to_read``/host read happens inside the
    timed region.  jax dispatch is ASYNC — the call returns the moment
    the program is enqueued — so the delta measures host dispatch, not
    device compute: the benchmark-lies-by-100x failure mode ISSUE 9's
    telemetry timings must not reintroduce.  Scans every function (and
    the module body); a jitted callable is one bound IN THAT SCOPE from
    a jit factory (``jax.jit``/``jit``/``.compile()``), so eager helper
    calls and host-only code never false-positive."""

    def __init__(self, collector, path):
        self.c = collector
        self.path = path
        self.func_stack = ["<module>"]

    def visit_Module(self, node):
        self._scan_scope(node)
        self.generic_visit(node)

    def visit_FunctionDef(self, node):
        self.func_stack.append(node.name)
        try:
            self._scan_scope(node)
            self.generic_visit(node)
        finally:
            self.func_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    @staticmethod
    def _walk_scope(scope):
        """Walk ``scope``'s body WITHOUT descending into nested
        function definitions — each function is its own timed scope
        (an outer clock variable must not pair with an inner
        function's delta)."""
        stack = list(ast.iter_child_nodes(scope))
        while stack:
            n = stack.pop()
            yield n
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                continue
            stack.extend(ast.iter_child_nodes(n))

    def _scan_scope(self, scope):
        # pass 1: names bound to compiled callables + clock variables
        jitted, timevars = set(), {}
        for sub in self._walk_scope(scope):
            if isinstance(sub, ast.Assign):
                if _is_jit_factory(sub.value):
                    for t in sub.targets:
                        if isinstance(t, ast.Name):
                            jitted.add(t.id)
                elif _is_time_call(sub.value):
                    for t in sub.targets:
                        if isinstance(t, ast.Name):
                            timevars.setdefault(t.id, []).append(
                                sub.lineno)
        if not timevars:
            return
        # pass 2: dispatches, syncs, and clock deltas (by line)
        jcalls, syncs, deltas = [], [], []
        for sub in self._walk_scope(scope):
            if isinstance(sub, ast.Call):
                f = sub.func
                if (isinstance(f, ast.Attribute)
                        and f.attr in _HB13_SYNC_METHODS) or \
                        (isinstance(f, ast.Name)
                         and f.id in _HB13_SYNC_METHODS):
                    syncs.append(sub.lineno)
                elif (isinstance(f, ast.Name) and f.id in jitted) or \
                        _is_jit_factory(f):
                    jcalls.append(sub.lineno)
            elif isinstance(sub, ast.BinOp) and \
                    isinstance(sub.op, ast.Sub) and \
                    isinstance(sub.right, ast.Name) and \
                    sub.right.id in timevars:
                if _is_time_call(sub.left):
                    deltas.append((sub.lineno, sub.right.id, sub.lineno))
                elif isinstance(sub.left, ast.Name) and \
                        sub.left.id in timevars:
                    # t1 - t0: the region closes at t1's assignment
                    ends = [l for l in timevars[sub.left.id]
                            if l <= sub.lineno]
                    if ends:
                        deltas.append((sub.lineno, sub.right.id,
                                       max(ends)))
        if not jcalls:
            return
        for lineno, t0_name, end in deltas:
            starts = [l for l in timevars[t0_name] if l <= lineno]
            if not starts:
                continue
            start = max(s for s in starts if s <= end) \
                if any(s <= end for s in starts) else None
            if start is None or end <= start:
                continue
            if any(start <= l <= end for l in jcalls) and \
                    not any(start <= l <= end for l in syncs):
                self.c.add(Violation(
                    rule="HB13", path=self.path, line=lineno, col=0,
                    message="wall-clock delta around a jitted/compiled "
                            "call with no block_until_ready/"
                            "wait_to_read/host read in the timed "
                            "region: jax dispatches asynchronously, so "
                            "this measures DISPATCH, not device "
                            "compute; sync on the result before "
                            "reading the clock (or name the metric "
                            "dispatch_ms)",
                    block="", func=self.func_stack[-1]))


# -- HB17: hardcoded mesh-axis literal outside parallel/mesh.py ----------

_HB17_AXIS_NAMES = {"dp", "tp", "pp"}
_HB17_SPEC_CALLEES = {"P", "PartitionSpec"}
_HB17_COLLECTIVE_CALLEES = {
    "psum", "pmean", "pmax", "pmin", "all_gather", "psum_scatter",
    "all_to_all", "ppermute", "pshuffle", "axis_index", "pcast",
    "reduce_scatter_bucket"}


class _MeshAxisLiteralScanner(ast.NodeVisitor):
    """HB17: a hardcoded ``"dp"``/``"tp"``/``"pp"`` string inside a
    PartitionSpec or collective call, or a literal index into a mesh's
    ``.shape``/``.axis_names`` (``mesh.shape["dp"]`` / ``mesh.shape[0]``)
    anywhere outside ``parallel/mesh.py``.  The axis names are
    MeshConfig's contract (ISSUE 11): literal copies silently break when
    the mesh layout changes (an elastic reshard, a 2x2x2 config, a
    renamed axis) — import ``AXIS_DP``/``AXIS_TP``/``AXIS_PP`` from
    ``parallel.mesh`` or go through the MeshConfig accessors instead."""

    def __init__(self, collector, path):
        self.c = collector
        self.path = path
        self.func_stack = ["<module>"]
        norm = path.replace("\\", "/")
        self.exempt = norm.endswith("parallel/mesh.py")

    def visit_FunctionDef(self, node):
        self.func_stack.append(node.name)
        try:
            self.generic_visit(node)
        finally:
            self.func_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def _add(self, node, what):
        self.c.add(Violation(
            rule="HB17", path=self.path, line=node.lineno,
            col=getattr(node, "col_offset", 0),
            message=f"hardcoded mesh-axis {what}: the dp/tp/pp axis "
                    "names are MeshConfig's contract (parallel/mesh.py)"
                    " — import AXIS_DP/AXIS_TP/AXIS_PP or use the "
                    "MeshConfig accessors so a changed mesh layout "
                    "cannot silently strand this call site",
            block="", func=self.func_stack[-1]))

    def visit_Call(self, node):
        if not self.exempt:
            f = node.func
            name = f.attr if isinstance(f, ast.Attribute) \
                else getattr(f, "id", None)
            if name in _HB17_SPEC_CALLEES or \
                    name in _HB17_COLLECTIVE_CALLEES:
                for sub in list(node.args) + \
                        [kw.value for kw in node.keywords]:
                    for n in ast.walk(sub):
                        if isinstance(n, ast.Constant) and \
                                n.value in _HB17_AXIS_NAMES:
                            self._add(n, f'literal "{n.value}" in '
                                         f"`{name}(...)`")
        self.generic_visit(node)

    def visit_Subscript(self, node):
        if not self.exempt:
            v = node.value
            if isinstance(v, ast.Attribute) and \
                    v.attr in ("shape", "axis_names", "axis_sizes"):
                base = v.value
                base_name = base.attr if isinstance(base, ast.Attribute) \
                    else getattr(base, "id", "")
                if "mesh" in str(base_name).lower():
                    sl = node.slice
                    if isinstance(sl, ast.Constant) and (
                            sl.value in _HB17_AXIS_NAMES or
                            isinstance(sl.value, int)):
                        self._add(
                            sl, f"index `{base_name}.{v.attr}"
                                f"[{sl.value!r}]`")
        self.generic_visit(node)


_HB21_LOWP_ATTRS = frozenset({
    "int8", "bfloat16",
    "float8_e4m3fn", "float8_e5m2", "float8_e4m3", "float8_e4m3fnuz",
    "float8_e5m2fnuz",
})
_HB21_LOWP_STRINGS = frozenset(_HB21_LOWP_ATTRS)
# the scaled-cast helpers live here; casts inside them ARE the pattern
_HB21_EXEMPT_SUFFIXES = ("ops/quant_matmul.py", "ops/quant_kv.py")


class _LowPrecisionCastScanner(ast.NodeVisitor):
    """HB21: a raw ``.astype(int8/fp8/bf16)`` (or
    ``lax.convert_element_type`` to one of those dtypes) anywhere
    outside the ``ops/quant_*`` scaled helpers.  Narrow formats clip:
    int8 saturates at ±127 and fp8-e4m3 at ±448, so a cast whose
    operand wasn't divided by an amax-derived scale silently flushes
    the tensor's tails — loss spikes on TPU that CPU tier-1 (running
    the same cast on the same small values) never sees.  Route the
    cast through ``ops.quant_matmul`` (``quantize_rtn_int8`` /
    ``quantize_sr_int8`` / ``quant_matmul``) or ``ops.quant_kv``
    (``kv_quantize_fp8`` / ``kv_cast``) so a scale always rides with
    the narrowed bits."""

    def __init__(self, collector, path):
        self.c = collector
        self.path = path
        self.func_stack = ["<module>"]
        norm = path.replace("\\", "/")
        self.exempt = norm.endswith(_HB21_EXEMPT_SUFFIXES)

    def visit_FunctionDef(self, node):
        self.func_stack.append(node.name)
        try:
            self.generic_visit(node)
        finally:
            self.func_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    @staticmethod
    def _lowp_name(expr):
        """The low-precision dtype a cast-argument expression names, or
        None.  Matches ``jnp.int8``-style attributes, bare ``int8``
        names, and ``"int8"``-style dtype strings — anywhere inside the
        argument (covers conditional dtype picks)."""
        for n in ast.walk(expr):
            if isinstance(n, ast.Attribute) and n.attr in _HB21_LOWP_ATTRS:
                return n.attr
            if isinstance(n, ast.Name) and n.id in _HB21_LOWP_ATTRS:
                return n.id
            if isinstance(n, ast.Constant) and \
                    isinstance(n.value, str) and \
                    n.value in _HB21_LOWP_STRINGS:
                return n.value
        return None

    def _add(self, node, dtype_name, callee):
        self.c.add(Violation(
            rule="HB21", path=self.path, line=node.lineno,
            col=getattr(node, "col_offset", 0),
            message=f"raw `{callee}` cast to {dtype_name}: narrow "
                    "formats clip (int8 ±127, fp8-e4m3 ±448), so an "
                    "unscaled cast silently flushes the tensor's tails"
                    " — use the scaled helpers in ops.quant_matmul "
                    "(quantize_rtn_int8 / quantize_sr_int8) or "
                    "ops.quant_kv (kv_quantize_fp8 / kv_cast) so an "
                    "amax scale rides with the narrowed bits",
            block="", func=self.func_stack[-1]))

    def visit_Call(self, node):
        if not self.exempt:
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr == "astype" \
                    and node.args:
                dt = self._lowp_name(node.args[0])
                if dt is not None:
                    self._add(node, dt, "astype")
            elif isinstance(f, ast.Attribute) and \
                    f.attr == "convert_element_type" and \
                    len(node.args) >= 2:
                dt = self._lowp_name(node.args[1])
                if dt is not None:
                    self._add(node, dt, "lax.convert_element_type")
        self.generic_visit(node)


class _Collector:
    def __init__(self, index, path):
        self.index = index
        self.path = path
        self.violations = []
        self._seen = set()
        self._helper_memo = set()

    def add(self, v):
        key = (v.rule, v.path, v.line, v.col, v.message)
        if key not in self._seen:
            self._seen.add(key)
            self.violations.append(v)

    def _seed_env(self, fn, class_name, pos_taints, kw_taints,
                  entry_all_tensor):
        """Bind call-site taints (or all-tensor for entry points) to the
        function's parameters."""
        env = {}
        args = fn.args
        params = [a.arg for a in args.posonlyargs + args.args]
        skip = 0
        if class_name is not None and params and params[0] == "self":
            skip = 1
        if fn.name == "hybrid_forward" and len(params) > skip:
            skip += 1                 # the F op-namespace argument
        # params with a non-None constant default (causal=False, axis=1)
        # are static config flags, not tensors
        n_def = len(args.defaults)
        static_flags = set()
        if n_def:
            for a, d in zip(params[-n_def:], args.defaults):
                if isinstance(d, ast.Constant) and d.value is not None:
                    static_flags.add(a)
        for i, name in enumerate(params[skip:]):
            if entry_all_tensor:
                env[name] = _NONE if name in static_flags else _TENSOR
            elif i < len(pos_taints):
                env[name] = pos_taints[i]
            elif name in kw_taints:
                env[name] = kw_taints[name]
            else:
                env[name] = _NONE
        for a, d in zip(args.kwonlyargs, args.kw_defaults):
            if isinstance(d, ast.Constant) and d.value is not None:
                static_flags.add(a.arg)
        for a in args.kwonlyargs:
            if entry_all_tensor:
                env[a.arg] = _NONE if a.arg in static_flags else _TENSOR
            else:
                env[a.arg] = kw_taints.get(a.arg, _NONE)
        if args.vararg is not None:
            # *args is a python TUPLE of tensors: `if args:` is a safe
            # len() check, while iteration/indexing yields tensors
            if entry_all_tensor:
                env[args.vararg.arg] = _CONTAINER
            else:
                extra = pos_taints[len(params) - skip:]
                t = _NONE
                for x in extra:
                    t = t | x
                env[args.vararg.arg] = _CONTAINER \
                    if (t.tensor or t.container) else t
        if args.kwarg is not None:
            env[args.kwarg.arg] = _CONTAINER if entry_all_tensor else _NONE
        return env

    def _op_names_for(self, fn, class_name):
        ops = set()
        args = fn.args
        params = [a.arg for a in args.posonlyargs + args.args]
        if fn.name == "hybrid_forward":
            idx = 1 if (class_name is not None and params
                        and params[0] == "self") else 0
            if len(params) > idx:
                ops.add(params[idx])   # whatever the F arg is called
        return ops

    def analyze_entry(self, fn, class_name):
        env = self._seed_env(fn, class_name, [], {}, entry_all_tensor=True)
        ops = self._op_names_for(fn, class_name)
        an = _FunctionAnalyzer(self, self.index, self.path, class_name or "",
                               fn.name, env, ops, depth=0)
        for stmt in fn.body:
            an.visit(stmt)
        return an.return_taint

    def analyze_helper(self, fn, class_name, name, pos_taints, kw_taints,
                       op_names, depth):
        if depth > _MAX_HELPER_DEPTH:
            return _TENSOR
        sig = (id(fn),
               tuple((t.tensor, t.host) for t in pos_taints),
               tuple(sorted((k, t.tensor, t.host)
                            for k, t in kw_taints.items())))
        tensor_out = any(t.tensor for t in pos_taints) or \
            any(t.tensor for t in kw_taints.values())
        if sig in self._helper_memo:
            # already analyzed with this taint signature; approximate the
            # return taint without re-reporting
            return _TENSOR if tensor_out else _NONE
        self._helper_memo.add(sig)
        env = self._seed_env(fn, class_name, pos_taints, kw_taints,
                             entry_all_tensor=False)
        ops = set(op_names) | self._op_names_for(fn, class_name)
        an = _FunctionAnalyzer(self, self.index, self.path,
                               class_name or "", name, env, ops, depth)
        for stmt in fn.body:
            an.visit(stmt)
        return an.return_taint


def lint_source(source, path="<string>", only_classes=None, rules=None):
    """Lint python source; returns a list of Violations (suppressions
    applied). ``only_classes`` restricts reporting to those class names;
    ``rules`` restricts to a subset of rule IDs."""
    tree = ast.parse(source, filename=path)
    src_lines = source.splitlines()
    index = _ModuleIndex(tree)
    collector = _Collector(index, path)
    for cname in index.classes:
        if only_classes is not None and cname not in only_classes:
            continue
        if not index.is_blocky(cname):
            continue
        methods = index.methods_of(cname)
        for entry in ("hybrid_forward", "forward"):
            owner_fn = methods.get(entry)
            if owner_fn is None:
                continue
            owner, fn = owner_fn
            if owner != cname:
                continue              # inherited: reported on the owner
            collector.analyze_entry(fn, cname)
    if only_classes is None:
        # HB07/HB09/HB10/HB11/HB13 are module-wide (any function), not
        # forward-scoped
        _LoopCollectiveScanner(collector, path).visit(tree)
        _BackwardStepScanner(collector, path).visit(tree)
        _MultiStepPullScanner(collector, path).visit(tree)
        _DecodeLoopPullScanner(collector, path).visit(tree)
        _UnsyncedTimingScanner(collector, path).visit(tree)
        _MeshAxisLiteralScanner(collector, path).visit(tree)
        # HB21: unscaled low-precision casts (ISSUE 20)
        _LowPrecisionCastScanner(collector, path).visit(tree)
        # HB14/HB15/HB16: the interprocedural concurrency pass (per-class
        # lock + field-access + call-graph model; concurrency.py)
        run_concurrency_pass(collector, tree, path, src_lines)
        # HB18/HB19/HB20: the intraprocedural dataflow pass (per-function
        # def-use chains over names + self.* paths; dataflow.py)
        run_dataflow_pass(collector, tree, path)
    suppressed, _unknown = parse_suppressions(source)
    out = []
    for v in sorted(collector.violations,
                    key=lambda v: (v.line, v.col, v.rule)):
        if rules is not None and v.rule not in rules:
            continue
        if is_suppressed(suppressed, v.line, v.rule):
            continue
        text = src_lines[v.line - 1].strip() if v.line <= len(src_lines) \
            else ""
        out.append(Violation(rule=v.rule, path=v.path, line=v.line,
                             col=v.col, message=v.message, block=v.block,
                             func=v.func, source_line=text))
    return out


def lint_file(path, rules=None):
    with open(path, encoding="utf-8") as f:
        source = f.read()
    return lint_source(source, path=path, rules=rules)
