"""Process-wide metrics registry: counters, gauges, fixed-edge histograms.

Design constraints (ISSUE 9 tentpole):

- **Deterministic aggregation**: histograms carry FIXED bucket edges
  chosen at creation (default :data:`DEFAULT_MS_EDGES`), so merging
  snapshots across workers — or comparing two runs — is exact bucket
  arithmetic, never a re-binning estimate.
- **Injectable clock**: the registry stamps snapshots through a ``now``
  callable (``testing.faults.FakeClock`` in tests — the PR 4 PSServer
  ``_now`` discipline).  Durations themselves are measured by callers
  with ``time.perf_counter`` and *observed* into histograms.
- **Zero overhead when disabled**: the package front end hands back
  :data:`NULL_METRIC` (one shared instance whose methods are ``pass``)
  instead of touching this module at all.
- **Thread-safe**: the PS serve threads, prefetch workers, checkpoint
  writer and the training thread all publish here.
"""
from __future__ import annotations

import bisect
import time

from ..base import MXNetError
from ..lint import racecheck as _racecheck

__all__ = ["MetricsRegistry", "Counter", "Gauge", "Histogram",
           "NULL_METRIC", "DEFAULT_MS_EDGES"]

#: default histogram edges, in milliseconds: spans sub-ms dispatch
#: through multi-second reshard/checkpoint times.  FIXED so cross-worker
#: aggregation is deterministic bucket-wise addition.
DEFAULT_MS_EDGES = (0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0,
                    100.0, 250.0, 500.0, 1000.0, 2500.0, 5000.0, 10000.0)


class _NullMetric:
    """The disabled-mode metric: every mutator is a no-op; shared as ONE
    module-level instance so the disabled path allocates nothing."""

    __slots__ = ()

    def inc(self, n=1):
        pass

    def set(self, v):
        pass

    def observe(self, v):
        pass

    @property
    def value(self):
        return None


NULL_METRIC = _NullMetric()


class Counter:
    """Monotonically increasing count (events, bytes, calls)."""

    __slots__ = ("name", "_v", "_lock")

    def __init__(self, name):
        self.name = name
        self._v = 0
        self._lock = _racecheck.make_lock("telemetry.Counter._lock")

    def inc(self, n=1):
        with self._lock:
            self._v += n

    @property
    def value(self):
        with self._lock:
            return self._v


class Gauge:
    """Last-write-wins instantaneous value (queue depth, epoch, ms)."""

    __slots__ = ("name", "_v", "_lock")

    def __init__(self, name):
        self.name = name
        self._v = None
        self._lock = _racecheck.make_lock("telemetry.Gauge._lock")

    def set(self, v):
        with self._lock:
            self._v = v

    @property
    def value(self):
        with self._lock:
            return self._v


class Histogram:
    """Fixed-edge histogram: ``counts[i]`` counts observations ``<=
    edges[i]`` (last slot: overflow), plus running sum/count/min/max.
    Edges are fixed at creation — deterministic aggregation is the
    contract."""

    __slots__ = ("name", "edges", "_counts", "_sum", "_count", "_min",
                 "_max", "_lock")

    def __init__(self, name, edges=None):
        self.name = name
        edges = tuple(float(e) for e in
                      (DEFAULT_MS_EDGES if edges is None else edges))
        if not edges or list(edges) != sorted(set(edges)):
            raise MXNetError(
                f"histogram {name!r}: edges must be a strictly "
                f"increasing non-empty sequence, got {edges!r}")
        self.edges = edges
        self._counts = [0] * (len(edges) + 1)
        self._sum = 0.0
        self._count = 0
        self._min = None
        self._max = None
        self._lock = _racecheck.make_lock("telemetry.Histogram._lock")

    def observe(self, v):
        v = float(v)
        i = bisect.bisect_left(self.edges, v)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1
            if self._min is None or v < self._min:
                self._min = v
            if self._max is None or v > self._max:
                self._max = v

    @property
    def value(self):
        """Mean observation (the scalar thin-reader view); None before
        the first observation."""
        with self._lock:
            return self._sum / self._count if self._count else None

    def state(self):
        with self._lock:
            return {"edges": list(self.edges),
                    "counts": list(self._counts),
                    "sum": self._sum, "count": self._count,
                    "min": self._min, "max": self._max}


class MetricsRegistry:
    """Name -> metric, with type checked on every lookup (a name can
    never silently change kind mid-run)."""

    def __init__(self, now=None):
        self._now = now if now is not None else time.time
        self._lock = _racecheck.make_lock("MetricsRegistry._lock")
        self._metrics = {}

    def _get(self, name, cls, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, **kw)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise MXNetError(
                    f"telemetry metric {name!r} already registered as "
                    f"{type(m).__name__}, requested {cls.__name__}")
            return m

    def counter(self, name):
        return self._get(name, Counter)

    def gauge(self, name):
        return self._get(name, Gauge)

    def histogram(self, name, edges=None):
        m = self._get(name, Histogram, edges=edges)
        if edges is not None and tuple(float(e) for e in edges) != m.edges:
            raise MXNetError(
                f"histogram {name!r} already registered with edges "
                f"{m.edges}; re-registration with different edges would "
                f"make aggregation non-deterministic")
        return m

    def value(self, name):
        with self._lock:
            m = self._metrics.get(name)
        return None if m is None else m.value

    def snapshot(self):
        """JSON-able state of every metric, grouped by kind, with names
        sorted so two snapshots of equal state serialize identically."""
        from .events import SCHEMA_VERSION
        with self._lock:
            items = sorted(self._metrics.items())
        counters, gauges, hists = {}, {}, {}
        for name, m in items:
            if isinstance(m, Counter):
                counters[name] = m.value
            elif isinstance(m, Gauge):
                gauges[name] = m.value
            else:
                hists[name] = m.state()
        return {"schema_version": SCHEMA_VERSION, "time": self._now(),
                "counters": counters, "gauges": gauges,
                "histograms": hists}

    def reset(self):
        with self._lock:
            self._metrics.clear()
