"""Fleet observability: pod-wide telemetry aggregation (ISSUE 15).

Every observability surface built so far — the PR 9 registry, PR 14
tracing/watchdog/live-MFU — is process-local, yet on a v5e-256 pod the
signal that matters is *cross-worker*: one straggling host sets the
step time for all 32 (the MLPerf TPU-pod analysis, arXiv:1909.09756,
attributes most lost scale efficiency to exactly this; the
concurrency-limits study, arXiv:2011.03641, shows the tail worker is
the ceiling).  PR 9 shipped the raw ingredients — a PS ``_OP_TELEMETRY``
scrape RPC, FIXED histogram bucket edges chosen for deterministic
cross-worker aggregation, schema-versioned events — and this module is
the aggregation plane that finally consumes them fleet-wide:

- :class:`FleetCollector` scrapes every worker's registry snapshot
  (``PSClient.telemetry()`` for remote ranks, the local registry for
  rank 0, or any injectable transport — N simulated workers test under
  FakeClock with zero sleeps) and merges them into ONE fleet snapshot:
  counters summed, gauges kept per-rank, histograms merged EXACTLY
  (possible because PR 9 fixed the bucket edges — element-wise bucket
  addition, never a re-binning estimate; mismatched edges REFUSE to
  merge).
- Per-rank **skew analysis**: each rank's ``train.step_ms`` vs. the
  fleet median gives a ``straggler_score``; the snapshot names the
  slowest rank, the skew ratio, and any desynced membership epoch.
- **Fleet watchdog rules** on the PR 14 edge-trigger machinery
  (:class:`~.watchdog.EdgeRuleEngine`): ``fleet.straggler``,
  ``fleet.epoch_desync``, ``fleet.scrape_dead`` — each firing is a
  typed ``fleet.<rule>`` event + a flight dump
  (``reason="fleet:<rule>"``) NAMING the offending rank, re-armed only
  after the condition clears.
- **Cross-worker trace stitching**: a ``fleet`` scrape also pulls each
  rank's finished-span ring (PS ``_OP_TELEMETRY`` fmt=2), and
  ``tracing.chrome_trace(fleet=...)`` merges them into one perfetto
  timeline with per-rank process lanes.  The per-rank clock offset is
  ESTIMATED from the scrape round-trip and DISCLOSED as a lane label —
  never silently applied to timestamps.

``MXTPU_FLEET=0`` is a bitwise-inert kill switch in the PR 9 style
(:meth:`FleetCollector.collect` scrapes nothing, emits nothing);
``MXTPU_FLEET_SCRAPE_S`` paces :meth:`FleetCollector.poll` (default
30 s, injectable clock — zero sleeps in tests); ``MXTPU_FLEET_SKEW``
is the straggler-score threshold (default 2.0).  Exposure:
``tools/telemetry_dump.py --fleet`` (multi-host scrape -> merged prom
text / JSON / ``--trace`` fleet timeline) and the bench ``fleet``
block (:func:`fleet_block`, null-when-unmeasured on a single process).
Topology diagram and merge-semantics table: docs/OBSERVABILITY.md
§Fleet.
"""
from __future__ import annotations

import os
import threading
import time

from ..base import MXNetError
from .events import SCHEMA_VERSION
from .watchdog import EdgeRuleEngine

__all__ = ["FLEET_SCHEMA_VERSION", "FleetCollector", "enabled",
           "default_scrape_s", "default_skew", "merge_histograms",
           "local_transport", "ps_transport", "transports_from_addrs",
           "fleet_prom_snapshot", "fleet_block"]

#: bump on any BREAKING fleet-snapshot field change (additive fields
#: keep the version); ``tools/bench_diff.py`` refuses to compare bench
#: ``fleet`` blocks across a drift, like the telemetry schema
FLEET_SCHEMA_VERSION = 1


def enabled():
    """Whether the fleet plane is live (``MXTPU_FLEET`` != 0).  Read at
    call time so chaos/tests can flip it without a reimport."""
    return os.environ.get("MXTPU_FLEET", "1") != "0"


def default_scrape_s():
    try:
        return float(os.environ.get("MXTPU_FLEET_SCRAPE_S", "") or 30.0)
    except ValueError:
        return 30.0


def default_skew():
    try:
        return float(os.environ.get("MXTPU_FLEET_SKEW", "") or 2.0)
    except ValueError:
        return 2.0


# -- transports ---------------------------------------------------------

def local_transport():
    """Scrape THIS process (rank 0's view in the default topology where
    the collector runs on the coordinator)."""
    def scrape():
        from . import snapshot
        from . import tracing
        return {"snapshot": snapshot(), "spans": tracing.spans(),
                "dropped_spans": tracing.dropped()}
    return scrape


def ps_transport(host, port, retries=3, policy=None):
    """Scrape a remote rank over its PS server's ``_OP_TELEMETRY`` RPC
    (fmt=2: snapshot + finished-span ring — the fleet payload).  A
    fresh connection per scrape: a wedged worker must fail THIS scrape,
    not wedge the collector's socket forever.  ``policy`` (a
    ``kvstore.rpc.RetryPolicy``) bounds the connect/read deadlines and
    retries (ISSUE 19); the default reads the ``MXTPU_RPC_*`` env, so a
    dead rank fails TYPED within the deadline instead of hanging the
    scrape."""
    def scrape():
        from ..kvstore.ps_server import PSClient
        client = PSClient(host, int(port), retries=retries,
                          policy=policy)
        try:
            return client.telemetry(fmt="fleet")
        finally:
            client.close()
    return scrape


def transports_from_addrs(addrs, retries=3):
    """``"h0:p0,h1:p1,..."`` (the ``MXTPU_FLEET_ADDRS`` spec) -> an
    ordered {rank: transport} map, rank = position in the list."""
    out = {}
    for rank, part in enumerate(p for p in str(addrs).split(",")
                                if p.strip()):
        host, _, port = part.strip().rpartition(":")
        if not host:
            raise MXNetError(f"fleet transport spec {part!r}: expected "
                             f"host:port")
        out[rank] = ps_transport(host, int(port), retries=retries)
    return out


# -- exact merge --------------------------------------------------------

def merge_histograms(states):
    """Element-wise merge of fixed-edge histogram states — EXACT, the
    PR 9 contract: all ranks must carry identical edges (they do, the
    edges are fixed at creation) or the merge REFUSES rather than
    re-bin.  Summation runs in the caller's rank order, so two merges
    of the same snapshots are bitwise identical."""
    states = list(states)
    if not states:
        return None
    edges = list(states[0]["edges"])
    for st in states[1:]:
        if list(st["edges"]) != edges:
            raise MXNetError(
                f"fleet merge: histogram edges differ across ranks "
                f"({edges} vs {list(st['edges'])}); fixed-edge "
                f"histograms merge exactly or not at all")
    counts = [0] * (len(edges) + 1)
    total_sum, total_count = 0.0, 0
    vmin = vmax = None
    for st in states:
        for i, c in enumerate(st["counts"]):
            counts[i] += c
        total_sum += st["sum"]
        total_count += st["count"]
        if st["min"] is not None and (vmin is None or st["min"] < vmin):
            vmin = st["min"]
        if st["max"] is not None and (vmax is None or st["max"] > vmax):
            vmax = st["max"]
    return {"edges": edges, "counts": counts, "sum": total_sum,
            "count": total_count, "min": vmin, "max": vmax}


def _normalize_payload(payload):
    """A transport may return the fleet payload ``{"snapshot": ...,
    "spans": [...]}`` or a bare registry snapshot (the PR 9 json fmt) —
    normalize to (snapshot, spans, dropped_spans)."""
    if isinstance(payload, dict) and "snapshot" in payload \
            and "counters" not in payload:
        return (payload["snapshot"], payload.get("spans") or [],
                payload.get("dropped_spans"))
    return payload, [], None


def _rank_step_ms(snap):
    """A rank's ``train.step_ms`` view: the fixed-edge histogram's mean
    (sum/count — exact, and what the merge preserves); None before the
    first committed step."""
    h = (snap.get("histograms") or {}).get("train.step_ms")
    if h and h.get("count"):
        return h["sum"] / h["count"]
    return (snap.get("gauges") or {}).get("train.step_ms")


def _rank_epoch(snap):
    v = (snap.get("gauges") or {}).get("elastic.epoch")
    if v is None:
        v = (snap.get("context") or {}).get("epoch")
    return v


class FleetCollector(EdgeRuleEngine):
    """The aggregation plane: scrape every rank, merge exactly, analyze
    skew, fire the fleet watchdog rules.

    ``transports`` is {rank: callable() -> scrape payload}; the
    callable raises on a dead endpoint (that IS the ``scrape_dead``
    signal).  ``now`` is the scrape/pacing clock (``time.time`` unless
    injected — FakeClock in tests and chaos, zero sleeps)."""

    _PREFIX = "fleet"

    def __init__(self, transports, now=None, skew=None, scrape_s=None):
        super().__init__()
        self._transports = dict(transports)
        self._now = now if now is not None else time.time
        self.skew = float(skew) if skew is not None else default_skew()
        self.scrape_s = float(scrape_s) if scrape_s is not None \
            else default_scrape_s()
        self._last_scrape_t = None   # poll() cadence (collector thread)
        self._stop = None            # threading.Event while started
        self.last = None             # newest fleet snapshot

    # -- scrape ----------------------------------------------------------
    def _scrape(self):
        """One pass over every transport, in rank order.  Per-rank
        result: the payload + round-trip, or a TYPED failure — a dead
        rank must never abort the fleet view."""
        out = {}
        for rank in sorted(self._transports):
            t0 = self._now()
            try:
                payload = self._transports[rank]()
            except Exception as e:  # noqa: BLE001 — typed, not fatal
                out[rank] = {
                    "ok": False,
                    "error": f"{type(e).__name__}: {e}",
                    "scrape_ms": round((self._now() - t0) * 1e3, 3)}
                continue
            t1 = self._now()
            snap, spans, dropped = _normalize_payload(payload)
            remote_t = snap.get("time") if isinstance(snap, dict) else None
            # clock-offset ESTIMATE: remote wall time vs the scrape
            # round-trip midpoint.  Disclosed on the trace lane, never
            # applied to timestamps (docs/OBSERVABILITY.md §Fleet).
            offset = (round(remote_t - (t0 + t1) / 2.0, 6)
                      if isinstance(remote_t, (int, float)) else None)
            sv = snap.get("schema_version") if isinstance(snap, dict) \
                else None
            if not isinstance(snap, dict) or "counters" not in snap:
                out[rank] = {"ok": False, "scrape_ms":
                             round((t1 - t0) * 1e3, 3),
                             "error": "malformed snapshot (no counters)"}
            elif sv != SCHEMA_VERSION:
                # a rank on a different telemetry schema cannot merge
                # deterministically — excluded, disclosed, typed
                out[rank] = {"ok": False, "scrape_ms":
                             round((t1 - t0) * 1e3, 3),
                             "error": f"telemetry schema drift "
                                      f"(rank v{sv} != local "
                                      f"v{SCHEMA_VERSION})"}
            else:
                out[rank] = {"ok": True, "snapshot": snap,
                             "spans": spans, "dropped_spans": dropped,
                             "scrape_ms": round((t1 - t0) * 1e3, 3),
                             "clock_offset_est_s": offset}
        return out

    # -- merge + analysis ------------------------------------------------
    def collect(self):
        """Scrape + merge + analyze + fire rules; returns the fleet
        snapshot.  With ``MXTPU_FLEET=0`` this is inert: no transport
        is called, nothing is emitted (the kill-switch gate)."""
        if not enabled():
            return {"fleet_schema_version": FLEET_SCHEMA_VERSION,
                    "enabled": False}
        scraped = self._scrape()
        alive = [r for r in sorted(scraped) if scraped[r]["ok"]]
        dead = [r for r in sorted(scraped) if not scraped[r]["ok"]]

        counters, gauges, hist_states = {}, {}, {}
        per_rank = {}
        for rank in sorted(scraped):
            info = scraped[rank]
            row = {"ok": info["ok"], "scrape_ms": info["scrape_ms"],
                   "error": info.get("error")}
            if info["ok"]:
                snap = info["snapshot"]
                row["clock_offset_est_s"] = info.get("clock_offset_est_s")
                row["step_ms"] = _rank_step_ms(snap)
                row["epoch"] = _rank_epoch(snap)
                row["events_seen"] = snap.get("events_seen")
                row["spans"] = info.get("spans") or []
                row["dropped_spans"] = info.get("dropped_spans")
                for name, v in (snap.get("counters") or {}).items():
                    counters[name] = counters.get(name, 0) + v
                for name, v in (snap.get("gauges") or {}).items():
                    gauges.setdefault(name, {})[str(rank)] = v
                for name, st in (snap.get("histograms") or {}).items():
                    hist_states.setdefault(name, []).append(st)
            per_rank[str(rank)] = row
        histograms = {name: merge_histograms(sts)
                      for name, sts in hist_states.items()}

        fleet = {"fleet_schema_version": FLEET_SCHEMA_VERSION,
                 "schema_version": SCHEMA_VERSION,
                 "enabled": True,
                 "time": self._now(),
                 "ranks": sorted(scraped),
                 "alive": alive, "dead": dead,
                 "per_rank": per_rank,
                 "counters": counters, "gauges": gauges,
                 "histograms": histograms}
        fleet["scrape_ms"] = round(max(
            (scraped[r]["scrape_ms"] for r in scraped), default=0.0), 3)
        self._analyze(fleet)
        self._publish(fleet)
        self._drain()
        self.last = fleet
        return fleet

    def _analyze(self, fleet):
        """Skew analysis + edge-triggered rule evaluation over the
        freshly merged view.  Rules queue under ``_lock`` and fire in
        :meth:`_drain` (the EdgeRuleEngine discipline)."""
        per_rank = fleet["per_rank"]
        steps = {r: per_rank[str(r)]["step_ms"] for r in fleet["alive"]
                 if per_rank[str(r)].get("step_ms") is not None}
        skew = {"median_step_ms": None, "slowest_rank": None,
                "skew_ratio": None, "straggler_scores": {}}
        if steps:
            vals = sorted(steps.values())
            n = len(vals)
            median = (vals[n // 2] if n % 2 else
                      (vals[n // 2 - 1] + vals[n // 2]) / 2.0)
            skew["median_step_ms"] = round(median, 3)
            slowest = max(sorted(steps), key=lambda r: steps[r])
            skew["slowest_rank"] = slowest
            if median > 0:
                skew["skew_ratio"] = round(steps[slowest] / median, 4)
                skew["straggler_scores"] = {
                    str(r): round(steps[r] / median, 4)
                    for r in sorted(steps)}
        fleet["skew"] = skew

        epochs = {r: per_rank[str(r)]["epoch"] for r in fleet["alive"]
                  if per_rank[str(r)].get("epoch") is not None}
        desynced = []
        if len(epochs) >= 2 and len(set(epochs.values())) > 1:
            newest = max(epochs.values())
            desynced = sorted(r for r, e in epochs.items() if e < newest)
        fleet["epoch_desync"] = ({"epochs": {str(r): epochs[r]
                                             for r in sorted(epochs)},
                                  "laggards": desynced}
                                 if desynced else None)

        with self._lock:
            # stragglers: per-rank edges so TWO slow hosts both get
            # named; needs >= 2 measured ranks (a fleet of one has no
            # median to lag)
            scores = skew["straggler_scores"]
            for r in sorted(steps):
                score = scores.get(str(r))
                firing = (score is not None and len(steps) >= 2
                          and score >= self.skew)
                self._edge(f"straggler:{r}", firing, rule="straggler",
                           rank=r, step_ms=round(steps[r], 3),
                           median_step_ms=skew["median_step_ms"],
                           score=score, threshold=self.skew)
            for r in fleet["ranks"]:
                row = per_rank[str(r)]
                self._edge(f"epoch_desync:{r}",
                           r in desynced, rule="epoch_desync",
                           rank=r, epoch=row.get("epoch"),
                           epochs={str(k): epochs[k]
                                   for k in sorted(epochs)})
                self._edge(f"scrape_dead:{r}", not row["ok"],
                           rule="scrape_dead", rank=r,
                           error=row.get("error"))

    def _publish(self, fleet):
        """Thin-reader seam: the fleet-level analysis lands on the LOCAL
        registry so bench's ``fleet`` block and a live scrape of the
        coordinator read one source (the ISSUE 9 discipline)."""
        from . import enabled as telem_enabled, inc, set_gauge
        if not telem_enabled():
            return
        inc("fleet.scrapes")
        set_gauge("fleet.ranks", len(fleet["ranks"]))
        set_gauge("fleet.ranks_alive", len(fleet["alive"]))
        set_gauge("fleet.scrape_ms", fleet["scrape_ms"])
        skew = fleet["skew"]
        if skew["slowest_rank"] is not None:
            set_gauge("fleet.slowest_rank", skew["slowest_rank"])
        if skew["skew_ratio"] is not None:
            set_gauge("fleet.step_ms_skew", skew["skew_ratio"])

    # -- pacing ----------------------------------------------------------
    def poll(self):
        """Collect when a scrape is due per ``scrape_s``; None when not
        due (or disabled).  The injectable-clock twin of the background
        thread — chaos drives this with a FakeClock, zero sleeps."""
        if not enabled():
            return None
        t = self._now()
        if self._last_scrape_t is not None and \
                t - self._last_scrape_t < self.scrape_s:
            return None
        self._last_scrape_t = t
        return self.collect()

    def start(self):
        """Background scrape loop at ``scrape_s`` (production pacing;
        daemon thread).  No-op when already started or disabled."""
        if self._stop is not None or not enabled():
            return self
        stop = threading.Event()
        self._stop = stop

        def _loop():
            while not stop.is_set():
                try:
                    self.collect()
                except Exception:  # noqa: BLE001 — the scrape loop
                    pass           # must survive any one bad pass
                stop.wait(max(0.05, self.scrape_s))

        threading.Thread(target=_loop, name="mxtpu-fleet-scrape",
                         daemon=True).start()
        return self

    def stop(self):
        if self._stop is not None:
            self._stop.set()
            self._stop = None

    def state(self):
        with self._lock:
            return {"trips": [r for r, _ in self.trips],
                    "tripped": sorted(self._tripped)}


# -- rendering / bench --------------------------------------------------

def fleet_prom_snapshot(fleet):
    """A registry-snapshot-shaped view of a fleet snapshot so the PR 9
    :func:`~.prom.prom_text` renderer serves the fleet path unchanged:
    merged counters/histograms pass through; per-rank gauges flatten to
    ``<name>.rank<r>``; the skew analysis lands as gauges."""
    gauges = {}
    for name, per in (fleet.get("gauges") or {}).items():
        for r, v in sorted(per.items()):
            gauges[f"{name}.rank{r}"] = v
    skew = fleet.get("skew") or {}
    for k in ("median_step_ms", "slowest_rank", "skew_ratio"):
        if skew.get(k) is not None:
            gauges[f"fleet.{k}"] = skew[k]
    gauges["fleet.ranks"] = len(fleet.get("ranks") or [])
    gauges["fleet.ranks_alive"] = len(fleet.get("alive") or [])
    return {"enabled": True,
            "schema_version": fleet.get("schema_version"),
            "counters": fleet.get("counters") or {},
            "gauges": gauges,
            "histograms": fleet.get("histograms") or {},
            "context": {}}


def fleet_block(enabled=False, ranks=0, slowest_rank=None,
                step_ms_skew=None, scrape_ms=None, stragglers=None,
                epoch_desync=None, scrape_dead=None):
    """The bench.py ``fleet`` observability block (the ``comm`` /
    ``serving`` / ``elastic`` block discipline): config is always real;
    MEASURED fields default to ``None`` — null-when-unmeasured, so a
    single-process CPU run can never pass off "no fleet to scrape" as
    "zero skew measured" (the PR 6 honesty rule, gated by
    tests/test_bench_line.py)."""
    def _r(x, n=3):
        return None if x is None else round(float(x), n)

    return {
        "fleet_schema_version": FLEET_SCHEMA_VERSION,
        "enabled": bool(enabled),
        "ranks": int(ranks),
        "slowest_rank": None if slowest_rank is None else int(slowest_rank),
        "step_ms_skew": _r(step_ms_skew, 4),
        "scrape_ms": _r(scrape_ms),
        "stragglers": None if stragglers is None else int(stragglers),
        "epoch_desync": None if epoch_desync is None else bool(epoch_desync),
        "scrape_dead": None if scrape_dead is None else int(scrape_dead),
    }
