"""Health watchdog: declarative run-health rules over the telemetry
spine (ISSUE 14).

PR 13's degradation ladder reacts to *capacity* signals; run *health*
— a NaN loss, a silent step-time stall, a KV-block leak — went
unwatched: the job limps until a human reads a dashboard.  The
watchdog is a set of cheap declarative rules ticked at the seams the
code already crosses (the trainer's per-step bookkeeping, the
estimator's loss pull, every serving scheduling boundary); each
firing emits a typed ``watchdog.<rule>`` event, bumps the
``watchdog.trips`` counter, and dumps the PR 9 flight recorder with
``reason="watchdog:<rule>"`` — the post-mortem exists the moment the
run goes bad, not when it finally dies.

Rule catalog (docs/OBSERVABILITY.md §Watchdog):

``nonfinite_loss``     loss is NaN/Inf at a step boundary
``nonfinite_grad``     gradient norm is NaN/Inf
``loss_spike``         loss > spike_factor x the trailing-window mean
``step_stall``         no step committed for ``stall_s`` seconds
                       (injectable clock — FakeClock in tests/chaos),
                       or one step alone took ``stall_s``
``queue_saturation``   serving queue depth >= ``queue_depth`` for
                       ``queue_boundaries`` consecutive boundaries
``kv_leak``            the per-window MINIMUM of ``kv_blocks_in_use``
                       strictly rose ``kv_windows`` windows in a row —
                       blocks never return to the pool even at the
                       emptiest boundary of each window (a refcount
                       leak trend, not normal load growth)

Each rule re-arms only after its condition clears (one incident, one
event — not one per step of a long NaN plateau).  ``MXTPU_WATCHDOG=0``
is a bitwise-inert kill switch in the PR 9 style: every hook is one
module-bool check and nothing allocates.  The NaN-loss chaos scenario
injects through the ``watchdog.loss`` fault point
(``testing/faults.py``) so the detection path is exactly the
production one.
"""
from __future__ import annotations

import math
import os
from collections import deque

from ..lint import racecheck as _racecheck

__all__ = ["Watchdog", "EdgeRuleEngine", "enabled", "watchdog",
           "configure", "reset", "on_step", "on_serving_boundary",
           "check"]


def _env_enabled():
    return os.environ.get("MXTPU_WATCHDOG", "1") != "0"


def _env_float(name, default):
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return float(default)


class EdgeRuleEngine:
    """The edge-trigger incident machinery, factored out of the process
    watchdog so the fleet collector (ISSUE 15) fires through the exact
    same discipline: rules queue an incident on a False->True transition
    under ``_lock`` and re-arm on the first healthy observation; the
    actual typed event + counter + flight dump run in :meth:`_drain`
    OUTSIDE the lock (the dump is file I/O — HB16).  ``_PREFIX`` names
    the incident family (``watchdog.<rule>`` / ``fleet.<rule>``, dump
    reason ``"<prefix>:<rule>"``)."""

    _PREFIX = "watchdog"

    def __init__(self):
        self._lock = _racecheck.make_lock(
            f"telemetry.{type(self).__name__}._lock")
        # everything below: guarded-by: _lock
        self._tripped = set()        # rules currently in-incident
        self._pending = []           # incidents to fire OUTSIDE _lock
        self.trips = []              # (rule, detail) history

    # -- firing ----------------------------------------------------------
    def _fire(self, rule, detail):
        """One incident: typed event + counter + flight dump.  The
        event is emitted BEFORE the dump so the dump's last event IS
        the incident (the chaos-harness contract).  Runs OUTSIDE the
        engine lock — the flight dump is file I/O (HB16)."""
        from . import event, inc, dump_flight
        event(f"{self._PREFIX}.{rule}", **detail)
        inc(f"{self._PREFIX}.trips")
        inc(f"{self._PREFIX}.{rule}.trips")
        dump_flight(f"{self._PREFIX}:{rule}")

    def _drain(self):
        """Fire every incident queued under the lock (caller must NOT
        hold it)."""
        while True:
            with self._lock:
                if not self._pending:
                    return
                rule, detail = self._pending.pop(0)
            self._fire(rule, detail)

    def _edge(self, key, firing, rule=None, **detail):  # guarded-by: _lock
        """Edge-trigger ``key``: queue a firing on False->True, re-arm
        on the first healthy observation.  Called under ``_lock``; the
        actual event/dump happens in :meth:`_drain` after release.
        ``rule`` names the fired incident when several edges share one
        rule (the fleet's per-rank straggler edges); defaults to
        ``key``."""
        if rule is None:
            rule = key
        if firing:
            if key not in self._tripped:
                self._tripped.add(key)
                self._pending.append((rule, detail))
                self.trips.append((rule, detail))
        else:
            self._tripped.discard(key)


class Watchdog(EdgeRuleEngine):
    """The rule engine.  ``now`` is the stall clock (injectable —
    ``testing.faults.FakeClock`` in tests and chaos; defaults to
    ``time.monotonic``).  Thresholds default from the env so a
    production job tunes them without code."""

    def __init__(self, now=None, stall_s=None, spike_factor=None,
                 spike_window=16, queue_depth=None, queue_boundaries=8,
                 kv_window=16, kv_windows=3):
        import time
        super().__init__()
        self._now = now if now is not None else time.monotonic
        self.stall_s = float(stall_s) if stall_s is not None \
            else _env_float("MXTPU_WATCHDOG_STALL_S", 120.0)
        self.spike_factor = float(spike_factor) if spike_factor \
            is not None else _env_float("MXTPU_WATCHDOG_SPIKE", 10.0)
        self.queue_depth = int(queue_depth) if queue_depth is not None \
            else int(_env_float("MXTPU_WATCHDOG_QUEUE", 64))
        self.queue_boundaries = int(queue_boundaries)
        self.kv_window = int(kv_window)
        self.kv_windows = int(kv_windows)
        # everything below: guarded-by: _lock
        self._losses = deque(maxlen=int(spike_window))
        self._last_step_t = None
        self._saturated = 0
        self._kv_samples = []
        self._kv_min_run = 0
        self._kv_last_min = None

    # -- training seams --------------------------------------------------
    def on_step(self, step, loss=None, grad_norm=None, step_ms=None):
        """Tick the training rules at a committed step boundary.
        ``loss``/``grad_norm`` are host floats (callers that already
        synced pass them; the trainer's own tick passes only
        ``step_ms`` — it never pulls the loss, HB10).  The
        ``watchdog.loss`` fault point lets chaos inject a NaN loss
        through the exact production path."""
        from ..testing import faults
        inj = faults.fault_point("watchdog.loss", payload=int(step))
        if isinstance(inj, (int, float)):
            loss = float(inj)
        with self._lock:
            now = self._now()
            gap = (now - self._last_step_t
                   if self._last_step_t is not None else None)
            self._last_step_t = now
            if loss is not None:
                loss = float(loss)
                self._edge("nonfinite_loss", not math.isfinite(loss),
                           step=int(step), loss=repr(loss))
                if math.isfinite(loss):
                    window = [v for v in self._losses]
                    if len(window) >= 4:
                        mean = sum(window) / len(window)
                        self._edge(
                            "loss_spike",
                            abs(loss) > self.spike_factor
                            * (abs(mean) + 1e-12) and abs(loss) > 1e-6,
                            step=int(step), loss=loss,
                            trailing_mean=mean)
                    self._losses.append(loss)
            if grad_norm is not None:
                self._edge("nonfinite_grad",
                           not math.isfinite(float(grad_norm)),
                           step=int(step), grad_norm=repr(grad_norm))
            stalled = (gap is not None and gap > self.stall_s) or \
                (step_ms is not None and step_ms > self.stall_s * 1e3)
            self._edge("step_stall", stalled, step=int(step),
                       gap_s=round(gap, 3) if gap is not None else None,
                       stall_s=self.stall_s)
        self._drain()

    def check(self, step=None):
        """Explicit stall probe for seams where no step arrives (a
        monitoring thread, a serving boundary, the chaos clock): fires
        ``step_stall`` when the last committed step is older than
        ``stall_s``."""
        with self._lock:
            if self._last_step_t is None:
                return False
            gap = self._now() - self._last_step_t
            self._edge("step_stall", gap > self.stall_s,
                       step=step, gap_s=round(gap, 3),
                       stall_s=self.stall_s)
            stalled = gap > self.stall_s
        self._drain()
        return stalled

    # -- serving seams ---------------------------------------------------
    def on_serving_boundary(self, queue_depth=None, kv_blocks_in_use=None):
        """Tick the serving rules at a scheduling boundary (host ints
        the batcher already holds — zero device traffic)."""
        with self._lock:
            if queue_depth is not None:
                if queue_depth >= self.queue_depth:
                    self._saturated += 1
                else:
                    self._saturated = 0
                self._edge("queue_saturation",
                           self._saturated >= self.queue_boundaries,
                           queue_depth=int(queue_depth),
                           boundaries=self._saturated)
            if kv_blocks_in_use is not None:
                self._kv_samples.append(int(kv_blocks_in_use))
                if len(self._kv_samples) >= self.kv_window:
                    wmin = min(self._kv_samples)
                    self._kv_samples = []
                    if self._kv_last_min is not None and \
                            wmin > self._kv_last_min:
                        self._kv_min_run += 1
                    else:
                        self._kv_min_run = 0
                    self._kv_last_min = wmin
                    self._edge("kv_leak",
                               self._kv_min_run >= self.kv_windows,
                               window_min=wmin,
                               rising_windows=self._kv_min_run)
        self._drain()

    def state(self):
        with self._lock:
            return {"trips": [r for r, _ in self.trips],
                    "tripped": sorted(self._tripped),
                    "losses": list(self._losses)}


_ENABLED = _env_enabled()
_WD = Watchdog()


def enabled():
    return _ENABLED


def watchdog():
    """The process-global instance the instrumented seams tick."""
    return _WD


def configure(enabled=None, instance=None, **kw):
    """Swap config (tests / chaos: ``configure(instance=Watchdog(
    now=fake_clock, stall_s=30))`` points the global seams at a
    deterministic engine)."""
    global _ENABLED, _WD
    if enabled is not None:
        _ENABLED = bool(enabled)
    if instance is not None:
        _WD = instance
    elif kw:
        _WD = Watchdog(**kw)
    return _WD


def reset():
    """Fresh rule state, default clock, re-read env kill switch (the
    conftest between-tests seam, via ``telemetry.reset()``) — an
    injected FakeClock must never leak into the next test."""
    global _ENABLED, _WD
    _ENABLED = _env_enabled()
    _WD = Watchdog()


# -- module-level hooks: one bool check when disabled -------------------

def on_step(step, loss=None, grad_norm=None, step_ms=None):
    if not _ENABLED:
        return
    _WD.on_step(step, loss=loss, grad_norm=grad_norm, step_ms=step_ms)


def on_serving_boundary(queue_depth=None, kv_blocks_in_use=None):
    if not _ENABLED:
        return
    _WD.on_serving_boundary(queue_depth=queue_depth,
                            kv_blocks_in_use=kv_blocks_in_use)


def check(step=None):
    if not _ENABLED:
        return False
    return _WD.check(step=step)
