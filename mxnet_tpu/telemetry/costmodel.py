"""Shared FLOP/MFU accounting: the one cost model bench.py AND the live
trainer gauges read (ISSUE 14).

bench.py computed MFU offline only — chip peak table, XLA cost
analysis, analytic 2*MAC fallbacks all private to the script — so a
running job could never see its own delivered FLOP/s.  This module is
those helpers lifted verbatim (bench.py now imports them; its output
for the same inputs is byte-identical — gated in test_bench_line.py),
plus the LIVE half: :func:`live_cost_enabled` decides once whether the
trainer should pay the one-per-compile ``cost_analysis`` (only when
the chip peak is actually known — a real TPU device kind or the
``MXTPU_CHIP_PEAK_TFLOPS`` override; a CPU run stamps nothing rather
than a fake number, the PR 6 honesty rule), and the trainer then
publishes ``train.mfu`` / ``train.tflops_delivered`` /
``train.step_flops`` gauges at O(1) arithmetic per step.
"""
from __future__ import annotations

import os

__all__ = ["PEAK_BF16", "chip_peak_flops", "compiled_flops",
           "resnet_train_flops_per_img", "bert_train_flops_per_sample",
           "attach_mfu", "live_cost_enabled"]

#: Advertised per-chip bf16 peak FLOP/s by device_kind substring (google
#: cloud TPU docs); lowercase match, first hit wins.
PEAK_BF16 = [
    ("v6", 918e12), ("trillium", 918e12),
    ("v5p", 459e12),
    ("v5 lite", 197e12), ("v5e", 197e12), ("v5litepod", 197e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 46e12),
]


def _env_peak():
    """``MXTPU_CHIP_PEAK_TFLOPS`` override (TFLOP/s): unknown device
    kinds, and the CPU-hosted live-MFU parity gate, set the peak
    explicitly.  None when unset/unparseable."""
    raw = os.environ.get("MXTPU_CHIP_PEAK_TFLOPS", "").strip()
    if not raw:
        return None
    try:
        v = float(raw) * 1e12
    except ValueError:
        return None
    return v if v > 0 else None


def chip_peak_flops(dev=None):
    """Peak bf16 FLOP/s for ``dev`` (default: first jax device); the
    env override wins.  None when unknown — callers must treat that as
    "MFU unmeasurable", never as zero."""
    peak = _env_peak()
    if peak is not None:
        return peak
    if dev is None:
        import jax
        dev = jax.devices()[0]
    kind = getattr(dev, "device_kind", "").lower()
    for sub, peak in PEAK_BF16:
        if sub in kind:
            return peak
    return None


def compiled_flops(jitted, *args):
    """XLA's own FLOP estimate for the compiled step (AOT cost
    analysis).  One lower+compile per call — do it once per compiled
    step, never per step."""
    try:
        cost = jitted.lower(*args).compile().cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        f = float(cost.get("flops", -1.0))
        return f if f > 0 else None
    except Exception:  # noqa: BLE001 — cost analysis is best-effort
        return None


def resnet_train_flops_per_img():
    # 4.1 GFLOP fwd at 224^2 (2*MAC convention) * 3 for fwd+bwd
    return 3 * 4.1e9


def bert_train_flops_per_sample(seq, layers=12, d=768, ffn=3072):
    # matmul MACs/token/layer: QKVO 4d^2, FFN 2*d*ffn, attention 2*L*d
    per_tok = layers * (4 * d * d + 2 * d * ffn + 2 * seq * d)
    return 3 * 2 * per_tok * seq  # fwd+bwd ~ 3x fwd; FLOPs = 2*MACs


def attach_mfu(result, flops_per_sample, samples_per_sec, jitted=None,
               jit_args=None):
    """Stamp ``tflops_delivered`` / ``flops_source`` / ``mfu`` /
    ``chip_peak_tflops_bf16`` onto a bench payload — the exact
    bench.py semantics (XLA cost analysis when available and
    ``MXTPU_BENCH_COST_ANALYSIS`` allows it, else the analytic 2*MAC
    count; MFU only when the chip peak is known)."""
    import jax
    analytic = flops_per_sample
    compiled = None
    if jitted is not None and jit_args is not None and \
            os.environ.get("MXTPU_BENCH_COST_ANALYSIS", "1") == "1":
        per_step = compiled_flops(jitted, *jit_args)
        if per_step is not None:
            compiled = per_step
    batch = result.get("batch", 1)
    flops_per_step = compiled if compiled is not None \
        else analytic * batch
    result["tflops_delivered"] = round(
        flops_per_step / batch * samples_per_sec / 1e12, 2)
    result["flops_source"] = "xla_cost_analysis" if compiled is not None \
        else "analytic_2mac"
    peak = chip_peak_flops(jax.devices()[0])
    if peak is not None:
        result["mfu"] = round(
            flops_per_step / batch * samples_per_sec / peak, 4)
        result["chip_peak_tflops_bf16"] = peak / 1e12
    return result


def live_cost_enabled():
    """Whether the trainer should pay the once-per-compile cost
    analysis for live MFU gauges: only when the peak is KNOWN (real
    TPU device kind, or the env override) — on a plain CPU host the
    answer is no, the gauges stay unset (null-when-unmeasured), and no
    extra compile is ever paid."""
    if _env_peak() is not None:
        return True
    try:
        import jax
        return chip_peak_flops(jax.devices()[0]) is not None
    except Exception:  # noqa: BLE001 — no backend yet: no live cost
        return False
