"""``mx.telemetry`` — the unified observability spine (ISSUE 9).

Every subsystem built in PRs 2-8 kept its own ad-hoc numbers
(``InferenceEngine.stats``, the overlap probe's ``exposed_comm_ms``,
``CheckpointManager`` timings, elastic ``reshard_ms``...) and none of it
was observable from a *running* job.  This package is the one spine they
now all publish to:

- a process-wide **metrics registry** (:mod:`registry`): counters,
  gauges, and histograms with FIXED bucket edges so aggregation across
  workers is deterministic; injectable clock (the PR 4 FakeClock
  discipline);
- a schema-versioned **structured event log** (:mod:`events`): JSONL
  records with a monotonic ``seq``, the current training ``step`` and
  membership ``epoch``, kept in a bounded in-memory ring and optionally
  appended to ``MXTPU_EVENT_LOG``;
- a **flight recorder** (:mod:`flight`): the ring + a metric snapshot
  dumped to disk on SIGTERM (via PR 4's ``PreemptionHandler``), on any
  fault-point trip (``testing/faults.py``), and on unhandled train-step
  exceptions — the post-mortem a preempted pod job otherwise never
  leaves behind.

Exposure, three ways: :func:`snapshot` (the API), a Prometheus-style
text dump (:func:`prom_text`, ``tools/telemetry_dump.py``, and the PS
server's ``_OP_TELEMETRY`` RPC for live pod scraping), and perfetto
correlation — ``profiler.record_span`` tags spans with the current
step/epoch from :func:`context`.

Zero overhead when ``MXTPU_TELEMETRY=0``: every helper below is a single
module-bool check (the ``testing.faults.fault_point`` discipline) and
the registry hands back one shared no-op metric — no allocation, no
locks, no dict growth.  See docs/OBSERVABILITY.md for the metric
catalog and the event/flight-recorder schema.
"""
from __future__ import annotations

import os
import time

from .registry import (MetricsRegistry, Counter, Gauge, Histogram,
                       NULL_METRIC, DEFAULT_MS_EDGES)
from .events import EventLog, SCHEMA_VERSION
from .flight import FlightRecorder, memory_block
from .prom import prom_text as _render_prom
from . import tracing
from . import watchdog
from . import costmodel
from . import fleet

__all__ = ["SCHEMA_VERSION", "enabled", "registry", "counter", "gauge",
           "histogram", "inc", "set_gauge", "observe", "value", "event",
           "events", "events_dropped", "set_context", "context",
           "snapshot", "prom_text", "flight", "dump_flight",
           "last_flight_dump", "on_fault", "on_preemption",
           "on_step_error", "reset", "configure", "clock",
           "MetricsRegistry", "EventLog", "FlightRecorder",
           "memory_block", "Counter", "Gauge", "Histogram",
           "DEFAULT_MS_EDGES", "tracing", "watchdog", "costmodel",
           "fleet"]


def _env_enabled():
    return os.environ.get("MXTPU_TELEMETRY", "1") != "0"


def _env_ring():
    try:
        return max(1, int(os.environ.get("MXTPU_TELEMETRY_RING", "256")))
    except ValueError:
        return 256


_ENABLED = _env_enabled()
_REGISTRY = MetricsRegistry(now=time.time)
_EVENTS = EventLog(ring_size=_env_ring(),
                   path=os.environ.get("MXTPU_EVENT_LOG") or None,
                   now=time.time)
_FLIGHT = FlightRecorder(_REGISTRY, _EVENTS)


def configure(enabled=None, ring_size=None, event_log=None, now=None):
    """Reconfigure the process-wide telemetry state (tests; production
    configures through the env vars at import).  ``now`` replaces the
    timestamp clock on the registry AND the event log — the FakeClock
    seam."""
    global _ENABLED, _REGISTRY, _EVENTS, _FLIGHT
    if enabled is not None:
        _ENABLED = bool(enabled)
    if ring_size is not None or event_log is not None or now is not None:
        clk = now if now is not None else _EVENTS._now
        _REGISTRY = MetricsRegistry(now=clk)
        _EVENTS = EventLog(
            ring_size=ring_size if ring_size is not None
            else _EVENTS.ring_size,
            path=event_log if event_log is not None else _EVENTS.path,
            now=clk)
        _FLIGHT = FlightRecorder(_REGISTRY, _EVENTS)
    return _ENABLED


def configure_from_env():
    """Re-read ``MXTPU_TELEMETRY`` / ``MXTPU_TELEMETRY_RING`` /
    ``MXTPU_EVENT_LOG`` (subprocess harnesses that mutate env after
    import)."""
    return configure(enabled=_env_enabled(), ring_size=_env_ring(),
                     event_log=os.environ.get("MXTPU_EVENT_LOG") or "")


def enabled():
    """Whether telemetry is live (``MXTPU_TELEMETRY`` != 0).  Callers on
    hot paths check this ONCE and skip their timing reads entirely when
    off — the zero-overhead contract."""
    return _ENABLED


def registry():
    return _REGISTRY


def clock():
    """Monotonic duration clock for instrumentation sites (NOT the
    injectable wall clock — durations must never go backwards under a
    FakeClock that only stamps events)."""
    return time.perf_counter()


# -- metric helpers (each a single bool check when disabled) ------------

def counter(name):
    if not _ENABLED:
        return NULL_METRIC
    return _REGISTRY.counter(name)


def gauge(name):
    if not _ENABLED:
        return NULL_METRIC
    return _REGISTRY.gauge(name)


def histogram(name, edges=None):
    if not _ENABLED:
        return NULL_METRIC
    return _REGISTRY.histogram(name, edges=edges)


def inc(name, n=1):
    if not _ENABLED:
        return
    _REGISTRY.counter(name).inc(n)


def set_gauge(name, v):
    if not _ENABLED:
        return
    _REGISTRY.gauge(name).set(v)


def observe(name, v, edges=None):
    if not _ENABLED:
        return
    _REGISTRY.histogram(name, edges=edges).observe(v)


def value(name):
    """Current value of a counter/gauge (None when absent or disabled)
    — the thin-reader seam bench blocks and the loadgen consume."""
    if not _ENABLED:
        return None
    return _REGISTRY.value(name)


# -- events / context ---------------------------------------------------

def set_context(step=None, epoch=None):
    """Update the ambient (step, membership-epoch) every event record —
    and every ``profiler.record_span`` while a profile runs — is stamped
    with.  The trainer sets ``step``; the elastic layer sets
    ``epoch``."""
    if not _ENABLED:
        return
    _EVENTS.set_context(step=step, epoch=epoch)


def context():
    """The ambient {step, epoch} (empty dict when unset or disabled)."""
    if not _ENABLED:
        return {}
    return _EVENTS.context()


def event(kind, **data):
    if not _ENABLED:
        return None
    return _EVENTS.emit(kind, **data)


def events():
    """The in-memory ring's current contents (oldest first)."""
    if not _ENABLED:
        return []
    return _EVENTS.events()


def events_dropped():
    """Event records the bounded ring evicted since the last reset
    (0 when disabled) — visible truncation (ISSUE 15)."""
    if not _ENABLED:
        return 0
    return _EVENTS.dropped


# -- snapshot / rendering -----------------------------------------------

def snapshot():
    """One JSON-able view of the whole registry + context: the
    ``mx.telemetry.snapshot()`` API of ISSUE 9.  ``{"enabled": False}``
    when telemetry is off — never fake zeros (the PR 6 honesty rule)."""
    if not _ENABLED:
        return {"schema_version": SCHEMA_VERSION, "enabled": False}
    snap = _REGISTRY.snapshot()
    snap["enabled"] = True
    snap["context"] = _EVENTS.context()
    snap["events_seen"] = _EVENTS.seq
    return snap


def prom_text(snap=None):
    """Prometheus text-format rendering of ``snap`` (default: a fresh
    :func:`snapshot`)."""
    return _render_prom(snapshot() if snap is None else snap)


# -- flight recorder ----------------------------------------------------

def flight():
    return _FLIGHT


def dump_flight(reason, path=None):
    """Write the flight-recorder dump (ring + snapshot) now.  Returns
    the path, or None when disabled."""
    if not _ENABLED:
        return None
    return _FLIGHT.dump(reason, path=path)


def last_flight_dump():
    """Path of the most recent dump this process wrote (None if none)."""
    return _FLIGHT.last_dump_path


def on_fault(site, payload=None):
    """Fault-point trip hook (called by ``testing.faults.fault_point``
    the moment an armed fault fires): record the trip as an event and
    dump the flight recorder — the post-mortem of an injected or real
    failure."""
    if not _ENABLED:
        return
    _EVENTS.emit("fault.trip", site=site,
                 payload=payload if isinstance(payload, (int, float, str,
                                                         bool, type(None)))
                 else repr(payload))
    _REGISTRY.counter("faults.trips").inc()
    _FLIGHT.dump(f"fault:{site}")


def on_preemption(reason):
    """Preemption hook (called by ``checkpoint.PreemptionHandler
    .request`` — the SIGTERM path): record + dump."""
    if not _ENABLED:
        return
    _EVENTS.emit("preemption", reason=str(reason))
    _REGISTRY.counter("preemptions").inc()
    _FLIGHT.dump(f"preemption:{reason}")


def on_step_error(step, exc):
    """Unhandled train-step exception hook (the trainer's dispatch
    wrapper): record + dump, then the caller re-raises."""
    if not _ENABLED:
        return
    _EVENTS.emit("train.step_error", step=int(step),
                 error=f"{type(exc).__name__}: {exc}")
    _REGISTRY.counter("train.step_errors").inc()
    _FLIGHT.dump(f"step_error:{step}")


def reset():
    """Clear metrics, events, context and the last-dump marker IN PLACE
    (module references held by instrumented sites stay valid).  The
    conftest autouse hook calls this between tests so metric assertions
    can't pair-flake — the profiler.reset() discipline.  The tracing
    ring and the watchdog rule state are process-global in the same
    way and reset alongside (both re-read their env kill switches)."""
    _REGISTRY.reset()
    _EVENTS.reset()
    _FLIGHT.last_dump_path = None
    tracing.reset()
    watchdog.reset()
