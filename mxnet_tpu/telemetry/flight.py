"""Flight recorder: the last-N-events + metric snapshot post-mortem.

A preempted or faulted pod job normally dies with nothing but a stack
trace; the flight recorder writes ONE small JSON file the moment
something goes wrong, so the operator (or the chaos harness) can read
what the process was doing at the instant of death::

    {"schema_version": 1,
     "reason": "fault:train.step",
     "time": 1000.25,
     "pid": 4242,
     "events": [...last N event records...],
     "metrics": {...registry snapshot...},
     "memory": {"devices": [...jax memory_stats or None...],
                "gauges": {...exact byte gauges, None when unset...}}}

Triggers (wired by the package front end):

- **SIGTERM / preemption** — ``checkpoint.PreemptionHandler.request``
  calls ``telemetry.on_preemption`` (the PR 4 stop seam);
- **fault-point trips** — ``testing.faults.fault_point`` calls
  ``telemetry.on_fault`` the moment an armed fault fires;
- **unhandled step exceptions** — ``DataParallelTrainer`` wraps its
  compiled dispatch and calls ``telemetry.on_step_error``.

The dump path is resolved AT DUMP TIME from ``MXTPU_FLIGHT_DIR``
(default: the system temp dir — never the working tree) as
``mxtpu_flight.<pid>.json`` — re-dumps overwrite, so the file always
holds the newest incident.  Write failures are swallowed: crash
reporting must never mask the crash.
"""
from __future__ import annotations

import json
import os
import tempfile

__all__ = ["FlightRecorder", "memory_block"]

#: exact byte gauges the subsystems publish (ISSUE 15 memory honesty):
#: an OOM post-mortem names the consumer.  Absent gauges report None —
#: never zero.
_BYTE_GAUGES = ("train.param_bytes", "train.zero1_shard_bytes",
                "train.opt_state_bytes", "serving.kv_bytes_in_use",
                "io.prefetch_buffer_bytes")


def memory_block(registry=None):
    """The flight dump's ``memory`` block: per-device backend memory
    stats when jax exposes them (``device.memory_stats()`` — ``None``
    otherwise, NEVER a fabricated zero: CPU backends report no stats),
    plus the exact byte gauges we already own (:data:`_BYTE_GAUGES`),
    so an OOM post-mortem names the consumer instead of just the
    corpse."""
    devices = None
    try:
        import jax
        rows = []
        for d in jax.devices():
            stats = None
            ms = getattr(d, "memory_stats", None)
            if callable(ms):
                try:
                    stats = ms() or None
                except Exception:  # noqa: BLE001 — honesty over crash
                    stats = None
            rows.append({
                "id": int(d.id), "platform": str(d.platform),
                "bytes_in_use": (stats or {}).get("bytes_in_use"),
                "peak_bytes_in_use": (stats or {}).get(
                    "peak_bytes_in_use"),
                "bytes_limit": (stats or {}).get("bytes_limit"),
            })
        devices = rows
    except Exception:  # noqa: BLE001 — the dump must never raise
        devices = None
    gauges = {}
    if registry is not None:
        for name in _BYTE_GAUGES:
            gauges[name] = registry.value(name)
    return {"devices": devices, "gauges": gauges}


class FlightRecorder:
    def __init__(self, registry, eventlog):
        self._registry = registry
        self._events = eventlog
        self.last_dump_path = None

    @staticmethod
    def default_path():
        d = os.environ.get("MXTPU_FLIGHT_DIR") or tempfile.gettempdir()
        return os.path.join(d, f"mxtpu_flight.{os.getpid()}.json")

    def payload(self, reason):
        from .events import SCHEMA_VERSION
        return {"schema_version": SCHEMA_VERSION,
                "reason": str(reason),
                "time": self._events._now(),
                "pid": os.getpid(),
                "events": self._events.events(),
                "metrics": self._registry.snapshot(),
                "memory": memory_block(self._registry)}

    def dump(self, reason, path=None):
        """Write the dump; returns the path (None when the write
        failed — never raises)."""
        path = path or self.default_path()
        try:
            payload = self.payload(reason)
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(payload, f, indent=1)
            os.replace(tmp, path)   # readers never see a torn dump
        except (OSError, TypeError, ValueError):
            return None
        self.last_dump_path = path
        return path
