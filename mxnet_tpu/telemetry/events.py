"""Structured event log: schema-versioned records, bounded ring, JSONL.

Every record carries::

    {"v": 1,               # SCHEMA_VERSION — consumers gate on this
     "seq": 42,            # monotonic per-process sequence number
     "t": 1000.25,         # injectable-clock timestamp
     "kind": "fault.trip", # dotted event kind
     "step": 17,           # ambient training step (None before any)
     "epoch": 2,           # ambient membership epoch (None outside
                           # elastic jobs)
     "data": {...}}        # kind-specific JSON-able payload

The last ``ring_size`` records live in memory (the flight recorder's
source); with ``MXTPU_EVENT_LOG=<path>`` every record is ALSO appended
as one JSON line — the durable stream a trace collector tails.  Write
failures are swallowed after the first warning: the event log must never
take the training loop down.
"""
from __future__ import annotations

import json
import time
import warnings
from collections import deque

from ..lint import racecheck as _racecheck

__all__ = ["EventLog", "SCHEMA_VERSION"]

#: bump on any BREAKING record/snapshot field change; additive fields
#: keep the version (consumers must ignore unknown keys)
SCHEMA_VERSION = 1


class EventLog:
    def __init__(self, ring_size=256, path=None, now=None):
        self.ring_size = int(ring_size)
        self.path = path or None
        self._now = now if now is not None else time.time
        self._lock = _racecheck.make_lock("EventLog._lock")
        self._ring = deque(maxlen=self.ring_size)
        self._seq = 0
        self._dropped = 0       # ring evictions since reset; guarded-by: _lock
        self._ctx = {"step": None, "epoch": None}
        # the JSONL appender has its OWN lock (never nested with _lock:
        # emit() releases _lock before touching the file) so a slow disk
        # stalls only other appenders, never the in-memory ring
        self._io_lock = _racecheck.make_lock("EventLog._io_lock")
        self._file = None
        self._write_warned = False

    @property
    def seq(self):
        with self._lock:
            return self._seq

    @property
    def dropped(self):
        """Records the bounded ring has evicted since the last reset —
        a truncated event history must be visibly truncated (ISSUE 15;
        mirrored as the ``telemetry.events.dropped`` counter)."""
        with self._lock:
            return self._dropped

    # -- context --------------------------------------------------------
    def set_context(self, step=None, epoch=None):
        with self._lock:
            if step is not None:
                self._ctx["step"] = int(step)
            if epoch is not None:
                self._ctx["epoch"] = int(epoch)

    def context(self):
        with self._lock:
            return {k: v for k, v in self._ctx.items() if v is not None}

    # -- emission -------------------------------------------------------
    def emit(self, kind, **data):
        with self._lock:
            self._seq += 1
            rec = {"v": SCHEMA_VERSION, "seq": self._seq,
                   "t": self._now(), "kind": str(kind),
                   "step": self._ctx["step"], "epoch": self._ctx["epoch"],
                   "data": data}
            evicting = len(self._ring) == self.ring_size
            self._ring.append(rec)
            if evicting:
                self._dropped += 1
            line = None
            if self.path:
                try:
                    line = json.dumps(rec)
                except (TypeError, ValueError):
                    line = json.dumps(dict(rec, data={"repr": repr(data)}))
        if evicting:
            # count the silent eviction where every reader looks (the
            # registry counter; chrome_trace stamps it too).  Outside
            # _lock — the counter has its own, and metric updates never
            # emit events, so this cannot recurse.
            from . import inc
            inc("telemetry.events.dropped")
        if line is not None:
            self._append_line(line)
        return rec

    def _append_line(self, line):
        # two concurrent emitters previously raced on self._file (HB14):
        # both could open the path, one handle leaked, and interleaved
        # write/flush pairs could tear lines.  The file I/O lives under
        # its own lock by design — serializing the append IS this lock's
        # job, so the blocking write is the invariant, not a bug:
        with self._io_lock:
            try:
                if self._file is None:
                    self._file = open(self.path, "a", encoding="utf-8")  # mxlint: disable=HB16 -- _io_lock exists to serialize this append path
                self._file.write(line + "\n")
                self._file.flush()  # mxlint: disable=HB16 -- _io_lock exists to serialize this append path
            except OSError as e:
                if not self._write_warned:
                    self._write_warned = True
                    warnings.warn(f"telemetry event log {self.path!r} "
                                  f"unwritable ({e}); further records "
                                  f"stay in-memory only")
                self._file = None

    def events(self):
        """Ring contents, oldest first (copies — the ring keeps moving)."""
        with self._lock:
            return [dict(r) for r in self._ring]

    def reset(self):
        with self._lock:
            self._ring.clear()
            self._seq = 0
            self._dropped = 0
            self._ctx = {"step": None, "epoch": None}

    def close(self):
        with self._io_lock:
            if self._file is not None:
                try:
                    self._file.close()
                except OSError:
                    pass
                self._file = None
