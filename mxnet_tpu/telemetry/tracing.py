"""End-to-end causal tracing: trace/span trees over the telemetry spine.

PR 9's registry records *aggregate* metrics (a TTFT histogram, a
step_ms gauge) but nothing causally links one request's life (router
admission -> queue -> prefill chunk(s) -> decode boundaries -> finish)
or one training step's phases (prepare -> h2d -> dispatch -> commit).
The MLPerf TPU-pod analysis (arXiv:1909.09756) and the
concurrency-limits study (arXiv:2011.03641) attribute their wins to
exactly this per-phase timeline attribution — you cannot close an MFU
gap or a p99 tail you cannot decompose.  This module is that timeline:

- **spans** with deterministic per-process ids (monotonic counters —
  two identical runs produce identical trees, the twin-request gate in
  tests/test_tracing.py), a ``trace`` id (the root span's id), a
  ``parent`` id, ``[t0, t1]`` stamps from an injectable clock, and
  JSON-able ``args``;
- **ambient context** per thread (:func:`span` nests automatically)
  with EXPLICIT cross-thread propagation — :func:`capture` on the
  owning thread, :func:`activate` on the worker (``DevicePrefetcher``,
  router replica workers, the async checkpoint writer all do this), so
  a span started on a worker thread parents under the trace that
  spawned the work;
- **manual spans** (:func:`start` / :func:`finish` / :func:`record`)
  for lifecycles that cross call boundaries — a serving request's root
  span lives on the ``Request`` object from admission to finish,
  surviving a drain-and-requeue hop across replicas;
- **Chrome-trace/perfetto export** (:func:`chrome_trace`): finished
  spans as complete ``"X"`` events merged with the existing
  ``profiler.record_span`` B/E stream — one timeline for both
  (``tools/telemetry_dump.py --trace out.json``).

``MXTPU_TRACE=0`` is a bitwise-inert kill switch in the PR 9 style:
every helper is one module-bool check, :func:`span` hands back one
shared no-op context manager, and nothing allocates.  The ring is
bounded by ``MXTPU_TRACE_RING`` (default 4096 finished spans).  Span
taxonomy and the export workflow: docs/OBSERVABILITY.md §Tracing.
"""
from __future__ import annotations

import os
import threading
import time
from collections import deque

from ..lint import racecheck as _racecheck

__all__ = ["Span", "enabled", "configure", "configure_from_env",
           "reset", "clock", "span", "start", "finish", "record",
           "current", "capture", "activate", "spans", "dropped",
           "chrome_trace"]


def _env_enabled():
    return os.environ.get("MXTPU_TRACE", "1") != "0"


def _env_ring():
    try:
        return max(1, int(os.environ.get("MXTPU_TRACE_RING", "4096")))
    except ValueError:
        return 4096


class Span:
    """One timed, named node of a trace tree.  ``trace`` is the root
    span's id; ``parent`` is None on roots.  ``t1`` is None while the
    span is open (open spans never export)."""

    __slots__ = ("name", "trace", "span", "parent", "t0", "t1",
                 "thread", "args")

    def __init__(self, name, trace, span_id, parent, t0, args):
        self.name = name
        self.trace = trace
        self.span = span_id
        self.parent = parent
        self.t0 = t0
        self.t1 = None
        self.thread = threading.current_thread().name
        self.args = args

    def to_record(self):
        return {"name": self.name, "trace": self.trace,
                "span": self.span, "parent": self.parent,
                "t0": self.t0, "t1": self.t1, "thread": self.thread,
                "args": dict(self.args)}


class _NullSpan:
    """The disabled-mode span: one shared instance, every method a
    no-op, usable as a context manager and as a ``parent=``."""

    __slots__ = ()
    name = trace = span = parent = t0 = t1 = None
    args = {}

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NULL_SPAN = _NullSpan()


class Tracer:
    """The process-wide span store: deterministic id counter, bounded
    finished-span ring, per-thread ambient span stack."""

    def __init__(self, ring_size=4096, now=None):
        self.ring_size = int(ring_size)
        self._now = now if now is not None else time.perf_counter
        self._lock = _racecheck.make_lock("telemetry.Tracer._lock")
        self._ring = deque(maxlen=self.ring_size)   # guarded-by: _lock
        self._next_id = 0                           # guarded-by: _lock
        self._dropped = 0                           # guarded-by: _lock
        self._tls = threading.local()               # per-thread ambient

    # -- ids / ambient ---------------------------------------------------
    def _new_id(self):
        with self._lock:
            self._next_id += 1
            return self._next_id

    def _stack(self):
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def current(self):
        st = self._stack()
        return st[-1] if st else None

    # -- span lifecycle --------------------------------------------------
    def start(self, name, parent=None, **args):
        """Open a span (NOT pushed as ambient — the manual API for
        lifecycles that cross call boundaries).  ``parent`` defaults to
        the ambient span; a root span's ``trace`` is its own id."""
        if parent is None:
            parent = self.current()
        sid = self._new_id()
        if parent is None or parent is NULL_SPAN:
            return Span(name, sid, sid, None, self._now(), args)
        return Span(name, parent.trace, sid, parent.span, self._now(),
                    args)

    def finish(self, sp, **args):
        """Stamp ``t1`` and commit ``sp`` to the ring.  Idempotent on
        the null span and on already-finished spans."""
        if sp is None or sp is NULL_SPAN or sp.t1 is not None:
            return sp
        sp.t1 = self._now()
        if args:
            sp.args.update(args)
        self._commit(sp.to_record())
        return sp

    def _commit(self, rec):
        """Append a finished record, counting the oldest entry a full
        ring silently evicts — a truncated timeline must be VISIBLY
        truncated (``telemetry.trace.dropped_spans``, and
        :func:`chrome_trace` stamps the count into its output)."""
        with self._lock:
            evicting = len(self._ring) == self.ring_size
            self._ring.append(rec)
            if evicting:
                self._dropped += 1
        if evicting:
            from . import inc       # outside _lock; one counter bump
            inc("telemetry.trace.dropped_spans")

    def record(self, name, t0, t1, parent=None, **args):
        """Commit an already-timed ``[t0, t1]`` span in one call (the
        pre-timed form: decode boundaries, prefetcher stage times)."""
        if parent is None:
            parent = self.current()
        sid = self._new_id()
        if parent is None or parent is NULL_SPAN:
            sp = Span(name, sid, sid, None, t0, args)
        else:
            sp = Span(name, parent.trace, sid, parent.span, t0, args)
        sp.t1 = t1
        self._commit(sp.to_record())
        return sp

    def push(self, sp):
        self._stack().append(sp)

    def pop(self, sp):
        st = self._stack()
        if st and st[-1] is sp:
            st.pop()

    def spans(self):
        """Finished spans, oldest first (copies — the ring moves on)."""
        with self._lock:
            return [dict(r) for r in self._ring]

    def dropped(self):
        """Spans the bounded ring has evicted since the last reset."""
        with self._lock:
            return self._dropped

    def reset(self):
        with self._lock:
            self._ring.clear()
            self._next_id = 0
            self._dropped = 0
        # the calling thread's ambient stack; other threads' stacks die
        # with their work
        self._tls = threading.local()


_ENABLED = _env_enabled()
_TRACER = Tracer(ring_size=_env_ring())


def configure(enabled=None, ring_size=None, now=None):
    """Reconfigure tracing (tests; production configures via env).
    ``now`` injects the span clock — the FakeClock seam the
    twin-request determinism gate uses."""
    global _ENABLED, _TRACER
    if enabled is not None:
        _ENABLED = bool(enabled)
    if ring_size is not None or now is not None:
        _TRACER = Tracer(
            ring_size=ring_size if ring_size is not None
            else _TRACER.ring_size,
            now=now if now is not None else _TRACER._now)
    return _ENABLED


def configure_from_env():
    return configure(enabled=_env_enabled(), ring_size=_env_ring())


def enabled():
    """Whether tracing is live (``MXTPU_TRACE`` != 0).  Hot paths check
    this ONCE and skip their clock reads entirely when off — the
    zero-overhead contract."""
    return _ENABLED


def clock():
    """The tracer's span clock (perf_counter unless injected)."""
    return _TRACER._now()


class _Scope:
    """The ambient context-manager span: child of the current ambient
    span, itself ambient for the scope's duration."""

    __slots__ = ("_sp",)

    def __init__(self, name, args):
        self._sp = _TRACER.start(name, **args)

    def __enter__(self):
        _TRACER.push(self._sp)
        return self._sp

    def __exit__(self, *exc):
        _TRACER.pop(self._sp)
        _TRACER.finish(self._sp)
        return False


def span(name, **args):
    """Scoped span: ``with tracing.span("train.step", step=i): ...`` —
    nests under the ambient span and is ambient inside the scope."""
    if not _ENABLED:
        return NULL_SPAN
    return _Scope(name, args)


def start(name, parent=None, **args):
    """Open a manual span (see :meth:`Tracer.start`); finish it with
    :func:`finish`.  Returns the shared null span when disabled."""
    if not _ENABLED:
        return NULL_SPAN
    return _TRACER.start(name, parent=parent, **args)


def finish(sp, **args):
    if not _ENABLED:
        return sp
    return _TRACER.finish(sp, **args)


def record(name, t0, t1, parent=None, **args):
    """Commit a pre-timed span (no-op when disabled)."""
    if not _ENABLED:
        return NULL_SPAN
    return _TRACER.record(name, t0, t1, parent=parent, **args)


def current():
    """The ambient span on THIS thread (None when none or disabled)."""
    if not _ENABLED:
        return None
    return _TRACER.current()


def capture():
    """Snapshot the ambient span for hand-off to a worker thread:
    ``ctx = tracing.capture()`` on the owner, ``with
    tracing.activate(ctx):`` on the worker — spans the worker opens
    then parent under the owner's trace."""
    if not _ENABLED:
        return None
    return _TRACER.current()


class _Activation:
    __slots__ = ("_ctx", "_pushed")

    def __init__(self, ctx):
        self._ctx = ctx
        self._pushed = False

    def __enter__(self):
        if _ENABLED and self._ctx is not None \
                and self._ctx is not NULL_SPAN:
            _TRACER.push(self._ctx)
            self._pushed = True
        return self._ctx

    def __exit__(self, *exc):
        if self._pushed:
            _TRACER.pop(self._ctx)
        return False


def activate(ctx):
    """Install a :func:`capture`\\ d span as this thread's ambient
    context for the scope's duration (worker-thread half of the
    propagation hand-shake).  Safe with ``ctx=None`` (no-op)."""
    return _Activation(ctx)


def spans():
    """Finished span records, oldest first ([] when disabled)."""
    if not _ENABLED:
        return []
    return _TRACER.spans()


def dropped():
    """Finished spans the bounded ring evicted since the last reset
    (0 when disabled) — the visible-truncation counter (ISSUE 15)."""
    if not _ENABLED:
        return 0
    return _TRACER.dropped()


def reset():
    """Fresh tracer: empty ring, id counter at zero, DEFAULT clock, env
    kill switch re-read (the conftest between-tests seam) — a test that
    injected a FakeClock or disabled tracing can't leak either."""
    global _ENABLED, _TRACER
    _ENABLED = _env_enabled()
    _TRACER = Tracer(ring_size=_env_ring())


# -- export -------------------------------------------------------------

def _span_event(r, pid, tid):
    return {
        "name": r["name"], "ph": "X", "pid": pid, "tid": tid,
        "ts": r["t0"] * 1e6,
        "dur": max(0.0, (r["t1"] - r["t0"]) * 1e6),
        "args": dict(r["args"], trace=r["trace"], span=r["span"],
                     parent=r["parent"]),
    }


def _fleet_chrome_trace(fleet):
    """Per-rank process lanes over a fleet snapshot's stitched span
    rings (ISSUE 15): ``pid`` = rank, threads keep their lanes inside
    each rank.  Span ids are per-process — cross-worker linkage rides
    the ``remote_trace``/``remote_span`` args the PS RPC context
    wrapper stamped server-side.  The estimated per-rank clock offset
    is DISCLOSED as a lane label and in ``otherData`` — timestamps are
    never shifted (the scrape round-trip bounds the estimate; shifting
    would fake a precision the estimate does not have)."""
    events, meta = [], []
    dropped = {}
    offsets = {}
    for rank_s, row in sorted((fleet.get("per_rank") or {}).items(),
                              key=lambda kv: int(kv[0])):
        pid = int(rank_s)
        off = row.get("clock_offset_est_s")
        offsets[rank_s] = off
        if row.get("dropped_spans"):
            dropped[rank_s] = row["dropped_spans"]
        meta.append({"name": "process_name", "ph": "M", "pid": pid,
                     "tid": 0, "args": {"name": f"rank {pid}"}})
        meta.append({"name": "process_labels", "ph": "M", "pid": pid,
                     "tid": 0, "args": {"labels":
                     ("scrape failed: " + str(row.get("error"))
                      if not row.get("ok") else
                      f"clock_offset_est_s={off} "
                      f"(disclosed estimate; NOT applied)")}})
        tids = {}
        for r in row.get("spans") or []:
            tid = tids.setdefault(r["thread"], len(tids))
            events.append(_span_event(r, pid, tid))
        meta.extend({"name": "thread_name", "ph": "M", "pid": pid,
                     "tid": tid, "args": {"name": thread}}
                    for thread, tid in tids.items())
    return {"traceEvents": meta + events,
            "otherData": {"fleet_schema_version":
                          fleet.get("fleet_schema_version"),
                          "clock_offset_est_s": offsets,
                          "dropped_spans": dropped}}


def chrome_trace(include_profiler=True, fleet=None):
    """The merged Chrome-trace JSON object: every finished tracing span
    as a complete ``"X"`` event (ts/dur in microseconds, ``args``
    carrying trace/span/parent ids for perfetto correlation) plus —
    when ``include_profiler`` — the ``profiler.record_span`` B/E event
    stream, so XLA-adjacent pipeline spans and causal request/step
    spans land on ONE timeline.  With ``fleet`` (a
    :meth:`~.fleet.FleetCollector.collect` snapshot) the export is the
    STITCHED multi-worker timeline instead: one process lane per rank,
    clock offsets disclosed, never applied.  ``otherData`` stamps the
    ring's drop count so a truncated timeline is visibly truncated.
    Valid input for chrome://tracing and https://ui.perfetto.dev."""
    if fleet is not None:
        return _fleet_chrome_trace(fleet)
    pid = os.getpid()
    events = []
    tids = {}
    for r in spans():
        tid = tids.setdefault(r["thread"], len(tids))
        events.append(_span_event(r, pid, tid))
    if include_profiler:
        from .. import profiler
        ptid = len(tids)
        for name, ph, ts, extra in profiler._STATE["events"]:
            ev = {"name": name, "ph": ph, "ts": ts * 1e6, "pid": pid,
                  "tid": ptid}
            ev.update(extra)
            events.append(ev)
    meta = [{"name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
             "args": {"name": thread}} for thread, tid in tids.items()]
    from . import events_dropped
    return {"traceEvents": meta + events,
            "otherData": {"dropped_spans": dropped(),
                          "dropped_events": events_dropped()}}
