"""Prometheus text-format rendering of a telemetry snapshot.

One pure function over the JSON snapshot (no registry access), so the
same renderer serves the live path (``mx.telemetry.prom_text()``, the
PS server's ``_OP_TELEMETRY`` RPC) and the offline path
(``tools/telemetry_dump.py`` over a flight-recorder file).

Metric names are sanitized to the Prometheus grammar: ``mxtpu_`` prefix,
dots/dashes to underscores.  Histograms render as the conventional
cumulative ``_bucket{le="..."}`` series plus ``_sum``/``_count``.
"""
from __future__ import annotations

__all__ = ["prom_text", "sanitize_name"]


def sanitize_name(name):
    out = []
    for ch in name:
        out.append(ch if (ch.isalnum() or ch == "_") else "_")
    s = "".join(out)
    if not s.startswith("mxtpu_"):
        s = "mxtpu_" + s
    return s


def _fmt(v):
    if v is None:
        return "NaN"
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, float):
        return repr(v)
    return str(v)


def prom_text(snap):
    lines = []
    if not snap.get("enabled", True):
        return "# telemetry disabled (MXTPU_TELEMETRY=0)\n"
    for name, v in (snap.get("counters") or {}).items():
        n = sanitize_name(name)
        lines.append(f"# TYPE {n} counter")
        lines.append(f"{n} {_fmt(v)}")
    for name, v in (snap.get("gauges") or {}).items():
        n = sanitize_name(name)
        lines.append(f"# TYPE {n} gauge")
        lines.append(f"{n} {_fmt(v)}")
    for name, h in (snap.get("histograms") or {}).items():
        n = sanitize_name(name)
        lines.append(f"# TYPE {n} histogram")
        cum = 0
        for edge, c in zip(h["edges"], h["counts"]):
            cum += c
            lines.append(f'{n}_bucket{{le="{edge}"}} {cum}')
        cum += h["counts"][-1]
        lines.append(f'{n}_bucket{{le="+Inf"}} {cum}')
        lines.append(f"{n}_sum {_fmt(h['sum'])}")
        lines.append(f"{n}_count {h['count']}")
    ctx = snap.get("context") or {}
    for k, v in sorted(ctx.items()):
        n = sanitize_name(f"context.{k}")
        lines.append(f"# TYPE {n} gauge")
        lines.append(f"{n} {_fmt(v)}")
    return "\n".join(lines) + "\n"
