"""``mx.model`` — legacy model-layer helpers.

Reference: python/mxnet/model.py — home of ``save_checkpoint`` /
``load_checkpoint`` (the canonical checkpoint functions every tutorial
calls), ``BatchEndParam`` (the namedtuple handed to batch callbacks),
and the deprecated ``FeedForward`` estimator.

The living implementations sit with Module (module/module.py); this
module keeps the reference import paths working. ``FeedForward`` was
deprecated in the reference well before the fork point with the
instruction to use Module — here that deprecation is terminal: the
class raises with the Module migration recipe instead of shipping a
second training loop.
"""
from __future__ import annotations

from .base import MXNetError
from .module.module import (BatchEndParam, load_checkpoint,
                            save_checkpoint_arrays)

__all__ = ["BatchEndParam", "load_checkpoint", "save_checkpoint",
           "FeedForward"]


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params,
                    remove_amp_cast=True):
    """Reference mx.model.save_checkpoint(prefix, epoch, sym, args, aux):
    writes prefix-symbol.json + prefix-NNNN.params."""
    save_checkpoint_arrays(prefix, epoch, symbol, arg_params, aux_params)


class FeedForward:
    """Deprecated in the reference (mx.model.FeedForward -> mx.mod.Module);
    kept as a named landing spot with the migration recipe."""

    def __init__(self, *args, **kwargs):
        raise MXNetError(
            "FeedForward was deprecated in the reference in favor of "
            "mx.mod.Module, which this framework implements in full: "
            "Module(symbol, data_names, label_names).fit(train_iter, "
            "eval_data=..., num_epoch=...). See docs/MIGRATION.md.")

    create = __init__
    load = __init__
