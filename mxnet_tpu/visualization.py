"""``mx.viz`` — network visualization.

Reference: python/mxnet/visualization.py (plot_network via graphviz,
print_summary). Works on the Symbol facade graph and on Gluon blocks.
"""
from __future__ import annotations

from .base import MXNetError

__all__ = ["print_summary", "plot_network"]


def print_summary(symbol, shape=None, line_length=120, positions=None):
    from .symbol.symbol import Symbol, _collect_nodes
    if not isinstance(symbol, Symbol):
        raise MXNetError("print_summary expects a Symbol")
    nodes = _collect_nodes(symbol)
    print("=" * line_length)
    print(f"{'Layer (type)':<50}{'Op':<30}Inputs")
    print("=" * line_length)
    for node in nodes:
        ins = ", ".join(a._name for a in node._args
                        if isinstance(a, Symbol))
        print(f"{node._name:<50}{node._op or 'null':<30}{ins}")
    print("=" * line_length)


def plot_network(symbol, title="plot", save_format="pdf", shape=None,
                 node_attrs=None, hide_weights=True):
    """Returns a graphviz Digraph if graphviz is installed, else a DOT
    string (no hard dependency)."""
    from .symbol.symbol import Symbol, _collect_nodes
    nodes = _collect_nodes(symbol)
    lines = ["digraph plot {"]
    for node in nodes:
        lines.append(f'  "{node._name}" [label="{node._name}\\n'
                     f'{node._op or "var"}"];')
        for a in node._args:
            if isinstance(a, Symbol):
                if hide_weights and a._op is None and \
                        a._name.endswith(("weight", "bias", "gamma", "beta")):
                    continue
                lines.append(f'  "{a._name}" -> "{node._name}";')
    lines.append("}")
    dot_src = "\n".join(lines)
    try:
        import graphviz
        return graphviz.Source(dot_src)
    except ImportError:
        return dot_src
