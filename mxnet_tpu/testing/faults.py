"""Deterministic fault injection for the fault-tolerance layer.

The recovery code paths (torn-checkpoint skip, writer-failure surfacing,
heartbeat death, preemption save) are exactly the paths a normal run
never exercises.  This module lets tests — and the ``--chaos`` smoke
mode of ``tools/tpu_queue_runner.py`` — provoke each failure on purpose
and deterministically (no wall-clock races, no real SIGKILL needed).

Instrumented code calls :func:`fault_point` at named sites::

    faults.fault_point("checkpoint.write", payload=path)

which is a single module-bool check when nothing is armed (safe on warm
paths).  Tests arm a site with the :func:`inject` context manager::

    with faults.inject("checkpoint.write", exc=OSError("disk full")):
        mgr.save(...)          # the writer thread dies with OSError

or with a callable action (e.g. :func:`truncate_file` /
:func:`corrupt_file` against the payload), firing on hit ``at`` (1-based)
for ``times`` consecutive hits.

Subprocesses (chaos mode) arm sites through the env hook::

    MXTPU_FAULT_INJECT="checkpoint.write:at=1,train.step:at=3:mode=preempt"

Fault points currently instrumented:

==========================  ===============================================
site                        payload / effect
==========================  ===============================================
``checkpoint.write``        path being written; raise -> writer thread dies
``checkpoint.manifest``     manifest path, fired BEFORE the atomic
                            ``os.replace`` -> torn checkpoint on raise
``checkpoint.d2h``          array name during the device->host snapshot
``ndarray.d2h``             raise on any ``asnumpy()`` D2H copy
``ps.heartbeat.drop``       heartbeat send suppressed (silent worker)
``train.step``              global step index; ``mode=preempt`` delivers a
                            simulated preemption signal at step K
``elastic.reshard``         attempt index during an elastic reshard's
                            peer-to-peer state transfer; raise -> the
                            transfer dies mid-flight and the controller
                            falls back to the newest valid checkpoint
``serving.replica<i>.step`` boundary counter of serving-router replica
                            ``i``; raise -> the replica dies mid-traffic
                            and the router drains + requeues its
                            requests (``--chaos serving``)
==========================  ===============================================
"""
from __future__ import annotations

import os
import threading
from contextlib import contextmanager

from ..base import MXNetError

__all__ = ["FaultInjected", "inject", "fault_point", "active", "reset",
           "truncate_file", "corrupt_file", "FakeClock"]


class FaultInjected(MXNetError):
    """Default exception raised by an armed fault point."""


_lock = threading.Lock()
_active = {}           # name -> _Fault
_armed = False         # fast-path guard: False => fault_point is a no-op
_env_parsed = False


class _Fault:
    __slots__ = ("name", "exc", "action", "at", "times", "hits", "fired")

    def __init__(self, name, exc=None, action=None, at=1, times=None):
        self.name = name
        self.exc = exc
        self.action = action
        self.at = int(at)
        self.times = None if times is None else int(times)
        self.hits = 0
        self.fired = 0

    def should_fire(self):
        self.hits += 1
        if self.hits < self.at:
            return False
        if self.times is not None and self.fired >= self.times:
            return False
        self.fired += 1
        return True


def _rearm():
    global _armed
    _armed = bool(_active)


def _parse_env():
    """``MXTPU_FAULT_INJECT="site:at=K:times=N:mode=raise|preempt|drop"``
    (comma-separated specs).  Parsed once; subprocess-friendly — the
    chaos runner arms its children this way."""
    global _env_parsed
    _env_parsed = True
    spec = os.environ.get("MXTPU_FAULT_INJECT", "").strip()
    if not spec:
        return
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        fields = part.split(":")
        name = fields[0]
        kw = {}
        for f in fields[1:]:
            k, _, v = f.partition("=")
            kw[k.strip()] = v.strip()
        mode = kw.get("mode", "raise")
        action = None
        exc = None
        if mode == "preempt":
            action = _preempt_action
        elif mode == "drop":
            action = _drop_action
        else:
            exc = FaultInjected(f"injected fault at {name!r} "
                                f"(MXTPU_FAULT_INJECT)")
        with _lock:
            _active[name] = _Fault(name, exc=exc, action=action,
                                   at=int(kw.get("at", 1)),
                                   times=(int(kw["times"])
                                          if "times" in kw else None))
    _rearm()


def _preempt_action(payload):
    """Deliver a simulated preemption: flips the installed
    :class:`~mxnet_tpu.checkpoint.PreemptionHandler` (graceful, exactly
    what a SIGTERM handler would do) — or raises if none is installed,
    so an unguarded loop cannot silently ignore the fault."""
    from .. import checkpoint as _ckpt
    handler = _ckpt.PreemptionHandler.installed()
    if handler is None:
        raise FaultInjected(
            "simulated preemption fired but no PreemptionHandler is "
            "installed (wrap the loop in run_preemptible / install())")
    handler.request(reason=f"injected preemption (payload={payload!r})")


#: public alias — arm with ``inject("train.step", at=K,
#: action=preempt_action)`` to deliver a simulated preemption at step K
def preempt_action(payload):
    return _preempt_action(payload)


def _drop_action(payload):
    """Swallow the instrumented side effect (used by heartbeat sends):
    the fault point returns True and the caller skips the send."""
    return "drop"


def fault_point(name, payload=None):
    """Instrumentation hook.  No-op (one bool check) unless a fault is
    armed for ``name``.  Returns ``"drop"`` when the armed fault says to
    suppress the caller's side effect; raises the armed exception for
    ``exc`` faults; runs (and returns the result of) callable actions.

    ``payload`` gives the action something to chew on (a path to
    corrupt, a step index); for ``at=K`` matching against an integer
    payload (step counters), K is compared against the payload rather
    than the hit count — "preempt at step 3" means step 3, however many
    times the point is hit before that.
    """
    if not _armed:
        if not _env_parsed:
            _parse_env()
            if not _armed:
                return None
        else:
            return None
    with _lock:
        f = _active.get(name)
        if f is None:
            return None
        if isinstance(payload, int) and f.at > 1:
            # step-indexed matching: fire exactly when payload reaches at
            if payload < f.at or \
                    (f.times is not None and f.fired >= f.times):
                f.hits += 1
                return None
            f.fired += 1
        elif not f.should_fire():
            return None
        exc, action = f.exc, f.action
    # the fault IS firing: record the trip + dump the flight recorder
    # BEFORE the exception/action changes control flow (ISSUE 9) — the
    # dump's last event is this trip, payload = the failing step/path.
    # Outside the lock: telemetry has its own locks and never calls
    # back into this module.
    from .. import telemetry as _telem
    _telem.on_fault(name, payload)
    if action is not None:
        return action(payload)
    raise exc if exc is not None else FaultInjected(
        f"injected fault at {name!r}")


@contextmanager
def inject(name, exc=None, action=None, at=1, times=None):
    """Arm fault point ``name`` for the scope's duration.

    ``exc``: exception instance to raise at the point (default
    :class:`FaultInjected` if no action given).  ``action``: callable
    run with the point's payload instead of raising (return ``"drop"``
    to suppress the caller's side effect), or the string ``"drop"`` as
    shorthand for the suppress action.  ``at``: 1-based hit index (or
    step index for integer payloads) to start firing.  ``times``: fire
    at most N times (default: every hit from ``at`` on).
    """
    if action == "drop":
        action = _drop_action
    if exc is None and action is None:
        exc = FaultInjected(f"injected fault at {name!r}")
    f = _Fault(name, exc=exc, action=action, at=at, times=times)
    with _lock:
        prev = _active.get(name)
        _active[name] = f
    _rearm()
    try:
        yield f
    finally:
        with _lock:
            if prev is None:
                _active.pop(name, None)
            else:
                _active[name] = prev
        _rearm()


def active():
    """Names of currently armed fault points (test introspection)."""
    with _lock:
        return sorted(_active)


def reset():
    """Disarm everything (incl. env-armed faults; env re-parses only on
    the next interpreter, not the next call)."""
    with _lock:
        _active.clear()
    _rearm()


# -- ready-made destructive actions (checkpoint corruption) -------------

def truncate_file(path, keep_bytes=16):
    """Truncate ``path`` to ``keep_bytes`` — a torn write."""
    with open(path, "r+b") as f:
        f.truncate(keep_bytes)


def corrupt_file(path, offset=-64, nbytes=32):
    """Flip a span of bytes in ``path`` (default: 32 bytes near the
    end, inside the tensor payload) — CRC must catch it."""
    size = os.path.getsize(path)
    off = offset if offset >= 0 else max(0, size + offset)
    with open(path, "r+b") as f:
        f.seek(off)
        span = f.read(nbytes)
        f.seek(off)
        f.write(bytes(b ^ 0xFF for b in span))


class FakeClock:
    """Controllable clock for deterministic timeout tests (the PS
    heartbeat death path).  Callable like ``time.time``."""

    def __init__(self, start=1_000_000.0):
        self._t = float(start)
        self._lock = threading.Lock()

    def __call__(self):
        with self._lock:
            return self._t

    def advance(self, dt):
        with self._lock:
            self._t += float(dt)
            return self._t
