"""Kill-and-resume chaos smoke: the fault-tolerance layer end to end.

``python -m mxnet_tpu.testing.chaos`` (or ``tools/tpu_queue_runner.py
--chaos``) runs, on the simulated CPU mesh, the exact scenario the
acceptance bar demands — in one process, deterministically:

1. **Reference run**: N training steps, uninterrupted; final params +
   optimizer state recorded.
2. **Chaos run**: same seed/data.  The checkpoint writer is killed on
   its first attempt (the save must survive via the next one), a
   simulated preemption fires at step K, the preemption save goes
   through, and the newest checkpoint is then CORRUPTED on disk — so
   resume must fall back to the previous valid one and replay forward.
3. **Resume**: a fresh net/trainer auto-resumes from ``latest()``
   (skipping the corrupted checkpoint), trains to N total steps, and
   must match the reference run BITWISE (params and optimizer state).

Runs the scenario twice: plain ``gluon.Trainer`` and
``DataParallelTrainer(shard_updates=True)``.  Prints one JSON verdict
line; exit code 0 only if every check passed.

``python -m mxnet_tpu.testing.chaos elastic`` (or ``tools/
tpu_queue_runner.py --chaos elastic``) runs the ELASTIC MEMBERSHIP
scenarios instead (ISSUE 8) — kill/join workers mid-run and demand
bitwise continuation parity, all on the simulated 8-device CPU mesh
with a ``FakeClock`` (zero sleeps):

- ``shrink``  — PS heartbeats stop for worker 1 at step K; the server's
  ``_scan_dead`` commits the death into the membership, the controller
  pauses at the boundary, reshards dp 8 -> 4 peer-to-peer, resumes.
  Final fp32 params + optimizer state must be BITWISE a fresh dp=4
  process restored from the same boundary state.
- ``grow``    — worker 1 announces a join at step K' (epoch-checked),
  the controller admits it at the boundary: dp 4 -> 8, same parity bar
  against a fresh dp=8 process.
- ``reshard_fault`` — the death fires at K but the peer transfer
  itself is killed (``elastic.reshard`` fault point, every retry): the
  controller falls back to the newest valid checkpoint, training
  rewinds to its step and replays at dp=4 — parity against a fresh
  process restored from that same checkpoint.

``python -m mxnet_tpu.testing.chaos serving`` (or ``tools/
tpu_queue_runner.py --chaos serving``) runs the SERVING FRONT-END
scenario instead (ISSUE 12), deterministic on CPU with a FakeClock and
zero sleeps: a 2-replica ``serving.frontend.Router`` (prefix cache +
chunked prefill on, shared warmup compile cache) serves a
shared-system-prompt mix; replica 1 is killed mid-traffic via the
``serving.replica1.step`` fault point; the router must bump the
replica-set epoch, drain and REQUEUE the dead replica's in-flight
requests, and finish every request exactly once with the exact token
stream a solo cold-path engine produces (greedy decode is
deterministic and the prefix path is bitwise the cold path).  The kill
must leave a parseable flight-recorder dump, racecheck must report
zero findings, and the surviving replica's KV pool must pass the leak
sweep (prefix-chain holds accounted).

``python -m mxnet_tpu.testing.chaos disagg`` runs the DISAGGREGATED
prefill/decode scenario (ISSUE 18): a 4-replica fleet (prefill rids
0/2, decode rids 1/3) over ONE shared ``PagedKVCache`` serves a mixed
prompt set; a prefill replica is killed mid-handoff via the
``serving.replica0.handoff`` fault point (between "prefill finished"
and "decode adopted" — the worst spot for the adopt-then-release
block-ownership protocol) and, in a second pass, a decode replica is
killed at a scheduling boundary.  Every request must finish exactly
once with the solo combined-role token stream, zero compiles after
warmup, and the shared pool must pass the leak sweep on the survivors.

``python -m mxnet_tpu.testing.chaos autoscale`` (or ``tools/
tpu_queue_runner.py --chaos autoscale``) runs the PRODUCTION-ELASTICITY
scenario (ISSUE 13), deterministic on the CPU mesh with a FakeClock and
zero sleeps: a preemption NOTICE for training worker 1 drains it at a
step boundary AHEAD of the heartbeat timeout (checkpoint-then-reshard
dp 8 -> 4), the degradation ladder sheds serving admissions while
capacity is below target, the notice is then REVOKED (maintenance
cancelled) and the load-based autoscaler grows dp back 4 -> 8 through
the same epoch-fenced resync — with params + optimizer state BITWISE a
fresh restore at EACH intermediate dp.  On the serving side a notice
drains a router replica mid-traffic (zero lost/duplicated requests,
identical-prompt streams bitwise-equal) and the serving autoscaler
adds a replacement replica from the shared compile cache (zero new
compiles).  Every injected notice leaves a parseable flight dump;
racecheck is armed; the KV pools pass the leak sweep.

``python -m mxnet_tpu.testing.chaos watchdog`` (or ``tools/
tpu_queue_runner.py --chaos watchdog``) runs the RUN-HEALTH scenario
(ISSUE 14): a NaN loss injected through the ``watchdog.loss`` fault
point and a FakeClock step stall must each emit a typed ``watchdog.*``
event and dump the flight recorder with ``reason="watchdog:<rule>"``.

``python -m mxnet_tpu.testing.chaos fleet`` (or ``tools/
tpu_queue_runner.py --chaos fleet``) runs the FLEET-OBSERVABILITY
scenario (ISSUE 15): N simulated workers (per-rank metric registries —
exactly what a remote ``PSClient.telemetry()`` scrape returns) stepped
under ONE FakeClock with zero sleeps, one injected straggler (its
steps run long via the ``fleet.straggle`` fault-point clock advance)
and one scrape-dead rank (its transport raises).  The
``FleetCollector`` must name BOTH ranks in typed ``fleet.straggler`` /
``fleet.scrape_dead`` events with flight dumps whose reason carries
the rule, the merged histograms must equal the element-wise per-rank
bucket sums bitwise, and racecheck must report zero findings on the
collector locks.

``python -m mxnet_tpu.testing.chaos procs`` (or ``tools/
tpu_queue_runner.py --chaos procs``) runs the MULTI-PROCESS scenario
(ISSUE 19) — the only suite with real processes instead of threads
under FakeClock: a 4-process pod over ``jax.distributed`` (the
``mxnet_tpu.pod.PodLauncher`` runtime), one worker SIGKILLed while the
whole pod is parked at a step gate.  The launcher must commit the
membership change, the survivors must tear down + re-init the JAX
coordination service at ``jax.process_count() == 3`` and resume from
the shared checkpoint BITWISE a fresh 3-process pod restored from the
same checkpoint, the file-lease request ledger must end exactly-once
(the victim's held lease requeued), and a real fleet scrape over the
workers' PS endpoints must name the dead rank typed with ``rpc.*``
counters and a flight dump behind it.

``python -m mxnet_tpu.testing.chaos all`` runs all eight suites.
"""
from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile

import numpy as _np


def _racecheck_arm():
    """Run the scenario under the runtime race/lock-order detector
    (ISSUE 10): every chaos interleaving doubles as a concurrency test.
    ``MXTPU_RACECHECK=0`` is the explicit opt-out; otherwise the
    detector is enabled for the scenario regardless of ambient env, so
    the tier-1 chaos tests always exercise it."""
    from mxnet_tpu.lint import racecheck
    if os.environ.get("MXTPU_RACECHECK", "") == "0":
        return None
    racecheck.reset()               # this scenario's findings only
    racecheck.configure(enabled=True)
    return racecheck


def _racecheck_verdict(rc):
    """Post-scenario gate: zero findings, or the scenario fails."""
    if rc is None:
        return None
    found = rc.findings()
    return {"enabled": True, "findings": len(found),
            "kinds": sorted({f["kind"] for f in found}),
            "ok": not found}


def _donation_arm():
    """Run the scenario under the use-after-donate sentinel (ISSUE 16):
    every chaos interleaving doubles as a donation-correctness test —
    the trainer/engine seams poison their donated buffers and any stale
    host touch fails the scenario the way a TPU run would crash.
    ``MXTPU_DONATION_CHECK=0`` is the explicit opt-out."""
    from mxnet_tpu.lint import donation
    if os.environ.get("MXTPU_DONATION_CHECK", "") == "0":
        return None
    donation.reset()                # this scenario's findings only
    donation.configure(enabled=True)
    return donation


def _donation_verdict(dc):
    """Post-scenario gate: zero use-after-donate findings, or the
    scenario fails."""
    if dc is None:
        return None
    found = dc.findings()
    return {"enabled": True, "findings": len(found),
            "sites": sorted({f["site"] for f in found}),
            "ok": not found}


def _flight_check(expect_kind=None):
    """Assert the telemetry flight recorder left a parseable dump for
    the kill this scenario just injected (ISSUE 9): the dump must exist,
    parse, carry a metric snapshot, and its LAST event must be the
    incident (``expect_kind`` prefix, e.g. ``"preemption"`` /
    ``"fault.trip"``).  Returns None when telemetry is disabled (nothing
    to assert — the kill switch is a supported mode)."""
    from mxnet_tpu import telemetry
    if not telemetry.enabled():
        return None
    path = telemetry.last_flight_dump()
    out = {"ok": False, "path": path}
    if not path or not os.path.exists(path):
        return out
    try:
        with open(path) as f:
            dump = json.load(f)
    except (OSError, ValueError) as e:
        out["error"] = f"unparseable: {e}"
        return out
    events = dump.get("events") or []
    last = events[-1] if events else {}
    out["reason"] = dump.get("reason")
    out["last_kind"] = last.get("kind")
    out["last_step"] = last.get("step")
    out["ok"] = bool(dump.get("metrics")) and bool(events) and (
        expect_kind is None or str(last.get("kind", "")
                                   ).startswith(expect_kind))
    return out


def _make_data(seed, n_batches=8, batch=16, din=8, dout=4):
    rng = _np.random.RandomState(seed)
    xs = rng.randn(n_batches, batch, din).astype(_np.float32)
    ys = rng.randn(n_batches, batch, dout).astype(_np.float32)
    return xs, ys


def _build(mode, dout=4):
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, parallel
    mx.random.seed(1234)
    _np.random.seed(1234)
    net = gluon.nn.Dense(dout)
    net.initialize()
    loss_fn = gluon.loss.L2Loss()
    if mode == "sharded":
        trainer = parallel.DataParallelTrainer(
            net, loss_fn, "adam", {"learning_rate": 0.05},
            shard_updates=True)

        def step(x, y):
            return trainer.step(mx.nd.array(x), mx.nd.array(y))
    else:
        trainer = gluon.Trainer(net.collect_params(), "adam",
                                {"learning_rate": 0.05})

        def step(x, y):
            from mxnet_tpu import autograd
            xb, yb = mx.nd.array(x), mx.nd.array(y)
            with autograd.record():
                loss = loss_fn(net(xb), yb)
            loss.backward()
            trainer.step(xb.shape[0])
            return loss
    return net, trainer, step


def _params_of(net):
    return {name: p.data().asnumpy()
            for name, p in net._collect_params_with_prefix().items()}


def _state_of(trainer):
    sd = trainer.state_dict()
    return {k: v.asnumpy() for k, v in sd["arrays"].items()}


def _bitwise(a, b):
    return set(a) == set(b) and \
        all(_np.array_equal(a[k], b[k]) for k in a)


def run_scenario(mode, total_steps=6, preempt_at=3, workdir=None,
                 resume_steps_per_call=1):
    """``resume_steps_per_call`` > 1 (ISSUE 6): the RESUME phase drives
    ``step_multi`` windows of that size instead of per-step calls — the
    surviving checkpoint sits at a step that is NOT a multiple of K
    (written mid-scan-window relative to the resumed run's grid), so
    this asserts that a non-K-aligned resume reproduces the K=1
    reference curve bitwise (partial tail windows included).  Needs a
    trainer with ``step_multi`` (the sharded mode)."""
    from mxnet_tpu.base import MXNetError
    from mxnet_tpu.checkpoint import CheckpointManager, run_preemptible
    from mxnet_tpu.testing import faults

    rc = _racecheck_arm()
    dc = _donation_arm()
    k_resume = int(resume_steps_per_call)
    if k_resume > 1 and mode != "sharded":
        raise MXNetError(
            "resume_steps_per_call>1 needs the sharded "
            "(DataParallelTrainer) scenario — gluon.Trainer is eager")
    ckdir = os.path.join(workdir, f"ckpt-{mode}-k{k_resume}")
    xs, ys = _make_data(99)
    result = {"mode": mode, "preempt_at": preempt_at,
              "total_steps": total_steps,
              "resume_steps_per_call": k_resume}

    # 1. reference: uninterrupted
    net, trainer, step = _build(mode)
    for i in range(total_steps):
        step(xs[i], ys[i])
    ref_params, ref_state = _params_of(net), _state_of(trainer)

    # 2. chaos run: writer killed on attempt 1, preempted at step K
    net, trainer, step = _build(mode)
    mgr = CheckpointManager(ckdir, keep=3)
    writer_died = False

    def loop(handler):
        nonlocal writer_died
        for i in range(total_steps):
            step(xs[i], ys[i])
            done = i + 1
            if handler.check_step(done):
                # preemption: force-sync the final checkpoint and stop
                mgr.save(done, params=net, trainer=trainer,
                         iterator={"batch": done}, sync=True)
                return done
            if done == 1:
                # kill THIS save's writer thread; the error must surface
                # on the NEXT save without dropping that next snapshot
                with faults.inject("checkpoint.write", times=1):
                    t1 = mgr.save(done, params=net, trainer=trainer,
                                  iterator={"batch": done})
                    # writer must HIT the armed fault before it disarms;
                    # the error stays unconsumed for the next save
                    t1._done.wait(30)
            else:
                try:
                    ticket = mgr.save(done, params=net, trainer=trainer,
                                      iterator={"batch": done})
                except MXNetError as e:
                    writer_died = True   # previous writer's death
                    ticket = getattr(e, "pending_ticket", None)
                if ticket is not None:
                    ticket.wait()
        return total_steps

    with faults.inject("train.step", at=preempt_at,
                       action=faults.preempt_action):
        preempted, stopped_at = run_preemptible(loop, mgr)
    result["writer_kill_surfaced"] = writer_died
    result["preempted_at"] = stopped_at
    result["preempted"] = preempted
    # the injected kill must have left a flight-recorder post-mortem
    # whose last event IS the preemption (ISSUE 9)
    result["flight_dump"] = _flight_check(expect_kind="preemption")

    # 3. corrupt the newest checkpoint: latest() must skip to an older one
    newest = mgr.latest()
    faults.corrupt_file(os.path.join(
        mgr._step_dir(newest), "params.ndz"))
    fallback = mgr.latest()
    result["corrupt_skipped"] = {"newest": newest, "fallback": fallback,
                                 "ok": fallback is not None
                                 and fallback < newest}

    # 4. resume from the surviving checkpoint, replay to total_steps
    net, trainer, step = _build(mode)
    # resolve shapes before trainer state restore
    import mxnet_tpu as mx
    net(mx.nd.array(xs[0]))
    manifest = mgr.restore(params=net, trainer=trainer)
    start = manifest["iterator"]["batch"]
    result["resumed_from"] = manifest["step"]
    if k_resume > 1:
        # K-step compiled replay from a mid-window checkpoint: windows
        # re-form at the resumed step; the tail window may be short
        i = start
        while i < total_steps:
            w = min(k_resume, total_steps - i)
            trainer.step_multi(
                [(mx.nd.array(xs[j]), mx.nd.array(ys[j]))
                 for j in range(i, i + w)])
            i += w
    else:
        for i in range(start, total_steps):
            step(xs[i], ys[i])
    result["params_bitwise"] = _bitwise(ref_params, _params_of(net))
    result["state_bitwise"] = _bitwise(ref_state, _state_of(trainer))
    fd = result["flight_dump"]
    result["racecheck"] = _racecheck_verdict(rc)
    rcv = result["racecheck"]
    result["donation"] = _donation_verdict(dc)
    dcv = result["donation"]
    result["ok"] = bool(
        result["params_bitwise"] and result["state_bitwise"]
        and result["corrupt_skipped"]["ok"] and preempted
        and writer_died and (fd is None or fd["ok"])
        and (rcv is None or rcv["ok"])
        and (dcv is None or dcv["ok"]))
    return result


# ----------------------------------------------------------------------
# Elastic membership scenarios (ISSUE 8): kill-at-K / join-at-K' with
# bitwise continuation parity, deterministic on the CPU mesh (FakeClock,
# no sleeps).
# ----------------------------------------------------------------------

def _build_elastic(mesh, seed=1234, dout=4):
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, parallel
    mx.random.seed(seed)
    _np.random.seed(seed)
    net = gluon.nn.Dense(dout)
    net.initialize()
    trainer = parallel.DataParallelTrainer(
        net, gluon.loss.L2Loss(), "adam", {"learning_rate": 0.05},
        mesh=mesh, shard_updates=True)
    return net, trainer


def _capture_boundary(net, trainer):
    """Host snapshot of EXACTLY what a fresh process would restore from
    a checkpoint of this instant: params, per-parameter-space optimizer
    state, and both RNG streams."""
    import mxnet_tpu as mx
    from mxnet_tpu.checkpoint import _rng_state
    sd = trainer.state_dict()
    rng_arrays, rng_meta = _rng_state()
    return {
        "params": {n: p.data().asnumpy().copy() for n, p
                   in net._collect_params_with_prefix().items()},
        "sd": {"arrays": {k: mx.nd.array(v.asnumpy())
                          for k, v in sd["arrays"].items()},
               "meta": dict(sd["meta"])},
        "rng": ({k: mx.nd.array(v.asnumpy())
                 for k, v in rng_arrays.items()}, dict(rng_meta)),
    }


def _restore_boundary(net, trainer, snap):
    import mxnet_tpu as mx
    from mxnet_tpu.checkpoint import _restore_rng
    net(mx.nd.array(_np.zeros((1, 8), _np.float32)))   # resolve shapes
    target = net._collect_params_with_prefix()
    for n, v in snap["params"].items():
        target[n].set_data(v)
    trainer.load_state_dict(snap["sd"])
    _restore_rng(*snap["rng"])


def _final_state(net, trainer):
    return ({n: p.data().asnumpy() for n, p
             in net._collect_params_with_prefix().items()},
            {k: v.asnumpy() for k, v in trainer.state_dict()
             ["arrays"].items()})


def _deliver_ps_death(membership, clock, dead_rank=1, num_workers=2):
    """Close the loop THROUGH the PS heartbeat path (not a direct state
    poke): spin a PSServer on the FakeClock, beat both ranks, drop the
    victim's beats, advance past the timeout, and let ``_scan_dead``
    commit the death into the membership."""
    import socket
    from mxnet_tpu.kvstore.ps_server import PSServer, PSClient
    from mxnet_tpu.testing import faults
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    srv = PSServer("127.0.0.1", port, num_workers=num_workers,
                   heartbeat_timeout=5.0)
    srv._now = clock
    srv.attach_membership(membership)
    clients = [PSClient("127.0.0.1", port) for _ in range(num_workers)]
    try:
        for r, c in enumerate(clients):
            c.beat_once(r)
        clock.advance(3.0)
        for r, c in enumerate(clients):
            if r == dead_rank:
                with faults.inject("ps.heartbeat.drop", action="drop"):
                    assert not c.beat_once(r)
            else:
                c.beat_once(r)
        clock.advance(3.0)      # victim silent past the 5 s timeout
        return srv._scan_dead()
    finally:
        for c in clients:
            c.close()
        srv._sock.close()


def run_elastic_scenario(kind="shrink", total_steps=6, event_at=3,
                         workdir=None):
    """One elastic membership scenario; see the module docstring for
    the three kinds.  Deterministic: FakeClock, no sleeps, bitwise
    parity asserted against a fresh-process reference."""
    import mxnet_tpu as mx
    from mxnet_tpu import elastic
    from mxnet_tpu.checkpoint import CheckpointManager
    from mxnet_tpu.parallel.mesh import make_mesh, \
        AXIS_DP as _AXIS_DP
    from mxnet_tpu.testing import faults
    import jax

    rc = _racecheck_arm()
    dc = _donation_arm()
    devices = jax.devices()
    dpw = 4
    ranks = [0] if kind == "grow" else [0, 1]
    dp0 = dpw * len(ranks)
    dp1 = dp0 // 2 if kind != "grow" else dp0 * 2
    clock = faults.FakeClock(1000.0)
    membership = elastic.Membership(ranks, now=clock, rendezvous_s=30)
    mgr = None
    if workdir is not None:
        mgr = CheckpointManager(
            os.path.join(workdir, f"elastic-{kind}"), keep=5)
    xs, ys = _make_data(77, n_batches=total_steps, batch=16)
    net, trainer = _build_elastic(make_mesh({_AXIS_DP: dp0},
                                            devices[:dp0]))
    controller = elastic.ElasticController(
        membership, devices=devices, devices_per_worker=dpw,
        checkpoint_manager=mgr, net=net, backoff_s=0.0,
        now=clock, sleep=lambda s: None)
    result = {"kind": kind, "dp_before": dp0, "dp_after": dp1,
              "event_at": event_at, "total_steps": total_steps}

    snap = None
    ckpt_step = None
    events = []
    step = 0
    fault_ctx = None
    try:
        while step < total_steps:
            trainer.step(mx.nd.array(xs[step]), mx.nd.array(ys[step]))
            step += 1
            if kind == "reshard_fault" and mgr is not None and \
                    step % 2 == 0 and snap is None:
                # pre-event cadence: checkpoints land on EVEN steps, so
                # the fallback genuinely rewinds (event_at is odd)
                mgr.save(step, params=net, trainer=trainer,
                         iterator={"batch": step}, sync=True)
                ckpt_step = step
            if step == event_at and snap is None:
                if kind == "reshard_fault":
                    # the fallback restores the newest checkpoint; the
                    # reference must restore the SAME instant
                    snap = {"from_checkpoint": True}
                else:
                    snap = _capture_boundary(net, trainer)
                if kind == "grow":
                    membership.announce_join(1, membership.epoch)
                else:
                    dead = _deliver_ps_death(membership, clock)
                    result["ps_declared_dead"] = dead
                if kind == "reshard_fault":
                    # every peer attempt (incl. retries) dies mid-
                    # transfer -> checkpoint fallback
                    fault_ctx = faults.inject("elastic.reshard")
                    fault_ctx.__enter__()
            ev = controller.check_step(step, trainer, params=net)
            if ev is not None:
                events.append({k: ev[k] for k in
                               ("source", "step", "dp", "epoch")})
                if fault_ctx is not None:
                    fault_ctx.__exit__(None, None, None)
                    fault_ctx = None
                if ev["source"] == "checkpoint":
                    result["rewound_to"] = ev["step"]
                    step = ev["step"]
    finally:
        if fault_ctx is not None:
            fault_ctx.__exit__(None, None, None)
    params_a, state_a = _final_state(net, trainer)
    result["events"] = events
    result["membership_epoch"] = membership.epoch
    result["final_dp"] = trainer.mesh.shape[_AXIS_DP]

    # reference: a FRESH process at the new dp restored from the same
    # state the reshard moved (boundary snapshot or the fallback
    # checkpoint), replaying the remaining steps
    ref_net, ref_trainer = _build_elastic(
        make_mesh({_AXIS_DP: dp1}, devices[:dp1]), seed=4321)
    if kind == "reshard_fault":
        ref_net(mx.nd.array(xs[0]))
        manifest = mgr.restore(step=ckpt_step, params=ref_net,
                               trainer=ref_trainer)
        start = int(manifest["step"])
    else:
        _restore_boundary(ref_net, ref_trainer, snap)
        start = event_at
    for i in range(start, total_steps):
        ref_trainer.step(mx.nd.array(xs[i]), mx.nd.array(ys[i]))
    params_b, state_b = _final_state(ref_net, ref_trainer)

    result["params_bitwise"] = _bitwise(params_a, params_b)
    result["state_bitwise"] = _bitwise(state_a, state_b)
    checks = [result["params_bitwise"], result["state_bitwise"],
              result["final_dp"] == dp1,
              membership.epoch >= 1, len(events) == 1]
    if kind == "reshard_fault":
        checks.append(events[0]["source"] == "checkpoint")
        checks.append(result.get("rewound_to") == ckpt_step)
        # the mid-transfer kill must have dumped the flight recorder,
        # last event = the elastic.reshard fault trip (ISSUE 9)
        result["flight_dump"] = _flight_check(expect_kind="fault.trip")
        fd = result["flight_dump"]
        checks.append(fd is None or fd["ok"])
    else:
        checks.append(events[0]["source"] == "peer")
    result["racecheck"] = _racecheck_verdict(rc)
    rcv = result["racecheck"]
    result["donation"] = _donation_verdict(dc)
    dcv = result["donation"]
    checks.append(rcv is None or rcv["ok"])
    checks.append(dcv is None or dcv["ok"])
    result["ok"] = bool(all(checks))
    return result


# ----------------------------------------------------------------------
# Serving front-end scenario (ISSUE 12): kill a router replica
# mid-traffic; zero lost/duplicated requests, outputs exactly the solo
# cold-path streams, flight dump + racecheck + KV leak sweep.
# ----------------------------------------------------------------------

def _serving_net():
    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo.nlp.llama import (LlamaConfig,
                                                     LlamaForCausalLM)
    cfg = LlamaConfig(vocab_size=64, hidden_size=32, num_layers=2,
                      num_heads=4, num_kv_heads=2, intermediate_size=64,
                      max_seq_len=64, tie_embeddings=True)
    net = LlamaForCausalLM(cfg)
    net.initialize()
    net(mx.nd.array([[1, 2, 3]], dtype="int32"))
    net.hybridize()
    return net


def run_serving_scenario(replicas=2, n_requests=6, kill_rid=1,
                         kill_at_boundary=2, workdir=None):
    """Kill replica ``kill_rid`` at its ``kill_at_boundary``-th
    scheduling boundary while ``n_requests`` shared-system-prompt
    requests are in flight; the router requeues and every request must
    complete exactly once with the solo cold-path token stream.
    Deterministic: the router's drive() mode (no threads), FakeClock
    timestamps, zero sleeps.

    ISSUE 20: under ``MXTPU_KV_DTYPE=fp8`` (or ``bf16``) every engine
    here — solo reference AND fleet — stores its KV pool quantized
    (engines read the env at init), so ``outputs_match_solo`` stays
    the bitwise fleet-vs-solo gate *within* the quantized mode; the
    scenario then additionally teacher-forces the solo streams through
    an explicit fp32-KV engine and gates the max |logit| drift
    (``kv_drift_ok``), publishing ``serving.kv_decode_drift``."""
    from mxnet_tpu import telemetry
    from mxnet_tpu.ops.quant_kv import resolve_kv_dtype
    from mxnet_tpu.serving import InferenceEngine, Request, Router
    from mxnet_tpu.testing import faults

    rc = _racecheck_arm()
    dc = _donation_arm()
    clock = faults.FakeClock(5000.0)
    net = _serving_net()
    rng = _np.random.RandomState(12)
    sys_prompt = rng.randint(0, 64, (12,)).tolist()
    prompts = [sys_prompt + rng.randint(0, 64, (3 + i % 4,)).tolist()
               for i in range(n_requests)]
    speculative = os.environ.get(
        "MXTPU_SPEC_DECODE", "0") not in ("", "0")
    kv_dtype = resolve_kv_dtype()
    result = {"kind": "serving", "replicas": replicas,
              "requests": n_requests, "kill_rid": kill_rid,
              "kill_at_boundary": kill_at_boundary,
              "speculative": speculative,
              "kv_dtype": kv_dtype or "fp32"}

    # solo cold-path references: one fresh single-replica engine per
    # prompt, full-prompt prefill, greedy decode — the stream every
    # routed request must reproduce bit-for-bit.  spec_decode is
    # FORCED OFF here regardless of env: the reference is the plain
    # path, so under MXTPU_SPEC_DECODE=1 the outputs_match_solo gate
    # is exactly the speculative-bitwise acceptance criterion (and a
    # drain/requeue mid-draft must land on the same stream)
    ref_eng = InferenceEngine(net, max_batch=2, block_size=8,
                              max_context=32, spec_decode=False)
    ref_eng.warmup()
    refs = []
    ref_fed = []      # full fed token streams (for fp8 drift replay)
    ref_logits = []   # per-step decode logits under the env kv_dtype
    for p in prompts:
        tok, _ = ref_eng.prefill(0, p)
        cur = list(p) + [int(tok)]
        lgs = []
        for _ in range(3):
            pos = len(cur) - 1
            assert ref_eng.reserve(0, pos)
            nxt, lg = ref_eng.decode([(0, cur[-1], pos)])
            lgs.append(_np.asarray(lg[0], _np.float32))
            cur.append(int(nxt[0]))
        ref_eng.release(0)
        refs.append(cur[len(p):])
        ref_fed.append(cur)
        ref_logits.append(lgs)

    def factory(compile_cache):
        return InferenceEngine(net, max_batch=2, block_size=8,
                               max_context=32, num_blocks=24,
                               prefill_chunk=8, prefix_cache=True,
                               compile_cache=compile_cache)

    router = Router(factory, replicas=replicas, now=clock)
    for rep in router.replicas:
        rep.engine.pin_prefix(sys_prompt)
    reqs = [router.submit(Request(p, max_new_tokens=4))
            for p in prompts]
    with faults.inject(f"serving.replica{kill_rid}.step",
                       at=kill_at_boundary):
        router.drive()
    fin = router.finished()
    result["finished"] = len(fin)
    result["epoch"] = router.epoch
    result["requeues"] = router.requeues
    result["no_lost_or_dup"] = (
        sorted(r.id for r in fin) == sorted(r.id for r in reqs)
        and len(fin) == len(reqs))
    result["outputs_match_solo"] = all(
        r.generated == ref for r, ref in zip(reqs, refs))
    st = router.stats()
    result["compiles_after_warmup"] = st["compiles_after_warmup"]
    result["prefix_hits"] = sum(
        (pr["prefix"] or {}).get("hits", 0)
        for pr in st["per_replica"])
    if speculative:
        # speculative accounting across surviving replicas (evidence,
        # not a gate — acceptance may legitimately be 0 on this mix;
        # the gate is outputs_match_solo staying bitwise)
        drafted = sum(r.batcher.spec_drafted for r in router.replicas
                      if r.alive)
        accepted = sum(r.batcher.spec_accepted for r in router.replicas
                       if r.alive)
        result["spec_drafted"] = drafted
        result["spec_accepted"] = accepted
        result["spec_accept_rate"] = (
            round(accepted / drafted, 4) if drafted else None)
    if kv_dtype is not None:
        # ISSUE 20 drift oracle: teacher-force the SAME token streams
        # the quantized solo reference committed through an explicit
        # fp32-KV engine and bound the max |logit| gap.  The bitwise
        # fleet-vs-solo gate above already ran within the quantized
        # mode; this bounds how far the quantized store sits from full
        # precision on identical inputs.
        f32_eng = InferenceEngine(net, max_batch=2, block_size=8,
                                  max_context=32, spec_decode=False,
                                  kv_dtype="fp32")
        f32_eng.warmup()
        drift = 0.0
        for p, fed, lgs in zip(prompts, ref_fed, ref_logits):
            f32_eng.prefill(0, p)
            for j, ref_lg in enumerate(lgs):
                pos = len(p) + j
                assert f32_eng.reserve(0, pos)
                _, lg = f32_eng.decode([(0, fed[pos], pos)])
                drift = max(drift, float(_np.max(_np.abs(
                    _np.asarray(lg[0], _np.float32) - ref_lg))))
            f32_eng.release(0)
        result["kv_decode_drift"] = round(drift, 6)
        result["kv_drift_ok"] = drift <= 0.25
        if telemetry.enabled():
            telemetry.set_gauge("serving.kv_decode_drift", drift)
    # the injected kill must have left a parseable flight dump whose
    # last event is the fault trip (ISSUE 9 discipline)
    result["flight_dump"] = _flight_check(expect_kind="fault.trip")
    # KV leak sweep on the survivors: with every request released, only
    # the prefix-cache chains may still hold blocks
    leaks_ok = True
    for rep in router.replicas:
        if not rep.alive:
            continue
        try:
            rep.engine.cache.check_leaks(
                holders=rep.engine.prefix_cache.held_blocks())
        except Exception as e:  # noqa: BLE001 — verdict, not crash
            leaks_ok = False
            result["leak_error"] = f"{type(e).__name__}: {e}"
    result["kv_leaks_clean"] = leaks_ok
    fd = result["flight_dump"]
    result["racecheck"] = _racecheck_verdict(rc)
    rcv = result["racecheck"]
    result["donation"] = _donation_verdict(dc)
    dcv = result["donation"]
    result["ok"] = bool(
        result["no_lost_or_dup"] and result["outputs_match_solo"]
        and result["epoch"] >= 1 and result["requeues"] >= 1
        and result["compiles_after_warmup"] == 0 and leaks_ok
        and result.get("kv_drift_ok", True)
        and (fd is None or fd["ok"]) and (rcv is None or rcv["ok"])
        and (dcv is None or dcv["ok"]))
    return result


# ----------------------------------------------------------------------
# Disaggregated prefill/decode scenario (ISSUE 18): paged-KV block
# handoff over ONE shared pool survives a replica killed mid-handoff.
# ----------------------------------------------------------------------

def run_disagg_scenario(n_requests=6, kill_rid=0, kill_point="handoff",
                        kill_at=2, workdir=None):
    """Kill one replica of a 4-replica DISAGGREGATED fleet (prefill
    rids 0/2, decode rids 1/3, ONE shared ``PagedKVCache``) while
    ``n_requests`` requests are in flight.  ``kill_point="handoff"``
    trips the ``serving.replica{rid}.handoff`` fault point — the kill
    lands BETWEEN "prefill finished" and "decode adopted", the worst
    spot for the adopt-then-release block-ownership protocol — and
    ``"step"`` kills at a plain scheduling boundary (pass an odd
    ``kill_rid`` to kill a decode-role replica).  Every request must
    finish exactly once with the solo combined-role token stream, and
    the SHARED pool must pass the leak sweep on the survivors (the dead
    replica's slot holds evacuated, zero blocks stranded).
    Deterministic: drive() mode, FakeClock, zero sleeps."""
    from mxnet_tpu.serving import (ContinuousBatcher, InferenceEngine,
                                   Request, Router)
    from mxnet_tpu.testing import faults

    rc = _racecheck_arm()
    dc = _donation_arm()
    clock = faults.FakeClock(5000.0)
    net = _serving_net()
    rng = _np.random.RandomState(18)
    prompts = [rng.randint(0, 64, (3 + i % 5,)).tolist()
               for i in range(n_requests)]
    result = {"kind": "disagg", "requests": n_requests,
              "kill_rid": kill_rid, "kill_point": kill_point,
              "kill_at": kill_at}

    # solo combined-role reference: one engine, one batcher, no fleet —
    # the stream the disaggregated path must reproduce bit-for-bit
    solo = ContinuousBatcher(InferenceEngine(
        net, max_batch=2, block_size=8, num_blocks=32,
        max_context=32).warmup())
    solo_reqs = [solo.submit(Request(p, max_new_tokens=4))
                 for p in prompts]
    solo.run()
    refs = [list(r.generated) for r in solo_reqs]

    def factory(compile_cache, kv_cache=None):
        return InferenceEngine(net, max_batch=2, block_size=8,
                               num_blocks=32, max_context=32,
                               compile_cache=compile_cache,
                               kv_cache=kv_cache)

    router = Router(factory, replicas=4, disaggregated=True, now=clock)
    reqs = [Request(p, max_new_tokens=4) for p in prompts]
    for r in reqs:
        router.submit(r)
    with faults.inject(f"serving.replica{kill_rid}.{kill_point}",
                       at=kill_at):
        router.drive()
    fin = router.finished()
    result["finished"] = len(fin)
    result["epoch"] = router.epoch
    result["requeues"] = router.requeues
    result["handoffs"] = router.handoffs
    result["no_lost_or_dup"] = (
        sorted(r.id for r in fin) == sorted(r.id for r in reqs)
        and len(fin) == len(reqs))
    result["outputs_match_solo"] = all(
        list(r.generated) == ref for r, ref in zip(reqs, refs))
    st = router.stats()
    result["compiles_after_warmup"] = st["compiles_after_warmup"]
    result["prefill_pool_occupancy"] = st["prefill_pool_occupancy"]
    result["decode_pool_occupancy"] = st["decode_pool_occupancy"]
    result["flight_dump"] = _flight_check(expect_kind="fault.trip")
    # leak sweep on the ONE shared pool: every request finished and the
    # dead replica's holds evacuated, so zero blocks may remain (the
    # scenario runs without prefix chains — no legitimate holders)
    leaks_ok = True
    try:
        router._shared_cache.check_leaks(holders=0)
    except Exception as e:  # noqa: BLE001 — verdict, not crash
        leaks_ok = False
        result["leak_error"] = f"{type(e).__name__}: {e}"
    result["kv_leaks_clean"] = leaks_ok
    fd = result["flight_dump"]
    result["racecheck"] = _racecheck_verdict(rc)
    rcv = result["racecheck"]
    result["donation"] = _donation_verdict(dc)
    dcv = result["donation"]
    result["ok"] = bool(
        result["no_lost_or_dup"] and result["outputs_match_solo"]
        and result["epoch"] >= 1 and result["requeues"] >= 1
        and result["handoffs"] >= 1
        and result["compiles_after_warmup"] == 0 and leaks_ok
        and (fd is None or fd["ok"]) and (rcv is None or rcv["ok"])
        and (dcv is None or dcv["ok"]))
    return result


# ----------------------------------------------------------------------
# Production-elasticity scenario (ISSUE 13): preemption notice -> drain
# -> shrink under load -> notice revoked -> load-driven grow back, with
# bitwise parity at each dp; serving replica drained by notice with
# zero lost requests and an autoscaled replacement replica.
# ----------------------------------------------------------------------

def run_autoscale_scenario(total_steps=6, notice_at=2, revoke_at=4,
                           workdir=None):
    """The ISSUE 13 acceptance scenario; see the module docstring.
    Deterministic: FakeClock, zero sleeps, drive()-mode router."""
    import mxnet_tpu as mx
    from mxnet_tpu import elastic
    from mxnet_tpu.checkpoint import CheckpointManager
    from mxnet_tpu.parallel.mesh import make_mesh, AXIS_DP as _AXIS_DP
    from mxnet_tpu.serving import (AdmissionShed, InferenceEngine,
                                   Request, Router)
    from mxnet_tpu.testing import faults
    import jax

    rc = _racecheck_arm()
    dc = _donation_arm()
    clock = faults.FakeClock(2000.0)
    devices = jax.devices()
    dpw, ranks = 4, [0, 1]
    dp0 = dpw * len(ranks)               # 8
    dp_small = dp0 // 2                  # 4 after the drain
    result = {"kind": "autoscale", "dp_before": dp0,
              "dp_small": dp_small, "notice_at": notice_at,
              "revoke_at": revoke_at, "total_steps": total_steps}

    # -- serving fleet: 2 replicas, shared-system-prompt mix ------------
    net_s = _serving_net()
    rng = _np.random.RandomState(21)
    sys_prompt = rng.randint(0, 64, (12,)).tolist()
    # 3 unique prompts, each submitted twice: greedy decode is
    # deterministic, so the twin of a drained-and-requeued request is
    # the bitwise oracle for its stream — no second warmup needed
    uniq = [sys_prompt + rng.randint(0, 64, (3 + i,)).tolist()
            for i in range(3)]
    prompts = [p for p in uniq for _ in range(2)]

    def factory(compile_cache):
        return InferenceEngine(net_s, max_batch=2, block_size=8,
                               max_context=32, num_blocks=24,
                               prefill_chunk=8, prefix_cache=True,
                               compile_cache=compile_cache)

    router = Router(factory, replicas=2, now=clock)
    for rep in router.replicas:
        rep.engine.pin_prefix(sys_prompt)
    sboard = elastic.NoticeBoard(now=clock)
    ssrc = elastic.FakeNoticeSource()
    sboard.attach_source(ssrc)
    router.attach_notices(sboard)
    serve_scaler = elastic.Autoscaler(
        elastic.ScalingPolicy(
            [elastic.ScalingRule("serving.queue_depth", high=10,
                                 domain="serving", window_s=0.0)],
            cooldown_s=0.0, max_replicas=3),
        router=router, now=clock)

    reqs = [router.submit(Request(p, max_new_tokens=4)) for p in prompts]
    # the doomed replica steps twice, THEN the notice lands mid-traffic
    ssrc.preempt(1, grace_s=60, after_polls=2)
    router.drive()
    result["serving_flight_dump"] = _flight_check(expect_kind="notice")
    fin = router.finished()
    result["serving_no_lost_or_dup"] = (
        sorted(r.id for r in fin) == sorted(r.id for r in reqs)
        and len(fin) == len(reqs))
    by_prompt = {}
    for r in reqs:
        by_prompt.setdefault(tuple(r.tokens), []).append(r.generated)
    result["serving_twin_streams_bitwise"] = all(
        all(len(g) > 0 for g in gs) and all(g == gs[0] for g in gs)
        for gs in by_prompt.values())
    result["serving_drained"] = any(
        e["kind"] == "replica_drained" for e in router.events)
    # load-driven replacement: the serving autoscaler adds replica 2
    # from the SHARED warmup compile cache — zero new compiles
    serve_scaler.tick(signals={"serving.queue_depth": 99.0})
    result["serving_replicas_live"] = len(router.live_replicas())
    router.replicas[-1].engine.pin_prefix(sys_prompt)

    # -- training: notice -> drain -> shrink -> revoke -> grow back -----
    xs, ys = _make_data(77, n_batches=total_steps, batch=16)
    net, trainer = _build_elastic(make_mesh({_AXIS_DP: dp0},
                                            devices[:dp0]))
    membership = elastic.Membership(ranks, now=clock, rendezvous_s=60)
    board = elastic.NoticeBoard(now=clock)
    src = elastic.FakeNoticeSource()
    board.attach_source(src)
    mgr = None
    if workdir is not None:
        mgr = CheckpointManager(
            os.path.join(workdir, "autoscale"), keep=5, async_save=False)
    ladder = elastic.DegradationLadder(router=router, now=clock)
    controller = elastic.ElasticController(
        membership, devices=devices, devices_per_worker=dpw,
        checkpoint_manager=mgr, net=net, backoff_s=0.0,
        now=clock, sleep=lambda s: None, notices=board, ladder=ladder)
    if mgr is not None:
        # checkpoint-THEN-reshard on every notice-driven drain
        controller.drain_checkpoint = lambda s: mgr.save(
            s, params=net, trainer=trainer, iterator={"batch": s},
            sync=True)
    scaler = elastic.Autoscaler(
        elastic.ScalingPolicy(
            [elastic.ScalingRule("train.step_ms", high=100.0,
                                 domain="train", window_s=5.0)],
            cooldown_s=5.0, max_dp=dp0),
        controller=controller, now=clock)

    snap_a = snap_b = None
    shed_blocked = False
    events = []
    for step in range(1, total_steps + 1):
        clock.advance(2.0)
        trainer.step(mx.nd.array(xs[step - 1]), mx.nd.array(ys[step - 1]))
        if step == notice_at:
            # GCE-style advance warning for worker 1, 30 s grace: the
            # boundary below drains it AHEAD of any heartbeat timeout
            src.preempt(1, grace_s=30)
            snap_a = _capture_boundary(net, trainer)
        if step == revoke_at:
            # maintenance cancelled: notice revoked, the worker lives
            # and re-announces; the grow itself is LOAD-driven (below)
            src.revoke(1)
            board.poll()
            membership.announce_join(1, membership.epoch)
        # the load-based control loop ticks at every boundary (the
        # synthetic step_ms signal stays hot, so the autoscaler wants
        # capacity the moment membership can back it)
        scaler.tick(signals={"train.step_ms": 500.0}, step=step)
        if step == revoke_at:
            snap_b = _capture_boundary(net, trainer)
        ev = controller.check_step(step, trainer, params=net)
        if ev is not None:
            events.append({k: ev.get(k) for k in
                           ("source", "step", "dp", "epoch")})
        if step == notice_at:
            result["training_flight_dump"] = _flight_check(
                expect_kind="notice")
            result["shed_after_drain"] = router.shedding
            try:
                router.submit(Request(prompts[0], max_new_tokens=2))
            except AdmissionShed:
                shed_blocked = True
    result["events"] = events
    result["shed_blocked"] = shed_blocked
    result["unshed_after_grow"] = not router.shedding
    result["drain_checkpoint_at"] = None if mgr is None else mgr.latest()
    result["membership_epoch"] = membership.epoch
    result["final_dp"] = trainer.mesh.shape[_AXIS_DP]
    result["drains"] = controller.drains
    result["autoscale"] = scaler.stats()
    grow = [d for d in scaler.decisions
            if d["domain"] == "train" and d["verdict"] == "grow"]
    result["load_driven_grow"] = bool(grow) and grow[0]["to"] == dp0
    params_final, state_final = _final_state(net, trainer)

    # parity 1: the dp=4 segment must be BITWISE a fresh dp=4 process
    # restored from the drain-boundary state
    ref_net, ref_trainer = _build_elastic(
        make_mesh({_AXIS_DP: dp_small}, devices[:dp_small]), seed=4321)
    _restore_boundary(ref_net, ref_trainer, snap_a)
    for i in range(notice_at, revoke_at):
        ref_trainer.step(mx.nd.array(xs[i]), mx.nd.array(ys[i]))
    pa, sa = _final_state(ref_net, ref_trainer)
    result["params_bitwise_dp4"] = _bitwise(
        {n: v for n, v in snap_b["params"].items()}, pa)
    result["state_bitwise_dp4"] = _bitwise(
        {k: v.asnumpy() for k, v in snap_b["sd"]["arrays"].items()}, sa)

    # parity 2: the grown dp=8 tail must be BITWISE a fresh dp=8
    # process restored from the grow-boundary state
    ref_net8, ref_trainer8 = _build_elastic(
        make_mesh({_AXIS_DP: dp0}, devices[:dp0]), seed=9876)
    _restore_boundary(ref_net8, ref_trainer8, snap_b)
    for i in range(revoke_at, total_steps):
        ref_trainer8.step(mx.nd.array(xs[i]), mx.nd.array(ys[i]))
    pb, sb = _final_state(ref_net8, ref_trainer8)
    result["params_bitwise"] = _bitwise(params_final, pb)
    result["state_bitwise"] = _bitwise(state_final, sb)

    # serving epilogue: admissions recovered — two more requests ride
    # the grown fleet (incl. the autoscaled replica) to completion
    extra = [router.submit(Request(p, max_new_tokens=4))
             for p in uniq[:2]]
    router.drive()
    result["serving_post_recovery_ok"] = all(r.done for r in extra)
    st = router.stats()
    result["compiles_after_warmup"] = st["compiles_after_warmup"]
    leaks_ok = True
    for rep in router.replicas:
        if not rep.alive:
            continue
        try:
            rep.engine.cache.check_leaks(
                holders=rep.engine.prefix_cache.held_blocks())
        except Exception as e:  # noqa: BLE001 — verdict, not crash
            leaks_ok = False
            result["leak_error"] = f"{type(e).__name__}: {e}"
    result["kv_leaks_clean"] = leaks_ok

    result["racecheck"] = _racecheck_verdict(rc)
    rcv = result["racecheck"]
    result["donation"] = _donation_verdict(dc)
    dcv = result["donation"]
    fds = [result.get("serving_flight_dump"),
           result.get("training_flight_dump")]
    checks = [
        result["serving_no_lost_or_dup"],
        result["serving_twin_streams_bitwise"],
        result["serving_drained"],
        result["serving_replicas_live"] == 2,
        result["serving_post_recovery_ok"],
        result["compiles_after_warmup"] == 0,
        leaks_ok,
        result["shed_after_drain"], shed_blocked,
        result["unshed_after_grow"],
        mgr is None or result["drain_checkpoint_at"] == notice_at,
        result["drains"] == 1,
        result["membership_epoch"] == 2,       # death + join
        result["final_dp"] == dp0,
        result["load_driven_grow"],
        len(events) == 2,
        result["params_bitwise_dp4"], result["state_bitwise_dp4"],
        result["params_bitwise"], result["state_bitwise"],
        all(fd is None or fd["ok"] for fd in fds),
        rcv is None or rcv["ok"],
        dcv is None or dcv["ok"],
    ]
    result["ok"] = bool(all(checks))
    return result


# ----------------------------------------------------------------------
# Watchdog scenario (ISSUE 14): injected NaN loss + FakeClock step
# stall, each leaving a typed watchdog.* event and a flight dump whose
# reason names the rule.
# ----------------------------------------------------------------------

def run_watchdog_scenario(total_steps=6, nan_at=3, workdir=None):
    """Run-health watchdog end to end: train a tiny sharded model,
    inject a NaN loss through the ``watchdog.loss`` fault point
    (testing/faults.py — the detection path is exactly production's),
    then starve the step clock (FakeClock, zero sleeps) past
    ``stall_s``.  Each incident must emit its typed ``watchdog.*``
    event and dump the flight recorder with ``reason="watchdog:<rule>"``
    — the same gates ``tools/tpu_queue_runner.py --chaos watchdog``
    applies in a child process."""
    import mxnet_tpu as mx
    from mxnet_tpu import telemetry
    from mxnet_tpu.telemetry import watchdog as wd_mod
    from mxnet_tpu.testing import faults

    rc = _racecheck_arm()
    dc = _donation_arm()
    result = {"mode": "watchdog", "nan_at": nan_at,
              "total_steps": total_steps}
    clock = faults.FakeClock(1000.0)
    wd = wd_mod.Watchdog(now=clock, stall_s=30.0)
    wd_mod.configure(enabled=True, instance=wd)
    try:
        xs, ys = _make_data(7)
        net, trainer, step = _build("sharded")
        with faults.inject("watchdog.loss", at=nan_at, times=1,
                           action=lambda p: float("nan")):
            for i in range(total_steps):
                loss = step(xs[i], ys[i])
                # the estimator's seam: tick with the host loss the
                # metric path already pulled (the fault point swaps in
                # the NaN at step nan_at)
                wd_mod.on_step(i + 1,
                               loss=float(loss.asnumpy().mean()))
                clock.advance(1.0)
        kinds = [e["kind"] for e in telemetry.events()]
        result["nan_event"] = "watchdog.nonfinite_loss" in kinds
        result["nan_flight"] = _flight_check(expect_kind="watchdog")
        nan_reason = (result["nan_flight"] or {}).get("reason")
        result["nan_reason_ok"] = nan_reason == "watchdog:nonfinite_loss"

        # training went quiet: no step for > stall_s (FakeClock)
        clock.advance(31.0)
        stalled = wd_mod.check(step=total_steps)
        kinds = [e["kind"] for e in telemetry.events()]
        result["stall_detected"] = bool(stalled)
        result["stall_event"] = "watchdog.step_stall" in kinds
        result["stall_flight"] = _flight_check(expect_kind="watchdog")
        stall_reason = (result["stall_flight"] or {}).get("reason")
        result["stall_reason_ok"] = stall_reason == "watchdog:step_stall"
        result["trips"] = [r for r, _ in wd.trips]
    finally:
        wd_mod.reset()           # never leak the FakeClock instance
    result["racecheck"] = _racecheck_verdict(rc)
    rcv = result["racecheck"]
    result["donation"] = _donation_verdict(dc)
    dcv = result["donation"]
    nf, sf = result["nan_flight"], result["stall_flight"]
    result["ok"] = bool(
        result["nan_event"] and result["stall_event"]
        and result["stall_detected"]
        and (nf is None or (nf["ok"] and result["nan_reason_ok"]))
        and (sf is None or (sf["ok"] and result["stall_reason_ok"]))
        and (rcv is None or rcv["ok"])
        and (dcv is None or dcv["ok"]))
    return result


# ----------------------------------------------------------------------
# Fleet observability scenario (ISSUE 15): N simulated workers, one
# straggler + one scrape-dead rank — the fleet collector must name both
# by rank, merge histograms exactly, and stay racecheck-clean.
# ----------------------------------------------------------------------

def run_fleet_scenario(n_workers=4, straggler_rank=2, dead_rank=3,
                       steps=4, workdir=None):
    """The ISSUE 15 acceptance scenario; see the module docstring.
    Deterministic: per-rank registries on ONE FakeClock, zero sleeps,
    the straggler's extra step time injected through the
    ``fleet.straggle`` fault point (the detection path is exactly what
    a real pod scrape sees)."""
    from mxnet_tpu import telemetry
    from mxnet_tpu.telemetry import fleet as fleet_mod
    from mxnet_tpu.telemetry.registry import MetricsRegistry
    from mxnet_tpu.testing import faults

    rc = _racecheck_arm()
    dc = _donation_arm()
    clock = faults.FakeClock(3000.0)
    result = {"kind": "fleet", "workers": n_workers,
              "straggler_rank": straggler_rank, "dead_rank": dead_rank,
              "steps": steps}

    # N simulated workers: each rank is its own registry — exactly the
    # snapshot a remote PSClient.telemetry() scrape returns — stepped
    # under the same FakeClock.  Every rank also carries the same
    # membership epoch (no desync in this scenario) and its own step
    # counter.
    regs = {r: MetricsRegistry(now=clock) for r in range(n_workers)}
    with faults.inject("fleet.straggle",
                       action=lambda rank: clock.advance(0.45)):
        for _ in range(steps):
            for r in range(n_workers):
                t0 = clock()
                clock.advance(0.05)          # the nominal 50 ms step
                if r == straggler_rank:
                    # the injected straggler: the armed fault point
                    # advances the clock mid-"step", so THIS rank's
                    # step_ms histogram runs ~10x long
                    faults.fault_point("fleet.straggle", payload=r)
                regs[r].histogram("train.step_ms").observe(
                    (clock() - t0) * 1e3)
                regs[r].counter("train.steps").inc()
                regs[r].gauge("elastic.epoch").set(3)

    def transport(rank):
        def scrape():
            if rank == dead_rank:
                raise ConnectionError("simulated dead scrape endpoint")
            return {"snapshot": regs[rank].snapshot()}
        return scrape

    coll = fleet_mod.FleetCollector(
        {r: transport(r) for r in range(n_workers)},
        now=clock, skew=3.0, scrape_s=0.0)
    snap = coll.collect()

    kinds = {}
    for ev in telemetry.events():
        kinds.setdefault(ev["kind"], []).append(ev["data"])
    stragglers = kinds.get("fleet.straggler", [])
    deads = kinds.get("fleet.scrape_dead", [])
    result["straggler_named"] = any(
        d.get("rank") == straggler_rank for d in stragglers)
    result["scrape_dead_named"] = any(
        d.get("rank") == dead_rank for d in deads)
    result["slowest_rank"] = snap["skew"]["slowest_rank"]
    result["skew_ratio"] = snap["skew"]["skew_ratio"]
    result["dead_error_typed"] = bool(
        snap["per_rank"][str(dead_rank)].get("error"))

    # the rule firings must have left a flight dump whose reason names
    # a fleet rule and whose last event is the incident (ISSUE 9/14
    # contract, reused verbatim)
    result["flight_dump"] = _flight_check(expect_kind="fleet")
    fd = result["flight_dump"]
    reason_ok = fd is None or str(fd.get("reason", "")
                                  ).startswith("fleet:")

    # merge exactness: every merged histogram equals the element-wise
    # sum of the per-rank buckets, computed here in the same ascending
    # rank order the collector uses — bitwise, not approximately
    alive = [r for r in range(n_workers) if r != dead_rank]
    merged = snap["histograms"]["train.step_ms"]
    expect_counts = [0] * (len(merged["edges"]) + 1)
    expect_sum, expect_count = 0.0, 0
    for r in alive:
        st = regs[r].snapshot()["histograms"]["train.step_ms"]
        for i, c in enumerate(st["counts"]):
            expect_counts[i] += c
        expect_sum += st["sum"]
        expect_count += st["count"]
    result["hist_merge_bitwise"] = (
        merged["counts"] == expect_counts
        and merged["sum"] == expect_sum
        and merged["count"] == expect_count)
    result["counters_summed"] = (
        snap["counters"]["train.steps"] == steps * len(alive))

    result["racecheck"] = _racecheck_verdict(rc)
    rcv = result["racecheck"]
    result["donation"] = _donation_verdict(dc)
    dcv = result["donation"]
    result["ok"] = bool(
        result["straggler_named"] and result["scrape_dead_named"]
        and result["slowest_rank"] == straggler_rank
        and result["dead_error_typed"]
        and result["hist_merge_bitwise"] and result["counters_summed"]
        and (fd is None or (fd["ok"] and reason_ok))
        and (rcv is None or rcv["ok"])
        and (dcv is None or dcv["ok"]))
    return result


def run_multiprocess_scenario(n_procs=4, victim=2, steps=8,
                              ckpt_every=3, kill_step=5, park_step=7,
                              workdir=None):
    """ISSUE 19 acceptance: SIGKILL a REAL worker process mid-run and
    assert the notice→drain→reshard path end-to-end at process level.

    Unlike every other suite (threads under FakeClock), this one spawns
    ``n_procs`` real processes over ``jax.distributed`` through
    :class:`mxnet_tpu.pod.PodLauncher` and kills one with SIGKILL — no
    simulation anywhere:

    - the launcher detects the death, requeues the victim's serving
      leases, and COMMITS a membership change (fresh coordinator port);
    - survivors drain at the step gate, tear down + re-init the
      coordination service (``reinit_distributed``) and re-rendezvous
      at ``jax.process_count() == n_procs - 1``;
    - training resumes from the shared checkpoint BITWISE a fresh
      ``n_procs - 1``-process pod restored from the same checkpoint;
    - the file-lease request ledger ends exactly-once (zero lost, zero
      duplicated) including the victim's requeued lease;
    - a fleet scrape over the workers' live PS telemetry endpoints
      (taken while survivors are parked at ``park_step``) names the
      dead rank typed, and the scrape failure leaves rpc.* counters
      plus a flight dump.

    The kill lands while every worker is parked at the held step gate
    — between collectives, which is exactly the elastic controller's
    drain-at-step-boundary contract (a kill mid-collective would wedge
    the survivors inside gloo, which is the launcher-level reason the
    gate exists at all)."""
    import shutil as _shutil
    import threading
    import time as _time

    from mxnet_tpu import telemetry
    from mxnet_tpu.kvstore import rpc as _rpc
    from mxnet_tpu.pod import (PodLauncher, queue_ledger,
                               submit_request)
    from mxnet_tpu.telemetry import fleet as fleet_mod

    workdir = workdir or tempfile.mkdtemp(prefix="mxtpu-chaos-procs-")
    pod_dir = os.path.join(workdir, "pod")
    result = {"kind": "procs", "procs": n_procs, "victim": victim,
              "steps": steps, "kill_step": kill_step}
    n_requests = 2 * n_procs
    for i in range(n_requests):
        submit_request(pod_dir, f"r{i}", {"x": i})
    launcher = PodLauncher(
        n_procs, pod_dir, steps=steps, ckpt_every=ckpt_every,
        env={"MXTPU_POD_HOLD_RANK": str(victim),
             "MXTPU_POD_SERVE_PER_STEP": "1"})
    launcher.hold_step = kill_step
    launcher.start()
    sup = {}

    def _run():
        try:
            sup["summary"] = launcher.supervise(timeout_s=180.0)
        except Exception as e:  # noqa: BLE001 — surfaced in verdict
            sup["error"] = f"{type(e).__name__}: {e}"
    thread = threading.Thread(target=_run)
    thread.start()

    def _wait(cond, what, timeout=90.0):
        deadline = _time.monotonic() + timeout
        while not cond():
            if _time.monotonic() > deadline:
                raise TimeoutError(f"chaos procs: timed out waiting "
                                   f"for {what}")
            _time.sleep(0.02)

    frozen = os.path.join(workdir, "ckpt.frozen.npz")
    fleet_snap = None
    try:
        # 1. everyone parked at the held gate (checkpoint exists)
        _wait(lambda: launcher.ready_ranks(kill_step)
              == set(range(n_procs)), f"gate {kill_step}")
        _shutil.copy(os.path.join(pod_dir, "ckpt.npz"), frozen)
        # 2. the real SIGKILL; survivors park again post-reshard so the
        #    fleet scrape sees live survivor endpoints + one dead port
        launcher.kill(victim)
        launcher.hold_step = park_step
        survivors = set(range(n_procs)) - {victim}
        _wait(lambda: launcher.ready_ranks(park_step) >= survivors,
              f"survivors at gate {park_step}")
        policy = _rpc.RetryPolicy(retries=0, timeout_s=5.0)
        coll = fleet_mod.FleetCollector(
            {r: fleet_mod.ps_transport("127.0.0.1",
                                       launcher.ps_ports[r],
                                       retries=1, policy=policy)
             for r in range(n_procs)}, scrape_s=0.0)
        fleet_snap = coll.collect()
        launcher.hold_step = None
        thread.join(timeout=120.0)
    finally:
        launcher.shutdown()
        thread.join(timeout=10.0)
    summary = sup.get("summary") or {}
    result["supervise_error"] = sup.get("error")
    result["summary"] = {k: summary.get(k)
                         for k in ("epoch", "dead", "done", "requeued")}

    # survivors re-rendezvoused at the smaller world (real
    # jax.process_count(), reported by each survivor post-reinit)
    statuses = launcher.statuses()
    worlds = {r: s.get("world") for r, s in statuses.items()
              if r != victim}
    reinits = [s.get("reinit_ms") for r, s in statuses.items()
               if r != victim]
    result["survivor_worlds"] = worlds
    result["world_ok"] = (len(worlds) == n_procs - 1 and
                          all(w == n_procs - 1 for w in worlds.values()))
    result["coordinator_reinit_ms"] = max(
        [r for r in reinits if r is not None], default=None)
    result["reinit_ok"] = all(r is not None for r in reinits)

    # exactly-once serving ledger, including the victim's requeued lease
    ledger = queue_ledger(pod_dir)
    result["requeued"] = summary.get("requeued")
    result["ledger"] = {k: len(v) for k, v in ledger.items()}
    result["ledger_exactly_once"] = (
        ledger["pending"] == [] and ledger["inflight"] == []
        and ledger["done"] == sorted(f"r{i}" for i in range(n_requests)))
    result["requeue_exercised"] = bool(summary.get("requeued"))

    # bitwise: survivor post-reshard digests == a fresh (n-1)-proc pod
    # restored from the SAME checkpoint
    surv_rank = min(set(range(n_procs)) - {victim})
    surv = [(r["step"], r["digest"])
            for r in launcher.digests(surv_rank)
            if r["world"] == n_procs - 1]
    fresh_dir = os.path.join(workdir, "pod_fresh")
    fresh_launcher = PodLauncher(
        n_procs - 1, fresh_dir, steps=steps, ckpt_every=ckpt_every,
        env={"MXTPU_POD_RESTORE": frozen})
    fresh_launcher.start()
    try:
        fresh_launcher.supervise(timeout_s=120.0)
    finally:
        fresh_launcher.shutdown()
    fresh = [(r["step"], r["digest"])
             for r in fresh_launcher.digests(0)]
    result["resumed_steps"] = [s for s, _ in surv]
    result["bitwise_resume"] = bool(surv) and surv == fresh

    # fleet snapshot names the dead rank, typed, from a REAL scrape
    dead_row = (fleet_snap or {}).get("per_rank", {}).get(str(victim),
                                                          {})
    result["dead_error"] = dead_row.get("error")
    result["dead_error_typed"] = "PeerUnreachable" in str(
        dead_row.get("error", "")) or "RPCTimeout" in str(
        dead_row.get("error", ""))
    kinds = {}
    for ev in telemetry.events():
        kinds.setdefault(ev["kind"], []).append(ev["data"])
    result["scrape_dead_named"] = any(
        d.get("rank") == victim
        for d in kinds.get("fleet.scrape_dead", []))
    snap = telemetry.snapshot()
    result["rpc_failures_counted"] = (
        snap.get("counters", {}).get("rpc.failures", 0) > 0)
    result["flight_dump"] = _flight_check()
    fd = result["flight_dump"]
    reason_ok = fd is None or str(fd.get("reason", "")).startswith(
        ("fleet:", "rpc_failure:"))

    result["ok"] = bool(
        not result["supervise_error"]
        and summary.get("dead") == [victim]
        and result["world_ok"] and result["reinit_ok"]
        and result["ledger_exactly_once"]
        and result["requeue_exercised"]
        and result["bitwise_resume"]
        and result["dead_error_typed"]
        and result["scrape_dead_named"]
        and result["rpc_failures_counted"]
        and (fd is None or (fd.get("path") and reason_ok)))
    return result


def main(argv=None):
    # the smoke must run anywhere — force the simulated CPU mesh exactly
    # like tests/conftest.py does
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    argv = list(sys.argv[1:] if argv is None else argv)
    suite = argv[0] if argv else "preempt"
    workdir = tempfile.mkdtemp(prefix="mxtpu-chaos-")
    # flight-recorder dumps land in the scenario workdir (cleaned up
    # with it) unless the caller pinned a directory
    os.environ.setdefault("MXTPU_FLIGHT_DIR", workdir)
    results = []
    try:
        if suite in ("preempt", "all"):
            results += [run_scenario(mode, workdir=workdir)
                        for mode in ("plain", "sharded")]
            # ISSUE 6: resume from the (non-K-aligned) surviving
            # checkpoint with K=4 multi-step windows — still bitwise K=1
            results.append(run_scenario("sharded", workdir=workdir,
                                        resume_steps_per_call=4))
        if suite in ("elastic", "all"):
            results += [run_elastic_scenario(kind, workdir=workdir)
                        for kind in ("shrink", "grow", "reshard_fault")]
        if suite in ("serving", "all"):
            results.append(run_serving_scenario(workdir=workdir))
        if suite in ("disagg", "all"):
            # prefill replica killed mid-handoff, then a decode replica
            # killed at a plain boundary — both over the shared pool
            results.append(run_disagg_scenario(workdir=workdir))
            results.append(run_disagg_scenario(
                kill_rid=1, kill_point="step", kill_at=3,
                workdir=workdir))
        if suite in ("autoscale", "all"):
            results.append(run_autoscale_scenario(workdir=workdir))
        if suite in ("watchdog", "all"):
            results.append(run_watchdog_scenario(workdir=workdir))
        if suite in ("fleet", "all"):
            results.append(run_fleet_scenario(workdir=workdir))
        if suite in ("procs", "all"):
            # the only suite with REAL processes + SIGKILL (ISSUE 19);
            # everything above runs threads under FakeClock
            results.append(run_multiprocess_scenario(workdir=workdir))
    finally:
        shutil.rmtree(workdir, ignore_errors=True)
    ok = bool(results) and all(r["ok"] for r in results)
    print(json.dumps({"chaos": results, "ok": ok}))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
