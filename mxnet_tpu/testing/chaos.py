"""Kill-and-resume chaos smoke: the fault-tolerance layer end to end.

``python -m mxnet_tpu.testing.chaos`` (or ``tools/tpu_queue_runner.py
--chaos``) runs, on the simulated CPU mesh, the exact scenario the
acceptance bar demands — in one process, deterministically:

1. **Reference run**: N training steps, uninterrupted; final params +
   optimizer state recorded.
2. **Chaos run**: same seed/data.  The checkpoint writer is killed on
   its first attempt (the save must survive via the next one), a
   simulated preemption fires at step K, the preemption save goes
   through, and the newest checkpoint is then CORRUPTED on disk — so
   resume must fall back to the previous valid one and replay forward.
3. **Resume**: a fresh net/trainer auto-resumes from ``latest()``
   (skipping the corrupted checkpoint), trains to N total steps, and
   must match the reference run BITWISE (params and optimizer state).

Runs the scenario twice: plain ``gluon.Trainer`` and
``DataParallelTrainer(shard_updates=True)``.  Prints one JSON verdict
line; exit code 0 only if every check passed.
"""
from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile

import numpy as _np


def _make_data(seed, n_batches=8, batch=16, din=8, dout=4):
    rng = _np.random.RandomState(seed)
    xs = rng.randn(n_batches, batch, din).astype(_np.float32)
    ys = rng.randn(n_batches, batch, dout).astype(_np.float32)
    return xs, ys


def _build(mode, dout=4):
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, parallel
    mx.random.seed(1234)
    _np.random.seed(1234)
    net = gluon.nn.Dense(dout)
    net.initialize()
    loss_fn = gluon.loss.L2Loss()
    if mode == "sharded":
        trainer = parallel.DataParallelTrainer(
            net, loss_fn, "adam", {"learning_rate": 0.05},
            shard_updates=True)

        def step(x, y):
            return trainer.step(mx.nd.array(x), mx.nd.array(y))
    else:
        trainer = gluon.Trainer(net.collect_params(), "adam",
                                {"learning_rate": 0.05})

        def step(x, y):
            from mxnet_tpu import autograd
            xb, yb = mx.nd.array(x), mx.nd.array(y)
            with autograd.record():
                loss = loss_fn(net(xb), yb)
            loss.backward()
            trainer.step(xb.shape[0])
            return loss
    return net, trainer, step


def _params_of(net):
    return {name: p.data().asnumpy()
            for name, p in net._collect_params_with_prefix().items()}


def _state_of(trainer):
    sd = trainer.state_dict()
    return {k: v.asnumpy() for k, v in sd["arrays"].items()}


def _bitwise(a, b):
    return set(a) == set(b) and \
        all(_np.array_equal(a[k], b[k]) for k in a)


def run_scenario(mode, total_steps=6, preempt_at=3, workdir=None,
                 resume_steps_per_call=1):
    """``resume_steps_per_call`` > 1 (ISSUE 6): the RESUME phase drives
    ``step_multi`` windows of that size instead of per-step calls — the
    surviving checkpoint sits at a step that is NOT a multiple of K
    (written mid-scan-window relative to the resumed run's grid), so
    this asserts that a non-K-aligned resume reproduces the K=1
    reference curve bitwise (partial tail windows included).  Needs a
    trainer with ``step_multi`` (the sharded mode)."""
    from mxnet_tpu.base import MXNetError
    from mxnet_tpu.checkpoint import CheckpointManager, run_preemptible
    from mxnet_tpu.testing import faults

    k_resume = int(resume_steps_per_call)
    if k_resume > 1 and mode != "sharded":
        raise MXNetError(
            "resume_steps_per_call>1 needs the sharded "
            "(DataParallelTrainer) scenario — gluon.Trainer is eager")
    ckdir = os.path.join(workdir, f"ckpt-{mode}-k{k_resume}")
    xs, ys = _make_data(99)
    result = {"mode": mode, "preempt_at": preempt_at,
              "total_steps": total_steps,
              "resume_steps_per_call": k_resume}

    # 1. reference: uninterrupted
    net, trainer, step = _build(mode)
    for i in range(total_steps):
        step(xs[i], ys[i])
    ref_params, ref_state = _params_of(net), _state_of(trainer)

    # 2. chaos run: writer killed on attempt 1, preempted at step K
    net, trainer, step = _build(mode)
    mgr = CheckpointManager(ckdir, keep=3)
    writer_died = False

    def loop(handler):
        nonlocal writer_died
        for i in range(total_steps):
            step(xs[i], ys[i])
            done = i + 1
            if handler.check_step(done):
                # preemption: force-sync the final checkpoint and stop
                mgr.save(done, params=net, trainer=trainer,
                         iterator={"batch": done}, sync=True)
                return done
            if done == 1:
                # kill THIS save's writer thread; the error must surface
                # on the NEXT save without dropping that next snapshot
                with faults.inject("checkpoint.write", times=1):
                    t1 = mgr.save(done, params=net, trainer=trainer,
                                  iterator={"batch": done})
                    # writer must HIT the armed fault before it disarms;
                    # the error stays unconsumed for the next save
                    t1._done.wait(30)
            else:
                try:
                    ticket = mgr.save(done, params=net, trainer=trainer,
                                      iterator={"batch": done})
                except MXNetError as e:
                    writer_died = True   # previous writer's death
                    ticket = getattr(e, "pending_ticket", None)
                if ticket is not None:
                    ticket.wait()
        return total_steps

    with faults.inject("train.step", at=preempt_at,
                       action=faults.preempt_action):
        preempted, stopped_at = run_preemptible(loop, mgr)
    result["writer_kill_surfaced"] = writer_died
    result["preempted_at"] = stopped_at
    result["preempted"] = preempted

    # 3. corrupt the newest checkpoint: latest() must skip to an older one
    newest = mgr.latest()
    faults.corrupt_file(os.path.join(
        mgr._step_dir(newest), "params.ndz"))
    fallback = mgr.latest()
    result["corrupt_skipped"] = {"newest": newest, "fallback": fallback,
                                 "ok": fallback is not None
                                 and fallback < newest}

    # 4. resume from the surviving checkpoint, replay to total_steps
    net, trainer, step = _build(mode)
    # resolve shapes before trainer state restore
    import mxnet_tpu as mx
    net(mx.nd.array(xs[0]))
    manifest = mgr.restore(params=net, trainer=trainer)
    start = manifest["iterator"]["batch"]
    result["resumed_from"] = manifest["step"]
    if k_resume > 1:
        # K-step compiled replay from a mid-window checkpoint: windows
        # re-form at the resumed step; the tail window may be short
        i = start
        while i < total_steps:
            w = min(k_resume, total_steps - i)
            trainer.step_multi(
                [(mx.nd.array(xs[j]), mx.nd.array(ys[j]))
                 for j in range(i, i + w)])
            i += w
    else:
        for i in range(start, total_steps):
            step(xs[i], ys[i])
    result["params_bitwise"] = _bitwise(ref_params, _params_of(net))
    result["state_bitwise"] = _bitwise(ref_state, _state_of(trainer))
    result["ok"] = bool(
        result["params_bitwise"] and result["state_bitwise"]
        and result["corrupt_skipped"]["ok"] and preempted
        and writer_died)
    return result


def main(argv=None):
    # the smoke must run anywhere — force the simulated CPU mesh exactly
    # like tests/conftest.py does
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    workdir = tempfile.mkdtemp(prefix="mxtpu-chaos-")
    try:
        results = [run_scenario(mode, workdir=workdir)
                   for mode in ("plain", "sharded")]
        # ISSUE 6: resume from the (non-K-aligned) surviving checkpoint
        # with K=4 multi-step windows — must still match K=1 bitwise
        results.append(run_scenario("sharded", workdir=workdir,
                                    resume_steps_per_call=4))
    finally:
        shutil.rmtree(workdir, ignore_errors=True)
    ok = all(r["ok"] for r in results)
    print(json.dumps({"chaos": results, "ok": ok}))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
