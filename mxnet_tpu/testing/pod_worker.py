"""Deterministic pod worker: real process, real collectives, real death.

``python -m mxnet_tpu.testing.pod_worker`` is the default workload
:class:`mxnet_tpu.pod.PodLauncher` spawns — one REAL process per rank
that rendezvouses over ``jax.distributed`` (the ``_dist_init`` env
seam fires at package import), then loops deterministic data-parallel
steps whose cross-process gradient sum runs through
``multihost_utils.process_allgather`` — a real collective over the
coordination service, so a wrong world size or a stale backend cannot
produce the right parameter digests.

Per step, gated by the launcher's ready/go files (the drain boundary):

1. serve: claim pending requests from the file-lease queue (atomic
   rename = one winner), write results to ``done``, release the lease.
   ``MXTPU_POD_HOLD_RANK`` makes that orig rank claim one lease and
   SIT on it — the workload shaping that guarantees the chaos kill
   lands on a lease holder; a surviving holder drains it before exit
   so fault-free runs stay exactly-once.
2. train: ``g_local = f(w, step, rank, world)`` (w-dependent, so any
   divergence compounds), allgather, host-side sum in rank order
   (deterministic), update, append the sha256 parameter digest.
3. checkpoint every ``MXTPU_POD_CKPT_EVERY`` steps (new-rank 0 writes,
   atomic rename; every rank holds identical w).

On a committed membership change (epoch bump in ``membership.json``,
observed while waiting at the gate) a survivor tears down and re-inits
the coordination service via ``_dist_init.reinit_distributed`` at the
new world size, restores w from the checkpoint, and resumes — which is
why its post-reshard digests must be BITWISE those of a fresh pod
restored from the same checkpoint at the same world size (the chaos
gate's core assertion).  Evidence lands in ``status.<orig>.json``
(pid, epoch, ``jax.process_count()``, reinit ms) and
``digests.<orig>.jsonl``; a per-worker ``PSServer`` on
``MXTPU_POD_PS_PORT`` is the fleet scrape endpoint.
"""
from __future__ import annotations

import hashlib
import json
import os
import sys
import time

import numpy as _np


def _env_int(name, default):
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _init_weights(dim):
    return _np.random.RandomState(1234).standard_normal(dim).astype(
        _np.float32)


def _local_grad(w, step, rank, world, dim):
    """Deterministic rank shard: depends on w (divergence compounds)
    and on (step, rank) but not on wall clock or pids."""
    rs = _np.random.RandomState(100_003 * step + 101 * rank + 7)
    batch = rs.standard_normal(dim).astype(_np.float32)
    return (_np.float32(0.01) * w * _np.float32(rank + 1)
            + batch / _np.float32(world))


def _save_ckpt(pod_dir, w, step):
    tmp = os.path.join(pod_dir, f"ckpt.tmp.{os.getpid()}.npz")
    _np.savez(tmp, w=w, step=_np.int64(step))
    os.replace(tmp, os.path.join(pod_dir, "ckpt.npz"))


def _load_ckpt(path):
    with _np.load(path) as z:
        return z["w"].astype(_np.float32), int(z["step"])


def main():
    pod_dir = os.environ["MXTPU_POD_DIR"]
    orig_rank = _env_int("MXTPU_POD_RANK", 0)
    epoch = _env_int("MXTPU_POD_EPOCH", 1)
    steps = _env_int("MXTPU_POD_STEPS", 8)
    ckpt_every = _env_int("MXTPU_POD_CKPT_EVERY", 3)
    ps_port = _env_int("MXTPU_POD_PS_PORT", 0)
    dim = _env_int("MXTPU_POD_DIM", 64)
    hold_rank = _env_int("MXTPU_POD_HOLD_RANK", -1)
    serve_per_step = _env_int("MXTPU_POD_SERVE_PER_STEP", 2)
    gate_timeout = float(os.environ.get("MXTPU_POD_GATE_TIMEOUT_S",
                                        "120"))

    import mxnet_tpu  # noqa: F401 — fires maybe_init_distributed
    from mxnet_tpu import pod as _pod
    from mxnet_tpu import telemetry as _telemetry
    from mxnet_tpu._dist_init import reinit_distributed
    import jax
    from jax.experimental import multihost_utils

    m = _pod.read_membership(pod_dir) or {
        "epoch": epoch, "world": 1, "ranks": {str(orig_rank): 0}}
    rank = int(m["ranks"][str(orig_rank)])
    world = int(m["world"])
    dirs = _pod.queue_dirs(pod_dir)
    if ps_port:
        from mxnet_tpu.kvstore.ps_server import PSServer
        PSServer("127.0.0.1", ps_port, 1)

    restore = os.environ.get("MXTPU_POD_RESTORE", "")
    ckpt_path = restore or os.path.join(pod_dir, "ckpt.npz")
    if restore or os.path.exists(ckpt_path):
        w, step0 = _load_ckpt(ckpt_path)
    else:
        w, step0 = _init_weights(dim), 0
    step = step0 + 1
    held = None          # (inflight_path, done_name, req) while holding
    reinit_ms = None

    def status(phase):
        _pod.write_json_atomic(
            os.path.join(pod_dir, f"status.{orig_rank}.json"),
            {"pid": os.getpid(), "orig_rank": orig_rank, "rank": rank,
             "epoch": epoch, "world": int(jax.process_count()),
             "step": step, "phase": phase, "ps_port": ps_port,
             "reinit_ms": reinit_ms})

    def serve_one(name, release=True):
        src = os.path.join(dirs["pending"], name)
        dst = os.path.join(dirs["inflight"],
                           f"{name}.lease.{orig_rank}")
        try:
            os.rename(src, dst)        # atomic claim: one winner
        except OSError:
            return None                # another rank won the race
        req = _pod.read_json(dst) or {}
        if not release:
            return (dst, name, req)
        _pod.write_json_atomic(
            os.path.join(dirs["done"], name),
            {"id": req.get("id"), "payload": req.get("payload"),
             "by": orig_rank, "epoch": epoch})
        os.unlink(dst)
        _telemetry.inc("pod.requests_served")
        return None

    def serve(limit):
        nonlocal held
        for name in sorted(os.listdir(dirs["pending"]))[:limit]:
            if held is None and orig_rank == hold_rank:
                held = serve_one(name, release=False)
                continue
            serve_one(name)

    def wait_gate():
        """Report ready; block for go or a newer membership epoch."""
        open(os.path.join(pod_dir,
                          f"ready.{epoch}.{step}.{orig_rank}"),
             "w").close()
        go = os.path.join(pod_dir, f"go.{epoch}.{step}")
        deadline = time.monotonic() + gate_timeout
        while True:
            if os.path.exists(go):
                return None
            mm = _pod.read_membership(pod_dir)
            if mm and int(mm["epoch"]) > epoch:
                return mm
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"rank {orig_rank}: no go/{epoch}/{step} within "
                    f"{gate_timeout}s")
            time.sleep(0.005)

    status("start")
    while step <= steps:
        status("gate")
        mm = wait_gate()
        if mm is not None:
            # committed membership change: drain here (the gate IS the
            # step boundary), reinit the coordination service at the
            # new world, restore from the shared checkpoint, resume
            if str(orig_rank) not in mm["ranks"]:
                return 3               # evicted (launcher saw us dead)
            epoch = int(mm["epoch"])
            rank = int(mm["ranks"][str(orig_rank)])
            world = int(mm["world"])
            reinit_ms = round(reinit_distributed(
                mm["coordinator"], world, rank) * 1e3, 3)
            _telemetry.inc("pod.reinits")
            _telemetry.set_gauge("pod.coordinator_reinit_ms", reinit_ms)
            _telemetry.set_gauge("elastic.epoch", epoch)
            _telemetry.event("pod.reinit", epoch=epoch, world=world,
                             rank=rank, dead=mm.get("dead"))
            if os.path.exists(ckpt_path):
                w, step0 = _load_ckpt(ckpt_path)
                step = step0 + 1
            status("reinit")
            continue
        serve(serve_per_step)
        g_local = _local_grad(w, step, rank, world, dim)
        gathered = _np.asarray(    # one allgather per STEP (the whole
            # update in one call), not per key — no O(n_keys) cliff
            multihost_utils.process_allgather(g_local))  # mxlint: disable=HB07 -- per-step, not per-key; see above
        g = gathered.sum(axis=0, dtype=_np.float32)
        w = (w - _np.float32(0.05) * g).astype(_np.float32)
        digest = hashlib.sha256(w.tobytes()).hexdigest()
        with open(os.path.join(pod_dir,
                               f"digests.{orig_rank}.jsonl"),
                  "a", encoding="utf-8") as f:
            f.write(json.dumps({"step": step, "epoch": epoch,
                                "rank": rank, "world": world,
                                "digest": digest}) + "\n")
        _telemetry.inc("pod.steps")
        _telemetry.observe("train.step_ms", 1.0)
        if rank == 0 and step % ckpt_every == 0:
            _save_ckpt(pod_dir, w, step)
        step += 1
    if held is not None:
        dst, name, req = held
        _pod.write_json_atomic(
            os.path.join(dirs["done"], name),
            {"id": req.get("id"), "payload": req.get("payload"),
             "by": orig_rank, "epoch": epoch})
        os.unlink(dst)
    status("done")
    return 0


if __name__ == "__main__":
    sys.exit(main())
