"""``mxnet_tpu.testing`` — fault-injection + chaos harness.

Production training at pod scale treats failure as the steady state
(ROADMAP north star; arXiv 1909.09756 §5): the only way to trust the
recovery machinery is to provoke failures deterministically.  This
package owns that machinery:

- :mod:`mxnet_tpu.testing.faults` — named fault points instrumented into
  the runtime (checkpoint writer, D2H, PS heartbeats, train step), armed
  via the :func:`~mxnet_tpu.testing.faults.inject` context manager or
  the ``MXTPU_FAULT_INJECT`` env hook.
- :mod:`mxnet_tpu.testing.chaos` — the self-contained kill-and-resume
  smoke scenario ``tools/tpu_queue_runner.py --chaos`` runs.
"""
from . import faults

__all__ = ["faults"]
