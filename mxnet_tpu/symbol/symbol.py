"""Symbol: a lazy expression graph evaluated by mx.nd ops.

Reference: python/mxnet/symbol/symbol.py. See package docstring for the
disposition; notably `simple_bind` shape inference runs the graph with
jax.eval_shape (XLA abstract interpretation replaces the nnvm InferShape
pass, reference src/executor/infer_graph_attr_pass.cc).
"""
from __future__ import annotations

import json

import numpy as _np

from ..base import MXNetError
from ..ndarray.ndarray import NDArray
from .. import ndarray as _nd

__all__ = ["Symbol", "var", "Variable", "Group", "load", "load_json"]


class AttrScope:
    """``with mx.AttrScope(ctx_group='stage1'):`` — attrs attached to every
    Symbol created in the scope. Reference: python/mxnet/attribute.py (the
    manual model-parallel placement mechanism: ctx_group + bind's
    group2ctx, SURVEY.md §2.5 "Model parallel")."""

    _stack = []

    def __init__(self, **attrs):
        self._attrs = attrs

    @classmethod
    def _current(cls):
        merged = {}
        for scope in cls._stack:
            merged.update(scope._attrs)
        return merged

    def __enter__(self):
        AttrScope._stack.append(self)
        return self

    def __exit__(self, *exc):
        AttrScope._stack.pop()
        return False


class Symbol:
    """A node in the lazy expression graph."""

    def __init__(self, op, args, kwargs, name=None, outputs=None):
        self._op = op                  # str op name or None for var
        self._args = args              # list of Symbol / constants
        self._kwargs = kwargs
        self._name = name or (op if op else "var")
        self._outputs = outputs        # for Group / multi-output slicing
        self._out_index = None
        self._attrs = dict(AttrScope._current()) if AttrScope._stack else {}

    # -- construction ---------------------------------------------------
    @staticmethod
    def _var(name, shape=None, **kwargs):
        sym = Symbol(None, [], {}, name=name)
        if shape is not None:
            # reference mx.sym.var(shape=...): a declared shape lets the
            # executor materialize vars no _PARAM_SHAPE_RULES entry covers
            # (e.g. the packed RNN parameter vector)
            sym._declared_shape = tuple(int(s) for s in shape)
        return sym

    @property
    def name(self):
        return self._name

    def list_arguments(self):
        out = []
        def walk(s):
            if s._op is None and s._outputs is None:
                if s._name not in out:
                    out.append(s._name)
            for a in s._args:
                if isinstance(a, Symbol):
                    walk(a)
            if s._outputs:
                for o in s._outputs:
                    walk(o)
        walk(self)
        return out

    def list_outputs(self):
        if self._outputs:
            return [o._name + "_output" for o in self._outputs]
        return [self._name + "_output"]

    def list_auxiliary_states(self):
        return []

    # -- composition ----------------------------------------------------
    def __call__(self, **kwargs):
        return self

    def __getitem__(self, idx):
        if self._outputs:
            return self._outputs[idx]
        out = Symbol(self._op, self._args, dict(self._kwargs),
                     name=f"{self._name}[{idx}]")
        out._out_index = idx
        # evaluation routes through the BASE symbol so a multi-output op
        # executes once however many of its outputs are consumed
        out._base = self
        return out

    def attr(self, key):
        return self._attrs.get(key) if self._attrs else None

    def get_internals(self):
        return Group(_collect_nodes(self))

    # -- arithmetic -----------------------------------------------------
    def _bin(self, other, opname):
        return Symbol(opname, [self, other], {})

    def __add__(self, other):
        return self._bin(other, "_plus")

    __radd__ = __add__

    def __sub__(self, other):
        return self._bin(other, "_minus")

    def __rsub__(self, other):
        return Symbol("_rminus", [self, other], {})

    def __mul__(self, other):
        return self._bin(other, "_mul")

    __rmul__ = __mul__

    def __truediv__(self, other):
        return self._bin(other, "_div")

    def __rtruediv__(self, other):
        return Symbol("_rdiv", [self, other], {})

    def __pow__(self, other):
        return self._bin(other, "_pow")

    def __neg__(self):
        return Symbol("negative", [self], {})

    # -- evaluation -----------------------------------------------------
    def _eval(self, bindings, cache=None, ctx_map=None):
        cache = {} if cache is None else cache
        key = id(self)
        if key in cache:
            return cache[key]
        if self._op is None and self._outputs is None:
            if self._name not in bindings:
                raise MXNetError(f"unbound symbol variable '{self._name}'")
            out = bindings[self._name]
        elif self._outputs is not None:
            out = [o._eval(bindings, cache, ctx_map) for o in self._outputs]
        elif getattr(self, "_base", None) is not None:
            out = self._base._eval(bindings, cache, ctx_map)
            out = out[self._out_index]
        else:
            args = [a._eval(bindings, cache, ctx_map)
                    if isinstance(a, Symbol) else a for a in self._args]
            if ctx_map:
                group = self._attrs.get("ctx_group")
                dev = ctx_map.get(group)
                if dev is not None:
                    # cross-device hop as a TAPE-VISIBLE op: device_put is
                    # a differentiable jax primitive, so the cotangent
                    # transfers back automatically in backward (the manual
                    # model-parallel boundary, reference group2ctx in
                    # Symbol.bind / example/model-parallel)
                    args = [_to_device(a, dev) for a in args]
            out = _apply_nd_op(self._op, args, self._kwargs)
            if self._out_index is not None:
                out = out[self._out_index]
        cache[key] = out
        return out

    def eval(self, ctx=None, **kwargs):
        out = self._eval(kwargs)
        return out if isinstance(out, list) else [out]

    # -- binding --------------------------------------------------------
    def simple_bind(self, ctx=None, grad_req="write", **shapes):
        from ..module.executor import Executor
        return Executor(self, ctx, shapes, grad_req=grad_req)

    def bind(self, ctx, args, args_grad=None, grad_req="write",
             aux_states=None, group2ctx=None):
        from ..module.executor import Executor
        return Executor(self, ctx, None, args=args, args_grad=args_grad,
                        grad_req=grad_req)

    def infer_shape(self, **shapes):
        """Shape inference via jax.eval_shape over the graph."""
        import jax
        import jax.numpy as jnp
        args = self.list_arguments()
        unknown = [a for a in args if a not in shapes]

        def run(*arrs):
            bindings = {name: NDArray(arr)
                        for name, arr in zip(known, arrs)}
            out = self._eval(bindings)
            outs = out if isinstance(out, list) else [out]
            return tuple(o.data for o in outs)

        known = [a for a in args if a in shapes]
        if unknown:
            return None, None, None
        protos = [jax.ShapeDtypeStruct(tuple(shapes[a]), jnp.float32)
                  for a in known]
        from .. import _tape
        with _tape.trace_scope():
            out_shapes = jax.eval_shape(run, *protos)
        return ([tuple(shapes[a]) for a in args],
                [tuple(o.shape) for o in out_shapes], [])

    def infer_type(self, **dtypes):
        args = self.list_arguments()
        return ([_np.float32] * len(args), [_np.float32], [])

    # -- serialization --------------------------------------------------
    def tojson(self):
        nodes = []
        index = {}

        def emit(s):
            if s._op == "__traced_fn__":
                raise MXNetError(
                    "symbols from autograd.get_symbol cannot be saved to "
                    "JSON (their ops are in-process closures); use "
                    "hybridize()+export() for deployable graphs")
            if id(s) in index:
                return index[id(s)]
            arg_ids = []
            for a in s._args:
                if isinstance(a, Symbol):
                    arg_ids.append(emit(a))
                else:
                    arg_ids.append(["const", a])
            node = {"op": s._op or "null", "name": s._name,
                    "attrs": {k: str(v) for k, v in s._kwargs.items()},
                    "inputs": arg_ids}
            declared = getattr(s, "_declared_shape", None)
            if declared is not None:
                # var(shape=...) must survive the round-trip or reloaded
                # graphs can't materialize the variable (e.g. nd.RNN's
                # packed parameter vector)
                node["shape"] = list(declared)
            if s._attrs:
                # AttrScope attrs (ctx_group etc.) must survive the json
                # round-trip or reloaded models lose their model-parallel
                # placement silently
                node["node_attrs"] = {k: str(v)
                                      for k, v in s._attrs.items()}
            nodes.append(node)
            index[id(s)] = len(nodes) - 1
            return len(nodes) - 1

        heads = self._outputs if self._outputs else [self]
        head_ids = [emit(h) for h in heads]
        return json.dumps({"format": "mxnet_tpu-symbol-v1", "nodes": nodes,
                           "heads": head_ids}, indent=2)

    def save(self, fname):
        with open(fname, "w") as f:
            f.write(self.tojson())

    def __repr__(self):
        return f"<Symbol {self._name}>"


def _to_device(a, dev):
    """Move an eval value to ``dev`` (a jax device) through the autograd
    tape; non-arrays and already-placed arrays pass through."""
    from ..ndarray.ndarray import NDArray, apply_nary
    import jax
    if not isinstance(a, NDArray):
        return a
    try:
        if a.data.devices() == {dev}:
            return a
    except Exception:  # noqa: BLE001 — uncommitted arrays just move
        pass
    return apply_nary(lambda d: jax.device_put(d, dev), [a],
                      name="_cross_device_copy")


def _collect_nodes(sym):
    seen = []
    def walk(s):
        for a in s._args:
            if isinstance(a, Symbol):
                walk(a)
        seen.append(s)
    walk(sym)
    return seen


def _apply_nd_op(opname, args, kwargs):
    if opname == "__traced_fn__":
        # autograd.get_symbol nodes: the recorded forward closure IS the
        # op (raw jax arrays in/out); n_out tells how to wrap
        from ..ndarray.ndarray import apply_nary
        fn = kwargs["_fn"]
        if not callable(fn):
            raise MXNetError(
                "this symbol came from autograd.get_symbol and was "
                "reloaded from JSON — traced closures are not "
                "serializable; rebuild it with get_symbol in-process "
                "(hybridize()+export() is the deployment path)")
        n_out = kwargs.get("_n_out", 1)
        return apply_nary(fn, list(args), n_out=n_out,
                          name=kwargs.get("_name", "traced"))
    special = {
        "_plus": lambda a, b: a + b, "_minus": lambda a, b: a - b,
        "_rminus": lambda a, b: b - a, "_mul": lambda a, b: a * b,
        "_div": lambda a, b: a / b, "_rdiv": lambda a, b: b / a,
        "_pow": lambda a, b: a ** b,
    }
    if opname in special:
        return special[opname](*args)
    if opname in ("LinearRegressionOutput", "MAERegressionOutput",
                  "LogisticRegressionOutput"):
        data, label = args[0], args[1] if len(args) > 1 else None
        if opname == "LogisticRegressionOutput":
            return _nd.sigmoid(data)
        return data
    if opname == "SoftmaxOutput" and (len(args) < 2 or args[1] is None):
        return _nd.softmax(args[0])    # predict path: no label bound
    if not hasattr(_nd, opname):
        raise MXNetError(f"symbol op '{opname}' has no nd implementation")
    return getattr(_nd, opname)(*args, **kwargs)


def var(name, shape=None, dtype=None, init=None, **kwargs):
    return Symbol._var(name, shape=shape)


Variable = var


def Group(symbols):
    return Symbol(None, [], {}, name="group", outputs=list(symbols))


def load(fname):
    with open(fname) as f:
        return load_json(f.read())


def load_json(json_str):
    data = json.loads(json_str)
    if data.get("format") != "mxnet_tpu-symbol-v1":
        raise MXNetError(
            "cannot load legacy nnvm symbol.json graphs: rebuild the network "
            "with gluon/model_zoo and load the .params file instead "
            "(SURVEY.md §2.1 Symbol row)")
    nodes = data["nodes"]
    built = []
    for node in nodes:
        if node["op"] == "null":
            v = var(node["name"], shape=node.get("shape"))
            if node.get("node_attrs"):
                v._attrs = dict(node["node_attrs"])
            built.append(v)
        else:
            args = []
            for ref in node["inputs"]:
                if isinstance(ref, list) and ref and ref[0] == "const":
                    args.append(ref[1])
                else:
                    args.append(built[ref])
            kwargs = {k: _parse_attr(v) for k, v in
                      node.get("attrs", {}).items()}
            sym = Symbol(node["op"], args, kwargs, name=node["name"])
            if node.get("node_attrs"):
                sym._attrs = dict(node["node_attrs"])
            built.append(sym)
    heads = [built[i] for i in data["heads"]]
    return heads[0] if len(heads) == 1 else Group(heads)


def _parse_attr(v):
    try:
        return json.loads(v.replace("(", "[").replace(")", "]")
                          .replace("'", '"'))
    except Exception:
        if v in ("True", "False"):
            return v == "True"
        return v


# ----------------------------------------------------------------------
# op mirrors: every mx.nd op is constructible symbolically
# ----------------------------------------------------------------------

# Parameterized ops auto-create their weight variables when not supplied,
# named {name}_{param} — the reference's hidden-variable behavior that
# Module.init_params depends on (python/mxnet/symbol: auto 'fc1_weight').
# Param shapes are materialized at bind time (module/executor.py rules).
_OP_PARAMS = {
    "FullyConnected": ("weight", "bias"),
    "Convolution": ("weight", "bias"),
    "Deconvolution": ("weight", "bias"),
    "BatchNorm": ("gamma", "beta", "moving_mean", "moving_var"),
    "LayerNorm": ("gamma", "beta"),
    "InstanceNorm": ("gamma", "beta"),
    "Embedding": ("weight",),
    # loss heads auto-create their label variable ({name}_label)
    "SoftmaxOutput": ("label",),
    "LinearRegressionOutput": ("label",),
    "MAERegressionOutput": ("label",),
    "LogisticRegressionOutput": ("label",),
}
_AUTO_NAME_COUNTER = {}


def _auto_name(opname):
    # reference python/mxnet/name.py: the innermost NameManager owns both
    # prefix and numbering, and a fresh scope restarts counts — so mixing
    # scoped and unscoped creation in ONE graph can collide (same upstream;
    # pass explicit name= where it matters). Prefixed names never collide
    # with unprefixed ones.
    from .. import name as _name_mod
    mgr = _name_mod.current()
    if mgr is not None:
        return mgr.get(None, opname.lower())
    i = _AUTO_NAME_COUNTER.get(opname, 0)
    _AUTO_NAME_COUNTER[opname] = i + 1
    return f"{opname.lower()}{i}"


def _make_op(opname):
    def op(*args, name=None, **kwargs):
        name = name or _auto_name(opname)
        args = list(args)
        if not args and "data" in kwargs:
            args.append(kwargs.pop("data"))    # data-as-kwarg call style
        params = _OP_PARAMS.get(opname, ())
        if params:
            n_given = max(len(args) - 1, 0)    # params supplied by caller
            # nd.Deconvolution defaults no_bias=True, the others False
            no_bias = kwargs.get("no_bias", opname == "Deconvolution")
            for p in params[n_given:]:
                if p == "bias" and no_bias:
                    args.append(None)
                else:
                    args.append(Symbol._var(f"{name}_{p}"))
        return Symbol(opname, args, kwargs, name=name)
    op.__name__ = opname
    return op


def __getattr__(opname):
    if opname.startswith("_"):
        raise AttributeError(opname)
    if hasattr(_nd, opname):
        return _make_op(opname)
    raise AttributeError(opname)


# commonly used ops pre-bound for introspection/tab-completion
for _name in ["FullyConnected", "Convolution", "Activation", "Pooling",
              "SoftmaxOutput", "Flatten", "BatchNorm", "Dropout", "Concat",
              "LeakyReLU", "Embedding", "Reshape", "transpose", "flip",
              "mean", "softmax", "log_softmax", "broadcast_add",
              "broadcast_mul", "zeros", "ones",
              "LinearRegressionOutput", "LogisticRegressionOutput",
              "MAERegressionOutput"]:
    globals()[_name] = _make_op(_name)
