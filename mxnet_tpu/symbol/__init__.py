"""``mx.sym`` — Symbol facade.

Reference: python/mxnet/symbol/ (~5k LoC over the nnvm graph). Disposition
per SURVEY.md §2.1 "Symbol/nnvm graph": the symbolic IR is absorbed by
jaxpr/StableHLO; this module keeps a thin, *executable* Symbol facade so
Module-API scripts and `sym.json` tooling keep working:

  - ``mx.sym.var`` / every nd op mirrored lazily: builds a small expression
    graph of (op, args, kwargs)
  - ``Symbol.bind / simple_bind`` -> an Executor that evaluates the graph
    with mx.nd ops
  - ``tojson`` / ``load_json`` round-trip the expression graph
"""
from . import symbol as _symbol_mod
from .symbol import (Symbol, AttrScope, var, Variable, Group, load,
                     load_json)


def __getattr__(name):
    return getattr(_symbol_mod, name)
