"""Scaled low-precision matmul for TRAINING (ISSUE 20).

PR 3 carried quantization onto the wire (int8 reduce-scatter with
stochastic rounding) and PR 7 onto serving weights (QuantizedDense);
this module carries it into COMPUTE: ``quant_matmul(a, b)`` runs the
trainer's dense contractions through int8 or fp8 inputs with exact
wide accumulation, behind ``MXTPU_COMPUTE_DTYPE`` (unset = bitwise
``jnp.matmul``, the kill-switch contract).

Scaling math, per mode:

- **int8**: per-tensor amax scaling (scale = amax/127) with the PR 3
  UNBIASED stochastic rounding — ``floor(x/scale + u)``, u ~ U[0,1) —
  so E[dequant(quant(x))] == x and the training signal keeps no
  systematic bias; the contraction accumulates in int32 (exact), then
  rescales in f32.  The SR noise key is deterministic per call site
  AND data-dependent (folded from the tensor's sum bits), so repeated
  steps draw fresh noise while runs stay reproducible.
- **fp8**: e4m3 inputs (max 448) with per-tensor amax scaling,
  round-to-nearest (fp8 keeps a mantissa, so RTN is already unbiased
  to first order; SR is the int8 story), f32 accumulation via
  ``preferred_element_type``.

Gradients (``jax.custom_vjp``): the straight-through estimator for the
rounding itself, with the grad-side matmuls ALSO quantized —
``da = dy @ b.T`` and ``db = a.T @ dy`` run through the same machinery
(e5m2 for fp8 grads: gradients need e5m2's range, not e4m3's
precision).  Plain autodiff would differentiate ``floor`` to zero;
the custom VJP is load-bearing, not cosmetic.

Scale selection is **current** (amax of this step's tensor, in-graph)
on the trainer wiring; the **delayed** variant — amax history window,
scale from the running max, the FP8-LM recipe — is the functional
threaded-state API (:func:`init_delayed_state` /
:func:`quant_matmul_delayed`), forward-only (no custom VJP; thread it
where grads are not taken, or wire its scales into ``quant_matmul``).

Numerically fragile call sites opt OUT per tag: a tag in
:func:`bf16_fallback_tags` (``MXTPU_QUANT_BF16_ALLOW`` + defaults)
computes in bf16 with f32 accumulation instead of 8-bit.

Telemetry: with the registry enabled at trace time, every quantized
site publishes ``quant.amax.<tag>.{a,w}`` and
``quant.overflow_pct.<tag>`` gauges (saturation fraction — nonzero
means a stale/clipped scale) through a ``jax.debug.callback``; off by
default, so the hot path carries zero host syncs (HB10 discipline).

This module (``ops/quant*``) is the sanctioned home for raw
low-precision ``astype`` — mxlint HB21 flags the pattern elsewhere.
"""
from __future__ import annotations

import os
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from ..base import MXNetError
from .. import telemetry as _telem

__all__ = ["quant_matmul", "resolve_compute_dtype",
           "quantize_sr_int8", "dequantize_int8", "quantize_rtn_int8",
           "bf16_fallback_tags", "init_delayed_state",
           "quant_matmul_delayed", "INT8_MAX", "FP8_MAX",
           "FP8_GRAD_MAX"]

INT8_MAX = 127.0
FP8_MAX = 448.0        # float8_e4m3fn max normal (forward inputs)
FP8_GRAD_MAX = 57344.0  # float8_e5m2 max normal (grad-side range)

#: call-site tags that always fall back to bf16 (numerically fragile
#: contractions: logit heads and normalization-adjacent matmuls keep
#: more mantissa than 8 bits).  MXTPU_QUANT_BF16_ALLOW extends this.
_DEFAULT_BF16_TAGS = frozenset({"head", "logits"})

_BASE_KEY = None


def resolve_compute_dtype(value=None):
    """Canonical training compute mode: ``"int8"``, ``"fp8"``, or
    ``None`` (= f32 ``jnp.matmul``, today's trainer).  ``None`` input
    reads ``MXTPU_COMPUTE_DTYPE``; unset/empty/``0``/``off``/``fp32``
    resolve to ``None`` (bitwise-inert kill switch).  Unknown values
    raise — a typo must not silently train full-width."""
    if value is None:
        value = os.environ.get("MXTPU_COMPUTE_DTYPE", "")
    v = str(value).strip().lower()
    if v in ("", "0", "off", "none", "fp32", "float32"):
        return None
    if v in ("int8", "i8"):
        return "int8"
    if v in ("fp8", "float8", "float8_e4m3fn"):
        return "fp8"
    raise MXNetError(
        f"MXTPU_COMPUTE_DTYPE={value!r}: expected int8|fp8|fp32")


def bf16_fallback_tags():
    """Tags whose matmuls compute in bf16 instead of 8-bit: the
    defaults plus ``MXTPU_QUANT_BF16_ALLOW`` (comma-separated)."""
    raw = os.environ.get("MXTPU_QUANT_BF16_ALLOW", "")
    extra = {t.strip() for t in raw.split(",") if t.strip()}
    return frozenset(_DEFAULT_BF16_TAGS | extra)


# ---------------------------------------------------------------------------
# int8 stochastic rounding — the PR 3 wire-quantization core, moved
# here so the wire (parallel/zero.py) and compute paths share ONE
# rounding implementation (zero.py re-exports these names).
# ---------------------------------------------------------------------------

def _sr_cast_int8(v, key):
    """Unbiased stochastic round of pre-scaled values to int8 codes:
    floor(v + u), u ~ U[0,1) — E[result] == v before the clip."""
    u = jax.random.uniform(key, v.shape, jnp.float32)
    return jnp.clip(jnp.floor(v + u), -127, 127).astype(jnp.int8)


def quantize_sr_int8(flat, key):
    """(codes int8, scale f32 scalar): stochastic-rounding blockwise
    quantization at per-tensor amax scale.  Unbiased:
    E[dequant(quant(x))] == x, so a cross-chip mean (the EQuARX wire
    use) and a training matmul (this module) keep no systematic
    error."""
    scale = jnp.maximum(jnp.max(jnp.abs(flat)) / INT8_MAX, 1e-30)
    return _sr_cast_int8(flat / scale, key), scale


def dequantize_int8(codes, scale):
    return codes.astype(jnp.float32) * scale


def quantize_rtn_int8(x, scale):
    """Round-to-nearest int8 at a FIXED (calibrated) scale — the PR 7
    serving activation quantization (QuantizedDense), op-for-op, so
    the engine's decode-parity contract survives the refactor
    bit-for-bit."""
    return jnp.clip(jnp.round(x / scale), -127, 127).astype(jnp.int8)


def _sr_key(x, salt):
    """Deterministic, data-dependent SR noise key: a fixed base key
    folded with a static per-operand salt and the bits of the
    tensor's f32 sum — different steps see different data, hence
    fresh noise; identical runs draw identical noise."""
    global _BASE_KEY
    if _BASE_KEY is None:
        _BASE_KEY = jax.random.key(20)
    bits = lax.bitcast_convert_type(
        jnp.sum(x, dtype=jnp.float32), jnp.uint32)
    return jax.random.fold_in(jax.random.fold_in(_BASE_KEY, salt), bits)


# ---------------------------------------------------------------------------
# the quantized 2D contraction (forward + quantized grad-side)
# ---------------------------------------------------------------------------

def _amax_scale(x, qmax):
    return jnp.maximum(jnp.max(jnp.abs(x)) / qmax, 1e-30) \
        .astype(jnp.float32)


def _qmm_impl(a, b, mode, tag, grad_side=False):
    """One quantized (M,K)@(K,N) contraction in f32-equivalent space:
    quantize both operands at per-tensor amax scale, contract in wide
    accumulation, rescale.  ``grad_side`` switches fp8 to e5m2 (range
    over precision for gradients)."""
    if mode == "int8":
        sa, sb = _amax_scale(a, INT8_MAX), _amax_scale(b, INT8_MAX)
        qa = _sr_cast_int8(a / sa, _sr_key(a, 0))
        qb = _sr_cast_int8(b / sb, _sr_key(b, 1))
        acc = lax.dot_general(qa, qb, (((1,), (0,)), ((), ())),
                              preferred_element_type=jnp.int32)
        out = acc.astype(jnp.float32) * (sa * sb)
        sat = jnp.mean((jnp.abs(qa) >= 127).astype(jnp.float32))
    else:
        qmax = FP8_GRAD_MAX if grad_side else FP8_MAX
        fp8 = jnp.float8_e5m2 if grad_side else jnp.float8_e4m3fn
        sa, sb = _amax_scale(a, qmax), _amax_scale(b, qmax)
        qa = jnp.clip(a / sa, -qmax, qmax).astype(fp8)
        qb = jnp.clip(b / sb, -qmax, qmax).astype(fp8)
        acc = lax.dot_general(qa, qb, (((1,), (0,)), ((), ())),
                              preferred_element_type=jnp.float32)
        out = acc * (sa * sb)
        sat = jnp.mean(
            (jnp.abs(qa.astype(jnp.float32)) >= qmax)
            .astype(jnp.float32))
    if not grad_side and _telem.enabled():
        # amax/saturation gauges ride an async debug callback —
        # published only when the registry is on at TRACE time, so the
        # default hot path stays host-sync-free
        jax.debug.callback(
            partial(_publish_stats, tag, mode),
            sa * (INT8_MAX if mode == "int8" else FP8_MAX),
            sb * (INT8_MAX if mode == "int8" else FP8_MAX), sat)
    return out


def _publish_stats(tag, mode, amax_a, amax_w, sat):
    _telem.set_gauge(f"quant.amax.{tag}.a", round(float(amax_a), 6))
    _telem.set_gauge(f"quant.amax.{tag}.w", round(float(amax_w), 6))
    _telem.set_gauge(f"quant.overflow_pct.{tag}",
                     round(float(sat) * 100.0, 4))
    _telem.inc(f"quant.matmuls.{mode}")


@partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _qmm(a, b, mode, tag):
    return _qmm_impl(a, b, mode, tag)


def _qmm_fwd(a, b, mode, tag):
    return _qmm_impl(a, b, mode, tag), (a, b)


def _qmm_bwd(mode, tag, res, dy):
    # straight-through for the rounding; the grad matmuls themselves
    # are quantized (the tentpole contract: low-precision compute on
    # BOTH sides of the step, not just the forward)
    a, b = res
    da = _qmm_impl(dy, b.T, mode, tag, grad_side=True)
    db = _qmm_impl(a.T, dy, mode, tag, grad_side=True)
    return da, db


_qmm.defvjp(_qmm_fwd, _qmm_bwd)


def quant_matmul(a, b, compute_dtype=None, tag="mm"):
    """``a @ b`` through the scaled low-precision path.

    a : (..., K) activations (leading dims flattened for the 2D
        contraction and restored after — per-tensor scales make the
        reshape exact).
    b : (K, N) weight-side operand.
    compute_dtype : ``"int8"`` / ``"fp8"`` / None; None reads
        ``MXTPU_COMPUTE_DTYPE`` and falls back to the EXACT
        ``jnp.matmul`` when unset (bitwise kill switch).
    tag : call-site label for telemetry and the bf16 fallback
        allowlist."""
    mode = resolve_compute_dtype(compute_dtype)
    if mode is None:
        return jnp.matmul(a, b)
    if b.ndim != 2:
        raise MXNetError(f"quant_matmul: b must be 2D (K, N), got "
                         f"{b.shape}")
    lead = a.shape[:-1]
    flat = a.reshape(-1, a.shape[-1])
    if tag in bf16_fallback_tags():
        # numerically fragile site: bf16 operands, f32 accumulation —
        # plain autodiff (casts are linear; no rounding to estimate
        # through)
        y = lax.dot_general(flat.astype(jnp.bfloat16),
                            b.astype(jnp.bfloat16),
                            (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
    else:
        y = _qmm(flat, b, mode, tag)
    return y.reshape(lead + (b.shape[1],))


# ---------------------------------------------------------------------------
# delayed (amax-history) scaling — the threaded-state variant
# ---------------------------------------------------------------------------

def init_delayed_state(history=16):
    """Fresh amax-history state for ONE quant_matmul_delayed site:
    a rolling window per operand, zeros = "no history yet" (the first
    step falls back to current scaling)."""
    if history < 1:
        raise MXNetError(f"history {history} must be >= 1")
    return {"a": jnp.zeros((history,), jnp.float32),
            "b": jnp.zeros((history,), jnp.float32)}


def _delayed_scale(hist, cur_amax, qmax):
    h = jnp.max(hist)
    amax = jnp.where(h > 0, h, cur_amax)  # cold start: current scaling
    return jnp.maximum(amax / qmax, 1e-30)


def quant_matmul_delayed(a, b, state, compute_dtype=None, tag="mm"):
    """``(y, new_state)``: the delayed-scaling variant — scales come
    from the amax HISTORY (max over the window), not this step's
    tensor, so the scale is known before the tensor exists (the FP8-LM
    recipe; on real hardware this removes the amax reduction from the
    critical path).  A stale scale CLIPS — watch
    ``quant.overflow_pct``.  Forward-only (no custom VJP): thread it
    where gradients are not taken, or feed its scales to
    :func:`quant_matmul`."""
    mode = resolve_compute_dtype(compute_dtype)
    if mode is None:
        return jnp.matmul(a, b), state
    if a.ndim != 2 or b.ndim != 2:
        raise MXNetError("quant_matmul_delayed operates on 2D operands")
    qmax = INT8_MAX if mode == "int8" else FP8_MAX
    cur_a = jnp.max(jnp.abs(a))
    cur_b = jnp.max(jnp.abs(b))
    sa = _delayed_scale(state["a"], cur_a, qmax)
    sb = _delayed_scale(state["b"], cur_b, qmax)
    if mode == "int8":
        qa = _sr_cast_int8(a / sa, _sr_key(a, 0))
        qb = _sr_cast_int8(b / sb, _sr_key(b, 1))
        acc = lax.dot_general(qa, qb, (((1,), (0,)), ((), ())),
                              preferred_element_type=jnp.int32)
        y = acc.astype(jnp.float32) * (sa * sb)
    else:
        qa = jnp.clip(a / sa, -qmax, qmax).astype(jnp.float8_e4m3fn)
        qb = jnp.clip(b / sb, -qmax, qmax).astype(jnp.float8_e4m3fn)
        acc = lax.dot_general(qa, qb, (((1,), (0,)), ((), ())),
                              preferred_element_type=jnp.float32)
        y = acc * (sa * sb)
    new_state = {"a": jnp.roll(state["a"], 1).at[0].set(cur_a),
                 "b": jnp.roll(state["b"], 1).at[0].set(cur_b)}
    return y, new_state
