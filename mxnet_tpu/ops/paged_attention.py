"""Paged decode attention: block-table gather kernel for serving.

The serving decode step attends one query token per sequence against a
block-table paged KV cache (``serving.kv_cache.PagedKVCache``).  The
engine's original formulation gathers the sequence's blocks into a
dense ``(B, L, KVH, D)`` view with a jnp fancy-index and runs the
shared ``llama._cache_attention`` math — correct, but on TPU the
gather materializes the full context width per step in HBM traffic.

This module packages that step as one op with two interchangeable
bodies (the ``ops.flash_attention`` discipline):

- **Pallas path** (TPU only): a ``PrefetchScalarGridSpec`` kernel whose
  K/V BlockSpec index maps read the BLOCK TABLE itself — grid step
  ``(b, j)`` DMAs physical block ``table[b, j]`` straight from the pool
  into VMEM and folds it into a per-sequence online-softmax
  accumulator.  Only the sequence's own blocks ever move; there is no
  dense gather.  Blocks wholly past ``pos`` are masked per-position
  (write-ahead garbage and table padding contribute exactly 0).
- **XLA fallback** (CPU, or any geometry the kernel declines): the
  engine's original gather + ``_cache_attention``, op-for-op — so on
  the fallback path this function is BITWISE the inline formulation it
  replaces (the parity gate in tests/test_paged_attention.py), and
  ``MXTPU_PAGED_ATTN`` is a bitwise-inert routing knob on CPU hosts.

Low-precision pools (ISSUE 20): when the engine stores the KV pool in
fp8 (``MXTPU_KV_DTYPE=fp8``) it passes the per-token-row amax scale
planes (``k_scale`` / ``v_scale``, one f32 scalar per written cache
row) and both bodies dequantize AFTER the block-table gather — the
gathered rows are codes × their row scales, so HBM traffic stays at
fp8 width and only VMEM-resident tiles widen to f32.  A bf16 pool
passes no scales; codes are upcast directly.  ``k_scale=None`` on an
f32 pool is the original op, untouched.

The Pallas body compiles only on TPU backends (``_use_pallas`` gate,
like flash); structure tests assert its shape and skip execution
elsewhere.  TPU-vs-fallback numerics are gated by the TPU round's
bench_diff, not claimed here.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["paged_decode_attention"]

_NEG_INF = -1e30


def _use_pallas(block_size, kv_heads, head_dim):
    """Pallas only on TPU backends, and only for geometries Mosaic
    tiles well (lane dim = head_dim multiple of 64, sublane = block
    rows multiple of 8).  Anything else: the bitwise fallback."""
    if jax.default_backend() != "tpu":
        return False
    return head_dim % 64 == 0 and block_size % 8 == 0


def _fallback(q, k_pool, v_pool, block_tables, pos, scale,
              k_scale=None, v_scale=None):
    """The engine's original decode attention, verbatim on an f32
    pool: dense gather through the block table, then the shared
    single-block online-softmax (one source with the full forward, so
    decode parity cannot drift — llama._cache_attention).  Quantized
    pools dequantize the gathered view first: codes upcast to f32 and,
    when scale planes ride along (fp8), multiply by the per-row amax
    scales gathered through the SAME block table."""
    from ..gluon.model_zoo.nlp.llama import _cache_attention
    from .quant_kv import kv_dequantize
    B = q.shape[0]
    nbl = block_tables.shape[1]
    bs, kvh, d = k_pool.shape[1:]
    L = nbl * bs
    ck = k_pool[block_tables].reshape(B, L, kvh, d)
    cv = v_pool[block_tables].reshape(B, L, kvh, d)
    if k_scale is not None:
        ck = kv_dequantize(ck, k_scale[block_tables].reshape(B, L))
        cv = kv_dequantize(cv, v_scale[block_tables].reshape(B, L))
    elif k_pool.dtype != jnp.float32:
        ck = kv_dequantize(ck)
        cv = kv_dequantize(cv)
    ck = ck.transpose(0, 2, 1, 3)
    cv = cv.transpose(0, 2, 1, 3)
    valid = jnp.arange(L)[None, :] <= pos[:, None]
    return _cache_attention(q, ck, cv, valid, scale)


def _pallas_paged(q, k_pool, v_pool, block_tables, pos, scale,
                  k_scale=None, v_scale=None):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, h, d = q.shape
    bs, kvh, _d = k_pool.shape[1:]
    nbl = block_tables.shape[1]
    rep = h // kvh
    scaled = k_scale is not None
    lowp = k_pool.dtype != jnp.float32

    def kernel(bt_ref, pos_ref, *refs):
        # refs layout: q, k, v[, ks, vs], o, acc, m_i, l_i — the scale
        # rows ride as extra block-table-gathered inputs when present
        if scaled:
            (q_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref,
             acc, m_i, l_i) = refs
        else:
            q_ref, k_ref, v_ref, o_ref, acc, m_i, l_i = refs
        b = pl.program_id(0)
        j = pl.program_id(1)

        @pl.when(j == 0)
        def _init():
            m_i[:] = jnp.full_like(m_i, _NEG_INF)
            l_i[:] = jnp.zeros_like(l_i)
            acc[:] = jnp.zeros_like(acc)

        p = pos_ref[b]
        # a block wholly past the query position contributes nothing —
        # skip its compute (table padding points at the null block and
        # lands here too, since padded indices start past pos)
        @pl.when(j * bs <= p)
        def _step():
            qg = q_ref[0].reshape(kvh, rep, d)        # grouped queries
            kb = k_ref[0]                             # (bs, kvh, d)
            vb = v_ref[0]
            if lowp:
                kb = kb.astype(jnp.float32)
                vb = vb.astype(jnp.float32)
            if scaled:
                # per-token-row amax scales: one f32 scalar per cache
                # row, broadcast over (kvh, d)
                kb = kb * ks_ref[0][:, None, None]
                vb = vb * vs_ref[0][:, None, None]
            s = jnp.einsum("grd,tgd->grt", qg, kb,
                           preferred_element_type=jnp.float32) * scale
            kpos = j * bs + lax.broadcasted_iota(
                jnp.int32, (kvh, rep, bs), 2)
            s = jnp.where(kpos <= p, s, _NEG_INF)
            m_new = jnp.maximum(m_i[:], jnp.max(s, axis=-1,
                                                keepdims=True))
            pr = jnp.exp(s - m_new)
            alpha = jnp.exp(m_i[:] - m_new)
            l_i[:] = l_i[:] * alpha + jnp.sum(pr, axis=-1, keepdims=True)
            acc[:] = acc[:] * alpha + jnp.einsum(
                "grt,tgd->grd", pr.astype(vb.dtype), vb,
                preferred_element_type=jnp.float32)
            m_i[:] = m_new

        @pl.when(j == nbl - 1)
        def _fin():
            out = acc[:] / jnp.maximum(l_i[:], 1e-30)
            o_ref[0] = out.reshape(h, d).astype(o_ref.dtype)

    in_specs = [
        pl.BlockSpec((1, h, d), lambda b, j, bt, ps: (b, 0, 0)),
        # gather-by-block-table: the index map reads the prefetched
        # table, so grid step (b, j) DMAs physical block bt[b, j]
        pl.BlockSpec((1, bs, kvh, d),
                     lambda b, j, bt, ps: (bt[b, j], 0, 0, 0)),
        pl.BlockSpec((1, bs, kvh, d),
                     lambda b, j, bt, ps: (bt[b, j], 0, 0, 0)),
    ]
    operands = [q, k_pool, v_pool]
    if scaled:
        # scale planes gather through the same table: step (b, j)
        # DMAs the matching (block_size,) row of per-token scales
        in_specs += [
            pl.BlockSpec((1, bs), lambda b, j, bt, ps: (bt[b, j], 0)),
            pl.BlockSpec((1, bs), lambda b, j, bt, ps: (bt[b, j], 0)),
        ]
        operands += [k_scale, v_scale]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,          # block tables + positions
        grid=(B, nbl),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, h, d),
                               lambda b, j, bt, ps: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((kvh, rep, d), jnp.float32),
            pltpu.VMEM((kvh, rep, 1), jnp.float32),
            pltpu.VMEM((kvh, rep, 1), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, h, d), q.dtype),
    )(block_tables, pos, *operands)
    return out.reshape(B, h * d)


def paged_decode_attention(q, k_pool, v_pool, block_tables, pos, scale,
                           k_scale=None, v_scale=None):
    """One decode step of attention against a paged KV cache.

    q : (B, H, D) current-position queries, already rotated.
    k_pool / v_pool : (num_blocks, block_size, KVH, D) — ONE layer's
        slice of the engine's pool; f32, bf16, or fp8 codes.
    block_tables : (B, n_blocks_bucket) int32 physical block ids per
        sequence (null-block padded).
    pos : (B,) int32 position being written this step; cache positions
        ``<= pos`` participate, everything later (write-ahead garbage,
        padding) is masked.
    scale : softmax scale (1/sqrt(D)).
    k_scale / v_scale : (num_blocks, block_size) f32 per-token-row
        amax scales for an fp8 pool (ONE layer's plane), or None for
        f32/bf16 pools.  Gathered by the same block table and applied
        after the gather in both bodies.

    Returns (B, H*D).  Traced inside the engine's compiled decode /
    verify graphs — both bodies are pure jnp/pallas on jax arrays.
    """
    bs, kvh, d = k_pool.shape[1:]
    if _use_pallas(bs, kvh, d):
        return _pallas_paged(q, k_pool, v_pool, block_tables, pos,
                             scale, k_scale=k_scale, v_scale=v_scale)
    return _fallback(q, k_pool, v_pool, block_tables, pos, scale,
                     k_scale=k_scale, v_scale=v_scale)
