"""Fused linear + softmax-cross-entropy with blocked vocabulary.

The LM-head bottleneck at long context is not FLOPs but HBM: materializing
``logits = x @ W`` costs O(N * V) activation memory (a (8, 2048, 32k)
bf16 logit tensor is ~1 GB before softmax intermediates), and autodiff
keeps it alive for the backward pass.  This op computes

    loss_i = logsumexp_v(x_i . W[:, v]) - x_i . W[:, target_i]

with a ``lax.scan`` over vocabulary blocks (online logsumexp, the same
streaming trick flash attention uses over keys), so peak activation
memory is O(N * block) and the full logit tensor never exists.  The
backward pass recomputes each block's softmax from the saved
``(x, logsumexp)`` — FLOPs traded for memory, exactly the
rematerialization economics TPUs want (HBM-bound, MXU-rich).

Reference context: the reference computes SoftmaxOutput/softmax_cross_entropy
on materialized logits (src/operator/nn/softmax.cc, softmax_output.cc) —
fine at V<=32k on GPU-era batches; this op is the TPU-first replacement
for the large-V long-context regime.  Public API surface is
``mxnet_tpu.ops.fused_linear_cross_entropy`` plus the NDArray wrapper
``mx.nd.contrib.fused_linear_cross_entropy``.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["fused_linear_cross_entropy"]


def _pad_vocab(w, block):
    d, v = w.shape
    pad = (-v) % block
    if pad:
        w = jnp.pad(w, ((0, 0), (0, pad)))
    return w, v + pad


def _scan_lse_and_target(x, w, targets, block, v_real):
    """One pass over vocab blocks: online logsumexp + the target logit.

    x: (N, d) f32; w: (d, Vpad) any float dtype (cast per BLOCK, so a
    bf16 head weight is never copied whole to f32); targets: (N,) int32.
    Returns (lse (N,), t_logit (N,))."""
    n = x.shape[0]
    nblk = w.shape[1] // block
    wb = w.reshape(w.shape[0], nblk, block).transpose(1, 0, 2)  # (nb,d,bv)

    def step(carry, args):
        m, s, t = carry
        wblk, v0 = args
        logits = x @ wblk.astype(jnp.float32)                # (N, bv)
        # mask the padded tail out of the logsumexp
        valid = (v0 + jnp.arange(block)) < v_real
        logits = jnp.where(valid[None, :], logits, -jnp.inf)
        bm = jnp.max(logits, axis=-1)
        new_m = jnp.maximum(m, bm)
        # rescale the running sum; exp(-inf - finite) == 0 handles blocks
        # that are entirely padding
        s = s * jnp.exp(m - new_m) + \
            jnp.sum(jnp.exp(logits - new_m[:, None]), axis=-1)
        # target logit if it lives in this block
        rel = targets - v0
        in_blk = (rel >= 0) & (rel < block)
        rel_c = jnp.clip(rel, 0, block - 1)
        t = jnp.where(in_blk, jnp.take_along_axis(
            logits, rel_c[:, None], axis=1)[:, 0], t)
        return (new_m, s, t), None

    init = (jnp.full((n,), -jnp.inf, jnp.float32),
            jnp.zeros((n,), jnp.float32),
            jnp.zeros((n,), jnp.float32))
    v0s = jnp.arange(nblk) * block
    (m, s, t), _ = lax.scan(step, init, (wb, v0s))
    return m + jnp.log(s), t


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def fused_linear_cross_entropy(x, w, targets, block=2048,
                               ignore_index=None):
    """Per-token CE loss of a linear head, vocab processed in blocks.

    x: (N, d) activations; w: (d, V) head weight (kept in its own dtype;
    each block is cast to f32 on the fly); targets: (N,) int.  Returns
    per-token loss (N,) float32.  Tokens whose target equals
    ``ignore_index`` OR falls outside [0, V) contribute zero loss and
    zero gradient (padding semantics, like the reference's
    SoftmaxOutput ignore_label).  O(N*block) peak activation memory; the
    (N, V) logit tensor is never materialized (forward OR backward — the
    backward recomputes block softmax from the saved logsumexp)."""
    loss, _ = _fwd(x, w, targets, block, ignore_index)
    return loss


def _valid_tokens(t, v_real, ignore_index):
    valid = (t >= 0) & (t < v_real)
    if ignore_index is not None:
        valid = valid & (t != ignore_index)
    return valid


def _fwd(x, w, targets, block, ignore_index):
    xf = x.astype(jnp.float32)
    t = targets.astype(jnp.int32)
    wp, _ = _pad_vocab(w, block)
    lse, t_logit = _scan_lse_and_target(xf, wp, t, block, w.shape[1])
    valid = _valid_tokens(t, w.shape[1], ignore_index)
    loss = jnp.where(valid, lse - t_logit, 0.0)
    return loss, (x, w, t, lse)


def _bwd(block, ignore_index, res, g):
    x, w, t, lse = res
    xf = x.astype(jnp.float32)
    v_real = w.shape[1]
    wp, vpad = _pad_vocab(w, block)
    nblk = vpad // block
    wb = wp.reshape(wp.shape[0], nblk, block).transpose(1, 0, 2)
    # ignored/out-of-range tokens get zero gradient
    g = g * _valid_tokens(t, v_real, ignore_index).astype(g.dtype)

    def step(carry, args):
        dx, = carry
        wblk, v0 = args
        wf32 = wblk.astype(jnp.float32)
        logits = xf @ wf32                                  # (N, bv)
        valid = (v0 + jnp.arange(block)) < v_real
        p = jnp.where(valid[None, :],
                      jnp.exp(logits - lse[:, None]), 0.0)  # block softmax
        rel = t - v0
        in_blk = (rel >= 0) & (rel < block)
        onehot = (jnp.arange(block)[None, :] == rel[:, None]) & \
            in_blk[:, None]
        dlogits = (p - onehot.astype(p.dtype)) * g[:, None]  # (N, bv)
        dx = dx + dlogits @ wf32.T
        dwblk = xf.T @ dlogits                               # (d, bv)
        return (dx,), dwblk

    v0s = jnp.arange(nblk) * block
    (dx,), dwb = lax.scan(step, (jnp.zeros_like(xf),), (wb, v0s))
    dw = dwb.transpose(1, 0, 2).reshape(wp.shape)[:, :v_real]
    return dx.astype(x.dtype), dw.astype(w.dtype), None


fused_linear_cross_entropy.defvjp(
    lambda x, w, targets, block=2048, ignore_index=None:
    _fwd(x, w, targets, block, ignore_index), _bwd)
