"""Fused multi-tensor optimizer update: one Pallas kernel per flat bucket.

The ZeRO-1 pipeline (parallel/zero.py) already flattens gradients into a
few large f32 buckets; the per-bucket optimizer update, however, still
lowers to a chain of separate XLA elementwise HLOs.  On TPU this module
replaces that chain with ONE Pallas kernel that streams the flat bucket
through VMEM once — read p/g/state, do the whole update math per
element, write p'/state' — instead of materializing each intermediate in
HBM (the reference's ``multi_sgd_mom_update`` / ``multi_mp_sgd`` fused
CUDA kernels, src/operator/optimizer_op.cc, rebuilt as Pallas).

Entry points:

``fused_bucket_rule(name, clip_gradient=None, **hyper)``
    same contract as ``optimizer.fused_rule`` — ``(init, apply)`` with
    ``apply(p, g, s, lr, wd) -> (new_p, new_state)`` — but ``apply``
    routes eligible flat f32 payloads through the Pallas kernel on TPU
    and otherwise falls back to the *exact* ``fused_rule`` kernel (same
    function object), so CPU numerics are bitwise-unchanged.

Eligibility: rule in {sgd, nag, adam, adamw}, f32 payload, TPU backend,
``MXTPU_PALLAS_UPDATE`` not ``0``.  Everything else silently takes the
XLA fallback — the kernel is an optimization, never a correctness gate.

The gluon ``Trainer`` fused group update concatenates its whole
parameter group into one flat bucket per state-layout (trainer.py
``_fused_jit_update``) and calls this rule once — "one kernel walks the
bucket" instead of one update chain per parameter.
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp

__all__ = ["fused_bucket_rule", "pallas_update_enabled", "PALLAS_RULES"]

#: rules with a Pallas bucket kernel; the rest always use the XLA chain
PALLAS_RULES = frozenset({"sgd", "nag", "adam", "adamw"})

_LANE = 128
_SUBLANE = 8


def pallas_update_enabled():
    """``MXTPU_PALLAS_UPDATE=0`` kills the Pallas bucket kernels (XLA
    fallback everywhere); default on — the TPU-backend gate still
    applies."""
    return os.environ.get("MXTPU_PALLAS_UPDATE", "1") != "0"


def _block_rows(n_rows, preferred=256):
    """Largest multiple-of-8 divisor of ``n_rows`` up to ``preferred``;
    None if n_rows is not a multiple of 8 (caller pads to avoid that)."""
    b = min(preferred, n_rows)
    b -= b % _SUBLANE
    while b >= _SUBLANE:
        if n_rows % b == 0:
            return b
        b -= _SUBLANE
    return None


def _pad_to_grid(flat, preferred=256):
    """(padded_2d, rows, block_rows, pad): reshape a flat f32 vector to
    (rows, 128) padded so a multiple-of-8 row block divides it."""
    n = flat.shape[0]
    rows = -(-n // _LANE)
    rows += (-rows) % _SUBLANE           # full (8, 128) tiles
    br = _block_rows(rows, preferred)    # rows % 8 == 0 => br >= 8
    pad = rows * _LANE - n
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    return flat.reshape(rows, _LANE), rows, br, pad


def _scalar_spec():
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    return pl.BlockSpec((1, 1), lambda i: (0, 0),
                        memory_space=pltpu.SMEM)


def _vec_spec(br):
    from jax.experimental import pallas as pl
    return pl.BlockSpec((br, _LANE), lambda i: (i, 0))


# ---------------------------------------------------------------------------
# kernels (one grid step = one (block_rows, 128) tile of the bucket)
# ---------------------------------------------------------------------------

def _sgd_kernel(momentum, nesterov, clip):
    def kernel(lr_ref, wd_ref, p_ref, g_ref, *refs):
        lr = lr_ref[0, 0]
        wd = wd_ref[0, 0]
        p = p_ref[:]
        g = g_ref[:]
        if clip is not None:
            g = jnp.clip(g, -clip, clip)
        g = g + wd * p
        if not momentum:
            refs[0][:] = p - lr * g
            return
        m_ref, out_p, out_m = refs
        if nesterov:
            m = momentum * m_ref[:] + g
            out_p[:] = p - lr * (g + momentum * m)
        else:
            m = momentum * m_ref[:] - lr * g
            out_p[:] = p + m
        out_m[:] = m
    return kernel


def _adam_kernel(beta1, beta2, epsilon, decoupled_wd, clip):
    def kernel(lr_ref, wd_ref, tf_ref, p_ref, g_ref, m_ref, v_ref,
               out_p, out_m, out_v):
        lr = lr_ref[0, 0]
        wd = wd_ref[0, 0]
        tf = tf_ref[0, 0]
        p = p_ref[:]
        g = g_ref[:]
        if clip is not None:
            g = jnp.clip(g, -clip, clip)
        if not decoupled_wd:
            g = g + wd * p
        m = beta1 * m_ref[:] + (1 - beta1) * g
        v = beta2 * v_ref[:] + (1 - beta2) * jnp.square(g)
        lr_t = lr * jnp.sqrt(1 - beta2 ** tf) / (1 - beta1 ** tf)
        new_p = p - lr_t * m / (jnp.sqrt(v) + epsilon)
        if decoupled_wd:
            new_p = new_p - lr * wd * p
        out_p[:] = new_p
        out_m[:] = m
        out_v[:] = v
    return kernel


def _run_pallas(kernel, scalars, tensors, n_out, br, rows,
                interpret=False):
    from jax.experimental import pallas as pl
    out = pl.pallas_call(
        kernel,
        grid=(rows // br,),
        in_specs=[_scalar_spec() for _ in scalars]
        + [_vec_spec(br) for _ in tensors],
        out_specs=[_vec_spec(br) for _ in range(n_out)],
        out_shape=[jax.ShapeDtypeStruct((rows, _LANE), jnp.float32)
                   for _ in range(n_out)],
        interpret=interpret,
    )(*[jnp.asarray(s, jnp.float32).reshape(1, 1) for s in scalars],
      *tensors)
    return out


def _pallas_sgd(p, g, s, lr, wd, momentum, nesterov, clip,
                interpret=False):
    n = p.shape[0]
    p2, rows, br, _ = _pad_to_grid(p)
    g2 = _pad_to_grid(g)[0]
    kernel = _sgd_kernel(momentum, nesterov, clip)
    if momentum:
        m2 = _pad_to_grid(s["mom"])[0]
        new_p, new_m = _run_pallas(kernel, (lr, wd), (p2, g2, m2), 2,
                                   br, rows, interpret)
        return (new_p.reshape(-1)[:n],
                {"mom": new_m.reshape(-1)[:n]})
    (new_p,) = _run_pallas(kernel, (lr, wd), (p2, g2), 1, br, rows,
                           interpret)
    return new_p.reshape(-1)[:n], dict(s)


def _pallas_adam(p, g, s, lr, wd, beta1, beta2, epsilon, decoupled_wd,
                 clip, interpret=False):
    n = p.shape[0]
    p2, rows, br, _ = _pad_to_grid(p)
    g2 = _pad_to_grid(g)[0]
    m2 = _pad_to_grid(s["m"])[0]
    v2 = _pad_to_grid(s["v"])[0]
    t = s["t"] + 1
    tf = t.astype(jnp.float32) if hasattr(t, "astype") else float(t)
    kernel = _adam_kernel(beta1, beta2, epsilon, decoupled_wd, clip)
    new_p, new_m, new_v = _run_pallas(
        kernel, (lr, wd, tf), (p2, g2, m2, v2), 3, br, rows, interpret)
    return (new_p.reshape(-1)[:n],
            {"m": new_m.reshape(-1)[:n], "v": new_v.reshape(-1)[:n],
             "t": t})


def _pallas_apply(name, hyper, clip, p, g, s, lr, wd, interpret=False):
    """Dispatch one flat f32 bucket through the rule's Pallas kernel."""
    if name in ("sgd", "nag"):
        momentum = float(hyper.get("momentum", 0.0))
        return _pallas_sgd(p, g, s, lr, wd, momentum, name == "nag",
                           clip, interpret)
    return _pallas_adam(p, g, s, lr, wd,
                        float(hyper.get("beta1", 0.9)),
                        float(hyper.get("beta2", 0.999)),
                        float(hyper.get("epsilon", 1e-8)),
                        name == "adamw", clip, interpret)


def _eligible(name, p):
    return (name in PALLAS_RULES
            and pallas_update_enabled()
            and jax.default_backend() == "tpu"
            and getattr(p, "ndim", 0) == 1
            and p.dtype == jnp.float32)


def fused_bucket_rule(name, clip_gradient=None, **hyper):
    """``optimizer.fused_rule`` contract with the Pallas fast path: the
    returned ``apply`` runs the flat-bucket Pallas kernel when eligible
    (TPU + flat f32 + supported rule) and the exact ``fused_rule``
    kernel — the identical function — everywhere else."""
    from ..optimizer.optimizer import fused_rule
    init, base_apply = fused_rule(name, clip_gradient=clip_gradient,
                                  **hyper)

    @functools.wraps(base_apply)
    def apply(p, g, s, lr, wd):
        if _eligible(name, p):
            try:
                return _pallas_apply(name, hyper, clip_gradient,
                                     p, g, s, lr, wd)
            except Exception:  # noqa: BLE001 — kernel lowering is an
                # optimization; the XLA chain is always valid
                pass
        return base_apply(p, g, s, lr, wd)

    return init, apply
