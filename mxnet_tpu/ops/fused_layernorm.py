"""Fused LayerNorm + residual (+dropout): Pallas TPU kernel + XLA fallback.

Transformer blocks pay LayerNorm twice per layer, and in the reference
each one lowers to a chain of mean/var/normalize/scale HLOs with the
residual add materialized separately.  This op fuses
``LayerNorm(x [+ residual]) * gamma + beta`` into ONE pass over the
activation: each (rows, D) tile is read from HBM once, the row
statistics are computed in f32 in VMEM, and the normalized output is
written once — no mean/var/centered intermediates round-trip through
HBM.  The backward is fused the same way (dx plus dgamma/dbeta partials
accumulated across sequential grid steps), recomputing the row
statistics from the saved inputs instead of storing them
(flash-attention's recompute-in-backward discipline, ops/flash_attention.py).

Layout: ``x`` is (..., D), normalized over the LAST axis; ``gamma`` /
``beta`` are (D,).  On TPU with D a multiple of 128 and the flattened
row count a multiple of 8 the Pallas kernels run; everything else takes
a jnp fallback with identical f32 accumulation semantics — the fallback
is the numerics reference the kernel is gated against
(tests/test_fused_kernels.py).

``dropout`` (optional) is applied to ``x`` *before* the residual add —
the post-attention ``LayerNorm(residual + dropout(x))`` shape — using
the standard inverted scaling; the dropout mask itself is XLA-side (the
kernel fuses the add+normalize that dominates the HBM traffic).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["fused_layer_norm"]

_LANE = 128
_SUBLANE = 8


def _pick_rows(rows, sublane=_SUBLANE, preferred=256):
    """Largest multiple-of-``sublane`` divisor of ``rows`` up to
    ``preferred``; None when rows is not a multiple of it (fallback
    path then runs)."""
    if rows % sublane:
        return None
    b = min(preferred, rows)
    b -= b % sublane
    while b >= sublane:
        if rows % b == 0:
            return b
        b -= sublane
    return None


def _use_pallas(rows, d, dtype=jnp.float32):
    import os
    if jax.default_backend() != "tpu":
        return None
    if os.environ.get("MXTPU_FUSED_LN", "1") == "0":
        return None
    if d % _LANE:
        return None
    # sublane tiling granularity depends on dtype (pallas guide): f32
    # tiles are (8, 128), bf16 (16, 128); anything else falls back
    if dtype == jnp.float32:
        sublane = _SUBLANE
    elif dtype == jnp.bfloat16:
        sublane = 2 * _SUBLANE
    else:
        return None
    return _pick_rows(rows, sublane)


# ---------------------------------------------------------------------------
# Pallas kernels (rows = flattened leading dims, D = normalized axis)
# ---------------------------------------------------------------------------

def _forward_kernel(eps, has_res):
    def kernel(x_ref, *refs):
        if has_res:
            res_ref, gamma_ref, beta_ref, y_ref = refs
            h = x_ref[:].astype(jnp.float32) \
                + res_ref[:].astype(jnp.float32)
        else:
            gamma_ref, beta_ref, y_ref = refs
            h = x_ref[:].astype(jnp.float32)
        mean = jnp.mean(h, axis=1, keepdims=True)
        var = jnp.mean(jnp.square(h - mean), axis=1, keepdims=True)
        xhat = (h - mean) * lax.rsqrt(var + eps)
        y = xhat * gamma_ref[:].astype(jnp.float32) \
            + beta_ref[:].astype(jnp.float32)
        y_ref[:] = y.astype(y_ref.dtype)
    return kernel


def _backward_kernel(eps, has_res):
    from jax.experimental import pallas as pl

    def kernel(x_ref, *refs):
        if has_res:
            res_ref, gamma_ref, dy_ref, dx_ref, dg_ref, db_ref = refs
            h = x_ref[:].astype(jnp.float32) \
                + res_ref[:].astype(jnp.float32)
        else:
            gamma_ref, dy_ref, dx_ref, dg_ref, db_ref = refs
            h = x_ref[:].astype(jnp.float32)
        i = pl.program_id(0)

        @pl.when(i == 0)
        def _init():
            dg_ref[:] = jnp.zeros_like(dg_ref)
            db_ref[:] = jnp.zeros_like(db_ref)

        mean = jnp.mean(h, axis=1, keepdims=True)
        var = jnp.mean(jnp.square(h - mean), axis=1, keepdims=True)
        rstd = lax.rsqrt(var + eps)
        xhat = (h - mean) * rstd
        dy = dy_ref[:].astype(jnp.float32)
        a = dy * gamma_ref[:].astype(jnp.float32)
        c1 = jnp.mean(a * xhat, axis=1, keepdims=True)
        c2 = jnp.mean(a, axis=1, keepdims=True)
        dx_ref[:] = ((a - c2 - xhat * c1) * rstd).astype(dx_ref.dtype)
        # dgamma/dbeta partials: the grid is sequential on TPU, so
        # accumulating into the single shared (1, D) output block is the
        # standard reduction-across-grid pattern
        dg_ref[:] = dg_ref[:] + jnp.sum(dy * xhat, axis=0, keepdims=True)
        db_ref[:] = db_ref[:] + jnp.sum(dy, axis=0, keepdims=True)
    return kernel


def _pallas_forward(x2, res2, gamma, beta, eps, br, interpret=False):
    from jax.experimental import pallas as pl
    rows, d = x2.shape
    has_res = res2 is not None
    row_spec = pl.BlockSpec((br, d), lambda i: (i, 0))
    vec_spec = pl.BlockSpec((1, d), lambda i: (0, 0))
    ins = [x2] + ([res2] if has_res else []) \
        + [gamma.reshape(1, d), beta.reshape(1, d)]
    return pl.pallas_call(
        _forward_kernel(eps, has_res),
        grid=(rows // br,),
        in_specs=[row_spec] + ([row_spec] if has_res else [])
        + [vec_spec, vec_spec],
        out_specs=row_spec,
        out_shape=jax.ShapeDtypeStruct((rows, d), x2.dtype),
        interpret=interpret,
    )(*ins)


def _pallas_backward(x2, res2, gamma, dy2, eps, br, interpret=False):
    from jax.experimental import pallas as pl
    rows, d = x2.shape
    has_res = res2 is not None
    row_spec = pl.BlockSpec((br, d), lambda i: (i, 0))
    vec_spec = pl.BlockSpec((1, d), lambda i: (0, 0))
    ins = [x2] + ([res2] if has_res else []) \
        + [gamma.reshape(1, d), dy2]
    dx, dg, db = pl.pallas_call(
        _backward_kernel(eps, has_res),
        grid=(rows // br,),
        in_specs=[row_spec] + ([row_spec] if has_res else [])
        + [vec_spec, row_spec],
        out_specs=[row_spec, vec_spec, vec_spec],
        out_shape=[jax.ShapeDtypeStruct((rows, d), x2.dtype),
                   jax.ShapeDtypeStruct((1, d), jnp.float32),
                   jax.ShapeDtypeStruct((1, d), jnp.float32)],
        interpret=interpret,
    )(*ins)
    return dx, dg[0], db[0]


# ---------------------------------------------------------------------------
# XLA fallback (identical f32 accumulation; the numerics reference)
# ---------------------------------------------------------------------------

def _fallback_forward(x, res, gamma, beta, eps):
    h = x.astype(jnp.float32)
    if res is not None:
        h = h + res.astype(jnp.float32)
    mean = jnp.mean(h, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(h - mean), axis=-1, keepdims=True)
    xhat = (h - mean) * lax.rsqrt(var + eps)
    y = xhat * gamma.astype(jnp.float32) + beta.astype(jnp.float32)
    return y.astype(x.dtype)


def _fallback_backward(x, res, gamma, dy, eps):
    h = x.astype(jnp.float32)
    if res is not None:
        h = h + res.astype(jnp.float32)
    mean = jnp.mean(h, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(h - mean), axis=-1, keepdims=True)
    rstd = lax.rsqrt(var + eps)
    xhat = (h - mean) * rstd
    dyf = dy.astype(jnp.float32)
    a = dyf * gamma.astype(jnp.float32)
    c1 = jnp.mean(a * xhat, axis=-1, keepdims=True)
    c2 = jnp.mean(a, axis=-1, keepdims=True)
    dx = ((a - c2 - xhat * c1) * rstd).astype(x.dtype)
    reduce_axes = tuple(range(x.ndim - 1))
    dgamma = jnp.sum(dyf * xhat, axis=reduce_axes)
    dbeta = jnp.sum(dyf, axis=reduce_axes)
    return dx, dgamma, dbeta


# ---------------------------------------------------------------------------
# custom VJP core
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def _fused_ln(x, res, gamma, beta, eps):
    return _fused_ln_fwd(x, res, gamma, beta, eps)[0]


def _fused_ln_fwd(x, res, gamma, beta, eps):
    d = x.shape[-1]
    rows = x.size // d
    br = _use_pallas(rows, d, x.dtype)
    if br is not None:
        x2 = x.reshape(rows, d)
        res2 = None if res is None else res.reshape(rows, d)
        y = _pallas_forward(x2, res2, gamma, beta, eps, br) \
            .reshape(x.shape)
    else:
        y = _fallback_forward(x, res, gamma, beta, eps)
    return y, (x, res, gamma)


def _fused_ln_bwd(eps, saved, dy):
    x, res, gamma = saved
    d = x.shape[-1]
    rows = x.size // d
    br = _use_pallas(rows, d, x.dtype)
    if br is not None:
        x2 = x.reshape(rows, d)
        res2 = None if res is None else res.reshape(rows, d)
        dx2, dgamma, dbeta = _pallas_backward(
            x2, res2, gamma, dy.reshape(rows, d), eps, br)
        dx = dx2.reshape(x.shape)
    else:
        dx, dgamma, dbeta = _fallback_backward(x, res, gamma, dy, eps)
    dres = None if res is None else dx.astype(res.dtype)
    return (dx, dres, dgamma.astype(gamma.dtype),
            dbeta.astype(gamma.dtype))


_fused_ln.defvjp(_fused_ln_fwd, _fused_ln_bwd)


# ---------------------------------------------------------------------------
# public op (NDArray tape-aware, like ops.flash_attention)
# ---------------------------------------------------------------------------

def fused_layer_norm(x, gamma, beta, residual=None, eps=1e-5,
                     dropout=0.0, training=None):
    """``LayerNorm(dropout(x) + residual) * gamma + beta`` in one fused
    pass over the activation (last-axis normalization, f32 statistics).

    ``x``: (..., D); ``gamma``/``beta``: (D,); ``residual``: optional
    (..., D) added before normalization (the transformer post-sublayer
    shape).  ``dropout`` > 0 applies inverted dropout to ``x`` before
    the residual add when training (``mx.autograd`` recording state by
    default).  Differentiable (custom VJP, fused backward) and
    tape-aware: NDArray inputs under ``autograd.record()`` record one
    tape node.  On TPU with D % 128 == 0 the core runs as a Pallas
    kernel; otherwise an identical-semantics XLA fallback.
    """
    from ..ndarray.ndarray import NDArray, apply_nary
    from .. import _tape

    if training is None:
        training = _tape.is_training()
    rate = float(dropout)

    def core(*raw):
        if residual is not None:
            xd, gd, bd, rd = raw
        else:
            (xd, gd, bd), rd = raw, None
        if xd.ndim < 1 or gd.shape != (xd.shape[-1],):
            raise ValueError(
                f"fused_layer_norm: x (..., D) with gamma/beta (D,); got "
                f"x {xd.shape}, gamma {gd.shape}")
        if rate > 0.0 and training:
            from ..ndarray import random as _rnd
            keep = 1.0 - rate
            mask = jax.random.bernoulli(_rnd.next_key(), keep, xd.shape)
            xd = jnp.where(mask, xd / keep, 0.0).astype(xd.dtype)
        return _fused_ln(xd, rd, gd, bd, float(eps))

    inputs = [x, gamma, beta] + ([residual] if residual is not None
                                 else [])
    if isinstance(x, NDArray):
        inputs = [a if isinstance(a, NDArray) else NDArray(jnp.asarray(a))
                  for a in inputs]
        return apply_nary(core, inputs, name="fused_layer_norm")
    return core(*[jnp.asarray(a) for a in inputs])
