"""Flash attention: Pallas TPU kernel + blockwise-XLA fallback.

Reference capability: the fused ``contrib`` multi-head attention ops
(src/operator/contrib/transformer.cc [>=1.6]) — but those materialize the
(Lq, Lk) score matrix; this is the online-softmax streaming algorithm, so
HBM traffic is O(L*D) not O(L^2) (SURVEY.md §5.7 TPU plan).

Layout: (B, H, L, D). The Pallas path tiles Lq into BQ-row blocks and
streams Lk in BK-column blocks through VMEM, with a float32 accumulator
and running (max, denom) per query row; the MXU sees two
(BQ, D) x (D, BK) / (BQ, BK) x (BK, D) matmuls per step. The fallback is
the same algorithm as a ``lax.scan`` over KV blocks, which XLA fuses
adequately on CPU and keeps memory O(L*BK).

Gradients: custom VJP; the backward pass recomputes scores blockwise from
the saved logsumexp (standard flash-attention backward), also as a scan —
no O(L^2) residuals are ever stored.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["flash_attention"]

_NEG_INF = -1e30


def _pick_block(n, preferred=512):
    """Largest multiple-of-128 divisor of n up to `preferred`; None if n
    is not a multiple of 128 (pallas path then declines)."""
    if n % 128:
        return None
    b = min(preferred, n)
    b -= b % 128
    while b >= 128:
        if n % b == 0:
            return b
        b -= 128
    return None


# ---------------------------------------------------------------------------
# Pallas TPU forward
# ---------------------------------------------------------------------------

def _pallas_forward(q, k, v, causal, sm_scale, bq, bk):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bh, lq, d = q.shape
    lk = k.shape[1]
    nq, nk = lq // bq, lk // bk

    def kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc, m_i, l_i):
        i = pl.program_id(1)
        j = pl.program_id(2)

        @pl.when(j == 0)
        def _init():
            m_i[:] = jnp.full_like(m_i, _NEG_INF)
            l_i[:] = jnp.zeros_like(l_i)
            acc[:] = jnp.zeros_like(acc)

        # Causal: the whole KV block is in the future of the whole Q block
        # when j*bk > i*bq + bq - 1 — skip its compute entirely.
        live = (i + 1) * bq > j * bk if causal else True

        @pl.when(live)
        def _step():
            qb = q_ref[0]                       # (bq, d)
            kb = k_ref[0]                       # (bk, d)
            vb = v_ref[0]                       # (bk, d)
            s = lax.dot_general(
                qb, kb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * sm_scale
            if causal:
                qpos = i * bq + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
                kpos = j * bk + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
                s = jnp.where(qpos >= kpos, s, _NEG_INF)
            m_new = jnp.maximum(m_i[:], jnp.max(s, axis=1, keepdims=True))
            p = jnp.exp(s - m_new)              # (bq, bk) f32
            alpha = jnp.exp(m_i[:] - m_new)     # (bq, 1)
            l_i[:] = l_i[:] * alpha + jnp.sum(p, axis=1, keepdims=True)
            acc[:] = acc[:] * alpha + lax.dot_general(
                p.astype(vb.dtype), vb, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            m_i[:] = m_new

        @pl.when(j == nk - 1)
        def _fin():
            denom = jnp.maximum(l_i[:], 1e-30)
            o_ref[0] = (acc[:] / denom).astype(o_ref.dtype)
            # lse is (bq,) but mosaic tiling wants an (8, 128k) block, so
            # the output carries a broadcast sublane dim (sliced off by the
            # wrapper)
            lse = (m_i[:] + jnp.log(denom))[:, 0]
            lse_ref[0] = jnp.broadcast_to(lse[None, :], (8, bq))

    grid = (bh, nq, nk)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, 8, bq), lambda b, i, j: (b, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, lq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, 8, lq), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
        ],
    )(q, k, v)
    return out, lse[:, 0, :]


# ---------------------------------------------------------------------------
# Blockwise XLA fallback (same algorithm, lax.scan over KV blocks)
# ---------------------------------------------------------------------------

def _scan_forward(q, k, v, causal, sm_scale, bk):
    bh, lq, d = q.shape
    lk = k.shape[1]
    nk = lk // bk
    kb = k.reshape(bh, nk, bk, d).transpose(1, 0, 2, 3)   # (nk, bh, bk, d)
    vb = v.reshape(bh, nk, bk, d).transpose(1, 0, 2, 3)
    qpos = lax.broadcasted_iota(jnp.int32, (lq, bk), 0)

    def step(carry, blk):
        acc, m_i, l_i, j = carry
        kj, vj = blk
        s = jnp.einsum("bqd,bkd->bqk", q, kj,
                       preferred_element_type=jnp.float32) * sm_scale
        if causal:
            kpos = j * bk + lax.broadcasted_iota(jnp.int32, (lq, bk), 1)
            s = jnp.where((qpos >= kpos)[None], s, _NEG_INF)
        m_new = jnp.maximum(m_i, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_i - m_new)
        l_new = l_i * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jnp.einsum(
            "bqk,bkd->bqd", p.astype(v.dtype), vj,
            preferred_element_type=jnp.float32)
        return (acc, m_new, l_new, j + 1), None

    init = (jnp.zeros((bh, lq, d), jnp.float32),
            jnp.full((bh, lq, 1), _NEG_INF, jnp.float32),
            jnp.zeros((bh, lq, 1), jnp.float32),
            jnp.int32(0))
    (acc, m_i, l_i, _), _ = lax.scan(step, init, (kb, vb))
    denom = jnp.maximum(l_i, 1e-30)
    out = (acc / denom).astype(q.dtype)
    lse = (m_i + jnp.log(denom))[..., 0]
    return out, lse


# ---------------------------------------------------------------------------
# Backward (blockwise, shared by both paths)
# ---------------------------------------------------------------------------

def _scan_backward(q, k, v, out, lse, g, causal, sm_scale, bk):
    bh, lq, d = q.shape
    lk = k.shape[1]
    nk = lk // bk
    kb = k.reshape(bh, nk, bk, d).transpose(1, 0, 2, 3)
    vb = v.reshape(bh, nk, bk, d).transpose(1, 0, 2, 3)
    delta = jnp.sum(out.astype(jnp.float32) * g.astype(jnp.float32),
                    axis=-1, keepdims=True)                 # (bh, lq, 1)
    qpos = lax.broadcasted_iota(jnp.int32, (lq, bk), 0)

    def step(dq, blk):
        kj, vj, j = blk
        s = jnp.einsum("bqd,bkd->bqk", q, kj,
                       preferred_element_type=jnp.float32) * sm_scale
        if causal:
            kpos = j * bk + lax.broadcasted_iota(jnp.int32, (lq, bk), 1)
            s = jnp.where((qpos >= kpos)[None], s, _NEG_INF)
        p = jnp.exp(s - lse[..., None])                     # (bh, lq, bk)
        dv_j = jnp.einsum("bqk,bqd->bkd", p, g.astype(jnp.float32))
        dp = jnp.einsum("bqd,bkd->bqk", g.astype(jnp.float32),
                        vj.astype(jnp.float32))
        ds = p * (dp - delta) * sm_scale
        dk_j = jnp.einsum("bqk,bqd->bkd", ds, q.astype(jnp.float32))
        dq = dq + jnp.einsum("bqk,bkd->bqd", ds, kj.astype(jnp.float32))
        return dq, (dk_j, dv_j)

    steps = (kb, vb, jnp.arange(nk, dtype=jnp.int32))
    dq, (dk, dv) = lax.scan(step, jnp.zeros((bh, lq, d), jnp.float32), steps)
    dk = dk.transpose(1, 0, 2, 3).reshape(bh, lk, d)
    dv = dv.transpose(1, 0, 2, 3).reshape(bh, lk, d)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


# ---------------------------------------------------------------------------
# Public op
# ---------------------------------------------------------------------------

def _use_pallas(lq, lk, d):
    if jax.default_backend() != "tpu":
        return None
    import os

    def _pref(var, legacy):
        # tuning knobs (MXTPU_FLASH_BLOCK_Q/KV, legacy alias
        # MXTPU_FLASH_BQ/BK): preferred block sizes for the kernel
        # autotune sweep (tools/flash_long_seq.py --block-sweep);
        # clamped to >=128 so a too-small value still falls back to a
        # valid divisor instead of silently disabling the kernel, and
        # malformed values are named
        raw = os.environ.get(var)
        if raw is None:
            raw = os.environ.get(legacy, "512")
            var = legacy
        try:
            return max(int(raw), 128)
        except ValueError as e:
            from ..base import MXNetError
            raise MXNetError(
                f"{var}={raw!r} is not an integer block size") from e

    pref_q = _pref("MXTPU_FLASH_BLOCK_Q", "MXTPU_FLASH_BQ")
    pref_k = _pref("MXTPU_FLASH_BLOCK_KV", "MXTPU_FLASH_BK")
    bq = _pick_block(lq, pref_q)
    bk = _pick_block(lk, pref_k)
    # d=64 is fine: Mosaic pads the lane dim; BERT-base heads (768/12) hit
    # this. Verified on TPU v5e vs the scan path (max abs diff 1.8e-7 f32).
    if bq is None or bk is None or d % 64:
        return None
    return bq, bk


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash(q, k, v, causal, sm_scale):
    return _flash_fwd(q, k, v, causal, sm_scale)[0]


def _flash_fwd(q, k, v, causal, sm_scale):
    blocks = _use_pallas(q.shape[1], k.shape[1], q.shape[2])
    if blocks is not None:
        out, lse = _pallas_forward(q, k, v, causal, sm_scale, *blocks)
    else:
        bk = _pick_block(k.shape[1], 256) or k.shape[1]
        out, lse = _scan_forward(q, k, v, causal, sm_scale, bk)
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, sm_scale, res, g):
    q, k, v, out, lse = res
    bk = _pick_block(k.shape[1], 256) or k.shape[1]
    return _scan_backward(q, k, v, out, lse, g, causal, sm_scale, bk)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(query, key, value, causal=False, sm_scale=None):
    """softmax(QK^T * sm_scale [+ causal mask]) V without materializing the
    score matrix. query/key/value: (B, H, L, D) NDArrays or jax arrays.

    Differentiable (custom VJP, blockwise backward) and tape-aware: with
    NDArray inputs under ``autograd.record()`` it records one tape node.
    On TPU with 128-aligned L and D the core runs as a Pallas kernel;
    otherwise a blockwise-scan XLA fallback with identical semantics.
    """
    from ..ndarray.ndarray import NDArray, apply_nary

    def core(qd, kd, vd):
        if qd.ndim != 4:
            raise ValueError("flash_attention expects (B, H, L, D) inputs, "
                             f"got shape {qd.shape}")
        b, h, lq, d = qd.shape
        lk = kd.shape[2]
        scale = 1.0 / math.sqrt(d) if sm_scale is None else float(sm_scale)
        out = _flash(qd.reshape(b * h, lq, d), kd.reshape(b * h, lk, d),
                     vd.reshape(b * h, lk, d), bool(causal), scale)
        return out.reshape(b, h, lq, d)

    if isinstance(query, NDArray):
        key = key if isinstance(key, NDArray) else NDArray(jnp.asarray(key))
        value = value if isinstance(value, NDArray) else \
            NDArray(jnp.asarray(value))
        return apply_nary(core, [query, key, value], name="flash_attention")
    return core(jnp.asarray(query), jnp.asarray(key), jnp.asarray(value))
