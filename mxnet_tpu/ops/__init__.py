"""Pallas TPU kernels (flash attention etc.)."""
