"""Custom TPU kernels (Pallas) behind MXNet-style op entry points.

The reference accelerates its hot ops with hand-written CUDA/cuDNN
(SURVEY.md §2.1 "Operator library"); here XLA covers the bulk and Pallas
covers what XLA won't fuse well — starting with flash attention.
"""
from .flash_attention import flash_attention
from .blocked_cross_entropy import fused_linear_cross_entropy
from .fused_layernorm import fused_layer_norm
from .fused_update import fused_bucket_rule
from .paged_attention import paged_decode_attention
from .quant_matmul import quant_matmul, resolve_compute_dtype
from .quant_kv import resolve_kv_dtype

__all__ = ["flash_attention", "fused_linear_cross_entropy",
           "fused_layer_norm", "fused_bucket_rule",
           "paged_decode_attention", "quant_matmul",
           "resolve_compute_dtype", "resolve_kv_dtype"]
