"""Low-precision KV-cache storage helpers (ISSUE 20).

The serving KV pools are pure STORAGE: every graph family writes
freshly-computed f32 K/V rows into pool blocks and reads them back for
attention.  Storing those rows in 8 bits doubles (4x, with fp8) the
sequences one HBM budget holds — the capacity lever behind
``MXTPU_KV_DTYPE`` — at the price of a bounded decode drift, since the
attention math itself stays f32 (quantize-on-write / dequantize-in-
attention; prefill attends over the fresh K/V and is untouched).

Scaling scheme (``fp8``, the interesting mode):

- codes are ``float8_e4m3fn`` (max normal 448);
- ONE f32 amax scale per written token row — amax over that row's
  (kv_heads, head_dim) values — stored in ``(layers, num_blocks,
  block_size)`` scale arrays riding alongside the pools.  Per-row
  scales make partial block writes exact: a decode step scattering one
  row never needs to requantize its neighbours (a per-block scalar
  would, the moment a new row raised the block amax).  Overhead is
  ``4 / (kv_heads * head_dim)`` of the fp8 pool bytes — accounted, not
  ignored, in :func:`kv_block_bytes`.
- quantization is round-to-nearest (``astype`` to fp8); dequantization
  multiplies the row scale back in f32 before any attention math.

``bf16`` stores plain bfloat16 codes with NO scales (bf16 keeps f32's
exponent range, so amax scaling buys nothing); ``fp32`` — and an unset
``MXTPU_KV_DTYPE`` — is today's engine, bitwise (resolves to ``None``:
no cast, no scales, no graph change).

These helpers are the ONLY sanctioned home for raw low-precision
``astype`` on KV tensors — mxlint HB21 (``unscaled-lowp-cast``) flags
the pattern everywhere outside ``ops/quant*``.
"""
from __future__ import annotations

import os

import jax.numpy as jnp

from ..base import MXNetError

__all__ = ["resolve_kv_dtype", "kv_pool_dtype", "kv_has_scales",
           "kv_cast", "kv_quantize_fp8", "kv_dequantize",
           "kv_block_bytes", "kv_blocks_in_budget", "FP8_MAX"]

#: max normal magnitude of float8_e4m3fn — the fp8 amax scaling target.
FP8_MAX = 448.0

_CANON = {"fp8": "fp8", "float8": "fp8", "float8_e4m3fn": "fp8",
          "bf16": "bf16", "bfloat16": "bf16",
          "fp32": None, "float32": None}


def resolve_kv_dtype(value=None):
    """Canonical KV storage mode: ``"fp8"``, ``"bf16"``, or ``None``
    (= f32, today's engine).  ``None`` input reads ``MXTPU_KV_DTYPE``;
    unset/empty/``0``/``off``/``fp32`` all resolve to ``None`` so the
    kill switch is bitwise-inert.  Unknown values raise (a typo must
    not silently serve full-width)."""
    if value is None:
        value = os.environ.get("MXTPU_KV_DTYPE", "")
    v = str(value).strip().lower()
    if v in ("", "0", "off", "none"):
        return None
    if v not in _CANON:
        raise MXNetError(
            f"MXTPU_KV_DTYPE={value!r}: expected fp8|bf16|fp32")
    return _CANON[v]


def kv_pool_dtype(kv_dtype):
    """The pool storage dtype for a resolved mode."""
    if kv_dtype == "fp8":
        return jnp.float8_e4m3fn
    if kv_dtype == "bf16":
        return jnp.bfloat16
    return jnp.float32


def kv_has_scales(kv_dtype):
    """Only fp8 carries per-row amax scale arrays."""
    return kv_dtype == "fp8"


def kv_cast(x, dtype):
    """Storage cast for the scale-free modes.  Identity (the SAME
    traced array, so the unset path stays bitwise) when the dtype
    already matches; otherwise the sanctioned bf16 storage cast."""
    if x.dtype == dtype:
        return x
    return x.astype(dtype)


def kv_quantize_fp8(x):
    """Quantize K or V rows ``x`` (..., kv_heads, head_dim) f32 to
    fp8 codes + per-row scales: amax over each row's (kvh, hd) values,
    scale = amax / 448 (clamped away from 0 so all-zero rows — warmup,
    null block — quantize to exact zeros), codes = round-to-nearest
    fp8 of x / scale.  Returns ``(codes x.shape fp8, scales
    x.shape[:-2] f32)``."""
    amax = jnp.max(jnp.abs(x), axis=(-2, -1))
    scale = jnp.maximum(amax / FP8_MAX, 1e-30).astype(jnp.float32)
    codes = (x / scale[..., None, None]).astype(jnp.float8_e4m3fn)
    return codes, scale


def kv_dequantize(codes, scale=None):
    """Back to f32 for the attention math: codes * per-row scale (fp8),
    or a plain widening cast (bf16, ``scale=None``).  ``scale`` must be
    ``codes.shape[:-2]`` — one scalar per (kvh, hd) row."""
    x = codes.astype(jnp.float32)
    if scale is None:
        return x
    return x * scale[..., None, None]


def kv_block_bytes(num_layers, num_kv_heads, head_dim, block_size,
                   kv_dtype=None):
    """Exact bytes ONE pool block pins across both (K and V) pools and
    all layers, INCLUDING the fp8 scale rows — the honest denominator
    for every capacity claim (a fp8 ratio quoted without its scale
    overhead would overstate the win)."""
    itemsize = jnp.dtype(kv_pool_dtype(kv_dtype)).itemsize
    per = 2 * num_layers * block_size * num_kv_heads * head_dim * itemsize
    if kv_has_scales(kv_dtype):
        per += 2 * num_layers * block_size * 4  # f32 scale per token row
    return per


def kv_blocks_in_budget(budget_bytes, num_layers, num_kv_heads, head_dim,
                        block_size, kv_dtype=None):
    """Allocatable blocks one HBM byte budget holds at a storage mode —
    the ISSUE 20 capacity gate compares this across modes at EQUAL
    budget (fp8 must fit >= 2x the f32 count, scale rows included)."""
    per = kv_block_bytes(num_layers, num_kv_heads, head_dim, block_size,
                         kv_dtype)
    return int(budget_bytes) // per
