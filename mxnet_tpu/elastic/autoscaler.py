"""Load-based autoscaling over the telemetry spine (ISSUE 13 tentpole,
second half).

PR 8 scales on FAILURE (a death shrinks dp); this module scales on
LOAD: a control loop watches the PR 9 registry signals —
``train.step_ms``, io prefetch-queue occupancy, the serving router's
``serving.replica<i>.queue_depth`` / ``ttft_ms`` /
``kv_block_utilization`` gauges — through **hysteresis windows** and
issues deliberate grow/shrink decisions:

- **training dp** rescales through the existing epoch-fenced
  ``ElasticController.resync`` (``request_dp``: same pause-at-boundary
  → ``reshard_in_place`` → resume machinery as a membership change, so
  the transition stays bitwise and tp/pp are preserved per ISSUE 11);
- **serving replicas** are added/removed through the Router's
  epoch-fenced replica set (``add_replica`` / ``drain_replica`` — a
  removed replica's requests requeue to the survivors, zero lost).

Control discipline (the 2011.03641 lesson: oscillating capacity is
worse than fixed capacity):

- a rule fires only after its signal stays past the threshold for the
  whole ``window_s`` (hysteresis — one hot step never triggers);
- a **cooldown** (``MXTPU_AUTOSCALE_COOLDOWN_S``) separates decisions
  per domain, so a reshard's own cost cannot trigger the next reshard;
- hard min/max bounds clamp every target;
- ``MXTPU_AUTOSCALE=0`` is a kill switch: ``tick()`` returns None
  without reading a signal — behavior is bitwise today's.

Everything reads the injectable ``now`` clock (FakeClock in tests,
zero sleeps) and the loop itself is pull-based: callers tick it at
step/scheduling boundaries (``estimator.fit(autoscaler=...)`` does).

:class:`DegradationLadder` is the declared what-when-capacity-is-lost
policy: shed serving admissions → run shrunken toward
``MXTPU_ELASTIC_MIN_DP`` → checkpoint-and-stop with ``.preempted``
(the PR 4 contract).  Every rung transition is a telemetry event.
"""
from __future__ import annotations

import os
import time

from ..base import MXNetError
from .. import telemetry as _telem

__all__ = ["ScalingRule", "ScalingPolicy", "Autoscaler",
           "DegradationLadder", "autoscale_enabled",
           "default_cooldown_s"]


def autoscale_enabled():
    """Kill switch: ``MXTPU_AUTOSCALE=0`` makes every ``tick()`` a
    no-op — no signal reads, no decisions, bitwise today's behavior.
    Default on: constructing an Autoscaler IS the opt-in (the
    ``MXTPU_ELASTIC`` discipline)."""
    return os.environ.get("MXTPU_AUTOSCALE", "1") != "0"


def default_cooldown_s():
    """Seconds between decisions per domain
    (``MXTPU_AUTOSCALE_COOLDOWN_S``, default 60): a reshard/warmup must
    never be able to trigger the next one."""
    return float(os.environ.get("MXTPU_AUTOSCALE_COOLDOWN_S", "60") or 60)


class ScalingRule:
    """One watched signal with a hysteresis window.

    ``signal``: registry metric name (for serving signals the
    Autoscaler aggregates ``serving.replica<i>.<suffix>`` with max —
    pass e.g. ``"serving.queue_depth"``; role-scoped signals
    ``serving.prefill.<suffix>`` / ``serving.decode.<suffix>``
    aggregate only that pool's replicas).  ``high``/``low``: breach
    thresholds (either may be None for one-sided rules).  ``domain``:
    ``"train"`` (dp), ``"serving"`` (replicas), or — against a
    DISAGGREGATED router — ``"serving:prefill"`` / ``"serving:decode"``
    to scale one pool independently (TTFT pressure grows the prefill
    pool, TPOT pressure the decode pool; each pool runs its own
    cooldown).  The verdict is ``"grow"`` only after the value stays
    ``> high`` for ``window_s`` continuous seconds, ``"shrink"`` after
    ``< low`` for the same — one spike never moves capacity."""

    def __init__(self, signal, high=None, low=None, domain="train",
                 window_s=30.0):
        if domain not in ("train", "serving", "serving:prefill",
                          "serving:decode"):
            raise MXNetError(f"ScalingRule domain {domain!r}: expected "
                             f"'train', 'serving', 'serving:prefill' "
                             f"or 'serving:decode'")
        if high is None and low is None:
            raise MXNetError(f"ScalingRule {signal!r}: need high and/or "
                             f"low threshold")
        self.signal = str(signal)
        self.high = None if high is None else float(high)
        self.low = None if low is None else float(low)
        self.domain = domain
        self.window_s = float(window_s)
        self._high_since = None
        self._low_since = None

    def update(self, value, now):
        """Feed one observation; returns "grow"/"shrink" when the
        hysteresis window completes, else None."""
        if value is None:
            return None
        v = float(value)
        if self.high is not None and v > self.high:
            if self._high_since is None:
                self._high_since = now
        else:
            self._high_since = None
        if self.low is not None and v < self.low:
            if self._low_since is None:
                self._low_since = now
        else:
            self._low_since = None
        if self._high_since is not None and \
                now - self._high_since >= self.window_s:
            return "grow"
        if self._low_since is not None and \
                now - self._low_since >= self.window_s:
            return "shrink"
        return None

    def reset(self):
        self._high_since = None
        self._low_since = None


class ScalingPolicy:
    """A rule set plus the bounds every decision is clamped to."""

    def __init__(self, rules, cooldown_s=None, min_dp=None, max_dp=None,
                 min_replicas=1, max_replicas=None):
        self.rules = list(rules)
        self.cooldown_s = (default_cooldown_s() if cooldown_s is None
                           else float(cooldown_s))
        from .controller import min_dp as _env_min_dp
        self.min_dp = _env_min_dp() if min_dp is None else int(min_dp)
        self.max_dp = None if max_dp is None else int(max_dp)
        self.min_replicas = int(min_replicas)
        self.max_replicas = None if max_replicas is None \
            else int(max_replicas)

    def evaluate(self, signals, now):
        """Feed the rules; returns {domain: "grow"|"shrink"} — grow
        wins over shrink within a domain (capacity pressure trumps
        idleness when both signals somehow coexist)."""
        verdicts = {}
        for rule in self.rules:
            v = rule.update(signals.get(rule.signal), now)
            if v is None:
                continue
            prev = verdicts.get(rule.domain)
            if prev != "grow":
                verdicts[rule.domain] = v if prev is None else (
                    "grow" if "grow" in (prev, v) else v)
        return verdicts


class Autoscaler:
    """The control loop: tick at boundaries, read signals, decide,
    apply through the epoch-fenced seams.

    ``controller``: an :class:`~mxnet_tpu.elastic.ElasticController`
    (training dp domain); ``router``: a
    :class:`~mxnet_tpu.serving.frontend.Router` (serving domain).
    Either may be None.  ``now`` is the injectable clock; ``signals``
    may be passed to :meth:`tick` explicitly (tests/chaos) or are read
    off the telemetry registry.
    """

    def __init__(self, policy, controller=None, router=None, now=None):
        self._policy = policy
        self._controller = controller
        self._router = router
        self._now = now if now is not None else time.time
        self._enabled = autoscale_enabled()   # read ONCE at construction
        self._last_decision_t = {}            # domain -> time
        self.decisions = []
        self.skipped = {"cooldown": 0, "bounds": 0, "capacity": 0}

    @property
    def enabled(self):
        return self._enabled

    # -- signal plumbing -------------------------------------------------
    def _serving_signal(self, suffix):
        """Max over the live replicas' published per-replica gauges
        (the fleet is as loaded as its hottest replica), falling back
        to direct reads when the registry is off.  A ``prefill.`` /
        ``decode.`` prefix scopes the aggregation to that role's pool
        (the disaggregated fleet's independent scaling signals)."""
        if self._router is None:
            return None
        role = None
        for r in ("prefill", "decode"):
            if suffix.startswith(r + "."):
                role, suffix = r, suffix[len(r) + 1:]
                break
        vals = []
        for rep in self._router.live_replicas():
            if role is not None and \
                    getattr(rep, "role", "combined") != role:
                continue
            v = _telem.value(f"serving.replica{rep.rid}.{suffix}")
            if v is None and suffix == "tpot_ms":
                recent = rep.tpots[-8:]
                v = (sorted(recent)[len(recent) // 2] * 1e3
                     if recent else None)
            elif v is None:
                v = rep.load_signals().get(suffix)
            if v is not None:
                vals.append(float(v))
        return max(vals) if vals else None

    def read_signals(self):
        """The registry view of every rule's signal (None when a
        signal has not been published — rules skip None)."""
        out = {}
        for rule in self._policy.rules:
            if rule.signal in out:
                continue
            if rule.signal.startswith("serving.") and \
                    _telem.value(rule.signal) is None:
                out[rule.signal] = self._serving_signal(
                    rule.signal[len("serving."):])
            else:
                out[rule.signal] = _telem.value(rule.signal)
        return out

    # -- current sizes ---------------------------------------------------
    def _current_dp(self):
        c = self._controller
        if c is None:
            return None
        return c.applied_dp if c.applied_dp is not None \
            else c.target_dp(include_pending=False)

    def _dp_capacity(self):
        return self._controller.target_dp(include_pending=True)

    # -- the loop body ---------------------------------------------------
    def tick(self, signals=None, step=None):
        """One control-loop pass (call at a step/scheduling boundary).
        Returns the list of decisions issued this tick (possibly
        empty), or None when the kill switch is on."""
        if not self._enabled:
            return None
        now = self._now()
        if signals is None:
            signals = self.read_signals()
        verdicts = self._policy.evaluate(signals, now)
        issued = []
        for domain, verdict in sorted(verdicts.items()):
            last = self._last_decision_t.get(domain)
            if last is not None and now - last < self._policy.cooldown_s:
                self.skipped["cooldown"] += 1
                continue
            if domain == "train":
                d = self._apply_train(verdict, signals, now, step)
            else:
                role = (domain.split(":", 1)[1] if ":" in domain
                        else None)
                d = self._apply_serving(verdict, signals, now, step,
                                        role=role, domain=domain)
            if d is not None:
                self._last_decision_t[domain] = now
                issued.append(d)
        return issued

    def _record(self, decision):
        self.decisions.append(decision)
        _telem.inc("autoscale.decisions")
        _telem.event("autoscale.decision", **{
            k: v for k, v in decision.items() if k != "signals"})
        return decision

    def _apply_train(self, verdict, signals, now, step):
        if self._controller is None:
            return None
        cur = self._current_dp()
        if cur is None:
            return None
        if verdict == "grow":
            target = min(cur * 2, self._dp_capacity())
            if self._policy.max_dp is not None:
                target = min(target, self._policy.max_dp)
            if target <= cur:
                self.skipped["capacity" if self._dp_capacity() <= cur
                             else "bounds"] += 1
                return None
        else:
            target = max(cur // 2, self._policy.min_dp)
            if target >= cur:
                self.skipped["bounds"] += 1
                return None
        self._controller.request_dp(target)
        _telem.set_gauge("autoscale.dp_target", target)
        return self._record({"t": now, "domain": "train",
                             "verdict": verdict, "from": cur,
                             "to": target, "step": step,
                             "signals": dict(signals)})

    def _apply_serving(self, verdict, signals, now, step, role=None,
                       domain="serving"):
        if self._router is None:
            return None
        if role is not None and \
                not getattr(self._router, "disaggregated", False):
            # a pool-scoped rule against a combined fleet: nothing to
            # scale by role — the rule is inert, not an error
            self.skipped["bounds"] += 1
            return None
        live = [r for r in self._router.live_replicas()
                if role is None or r.role == role]
        cur = len(live)
        if verdict == "grow":
            if self._policy.max_replicas is not None and \
                    cur + 1 > self._policy.max_replicas:
                self.skipped["bounds"] += 1
                return None
            rep = self._router.add_replica(role=role) \
                if role is not None else self._router.add_replica()
            to = rep.rid
        else:
            if cur - 1 < self._policy.min_replicas:
                self.skipped["bounds"] += 1
                return None
            # drain the highest-rid live replica (of the pool): the
            # newest capacity leaves first (LIFO keeps replica 0's
            # warm caches longest)
            victim = max(live, key=lambda r: r.rid)
            self._router.drain_replica(victim.rid, reason="autoscale")
            to = victim.rid
        return self._record({"t": now, "domain": domain,
                             "verdict": verdict, "from": cur,
                             "to": cur + (1 if verdict == "grow" else -1),
                             "rid": to, "step": step,
                             "signals": dict(signals)})

    def stats(self):
        return {"enabled": self._enabled,
                "decisions": len(self.decisions),
                "skipped": dict(self.skipped),
                "last": self.decisions[-1] if self.decisions else None}


class DegradationLadder:
    """The declared graceful-degradation policy for capacity loss,
    walked rung by rung as notices/deaths eat the fleet:

    ======  ==========================  =================================
    rung    trigger                     action
    ======  ==========================  =================================
    1       capacity < healthy target   **shed serving admissions**
                                        (``Router.set_shedding(True)`` —
                                        new submits get a typed
                                        ``AdmissionShed``; in-flight and
                                        requeued work is untouched)
    2       (implicit)                  **run shrunken**: the controller
                                        reshards dp toward
                                        ``MXTPU_ELASTIC_MIN_DP`` and
                                        training continues
    3       capacity < min_dp floor     **checkpoint-and-stop**: request
                                        a preemption (PR 4 contract —
                                        sync checkpoint, ``.preempted``)
                                        instead of limping or raising
    ======  ==========================  =================================

    Capacity recovering to the healthy target un-sheds (rung 0).  Every
    transition is a telemetry event (``degrade.*``)."""

    def __init__(self, router=None, stop=None, now=None):
        self._router = router
        self._stop = stop
        self._now = now if now is not None else time.time
        self.level = 0
        self.transitions = []

    def _log(self, kind, **data):
        rec = dict(data, kind=kind, t=self._now(), level=self.level)
        self.transitions.append(rec)
        _telem.event(f"degrade.{kind}", **data)
        _telem.set_gauge("degrade.level", self.level)
        return rec

    def assess(self, capacity_dp, healthy_dp, floor_dp):
        """Called by the controller on every capacity change.  Returns
        "ok" | "shed" | "stop"."""
        capacity_dp = int(capacity_dp)
        if capacity_dp < int(floor_dp):
            self.level = 3
            self._log("stop", capacity_dp=capacity_dp, floor=floor_dp)
            if self._stop is not None:
                self._stop(f"elastic capacity dp={capacity_dp} below "
                           f"floor {floor_dp}")
                return "stop"
            from ..checkpoint import PreemptionHandler
            handler = PreemptionHandler.installed()
            if handler is not None:
                handler.request(
                    reason=f"degradation ladder: capacity dp="
                           f"{capacity_dp} below MXTPU_ELASTIC_MIN_DP="
                           f"{floor_dp} — checkpoint and stop")
                return "stop"
            return "stop-unhandled"
        if capacity_dp < int(healthy_dp):
            if self.level < 1:
                self.level = max(self.level, 1)
                self._log("shed", capacity_dp=capacity_dp,
                          healthy=healthy_dp)
            if self._router is not None:
                self._router.set_shedding(True, reason="degraded")
            return "shed"
        if self.level != 0:
            self.level = 0
            self._log("recovered", capacity_dp=capacity_dp)
            if self._router is not None:
                self._router.set_shedding(False, reason="recovered")
        return "ok"
