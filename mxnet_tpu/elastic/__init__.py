"""``mxnet_tpu.elastic`` — scale data-parallel workers up/down mid-run
without a restart (ISSUE 8, ROADMAP item 4).

Three layers stitched through the existing stack:

- :class:`Membership` — the epoch-numbered membership state machine
  (``membership.py``), fed by the PS heartbeat death path
  (``PSServer.attach_membership`` + the join/announce RPC) and fully
  deterministic under ``testing.faults.FakeClock``;
- :class:`ElasticController` — pause at a step boundary, reshard
  params + ZeRO-1 optimizer state to the new dp (peer-to-peer via
  ``checkpoint.reshard_in_place``, checkpoint fallback when the
  transfer itself dies), rebuild the mesh/BucketPlan/compiled steps,
  resume — with retry/backoff and a bounded rendezvous so a flapping
  worker degrades to a smaller dp instead of hanging the job;
- the **epoch fence** — ``kvstore.attach_membership`` rejects a stale
  worker's collective with a clean error instead of letting it deadlock
  a ring against departed peers.

``estimator.fit(elastic_controller=...)`` wires the pause/resume hook
into the high-level loop; ``testing/chaos.py`` (``tools/
tpu_queue_runner.py --chaos elastic``) is the end-to-end kill-at-K /
join-at-K' smoke with bitwise continuation parity.  docs/
FAULT_TOLERANCE.md §Elastic membership has the state diagram.

Env knobs: ``MXTPU_ELASTIC=0`` (kill switch),
``MXTPU_ELASTIC_RENDEZVOUS_S`` (join window, default 30),
``MXTPU_ELASTIC_MIN_DP`` (degradation floor, default 1).
"""
from __future__ import annotations

from .membership import (Membership, MembershipEvent,
                         StaleMembershipEpoch, STABLE, RENDEZVOUS,
                         default_rendezvous_s)
from .controller import ElasticController, elastic_enabled, min_dp

__all__ = ["Membership", "MembershipEvent", "StaleMembershipEpoch",
           "ElasticController", "elastic_enabled", "min_dp",
           "default_rendezvous_s", "elastic_block", "STABLE",
           "RENDEZVOUS"]


def elastic_block(enabled=False, dp=1, membership_epoch=0, transitions=0,
                  degraded=False, reshard_ms=None, pause_ms=None):
    """The bench.py ``elastic`` observability block (the ``comm`` /
    ``serving`` block discipline): static config/counters are always
    real; MEASURED fields (``reshard_ms``, ``pause_ms``) default to
    ``None`` — null-when-unmeasured, so a CPU run can never pass off an
    absent measurement as "resharding is free" (the PR 6 honesty rule,
    gated by tests/test_bench_line.py)."""
    def _r(x, n=3):
        return None if x is None else round(float(x), n)

    return {
        "enabled": bool(enabled),
        "dp": int(dp),
        "membership_epoch": int(membership_epoch),
        "transitions": int(transitions),
        "degraded": bool(degraded),
        "reshard_ms": _r(reshard_ms),
        "pause_ms": _r(pause_ms),
    }
