"""``mxnet_tpu.elastic`` — scale data-parallel workers up/down mid-run
without a restart (ISSUE 8, ROADMAP item 4).

Three layers stitched through the existing stack:

- :class:`Membership` — the epoch-numbered membership state machine
  (``membership.py``), fed by the PS heartbeat death path
  (``PSServer.attach_membership`` + the join/announce RPC) and fully
  deterministic under ``testing.faults.FakeClock``;
- :class:`ElasticController` — pause at a step boundary, reshard
  params + ZeRO-1 optimizer state to the new dp (peer-to-peer via
  ``checkpoint.reshard_in_place``, checkpoint fallback when the
  transfer itself dies), rebuild the mesh/BucketPlan/compiled steps,
  resume — with retry/backoff and a bounded rendezvous so a flapping
  worker degrades to a smaller dp instead of hanging the job;
- the **epoch fence** — ``kvstore.attach_membership`` rejects a stale
  worker's collective with a clean error instead of letting it deadlock
  a ring against departed peers.

``estimator.fit(elastic_controller=...)`` wires the pause/resume hook
into the high-level loop; ``testing/chaos.py`` (``tools/
tpu_queue_runner.py --chaos elastic``) is the end-to-end kill-at-K /
join-at-K' smoke with bitwise continuation parity.  docs/
FAULT_TOLERANCE.md §Elastic membership has the state diagram.

ISSUE 13 adds the production half: ``notices.py`` (a pluggable
``NoticeBoard`` — GCE maintenance poller / SIGTERM-grace / scripted
fake — drains doomed workers at step boundaries AHEAD of the heartbeat
timeout; lapsed grace raises the typed ``DrainDeadline``) and
``autoscaler.py`` (an ``Autoscaler`` control loop scaling dp and
serving replicas ON LOAD through hysteresis windows + cooldown, and a
``DegradationLadder``: shed serving admissions -> run shrunken ->
checkpoint-and-stop).  Chaos gate:
``tools/tpu_queue_runner.py --chaos autoscale``.

Env knobs: ``MXTPU_ELASTIC=0`` (kill switch),
``MXTPU_ELASTIC_RENDEZVOUS_S`` (join window, default 30),
``MXTPU_ELASTIC_MIN_DP`` (degradation floor, default 1),
``MXTPU_AUTOSCALE=0`` / ``MXTPU_AUTOSCALE_COOLDOWN_S`` (autoscaler),
``MXTPU_NOTICE_SOURCE`` / ``MXTPU_NOTICE_GRACE_S`` (notices).
"""
from __future__ import annotations

from .membership import (Membership, MembershipEvent,
                         StaleMembershipEpoch, STABLE, RENDEZVOUS,
                         default_rendezvous_s)
from .controller import ElasticController, elastic_enabled, min_dp
from .notices import (Notice, NoticeBoard, NoticeSource,
                      FakeNoticeSource, SignalNoticeSource,
                      GCENoticeSource, DrainDeadline,
                      make_notice_source, default_notice_grace_s)
from .autoscaler import (ScalingRule, ScalingPolicy, Autoscaler,
                         DegradationLadder, autoscale_enabled,
                         default_cooldown_s)

__all__ = ["Membership", "MembershipEvent", "StaleMembershipEpoch",
           "ElasticController", "elastic_enabled", "min_dp",
           "default_rendezvous_s", "elastic_block", "STABLE",
           "RENDEZVOUS", "Notice", "NoticeBoard", "NoticeSource",
           "FakeNoticeSource", "SignalNoticeSource", "GCENoticeSource",
           "DrainDeadline", "make_notice_source",
           "default_notice_grace_s", "ScalingRule", "ScalingPolicy",
           "Autoscaler", "DegradationLadder", "autoscale_enabled",
           "default_cooldown_s"]


def elastic_block(enabled=False, dp=1, membership_epoch=0, transitions=0,
                  degraded=False, reshard_ms=None, pause_ms=None,
                  drain_ms=None, drains=0, pending_notices=0,
                  autoscale_decisions=None):
    """The bench.py ``elastic`` observability block (the ``comm`` /
    ``serving`` block discipline): static config/counters are always
    real; MEASURED fields (``reshard_ms``, ``pause_ms``, ``drain_ms``,
    ``autoscale_decisions``) default to ``None`` —
    null-when-unmeasured, so a CPU run can never pass off an absent
    measurement as "resharding is free" (the PR 6 honesty rule, gated
    by tests/test_bench_line.py).  ISSUE 13 grew the block with the
    notice-drain and autoscaling evidence: ``drain_ms`` (last
    notice-driven drain commit), ``drains``/``pending_notices``
    counters, and ``autoscale_decisions`` (None until a real autoscale
    loop ran — a CPU round without one reports null, not 0-decisions-
    measured)."""
    def _r(x, n=3):
        return None if x is None else round(float(x), n)

    return {
        "enabled": bool(enabled),
        "dp": int(dp),
        "membership_epoch": int(membership_epoch),
        "transitions": int(transitions),
        "degraded": bool(degraded),
        "reshard_ms": _r(reshard_ms),
        "pause_ms": _r(pause_ms),
        "drain_ms": _r(drain_ms),
        "drains": int(drains),
        "pending_notices": int(pending_notices),
        "autoscale_decisions": (None if autoscale_decisions is None
                                else int(autoscale_decisions)),
    }
