"""Epoch-numbered cluster membership state machine (ISSUE 8 tentpole).

The PS layer already *detects* dead workers (heartbeat silence ->
``PSServer._scan_dead``) and PR 4 made optimizer state dp-independent on
disk — but nothing closed the loop: a preemption still meant a full job
restart.  This module is the missing bookkeeping: a deterministic state
machine over WHO is in the job, numbered by a monotonically increasing
**membership epoch** that every committed transition (death, join)
bumps.  The epoch is the fencing token for the whole elastic layer:

- collectives are guarded by it (``kvstore.attach_membership``): a
  worker still on epoch N when the cluster moved to N+1 gets a clean
  ``MXNetError`` instead of deadlocking a ring against peers that no
  longer exist;
- a worker that rejoins carrying a stale epoch is **rejected** at the
  announce RPC (``PSServer`` opcode ``_OP_JOIN``) — it must resync
  state through the controller path, not slide back into the ring;
- the controller (``elastic.controller``) reshards exactly when its
  applied epoch falls behind.

Joins are two-phase (announce -> confirm) with a **bounded rendezvous**:
``announce_join`` parks the candidate as pending; the controller admits
it at the next step boundary and calls :meth:`confirm_join` after the
state transfer succeeds.  A candidate that goes silent past
``rendezvous_s`` (or dies mid-rendezvous) is dropped by :meth:`poll` —
the job **degrades to the smaller dp** instead of hanging on a flapping
worker (TensorFlow's dynamic cluster membership treats this as table
stakes, arXiv:1605.08695; at v5e-256 pod scale churn is the steady
state, arXiv:2011.03641).

Every timeout decision reads the injectable ``_now`` clock (the PR 4
``PSServer._now`` discipline), so the whole machine is testable under
``testing.faults.FakeClock`` with zero sleeps.
"""
from __future__ import annotations

import os
import time

from ..base import MXNetError
from ..lint import racecheck as _racecheck
from .. import telemetry as _telem

__all__ = ["Membership", "MembershipEvent", "StaleMembershipEpoch",
           "STABLE", "RENDEZVOUS"]

#: states of the machine.  STABLE: ranks are final for this epoch.
#: RENDEZVOUS: a join was announced and waits for the controller to
#: transfer state and confirm (bounded by ``rendezvous_s``).
STABLE, RENDEZVOUS = "stable", "rendezvous"


class StaleMembershipEpoch(MXNetError):
    """A worker announced/acted with an epoch the cluster moved past."""


class MembershipEvent:
    """One committed (or rejected/expired) transition, for observability
    and tests: ``kind`` in {"death", "join", "announce",
    "rendezvous_expired", "rendezvous_cancelled"}."""

    __slots__ = ("kind", "rank", "epoch", "time")

    def __init__(self, kind, rank, epoch, time_):
        self.kind = kind
        self.rank = int(rank)
        self.epoch = int(epoch)
        self.time = float(time_)

    def __repr__(self):
        return (f"MembershipEvent({self.kind}, rank={self.rank}, "
                f"epoch={self.epoch})")


def default_rendezvous_s():
    """Join rendezvous window in seconds (``MXTPU_ELASTIC_RENDEZVOUS_S``,
    default 30): how long an announced joiner may take to finish state
    transfer before the job stops waiting and continues at the smaller
    dp."""
    return float(os.environ.get("MXTPU_ELASTIC_RENDEZVOUS_S", "30") or 30)


class Membership:
    """The membership state machine.  Thread-safe (the PS serve threads
    and the training thread both touch it); every method is a pure state
    transition — no sleeps, no sockets — so the PS layer can drive it
    from heartbeats and tests can drive it directly.

    ``ranks``: the initial live worker ranks.  ``now``: injectable clock
    (``testing.faults.FakeClock`` in tests).  ``rendezvous_s``: join
    rendezvous bound (default ``MXTPU_ELASTIC_RENDEZVOUS_S``).
    """

    def __init__(self, ranks, epoch=0, now=None, rendezvous_s=None):
        self._lock = _racecheck.make_lock("Membership._lock")
        self._ranks = sorted(int(r) for r in ranks)
        if len(set(self._ranks)) != len(self._ranks):
            raise MXNetError(f"duplicate ranks in {ranks!r}")
        self._epoch = int(epoch)
        self._now = now if now is not None else time.time
        self._rendezvous_s = (float(rendezvous_s) if rendezvous_s
                              is not None else default_rendezvous_s())
        self._pending = None           # (rank, deadline) during RENDEZVOUS
        self._events = []
        self._subscribers = []

    # -- views ----------------------------------------------------------
    @property
    def epoch(self):
        with self._lock:
            return self._epoch

    @property
    def ranks(self):
        with self._lock:
            return tuple(self._ranks)

    @property
    def state(self):
        with self._lock:
            return RENDEZVOUS if self._pending is not None else STABLE

    @property
    def pending_join(self):
        """The announced-but-unconfirmed rank, or None."""
        with self._lock:
            return self._pending[0] if self._pending is not None else None

    @property
    def events(self):
        with self._lock:
            return list(self._events)

    def view(self):
        """JSON-able snapshot (the ``_OP_MEMBERSHIP`` RPC payload)."""
        with self._lock:
            return {"epoch": self._epoch, "ranks": list(self._ranks),
                    "state": (RENDEZVOUS if self._pending is not None
                              else STABLE),
                    "pending": (self._pending[0] if self._pending
                                is not None else None)}

    def subscribe(self, fn):
        """Call ``fn(event)`` on every committed transition (death/join
        commit and rendezvous expiry) — the controller's wake-up."""
        with self._lock:
            self._subscribers.append(fn)

    # -- transitions ----------------------------------------------------
    def _emit(self, kind, rank):  # guarded-by: _lock
        """Record + fan out one event.  Caller holds the lock; subscriber
        callbacks run OUTSIDE it (a controller may call back into us).
        Every committed transition also lands in the telemetry event log
        with the epoch as ambient context (ISSUE 9) — telemetry never
        calls back into the membership, so emitting under the lock is
        safe."""
        ev = MembershipEvent(kind, rank, self._epoch, self._now())
        self._events.append(ev)
        _telem.set_context(epoch=self._epoch)
        _telem.set_gauge("elastic.epoch", self._epoch)
        _telem.event(f"membership.{kind}", rank=int(rank),
                     epoch=self._epoch)
        subs = list(self._subscribers)
        return ev, subs

    @staticmethod
    def _fan_out(ev, subs):
        for fn in subs:
            fn(ev)

    def worker_dead(self, rank):
        """Commit a death (heartbeat silence past the timeout — the
        ``PSServer._scan_dead`` feed).  Bumps the epoch.  A death of the
        pending joiner cancels the rendezvous instead (the flapping-
        worker degrade: the job simply continues at the smaller dp)."""
        rank = int(rank)
        with self._lock:
            if self._pending is not None and self._pending[0] == rank:
                self._pending = None
                ev, subs = self._emit("rendezvous_cancelled", rank)
            elif rank in self._ranks:
                self._ranks.remove(rank)
                self._epoch += 1
                ev, subs = self._emit("death", rank)
            else:
                return None            # unknown rank: nothing to commit
        self._fan_out(ev, subs)
        return ev

    def announce_join(self, rank, seen_epoch):
        """Phase 1 of a join: the candidate announces itself with the
        newest epoch it knows.  A stale epoch is REJECTED (clean typed
        error — the worker must resync, not resume); an accepted
        announce parks the candidate as pending until
        :meth:`confirm_join` (bounded by the rendezvous window).
        Returns the rendezvous deadline."""
        rank = int(rank)
        with self._lock:
            if int(seen_epoch) < self._epoch:
                raise StaleMembershipEpoch(
                    f"join announce from rank {rank} carries stale "
                    f"membership epoch {int(seen_epoch)} (cluster is at "
                    f"{self._epoch}): rejected — resync state through "
                    f"the elastic controller and re-announce with the "
                    f"current epoch")
            if rank in self._ranks:
                raise MXNetError(
                    f"rank {rank} is already a live member "
                    f"(epoch {self._epoch})")
            if self._pending is not None and self._pending[0] != rank:
                raise MXNetError(
                    f"rank {self._pending[0]} is already in rendezvous; "
                    f"one join at a time")
            deadline = self._now() + self._rendezvous_s
            self._pending = (rank, deadline)
            ev, subs = self._emit("announce", rank)
        self._fan_out(ev, subs)
        return deadline

    def confirm_join(self, rank):
        """Phase 2: the controller finished the state transfer — commit
        the join and bump the epoch."""
        rank = int(rank)
        with self._lock:
            if self._pending is None or self._pending[0] != rank:
                raise MXNetError(
                    f"confirm_join({rank}): no matching announced join "
                    f"(pending: {self._pending})")
            self._pending = None
            self._ranks.append(rank)
            self._ranks.sort()
            self._epoch += 1
            ev, subs = self._emit("join", rank)
        self._fan_out(ev, subs)
        return ev

    def poll(self):
        """Expire an overdue rendezvous (no sleeps anywhere: whoever
        calls — controller boundary check, PS scan — just reads the
        clock).  Returns the expiry event, or None."""
        with self._lock:
            if self._pending is None:
                return None
            rank, deadline = self._pending
            if self._now() <= deadline:
                return None
            self._pending = None
            ev, subs = self._emit("rendezvous_expired", rank)
        self._fan_out(ev, subs)
        return ev

    def check_epoch(self, epoch, what="collective"):
        """Fencing-token check: raise :class:`StaleMembershipEpoch` when
        ``epoch`` is behind the cluster (the pushpull guard — a stale
        worker's collective must be *rejected*, never allowed to
        deadlock a ring against departed peers)."""
        with self._lock:
            cur = self._epoch
        if int(epoch) != cur:
            raise StaleMembershipEpoch(
                f"{what} carries membership epoch {int(epoch)} but the "
                f"cluster is at {cur}: rejected instead of deadlocking "
                f"— reshard via elastic.ElasticController and "
                f"refresh_membership()")
        return cur
