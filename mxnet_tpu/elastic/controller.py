"""Elastic membership controller (ISSUE 8 tentpole): pause -> reshard
-> resume at a step boundary, without a process restart.

The pieces existed separately — PR 4 reshards ZeRO-1 optimizer state
bitwise across dp sizes and the PS layer detects dead workers by
heartbeat — this closes the loop.  On a committed membership transition
(a death fed from ``PSServer._scan_dead``, or a join announced through
the ``_OP_JOIN`` RPC and admitted at the next boundary):

1. **pause** — nothing interrupts a step mid-flight: the training loop
   (``estimator.fit`` window boundary, or a custom loop calling
   :meth:`ElasticController.check_step`) hands control over exactly
   where the PR 4 ``PreemptionHandler`` stop seam sits, so the
   in-flight step/scan-window always completes first;
2. **reshard** — peer-to-peer from the live trainer's state
   (``checkpoint.reshard_in_place``: per-parameter-space capture ->
   ``DataParallelTrainer.rebuild(mesh)`` -> restore), because the live
   state is newer than any checkpoint; retried with bounded backoff; a
   reshard that dies mid-transfer (``elastic.reshard`` fault point)
   falls back to ``checkpoint.reshard_from_checkpoint`` — the newest
   valid checkpoint, with the resume step returned so the loop rewinds;
3. **resume** — the new mesh / ``BucketPlan`` / compiled steps rebuild
   lazily on the next step; an attached kvstore's membership epoch is
   refreshed (collectives fenced by the old epoch are rejected, not
   deadlocked) and an attached ``OverlapScheduler`` re-observes its
   backward order.

Degradation policy: a join that outlives its rendezvous window — or a
joiner that dies mid-rendezvous — is dropped (``Membership.poll``) and
the job **continues at the smaller dp**; shrinking below
``MXTPU_ELASTIC_MIN_DP`` raises instead of limping.  All timeout logic
reads the injectable ``now``/``sleep`` hooks, so every path is
deterministic under ``testing.faults.FakeClock`` with zero sleeps.
"""
from __future__ import annotations

import os
import time

from ..base import MXNetError
from .. import telemetry as _telem
from ..telemetry import tracing as _trace
from .membership import Membership  # noqa: F401  (re-exported surface)
from .notices import DrainDeadline

__all__ = ["ElasticController", "elastic_enabled", "min_dp"]


def elastic_enabled():
    """Kill switch: ``MXTPU_ELASTIC=0`` makes every controller inert
    (``check_step`` returns None without touching the trainer) — the
    same opt-out semantics as ``MXTPU_FUSED_STEP``/``MXTPU_OVERLAP_COMM``.
    Default on: constructing a controller IS the opt-in."""
    return os.environ.get("MXTPU_ELASTIC", "1") != "0"


def min_dp():
    """Degradation floor (``MXTPU_ELASTIC_MIN_DP``, default 1): the
    smallest dp the controller will shrink to; a transition below it
    raises instead of continuing with a crippled job."""
    return int(os.environ.get("MXTPU_ELASTIC_MIN_DP", "1") or 1)


class ElasticController:
    """Drives elastic reshards from membership transitions.

    ``membership``: the :class:`~mxnet_tpu.elastic.Membership` machine
    (typically also attached to a ``PSServer`` so heartbeat deaths feed
    it).  ``devices``: the device pool meshes are carved from (default
    ``jax.devices()``).  ``devices_per_worker``: how many mesh devices
    each membership rank contributes (default: pool size / initial rank
    count — the v5e host granularity).  ``checkpoint_manager``: the
    fallback source when the peer transfer dies.  ``net``: the gluon
    block whose parameters ride along (required for the checkpoint
    fallback; the peer path snapshots it too when given).

    ``backoff_s``/``max_retries`` bound the peer-path retry loop;
    ``now``/``sleep`` are injectable for deterministic tests (a
    ``FakeClock`` and a no-op make every scenario sleep-free).
    """

    def __init__(self, membership, devices=None, devices_per_worker=None,
                 checkpoint_manager=None, net=None, kvstore=None,
                 scheduler=None, min_dp=None, max_retries=2,
                 backoff_s=0.5, now=None, sleep=None, notices=None,
                 ladder=None, drain_checkpoint=None):
        import jax
        self._membership = membership
        self._devices = list(devices) if devices is not None \
            else list(jax.devices())
        n_ranks = max(1, len(membership.ranks))
        self._dpw = int(devices_per_worker) if devices_per_worker \
            is not None else max(1, len(self._devices) // n_ranks)
        self._manager = checkpoint_manager
        self._net = net
        self._kvstore = kvstore
        self._scheduler = scheduler
        self._min_dp = int(min_dp) if min_dp is not None \
            else globals()["min_dp"]()
        self._max_retries = int(max_retries)
        self._backoff_s = float(backoff_s)
        self._now = now if now is not None else time.time
        self._sleep = sleep if sleep is not None else time.sleep
        self._enabled = elastic_enabled()   # read ONCE at construction
        self._applied_epoch = membership.epoch
        # ISSUE 13: preemption notices, load-based rescale requests,
        # and the graceful-degradation ladder
        self._notices = notices
        self._ladder = ladder
        self._requested_dp = None     # autoscaler target (one-shot)
        self._applied_dp = None       # dp the trainer was last built for
        self._healthy_dp = self.target_dp(include_pending=False)
        #: callable(step) run sync BEFORE a notice-driven drain commits
        #: (checkpoint-then-reshard; estimator.fit wires its own saver)
        self.drain_checkpoint = drain_checkpoint
        # ISSUE 19: process-level coordinator re-init (attach_dist_reinit)
        self._dist_reinit = None
        self.dist_reinits = 0
        self.last_reinit_ms = None
        # observability (the bench `elastic` block + tests)
        self.transitions = 0
        self.drains = 0
        self.degraded = False
        self.last_pause_ms = None
        self.last_reshard_ms = None
        self.last_drain_ms = None
        self.last_event = None

    # -- wiring ---------------------------------------------------------
    def attach_kvstore(self, kvstore):
        """Fence an eager kvstore's collectives by the membership epoch
        (``kvstore.attach_membership``) and keep it refreshed across
        reshards."""
        kvstore.attach_membership(self._membership)
        self._kvstore = kvstore
        return self

    def attach_notices(self, board):
        """Wire a :class:`~mxnet_tpu.elastic.NoticeBoard`: pending
        notices are drained at step boundaries AHEAD of the heartbeat
        timeout (``check_step`` commits ``worker_dead`` the moment a
        noticed rank is seen at a boundary, instead of waiting for
        ``PSServer._scan_dead``)."""
        self._notices = board
        return self

    def attach_dist_reinit(self, fn):
        """ISSUE 19: the process-level coordinator re-init seam.  When
        attached, a COMMITTED membership change (epoch bump) makes
        ``resync`` call ``fn(epoch, ranks)`` BEFORE rebuilding the mesh
        — the hook tears down and re-initializes the JAX coordination
        service at the new world size (see
        ``_dist_init.reinit_distributed``; a real death changes
        ``jax.process_count()``) and returns the new device list (or
        None to keep the current one).  Every live device buffer dies
        with the old backend, so the transition is forced onto the
        checkpoint-restore reshard path — the peer transfer has nothing
        left to read."""
        self._dist_reinit = fn
        return self

    def attach_ladder(self, ladder):
        """Wire a :class:`~mxnet_tpu.elastic.DegradationLadder`: on
        every capacity change the ladder sheds/recovers serving
        admissions, and a drop below the ``MXTPU_ELASTIC_MIN_DP`` floor
        walks rung 3 (checkpoint-and-stop via the PR 4 preemption
        contract) instead of raising."""
        self._ladder = ladder
        return self

    @property
    def membership(self):
        return self._membership

    @property
    def notices(self):
        return self._notices

    @property
    def applied_epoch(self):
        """The membership epoch the running trainer was last built for."""
        return self._applied_epoch

    @property
    def applied_dp(self):
        """The dp the trainer was last rebuilt for (None before the
        first transition — the construction-time mesh is the trainer's
        business)."""
        return self._applied_dp

    def request_dp(self, n):
        """ISSUE 13: a deliberate, load-based dp target (the
        autoscaler's seam).  Applied at the next step boundary through
        the SAME epoch-fenced ``resync`` as a membership change —
        bitwise reshard, tp/pp preserved.  The target is clamped to
        [min_dp, membership capacity]; returns the clamped value."""
        cap = self.target_dp(include_pending=True)
        self._requested_dp = max(self._min_dp, min(int(n), cap))
        return self._requested_dp

    def target_dp(self, include_pending=True):
        """The dp size the current membership implies: ranks (plus an
        in-rendezvous joiner about to be admitted) x devices-per-worker,
        capped at the device pool."""
        n = len(self._membership.ranks)
        if include_pending and self._membership.pending_join is not None:
            n += 1
        return max(1, min(n * self._dpw, len(self._devices)))

    # -- the step-boundary hook -----------------------------------------
    def pending(self):
        """True when a transition awaits the next step boundary (epoch
        moved, or a joiner sits in rendezvous).  Also expires overdue
        rendezvous — the degrade-to-smaller-dp policy needs no thread of
        its own."""
        if not self._enabled:
            return False
        if self._membership.poll() is not None:
            self.degraded = True       # rendezvous expired: continue small
        return (self._membership.epoch != self._applied_epoch
                or self._membership.pending_join is not None
                or self._requested_dp is not None)

    def _check_notices(self, step):
        """ISSUE 13: drain every pending preemption notice at this
        boundary — commit ``worker_dead`` for the doomed rank NOW,
        ahead of the heartbeat timeout, optionally checkpointing first
        (``drain_checkpoint``).  A notice whose grace window already
        lapsed raises the typed :class:`DrainDeadline` instead of
        silently degrading to the heartbeat path.  Returns the number
        of drains committed."""
        board = self._notices
        if board is None:
            return 0
        board.poll()
        pending = board.pending()
        _telem.set_gauge("elastic.pending_notices", len(pending))
        drained = 0
        for notice in pending:
            if notice.rank not in self._membership.ranks:
                # unknown or already-departed rank: nothing to drain
                board.mark_drained(notice)
                continue
            now = board.now()
            if notice.deadline is not None and now > notice.deadline:
                board.mark_expired(notice)
                raise DrainDeadline(
                    f"preemption notice for rank {notice.rank} "
                    f"({notice.kind}) expired {now - notice.deadline:.1f}s "
                    f"before this step boundary could drain it — the "
                    f"worker may already be gone and the heartbeat path "
                    f"will commit the death late; take the emergency "
                    f"exit (sync checkpoint + stop) now", notice=notice)
            t0 = time.perf_counter()
            if self.drain_checkpoint is not None and step is not None:
                # checkpoint-THEN-reshard: the drain leaves a durable
                # boundary before the membership moves
                self.drain_checkpoint(int(step))
            self._membership.worker_dead(notice.rank)
            board.mark_drained(notice)
            self.drains += 1
            self.last_drain_ms = round((time.perf_counter() - t0) * 1e3, 3)
            drained += 1
            if _telem.enabled():
                _telem.inc("elastic.drains")
                _telem.set_gauge("elastic.drain_ms", self.last_drain_ms)
                _telem.event("elastic.drain", rank=notice.rank,
                             notice=notice.kind,
                             step=None if step is None else int(step))
        return drained

    def check_step(self, step, trainer, params=None):
        """The pause seam (same contract as
        ``PreemptionHandler.check_step``): call between steps / at scan
        -window boundaries.  No transition -> None, O(1).  Otherwise the
        boundary IS the pause: reshard + resume happen here, and the
        returned dict tells the loop what happened —
        ``{"source": "peer", "step": None}`` (continue at the same
        step) or ``{"source": "checkpoint", "step": S}`` (rewind to S;
        the RNG came back with the checkpoint, so the replay is
        bitwise).  With a :class:`NoticeBoard` attached the boundary
        first drains noticed ranks (death committed AHEAD of the
        heartbeat timeout; ``elastic.pending_notices`` gauge published;
        lapsed grace raises :class:`DrainDeadline`)."""
        if not self._enabled:
            return None
        self._check_notices(step)
        if not self.pending():
            return None
        return self.resync(step, trainer, params=params)

    # -- the transition -------------------------------------------------
    def resync(self, step, trainer, params=None):
        """Apply the pending membership transition (or a load-based
        ``request_dp`` target) to ``trainer``."""
        from .. import checkpoint as _ckpt
        from ..parallel.mesh import AXIS_DP as _AXIS_DP
        t_pause = time.perf_counter()
        joiner = self._membership.pending_join
        force_ckpt = False
        if self._dist_reinit is not None \
                and self._membership.epoch != self._applied_epoch:
            # ISSUE 19: a REAL membership change — tear down + re-init
            # the JAX coordination service at the new world size before
            # any mesh math (jax.process_count() and the device pool
            # both change under us).  The old backend's buffers are
            # gone, so the peer-transfer reshard has nothing to read:
            # force the checkpoint-restore path.
            t_r = time.perf_counter()
            devices = self._dist_reinit(self._membership.epoch,
                                        sorted(self._membership.ranks))
            self.last_reinit_ms = round(
                (time.perf_counter() - t_r) * 1e3, 3)
            self.dist_reinits += 1
            if devices is not None:
                self._devices = list(devices)
            force_ckpt = True
            if _telem.enabled():
                _telem.inc("elastic.dist_reinits")
                _telem.set_gauge("elastic.coordinator_reinit_ms",
                                 self.last_reinit_ms)
                _telem.event("elastic.dist_reinit",
                             epoch=self._membership.epoch,
                             reinit_ms=self.last_reinit_ms)
        capacity = self.target_dp()
        new_dp = capacity if self._requested_dp is None \
            else max(1, min(self._requested_dp, capacity))
        same_membership = (self._membership.epoch == self._applied_epoch
                           and joiner is None)
        if same_membership and self._requested_dp is not None:
            # load-based rescale only: skip the reshard when the trainer
            # already runs at the requested dp (no-op transition)
            try:
                cur = int(dict(trainer.mesh.shape).get(_AXIS_DP, 0))
            except (AttributeError, TypeError):
                cur = 0
            if cur == new_dp:
                self._requested_dp = None
                return None
        if self._ladder is not None:
            outcome = self._ladder.assess(capacity, self._healthy_dp,
                                          self._min_dp)
            if outcome in ("stop", "stop-unhandled"):
                # rung 3: capacity below the floor.  The ladder already
                # requested the PR 4 preemption exit (sync checkpoint +
                # clean stop at the caller's boundary); do NOT reshard
                # below the floor, and do not raise when someone is
                # handling the stop.
                self.degraded = True
                self._requested_dp = None
                self._applied_epoch = self._membership.epoch
                info = {"source": "stop", "step": None, "dp": capacity,
                        "epoch": self._applied_epoch}
                self.last_event = info
                _telem.event("elastic.capacity_stop", dp=capacity,
                             floor=self._min_dp)
                if outcome == "stop-unhandled":
                    raise MXNetError(
                        f"elastic: membership epoch "
                        f"{self._membership.epoch} implies dp="
                        f"{capacity}, below the MXTPU_ELASTIC_MIN_DP="
                        f"{self._min_dp} floor, and no PreemptionHandler"
                        f"/stop hook is installed to take the "
                        f"checkpoint-and-stop exit — restore capacity "
                        f"or lower the floor")
                return info
        if new_dp < self._min_dp:
            raise MXNetError(
                f"elastic: membership epoch {self._membership.epoch} "
                f"implies dp={new_dp}, below the MXTPU_ELASTIC_MIN_DP="
                f"{self._min_dp} floor — refusing to continue crippled; "
                f"restore capacity or lower the floor")
        mesh = self._make_mesh(new_dp, trainer)
        t0 = time.perf_counter()
        info = None
        last_err = None
        for attempt in range(0 if force_ckpt else 1 + self._max_retries):
            try:
                info = _ckpt.reshard_in_place(trainer, mesh,
                                              params=params or self._net,
                                              _attempt=attempt)
                break
            except MXNetError as e:
                last_err = e
                if attempt < self._max_retries:
                    # bounded exponential backoff before re-trying the
                    # peer transfer (injectable: tests pass a no-op)
                    self._sleep(self._backoff_s * (2 ** attempt))
        if info is None:
            # peer transfer kept dying (e.g. the source worker itself
            # went down mid-reshard): recover from the newest valid
            # checkpoint instead of hanging or crashing the job
            try:
                info = _ckpt.reshard_from_checkpoint(
                    trainer, mesh, params=params or self._net,
                    manager=self._manager)
            except MXNetError as e:
                peer = ("skipped (dist reinit: buffers died with the "
                        "old backend)" if force_ckpt else last_err)
                raise MXNetError(
                    f"elastic reshard failed on both paths — peer: "
                    f"{peer}; checkpoint: {e}") from e
        if joiner is not None and \
                self._membership.pending_join == joiner:
            # state transfer done: commit the join (epoch bump)
            self._membership.confirm_join(joiner)
        self._applied_epoch = self._membership.epoch
        self._applied_dp = new_dp
        self._requested_dp = None
        if self._ladder is not None:
            # post-transition reassessment: capacity back at the healthy
            # target un-sheds serving admissions (rung 0)
            self._ladder.assess(self.target_dp(include_pending=False),
                                self._healthy_dp, self._min_dp)
        if self._kvstore is not None:
            self._kvstore.refresh_membership()
        if self._scheduler is not None:
            self._scheduler.reset_plan()
        t1 = time.perf_counter()
        self.transitions += 1
        self.last_reshard_ms = round((t1 - t0) * 1e3, 3)
        self.last_pause_ms = round((t1 - t_pause) * 1e3, 3)
        info = dict(info, dp=new_dp, epoch=self._applied_epoch,
                    reshard_ms=self.last_reshard_ms,
                    pause_ms=self.last_pause_ms)
        self.last_event = info
        if _telem.enabled():
            # the bench `elastic` block and live scrapes read these off
            # the registry — same numbers as stats(), one source
            _telem.set_context(step=None if step is None else int(step),
                               epoch=self._applied_epoch)
            _telem.inc("elastic.transitions")
            _telem.set_gauge("elastic.dp", new_dp)
            _telem.set_gauge("elastic.reshard_ms", self.last_reshard_ms)
            _telem.set_gauge("elastic.pause_ms", self.last_pause_ms)
            _telem.observe("elastic.reshard_ms_hist",
                           self.last_reshard_ms)
            _telem.event("elastic.transition", source=info["source"],
                         dp=new_dp, epoch=self._applied_epoch,
                         rewind_step=info.get("step"))
        if _trace.enabled():
            # the transition on the causal timeline (ISSUE 14): the
            # pause window with the reshard inside it — a training trace
            # shows exactly which step boundary paid the resync
            root = _trace.record("elastic.pause", t_pause, t1,
                                 dp=new_dp, epoch=self._applied_epoch,
                                 source=info["source"])
            _trace.record("elastic.reshard", t0, t1, parent=root)
        return info

    def _make_mesh(self, dp, trainer=None):
        """The post-transition mesh: the dp axis follows membership, the
        tp/pp axes follow the TRAINER's MeshConfig (ISSUE 11: an elastic
        transition epoch-fences all three axes — tp/pp shape is a model
        property and survives the reshard, dp is the elastic one)."""
        from ..parallel.mesh import MeshConfig
        cfg = getattr(trainer, "mesh_config", None)
        tp = cfg.tp if cfg is not None else 1
        pp = cfg.pp if cfg is not None else 1
        if tp > 1 or pp > 1:
            dp = max(1, min(dp, len(self._devices) // (tp * pp)))
        new = MeshConfig(dp=dp, tp=tp, pp=pp)
        return new.build(self._devices[:new.size])

    # -- observability ---------------------------------------------------
    def stats(self):
        """The bench ``elastic`` block inputs (see
        :func:`mxnet_tpu.elastic.elastic_block`)."""
        return {"enabled": self._enabled,
                "dp": self.target_dp(include_pending=False),
                "membership_epoch": self._membership.epoch,
                "transitions": self.transitions,
                "degraded": self.degraded,
                "reshard_ms": self.last_reshard_ms,
                "pause_ms": self.last_pause_ms,
                "drain_ms": self.last_drain_ms,
                "drains": self.drains,
                "dist_reinits": self.dist_reinits,
                "coordinator_reinit_ms": self.last_reinit_ms,
                "pending_notices": (len(self._notices.pending())
                                    if self._notices is not None else 0)}
