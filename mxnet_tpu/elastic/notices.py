"""Preemption-notice plumbing (ISSUE 13 tentpole, first half).

PR 8 reacts to worker deaths *after the fact*: heartbeat silence past
``MXTPU_PS_HEARTBEAT_TIMEOUT`` is the first signal, and by then the
victim may already have died mid-collective.  Real platforms announce
most deaths IN ADVANCE — GCE publishes maintenance events on the
instance metadata server, and a preemption delivers SIGTERM with a
grace window before SIGKILL.  This module turns those advance signals
into first-class membership input:

- a :class:`Notice` names a doomed rank, why, and the absolute deadline
  its grace window expires at;
- the :class:`NoticeBoard` is the process-wide ledger the elastic
  controller and the serving router read at their boundaries: a posted
  notice triggers an orderly **drain** (checkpoint-then-reshard for
  training, requeue-to-survivors for serving) *ahead of* the heartbeat
  timeout; a revoked notice (maintenance cancelled) cancels a pending
  drain before it commits;
- pluggable :class:`NoticeSource`\\ s feed the board:
  :class:`GCENoticeSource` polls the metadata server,
  :class:`SignalNoticeSource` converts SIGTERM into a graced notice,
  and :class:`FakeNoticeSource` scripts notices deterministically for
  tests and chaos scenarios (zero sleeps, FakeClock timestamps).

Every posted notice is an *incident*: it lands in the telemetry event
log and triggers a flight-recorder dump (``reason="notice:..."``) so a
preempted job always leaves a post-mortem, even when the drain itself
then succeeds.  :class:`DrainDeadline` is the typed failure for the
case PR 8 silently degraded: a notice whose grace window lapsed before
the next step boundary could drain it (the heartbeat path will still
catch the death — but late, and the caller deserves to know NOW).

TensorFlow's dynamic cluster membership (arXiv:1605.08695) treats
exactly this — deliberate, signal-driven membership change — as what
separates a production system from a demo.
"""
from __future__ import annotations

import os
import time

from ..base import MXNetError
from ..lint import racecheck as _racecheck
from .. import telemetry as _telem

__all__ = ["Notice", "NoticeBoard", "NoticeSource", "FakeNoticeSource",
           "SignalNoticeSource", "GCENoticeSource", "DrainDeadline",
           "make_notice_source", "default_notice_grace_s"]


class DrainDeadline(MXNetError):
    """A preemption notice's grace window expired before a step boundary
    could drain it.  The heartbeat path will still commit the death —
    late, mid-collective — but the caller is told NOW so it can take
    the emergency exit (sync checkpoint + clean stop) instead of
    limping into the timeout."""

    def __init__(self, msg, notice=None):
        super().__init__(msg)
        self.notice = notice


def default_notice_grace_s():
    """Grace window assumed for sources that do not carry one
    (``MXTPU_NOTICE_GRACE_S``, default 30 — the GCE preemption grace)."""
    return float(os.environ.get("MXTPU_NOTICE_GRACE_S", "30") or 30)


class Notice:
    """One advance warning: ``rank`` is doomed, ``deadline`` (absolute,
    board clock) is when the grace window runs out.  ``kind`` in
    {"preempt", "maintenance", "sigterm"} by convention — free-form."""

    __slots__ = ("rank", "kind", "grace_s", "posted_at", "deadline",
                 "source")

    def __init__(self, rank, kind, grace_s, posted_at, source="api"):
        self.rank = int(rank)
        self.kind = str(kind)
        self.grace_s = None if grace_s is None else float(grace_s)
        self.posted_at = float(posted_at)
        self.deadline = (None if self.grace_s is None
                         else self.posted_at + self.grace_s)
        self.source = str(source)

    def view(self):
        return {"rank": self.rank, "kind": self.kind,
                "grace_s": self.grace_s, "posted_at": self.posted_at,
                "deadline": self.deadline, "source": self.source}

    def __repr__(self):
        return (f"Notice(rank={self.rank}, kind={self.kind!r}, "
                f"deadline={self.deadline})")


class NoticeBoard:
    """The process-wide notice ledger.  Thread-safe: signal handlers,
    metadata pollers, the PS serve threads and the training thread may
    all touch it.  ``now`` is the injectable clock deadlines are
    measured against (``testing.faults.FakeClock`` in tests — the PR 4
    discipline; zero sleeps anywhere).
    """

    def __init__(self, now=None):
        self._lock = _racecheck.make_lock("NoticeBoard._lock")
        self._now = now if now is not None else time.time
        self._pending = {}        # guarded-by: _lock — rank -> Notice
        self._sources = []        # guarded-by: _lock
        self.posted = 0           # guarded-by: _lock — lifetime counters
        self.revoked = 0          # guarded-by: _lock
        self.expired = 0          # guarded-by: _lock
        self.drained = 0          # guarded-by: _lock

    def now(self):
        return self._now()

    # -- sources --------------------------------------------------------
    def attach_source(self, source):
        """Register a :class:`NoticeSource`; :meth:`poll` pulls it."""
        with self._lock:
            self._sources.append(source)
        attach = getattr(source, "attach", None)
        if callable(attach):
            attach(self)
        return self

    def poll(self):
        """Pull every attached source once (the controller/router call
        this at their boundaries — no polling thread of its own)."""
        with self._lock:
            sources = list(self._sources)
        for s in sources:
            s.poll(self)
        return self.pending()

    # -- the ledger -----------------------------------------------------
    def post(self, rank, grace_s=None, kind="preempt", source="api"):
        """Record an advance warning for ``rank``.  Re-posting for a
        rank already noticed keeps the EARLIER deadline (a second signal
        never extends a grace window).  The posting is an incident:
        event + flight-recorder dump."""
        if grace_s is None:
            grace_s = default_notice_grace_s()
        n = Notice(rank, kind, grace_s, self._now(), source=source)
        with self._lock:
            prev = self._pending.get(n.rank)
            if prev is not None and prev.deadline is not None and \
                    (n.deadline is None or prev.deadline <= n.deadline):
                return prev
            self._pending[n.rank] = n
            self.posted += 1
            pending = len(self._pending)
        _telem.event("notice.posted", rank=n.rank, notice=n.kind,
                     grace_s=n.grace_s, source=n.source)
        _telem.inc("notices.posted")
        _telem.set_gauge("elastic.pending_notices", pending)
        # a notice IS an incident: leave the post-mortem now, while the
        # process is still healthy enough to write it
        _telem.dump_flight(f"notice:{n.kind}:rank{n.rank}")
        return n

    def revoke(self, rank, source="api"):
        """Cancel the pending notice for ``rank`` (maintenance window
        cancelled / preemption withdrawn).  A drain that has not yet
        committed at a boundary is thereby cancelled.  Returns the
        revoked notice, or None."""
        rank = int(rank)
        with self._lock:
            n = self._pending.pop(rank, None)
            if n is not None:
                self.revoked += 1
            pending = len(self._pending)
        if n is not None:
            _telem.event("notice.revoked", rank=rank, notice=n.kind,
                         source=source)
            _telem.inc("notices.revoked")
            _telem.set_gauge("elastic.pending_notices", pending)
        return n

    def pending(self):
        """Pending notices, oldest-posted first."""
        with self._lock:
            return sorted(self._pending.values(),
                          key=lambda n: (n.posted_at, n.rank))

    def pending_for(self, rank):
        with self._lock:
            return self._pending.get(int(rank))

    def mark_drained(self, notice):
        """The consumer (controller/router) committed the drain this
        notice asked for — retire it."""
        with self._lock:
            cur = self._pending.get(notice.rank)
            if cur is notice or (cur is not None
                                 and cur.posted_at == notice.posted_at):
                del self._pending[notice.rank]
                self.drained += 1
            pending = len(self._pending)
        _telem.event("notice.drained", rank=notice.rank,
                     notice=notice.kind)
        _telem.set_gauge("elastic.pending_notices", pending)

    def mark_expired(self, notice):
        """The grace window lapsed before a boundary could drain it —
        retire the notice (the heartbeat path owns the death now) and
        record the miss."""
        with self._lock:
            cur = self._pending.get(notice.rank)
            if cur is notice or (cur is not None
                                 and cur.posted_at == notice.posted_at):
                del self._pending[notice.rank]
                self.expired += 1
            pending = len(self._pending)
        _telem.event("notice.expired", rank=notice.rank,
                     notice=notice.kind, deadline=notice.deadline)
        _telem.inc("notices.expired")
        _telem.set_gauge("elastic.pending_notices", pending)

    def stats(self):
        with self._lock:
            return {"pending": len(self._pending),
                    "posted": self.posted, "revoked": self.revoked,
                    "expired": self.expired, "drained": self.drained}


class NoticeSource:
    """Base class: a producer of notices.  ``poll(board)`` is called by
    :meth:`NoticeBoard.poll` at consumer boundaries — sources never need
    their own thread (they may run one if their transport demands it,
    but every built-in source is pull-based)."""

    def poll(self, board):  # pragma: no cover - interface
        raise NotImplementedError


class FakeNoticeSource(NoticeSource):
    """Deterministic scripted source for tests/chaos: queue preempt/
    revoke actions, optionally deferred by ``after_polls`` poll calls,
    and :meth:`poll` applies the due ones.  Zero wall-clock anywhere —
    deadlines come from the board's (Fake)clock."""

    def __init__(self):
        self._lock = _racecheck.make_lock("FakeNoticeSource._lock")
        self._script = []        # guarded-by: _lock
        self.polls = 0           # guarded-by: _lock

    def preempt(self, rank, grace_s=None, kind="preempt", after_polls=0):
        with self._lock:
            self._script.append(
                ["post", int(rank), grace_s, kind, int(after_polls)])
        return self

    def revoke(self, rank, after_polls=0):
        with self._lock:
            self._script.append(
                ["revoke", int(rank), None, None, int(after_polls)])
        return self

    def poll(self, board):
        due = []
        with self._lock:
            self.polls += 1
            keep = []
            for item in self._script:
                if item[4] <= 0:
                    due.append(item)
                else:
                    item[4] -= 1
                    keep.append(item)
            self._script = keep
        for op, rank, grace_s, kind, _ in due:
            if op == "post":
                board.post(rank, grace_s=grace_s, kind=kind,
                           source="fake")
            else:
                board.revoke(rank, source="fake")


class SignalNoticeSource(NoticeSource):
    """SIGTERM-grace source: converts the platform's kill signal into a
    graced notice for THIS worker's rank, so the controller drains at
    the next boundary instead of dying mid-step.

    Complementary to ``checkpoint.PreemptionHandler`` (which
    checkpoint-stops): use this one when the job should *reshard and
    continue on the survivors* rather than stop.  ``install()`` hooks
    ``signal.SIGTERM`` (chaining any previous handler); tests call
    :meth:`deliver` directly — no real signal needed."""

    def __init__(self, rank, grace_s=None):
        self.rank = int(rank)
        self.grace_s = (default_notice_grace_s() if grace_s is None
                        else float(grace_s))
        self._board = None
        self._fired = False
        self._prev = None
        self._installed = False

    def attach(self, board):
        self._board = board

    def deliver(self):
        """The signal body (callable directly from tests): post the
        notice for our rank.  Idempotent until the notice is consumed."""
        if self._board is not None:
            self._fired = True
            self._board.post(self.rank, grace_s=self.grace_s,
                             kind="sigterm", source="signal")

    def install(self):
        import signal as _signal
        if self._installed:
            return self

        def _handler(signum, frame):
            self.deliver()
            if callable(self._prev):
                self._prev(signum, frame)

        self._prev = _signal.signal(_signal.SIGTERM, _handler)
        self._installed = True
        return self

    def remove(self):
        import signal as _signal
        if self._installed:
            _signal.signal(_signal.SIGTERM,
                           self._prev if self._prev is not None
                           else _signal.SIG_DFL)
            self._installed = False
        return self

    def poll(self, board):
        # push-based (the signal posts directly); nothing to pull
        return None


class GCENoticeSource(NoticeSource):
    """GCE maintenance-event poller: reads the instance metadata server
    (``maintenance-event``) and posts/revokes a notice for THIS
    worker's rank.  Any transport failure (not on GCE, no network,
    timeout) counts as "no event" — the source degrades to the
    heartbeat path, it never takes the job down.

    ``fetch`` is injectable for tests (a callable returning the
    metadata string, e.g. ``"NONE"`` / ``"TERMINATE_ON_HOST_MAINTENANCE"``).
    """

    METADATA_URL = ("http://metadata.google.internal/computeMetadata/v1/"
                    "instance/maintenance-event")
    _DOOM = ("TERMINATE_ON_HOST_MAINTENANCE", "MIGRATE_ON_HOST_MAINTENANCE",
             "TERMINATE", "PREEMPTED")

    def __init__(self, rank, grace_s=None, fetch=None, timeout_s=0.5):
        self.rank = int(rank)
        self.grace_s = (default_notice_grace_s() if grace_s is None
                        else float(grace_s))
        self._timeout = float(timeout_s)
        self._fetch = fetch if fetch is not None else self._fetch_http
        self.errors = 0

    def _fetch_http(self):
        from urllib.request import Request, urlopen
        req = Request(self.METADATA_URL,
                      headers={"Metadata-Flavor": "Google"})
        with urlopen(req, timeout=self._timeout) as resp:  # pragma: no cover
            return resp.read().decode("utf-8", "replace").strip()

    def poll(self, board):
        try:
            state = (self._fetch() or "").strip().upper()
        except Exception:  # noqa: BLE001 — off-GCE/no-network is normal
            self.errors += 1
            return None
        if any(state.startswith(d) for d in self._DOOM):
            kind = "preempt" if "PREEMPT" in state else "maintenance"
            return board.post(self.rank, grace_s=self.grace_s,
                              kind=kind, source="gce")
        if state == "NONE" and board.pending_for(self.rank) is not None \
                and board.pending_for(self.rank).source == "gce":
            return board.revoke(self.rank, source="gce")
        return None


def make_notice_source(rank=0, spec=None):
    """Build the production notice source named by ``MXTPU_NOTICE_SOURCE``
    (``gce`` | ``sigterm`` | ``none``/unset).  Returns None when no
    source is configured — constructing a board/source explicitly is
    always the test/API path."""
    spec = (os.environ.get("MXTPU_NOTICE_SOURCE", "")
            if spec is None else spec).strip().lower()
    if spec in ("", "none", "0"):
        return None
    if spec == "gce":
        return GCENoticeSource(rank)
    if spec == "sigterm":
        return SignalNoticeSource(rank).install()
    raise MXNetError(
        f"MXTPU_NOTICE_SOURCE={spec!r}: expected 'gce', 'sigterm' or "
        f"'none'")
