"""Flagship model implementations (BERT, Transformer, Llama)."""
