"""``mx.test_utils`` — the testing toolkit.

Reference: python/mxnet/test_utils.py (SURVEY.md §4): assert_almost_equal
with dtype-scaled tolerances, check_numeric_gradient (central finite
differences), check_consistency (cross-backend), default_context
(env-switchable), rand_ndarray, @retry / with_seed seeding discipline.
"""
from __future__ import annotations

import functools
import os
import random as _pyrandom
import time

import numpy as _np
import jax

from .base import MXNetError
from .context import Context, cpu, tpu, current_context, num_tpus
from .ndarray.ndarray import NDArray, array
from .ndarray import random as _rnd

__all__ = ["default_context", "set_default_context", "assert_almost_equal",
           "almost_equal", "same", "rand_ndarray", "rand_shape_2d",
           "rand_shape_3d", "rand_shape_nd", "check_numeric_gradient",
           "check_consistency", "retry", "with_seed", "default_dtype",
           "effective_dtype", "assert_allclose"]

_DEFAULT_CTX = None


def default_context():
    """Env-switchable default (MXTPU_TEST_CTX=cpu|tpu), reference
    test_utils.default_context with MXNET_TEST_DEVICE."""
    global _DEFAULT_CTX
    if _DEFAULT_CTX is not None:
        return _DEFAULT_CTX
    env = os.environ.get("MXTPU_TEST_CTX", os.environ.get("MXNET_TEST_DEVICE"))
    if env:
        return Context(env.split("(")[0], 0)
    return current_context()


def set_default_context(ctx):
    global _DEFAULT_CTX
    _DEFAULT_CTX = ctx


def default_dtype():
    return _np.float32


def effective_dtype(arr):
    dt = arr.data.dtype if isinstance(arr, NDArray) else _np.asarray(arr).dtype
    return str(dt)


def _tols(dtype_a, dtype_b, rtol, atol):
    default = {"float16": (1e-2, 1e-4), "bfloat16": (4e-2, 1e-3),
               "float32": (1e-4, 1e-6), "float64": (1e-7, 1e-9)}
    loosest = (1e-7, 1e-9)
    for d in (str(dtype_a), str(dtype_b)):
        r, a = default.get(d, (1e-4, 1e-6))
        loosest = (max(loosest[0], r), max(loosest[1], a))
    return (rtol if rtol is not None else loosest[0],
            atol if atol is not None else loosest[1])


def _to_np(x):
    if isinstance(x, NDArray):
        return x.asnumpy()
    return _np.asarray(jax.device_get(x)) if hasattr(x, "devices") else \
        _np.asarray(x)


def assert_almost_equal(a, b, rtol=None, atol=None, names=("a", "b"),
                        equal_nan=False):
    """Reference: test_utils.assert_almost_equal (dtype-scaled tols)."""
    da = a.data.dtype if isinstance(a, NDArray) else _np.asarray(a).dtype
    db = b.data.dtype if isinstance(b, NDArray) else _np.asarray(b).dtype
    rtol, atol = _tols(da, db, rtol, atol)
    na, nb = _to_np(a).astype(_np.float64), _to_np(b).astype(_np.float64)
    if na.shape != nb.shape:
        raise AssertionError(
            f"shape mismatch: {names[0]}{na.shape} vs {names[1]}{nb.shape}")
    if not _np.allclose(na, nb, rtol=rtol, atol=atol, equal_nan=equal_nan):
        diff = _np.abs(na - nb)
        rel = diff / (_np.abs(nb) + atol)
        idx = _np.unravel_index(_np.argmax(rel), rel.shape)
        raise AssertionError(
            f"Values differ (rtol={rtol}, atol={atol}): max abs diff "
            f"{diff.max():g}, max rel diff {rel.max():g} at {idx}: "
            f"{names[0]}={na[idx]!r} {names[1]}={nb[idx]!r}")


assert_allclose = assert_almost_equal


def almost_equal(a, b, rtol=None, atol=None, equal_nan=False):
    try:
        assert_almost_equal(a, b, rtol, atol, equal_nan=equal_nan)
        return True
    except AssertionError:
        return False


def same(a, b):
    return _np.array_equal(_to_np(a), _to_np(b))


def rand_ndarray(shape, stype="default", density=None, dtype="float32",
                 ctx=None, scale=1.0):
    if stype == "default":
        data = _np.random.uniform(-scale, scale, shape).astype(dtype)
        return array(data, ctx=ctx, dtype=dtype)
    from .ndarray import sparse
    data = _np.random.uniform(-scale, scale, shape).astype(dtype)
    density = 0.3 if density is None else density
    if stype == "row_sparse":
        # density = fraction of non-zero ROWS (reference rand_ndarray)
        mask = (_np.random.rand(shape[0]) < density).reshape(
            (-1,) + (1,) * (len(shape) - 1))
    else:
        mask = _np.random.rand(*shape) < density
    data = data * mask
    if stype == "row_sparse":
        return sparse.row_sparse_array(data, shape=shape, ctx=ctx, dtype=dtype)
    if stype == "csr":
        return sparse.csr_matrix(data, shape=shape, ctx=ctx, dtype=dtype)
    raise MXNetError(f"bad stype {stype}")


def rand_shape_2d(dim0=10, dim1=10):
    return (_np.random.randint(1, dim0 + 1), _np.random.randint(1, dim1 + 1))


def rand_shape_3d(dim0=10, dim1=10, dim2=10):
    return (_np.random.randint(1, dim0 + 1), _np.random.randint(1, dim1 + 1),
            _np.random.randint(1, dim2 + 1))


def rand_shape_nd(num_dim, dim=10):
    return tuple(_np.random.randint(1, dim + 1, size=num_dim))


def check_numeric_gradient(fn, inputs, eps=1e-3, rtol=1e-2, atol=1e-4,
                           grad_nodes=None):
    """Compare autograd gradients against central finite differences.

    ``fn(*inputs) -> scalar NDArray``; inputs are NDArrays to differentiate.
    Reference: test_utils.check_numeric_gradient (the per-op correctness
    workhorse, SURVEY.md §4 technique 1)."""
    from . import autograd
    for x in inputs:
        x.attach_grad()
    with autograd.record():
        out = fn(*inputs)
    out.backward()
    analytic = [x.grad.asnumpy().copy() for x in inputs]

    for i, x in enumerate(inputs):
        base = x.asnumpy().astype(_np.float64)
        numeric = _np.zeros_like(base)
        flat = base.reshape(-1)
        num_flat = numeric.reshape(-1)
        for j in range(flat.size):
            orig = flat[j]
            flat[j] = orig + eps
            fp = float(fn(*[array(base.astype(_np.float32))
                            if k == i else inputs[k]
                            for k in range(len(inputs))]).asnumpy().sum())
            flat[j] = orig - eps
            fm = float(fn(*[array(base.astype(_np.float32))
                            if k == i else inputs[k]
                            for k in range(len(inputs))]).asnumpy().sum())
            flat[j] = orig
            num_flat[j] = (fp - fm) / (2 * eps)
        assert_almost_equal(analytic[i], numeric.astype(_np.float32),
                            rtol=rtol, atol=atol,
                            names=(f"autograd[{i}]", f"numeric[{i}]"))


def check_consistency(fn, inputs, ctx_list=None, rtol=None, atol=None):
    """Run fn on each context/dtype combination and cross-assert.
    Reference: test_utils.check_consistency (cpu-vs-gpu; here cpu-vs-tpu
    and fp32-vs-bf16, SURVEY.md §4 technique 2)."""
    if ctx_list is None:
        ctx_list = [cpu(0)] + ([tpu(0)] if num_tpus() else [])
    results = []
    for ctx in ctx_list:
        moved = [x.as_in_context(ctx) for x in inputs]
        results.append(fn(*moved))
    for r in results[1:]:
        assert_almost_equal(results[0], r, rtol=rtol, atol=atol)
    return results


def retry(n):
    """Retry flaky (statistical) tests n times. Reference:
    test_utils.retry."""
    assert n > 0

    def decorate(f):
        @functools.wraps(f)
        def wrapper(*args, **kwargs):
            for i in range(n):
                try:
                    return f(*args, **kwargs)
                except AssertionError:
                    if i == n - 1:
                        raise
                    _np.random.seed()
        return wrapper
    return decorate


def with_seed(seed=None):
    """Seed numpy/python/mx PRNGs per test and log the seed on failure.
    Reference: tests/python/unittest/common.py with_seed (SURVEY.md §4
    technique 4). Honors MXTPU_TEST_SEED for reproduction."""

    def decorate(f):
        @functools.wraps(f)
        def wrapper(*args, **kwargs):
            env = os.environ.get("MXTPU_TEST_SEED",
                                 os.environ.get("MXNET_TEST_SEED"))
            this_seed = int(env) if env else \
                (seed if seed is not None else
                 _np.random.randint(0, 2 ** 31))
            _np.random.seed(this_seed)
            _pyrandom.seed(this_seed)
            _rnd.seed(this_seed)
            try:
                return f(*args, **kwargs)
            except Exception:
                print(f"*** test failed with seed {this_seed}; reproduce "
                      f"with MXTPU_TEST_SEED={this_seed} ***")
                raise
        return wrapper
    return decorate


def list_gpus():
    """Reference test_utils.list_gpus: usable GPU indices. This build
    targets TPU — there are never CUDA GPUs; TPU devices live behind
    mx.tpu()/mx.context.num_tpus()."""
    return []


def download(url, fname=None, dirname=None, overwrite=False):
    """Reference test_utils.download (test-data fetcher). Zero-egress
    build: resolves only files that already exist locally."""
    import os as _os
    fname = fname or url.split("/")[-1]
    if dirname:
        fname = _os.path.join(dirname, fname)
    if _os.path.exists(fname):
        # overwrite would require re-fetching, which this build cannot do;
        # the existing local copy is the only usable answer either way
        return fname
    raise MXNetError(
        f"download() is unavailable (no network access) and {fname!r} "
        "does not exist locally; place the file there first.")
