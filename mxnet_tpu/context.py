"""Device contexts: ``mx.cpu()``, ``mx.tpu()`` (and ``mx.gpu()`` alias).

Rebuild of ``python/mxnet/context.py`` (reference): ``Context`` objects with a
``with``-scope "current context" stack. The TPU-native twist: ``device_id``
indexes into ``jax.devices(device_type)``, and placing an NDArray on a context
is a ``jax.device_put``. There are no streams or per-device worker threads to
manage — XLA's async runtime (which replaces ``src/engine/`` wholesale, see
SURVEY.md §1) owns scheduling.
"""
from __future__ import annotations

import threading

import jax

from .base import MXNetError

__all__ = ["Context", "cpu", "gpu", "tpu", "cpu_pinned", "current_context",
           "num_tpus", "num_gpus"]

_DEVTYPE_ALIASES = {
    "cpu": "cpu",
    "cpu_pinned": "cpu",
    # ``gpu`` kept for one-line porting of reference scripts: on this stack the
    # accelerator is whatever jax exposes as the default backend.
    "gpu": None,
    "tpu": None,
}


def _default_accelerator_platform():
    """Best accelerator platform name known to jax, else 'cpu'."""
    try:
        return jax.default_backend()
    except Exception:  # pragma: no cover - jax init failure
        return "cpu"


class Context:
    """A device context. Reference: python/mxnet/context.py (class Context)."""

    _current = threading.local()

    def __init__(self, device_type, device_id=0):
        if isinstance(device_type, Context):
            device_type, device_id = device_type.device_type, device_type.device_id
        device_type = device_type.lower()
        if device_type not in _DEVTYPE_ALIASES:
            raise MXNetError(f"unknown device type {device_type!r}")
        self.device_type = device_type
        self.device_id = int(device_id)

    # -- jax interop ------------------------------------------------------
    @property
    def jax_device(self):
        """Resolve to a concrete jax.Device."""
        platform = _DEVTYPE_ALIASES[self.device_type]
        if platform is None:
            platform = _default_accelerator_platform()
        try:
            devices = jax.devices(platform)
        except RuntimeError:
            if self.device_type in ("tpu", "gpu"):
                # graceful degradation mirroring mx.gpu() on a CPU build
                devices = jax.devices("cpu")
            else:
                raise
        if self.device_id >= len(devices):
            raise MXNetError(
                f"{self} out of range: only {len(devices)} {self.device_type} "
                f"device(s) visible")
        return devices[self.device_id]

    # -- scope handling ---------------------------------------------------
    def __enter__(self):
        if not hasattr(Context._current, "stack"):
            Context._current.stack = []
        Context._current.stack.append(self)
        return self

    def __exit__(self, *exc):
        Context._current.stack.pop()
        return False

    def __eq__(self, other):
        return (isinstance(other, Context)
                and self.device_type == other.device_type
                and self.device_id == other.device_id)

    def __hash__(self):
        return hash((self.device_type, self.device_id))

    def __repr__(self):
        return f"{self.device_type}({self.device_id})"

    def __str__(self):
        return self.__repr__()


def cpu(device_id=0):
    return Context("cpu", device_id)


def cpu_pinned(device_id=0):
    return Context("cpu_pinned", device_id)


def gpu(device_id=0):
    """Accelerator context, kept for script compatibility; same as tpu()."""
    return Context("gpu", device_id)


def tpu(device_id=0):
    """The TPU context — the north-star API (`mx.tpu()`)."""
    return Context("tpu", device_id)


def num_tpus():
    try:
        backend = _default_accelerator_platform()
        if backend == "cpu":
            return 0
        return len(jax.devices(backend))
    except RuntimeError:
        return 0


def num_gpus():
    return num_tpus()


def current_context():
    """Reference: python/mxnet/context.py current_context(); defaults to cpu(0)
    upstream — here it defaults to the best available device so that model-zoo
    scripts run on the TPU without a context argument."""
    stack = getattr(Context._current, "stack", None)
    if stack:
        return stack[-1]
    return default_context()


def default_context():
    if num_tpus() > 0:
        return tpu(0)
    return cpu(0)
