"""Dynamic loss scaler (reference: python/mxnet/contrib/amp/loss_scaler.py).

On TPU the target dtype is bfloat16, whose exponent range equals fp32 —
loss scaling is then a no-op (scale pinned to 1). For fp16 the classic
dynamic scheme applies: double every `scale_window` clean steps, halve on
overflow and skip the update.
"""
from __future__ import annotations

import numpy as _np


class LossScaler:
    def __init__(self, init_scale=2.0 ** 16, scale_factor=2.0,
                 scale_window=2000, dynamic=True):
        self.loss_scale = float(init_scale)
        self._scale_factor = scale_factor
        self._scale_window = scale_window
        self._dynamic = dynamic
        self._unskipped = 0

    def has_overflow(self, params):
        """True if any present gradient is non-finite."""
        import jax.numpy as jnp
        for p in params:
            if p._data is not None and p._data._grad is not None:
                if not bool(jnp.isfinite(p._data._grad).all()):
                    return True
        return False

    def update_scale(self, overflow):
        if not self._dynamic:
            return
        if overflow:
            self.loss_scale = max(self.loss_scale / self._scale_factor, 1.0)
            self._unskipped = 0
        else:
            self._unskipped += 1
            if self._unskipped >= self._scale_window:
                self.loss_scale = min(self.loss_scale * self._scale_factor,
                                      2.0 ** 24)
                self._unskipped = 0
