"""AMP op lists (reference: python/mxnet/contrib/amp/lists/symbol.py).

Three classes, MXNet's scheme:
- TARGET_DTYPE_OPS: run in the low-precision target dtype (bf16 on TPU —
  these are the MXU ops where reduced precision buys throughput).
- FP32_OPS: numerically sensitive; inputs are cast up to float32.
- WIDEST_TYPE_CASTS: multi-input ops whose inputs are cast to the widest
  dtype among them (e.g. elementwise add of bf16 + fp32).
Everything unlisted runs in whatever dtype arrives.
"""

# MXU-bound ops: matmuls / convs / rnn — the fp16 whitelist of the reference
TARGET_DTYPE_OPS = [
    "FullyConnected", "Convolution", "Deconvolution", "dot", "batch_dot",
    "linalg_gemm2", "RNN",
]

# the reference's fp32 blacklist: softmax family, norms, losses, exp/log/pow
FP32_OPS = [
    "softmax", "log_softmax", "softmin", "SoftmaxActivation", "SoftmaxOutput",
    "softmax_cross_entropy", "BatchNorm", "LayerNorm", "InstanceNorm",
    "L2Normalization", "norm", "exp", "log", "log2", "log10", "expm1",
    "log1p", "erf", "gamma", "gammaln", "smooth_l1", "mean", "sum", "nansum",
    "prod", "nanprod", "cumsum",
]

WIDEST_TYPE_CASTS = [
    "add_n", "concat", "stack", "where", "broadcast_add", "broadcast_sub",
    "broadcast_mul", "broadcast_div",
]
