"""``mx.amp`` — automatic mixed precision.

Reference: python/mxnet/contrib/amp/amp.py (SURVEY.md §2.2 "AMP"): op-list
driven low-precision casting + dynamic loss scaling, `amp.init()`,
`amp.init_trainer()`, `amp.scale_loss()`.

TPU-first: the default target dtype is **bfloat16** (MXU-native; same
exponent range as fp32, so no loss scaling needed — the scaler pins to 1).
`init()` wraps the op-registry functions (the `mx.nd.*` the reference would
rewrite at the symbol-graph level): TARGET_DTYPE_OPS cast inputs down to
bf16 before dispatch, FP32_OPS cast up to fp32, WIDEST_TYPE_CASTS promote
to the widest input dtype. Under `hybridize()` the casts trace into the
jitted XLA program, so mixed precision is compiled, not interpreted.
"""
from __future__ import annotations

import functools
from contextlib import contextmanager

import numpy as _np

from ..base import MXNetError
from . import lists
from .loss_scaler import LossScaler

__all__ = ["init", "init_trainer", "scale_loss", "unscale",
           "list_lp16_ops", "list_fp32_ops", "convert_model",
           "convert_hybrid_block", "LossScaler"]

_initialized = False
_target_dtype = None
_originals = {}


def _cast_arrays(args, kwargs, dtype):
    import jax.numpy as jnp
    from ..ndarray.ndarray import NDArray

    def cast(x):
        # jnp.issubdtype knows the ml_dtypes (bfloat16), numpy's does not
        if isinstance(x, NDArray) and jnp.issubdtype(x.data.dtype,
                                                     jnp.floating):
            if str(x.data.dtype) != dtype:
                return x.astype(dtype)
        return x

    return [cast(a) for a in args], {k: cast(v) for k, v in kwargs.items()}


def _widest_dtype(args, kwargs):
    import jax.numpy as jnp
    from ..ndarray.ndarray import NDArray
    widest = None
    for x in list(args) + list(kwargs.values()):
        if isinstance(x, NDArray) and jnp.issubdtype(x.data.dtype,
                                                     jnp.floating):
            widest = x.data.dtype if widest is None else \
                jnp.promote_types(widest, x.data.dtype)
    return None if widest is None else str(widest)


def _wrap(fn, mode, target_dtype):
    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        if mode == "low":
            args, kwargs = _cast_arrays(args, kwargs, target_dtype)
        elif mode == "fp32":
            args, kwargs = _cast_arrays(args, kwargs, "float32")
        elif mode == "widest":
            w = _widest_dtype(args, kwargs)
            if w is not None:
                args, kwargs = _cast_arrays(args, kwargs, w)
        return fn(*args, **kwargs)

    wrapper._amp_original = fn
    return wrapper


def init(target_dtype="bfloat16", target_precision_ops=None,
         conditional_fp32_ops=None, fp32_ops=None):
    """Patch the op registry for mixed precision.

    target_dtype: 'bfloat16' (TPU default) or 'float16' (API compat).
    """
    global _initialized, _target_dtype
    if _initialized:
        return
    if target_dtype not in ("bfloat16", "float16"):
        raise MXNetError("target_dtype must be bfloat16 or float16")
    _target_dtype = target_dtype

    from .. import ndarray as nd_ns
    from ..ndarray import ops as ops_mod

    low = set(lists.TARGET_DTYPE_OPS) | set(target_precision_ops or [])
    fp32 = (set(lists.FP32_OPS) | set(fp32_ops or [])) - low
    widest = set(lists.WIDEST_TYPE_CASTS) - low - fp32

    for name_set, mode in ((low, "low"), (fp32, "fp32"), (widest, "widest")):
        for name in name_set:
            fn = getattr(ops_mod, name, None)
            if fn is None or not callable(fn):
                continue
            wrapped = _wrap(fn, mode, target_dtype)
            _originals[name] = fn
            setattr(ops_mod, name, wrapped)
            # the gluon F namespace is the `mxnet_tpu.ndarray` module
            if getattr(nd_ns, name, None) is fn:
                setattr(nd_ns, name, wrapped)
    _initialized = True


def _deinit_for_tests():
    """Undo init() — test helper, not part of the reference API."""
    global _initialized, _target_dtype
    from .. import ndarray as nd_ns
    from ..ndarray import ops as ops_mod
    for name, fn in _originals.items():
        setattr(ops_mod, name, fn)
        if hasattr(nd_ns, name):
            setattr(nd_ns, name, fn)
    _originals.clear()
    _initialized = False
    _target_dtype = None


def init_trainer(trainer):
    """Attach a loss scaler to a Trainer (reference: amp.init_trainer).

    bf16 needs no scaling -> static scale 1; fp16 gets the dynamic scaler.
    """
    if not _initialized:
        raise MXNetError("call amp.init() before amp.init_trainer()")
    if _target_dtype == "bfloat16":
        trainer._amp_loss_scaler = LossScaler(init_scale=1.0, dynamic=False)
    else:
        trainer._amp_loss_scaler = LossScaler()
    trainer._amp_original_step = trainer.step

    def amp_step(batch_size, ignore_stale_grad=False):
        scaler = trainer._amp_loss_scaler
        trainer._optimizer.rescale_grad = \
            trainer._scale / batch_size / scaler.loss_scale
        trainer._all_reduce_grads()
        # dynamic (fp16) scaling always checks for overflow — the scale can
        # sit at its 1.0 floor and grads still be inf; the static bf16
        # scaler skips the check (bf16 has fp32's exponent range).
        # Checked AFTER the grad sync: reduced grads are identical on every
        # worker (inf/nan propagates through the sum), so all workers take
        # the same skip decision — a pre-sync local check could desync the
        # collective schedule under a dist kvstore.
        overflow = scaler._dynamic and scaler.has_overflow(trainer._params)
        if not overflow:
            trainer._update(ignore_stale_grad)
        else:   # skip step, drop stale grads
            for p in trainer._params:
                if p._data is not None and p._data._grad is not None:
                    p._data._grad_fresh = False
        scaler.update_scale(overflow)

    def step(batch_size, ignore_stale_grad=False):
        if not trainer._kv_initialized:
            trainer._init_kvstore()
        amp_step(batch_size, ignore_stale_grad)

    trainer.step = step


@contextmanager
def scale_loss(loss, trainer):
    """Scale the loss before backward (reference: amp.scale_loss)."""
    scaler = getattr(trainer, "_amp_loss_scaler", None)
    if scaler is None or scaler.loss_scale == 1.0:
        yield loss
        return
    if isinstance(loss, (list, tuple)):
        yield [l * scaler.loss_scale for l in loss]
    else:
        yield loss * scaler.loss_scale


def unscale(trainer):
    """Divide current grads by the loss scale (reference: amp.unscale)."""
    scaler = getattr(trainer, "_amp_loss_scaler", None)
    if scaler is None or scaler.loss_scale == 1.0:
        return
    inv = 1.0 / scaler.loss_scale
    for p in trainer._params:
        if p._data is not None and p._data._grad is not None:
            p._data._grad = p._data._grad * inv


def convert_hybrid_block(block, target_dtype="bfloat16"):
    """Cast a HybridBlock's parameters to the target dtype in place and
    return it (reference: amp.convert_hybrid_block returns a converted
    block; here parameters are cast and activations follow op lists)."""
    block.cast(target_dtype)
    return block


def list_lp16_ops(target_dtype="bfloat16"):
    """Reference amp.list_lp16_ops: op names cast to the low-precision
    dtype under AMP (the list is dtype-independent here: one policy
    table serves bf16 and fp16)."""
    return list(lists.TARGET_DTYPE_OPS)


def list_fp32_ops(target_dtype="bfloat16"):
    """Reference amp.list_fp32_ops: op names pinned to fp32 under AMP
    (dtype-independent, see list_lp16_ops)."""
    return list(lists.FP32_OPS)


def convert_model(sym, arg_params, aux_params, target_dtype="bfloat16",
                  target_dtype_ops=None, fp32_ops=None,
                  conditional_fp32_ops=None, excluded_sym_names=None,
                  cast_optional_params=False):
    """Reference amp.convert_model(sym, args, aux): Module-API mixed
    precision. Under XLA the cast policy is applied at DISPATCH (amp.init
    wraps the op table), not by graph surgery, so the symbol is returned
    unchanged; floating-point parameters are cast when
    cast_optional_params is set. conditional_fp32_ops/excluded_sym_names
    are accepted for reference-API compatibility (per-node graph surgery
    does not exist here; exclude at the op level via fp32_ops)."""
    import jax.numpy as jnp
    if _initialized:
        if target_dtype != _target_dtype:
            raise MXNetError(
                f"amp already initialized with target_dtype="
                f"{_target_dtype}; convert_model(target_dtype="
                f"{target_dtype}) cannot change the dispatch policy "
                "mid-process")
        if target_dtype_ops or fp32_ops:
            # init() would silently drop these on its already-initialized
            # fast path — refuse rather than pretend the pins applied
            raise MXNetError(
                "amp already initialized; convert_model cannot add "
                "target_dtype_ops/fp32_ops to an installed policy — pass "
                "them to the FIRST amp.init/convert_model call")
    init(target_dtype=target_dtype, target_precision_ops=target_dtype_ops,
         fp32_ops=fp32_ops)
    aux_params = aux_params or {}
    if cast_optional_params:
        dt = "bfloat16" if target_dtype == "bfloat16" else "float16"
        norm_suffixes = ("gamma", "beta", "running_mean", "running_var",
                         "moving_mean", "moving_var")

        def cast(name, v):
            # float params only (integer counters/index tables keep their
            # dtype), and norm-family params stay fp32 — their ops are
            # FP32_OPS and the reference keeps fp32-op params in fp32
            # (a bf16 round-trip would truncate running stats for good)
            if name.endswith(norm_suffixes):
                return v
            if jnp.issubdtype(v.data.dtype, jnp.floating):
                return v.astype(dt)
            return v

        arg_params = {k: cast(k, v) for k, v in arg_params.items()}
        aux_params = {k: cast(k, v) for k, v in aux_params.items()}
    return sym, arg_params, aux_params
