"""Imperative autograd tape.

TPU-native replacement for the reference's C++ imperative autograd runtime
(``src/imperative/imperative.cc``: ``Imperative::RecordOp`` /
``Imperative::Backward``; SURVEY.md §2.1 "Imperative runtime + autograd").

Design (SURVEY.md §7 "core trick"): JAX's autodiff is functional, while MXNet's
API is an imperative tape (``autograd.record()`` … ``loss.backward()``). We
bridge them by recording, at dispatch time, one tape *node* per executed op.
While recording, every op is executed through ``jax.vjp`` so the node captures
a ready-to-run pullback (residuals live on device — this IS the forward pass,
nothing is computed twice). ``backward()`` then walks nodes in reverse creation
order, feeding output cotangents into each pullback and accumulating input
cotangents into either producer nodes or user gradients (``attach_grad`` with
``grad_req`` write/add/null, matching ``Imperative::MarkVariables``).
"""
from __future__ import annotations

import threading

import jax
import jax.numpy as jnp

from .base import MXNetError

__all__ = ["is_recording", "is_training", "set_recording", "set_training",
           "apply_op", "backward", "mark_variable", "Node",
           "register_grad_ready_hook"]


class _TapeState(threading.local):
    def __init__(self):
        self.recording = False
        self.training = False
        self.counter = 0
        # inside a jit trace we must not record (pure replay), see CachedOp
        self.trace_depth = 0
        # autograd.grad() temporarily hijacks _grad/_grad_req on its
        # variables; grad-ready hooks must not observe that scratch state
        self.hooks_disabled = False


_STATE = _TapeState()


def is_recording():
    return _STATE.recording and _STATE.trace_depth == 0


def is_training():
    return _STATE.training


def set_recording(flag):
    prev = _STATE.recording
    _STATE.recording = flag
    return prev


def set_training(flag):
    prev = _STATE.training
    _STATE.training = flag
    return prev


class trace_scope:
    """Disable tape recording while tracing a CachedOp/jit region."""

    def __enter__(self):
        _STATE.trace_depth += 1
        return self

    def __exit__(self, *exc):
        _STATE.trace_depth -= 1
        return False


class Node:
    """One recorded op: inputs, pullback, and per-output cotangent slots."""

    __slots__ = ("inputs", "vjp_fn", "fn", "n_out", "out_grads",
                 "out_protos", "order", "name", "__weakref__")

    def __init__(self, inputs, vjp_fn, outs, order, name="", fn=None):
        self.inputs = inputs            # list[NDArray]
        self.vjp_fn = vjp_fn
        self.fn = fn                    # pure forward, kept for replay
        self.n_out = len(outs)
        self.out_grads = [None] * self.n_out
        self.out_protos = [(o.shape, o.dtype) for o in outs]
        self.order = order
        self.name = name


class SparseCotangent:
    """A row-sparse cotangent flowing through backward: (row indices,
    row values, dense shape). Produced by ops with ``sparse_grad=True``
    (Embedding); accumulated leaf-side without densifying — the memory
    contract of reference row_sparse gradients (SURVEY.md §2.5)."""

    __slots__ = ("indices", "values", "shape")

    def __init__(self, indices, values, shape):
        self.indices = indices   # jnp int array (rows,)
        self.values = values     # jnp array (rows, ...)
        self.shape = tuple(shape)

    @property
    def dtype(self):
        return self.values.dtype

    def densify(self):
        # .add, not .set: indices may repeat (Embedding emits raw batch
        # ids) and duplicate rows must SUM
        return jnp.zeros(self.shape, self.values.dtype) \
            .at[self.indices].add(self.values)

    def merge(self, other):
        """Sum with another sparse cotangent of the same dense shape —
        indices concat now, dedup deferred to materialization."""
        return SparseCotangent(
            jnp.concatenate([self.indices, other.indices]),
            jnp.concatenate([self.values, other.values], axis=0),
            self.shape)

    def dedup(self):
        from .ndarray.sparse import sum_duplicate_rows
        uniq, summed = sum_duplicate_rows(self.indices, self.values)
        return SparseCotangent(uniq, summed, self.shape)

    def astype(self, dtype):
        return SparseCotangent(self.indices, self.values.astype(dtype),
                               self.shape)


def _add_cotangents(a, b):
    """Sum two cotangents, either of which may be sparse."""
    a_sp = isinstance(a, SparseCotangent)
    b_sp = isinstance(b, SparseCotangent)
    if a_sp and b_sp:
        return a.merge(b)
    if a_sp:
        return b.at[a.indices].add(a.values)
    if b_sp:
        return a.at[b.indices].add(b.values)
    return a + b


def _on_tape(arr):
    return arr._grad_req != "null" or arr._node is not None


def apply_op(fn, inputs, n_out=1, name=""):
    """Execute ``fn`` (pure, jax arrays -> jax array(s)) over NDArray inputs.

    Every NDArray op routes through here — the single dispatch point standing
    in for ``Imperative::Invoke`` (reference src/imperative/imperative.cc).
    Returns raw jax output(s) plus the Node to attach (or None).
    """
    datas = [x._data for x in inputs]
    record = is_recording() and any(_on_tape(x) for x in inputs)
    try:
        if record:
            outs, vjp_fn = jax.vjp(lambda *a: fn(*a), *datas)
            if n_out == 1:
                outs = (outs,)
            _STATE.counter += 1
            node = Node(list(inputs), vjp_fn, outs, _STATE.counter, name,
                        fn=fn)
            return outs, node
        outs = fn(*datas)
        if n_out == 1:
            outs = (outs,)
        return outs, None
    except FloatingPointError as e:
        # MXTPU_DEBUG_NANS=1: jax_debug_nans raised on the first NaN/Inf —
        # attach the framework op name (jax only names the XLA primitive).
        # If the user enabled jax debug_nans themselves, leave the exception
        # type alone so their `except FloatingPointError` handlers still work.
        from . import debug as _debug
        if not _debug.debug_nans_enabled():
            raise
        raise MXNetError(
            f"NaN/Inf produced by op '{name or getattr(fn, '__name__', fn)}'"
            f" (MXTPU_DEBUG_NANS): {e}") from e


def mark_variable(arr, grad_req="write", stype=None):
    """attach_grad: reference Imperative::MarkVariables."""
    if grad_req not in ("write", "add", "null"):
        raise MXNetError(f"invalid grad_req {grad_req!r}")
    arr._grad_req = grad_req
    # attach_grad detaches the array from any producing graph, matching the
    # reference behaviour of NDArray.attach_grad (python/mxnet/ndarray/ndarray.py)
    arr._node = None
    arr._out_index = 0
    if grad_req == "null":
        arr._grad = None
    elif stype == "row_sparse":
        # no dense zero buffer: the first backward installs a
        # RowSparseNDArray grad with memory O(nnz)
        arr._grad = None
    else:
        arr._grad = jnp.zeros(arr.shape, arr.dtype)
    arr._grad_fresh = False


def _accumulate(slot, value):
    return value if slot is None else slot + value


# ---------------------------------------------------------------------------
# grad-ready hooks (ISSUE 5 tentpole): fire per variable, in backward order,
# the moment its gradient is FINAL — no remaining tape node can still
# contribute.  parallel.OverlapScheduler hangs per-bucket gradient
# communication off these so collectives overlap the rest of backprop
# instead of waiting for the whole backward (arXiv:2011.03641 §4).
# ---------------------------------------------------------------------------

_HOOK_COUNTER = [0]


class _HookHandle:
    """Returned by :func:`register_grad_ready_hook`; ``remove()``
    unregisters."""

    __slots__ = ("_arr", "_key")

    def __init__(self, arr, key):
        self._arr = arr
        self._key = key

    def remove(self):
        hooks = getattr(self._arr, "_grad_hooks", None)
        if hooks:
            hooks.pop(self._key, None)


def register_grad_ready_hook(arr, fn):
    """Register ``fn(arr)`` to run when ``arr``'s gradient is finalized
    by a backward pass (after grad_req write/add is applied, so
    ``arr._grad`` holds the finished value).  Hooks fire in backward
    order — variables used late in the forward fire first.  Returns a
    handle with ``remove()``."""
    if arr._grad_hooks is None:
        arr._grad_hooks = {}
    _HOOK_COUNTER[0] += 1
    key = _HOOK_COUNTER[0]
    arr._grad_hooks[key] = fn
    return _HookHandle(arr, key)


def _finalize_leaf(arr, g):
    """Apply grad_req and fire the variable's grad-ready hooks."""
    _apply_grad_req(arr, g)
    hooks = arr._grad_hooks
    if hooks and not _STATE.hooks_disabled:
        for fn in list(hooks.values()):
            fn(arr)


class suppress_grad_hooks:
    """Scope that keeps grad-ready hooks from firing (autograd.grad)."""

    def __enter__(self):
        self._prev = _STATE.hooks_disabled
        _STATE.hooks_disabled = True
        return self

    def __exit__(self, *exc):
        _STATE.hooks_disabled = self._prev
        return False


def backward(heads, head_grads=None, retain_graph=False, train_mode=True):
    """Run the reverse pass from ``heads``.

    Reference: ``Imperative::Backward`` (src/imperative/imperative.cc) invoked
    from ``python/mxnet/autograd.py`` ``backward()``.
    """
    if not isinstance(heads, (list, tuple)):
        heads = [heads]
    if head_grads is None:
        head_grads = [None] * len(heads)
    elif not isinstance(head_grads, (list, tuple)):
        head_grads = [head_grads]

    # Per-backward leaf accumulator: within ONE backward pass contributions
    # always sum; grad_req write/add governs behaviour ACROSS backward calls
    # (matching reference grad_req semantics in include/mxnet/op_attr_types.h).
    leaf_grads = {}

    def _leaf_accumulate(arr, g):
        if id(arr) in leaf_grads:
            leaf_grads[id(arr)] = (arr, _add_cotangents(
                leaf_grads[id(arr)][1], g))
        else:
            leaf_grads[id(arr)] = (arr, g)

    # seed output cotangents
    live = False
    for h, hg in zip(heads, head_grads):
        seed = jnp.ones(h.shape, h.dtype) if hg is None else hg._data
        if h._node is not None and h._node.vjp_fn is not None:
            node, idx = h._node, h._out_index
            node.out_grads[idx] = _accumulate(node.out_grads[idx], seed)
            live = True
        elif h._grad_req != "null":
            _leaf_accumulate(h, seed)

    if not live:
        for arr, g in leaf_grads.values():
            _finalize_leaf(arr, g)
        return

    # Collect the subgraph reachable from the heads (the tape holds no
    # global node list: the graph lives in NDArray._node / Node.inputs
    # references, so dropped graphs are garbage-collected and backward on
    # one graph can never disturb another recorded in the same scope).
    reachable = {}
    stack = [h._node for h in heads
             if h._node is not None and h._node.vjp_fn is not None]
    while stack:
        node = stack.pop()
        if id(node) in reachable:
            continue
        reachable[id(node)] = node
        for inp in node.inputs:
            if inp._node is not None and inp._node.vjp_fn is not None:
                stack.append(inp._node)

    # Per-leaf pending contribution counts: a grad-capable leaf is FINAL
    # (ready to fire its hooks) once every reachable node that lists it
    # as an input has been visited by the walk below.  Counted per input
    # POSITION, matching the zip(node.inputs, in_grads) delivery loop.
    pending = {}
    for node in reachable.values():
        for inp in node.inputs:
            if inp._grad_req != "null":
                pending[id(inp)] = pending.get(id(inp), 0) + 1

    def _maybe_finalize(arr):
        if pending.get(id(arr), 0) == 0 and id(arr) in leaf_grads:
            a, g = leaf_grads.pop(id(arr))
            _finalize_leaf(a, g)

    # head-seeded leaves with no upstream contributions are final now
    for arr, _ in list(leaf_grads.values()):
        _maybe_finalize(arr)

    # Walk reachable nodes newest->oldest; skip nodes with no cotangent.
    for node in sorted(reachable.values(), key=lambda n: n.order,
                       reverse=True):
        if node.vjp_fn is None or all(g is None for g in node.out_grads):
            # visiting still retires this node's pending contributions —
            # a skipped node can never deliver a cotangent later
            for inp in node.inputs:
                if inp._grad_req != "null":
                    pending[id(inp)] -= 1
                    _maybe_finalize(inp)
            continue
        cotangents = tuple(
            jnp.zeros(node.out_protos[k][0], node.out_protos[k][1])
            if g is None else g
            for k, g in enumerate(node.out_grads))
        try:
            in_grads = node.vjp_fn(
                cotangents if node.n_out > 1 else cotangents[0])
        except FloatingPointError as e:
            from . import debug as _debug
            if not _debug.debug_nans_enabled():
                raise
            raise MXNetError(
                f"NaN/Inf produced in backward of op "
                f"'{node.name or node.fn}' (MXTPU_DEBUG_NANS): {e}") from e
        if not isinstance(in_grads, (list, tuple)):
            in_grads = (in_grads,)
        for inp, g in zip(node.inputs, in_grads):
            if g is None:
                continue
            if inp._node is not None and inp._node.vjp_fn is not None:
                # upstream pullbacks are dense jax.vjp closures — a sparse
                # cotangent headed into one must materialize
                if isinstance(g, SparseCotangent):
                    g = g.densify()
                pnode, pidx = inp._node, inp._out_index
                pnode.out_grads[pidx] = _accumulate(pnode.out_grads[pidx], g)
            # an intermediate with attach_grad'd grad_req receives its grad
            # IN ADDITION to propagating upstream (reference autograd.grad
            # supports non-leaf variables)
            if inp._grad_req != "null":
                _leaf_accumulate(inp, g)
        # this node's contributions are delivered: retire them and fire
        # grad-ready hooks for any leaf that just became final — this IS
        # the backward-order firing the overlap scheduler keys off
        for inp in node.inputs:
            if inp._grad_req != "null":
                pending[id(inp)] -= 1
                _maybe_finalize(inp)
        # cotangent slots are consumed by this pass either way; only the
        # pullback/inputs survive under retain_graph
        node.out_grads = [None] * node.n_out
        if not retain_graph:
            node.vjp_fn = None
            node.fn = None      # also blocks replay_function on this graph
            node.inputs = []

    for arr, g in leaf_grads.values():
        _finalize_leaf(arr, g)


def replay_function(heads, variables):
    """Rebuild the pure function variables -> heads from the recorded tape.

    The higher-order-grad path (reference: MXAutogradBackwardEx with
    create_graph, python/mxnet/autograd.py grad()): the imperative tape is
    replayed as a pure jax function so ``jax.vjp`` of it can itself be
    recorded as one tape op — grad-of-grad then falls out of jax's ability
    to differentiate through vjp. Requires nodes that still hold their
    forward ``fn`` (i.e. recorded in this scope, not consumed by a
    non-retaining backward).
    """
    reachable = {}
    stack = [h._node for h in heads if h._node is not None]
    while stack:
        node = stack.pop()
        if node is None or id(node) in reachable:
            continue
        if node.fn is None:
            raise MXNetError(
                "graph was consumed by a previous backward; pass "
                "retain_graph=True / create_graph=True on the earlier call")
        reachable[id(node)] = node
        for inp in node.inputs:
            if inp._node is not None:
                stack.append(inp._node)
    order = sorted(reachable.values(), key=lambda n: n.order)
    var_ids = {id(v): i for i, v in enumerate(variables)}

    def f(*var_datas):
        out_cache = {}

        def val(arr):
            if id(arr) in var_ids:
                return var_datas[var_ids[id(arr)]]
            n = arr._node
            if n is not None and id(n) in out_cache:
                return out_cache[id(n)][arr._out_index]
            return arr._data

        for node in order:
            outs = node.fn(*[val(i) for i in node.inputs])
            if node.n_out == 1:
                outs = (outs,)
            out_cache[id(node)] = outs
        return tuple(val(h) for h in heads)

    return f


def _apply_grad_req(arr, g):
    if g.dtype != arr.dtype:
        g = g.astype(arr.dtype)
    if isinstance(g, SparseCotangent):
        from .ndarray.sparse import RowSparseNDArray
        prev = arr._grad
        if arr._grad_req == "add" and isinstance(prev, RowSparseNDArray):
            g = SparseCotangent(prev.indices.data, prev.values.data,
                                g.shape).merge(g)
        elif arr._grad_req == "add" and prev is not None:
            # dense accumulator already exists (attach_grad default)
            arr._grad = prev.at[g.indices].add(g.values)
            arr._grad_fresh = True
            arr._grad_reduced = False
            return
        g = g.dedup()
        arr._grad = RowSparseNDArray(g.values, g.indices, g.shape, arr._ctx)
    elif arr._grad_req == "add" and arr._grad is not None:
        prev = arr._grad
        from .ndarray.sparse import RowSparseNDArray
        if isinstance(prev, RowSparseNDArray):
            prev = prev.data
        arr._grad = prev + g
    else:
        arr._grad = g
    arr._grad_fresh = True
    arr._grad_reduced = False
