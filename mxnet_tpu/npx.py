"""``mx.npx`` — numpy-extension namespace.

Reference: python/mxnet/numpy_extension/ [≥1.6]. Provides the non-numpy
neural ops under numpy semantics. Backed directly by the op library.
"""
from __future__ import annotations

from .ndarray.ops import (softmax, log_softmax, relu, sigmoid, one_hot,
                          topk, pick, batch_dot, FullyConnected, Convolution,
                          Pooling, BatchNorm, LayerNorm, Embedding, Dropout,
                          Activation, sequence_mask)
from .util import set_np, reset_np, is_np_array

fully_connected = FullyConnected
convolution = Convolution
pooling = Pooling
batch_norm = BatchNorm
layer_norm = LayerNorm
embedding = Embedding
dropout = Dropout
activation = Activation


def gelu(x):
    from .ndarray.ops import LeakyReLU
    return LeakyReLU(x, act_type="gelu")
