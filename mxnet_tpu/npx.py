"""``mx.npx`` — the numpy-extension namespace.

Reference: python/mxnet/numpy_extension/ [>=1.6]: the neural/network ops
that have no numpy counterpart, exposed under numpy-array semantics
(``mx.np`` is jax.numpy per SURVEY.md §2.2 "numpy-compat" disposition).
Families mirrored here: nn compute ops, control flow
(src/operator/control_flow.cc), sequence ops, detection/contrib ops,
engine/state utilities (seed/waitall), and io.
"""
from __future__ import annotations

from .ndarray.ops import (  # noqa: F401 — re-exported surface
    softmax, log_softmax, relu, sigmoid, one_hot, topk, pick, batch_dot,
    FullyConnected, Convolution, Deconvolution, Pooling, BatchNorm,
    LayerNorm, Embedding, Dropout, Activation, LeakyReLU, sequence_mask,
    sequence_last, sequence_reverse, gather_nd, scatter_nd, arange_like,
    smooth_l1, ctc_loss, GridGenerator, BilinearSampler, where, clip,
    erf, erfinv, gamma, gammaln, reshape, foreach, while_loop, cond)
from .ndarray.contrib import box_iou, box_nms, ROIAlign as roi_align  # noqa: F401
from .ndarray.ndarray import waitall  # noqa: F401
from .ndarray import random  # noqa: F401
from .ndarray.utils import save, load  # noqa: F401
from .util import set_np, reset_np, is_np_array  # noqa: F401
from .context import cpu, gpu, num_gpus  # noqa: F401

# snake_case aliases (npx convention)
fully_connected = FullyConnected
convolution = Convolution
deconvolution = Deconvolution
pooling = Pooling
batch_norm = BatchNorm
layer_norm = LayerNorm
embedding = Embedding
dropout = Dropout
activation = Activation
leaky_relu = LeakyReLU
grid_generator = GridGenerator
bilinear_sampler = BilinearSampler
top_k = topk


def gelu(x):
    """Gaussian error linear unit (reference npx.leaky_relu
    act_type='gelu')."""
    return LeakyReLU(x, act_type="gelu")


def seed(s):
    """Global PRNG seed (reference npx.random.seed)."""
    from .ndarray import random as _r
    _r.seed(s)


def batch_flatten(x):
    """Collapse all but the first axis (reference npx.batch_flatten)."""
    return x.reshape((x.shape[0], -1))


def sigmoid_binary_cross_entropy(pred, label):
    """Numerically-stable fused sigmoid + binary cross entropy."""
    import jax.numpy as jnp
    from .ndarray.ndarray import NDArray, apply_nary

    def fn(p, y):
        return jnp.maximum(p, 0) - p * y + jnp.log1p(jnp.exp(-jnp.abs(p)))
    if isinstance(pred, NDArray):
        return apply_nary(fn, [pred, label],
                          name="sigmoid_binary_cross_entropy")
    return fn(pred, label)


__all__ = [
    "softmax", "log_softmax", "relu", "sigmoid", "one_hot", "topk",
    "top_k", "pick", "batch_dot", "fully_connected", "convolution",
    "deconvolution", "pooling", "batch_norm", "layer_norm", "embedding",
    "dropout", "activation", "leaky_relu", "gelu", "sequence_mask",
    "sequence_last", "sequence_reverse", "gather_nd", "scatter_nd",
    "arange_like", "smooth_l1", "ctc_loss", "grid_generator",
    "bilinear_sampler", "roi_align", "box_iou", "box_nms", "foreach",
    "while_loop", "cond", "waitall", "seed", "random", "save", "load",
    "set_np", "reset_np", "is_np_array", "cpu", "gpu", "num_gpus",
    "batch_flatten", "sigmoid_binary_cross_entropy", "reshape", "where",
    "clip", "erf", "erfinv", "gamma", "gammaln",
]
