"""Multi-process rendezvous, run before any JAX computation.

Reference contract: ps-lite rendezvous happens when the first KVStore is
created from DMLC_* env (SURVEY.md §3.5). JAX's coordination service must
instead be up BEFORE the backend initializes, so this runs at package
import when tools/launch.py (or an operator) set the MXTPU_* env.

ISSUE 19 (real multi-process pods) made the init path fault-TOLERANT:
``jax.distributed.initialize``'s default client installs a
missed-heartbeat / error-poll callback that ``LOG(FATAL)``-terminates
the process the moment ANY peer dies, and its ``shutdown()`` runs a
coordination-service barrier that can never be satisfied once a peer
was SIGKILLed — i.e. the stock path turns one death into pod suicide.
``_raw_init`` builds the same service/client pair through the jaxlib
extension directly, but with a benign missed-heartbeat callback (a
peer death is the POD LAUNCHER's membership signal, not a reason to
terminate survivors) and ``shutdown_on_destruction=False`` so teardown
can ORPHAN a coordination service whose shutdown barrier is
unsatisfiable.  ``reinit_distributed`` is the committed-membership-
change seam: tear down, clear every cached world-size view, re-init at
the new coordinates.
"""
from __future__ import annotations

import os

_DONE = False

#: orphaned (client, service) pairs from pre-reshard epochs — kept
#: referenced so their destructors (which would block on RPCs to dead
#: peers) never run; the port leak lasts only for the process lifetime
_ORPHANED = []


def _heartbeat_knobs():
    """(interval_s, max_missing) for the coordination service/client.
    The defaults keep detection with the launcher (which watches real
    pids) rather than the coordination service: a huge miss budget so
    the service never error-propagates a death into the survivors —
    they'll have re-initialized at a new epoch long before."""
    try:
        interval = int(os.environ.get(
            "MXTPU_COORD_HEARTBEAT_INTERVAL_S", "10") or 10)
    except ValueError:
        interval = 10
    try:
        max_missing = int(os.environ.get(
            "MXTPU_COORD_MAX_MISSING_HEARTBEATS", "1000") or 1000)
    except ValueError:
        max_missing = 1000
    return max(1, interval), max(1, max_missing)


def _raw_init(coordinator, num_processes, process_id):
    """Bring up the coordination service (process 0) + client without
    the stock fatal-on-peer-death callbacks.  Fills
    ``jax._src.distributed.global_state`` exactly like
    ``jax.distributed.initialize`` so the backend and
    ``multihost_utils`` see a normal distributed world."""
    import jax
    from jax._src import distributed as _dist
    from jax._src.lib import xla_extension as _xe

    gs = _dist.global_state
    if gs.client is not None:       # operator initialized it already
        return
    interval, max_missing = _heartbeat_knobs()
    port = str(coordinator).rsplit(":", 1)[1]
    if int(process_id) == 0 and gs.service is None:
        gs.service = _xe.get_distributed_runtime_service(
            "[::]:" + port, int(num_processes),
            heartbeat_interval=interval,
            max_missing_heartbeats=max_missing)

    def _on_missed(status):
        # a silent peer is the launcher's membership problem; log +
        # count, never terminate (the stock callback LOG(FATAL)s here)
        try:
            from . import telemetry as _telemetry
            _telemetry.inc("pod.coordination_errors")
            _telemetry.event("pod.coordination_error",
                             status=str(status))
        except Exception:  # noqa: BLE001 — never raise into the cb
            pass

    gs.client = _xe.get_distributed_runtime_client(
        str(coordinator), int(process_id),
        init_timeout=int(os.environ.get("MXTPU_COORD_INIT_TIMEOUT_S",
                                        "120") or 120),
        heartbeat_interval=interval,
        max_missing_heartbeats=max_missing,
        missed_heartbeat_callback=_on_missed,
        shutdown_on_destruction=False,
        use_compression=True)
    gs.client.connect()
    gs.process_id = int(process_id)
    gs.num_processes = int(num_processes)
    gs.coordinator_address = str(coordinator)
    assert jax  # keep the import: config side-effects must have run


def maybe_init_distributed():
    global _DONE
    if _DONE:
        return
    coord = os.environ.get("MXTPU_COORDINATOR")
    nproc = int(os.environ.get("MXTPU_NUM_PROCESSES", "1"))
    if coord and nproc > 1:
        # only latch once an actual init was attempted, so a store created
        # before the env is set still triggers rendezvous later
        _DONE = True
        import jax
        try:
            if os.environ.get("JAX_PLATFORMS", "") == "cpu":
                # CPU processes need an XLA collective transport for the
                # in-graph allreduce wire path (kvstore
                # _bucketed_allreduce); gloo ships with jaxlib
                jax.config.update(
                    "jax_cpu_collectives_implementation", "gloo")
        except Exception:  # noqa: BLE001 — allgather fallback still works
            pass
        _raw_init(coord, nproc,
                  int(os.environ.get("MXTPU_PROCESS_ID", "0")))


def teardown_distributed(graceful=False):
    """Leave the current coordination service WITHOUT the shutdown
    barrier (unsatisfiable once a peer was SIGKILLed): orphan the
    client/service pair so no destructor blocks on dead peers, then
    clear every cached world-size view so the next init starts clean.
    ``graceful=True`` additionally attempts the barriered shutdown
    first (clean full-pod exits, where every peer participates)."""
    import jax
    from jax._src import distributed as _dist
    from jax._src import xla_bridge

    gs = _dist.global_state
    if graceful and gs.client is not None:
        try:
            gs.client.shutdown()
            gs.client = None
        except Exception:  # noqa: BLE001 — fall through to orphaning
            pass
    if gs.client is not None or gs.service is not None:
        _ORPHANED.append((gs.client, gs.service))
    gs.client = None
    gs.service = None
    gs.preemption_sync_manager = None
    gs.process_id = 0
    gs.num_processes = 1
    gs.coordinator_address = None
    # jax.distributed.initialize refuses to run once backends exist,
    # and the old backend pins the old world size.  Every LIVE device
    # buffer dies here: callers must capture state to host (numpy /
    # checkpoint) FIRST — which is why the elastic controller drives
    # resharding through the checkpoint restore path on this route.
    xla_bridge._clear_backends()
    # both are @lru_cache'd on the bridge and would keep reporting the
    # old world (process_index is not cached)
    for cached in (xla_bridge.process_count, xla_bridge.local_devices):
        try:
            cached.cache_clear()
        except AttributeError:
            pass
    # compiled computations hold old Device objects; executing them
    # against the new backend fails with a buffer-on-wrong-client
    # error even though the device NAMES match
    jax.clear_caches()


def reinit_distributed(coordinator, num_processes, process_id):
    """Tear down and re-create the JAX coordination service at a new
    world size (ISSUE 19) — what a COMMITTED membership change means at
    process level: a real death changes ``jax.process_count()``, and
    that number is baked into the coordination service, the backend
    client, and several ``lru_cache``\\ d accessors.

    Also re-exports the MXTPU_* env so children forked after the change
    inherit the new world.  Returns the elapsed seconds (the bench
    ``coordinator_reinit_ms`` source).
    """
    import time as _time

    t0 = _time.monotonic()
    teardown_distributed()
    os.environ["MXTPU_COORDINATOR"] = str(coordinator)
    os.environ["MXTPU_NUM_PROCESSES"] = str(num_processes)
    os.environ["MXTPU_PROCESS_ID"] = str(process_id)
    if int(num_processes) > 1:
        _raw_init(coordinator, num_processes, process_id)
    return _time.monotonic() - t0
