"""Multi-process rendezvous, run before any JAX computation.

Reference contract: ps-lite rendezvous happens when the first KVStore is
created from DMLC_* env (SURVEY.md §3.5). JAX's coordination service must
instead be up BEFORE the backend initializes, so this runs at package
import when tools/launch.py (or an operator) set the MXTPU_* env.
"""
from __future__ import annotations

import os

_DONE = False


def maybe_init_distributed():
    global _DONE
    if _DONE:
        return
    coord = os.environ.get("MXTPU_COORDINATOR")
    nproc = int(os.environ.get("MXTPU_NUM_PROCESSES", "1"))
    if coord and nproc > 1:
        # only latch once an actual init was attempted, so a store created
        # before the env is set still triggers rendezvous later
        _DONE = True
        import jax
        try:
            if os.environ.get("JAX_PLATFORMS", "") == "cpu":
                # CPU processes need an XLA collective transport for the
                # in-graph allreduce wire path (kvstore
                # _bucketed_allreduce); gloo ships with jaxlib
                jax.config.update(
                    "jax_cpu_collectives_implementation", "gloo")
        except Exception:  # noqa: BLE001 — allgather fallback still works
            pass
        try:
            jax.distributed.initialize(
                coordinator_address=coord,
                num_processes=nproc,
                process_id=int(os.environ.get("MXTPU_PROCESS_ID", "0")))
        except RuntimeError:
            pass    # operator initialized it already
