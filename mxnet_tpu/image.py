"""``mx.image`` — image decode and augmentation.

Reference: python/mxnet/image/image.py (+detection.py) over OpenCV ops.
Decode uses PIL or cv2 when present, with a raw-numpy PPM/NPY fallback so the
module works in minimal environments. Augmenters mirror the reference's
CreateAugmenter pipeline.
"""
from __future__ import annotations

import io as _io
import struct

import numpy as _np

from .base import MXNetError
from .ndarray.ndarray import NDArray, array
from . import ndarray as nd

__all__ = ["imdecode", "imencode", "imread", "imresize", "resize_short",
           "fixed_crop", "center_crop", "random_crop", "color_normalize",
           "ImageIter", "CreateAugmenter", "Augmenter", "ResizeAug",
           "ForceResizeAug", "RandomCropAug", "CenterCropAug",
           "HorizontalFlipAug", "CastAug", "BrightnessJitterAug",
           "ContrastJitterAug", "SaturationJitterAug", "HueJitterAug",
           "RandomGrayAug", "ColorNormalizeAug", "ImageDetIter",
           "CreateDetAugmenter", "DetHorizontalFlipAug", "DetBorrowAug"]


def _get_backend():
    try:
        import cv2
        return "cv2", cv2
    except ImportError:
        pass
    try:
        from PIL import Image
        return "pil", Image
    except ImportError:
        return None, None


def imdecode(buf, flag=1, to_rgb=True, out=None):
    """Decode image bytes -> HWC uint8 NDArray (reference mx.image.imdecode
    over cv::imdecode)."""
    if isinstance(buf, NDArray):
        buf = bytes(buf.asnumpy().astype(_np.uint8))
    kind, mod = _get_backend()
    if kind == "cv2":
        img = mod.imdecode(_np.frombuffer(buf, _np.uint8),
                           mod.IMREAD_COLOR if flag else
                           mod.IMREAD_GRAYSCALE)
        if img is None:
            raise MXNetError("cv2 failed to decode image")
        if flag and to_rgb:
            img = img[:, :, ::-1]
        if not flag:
            img = img[:, :, None]
        return array(_np.ascontiguousarray(img), dtype="uint8")
    if kind == "pil":
        img = mod.open(_io.BytesIO(buf))
        img = img.convert("RGB" if flag else "L")
        arr = _np.asarray(img)
        if not flag:
            arr = arr[:, :, None]
        return array(arr, dtype="uint8")
    # fallback: raw .npy payloads (used by synthetic .rec files in tests)
    if buf[:6] == b"\x93NUMPY":
        return array(_np.load(_io.BytesIO(buf)), dtype="uint8")
    raise MXNetError("no image decode backend (cv2/PIL) available and "
                     "payload is not npy")


def imencode(img, quality=95, img_fmt=".jpg"):
    if isinstance(img, NDArray):
        img = img.asnumpy()
    img = _np.asarray(img, dtype=_np.uint8)
    kind, mod = _get_backend()
    if kind == "cv2":
        ok, buf = mod.imencode(img_fmt, img[:, :, ::-1])
        if not ok:
            raise MXNetError("cv2 imencode failed")
        return buf.tobytes()
    if kind == "pil":
        pil_img = mod.fromarray(img.squeeze() if img.shape[-1] == 1 else img)
        bio = _io.BytesIO()
        pil_img.save(bio, format="JPEG" if "jp" in img_fmt else "PNG",
                     quality=quality)
        return bio.getvalue()
    # npy fallback
    bio = _io.BytesIO()
    _np.save(bio, img)
    return bio.getvalue()


def imresize(src, w, h, interp=1):
    from .gluon.data.vision.transforms import _resize_np
    np_img = src.asnumpy() if isinstance(src, NDArray) else _np.asarray(src)
    return array(_resize_np(np_img, (w, h)))


def resize_short(src, size, interp=2):
    h, w = src.shape[:2]
    if h > w:
        new_w, new_h = size, int(size * h / w)
    else:
        new_w, new_h = int(size * w / h), size
    return imresize(src, new_w, new_h, interp)


def fixed_crop(src, x0, y0, w, h, size=None, interp=2):
    out = src[y0:y0 + h, x0:x0 + w]
    if size is not None and (w, h) != tuple(size):
        out = imresize(out, size[0], size[1], interp)
    return out


def center_crop(src, size, interp=2):
    h, w = src.shape[:2]
    new_w, new_h = size
    x0 = max((w - new_w) // 2, 0)
    y0 = max((h - new_h) // 2, 0)
    return fixed_crop(src, x0, y0, min(new_w, w), min(new_h, h), size,
                      interp), (x0, y0, new_w, new_h)


def random_crop(src, size, interp=2):
    h, w = src.shape[:2]
    new_w, new_h = min(size[0], w), min(size[1], h)
    x0 = _np.random.randint(0, w - new_w + 1)
    y0 = _np.random.randint(0, h - new_h + 1)
    return fixed_crop(src, x0, y0, new_w, new_h, size, interp), \
        (x0, y0, new_w, new_h)


def color_normalize(src, mean, std=None):
    src = src if isinstance(src, NDArray) else array(src)
    out = src.astype("float32") - (mean if isinstance(mean, NDArray)
                                   else array(_np.asarray(mean, "float32")))
    if std is not None:
        out = out / (std if isinstance(std, NDArray)
                     else array(_np.asarray(std, "float32")))
    return out


class Augmenter:
    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def __call__(self, src):
        raise NotImplementedError


class ResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return resize_short(src, self.size, self.interp)


class ForceResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return imresize(src, self.size[0], self.size[1], self.interp)


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return random_crop(src, self.size, self.interp)[0]


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return center_crop(src, self.size, self.interp)[0]


class HorizontalFlipAug(Augmenter):
    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if _np.random.rand() < self.p:
            return NDArray(src.data[:, ::-1], src.context) \
                if isinstance(src, NDArray) else src[:, ::-1]
        return src


class BrightnessJitterAug(Augmenter):
    """src *= 1 + U(-b, b) (reference image.BrightnessJitterAug)."""

    def __init__(self, brightness):
        super().__init__(brightness=brightness)
        self.brightness = brightness

    def __call__(self, src):
        alpha = 1.0 + _np.random.uniform(-self.brightness, self.brightness)
        return src * alpha


class ContrastJitterAug(Augmenter):
    """Blend toward the mean gray level (reference ContrastJitterAug)."""

    _coef = _np.array([0.299, 0.587, 0.114], _np.float32)

    def __init__(self, contrast):
        super().__init__(contrast=contrast)
        self.contrast = contrast

    def __call__(self, src):
        alpha = 1.0 + _np.random.uniform(-self.contrast, self.contrast)
        a = src.asnumpy() if isinstance(src, NDArray) else _np.asarray(src)
        gray = (a * self._coef).sum(axis=-1, keepdims=True)
        out = a * alpha + gray.mean() * (1.0 - alpha)
        return array(out.astype(a.dtype))


class SaturationJitterAug(Augmenter):
    """Blend toward per-pixel gray (reference SaturationJitterAug)."""

    _coef = _np.array([0.299, 0.587, 0.114], _np.float32)

    def __init__(self, saturation):
        super().__init__(saturation=saturation)
        self.saturation = saturation

    def __call__(self, src):
        alpha = 1.0 + _np.random.uniform(-self.saturation, self.saturation)
        a = src.asnumpy() if isinstance(src, NDArray) else _np.asarray(src)
        gray = (a * self._coef).sum(axis=-1, keepdims=True)
        return array((a * alpha + gray * (1.0 - alpha)).astype(a.dtype))


class HueJitterAug(Augmenter):
    """Rotate hue in YIQ space (reference HueJitterAug, the tyiq trick)."""

    def __init__(self, hue):
        super().__init__(hue=hue)
        self.hue = hue
        self.tyiq = _np.array([[0.299, 0.587, 0.114],
                               [0.596, -0.274, -0.321],
                               [0.211, -0.523, 0.311]], _np.float32)
        self.ityiq = _np.array([[1.0, 0.956, 0.621],
                                [1.0, -0.272, -0.647],
                                [1.0, -1.107, 1.705]], _np.float32)

    def __call__(self, src):
        alpha = _np.random.uniform(-self.hue, self.hue)
        u, w = _np.cos(alpha * _np.pi), _np.sin(alpha * _np.pi)
        bt = _np.array([[1.0, 0.0, 0.0],
                        [0.0, u, -w],
                        [0.0, w, u]], _np.float32)
        t = _np.dot(_np.dot(self.ityiq, bt), self.tyiq).T
        a = src.asnumpy() if isinstance(src, NDArray) else _np.asarray(src)
        return array(_np.dot(a, t).astype(a.dtype))


class RandomGrayAug(Augmenter):
    """With probability p collapse to 3-channel gray (reference
    RandomGrayAug)."""

    _coef = _np.array([0.299, 0.587, 0.114], _np.float32)

    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if _np.random.rand() < self.p:
            a = src.asnumpy() if isinstance(src, NDArray) \
                else _np.asarray(src)
            gray = (a * self._coef).sum(axis=-1, keepdims=True)
            return array(_np.broadcast_to(gray, a.shape)
                         .astype(a.dtype).copy())
        return src


def imread(filename, flag=1, to_rgb=True):
    """Read an image file -> HWC uint8 NDArray (reference mx.image.imread
    over cv::imread)."""
    with open(filename, "rb") as f:
        return imdecode(f.read(), flag=flag, to_rgb=to_rgb)


class ColorNormalizeAug(Augmenter):
    """(src - mean) / std (reference: image.ColorNormalizeAug)."""

    def __init__(self, mean, std=None):
        super().__init__()
        self.mean = mean if isinstance(mean, NDArray) or mean is None \
            else array(mean)
        self.std = std if isinstance(std, NDArray) or std is None \
            else array(std)

    def __call__(self, src):
        return color_normalize(src, self.mean, self.std)


class CastAug(Augmenter):
    def __init__(self, typ="float32"):
        super().__init__(type=typ)
        self.typ = typ

    def __call__(self, src):
        return src.astype(self.typ)


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, hue=0, pca_noise=0,
                    rand_gray=0, inter_method=2):
    """Reference: image.CreateAugmenter — builds the standard aug list."""
    auglist = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    if brightness:
        auglist.append(BrightnessJitterAug(brightness))
    if contrast:
        auglist.append(ContrastJitterAug(contrast))
    if saturation:
        auglist.append(SaturationJitterAug(saturation))
    if hue:
        auglist.append(HueJitterAug(hue))
    if rand_gray:
        auglist.append(RandomGrayAug(rand_gray))
    if mean is True:
        mean = array([123.68, 116.28, 103.53])
    if std is True:
        std = array([58.395, 57.12, 57.375])
    if mean is not None:
        auglist.append(ColorNormalizeAug(mean, std))
    return auglist


class ImageIter:
    """Reference: image.ImageIter (python-side image iterator with
    augmenters, .rec or list-file backed)."""

    def __init__(self, batch_size, data_shape, path_imgrec=None,
                 path_imglist=None, shuffle=False, aug_list=None, **kwargs):
        from .io import ImageRecordIter
        if path_imgrec is None:
            raise MXNetError("ImageIter requires path_imgrec on this build")
        self._inner = ImageRecordIter(path_imgrec, data_shape, batch_size,
                                      shuffle=shuffle)
        self.batch_size = batch_size
        self.provide_data = self._inner.provide_data
        self.provide_label = self._inner.provide_label

    def reset(self):
        self._inner.reset()

    def __iter__(self):
        return self

    def __next__(self):
        return self._inner.next()

    next = __next__


class DetHorizontalFlipAug(Augmenter):
    """Flip image AND bounding boxes (reference: image/detection.py
    DetHorizontalFlipAug). Labels are (N, 5+) rows [cls, x0, y0, x1, y1]
    in [0,1] coords."""

    def __init__(self, p=0.5):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src, label):
        import random as _random
        if _random.random() < self.p:
            src = nd.flip(src, axis=1)
            out = label.copy()
            out[:, 1] = 1.0 - label[:, 3]
            out[:, 3] = 1.0 - label[:, 1]
            return src, out
        return src, label


class DetBorrowAug(Augmenter):
    """Apply an image-only augmenter, passing labels through (reference:
    image/detection.py DetBorrowAug)."""

    def __init__(self, augmenter):
        super().__init__()
        self.augmenter = augmenter

    def __call__(self, src, label):
        return self.augmenter(src), label


def CreateDetAugmenter(data_shape, resize=0, rand_mirror=False, mean=None,
                       std=None, **kwargs):
    """Reference: image.CreateDetAugmenter (detection augmenter list)."""
    augs = []
    if resize > 0:
        augs.append(DetBorrowAug(ResizeAug(resize)))
    augs.append(DetBorrowAug(ForceResizeAug((data_shape[2], data_shape[1]))))
    if rand_mirror:
        augs.append(DetHorizontalFlipAug(0.5))
    if mean is True:
        mean = nd.array([123.68, 116.28, 103.53])
    if std is True:
        std = nd.array([58.395, 57.12, 57.375])
    if mean is not None:
        augs.append(DetBorrowAug(ColorNormalizeAug(mean, std)))
    return augs


class ImageDetIter:
    """Detection iterator: images + (N, 5) box labels with detection-aware
    augmentation (reference: image/detection.py ImageDetIter). This build
    is array-backed: pass `data` (B, H, W, C) and `label` (B, N, 5)."""

    def __init__(self, batch_size, data_shape, data=None, label=None,
                 aug_list=None, shuffle=False, **kwargs):
        if data is None or label is None:
            raise MXNetError("ImageDetIter on this build is array-backed: "
                             "pass data=(B,H,W,C) and label=(B,N,5) arrays "
                             "(use tools/im2rec.py + gluon.data for .rec)")
        self._data = data if isinstance(data, nd.NDArray) else nd.array(data)
        self._label = label if isinstance(label, nd.NDArray) \
            else nd.array(label)
        self.batch_size = batch_size
        self._aug = aug_list if aug_list is not None else \
            CreateDetAugmenter(data_shape)
        self._shuffle = shuffle
        self._order = None
        self._cursor = 0
        c, h, w = data_shape
        self.provide_data = [("data", (batch_size, c, h, w))]
        self.provide_label = [("label", (batch_size,) +
                               tuple(self._label.shape[1:]))]
        self.reset()

    def reset(self):
        import numpy as _np
        n = self._data.shape[0]
        self._order = _np.random.permutation(n) if self._shuffle \
            else _np.arange(n)
        self._cursor = 0

    def __iter__(self):
        return self

    def __next__(self):
        from .io import DataBatch
        n = self._data.shape[0]
        if self._cursor >= n:
            raise StopIteration
        idx = self._order[self._cursor:self._cursor + self.batch_size]
        self._cursor += self.batch_size
        imgs, labels = [], []
        for i in idx:
            img = self._data[int(i)]
            lab = self._label[int(i)].asnumpy()
            for aug in self._aug:
                img, lab = aug(img, lab) if isinstance(
                    aug, (DetHorizontalFlipAug, DetBorrowAug)) \
                    else (aug(img), lab)
            imgs.append(nd.transpose(img, (2, 0, 1)))
            labels.append(nd.array(lab))
        # Pad the final ragged batch to the advertised fixed batch shape by
        # repeating samples (reference behavior); `pad` records how many are
        # repeats so consumers can mask them. Static shapes keep XLA from
        # recompiling on the last batch.
        pad = max(0, self.batch_size - len(imgs))
        for k in range(pad):
            imgs.append(imgs[k % (self.batch_size - pad)])
            labels.append(labels[k % (self.batch_size - pad)])
        return DataBatch(data=[nd.stack(*imgs, axis=0)],
                         label=[nd.stack(*labels, axis=0)],
                         pad=pad)

    next = __next__
