"""Host-side parameter server for ``dist_async``.

Reference counterpart: src/kvstore/kvstore_dist_server.h (KVStoreDistServer:
``DataHandleEx`` applies the server-side optimizer per push with NO worker
barrier — the reference's distinctive async training mode) over ps-lite's
ZMQ van (3rdparty/ps-lite). TPU-native design keeps the split the same way:
the XLA/ICI collectives own the synchronous in-graph path
(KVStoreDistTPUSync), while THIS module owns asynchronous host-side state.

Wire format: a length-prefixed TYPED binary protocol (like ps-lite's binary
van, NOT pickle — nothing on the wire can execute code):

    frame   := u64 payload_len, payload
    payload := u8 opcode, fields...
    key     := u16 len, utf8 bytes
    tensor  := u8 dtype_flag, u8 ndim, i64*ndim shape, raw LE bytes
    text    := u32 len, utf8 bytes (JSON for optimizer conf / stats)

The server-side optimizer travels as a typed JSON config (registry name +
scalar hyper-parameters), reconstructed through mx.optimizer.create — a
malicious peer can at worst pick a registered optimizer, not run code.

Sharding: with ``launch.py -s N`` (reference ``DMLC_NUM_SERVER``), N server
processes run this module's ``__main__``; every worker connects to all of
them and routes each key by a deterministic hash (crc32 % N), the
reference's key-to-server assignment role. Barriers coordinate on server 0.

Async semantics preserved: each push is applied to the live table the
moment it arrives (stale gradients included); pulls return the newest
weights; no global step barrier exists anywhere on the training path.

Failure detection (reference ps-lite heartbeat, SURVEY §5.3): with
``MXTPU_PS_HEARTBEAT_TIMEOUT`` (or the reference-named
``PS_HEARTBEAT_TIMEOUT``) seconds set, workers beat each server from a
dedicated socket; a worker silent past the timeout is declared dead and
logged, dist_async keeps serving the survivors (async degrade), and
barriers abort with a clean error naming the dead rank instead of
hanging. 0 (the default) disables, matching ps-lite.
"""
from __future__ import annotations

import json
import os
import socket
import struct
import threading
import time
import zlib

import numpy as _np

from ..lint import racecheck as _racecheck

__all__ = ["PSServer", "PSClient", "default_ps_addr", "ps_addrs",
           "key_to_server"]

_HDR = struct.Struct("<Q")

# opcodes (requests)
_OP_INIT, _OP_PUSH, _OP_PULL, _OP_SET_OPT, _OP_STATS, _OP_BARRIER, \
    _OP_SHUTDOWN, _OP_CMD, _OP_CMDLOG = 1, 2, 3, 4, 5, 6, 7, 8, 9
_OP_HEARTBEAT, _OP_HEALTH = 10, 11
_OP_JOIN, _OP_MEMBERSHIP = 12, 13   # elastic membership (ISSUE 8)
_OP_TELEMETRY = 14                  # live telemetry scrape (ISSUE 9)
_OP_CTX = 15                        # span-context wrapper (ISSUE 15):
                                    # i64 trace + i64 span + inner frame
# opcodes (replies)
_OP_OK, _OP_OK_TENSOR, _OP_OK_TEXT, _OP_ERR = 100, 101, 102, 200

#: opcode -> rpc name for the server-side stitching span
_OP_NAMES = {_OP_INIT: "init", _OP_PUSH: "push", _OP_PULL: "pull",
             _OP_SET_OPT: "set_optimizer", _OP_STATS: "stats",
             _OP_BARRIER: "barrier", _OP_SHUTDOWN: "shutdown",
             _OP_CMD: "cmd", _OP_CMDLOG: "cmdlog",
             _OP_HEARTBEAT: "heartbeat", _OP_HEALTH: "health",
             _OP_JOIN: "join", _OP_MEMBERSHIP: "membership",
             _OP_TELEMETRY: "telemetry"}

_DTYPE_FLAGS = {"float32": 0, "float64": 1, "float16": 2, "uint8": 3,
                "int32": 4, "int8": 5, "int64": 6, "bool": 7,
                "bfloat16": 8}   # the headline TPU dtype (ml_dtypes)
_FLAG_DTYPES = {v: k for k, v in _DTYPE_FLAGS.items()}


def _np_dtype(name):
    if name == "bfloat16":
        import ml_dtypes
        return _np.dtype(ml_dtypes.bfloat16)
    return _np.dtype(name)


# -- frame primitives --------------------------------------------------

def _pack_key(key):
    b = str(key).encode()
    return struct.pack("<H", len(b)) + b


def _unpack_key(buf, off):
    (n,) = struct.unpack_from("<H", buf, off)
    off += 2
    return buf[off:off + n].decode(), off + n


def _pack_tensor(arr):
    arr = _np.ascontiguousarray(arr)
    dname = str(arr.dtype)
    if dname not in _DTYPE_FLAGS:
        raise TypeError(f"dtype {dname} not wire-encodable")
    head = struct.pack("<BB", _DTYPE_FLAGS[dname], arr.ndim)
    head += struct.pack(f"<{arr.ndim}q", *arr.shape) if arr.ndim else b""
    return head + arr.tobytes()


def _unpack_tensor(buf, off):
    flag, ndim = struct.unpack_from("<BB", buf, off)
    off += 2
    shape = struct.unpack_from(f"<{ndim}q", buf, off) if ndim else ()
    off += 8 * ndim
    dtype = _np_dtype(_FLAG_DTYPES[flag])
    count = int(_np.prod(shape)) if ndim else 1
    arr = _np.frombuffer(buf, dtype=dtype, count=count,
                         offset=off).reshape(shape)
    return arr, off + count * dtype.itemsize


def _pack_text(s):
    b = s.encode()
    return struct.pack("<I", len(b)) + b


def _unpack_text(buf, off):
    (n,) = struct.unpack_from("<I", buf, off)
    off += 4
    return buf[off:off + n].decode(), off + n


def _send_frame(sock, payload):
    sock.sendall(_HDR.pack(len(payload)) + payload)


def _recv_exact(sock, n):
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf.extend(chunk)
    return bytes(buf)


def _recv_frame(sock):
    (n,) = _HDR.unpack(_recv_exact(sock, _HDR.size))
    return _recv_exact(sock, n)


# -- optimizer conf (typed, code-free) ---------------------------------

def _serialize_optimizer_conf(opt):
    """Registry name + JSON-scalar hyper-parameters. Raises on optimizers
    whose config can't be expressed as data (e.g. a live lr_scheduler
    object) — the reference shipped pickled objects here; we refuse to
    put executable payloads on the wire."""
    from ..base import MXNetError
    conf = {}
    for k, v in vars(opt).items():
        try:
            json.dumps(v)
        except TypeError:
            if k.startswith("_"):
                continue        # runtime state, rebuilt server-side
            raise MXNetError(
                f"dist_async set_optimizer: attribute {k!r} of "
                f"{type(opt).__name__} is not JSON-encodable; the binary "
                "PS protocol ships optimizer CONFIG, not objects. Use "
                "scalar hyper-parameters (schedulers run worker-side).")
        else:
            conf[k] = v
    return json.dumps({"class": type(opt).__name__.lower(), "conf": conf})


def _deserialize_optimizer_conf(blob):
    from .. import optimizer as _opt
    d = json.loads(blob)
    opt = _opt.create(d["class"])
    for k, v in d["conf"].items():
        setattr(opt, k, v)
    return opt


# -- addressing --------------------------------------------------------

def default_ps_addr():
    """Single-server address: MXTPU_PS_ADDR, or the coordinator host with
    a fixed port offset (launch.py exports MXTPU_COORDINATOR)."""
    addr = os.environ.get("MXTPU_PS_ADDR")
    if addr:
        host, port = addr.rsplit(":", 1)
        return host, int(port)
    coord = os.environ.get("MXTPU_COORDINATOR", "127.0.0.1:9876")
    host, port = coord.rsplit(":", 1)
    return host, int(port) + 1000


def ps_addrs():
    """All server addresses: MXTPU_PS_ADDRS="h0:p0,h1:p1,..." (exported by
    launch.py -s N), else the single default address."""
    multi = os.environ.get("MXTPU_PS_ADDRS")
    if multi:
        out = []
        for a in multi.split(","):
            host, port = a.strip().rsplit(":", 1)
            out.append((host, int(port)))
        return out
    return [default_ps_addr()]


def key_to_server(key, num_servers):
    """Deterministic key -> server assignment (the ps-lite key-range
    role). crc32, NOT hash(): PYTHONHASHSEED must not move keys."""
    return zlib.crc32(str(key).encode()) % num_servers


def heartbeat_timeout():
    """Configured failure-detection timeout in seconds; 0 = disabled.
    One reader for the env pair so server, client, and kvstore can never
    disagree about whether detection is on."""
    return float(os.environ.get("MXTPU_PS_HEARTBEAT_TIMEOUT",
                                os.environ.get("PS_HEARTBEAT_TIMEOUT", "0"))
                 or 0)


_ENV_HB_TIMEOUT = heartbeat_timeout   # PSServer.__init__'s kwarg shadows it


class PSServer:
    """The server role. Runs as a daemon thread pool inside worker 0's
    process (default single-server mode) or as a standalone process
    (``python -m mxnet_tpu.kvstore.ps_server`` under launch.py -s N)."""

    def __init__(self, host, port, num_workers, heartbeat_timeout=None):
        self._lock = _racecheck.make_lock("PSServer._lock")
        # key -> np.ndarray (the live weights); racecheck-registered:
        # under MXTPU_RACECHECK=1 any access off self._lock is a finding
        self._table = _racecheck.guard({}, self._lock, "PSServer._table")
        self._updater = None      # server-side optimizer (set_optimizer;
                                  # per-key state lives in _ServerUpdater)
        self._push_count = {}     # key -> applied pushes (incl. stale)
        from collections import deque
        self._commands = deque(maxlen=64)   # recent controller messages,
                                            # readable via _OP_CMDLOG
        self._num_workers = num_workers
        self._barrier_gen = 0
        self._barrier_count = 0
        self._barrier_cv = _racecheck.make_condition("PSServer._barrier_cv")
        # failure detection (reference ps-lite heartbeat: workers beat,
        # PS_HEARTBEAT_TIMEOUT seconds of silence marks a node dead).
        # 0 disables, like ps-lite's default.
        self._hb_timeout = heartbeat_timeout if heartbeat_timeout \
            is not None else _ENV_HB_TIMEOUT()
        self._now = time.time     # injectable clock: the fault-harness
                                  # tests drive death detection with a
                                  # FakeClock instead of real sleeps
        self._last_seen = {}      # rank -> last heartbeat time
        self._dead = {}           # rank -> time declared dead
        self._membership = None   # elastic.Membership once attached
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(64)
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               daemon=True)
        self._accept_thread.start()
        if self._hb_timeout > 0:
            threading.Thread(target=self._monitor_loop, daemon=True).start()

    def _monitor_loop(self):
        """Declare workers dead after heartbeat silence (the ps-lite
        Heartbeat/UpdateHeartbeat role). dist_async keeps serving the
        survivors — async tolerates stragglers and deaths by design —
        but barrier waiters are woken so they can abort with a clean
        error instead of hanging forever on a rank that will never
        arrive."""
        tick = max(0.2, self._hb_timeout / 4.0)
        while self._sock.fileno() != -1:   # dies with the listen socket
            time.sleep(tick)
            self._scan_dead()

    def _scan_dead(self, now=None):
        """ONE death-detection pass: declare every rank silent past the
        timeout dead, log it, and wake barrier waiters.  Factored out of
        the monitor loop so the fault-injection tests can drive it
        deterministically (``now`` from a FakeClock) — no wall-clock
        sleeps.  Returns the ranks newly declared dead."""
        if now is None:
            now = self._now()
        newly_dead = []
        with self._lock:
            for rank, seen in self._last_seen.items():
                if rank not in self._dead and \
                        now - seen > self._hb_timeout:
                    self._dead[rank] = now
                    newly_dead.append((rank, now - seen))
        for rank, age in newly_dead:
            print(f"[ps_server] worker rank {rank} declared DEAD: "
                  f"no heartbeat for {age:.1f}s "
                  f"(timeout {self._hb_timeout:.1f}s); dist_async "
                  f"continues with the remaining workers", flush=True)
        if self._membership is not None:
            # close the elastic loop: a detected death is a committed
            # membership transition (epoch bump) the controller reshards
            # on at its next step boundary.  Outside self._lock — the
            # membership fans out to subscriber callbacks.
            for rank, _ in newly_dead:
                self._membership.worker_dead(rank)
            self._membership.poll()     # expire an overdue rendezvous
        if newly_dead:
            with self._barrier_cv:
                self._barrier_cv.notify_all()
        return [rank for rank, _ in newly_dead]

    def dead_workers(self):
        with self._lock:
            return sorted(self._dead)

    def attach_membership(self, membership):
        """Wire an :class:`~mxnet_tpu.elastic.Membership` into the
        heartbeat path: deaths detected by :meth:`_scan_dead` commit
        membership transitions, and the ``_OP_JOIN`` /
        ``_OP_MEMBERSHIP`` RPCs become live (a join announce with a
        stale epoch is rejected with a clean error instead of being
        silently readmitted).  Returns the server for chaining."""
        self._membership = membership
        return self

    def _accept_loop(self):
        while True:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn):
        try:
            while True:
                frame = _recv_frame(conn)
                try:
                    done = self._handle(conn, frame)
                except (ConnectionError, OSError):
                    raise
                except Exception as e:  # noqa: BLE001 — reply, don't die
                    # e.g. KeyError on push/pull of an uninitialized key:
                    # the worker gets a diagnosable PS error instead of a
                    # dead connection
                    _send_frame(conn, bytes([_OP_ERR]) + _pack_text(
                        f"{type(e).__name__}: {e}"))
                    done = False
                if done:
                    return
        except (ConnectionError, EOFError, OSError):
            pass
        finally:
            conn.close()

    def _handle(self, conn, frame):
        """Serve one frame; returns True when the server should stop."""
        op = frame[0]
        off = 1
        if op == _OP_CTX:
            # cross-worker trace stitching (ISSUE 15): the client
            # prefixed its ambient span ids, so this RPC's server-side
            # handling gets a span that DISCLOSES the remote parent
            # (span ids are per-process; the fleet chrome_trace
            # correlates via these args — it never renames ids).
            rtrace, rspan = struct.unpack_from("<qq", frame, off)
            inner = frame[off + 16:]
            from ..telemetry import tracing as _tracing
            sp = _tracing.start(
                f"ps.rpc.{_OP_NAMES.get(inner[0], inner[0])}",
                remote_trace=int(rtrace), remote_span=int(rspan))
            try:
                return self._handle(conn, inner)
            finally:
                _tracing.finish(sp)
        if op == _OP_INIT:
            key, off = _unpack_key(frame, off)
            value, _ = _unpack_tensor(frame, off)
            with self._lock:
                # reference InitImpl: first init wins (worker 0 inits
                # first under launch.py ordering)
                if key not in self._table:
                    self._table[key] = _np.array(value)
            _send_frame(conn, bytes([_OP_OK]))
        elif op == _OP_PUSH:
            key, off = _unpack_key(frame, off)
            grad, _ = _unpack_tensor(frame, off)
            with self._lock:
                w = self._table[key]
                if self._updater is not None:
                    # DataHandleEx: apply optimizer NOW — no waiting for
                    # other workers (async mode)
                    self._updater(key, grad, w)
                else:
                    w += grad
                self._push_count[key] = self._push_count.get(key, 0) + 1
            _send_frame(conn, bytes([_OP_OK]))
        elif op == _OP_PULL:
            key, off = _unpack_key(frame, off)
            with self._lock:
                value = self._table[key].copy()
            _send_frame(conn, bytes([_OP_OK_TENSOR]) + _pack_tensor(value))
        elif op == _OP_SET_OPT:
            conf, _ = _unpack_text(frame, off)
            updater = _ServerUpdater(_deserialize_optimizer_conf(conf))
            with self._lock:
                self._updater = updater
            _send_frame(conn, bytes([_OP_OK]))
        elif op == _OP_STATS:
            with self._lock:
                stats = json.dumps(self._push_count)
            _send_frame(conn, bytes([_OP_OK_TEXT]) + _pack_text(stats))
        elif op == _OP_BARRIER:
            # a declared-dead worker can never arrive: abort with a clean
            # error naming the rank instead of hanging the survivors
            # (reference ps-lite Barrier simply hangs; SURVEY §5.3 asks
            # for the detected-failure upgrade)
            dead = self.dead_workers()
            if dead:
                _send_frame(conn, bytes([_OP_ERR]) + _pack_text(
                    f"barrier aborted: worker rank(s) {dead} declared "
                    f"dead (no heartbeat within {self._hb_timeout:.1f}s); "
                    f"a {self._num_workers}-worker barrier cannot "
                    f"complete"))
                return False
            aborted = None
            with self._barrier_cv:
                gen = self._barrier_gen
                self._barrier_count += 1
                if self._barrier_count >= self._num_workers:
                    self._barrier_count = 0
                    self._barrier_gen += 1
                    self._barrier_cv.notify_all()
                else:
                    while self._barrier_gen == gen:
                        dead = self.dead_workers()
                        if dead:
                            self._barrier_count = max(
                                0, self._barrier_count - 1)
                            aborted = dead
                            break
                        self._barrier_cv.wait(timeout=5)
            if aborted is not None:
                _send_frame(conn, bytes([_OP_ERR]) + _pack_text(
                    f"barrier aborted: worker rank(s) {aborted} declared "
                    f"dead (no heartbeat within {self._hb_timeout:.1f}s); "
                    f"a {self._num_workers}-worker barrier cannot "
                    f"complete"))
            else:
                _send_frame(conn, bytes([_OP_OK]))
        elif op == _OP_CMD:
            # reference send_command_to_servers(head, body): ps-lite
            # kController messages. Typed here: head int + body text.
            # Built-in head 0 + "lr:<x>" retunes the server optimizer
            # (the reference's canonical mid-training use); the last 64
            # commands are readable via PSClient.command_log().
            (head,) = struct.unpack_from("<i", frame, off)
            body, _ = _unpack_text(frame, off + 4)
            with self._lock:
                self._commands.append((head, body))
                if head == 0 and body.startswith("lr:") and \
                        self._updater is not None:
                    self._updater.set_learning_rate(float(body[3:]))
            _send_frame(conn, bytes([_OP_OK]))
        elif op == _OP_CMDLOG:
            with self._lock:
                log = json.dumps(list(self._commands))
            _send_frame(conn, bytes([_OP_OK_TEXT]) + _pack_text(log))
        elif op == _OP_HEARTBEAT:
            (rank,) = struct.unpack_from("<i", frame, off)
            rejoined = False
            with self._lock:
                self._last_seen[rank] = self._now()
                if rank in self._dead:
                    # a beat from a "dead" rank: it was only slow (or the
                    # launcher restarted it) — async mode simply resumes
                    # applying its pushes
                    del self._dead[rank]
                    rejoined = True
            if rejoined:
                # log OUTSIDE the table lock (HB16): console I/O can
                # block on a slow/full pipe, and every serve thread's
                # push/pull would stall behind it
                print(f"[ps_server] worker rank {rank} heartbeat "
                      f"resumed; marking alive again", flush=True)
            _send_frame(conn, bytes([_OP_OK]))
        elif op == _OP_HEALTH:
            now = self._now()
            with self._lock:
                health = {"alive": {str(r): round(now - t, 2)
                                    for r, t in self._last_seen.items()
                                    if r not in self._dead},
                          "dead": sorted(self._dead),
                          "heartbeat_timeout": self._hb_timeout,
                          "num_workers": self._num_workers}
            _send_frame(conn, bytes([_OP_OK_TEXT]) + _pack_text(
                json.dumps(health)))
        elif op == _OP_JOIN:
            # elastic join/announce (ISSUE 8): the worker presents the
            # newest membership epoch it knows.  Stale epoch -> typed
            # rejection (the _serve loop turns the raise into _OP_ERR);
            # accepted -> the candidate parks in rendezvous until the
            # controller transfers state and confirms.  Also counts as
            # a heartbeat — an announced joiner is by definition alive.
            (rank,) = struct.unpack_from("<i", frame, off)
            (epoch,) = struct.unpack_from("<q", frame, off + 4)
            if self._membership is None:
                _send_frame(conn, bytes([_OP_ERR]) + _pack_text(
                    "no membership attached: this server does not run "
                    "elastic membership (attach_membership)"))
                return False
            deadline = self._membership.announce_join(rank, epoch)
            with self._lock:
                self._last_seen[rank] = self._now()
                self._dead.pop(rank, None)
            view = self._membership.view()
            view["rendezvous_deadline"] = deadline
            _send_frame(conn, bytes([_OP_OK_TEXT]) + _pack_text(
                json.dumps(view)))
        elif op == _OP_MEMBERSHIP:
            if self._membership is None:
                view = {"epoch": None, "ranks": [], "state": None,
                        "pending": None}
            else:
                self._membership.poll()
                view = self._membership.view()
            _send_frame(conn, bytes([_OP_OK_TEXT]) + _pack_text(
                json.dumps(view)))
        elif op == _OP_TELEMETRY:
            # live scrape of THIS process's telemetry (ISSUE 9): the PS
            # RPC loop is the one long-lived listener every training/
            # serving job already runs, so it doubles as the scrape
            # endpoint — no extra port, no extra thread.  fmt byte:
            # 0 = JSON snapshot, 1 = Prometheus text (wrapped in JSON so
            # the typed reply framing stays uniform).  fmt 2 = the
            # fleet scrape payload (ISSUE 15): snapshot + this rank's
            # finished-span ring, what FleetCollector stitches.
            from .. import telemetry as _telemetry
            fmt = frame[off] if len(frame) > off else 0
            snap = _telemetry.snapshot()
            if fmt == 1:
                payload = {"format": "prom",
                           "text": _telemetry.prom_text(snap)}
            elif fmt == 2:
                from ..telemetry import tracing as _tracing
                payload = {"snapshot": snap,
                           "spans": _tracing.spans(),
                           "dropped_spans": _tracing.dropped()}
            else:
                payload = snap
            _send_frame(conn, bytes([_OP_OK_TEXT]) + _pack_text(
                json.dumps(payload)))
        elif op == _OP_SHUTDOWN:
            _send_frame(conn, bytes([_OP_OK]))
            self._sock.close()
            return True
        else:
            _send_frame(conn, bytes([_OP_ERR]) + _pack_text(
                f"unknown opcode {op}"))
        return False


class _ServerUpdater:
    """Server-side optimizer application (reference ``set_optimizer`` →
    server Updater): numpy in, numpy out, state kept per key."""

    def __init__(self, optimizer):
        self._optimizer = optimizer
        self._states = {}

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    def __call__(self, key, grad, weight):
        from ..ndarray.ndarray import array
        w = array(weight)
        g = array(_np.asarray(grad))
        if key not in self._states:
            self._states[key] = self._optimizer.create_state(key, w)
        self._optimizer.update(key, w, g, self._states[key])
        weight[...] = _np.asarray(w.asnumpy())


class PSClient:
    """Worker-side connection to ONE server (the ps::KVWorker role; the
    kvstore owns one client per server and routes by key_to_server)."""

    #: class-level default so a half-built client (tests construct via
    #: ``__new__``) still answers the closed check
    _closed = False

    def __init__(self, host, port, retries=60, policy=None):
        from .rpc import RetryPolicy, PeerUnreachable, report_failure
        self._policy = policy if policy is not None \
            else RetryPolicy.from_env()
        self._addr = (host, port)
        self._lock = _racecheck.make_lock("PSClient._lock")
        self._hb_stop = None      # threading.Event while beating
        self._closed = False
        last = None
        for _ in range(retries):
            try:
                self._connect(self._policy.timeout_s or 120)
                break
            except OSError as e:     # server thread may start a bit later
                last = e
                time.sleep(0.25)
        else:
            err = PeerUnreachable(
                f"cannot reach PS at {host}:{port}: {last}",
                peer=f"{host}:{port}", op="connect", attempts=retries)
            report_failure(err)
            raise err

    def _connect(self, timeout_s):
        """(Re)open the RPC socket.  The connect deadline must NOT
        become a standing RPC timeout: async workers legitimately block
        in barrier()/pull() for as long as the slowest worker takes
        (reference ps-lite blocks indefinitely) — per-call deadlines are
        applied around each exchange in :meth:`_rpc` instead.  The
        blocking connect runs OUTSIDE the client lock (a slow peer must
        not stall other threads); only the socket swap itself is locked
        — the socket IS the locked RPC channel, and a reconnect racing
        another thread's in-flight exchange would otherwise swap it out
        from under a half-read frame."""
        if self._closed:
            # close() is lock-free so it can interrupt a blocked
            # exchange; a retry racing it must NOT resurrect the socket
            # (the owner believes the client is closed — a reconnect
            # here would leak a live fd nobody will ever close)
            from .rpc import PeerUnreachable
            raise PeerUnreachable(
                "PSClient to %s:%s is closed" % self._addr,
                peer="%s:%s" % self._addr, op="connect")
        new = socket.create_connection(self._addr,
                                       timeout=timeout_s or 120)
        new.settimeout(None)
        with self._lock:
            old = getattr(self, "_sock", None)
            self._sock = new
        if old is not None:
            try:
                old.close()
            except OSError:
                pass

    def _rpc(self, payload, blocking=False, idempotent=False):
        op_name = _OP_NAMES.get(payload[0], f"op{payload[0]}")
        if self._closed:
            from .rpc import PeerUnreachable
            raise PeerUnreachable(
                "PSClient to %s:%s is closed" % self._addr,
                peer="%s:%s" % self._addr, op=op_name)
        # cross-worker trace stitching (ISSUE 15): when this thread has
        # an ambient span, prefix its (trace, span) ids so the server's
        # handling span discloses the remote parent — a push/pushpull/
        # join then correlates with the issuing side in the stitched
        # fleet timeline
        from ..telemetry import tracing as _tracing
        sp = _tracing.current()
        if sp is not None and sp.span is not None:
            payload = bytes([_OP_CTX]) + struct.pack(
                "<qq", int(sp.trace), int(sp.span)) + payload

        def _attempt(timeout_s):
            # the lock IS the RPC channel: one request/response pair in
            # flight per socket, so the wire round necessarily happens
            # with it held — callers that must not stall (heartbeats)
            # use their own socket (start_heartbeat), exactly because of
            # this
            with self._lock:
                try:
                    self._sock.settimeout(None if blocking else timeout_s)
                    _send_frame(self._sock, payload)  # mxlint: disable=HB16 -- the lock serializes this socket; see above
                    return _recv_frame(self._sock)
                finally:
                    try:
                        self._sock.settimeout(None)
                    except OSError:
                        pass

        if blocking:
            # barrier() blocks for as long as the slowest worker takes
            # (reference ps-lite semantics) and is NOT idempotent — a
            # resent arrival would double-count at the server — so it
            # runs single-attempt with no deadline; a dead peer there is
            # the heartbeat detector's job (barriers abort typed on a
            # declared-dead rank).
            from .rpc import classify as _classify
            try:
                resp = _attempt(None)
            except (ConnectionError, EOFError, OSError) as e:
                raise _classify(e, peer="%s:%s" % self._addr,
                                op=op_name, attempts=1) from e
        else:
            # the retry budget is reserved for ops the server can
            # safely see TWICE (reads, heartbeats).  Mutating ops
            # (push is `w += grad` / an optimizer apply, cmd appends
            # to the command log, join announces) share barrier's
            # double-apply hazard: a reply lost AFTER the server
            # processed the request would make a blind resend apply it
            # again — so they run one typed, deadline-bounded attempt
            # and leave recovery to the caller, who knows whether the
            # op landed (e.g. via pull/stats).
            policy = self._policy if idempotent else self._policy.once()
            resp = policy.run(
                _attempt, peer="%s:%s" % self._addr, op=op_name,
                reconnect=self._connect)
        op = resp[0]
        if op == _OP_OK:
            return None
        if op == _OP_OK_TENSOR:
            arr, _ = _unpack_tensor(resp, 1)
            return arr
        if op == _OP_OK_TEXT:
            text, _ = _unpack_text(resp, 1)
            return json.loads(text)
        text, _ = _unpack_text(resp, 1)
        from ..base import MXNetError
        raise MXNetError(f"PS error: {text}")

    def init(self, key, value):
        return self._rpc(bytes([_OP_INIT]) + _pack_key(key)
                         + _pack_tensor(_np.asarray(value)))

    def push(self, key, grad):
        return self._rpc(bytes([_OP_PUSH]) + _pack_key(key)
                         + _pack_tensor(_np.asarray(grad)))

    def pull(self, key):
        return self._rpc(bytes([_OP_PULL]) + _pack_key(key),
                         idempotent=True)

    def set_optimizer(self, optimizer):
        return self._rpc(bytes([_OP_SET_OPT]) + _pack_text(
            _serialize_optimizer_conf(optimizer)))

    def stats(self):
        return self._rpc(bytes([_OP_STATS]), idempotent=True)

    def send_command(self, head, body):
        return self._rpc(bytes([_OP_CMD]) + struct.pack("<i", int(head))
                         + _pack_text(str(body)))

    def command_log(self):
        """Recent (head, body) controller messages this server received."""
        return self._rpc(bytes([_OP_CMDLOG]), idempotent=True)

    def barrier(self):
        return self._rpc(bytes([_OP_BARRIER]), blocking=True)

    def join(self, rank, epoch):
        """Announce this worker as a joiner carrying the newest
        membership ``epoch`` it knows (elastic membership, ISSUE 8).
        Returns the membership view (incl. the rendezvous deadline);
        raises the server's typed rejection when the epoch is stale —
        the worker must resync through the controller, not rejoin the
        ring directly."""
        return self._rpc(bytes([_OP_JOIN]) + struct.pack("<i", int(rank))
                         + struct.pack("<q", int(epoch)))

    def membership(self):
        """The server's membership view: {epoch, ranks, state, pending}
        (epoch None when the server runs without elastic membership)."""
        return self._rpc(bytes([_OP_MEMBERSHIP]), idempotent=True)

    def health(self):
        """Server's liveness view: {alive: {rank: age_s}, dead: [ranks],
        heartbeat_timeout, num_workers}."""
        return self._rpc(bytes([_OP_HEALTH]), idempotent=True)

    def telemetry(self, fmt="json"):
        """Scrape the server process's ``mx.telemetry`` state (ISSUE 9):
        ``fmt="json"`` returns the snapshot dict, ``fmt="prom"`` a
        ``{"format": "prom", "text": ...}`` wrapper holding the
        Prometheus text exposition — what ``tools/telemetry_dump.py``
        prints for a scraper.  ``fmt="fleet"`` (ISSUE 15) returns
        ``{"snapshot", "spans", "dropped_spans"}`` — the payload
        ``telemetry.fleet.FleetCollector`` merges and stitches."""
        code = {"prom": 1, "fleet": 2}.get(fmt, 0)
        return self._rpc(bytes([_OP_TELEMETRY, code]), idempotent=True)

    def beat_once(self, rank):
        """Send ONE heartbeat for ``rank`` synchronously over the RPC
        socket (deterministic tests; the production path is the
        :meth:`start_heartbeat` thread).  Honors the
        ``ps.heartbeat.drop`` fault point — an armed drop simulates a
        silent worker without killing anything.  Returns False when the
        beat was dropped, or when the transport failed transiently — a
        missed beat is the heartbeat DETECTOR's job to judge, not a
        reason to crash the worker (ISSUE 19), so typed transport errors
        are swallowed and counted (``rpc.heartbeat.dropped``)."""
        from ..testing import faults as _faults
        from .rpc import RPCError
        if _faults.fault_point("ps.heartbeat.drop", rank) == "drop":
            return False
        try:
            # a repeated beat only refreshes last-seen: idempotent,
            # safe to retry
            self._rpc(bytes([_OP_HEARTBEAT]) + struct.pack("<i",
                                                           int(rank)),
                      idempotent=True)
        except RPCError:
            from .. import telemetry as _telemetry
            _telemetry.inc("rpc.heartbeat.dropped")
            return False
        return True

    def start_heartbeat(self, rank, interval=None):
        """Beat this worker's rank to the server from a daemon thread.

        Uses its OWN socket: the RPC socket can legitimately block for
        minutes inside barrier()/pull() under self._lock, and a heartbeat
        that queues behind a blocked barrier would read as death — the
        exact false positive ps-lite's separate heartbeat path avoids.
        No-op if already beating."""
        if self._hb_stop is not None:
            return
        if interval is None:
            interval = float(
                os.environ.get("MXTPU_PS_HEARTBEAT_INTERVAL", "0") or 0)
        if interval <= 0:
            timeout = heartbeat_timeout()
            interval = max(0.1, timeout / 3.0) if timeout > 0 else 1.0
        stop = threading.Event()
        self._hb_stop = stop
        payload = bytes([_OP_HEARTBEAT]) + struct.pack("<i", int(rank))

        def _beat():
            from ..testing import faults as _faults
            sock = None
            while not stop.is_set():
                try:
                    if _faults.fault_point("ps.heartbeat.drop",
                                           rank) == "drop":
                        stop.wait(interval)    # silent worker simulation
                        continue
                    if sock is None:
                        sock = socket.create_connection(self._addr,
                                                        timeout=30)
                    _send_frame(sock, payload)
                    _recv_frame(sock)
                except OSError:
                    # server gone or restarting: retry next tick (worker
                    # liveness is the launcher's job, not ours)
                    try:
                        if sock is not None:
                            sock.close()
                    except OSError:
                        pass
                    sock = None
                stop.wait(interval)
            if sock is not None:
                try:
                    sock.close()
                except OSError:
                    pass

        threading.Thread(target=_beat, daemon=True).start()

    def close(self):
        # the flag first: _connect/_rpc check it, so a concurrent retry
        # observing the dying socket fails typed (PeerUnreachable)
        # instead of reconnecting a client the owner believes is closed
        self._closed = True
        if self._hb_stop is not None:
            self._hb_stop.set()
            self._hb_stop = None
        try:
            # deliberately lock-free: close() must be able to interrupt
            # an exchange blocked under the lock (barrier can block for
            # minutes); closing the fd wakes the blocked recv with a
            # typed error instead of deadlocking behind it
            self._sock.close()  # mxlint: disable=HB14 -- out-of-band interrupt; see above
        except OSError:
            pass


def _server_main():
    """Standalone server role: ``python -m mxnet_tpu.kvstore.ps_server``
    (spawned by launch.py -s N with MXTPU_SERVER_ID / MXTPU_PS_ADDRS /
    MXTPU_NUM_PROCESSES in env). Serves until killed by the launcher."""
    sid = int(os.environ.get("MXTPU_SERVER_ID", "0"))
    addrs = ps_addrs()
    host, port = addrs[sid]
    num_workers = int(os.environ.get("MXTPU_NUM_PROCESSES", "1"))
    PSServer("0.0.0.0", port, num_workers)
    print(f"[ps_server {sid}] serving on {host}:{port} "
          f"({num_workers} workers)", flush=True)
    while True:
        time.sleep(3600)


if __name__ == "__main__":
    _server_main()
