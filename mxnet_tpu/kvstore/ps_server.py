"""Host-side parameter server for ``dist_async``.

Reference counterpart: src/kvstore/kvstore_dist_server.h (KVStoreDistServer:
``DataHandleEx`` applies the server-side optimizer per push with NO worker
barrier — the reference's distinctive async training mode) over ps-lite's
ZMQ van (3rdparty/ps-lite). TPU-native design keeps the split the same way:
the XLA/ICI collectives own the synchronous in-graph path
(KVStoreDistTPUSync), while THIS module owns asynchronous host-side state —
a TCP server thread on worker 0's host (DCN), length-prefixed pickle frames
standing in for ZMQ messages.

Async semantics preserved: each push is applied to the live table the
moment it arrives (stale gradients included); pulls return the newest
weights; no global step barrier exists anywhere on the training path.
"""
from __future__ import annotations

import os
import pickle
import socket
import struct
import threading
import time

import numpy as _np

__all__ = ["PSServer", "PSClient", "default_ps_addr"]

_HDR = struct.Struct("<Q")


def _send_msg(sock, obj):
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(_HDR.pack(len(payload)) + payload)


def _recv_exact(sock, n):
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf.extend(chunk)
    return bytes(buf)


def _recv_msg(sock):
    (n,) = _HDR.unpack(_recv_exact(sock, _HDR.size))
    return pickle.loads(_recv_exact(sock, n))


def default_ps_addr():
    """Server address: MXTPU_PS_ADDR, or the coordinator host with a fixed
    port offset (launch.py exports MXTPU_COORDINATOR for every role)."""
    addr = os.environ.get("MXTPU_PS_ADDR")
    if addr:
        host, port = addr.rsplit(":", 1)
        return host, int(port)
    coord = os.environ.get("MXTPU_COORDINATOR", "127.0.0.1:9876")
    host, port = coord.rsplit(":", 1)
    return host, int(port) + 1000


class PSServer:
    """The server role. One instance runs (as a daemon thread pool) inside
    worker 0's process — matching the reference's default of co-locating
    servers with workers under ``launch.py -n N -s N`` on one host."""

    def __init__(self, host, port, num_workers):
        self._table = {}          # key -> np.ndarray (the live weights)
        self._updater = None      # server-side optimizer (set_optimizer;
                                  # per-key state lives in _ServerUpdater)
        self._push_count = {}     # key -> applied pushes (incl. stale)
        self._lock = threading.Lock()
        self._num_workers = num_workers
        self._barrier_gen = 0
        self._barrier_count = 0
        self._barrier_cv = threading.Condition()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(64)
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               daemon=True)
        self._accept_thread.start()

    def _accept_loop(self):
        while True:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn):
        try:
            while True:
                msg = _recv_msg(conn)
                try:
                    done = self._handle(conn, msg)
                except (ConnectionError, OSError):
                    raise
                except Exception as e:  # noqa: BLE001 — reply, don't die
                    # e.g. KeyError on push/pull of an uninitialized key:
                    # the worker gets a diagnosable PS error instead of a
                    # dead connection
                    _send_msg(conn, ("err", f"{type(e).__name__}: {e}"))
                    done = False
                if done:
                    return
        except (ConnectionError, EOFError, OSError):
            pass
        finally:
            conn.close()

    def _handle(self, conn, msg):
        """Serve one message; returns True when the server should stop.
        Key lookups may raise (KeyError on an uninitialized key) — the
        caller converts that to an ("err", ...) reply."""
        op = msg[0]
        if op == "init":
            _, key, value = msg
            with self._lock:
                # reference InitImpl: first init wins (worker 0 inits
                # first under launch.py ordering)
                if key not in self._table:
                    self._table[key] = _np.array(value)
            _send_msg(conn, ("ok",))
        elif op == "push":
            _, key, grad = msg
            with self._lock:
                w = self._table[key]
                if self._updater is not None:
                    # DataHandleEx: apply optimizer NOW — no waiting for
                    # other workers (async mode)
                    self._updater(key, grad, w)
                else:
                    w += grad
                self._push_count[key] = self._push_count.get(key, 0) + 1
            _send_msg(conn, ("ok",))
        elif op == "pull":
            _, key = msg
            with self._lock:
                value = self._table[key].copy()
            _send_msg(conn, ("ok", value))
        elif op == "set_optimizer":
            _, blob = msg
            optimizer = pickle.loads(blob)
            with self._lock:
                self._updater = _ServerUpdater(optimizer)
            _send_msg(conn, ("ok",))
        elif op == "stats":
            with self._lock:
                _send_msg(conn, ("ok", dict(self._push_count)))
        elif op == "barrier":
            with self._barrier_cv:
                gen = self._barrier_gen
                self._barrier_count += 1
                if self._barrier_count >= self._num_workers:
                    self._barrier_count = 0
                    self._barrier_gen += 1
                    self._barrier_cv.notify_all()
                else:
                    while self._barrier_gen == gen:
                        self._barrier_cv.wait(timeout=60)
            _send_msg(conn, ("ok",))
        elif op == "shutdown":
            _send_msg(conn, ("ok",))
            self._sock.close()
            return True
        else:
            _send_msg(conn, ("err", f"unknown op {op!r}"))
        return False


class _ServerUpdater:
    """Server-side optimizer application (reference ``set_optimizer`` →
    server Updater): numpy in, numpy out, state kept per key."""

    def __init__(self, optimizer):
        self._optimizer = optimizer
        self._states = {}

    def __call__(self, key, grad, weight):
        from ..ndarray.ndarray import NDArray, array
        w = array(weight)
        g = array(_np.asarray(grad))
        if key not in self._states:
            self._states[key] = self._optimizer.create_state(key, w)
        self._optimizer.update(key, w, g, self._states[key])
        weight[...] = _np.asarray(w.asnumpy())


class PSClient:
    """Worker-side connection (the ps::KVWorker role)."""

    def __init__(self, host, port, retries=60):
        last = None
        for _ in range(retries):
            try:
                self._sock = socket.create_connection((host, port),
                                                      timeout=120)
                # connect timeout must NOT become the RPC timeout: async
                # workers legitimately block in barrier()/pull() for as
                # long as the slowest worker takes (reference ps-lite
                # blocks indefinitely; liveness is the launcher's job)
                self._sock.settimeout(None)
                break
            except OSError as e:     # server thread may start a bit later
                last = e
                time.sleep(0.25)
        else:
            raise ConnectionError(f"cannot reach PS at {host}:{port}: "
                                  f"{last}")
        self._lock = threading.Lock()

    def _rpc(self, *msg):
        with self._lock:
            _send_msg(self._sock, msg)
            resp = _recv_msg(self._sock)
        if resp[0] != "ok":
            raise RuntimeError(f"PS error: {resp[1:]}" )
        return resp[1] if len(resp) > 1 else None

    def init(self, key, value):
        return self._rpc("init", key, _np.asarray(value))

    def push(self, key, grad):
        return self._rpc("push", key, _np.asarray(grad))

    def pull(self, key):
        return self._rpc("pull", key)

    def set_optimizer(self, optimizer):
        return self._rpc("set_optimizer",
                         pickle.dumps(optimizer,
                                      protocol=pickle.HIGHEST_PROTOCOL))

    def stats(self):
        return self._rpc("stats")

    def barrier(self):
        return self._rpc("barrier")

    def close(self):
        try:
            self._sock.close()
        except OSError:
            pass
