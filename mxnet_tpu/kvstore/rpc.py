"""Deadline-bounded retry policy + typed errors for socket transports.

Until ISSUE 19 every socket path in the repo (``PSClient._rpc``, the
``FleetCollector`` scrape transport) either blocked forever on a silent
peer or surfaced raw ``ConnectionRefusedError``/``socket.timeout`` to
callers.  That was survivable while "distributed" meant threads in one
process; against real processes a hung RPC wedges the whole worker and a
raw ``OSError`` loses the peer/op context the heartbeat-death and
scrape-dead rules need.

This module is the ONE retry/deadline policy those transports share:

- typed errors: :class:`RPCTimeout` (deadline elapsed mid-call) and
  :class:`PeerUnreachable` (connect refused / peer reset), both carrying
  ``peer`` and ``op`` so the existing rules can name the offender.  Both
  subclass :class:`RPCError` which subclasses :class:`ConnectionError`,
  so every pre-existing ``except (ConnectionError, OSError)`` transport
  guard keeps working unchanged.
- bounded exponential backoff with deterministic seeded jitter, clocks
  injectable (``now``/``sleep``) so tier-1 gates the whole policy under
  FakeClock with zero real sleeps.
- telemetry: every retry increments ``rpc.retries`` (and
  ``rpc.retries.<op>``); timeouts/refusals count under ``rpc.timeouts``
  / ``rpc.unreachable``; the FINAL failure fires a flight dump
  (``reason="rpc_failure:<op>"``) so a dead peer leaves evidence.

Env knobs (read per-policy at construction, see ``RetryPolicy.from_env``):

- ``MXTPU_RPC_TIMEOUT_S`` — per-attempt connect/read deadline
  (default 5.0; 0 disables the deadline: block forever, pre-19 behavior).
- ``MXTPU_RPC_RETRIES`` — attempts AFTER the first (default 2).
  ``0`` is the kill switch: single attempt, no backoff — exactly the
  pre-19 single-shot behavior, but still typed.  The budget applies to
  IDEMPOTENT ops only (reads, heartbeats): mutating ops (push/init/
  cmd/...) always run single-attempt via :meth:`RetryPolicy.once`,
  because a resend after a lost reply could double-apply server-side.
- ``MXTPU_RPC_BACKOFF_S`` / ``MXTPU_RPC_BACKOFF_MAX_S`` — initial and
  cap of the exponential backoff (defaults 0.05 / 2.0).
- ``MXTPU_RPC_DEADLINE_S`` — optional TOTAL deadline across all
  attempts+backoffs; elapsed ⇒ :class:`RPCTimeout` even with retry
  budget left (default: unbounded; the per-attempt timeout still binds).
"""
from __future__ import annotations

import os
import random
import socket
import time


class RPCError(ConnectionError):
    """Base of the typed transport errors; carries peer + op name."""

    def __init__(self, message, peer=None, op=None, attempts=None):
        super().__init__(message)
        self.peer = peer
        self.op = op
        self.attempts = attempts


class RPCTimeout(RPCError):
    """The per-attempt or total deadline elapsed before a reply."""


class PeerUnreachable(RPCError):
    """Connect refused, peer reset, or the socket died mid-exchange."""


#: raw exception types each typed error wraps.  ``socket.timeout`` is an
#: alias of ``TimeoutError`` on py3.10+ but kept explicit for intent.
_TIMEOUT_EXCS = (socket.timeout, TimeoutError)
_UNREACHABLE_EXCS = (ConnectionError, EOFError, OSError)


def classify(exc, peer=None, op=None, attempts=None):
    """Wrap a raw transport exception into the matching typed error."""
    if isinstance(exc, RPCError):
        return exc
    cls = RPCTimeout if isinstance(exc, _TIMEOUT_EXCS) else PeerUnreachable
    return cls(f"{op or 'rpc'} to {peer}: {exc!r}", peer=peer, op=op,
               attempts=attempts)


class RetryPolicy:
    """Bounded exponential backoff with jitter around ONE callable.

    ``run(attempt_fn)`` calls ``attempt_fn(timeout_s)`` up to
    ``1 + retries`` times.  The callable does the actual socket work
    with the given per-attempt deadline (None = block forever) and must
    raise on failure; between attempts the policy sleeps
    ``min(backoff_max_s, backoff_s * 2**i)`` plus up to 10% seeded
    jitter.  Clocks are injectable so tests never sleep for real.
    """

    def __init__(self, retries=2, timeout_s=5.0, backoff_s=0.05,
                 backoff_max_s=2.0, deadline_s=None, seed=0,
                 now=time.monotonic, sleep=time.sleep):
        self.retries = max(0, int(retries))
        self.timeout_s = None if not timeout_s or timeout_s <= 0 \
            else float(timeout_s)
        self.backoff_s = float(backoff_s)
        self.backoff_max_s = float(backoff_max_s)
        self.deadline_s = None if not deadline_s or deadline_s <= 0 \
            else float(deadline_s)
        self._rng = random.Random(seed)
        self._now = now
        self._sleep = sleep

    @classmethod
    def from_env(cls, env=None, **overrides):
        env = os.environ if env is None else env

        def _f(name, default):
            try:
                return float(env.get(name, "") or default)
            except ValueError:
                return default
        kw = dict(retries=int(_f("MXTPU_RPC_RETRIES", 2)),
                  timeout_s=_f("MXTPU_RPC_TIMEOUT_S", 5.0),
                  backoff_s=_f("MXTPU_RPC_BACKOFF_S", 0.05),
                  backoff_max_s=_f("MXTPU_RPC_BACKOFF_MAX_S", 2.0),
                  deadline_s=_f("MXTPU_RPC_DEADLINE_S", 0.0))
        kw.update(overrides)
        return cls(**kw)

    def backoff(self, attempt):
        """Deterministic (per seeded rng state) backoff for attempt i."""
        base = min(self.backoff_max_s, self.backoff_s * (2.0 ** attempt))
        return base * (1.0 + 0.1 * self._rng.random())

    def once(self):
        """A single-attempt twin sharing this policy's deadlines and
        clocks — for NON-idempotent ops.  A reply lost after the server
        already applied the op (per-attempt timeout, connection reset
        before the OK is read) would make a blind resend apply it
        TWICE (push is ``w += grad`` server-side), so such ops get one
        typed, deadline-bounded attempt: the same evidence trail as
        ``run``, just no retry loop."""
        return RetryPolicy(retries=0, timeout_s=self.timeout_s or 0,
                           backoff_s=self.backoff_s,
                           backoff_max_s=self.backoff_max_s,
                           deadline_s=self.deadline_s or 0,
                           now=self._now, sleep=self._sleep)

    def run(self, attempt_fn, peer=None, op=None, reconnect=None,
            on_failure=None):
        """Run ``attempt_fn(timeout_s)`` under the policy.

        ``reconnect()`` (optional) is called before every RE-attempt —
        a half-read length-prefixed stream is poisoned, so retrying on
        the same socket would desync framing.  ``on_failure(exc)``
        (optional) runs once when the budget is exhausted, before the
        typed error propagates.  Telemetry and the final flight dump
        are emitted here so every transport shares one evidence shape.
        """
        from .. import telemetry as _telemetry
        start = self._now()
        attempts = 1 + self.retries
        last = None
        for i in range(attempts):
            if i > 0:
                _telemetry.inc("rpc.retries")
                if op:
                    _telemetry.inc(f"rpc.retries.{op}")
                self._sleep(self.backoff(i - 1))
                if reconnect is not None:
                    try:
                        reconnect(self.timeout_s)
                    except Exception as e:  # noqa: BLE001 — typed below
                        last = classify(e, peer=peer, op=op, attempts=i + 1)
                        _telemetry.inc("rpc.unreachable")
                        continue
            if self.deadline_s is not None \
                    and self._now() - start >= self.deadline_s:
                last = RPCTimeout(
                    f"{op or 'rpc'} to {peer}: total deadline "
                    f"{self.deadline_s}s elapsed after {i} attempts",
                    peer=peer, op=op, attempts=i)
                break
            try:
                return attempt_fn(self.timeout_s)
            except Exception as e:  # noqa: BLE001 — typed + re-raised
                if not isinstance(e, _TIMEOUT_EXCS + _UNREACHABLE_EXCS):
                    raise       # not a transport error (e.g. MXNetError)
                last = classify(e, peer=peer, op=op, attempts=i + 1)
                _telemetry.inc("rpc.timeouts"
                               if isinstance(last, RPCTimeout)
                               else "rpc.unreachable")
        report_failure(last, on_failure=on_failure)
        raise last


def report_failure(err, on_failure=None):
    """Final-failure evidence shared by every transport: counters, a
    typed event, and a flight dump whose reason names the op — so a
    dead peer leaves the same trail whether the call died at connect
    (``PSClient.__init__``) or mid-exchange (``RetryPolicy.run``)."""
    from .. import telemetry as _telemetry
    op = getattr(err, "op", None)
    _telemetry.inc("rpc.failures")
    _telemetry.event("rpc.failed", peer=str(getattr(err, "peer", None)),
                     op=op or "", attempts=getattr(err, "attempts", None),
                     error=type(err).__name__)
    if on_failure is not None:
        try:
            on_failure(err)
        except Exception:  # noqa: BLE001 — evidence must not mask
            pass
    try:
        _telemetry.dump_flight(reason=f"rpc_failure:{op or 'rpc'}")
    except Exception:  # noqa: BLE001 — flight dump is best-effort
        pass
