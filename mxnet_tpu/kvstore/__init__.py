"""``mx.kv`` package (reference: python/mxnet/kvstore.py)."""
from .kvstore import (KVStore, KVStoreLocal, KVStoreTPUSync,
                      KVStoreDistTPUSync, create)
