"""``mx.kv`` — the KVStore: multi-device / multi-host gradient communication.

Reference (SURVEY.md §2.1 KVStore rows + §2.6):
  - local/device:  src/kvstore/kvstore_local.h, comm.h (CPU/GPU reduce)
  - nccl:          src/kvstore/kvstore_nccl.cc
  - dist_*:        src/kvstore/kvstore_dist.h + 3rdparty/ps-lite (ZMQ PS)

TPU-native design: the reference's runtime communication calls become XLA
collectives. Types:
  - ``local`` / ``device``: single-process aggregation; with one addressable
    device this is a passthrough, with several it averages across per-device
    values (list push) exactly like CommDevice.
  - ``tpu_sync``  (alias ``nccl``): single-host multi-chip — values live as
    sharded jax.Arrays on a mesh; pushpull is a jitted psum over the data
    axis (in-graph when called inside a jitted step; eager jit otherwise).
  - ``dist_tpu_sync`` (aliases ``dist_sync``, ``dist_device_sync``): multi-host
    — jax.distributed + global mesh; psum rides ICI/DCN. rank/num_workers map
    to process_index/process_count.
  - ``dist_async``: TRUE async parameter server — host-side TCP PS on
    worker 0 (kvstore/ps_server.py), server-side optimizer applied per
    (stale) push, no training-path barrier; reference
    kvstore_dist_server.h DataHandleEx.
The push/pull API outside a jitted step pays an extra dispatch — the perf
cliff is documented in SURVEY.md §7; Trainer fuses the hot path.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..base import MXNetError
from ..ndarray.ndarray import NDArray
from .. import optimizer as opt

# ONE compiled program per bucket shape: XLA lowers the stacked sum to a
# ring all-reduce across the 'w' mesh axis (the O(payload) wire path)
_sum_stacked = jax.jit(lambda x: jnp.sum(x, axis=0))

# ONE compiled program per pushpull signature: reduces every key's
# per-device copies in a single dispatch (the fused eager pushpull —
# the old push-then-pull pair paid two; acknowledged perf cliff below)
_fused_reduce = jax.jit(
    lambda vss: [v[0] if len(v) == 1 else jnp.sum(jnp.stack(v), axis=0)
                 for v in vss])

__all__ = ["KVStore", "KVStoreLocal", "KVStoreTPUSync", "KVStoreDistTPUSync",
           "KVStoreDistAsync", "create"]


class KVStore:
    """Abstract base matching python/mxnet/kvstore.py KVStore."""

    def __init__(self):
        self._updater = None
        self._optimizer = None
        self._compression = None
        self._membership = None       # elastic.Membership once attached
        self._member_epoch = None     # this worker's applied epoch

    # -- elastic membership fencing (ISSUE 8) --------------------------
    def attach_membership(self, membership):
        """Fence this store's collectives by the cluster's membership
        epoch (``mx.elastic.Membership``): the worker records the epoch
        it was built for, and a collective attempted after the cluster
        moved on raises a clean :class:`StaleMembershipEpoch` instead
        of entering a ring whose peers died or changed — the classic
        unrecoverable hang this turns into a recoverable error.  After
        the controller reshards, :meth:`refresh_membership` re-arms the
        fence at the new epoch."""
        self._membership = membership
        self._member_epoch = membership.epoch
        return self

    def refresh_membership(self):
        """Adopt the current membership epoch (call after a controller-
        led reshard completed on this worker)."""
        if self._membership is not None:
            self._member_epoch = self._membership.epoch
        return self._member_epoch

    def _guard_membership(self):
        """The pushpull-entry fence: no-op without a membership."""
        if self._membership is not None:
            self._membership.check_epoch(
                self._member_epoch,
                what=f"{self.type} collective from this worker")

    @staticmethod
    def _telem_pushpull(n_keys):
        """Registry twin of the store's data-plane activity (ISSUE 9):
        one increment per eager pushpull dispatch + the key count, so a
        live scrape sees collective pressure without a store-specific
        stats call."""
        from .. import telemetry as _telem
        if _telem.enabled():
            _telem.inc("kvstore.pushpull_calls")
            _telem.inc("kvstore.pushpull_keys", n_keys)

    # -- identity ------------------------------------------------------
    @property
    def type(self):
        raise NotImplementedError

    @property
    def rank(self):
        return 0

    @property
    def num_workers(self):
        return 1

    # -- data plane ----------------------------------------------------
    def init(self, key, value):
        raise NotImplementedError

    def push(self, key, value, priority=0):
        raise NotImplementedError

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        raise NotImplementedError

    def pushpull(self, key, value, out=None, priority=0):
        self._telem_pushpull(len(key) if isinstance(key, (list, tuple))
                             else 1)
        self.push(key, value, priority)
        self.pull(key, out=out if out is not None else value,
                  priority=priority)
        return out

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        raise MXNetError(f"row_sparse_pull not supported by {self.type}")

    def send_command_to_servers(self, head, body):
        """Reference KVStore.send_command_to_servers: ps-lite controller
        messages. No-op on serverless stores (matching the reference,
        where only dist stores have servers to talk to); dist_async
        forwards to every server over the typed binary protocol."""

    @staticmethod
    def _local_reduce(vs):
        """CommDevice::Reduce over per-device copies. row_sparse values
        reduce on the compressed pair (concat + segment-sum over unique
        rows) — never densified."""
        from ..ndarray.sparse import RowSparseNDArray, sum_duplicate_rows
        if all(isinstance(v, RowSparseNDArray) for v in vs):
            idx = jnp.concatenate([v.indices.data for v in vs])
            vals = jnp.concatenate([v.values.data for v in vs], axis=0)
            uniq, summed = sum_duplicate_rows(idx, vals)
            return RowSparseNDArray(summed, uniq,
                                    vs[0].shape, vs[0].context)
        # mixed row_sparse + dense: fall through to the dense sum — the
        # sparse members densify via .data (correctness over memory)
        merged = vs[0].data
        for extra in vs[1:]:
            merged = merged + extra.data
        return NDArray(merged, vs[0].context)

    def broadcast(self, key, value, out, priority=0):
        self.init(key, value)
        self.pull(key, out=out, priority=priority)

    # -- optimizer on the store (server-side updates in the reference) --
    def set_optimizer(self, optimizer):
        self._optimizer = optimizer
        self._updater = opt.get_updater(optimizer)

    def _set_updater(self, updater):
        self._updater = updater

    def set_gradient_compression(self, compression_params):
        """Reference: KVStore.set_gradient_compression -> GradientCompression
        (src/kvstore/gradient_compression.cc, 2-bit quantization with error
        feedback). Here compression applies to the cross-worker hop: codes
        are packed 4-per-byte (a real 16x wire reduction for the
        process_allgather DCN path) and dequantized before the reduce."""
        params = dict(compression_params or {})
        ctype = params.get("type", "2bit")
        if ctype == "2bit":
            extra = set(params) - {"type", "threshold"}
            if extra:
                raise MXNetError(f"unknown compression params {sorted(extra)}")
            self._compression = GradientCompression(
                threshold=float(params.get("threshold", 0.5)))
        elif ctype == "int8":
            # EQuARX-style blockwise int8 wire quantization (this build's
            # extension beyond the reference's 2-bit — see PAPERS.md)
            extra = set(params) - {"type"}
            if extra:
                raise MXNetError(
                    f"int8 compression takes no params, got {sorted(extra)}")
            self._compression = Int8GradientCompression()
        else:
            raise MXNetError(f"unsupported compression type {ctype!r}")

    def save_optimizer_states(self, fname, dump_optimizer=False):
        if self._updater is None:
            raise MXNetError("Cannot save states for distributed training")
        with open(fname, "wb") as f:
            f.write(self._updater.get_states(dump_optimizer))

    def load_optimizer_states(self, fname):
        if self._updater is None:
            raise MXNetError("Cannot load states for distributed training")
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())

    def barrier(self):
        pass

    def _barrier(self):
        self.barrier()


def _listify(v):
    return v if isinstance(v, (list, tuple)) else [v]


def _make_buckets(flats, bound):
    """Greedy coalescing of flat arrays into <=bound-byte buckets (index
    lists) — the BIGARRAY_BOUND wire coalescing shared by the allreduce
    and allgather paths."""
    buckets, cur, cur_bytes = [], [], 0
    for i, f in enumerate(flats):
        nbytes = f.size * f.dtype.itemsize
        if cur and cur_bytes + nbytes > bound:
            buckets.append(cur)
            cur, cur_bytes = [], 0
        cur.append(i)
        cur_bytes += nbytes
    if cur:
        buckets.append(cur)
    return buckets


class KVStoreLocal(KVStore):
    """Single-process store. Reference: KVStoreLocal + CommCPU/CommDevice
    (src/kvstore/kvstore_local.h, comm.h): push of a list of per-device
    values reduces them; pull broadcasts the merged value."""

    def __init__(self, device_reduce=True):
        super().__init__()
        self._store = {}
        self._device_reduce = device_reduce

    @property
    def type(self):
        return "device" if self._device_reduce else "local"

    def _canon(self, keys, values):
        if isinstance(keys, (list, tuple)):
            return list(keys), list(values)
        return [keys], [values]

    def init(self, key, value):
        keys, values = self._canon(key, value)
        for k, v in zip(keys, values):
            if isinstance(v, (list, tuple)):
                v = v[0]
            self._store[str(k)] = NDArray(v.data, v.context)

    def push(self, key, value, priority=0):
        from ..ndarray.sparse import RowSparseNDArray
        keys, values = self._canon(key, value)
        for k, v in zip(keys, values):
            k = str(k)
            if k not in self._store:
                raise MXNetError(f"key {k} not initialized (call init first)")
            vs = _listify(v)
            # reduce across device copies (CommDevice::Reduce). Gradient
            # compression is NOT applied here — there is no wire hop in a
            # local reduce (matching the reference, where only dist stores
            # honor it); see KVStoreDistTPUSync.push.
            grad = self._local_reduce(vs)
            if self._updater is not None:
                self._updater(int(k) if k.isdigit() else k, grad,
                              self._store[k])
            elif isinstance(grad, RowSparseNDArray):
                # replace semantics, exactly like the dense branch — the
                # store value BECOMES the reduced (sparse) push; pull of a
                # sparse out preserves the compressed pair
                self._store[k] = grad
            else:
                self._store[k]._set_data(grad.data)

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        from ..ndarray.sparse import RowSparseNDArray
        keys, outs = self._canon(key, out)
        for k, o in zip(keys, outs):
            k = str(k)
            if k not in self._store:
                raise MXNetError(f"key {k} not initialized")
            stored = self._store[k]
            for dst in _listify(o):
                if isinstance(stored, RowSparseNDArray) and \
                        isinstance(dst, RowSparseNDArray):
                    stored.copyto(dst)     # stays O(nnz)
                else:
                    dst._set_data(stored.data)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """Pull only the rows in ``row_ids`` as a RowSparseNDArray —
        traffic and memory proportional to nnz, the sparse-embedding
        training hot path (reference: python/mxnet/kvstore.py
        row_sparse_pull; SURVEY.md §2.5 sparse/embedding parallel)."""
        import numpy as _np
        from ..ndarray.sparse import RowSparseNDArray
        if row_ids is None:
            raise MXNetError("row_sparse_pull requires row_ids")
        keys, outs = self._canon(key, out)
        ids = row_ids if isinstance(row_ids, (list, tuple)) \
            else [row_ids] * len(keys)
        results = []
        for k, o, rid in zip(keys, outs, ids):
            k = str(k)
            if k not in self._store:
                raise MXNetError(f"key {k} not initialized")
            stored = self._store[k]
            rows = _np.unique(_np.asarray(
                getattr(rid, "data", rid)).astype(_np.int64).ravel())
            vals = jnp.take(stored.data, jnp.asarray(rows), axis=0)
            rsp = RowSparseNDArray(vals, jnp.asarray(rows), stored.shape,
                                   stored.context)
            if o is not None:
                rsp.copyto(o) if isinstance(o, RowSparseNDArray) \
                    else o._set_data(rsp.data)
                results.append(o)
            else:
                results.append(rsp)
        return results if isinstance(key, (list, tuple)) else results[0]


def _contains_tracer(values):
    """True when any pushed value is a jax tracer — i.e. the push happens
    inside a jitted/shard_mapped training step."""
    from jax.core import Tracer
    for v in values:
        for x in _listify(v):
            if isinstance(getattr(x, "_data", x), Tracer):
                return True
    return False


def _tracing_active():
    """True while jax is tracing in this thread. Used to tell a traced
    pull apart from an eager pull that would otherwise pick up a stale
    tracer left by an aborted trace."""
    try:
        from jax._src.core import trace_state_clean
        return not trace_state_clean()
    except Exception:  # noqa: BLE001 — jax internals moved; assume tracing
        return True


class KVStoreTPUSync(KVStoreLocal):
    """Single-host multi-chip synchronous store.

    Replaces KVStoreNCCL (src/kvstore/kvstore_nccl.cc): the "allreduce" is a
    jitted mean over per-device copies, or — the fast path — a psum folded
    into the training step over the mesh's data axis: a ``push``/``pull``/
    ``pushpull`` of a *traced* value (inside jit / shard_map over the
    training mesh) stays entirely in-graph as ``lax.psum`` over
    ``data_axis`` (default ``"dp"``; see :meth:`set_data_axis`) — no host
    round-trip, XLA schedules the collective on ICI. Eager pushes reduce
    per-device copies like the local store.
    """

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        from ..parallel.mesh import AXIS_DP
        self._data_axis = AXIS_DP
        self._traced_store = {}   # key -> reduced tracer, within one trace

    @property
    def type(self):
        return "tpu_sync"

    def set_data_axis(self, name):
        """Name of the mesh axis the in-graph collective reduces over."""
        self._data_axis = str(name)

    def _ingraph_reduce(self, x):
        # lax.psum raises NameError or (under shard_map) a bare
        # AssertionError when the axis name is not bound in scope
        try:
            return lax.psum(x, self._data_axis)
        except (NameError, AssertionError) as e:
            raise MXNetError(
                f"in-graph push requires a '{self._data_axis}' mesh axis in "
                f"scope (shard_map the training step over the mesh, or "
                f"set_data_axis() to your axis name)") from e

    def pushpull_scatter(self, key, value, priority=0):
        """Reduce-scatter-aware in-graph pushpull (ISSUE 3 tentpole):
        called with *traced* values inside ``shard_map``, each chip
        contributes its local gradient and receives only its 1/N
        contiguous shard of the cross-chip SUM — ``lax.psum_scatter``
        instead of the full ``psum``, half the ring wire bytes of an
        all-reduce and the entry point for ZeRO-style sharded updates
        (parallel/zero.py owns the bucketed pipeline; this is the
        kvstore-facade spelling).  Values must be flat with length
        divisible by the axis size.  The EAGER path is unchanged: no
        mesh axis is bound outside a trace, so it falls back to the
        fused full pushpull and returns the full reduced values.

        Returns the shard (traced) / full value (eager) NDArray, or the
        list of them for a key list."""
        self._guard_membership()
        keys, values = self._canon(key, value)
        if not _contains_tracer(values):
            outs = [NDArray(jnp.zeros_like(_listify(v)[0].data))
                    for v in values]
            self.pushpull(key, value,
                          out=outs if isinstance(key, (list, tuple))
                          else outs[0], priority=priority)
            return outs if isinstance(key, (list, tuple)) else outs[0]
        if self._updater is not None:
            raise MXNetError(
                "update-on-kvstore is a host-side path; pushpull_scatter "
                "supports updater=None only")
        from ..ndarray.sparse import RowSparseNDArray
        shards = []
        for k, v in zip(keys, values):
            if str(k) not in self._store:
                raise MXNetError(
                    f"key {k} not initialized (call init first)")
            red = self._local_reduce(_listify(v))
            if isinstance(red, RowSparseNDArray):
                raise MXNetError(
                    "row_sparse values are not supported on the in-graph "
                    "reduce-scatter path; push them eagerly (outside jit)")
            flat = jnp.ravel(red.data)
            try:
                shard = lax.psum_scatter(flat, self._data_axis, tiled=True)
            except (NameError, AssertionError) as e:
                raise MXNetError(
                    f"pushpull_scatter requires a '{self._data_axis}' "
                    f"mesh axis in scope (shard_map the step over the "
                    f"mesh, or set_data_axis())") from e
            except ValueError as e:
                raise MXNetError(
                    f"pushpull_scatter: key {k} has {flat.shape[0]} "
                    f"elements, not divisible by the "
                    f"'{self._data_axis}' axis size (pad the bucket — "
                    f"parallel/zero.py BucketPlan does)") from e
            shards.append(NDArray(shard))
        return shards if isinstance(key, (list, tuple)) else shards[0]

    def _push_traced(self, keys, values):
        from ..ndarray.sparse import RowSparseNDArray
        if self._updater is not None:
            raise MXNetError(
                "update-on-kvstore (set_optimizer) is a host-side path; "
                "in-graph push supports updater=None only — apply the "
                "optimizer inside the traced step instead")
        for k, v in zip(keys, values):
            if str(k) not in self._store:
                raise MXNetError(
                    f"key {k} not initialized (call init first)")
            red = self._local_reduce(_listify(v))
            if isinstance(red, RowSparseNDArray):
                raise MXNetError(
                    "row_sparse values are not supported on the in-graph "
                    "push path; push them eagerly (outside jit)")
            self._traced_store[str(k)] = self._ingraph_reduce(red.data)

    def push(self, key, value, priority=0):
        self._guard_membership()
        keys, values = self._canon(key, value)
        if _contains_tracer(values):
            return self._push_traced(keys, values)
        self._traced_store.clear()   # scrub leftovers of an aborted trace
        return super().push(key, value, priority)

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        if not _tracing_active():
            # entries can only be consumed inside the trace that made them;
            # anything still here on an eager pull is a dead tracer
            self._traced_store.clear()
        keys, outs = self._canon(key, out)
        if not any(str(k) in self._traced_store for k in keys):
            return super().pull(key, out=out, priority=priority,
                                ignore_sparse=ignore_sparse)
        # mixed pulls: traced keys come from the in-graph slot, the rest
        # take the eager path, key by key
        for k, o in zip(keys, outs):
            if str(k) in self._traced_store:
                red = self._traced_store.pop(str(k))   # pop: tracers must
                for dst in _listify(o):                # not outlive the trace
                    dst._set_data(red)
            else:
                super().pull(k, out=o, priority=priority,
                             ignore_sparse=ignore_sparse)

    def pushpull(self, key, value, out=None, priority=0):
        """Fused eager pushpull (ISSUE 3 satellite): ONE jitted reduce
        covering every key in the call, with the store and ``out``
        aliasing the same reduced arrays — a single dispatch where the
        push-then-pull composition paid two (the SURVEY §7 eager
        dispatch cliff acknowledged in the module docstring).  Traced
        values (in-graph psum), updater-on-store, and sparse values
        keep the exact push/pull composition."""
        self._guard_membership()
        keys, values = self._canon(key, value)
        if _contains_tracer(values) or self._updater is not None:
            return super().pushpull(key, value, out=out, priority=priority)
        from ..ndarray.sparse import RowSparseNDArray
        vss = []
        for k, v in zip(keys, values):
            vs = _listify(v)
            if str(k) not in self._store or \
                    any(isinstance(x, RowSparseNDArray) for x in vs):
                return super().pushpull(key, value, out=out,
                                        priority=priority)
            vss.append([x.data for x in vs])
        self._telem_pushpull(len(keys))
        self._traced_store.clear()
        merged = _fused_reduce(vss)
        outs = out if out is not None else value
        _, outs_l = self._canon(key, outs)
        for k, m, o in zip(keys, merged, outs_l):
            self._store[str(k)]._set_data(m)
            for dst in _listify(o):
                dst._set_data(m)     # alias, not a copy: zero dispatches
        return out


class KVStoreDistTPUSync(KVStoreTPUSync):
    """Multi-host synchronous store over jax.distributed.

    Reference counterpart: KVStoreDist over ps-lite (push grads to servers,
    pull weights). Here push+pull of a gradient key is an allreduce across
    processes (psum over DCN/ICI via jax collectives through
    multihost_utils); there are no server processes (SURVEY.md §2.6).
    """

    def __init__(self):
        super().__init__()
        _maybe_init_distributed()
        self._rank = jax.process_index()
        self._size = jax.process_count()
        self._gmesh = None        # lazy global mesh for in-graph allreduce
        self._wire_mode = None    # "allreduce" | "allgather" after 1st push
        self._allreduce_broken = False   # latched on collective failure
        self._zeros_cache = {}    # n -> per-extra-local-device zero shards

    @property
    def type(self):
        return "dist_tpu_sync"

    @property
    def rank(self):
        return self._rank

    @property
    def num_workers(self):
        return self._size

    #: bucket size for the fused wire path (bytes). Reference spirit:
    #: MXNET_KVSTORE_BIGARRAY_BOUND — keys below the bound are coalesced
    #: into one allgather round instead of one DCN round per tensor
    #: (VERDICT r1 weak #5: the per-key path craters bandwidth).
    BIGARRAY_BOUND = None  # resolved lazily from MXTPU_KVSTORE_BIGARRAY_BOUND

    def _bound(self):
        if KVStoreDistTPUSync.BIGARRAY_BOUND is None:
            import os
            KVStoreDistTPUSync.BIGARRAY_BOUND = int(os.environ.get(
                "MXTPU_KVSTORE_BIGARRAY_BOUND", str(25 * 1024 * 1024)))
        return KVStoreDistTPUSync.BIGARRAY_BOUND

    def _allgather_sparse(self, rsp):
        """Cross-process sum of a row-sparse value at O(nnz) wire cost:
        allgather per-worker nnz, pad (indices, values) to the max, one
        allgather each, then merge by unique row. Never densifies."""
        import numpy as _np
        from jax.experimental import multihost_utils
        from ..ndarray.sparse import RowSparseNDArray, sum_duplicate_rows
        idx = rsp.indices.data
        vals = rsp.values.data
        sizes = multihost_utils.process_allgather(
            jnp.asarray([idx.shape[0]], jnp.int32))
        sizes = _np.asarray(sizes).ravel()
        cap = int(sizes.max()) if sizes.size else 0
        if cap == 0:
            return rsp
        pad = cap - idx.shape[0]
        if pad:
            idx = jnp.concatenate([idx, jnp.zeros(pad, idx.dtype)])
            vals = jnp.concatenate(
                [vals, jnp.zeros((pad,) + vals.shape[1:], vals.dtype)])
        all_idx = _np.asarray(multihost_utils.process_allgather(idx))
        all_vals = multihost_utils.process_allgather(vals)
        keep_idx = _np.concatenate(
            [all_idx[w, :sizes[w]] for w in range(len(sizes))])
        keep_vals = jnp.concatenate(
            [all_vals[w, :sizes[w]] for w in range(len(sizes))], axis=0)
        uniq, summed = sum_duplicate_rows(keep_idx, keep_vals)
        return RowSparseNDArray(summed, uniq, rsp.shape, rsp.context)

    def init(self, key, value):
        """Reference semantics (KVStoreDist::InitImpl): the server keeps
        worker 0's value; other workers' inits are ignored. Implemented as
        a rank-0 broadcast (zeros elsewhere + cross-process sum), bucketed
        into ONE collective round per BIGARRAY_BOUND of payload — not one
        blocking DCN round per parameter."""
        super().init(key, value)
        if self._size > 1:
            keys, _ = self._canon(key, value)
            vals = [self._store[str(k)].data for k in keys]
            contribs = [v if self._rank == 0 else jnp.zeros_like(v)
                        for v in vals]
            reduced = self._bucketed_allreduce(contribs)
            if reduced is None:
                reduced = [_cross_process_sum(c) for c in contribs]
            for k, r in zip(keys, reduced):
                self._store[str(k)]._set_data(r)

    def push(self, key, value, priority=0):
        self._guard_membership()
        keys, values = self._canon(key, value)
        if _contains_tracer(values):
            # inside a jitted step: stay in-graph as a psum over the global
            # mesh axis — the eager bucketed-allreduce/compression machinery
            # below is the host-mediated wire path and would force a D2H
            # sync per bucket (VERDICT r3 weak #5). Wire compression only
            # applies to the eager path; in-graph, XLA owns the collective.
            return self._push_traced(keys, values)
        self._traced_store.clear()   # scrub leftovers of an aborted trace
        self._eager_push(keys, values)

    def pushpull(self, key, value, out=None, priority=0):
        """Fused eager pushpull over the dist wire: the reduce (ONE
        jitted dispatch for all dense keys), the cross-process hop, and
        the store/out writes happen in a single pass — push-then-pull
        paid a second dispatch round just to copy the stored values out.
        Traced values and updater-on-kvstore keep the composition."""
        self._guard_membership()
        keys, values = self._canon(key, value)
        if _contains_tracer(values) or self._updater is not None:
            return KVStore.pushpull(self, key, value, out=out,
                                    priority=priority)
        self._traced_store.clear()
        outs = out if out is not None else value
        _, outs_l = self._canon(key, outs)
        self._eager_push(keys, values, outs=outs_l)
        return out

    def _eager_push(self, keys, values, outs=None):
        """Shared eager wire path for push/pushpull: per-device reduce
        (one fused jit for every dense key), cross-process transport
        (compressed / bucketed-allreduce / allgather fallback), one
        write pass into the store — and into ``outs``, aliasing the
        same arrays (the pushpull fusion)."""
        from ..ndarray.sparse import RowSparseNDArray
        done = {}                     # str key -> reduced value
        dense_keys, dense_vss = [], []
        for k, v in zip(keys, values):
            vs = _listify(v)
            if any(isinstance(x, RowSparseNDArray) for x in vs):
                red = self._local_reduce(vs)
                if isinstance(red, RowSparseNDArray):
                    if self._size > 1:
                        red = self._allgather_sparse(red)
                    done[str(k)] = red
                else:
                    # mixed sparse+dense copies densify in _local_reduce
                    dense_keys.append(str(k))
                    dense_vss.append([red.data])
            else:
                if str(k) not in self._store:
                    raise MXNetError(
                        f"key {k} not initialized (call init first)")
                dense_keys.append(str(k))
                dense_vss.append([x.data for x in vs])
        merged = list(_fused_reduce(dense_vss)) if dense_vss else []
        if self._compression is not None:
            payloads = []   # per-key packed uint8 codes
            shapes = []
            for k, m in zip(dense_keys, merged):
                packed, shape = self._compression.compress(k, m)
                payloads.append(packed)
                shapes.append(shape)
            if self._size > 1:
                gathered = self._bucketed_allgather(payloads)
                merged = [
                    jnp.sum(jnp.stack(
                        [self._compression.decompress(p, shape, m.dtype)
                         for p in worker_payloads]), axis=0)
                    for shape, m, worker_payloads in
                    zip(shapes, merged, gathered)]
            else:
                merged = [self._compression.decompress(p, shape, m.dtype)
                          for p, shape, m in zip(payloads, shapes, merged)]
        elif self._size > 1:
            reduced = self._bucketed_allreduce(merged)
            if reduced is not None:
                merged = reduced
            else:
                gathered = self._bucketed_allgather(merged)
                merged = [jnp.sum(jnp.stack(list(worker_vals)), axis=0)
                          for worker_vals in gathered]
        done.update(zip(dense_keys, merged))
        for k in [str(k) for k in keys]:
            red = done[k]
            if self._updater is not None:
                grad = red if isinstance(red, RowSparseNDArray) \
                    else NDArray(red)
                self._updater(int(k) if k.isdigit() else k, grad,
                              self._store[k])
            elif isinstance(red, RowSparseNDArray):
                # replace semantics, like KVStoreLocal.push
                self._store[k] = red
            else:
                self._store[k]._set_data(red)
        if outs is not None:
            for k, o in zip([str(k) for k in keys], outs):
                stored = self._store[k]
                for dst in _listify(o):
                    if isinstance(stored, RowSparseNDArray) and \
                            isinstance(dst, RowSparseNDArray):
                        stored.copyto(dst)           # stays O(nnz)
                    else:
                        dst._set_data(stored.data)

    def _global_mesh(self):
        """Mesh over EVERY device of every process — the in-graph
        collective domain (SURVEY.md §2.6: XLA collectives over ICI/DCN,
        no ZMQ/ps-lite)."""
        if self._gmesh is None:
            try:
                import numpy as _np
                devs = jax.devices()
                if len(devs) < self._size:
                    return None
                self._gmesh = Mesh(_np.array(devs), ("w",))
            except Exception:  # noqa: BLE001 — fall back to allgather
                return None
        return self._gmesh

    def _bucketed_allreduce(self, arrays):
        """Sum per-key dense tensors across processes with ONE compiled
        XLA all-reduce per bucket: O(payload) wire cost (vs the allgather
        path's O(workers x payload) — VERDICT r2 weak #3). Returns None
        when the global mesh / cross-process collectives are unavailable,
        letting the caller fall back.

        Reference counterpart: the ps-lite server sum in
        kvstore_dist_server.h; here the reduction IS the wire protocol —
        a jitted ``sum`` over the device-stacked bucket that XLA lowers
        to a ring all-reduce over ICI/DCN (gloo on CPU processes)."""
        import os as _os
        import numpy as _np
        if _os.environ.get("MXTPU_KVSTORE_WIRE", "") == "allgather" or \
                self._allreduce_broken:
            self._wire_mode = "allgather"
            return None
        mesh = self._global_mesh()
        if mesh is None:
            self._wire_mode = "allgather"
            return None
        try:
            # "w" is the PRIVATE single-axis wire mesh _global_mesh()
            # builds for the bucket all-reduce — never a MeshConfig
            # mesh, so the AXIS_* contract does not own the name
            spec = NamedSharding(mesh, P("w"))  # mxlint: disable=HB19
            ndev = len(mesh.devices.ravel())
            local_devs = jax.local_devices()
            bound = self._bound()
            flats = [jnp.ravel(a).astype(jnp.float32) for a in arrays]
            buckets = _make_buckets(flats, bound)
            out_per_key = [None] * len(arrays)
            for idxs in buckets:
                concat = jnp.concatenate([flats[i] for i in idxs]) \
                    if len(idxs) > 1 else flats[idxs[0]]
                n = concat.shape[0]
                # each process contributes its payload on its first local
                # device; other local devices hold (cached) zeros so the
                # stacked sum counts every process exactly once
                if len(local_devs) > 1 and n not in self._zeros_cache:
                    self._zeros_cache[n] = [
                        jax.device_put(jnp.zeros((1, n), jnp.float32), d)
                        for d in local_devs[1:]]
                shards = [jax.device_put(concat.reshape(1, n),
                                         local_devs[0])]
                shards += self._zeros_cache.get(n, [])
                garr = jax.make_array_from_single_device_arrays(
                    (ndev, n), spec, shards)
                summed = _sum_stacked(garr)
                # ONE D2H (local replica) + ONE H2D per bucket; per-key
                # splits are device-side slices of the uploaded bucket
                dev = jnp.asarray(_np.asarray(summed))
                offset = 0
                for i in idxs:
                    sz = flats[i].size
                    out_per_key[i] = dev[offset:offset + sz].reshape(
                        arrays[i].shape).astype(arrays[i].dtype)
                    offset += sz
            self._wire_mode = "allreduce"
            return out_per_key
        except Exception:  # noqa: BLE001 — collective backend missing;
            # latch the failure so later pushes skip straight to allgather
            # instead of re-paying the failed transfer each step
            self._gmesh = None
            self._allreduce_broken = True
            self._wire_mode = "allgather"
            return None

    def _bucketed_allgather(self, arrays):
        """Coalesce per-key tensors into <=BIGARRAY_BOUND-byte flat buckets,
        allgather each bucket once across processes, split back.

        Returns, per input array, the list of that array's value on every
        worker (self first is NOT guaranteed; callers only sum)."""
        from jax.experimental import multihost_utils
        bound = self._bound()
        flats = [a.reshape(-1) for a in arrays]
        buckets = _make_buckets(flats, bound)
        per_key = [None] * len(arrays)
        for idxs in buckets:
            if len({flats[i].dtype for i in idxs}) > 1:
                # mixed dtypes can't concat; gather individually
                for i in idxs:
                    g = multihost_utils.process_allgather(flats[i])  # mxlint: disable=HB07 -- mixed-dtype fallback within ONE bucket; the common path below is batched
                    per_key[i] = [g[w].reshape(arrays[i].shape)
                                  for w in range(g.shape[0])]
                continue
            concat = jnp.concatenate([flats[i] for i in idxs]) \
                if len(idxs) > 1 else flats[idxs[0]]
            g = multihost_utils.process_allgather(concat)  # (workers, n)  # mxlint: disable=HB07 -- one DCN round per >=BIGARRAY_BOUND bucket IS the batching
            offset = 0
            for i in idxs:
                n = flats[i].size
                per_key[i] = [g[w, offset:offset + n]
                              .reshape(arrays[i].shape)
                              for w in range(g.shape[0])]
                offset += n
        return per_key

    def barrier(self):
        if self._size > 1:
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices("kvstore_barrier")


class KVStoreDistAsync(KVStoreLocal):
    """True asynchronous parameter server (``dist_async``).

    Reference: KVStoreDist in async mode — workers push gradients at their
    own pace, the SERVER applies the optimizer the moment each (possibly
    stale) gradient arrives (kvstore_dist_server.h DataHandleEx), pulls
    return the newest weights, and nothing on the training path barriers.
    Server transport: kvstore/ps_server.py (TCP on worker 0's host — the
    DCN side; the synchronous ICI path stays in KVStoreDistTPUSync).
    """

    def __init__(self):
        super().__init__()
        import os
        from .ps_server import (PSServer, PSClient, ps_addrs,
                                key_to_server, heartbeat_timeout)
        self._rank = int(os.environ.get("MXTPU_PROCESS_ID", "0"))
        self._size = int(os.environ.get("MXTPU_NUM_PROCESSES", "1"))
        self._key_to_server = key_to_server
        addrs = ps_addrs()
        self._server = None
        if "MXTPU_PS_ADDRS" not in os.environ and self._rank == 0:
            # no dedicated server role (launch.py without -s): one server
            # co-locates with worker 0, reference local-launcher style
            host, port = addrs[0]
            self._server = PSServer("0.0.0.0", port, self._size)
            addrs = [("127.0.0.1", port)]
        # one client per server; keys shard across them (ps-lite key
        # ranges -> crc32 hash here); barriers coordinate on server 0
        self._clients = [PSClient(h, p) for h, p in addrs]
        self._client = self._clients[0]
        # failure detection (reference PS_HEARTBEAT_TIMEOUT): when the
        # timeout env is set, every worker beats every server; a silent
        # worker is declared dead server-side, async training continues,
        # and barriers abort cleanly naming the dead rank
        if heartbeat_timeout() > 0:
            for c in self._clients:
                c.start_heartbeat(self._rank)

    def _client_for(self, key):
        return self._clients[self._key_to_server(key, len(self._clients))]

    @property
    def type(self):
        return "dist_async"

    @property
    def rank(self):
        return self._rank

    @property
    def num_workers(self):
        return self._size

    def init(self, key, value):
        keys, values = self._canon(key, value)
        for k, v in zip(keys, values):
            if isinstance(v, (list, tuple)):
                v = v[0]
            self._store[str(k)] = NDArray(v.data, v.context)
            if self._rank == 0:
                self._client_for(str(k)).init(str(k), _onp_asarray(v))
        # worker 0's init wins (reference InitImpl); everyone else waits
        # for it then pulls the authoritative value
        self._client.barrier()
        if self._rank != 0:
            for k in keys:
                w = self._client_for(str(k)).pull(str(k))
                self._store[str(k)]._set_data(jnp.asarray(w))

    def set_optimizer(self, optimizer):
        # optimizer runs ON the servers (update_on_kvstore) — exactly the
        # reference flow; no local updater. Every server gets the config.
        self._optimizer = optimizer
        for c in self._clients:
            c.set_optimizer(optimizer)

    def push(self, key, value, priority=0):
        keys, values = self._canon(key, value)
        for k, v in zip(keys, values):
            grad = self._local_reduce(_listify(v))
            self._client_for(str(k)).push(str(k), _onp_asarray(grad))

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        keys, outs = self._canon(key, out)
        for k, o in zip(keys, outs):
            w = jnp.asarray(self._client_for(str(k)).pull(str(k)))
            for dst in _listify(o):
                dst._set_data(w)

    def push_stats(self):
        """Applied-push counters per key (stale pushes included), merged
        across all servers — test / observability hook."""
        merged = {}
        for c in self._clients:
            merged.update(c.stats())
        return merged

    def per_server_stats(self):
        """Per-server push counters (observability for the key sharding)."""
        return [c.stats() for c in self._clients]

    def send_command_to_servers(self, head, body):
        for c in self._clients:
            c.send_command(head, body)

    def barrier(self):
        self._client.barrier()


def _onp_asarray(v):
    import numpy as _np
    return _np.asarray(v.data if isinstance(v, NDArray) else v)


def _maybe_init_distributed():
    """Rendezvous normally happens at `import mxnet_tpu` (see _dist_init);
    this re-check covers stores created before the env was set."""
    from .._dist_init import maybe_init_distributed
    maybe_init_distributed()


class GradientCompression:
    """2-bit gradient quantization with error feedback.

    Reference semantics (src/kvstore/gradient_compression.cc Quantize2Bit):
    values >= threshold send +threshold (code 1), <= -threshold send
    -threshold (code 2), else 0 (code 0); the quantization error is kept in
    a per-key residual and added before the next quantization. Codes pack 4
    per uint8 byte. Everything is jax ops, so under a jitted step the
    pack/unpack fuses on-device.
    """

    def __init__(self, threshold=0.5):
        if threshold <= 0:
            raise MXNetError("threshold must be positive")
        self.threshold = float(threshold)
        self._residuals = {}

    def compress(self, key, grad):
        """grad -> (packed uint8 codes, original shape); updates residual."""
        t = self.threshold
        res = self._residuals.get(key)
        g = grad if res is None else grad + res
        codes = jnp.where(g >= t, jnp.uint8(1),
                          jnp.where(g <= -t, jnp.uint8(2), jnp.uint8(0)))
        q = jnp.where(codes == 1, t, jnp.where(codes == 2, -t, 0.0)) \
            .astype(grad.dtype)
        self._residuals[key] = g - q
        flat = codes.reshape(-1)
        pad = (-flat.shape[0]) % 4
        if pad:
            flat = jnp.concatenate([flat, jnp.zeros(pad, jnp.uint8)])
        quads = flat.reshape(-1, 4)
        packed = (quads[:, 0] | (quads[:, 1] << 2) | (quads[:, 2] << 4)
                  | (quads[:, 3] << 6))
        return packed, grad.shape

    def decompress(self, packed, shape, dtype=jnp.float32):
        t = self.threshold
        quads = jnp.stack([(packed >> s) & 3 for s in (0, 2, 4, 6)], axis=1)
        flat = quads.reshape(-1)[:int(_np_prod(shape))]
        vals = jnp.where(flat == 1, t, jnp.where(flat == 2, -t, 0.0))
        return vals.reshape(shape).astype(dtype)


class Int8GradientCompression:
    """Blockwise int8 wire quantization with error feedback (EQuARX-style,
    arXiv:2506.17615 — quantized all-reduce payloads; PAPERS.md row 9).

    Each 256-value block carries one f32 scale (max|g|/127) plus int8
    codes: 8.1 bits/value on the wire vs 32 — ~4x less than f32, 4x more
    than the 2-bit scheme but with value-proportional (not threshold)
    error, so it converges without tuning. Quantization error feeds back
    through a per-key residual like the reference 2-bit path
    (src/kvstore/gradient_compression.cc error feedback). All ops are jax;
    scales ride inside the same uint8 payload (bitcast), so the existing
    bucketed-allgather wire carries one array per key.
    """

    BLOCK = 256

    def __init__(self):
        self._residuals = {}

    def compress(self, key, grad):
        b = self.BLOCK
        res = self._residuals.get(key)
        g = grad if res is None else grad + res
        flat = jnp.ravel(g).astype(jnp.float32)
        pad = (-flat.shape[0]) % b
        if pad:
            flat = jnp.concatenate([flat, jnp.zeros(pad, jnp.float32)])
        blocks = flat.reshape(-1, b)
        scale = jnp.max(jnp.abs(blocks), axis=1, keepdims=True) / 127.0
        scale = jnp.maximum(scale, 1e-30)
        from ..ops.quant_matmul import quantize_rtn_int8
        q = quantize_rtn_int8(blocks, scale)
        deq = (q.astype(jnp.float32) * scale).reshape(-1)
        deq = deq[:g.size].reshape(g.shape).astype(grad.dtype)
        self._residuals[key] = g - deq
        codes_u8 = lax.bitcast_convert_type(q, jnp.uint8).reshape(-1)
        scale_u8 = lax.bitcast_convert_type(
            scale.reshape(-1), jnp.uint8).reshape(-1)
        return jnp.concatenate([codes_u8, scale_u8]), grad.shape

    def decompress(self, packed, shape, dtype=jnp.float32):
        b = self.BLOCK
        n = int(_np_prod(shape))
        npad = -(-n // b) * b
        nblocks = npad // b
        codes = lax.bitcast_convert_type(
            packed[:npad].reshape(-1, 1), jnp.int8).reshape(-1, b)
        scale = lax.bitcast_convert_type(
            packed[npad:npad + 4 * nblocks].reshape(-1, 4), jnp.float32)
        vals = codes.astype(jnp.float32) * scale.reshape(-1, 1)
        return vals.reshape(-1)[:n].reshape(shape).astype(dtype)


def _np_prod(shape):
    out = 1
    for s in shape:
        out *= int(s)
    return out


def _cross_process_sum(arr):
    from jax.experimental import multihost_utils
    stacked = multihost_utils.process_allgather(arr)
    return jnp.sum(stacked, axis=0)


_TYPES = {}


def create(name="local"):
    """Factory, reference: mx.kv.create(type)."""
    name = name.lower()
    if name == "local":
        return KVStoreLocal(device_reduce=False)
    if name == "device":
        return KVStoreLocal(device_reduce=True)
    if name in ("nccl", "tpu_sync"):
        return KVStoreTPUSync()
    if name in ("dist_sync", "dist_device_sync", "dist_tpu_sync"):
        return KVStoreDistTPUSync()
    if name == "dist_async":
        return KVStoreDistAsync()
    if name in ("horovod", "byteps"):
        # reference >=1.6 adapter facades (kvstore/horovod.py, byteps.py):
        # on TPU the XLA collectives already play the allreduce role
        from .horovod import KVStoreHorovod, KVStoreBytePS
        return KVStoreHorovod() if name == "horovod" else KVStoreBytePS()
    raise MXNetError(f"unknown KVStore type {name!r}")
