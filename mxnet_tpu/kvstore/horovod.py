"""Horovod / BytePS adapter facades.

Reference [>=1.6]: python/mxnet/kvstore/horovod.py and byteps.py — thin
KVStore adapters that re-route push/pull onto horovod.mxnet /
byteps.mxnet allreduce so `--kv-store horovod` scripts run unchanged.

On TPU there is no Horovod or BytePS daemon to adapt to: XLA collectives
over ICI/DCN already ARE the allreduce engine both of those libraries
exist to provide. The facades therefore map onto the synchronous
in-graph store (KVStoreDistTPUSync): `mx.kv.create('horovod')` and
`mx.kv.create('byteps')` keep working for migrating scripts, with the
same push=allreduce / pull=read semantics the adapters had — rank/size
come from jax.distributed instead of hvd.rank()/bps.rank().
"""
from __future__ import annotations

from .kvstore import KVStoreDistTPUSync

__all__ = ["KVStoreHorovod", "KVStoreBytePS"]


class KVStoreHorovod(KVStoreDistTPUSync):
    """`--kv-store horovod` compatibility (reference kvstore/horovod.py).

    The reference adapter forbade a server-side optimizer (horovod has no
    servers; the update runs in the worker) — same constraint here."""

    @property
    def type(self):
        return "horovod"

    def set_optimizer(self, optimizer):
        from ..base import MXNetError
        raise MXNetError(
            f"kvstore '{self.type}' does not run a server-side optimizer "
            "(reference adapter behavior): update_on_kvstore is "
            "False — apply the optimizer in the worker (gluon.Trainer "
            "does this automatically).")


class KVStoreBytePS(KVStoreHorovod):
    """`--kv-store byteps` compatibility (reference kvstore/byteps.py)."""

    @property
    def type(self):
        return "byteps"
