"""``mx.mod`` — Module API (reference: python/mxnet/module/)."""
from .module import BaseModule, Module, BatchEndParam, load_checkpoint
from .bucketing_module import BucketingModule
from .executor import Executor
