"""Executor: evaluates a Symbol graph with autograd support.

Reference: src/executor/graph_executor.cc + python/mxnet/executor.py.
Memory planning / op bulking are absorbed by XLA (SURVEY.md §2.1 "Graph
executor" row); what remains is the bind contract: arg arrays, grad arrays,
forward(is_train)/backward().
"""
from __future__ import annotations

import numpy as _np

from ..base import MXNetError
from ..context import current_context
from ..ndarray.ndarray import NDArray, zeros as nd_zeros
from .. import autograd

__all__ = ["Executor"]


class _SymSlot:
    """Marks a symbol-input position (with its inferred shape) during
    shape materialization, so literal tuple arguments survive."""

    __slots__ = ("shape",)

    def __init__(self, shape):
        self.shape = tuple(shape)


class Executor:
    def __init__(self, symbol, ctx=None, shapes=None, args=None,
                 args_grad=None, grad_req="write", label_shapes=None,
                 group2ctxs=None):
        self._symbol = symbol
        self._ctx = ctx or current_context()
        # manual model parallel (reference group2ctx in Symbol.bind):
        # {ctx_group attr -> Context}; ops in a group run on its device
        self._ctx_map = {}
        self._group_placements = None   # name -> device, built lazily
        if group2ctxs:
            g2c = group2ctxs[0] if isinstance(group2ctxs, (list, tuple)) \
                else group2ctxs
            for group, c in g2c.items():
                d = getattr(c, "jax_device", c)
                self._ctx_map[group] = d
        self.grad_req = grad_req
        arg_names = symbol.list_arguments()
        self.arg_dict = {}
        if args is not None:
            if isinstance(args, dict):
                self.arg_dict.update(args)
            else:
                for name, arr in zip(arg_names, args):
                    self.arg_dict[name] = arr
        if shapes:
            for name in arg_names:
                if name in self.arg_dict:
                    continue
                if name in shapes:
                    self.arg_dict[name] = nd_zeros(tuple(shapes[name]),
                                                   ctx=self._ctx)
        self.grad_dict = {}
        if args_grad:
            if isinstance(args_grad, dict):
                self.grad_dict.update(args_grad)
            else:
                for name, arr in zip(arg_names, args_grad):
                    self.grad_dict[name] = arr
        self.aux_dict = {}
        self.outputs = []
        self._req = grad_req if isinstance(grad_req, dict) else \
            {n: grad_req for n in arg_names}
        self._data_names = [n for n in arg_names
                            if n in ("data", "softmax_label", "label") or
                            n.endswith("_label") or n.endswith("data")]

    def _materialize_params(self):
        """Create zero arrays for auto-generated parameter variables.

        Walks the expression graph in eval order; each parameterized op's
        input shape is known by the time the op is reached (data shapes
        come from bind), so its weight shapes follow from
        _PARAM_SHAPE_RULES — the working remnant of the reference's
        InferShape pass."""
        from ..symbol.symbol import Symbol
        if getattr(self, "_materialized", False):
            return      # labels may stay unbound forever (predict path)
        missing = [n for n in self._symbol.list_arguments()
                   if n not in self.arg_dict]
        if not missing:
            self._materialized = True
            return
        import jax
        import jax.numpy as jnp
        shape_env = {n: jax.ShapeDtypeStruct(tuple(a.shape), jnp.float32)
                     for n, a in self.arg_dict.items()}
        created = {}

        def shape_of(s):
            if s._op is None and s._outputs is None:
                if s._name in shape_env:
                    return tuple(shape_env[s._name].shape)
                declared = getattr(s, "_declared_shape", None)
                if declared is not None:
                    created[s._name] = declared
                    shape_env[s._name] = jax.ShapeDtypeStruct(
                        declared, jnp.float32)
                    return declared
                raise MXNetError(
                    f"cannot infer shape for unbound variable '{s._name}' "
                    "(not produced by a parameterized op; declare "
                    "var(shape=...) or bind it explicitly)")
            if s._outputs is not None:
                return shape_of(s._outputs[0])
            return _infer_node(s)

        cache = {}

        def _infer_node(s):
            if id(s) in cache:
                return cache[id(s)]
            if s._op in _LABEL_OPS:
                # label vars are inputs, not params: default to (batch,)
                in_shape = shape_of(s._args[0])
                for a in s._args[1:]:
                    if isinstance(a, Symbol) and a._op is None and \
                            a._name not in shape_env:
                        shape_env[a._name] = jax.ShapeDtypeStruct(
                            (in_shape[0],), jnp.float32)
            rule = _PARAM_SHAPE_RULES.get(s._op)
            if rule is not None:
                in_shape = shape_of(s._args[0])
                shapes = rule(in_shape, s._kwargs)
                for a in s._args[1:]:
                    if isinstance(a, Symbol) and a._op is None and \
                            a._name not in shape_env:
                        suffix = a._name.rsplit("_", 1)[-1]
                        key = ("moving_" + a._name.rsplit("_", 2)[-1]
                               if a._name.endswith(("moving_mean",
                                                    "moving_var"))
                               else suffix)
                        pshape = shapes.get(key) or shapes.get(suffix)
                        if pshape is None:
                            raise MXNetError(
                                f"no shape rule for param '{a._name}' "
                                f"of op {s._op}")
                        shape_env[a._name] = jax.ShapeDtypeStruct(
                            tuple(pshape), jnp.float32)
                        created[a._name] = tuple(pshape)
            # output shape via jax.eval_shape on the single op
            from ..symbol.symbol import _apply_nd_op
            from .. import _tape

            arg_protos = []
            for a in s._args:
                if isinstance(a, Symbol):
                    # marker class, NOT a raw tuple: literal tuple args
                    # (e.g. reshape's positional shape) must pass through
                    # untouched instead of being mistaken for array slots
                    arg_protos.append(_SymSlot(shape_of(a)))
                else:
                    arg_protos.append(a)

            def run(*arrs):
                it = iter(arrs)
                vals = [NDArray(next(it)) if isinstance(p, _SymSlot) else p
                        for p in arg_protos]
                out = _apply_nd_op(s._op, vals, s._kwargs)
                outs = out if isinstance(out, list) else [out]
                return tuple(o.data for o in outs)

            protos = [jax.ShapeDtypeStruct(p.shape, jnp.float32)
                      for p in arg_protos if isinstance(p, _SymSlot)]
            with _tape.trace_scope():
                out_shapes = jax.eval_shape(run, *protos)
            shape = tuple(out_shapes[s._out_index or 0].shape)
            cache[id(s)] = shape
            return shape

        shape_of(self._symbol)
        for name in missing:
            if name in created:
                self.arg_dict[name] = nd_zeros(created[name], ctx=self._ctx)
                if name.rsplit("_", 1)[-1] in ("mean", "var"):
                    self._req[name] = "null"
            elif _is_input_name(name):
                pass    # labels may stay unbound (predict path)
            else:
                raise MXNetError(f"argument '{name}' was never bound and "
                                 "could not be materialized")
        self._materialized = True

    @property
    def arg_arrays(self):
        return [self.arg_dict[n] for n in self._symbol.list_arguments()]

    @property
    def grad_arrays(self):
        return [self.grad_dict.get(n)
                for n in self._symbol.list_arguments()]

    @property
    def aux_arrays(self):
        return []

    def _place_group_params(self):
        """Pin each ctx_group's PARAMETERS on its mapped device once (the
        reference binds weights to group2ctx devices at bind time) — so
        only activations hop across stages in _eval, not whole weight
        stacks every step."""
        if not self._ctx_map:
            return
        import jax
        if self._group_placements is None:
            # the graph and ctx_map are fixed after bind: walk ONCE and
            # cache (param name -> device); per-forward cost is then just
            # an identity check per grouped param
            from ..symbol.symbol import Symbol, _collect_nodes
            heads = self._symbol._outputs or [self._symbol]
            nodes = [n for h in heads for n in _collect_nodes(h)]
            placements = {}
            for node in nodes:
                group = node._attrs.get("ctx_group") if node._attrs \
                    else None
                dev = self._ctx_map.get(group)
                if dev is None:
                    continue
                for a in node._args:
                    if isinstance(a, Symbol) and a._op is None and \
                            not _is_input_name(a._name):
                        placements[a._name] = dev
            self._group_placements = placements
        for name, dev in self._group_placements.items():
            arr = self.arg_dict.get(name)
            if arr is not None and arr._data is not None and \
                    arr.data.devices() != {dev}:
                arr._set_data(jax.device_put(arr.data, dev))

    def forward(self, is_train=False, **kwargs):
        for name, value in kwargs.items():
            if name not in self.arg_dict:
                self.arg_dict[name] = value
            else:
                self.arg_dict[name]._set_data(
                    value.data if isinstance(value, NDArray) else value)
        self._materialize_params()
        self._place_group_params()
        bindings = dict(self.arg_dict)
        # unbound labels evaluate as None: output heads then run
        # forward-only (softmax / identity), matching reference predict
        for n in self._symbol.list_arguments():
            if n not in bindings and _is_input_name(n):
                bindings[n] = None
        if is_train:
            for name, arr in self.arg_dict.items():
                req = self._req.get(name, "write")
                if req != "null" and not _is_input_name(name):
                    arr.attach_grad(req)
            with autograd.record():
                out = self._symbol._eval(bindings,
                                         ctx_map=self._ctx_map or None)
        else:
            out = self._symbol._eval(bindings,
                                     ctx_map=self._ctx_map or None)
        self.outputs = out if isinstance(out, list) else [out]
        self._train_outputs = self.outputs if is_train else None
        return self.outputs

    def backward(self, out_grads=None):
        if self._train_outputs is None:
            raise MXNetError("call forward(is_train=True) before backward")
        heads = self._train_outputs
        autograd.backward(heads, out_grads)
        for name, arr in self.arg_dict.items():
            if self._req.get(name, "write") != "null" and \
                    not _is_input_name(name) and arr._grad is not None:
                self.grad_dict[name] = arr.grad

    def copy_params_from(self, arg_params, aux_params=None,
                         allow_extra_params=False):
        for name, arr in arg_params.items():
            if name in self.arg_dict:
                self.arg_dict[name]._set_data(arr.data)
            elif not allow_extra_params:
                raise MXNetError(f"unknown param {name}")


def _fc_rules(in_shape, kw):
    num_hidden = int(kw["num_hidden"])
    flatten = kw.get("flatten", True)
    in_units = 1
    if flatten:
        for s in in_shape[1:]:
            in_units *= int(s)
    else:
        in_units = int(in_shape[-1])
    return {"weight": (num_hidden, in_units), "bias": (num_hidden,)}


def _conv_rules(in_shape, kw):
    nf = int(kw["num_filter"])
    kernel = tuple(kw["kernel"])
    groups = int(kw.get("num_group", 1))
    return {"weight": (nf, int(in_shape[1]) // groups) + kernel,
            "bias": (nf,)}


def _deconv_rules(in_shape, kw):
    # deconv weight layout is (C_in, num_filter//groups, *k) — see
    # gluon/nn/conv_layers.py and nd.Deconvolution(transpose_kernel)
    nf = int(kw["num_filter"])
    kernel = tuple(kw["kernel"])
    groups = int(kw.get("num_group", 1))
    return {"weight": (int(in_shape[1]), nf // groups) + kernel,
            "bias": (nf,)}


def _chan_rules(in_shape, kw):
    c = int(in_shape[1])
    return {"gamma": (c,), "beta": (c,), "moving_mean": (c,),
            "moving_var": (c,)}


def _lastdim_rules(in_shape, kw):
    c = int(in_shape[-1])
    return {"gamma": (c,), "beta": (c,)}


def _embed_rules(in_shape, kw):
    return {"weight": (int(kw["input_dim"]), int(kw["output_dim"]))}


# The reference's InferShape pass (SURVEY.md §2.1 Symbol/nnvm row) reduced
# to what bind actually needs: shapes for auto-created parameter variables,
# derived from the (already materialized) first-input shape of each
# parameterized op during a forward walk of the expression graph.
_PARAM_SHAPE_RULES = {
    "FullyConnected": _fc_rules,
    "Convolution": _conv_rules,
    "Deconvolution": _deconv_rules,
    "BatchNorm": _chan_rules,
    "LayerNorm": _lastdim_rules,
    "InstanceNorm": _lastdim_rules,
    "Embedding": _embed_rules,
}

_NO_GRAD_PARAMS = {"moving_mean", "moving_var"}    # aux states

_LABEL_OPS = ("SoftmaxOutput", "LinearRegressionOutput",
              "MAERegressionOutput", "LogisticRegressionOutput")


def _is_input_name(name):
    return name in ("data", "label", "softmax_label") or \
        name.endswith("_label") or name.endswith("_data") or name == "data"
