"""Executor: evaluates a Symbol graph with autograd support.

Reference: src/executor/graph_executor.cc + python/mxnet/executor.py.
Memory planning / op bulking are absorbed by XLA (SURVEY.md §2.1 "Graph
executor" row); what remains is the bind contract: arg arrays, grad arrays,
forward(is_train)/backward().
"""
from __future__ import annotations

import numpy as _np

from ..base import MXNetError
from ..context import current_context
from ..ndarray.ndarray import NDArray, zeros as nd_zeros
from .. import autograd

__all__ = ["Executor"]


class Executor:
    def __init__(self, symbol, ctx=None, shapes=None, args=None,
                 args_grad=None, grad_req="write", label_shapes=None):
        self._symbol = symbol
        self._ctx = ctx or current_context()
        self.grad_req = grad_req
        arg_names = symbol.list_arguments()
        self.arg_dict = {}
        if args is not None:
            if isinstance(args, dict):
                self.arg_dict.update(args)
            else:
                for name, arr in zip(arg_names, args):
                    self.arg_dict[name] = arr
        if shapes:
            for name in arg_names:
                if name in self.arg_dict:
                    continue
                if name in shapes:
                    self.arg_dict[name] = nd_zeros(tuple(shapes[name]),
                                                   ctx=self._ctx)
        self.grad_dict = {}
        if args_grad:
            if isinstance(args_grad, dict):
                self.grad_dict.update(args_grad)
            else:
                for name, arr in zip(arg_names, args_grad):
                    self.grad_dict[name] = arr
        self.aux_dict = {}
        self.outputs = []
        self._req = grad_req if isinstance(grad_req, dict) else \
            {n: grad_req for n in arg_names}
        self._data_names = [n for n in arg_names
                            if n in ("data", "softmax_label", "label") or
                            n.endswith("_label") or n.endswith("data")]

    @property
    def arg_arrays(self):
        return [self.arg_dict[n] for n in self._symbol.list_arguments()]

    @property
    def grad_arrays(self):
        return [self.grad_dict.get(n)
                for n in self._symbol.list_arguments()]

    @property
    def aux_arrays(self):
        return []

    def forward(self, is_train=False, **kwargs):
        for name, value in kwargs.items():
            if name not in self.arg_dict:
                self.arg_dict[name] = value
            else:
                self.arg_dict[name]._set_data(
                    value.data if isinstance(value, NDArray) else value)
        bindings = dict(self.arg_dict)
        if is_train:
            for name, arr in self.arg_dict.items():
                req = self._req.get(name, "write")
                if req != "null" and not _is_input_name(name):
                    arr.attach_grad(req)
            with autograd.record():
                out = self._symbol._eval(bindings)
        else:
            out = self._symbol._eval(bindings)
        self.outputs = out if isinstance(out, list) else [out]
        self._train_outputs = self.outputs if is_train else None
        return self.outputs

    def backward(self, out_grads=None):
        if self._train_outputs is None:
            raise MXNetError("call forward(is_train=True) before backward")
        heads = self._train_outputs
        autograd.backward(heads, out_grads)
        for name, arr in self.arg_dict.items():
            if self._req.get(name, "write") != "null" and \
                    not _is_input_name(name) and arr._grad is not None:
                self.grad_dict[name] = arr.grad

    def copy_params_from(self, arg_params, aux_params=None,
                         allow_extra_params=False):
        for name, arr in arg_params.items():
            if name in self.arg_dict:
                self.arg_dict[name]._set_data(arr.data)
            elif not allow_extra_params:
                raise MXNetError(f"unknown param {name}")


def _is_input_name(name):
    return name in ("data", "label", "softmax_label") or \
        name.endswith("_label") or name.endswith("_data") or name == "data"
