"""``mx.mod.BucketingModule`` — variable-length sequence training.

Reference: python/mxnet/module/bucketing_module.py. The reference kept one
bound executor per bucket (seq length); here each bucket key gets its own
Module and XLA compiles one program per bucket — identical retrace economics
(SURVEY.md §7 hard parts: dynamic shapes / bucketed padding).
"""
from __future__ import annotations

import logging

from ..base import MXNetError
from .module import BaseModule, Module

__all__ = ["BucketingModule"]


class BucketingModule(BaseModule):
    def __init__(self, sym_gen, default_bucket_key=None, logger=logging,
                 context=None, **kwargs):
        super().__init__(logger)
        assert default_bucket_key is not None
        self._sym_gen = sym_gen
        self._default_bucket_key = default_bucket_key
        self._context = context
        self._kwargs = kwargs
        self._buckets = {}
        self._curr_module = None
        self._curr_bucket_key = None
        self._opt_config = None

    @property
    def symbol(self):
        return self._curr_module.symbol

    def _gen_module(self, bucket_key):
        if bucket_key not in self._buckets:
            symbol, data_names, label_names = self._sym_gen(bucket_key)
            mod = Module(symbol, data_names, label_names,
                         logger=self.logger, context=self._context,
                         **self._kwargs)
            self._buckets[bucket_key] = mod
        return self._buckets[bucket_key]

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             **kwargs):
        self._curr_module = self._gen_module(self._default_bucket_key)
        self._curr_bucket_key = self._default_bucket_key
        self._curr_module.bind(data_shapes, label_shapes, for_training)
        self.binded = True
        self.for_training = for_training

    def switch_bucket(self, bucket_key, data_shapes, label_shapes=None):
        mod = self._gen_module(bucket_key)
        if not mod.binded:
            mod.bind(data_shapes, label_shapes, self.for_training)
            if self._curr_module.params_initialized:
                arg, aux = self._curr_module.get_params()
                mod.init_params(arg_params=arg, aux_params=aux,
                                force_init=True)
                mod.params_initialized = True
            if self._opt_config is not None:
                mod.init_optimizer(**self._opt_config)
        self._curr_module = mod
        self._curr_bucket_key = bucket_key

    def init_params(self, **kwargs):
        self._curr_module.init_params(**kwargs)
        self.params_initialized = True

    def init_optimizer(self, **kwargs):
        self._opt_config = kwargs
        self._curr_module.init_optimizer(**kwargs)
        self.optimizer_initialized = True

    def forward(self, data_batch, is_train=None):
        key = getattr(data_batch, "bucket_key", None) or \
            self._default_bucket_key
        if key != self._curr_bucket_key:
            self.switch_bucket(key, data_batch.provide_data,
                               data_batch.provide_label)
        self._curr_module.forward(data_batch, is_train)

    def backward(self, out_grads=None):
        self._curr_module.backward(out_grads)

    def update(self):
        self._curr_module.update()
        # weights are shared through get/set on switch; nothing else needed

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        self._curr_module.update_metric(eval_metric, labels)

    def get_outputs(self, merge_multi_context=True):
        return self._curr_module.get_outputs()

    def get_params(self):
        return self._curr_module.get_params()
