"""``mx.mod.BucketingModule`` — variable-length sequence training.

Reference: python/mxnet/module/bucketing_module.py. The reference kept one
bound executor per bucket (seq length); here each bucket key gets its own
Module and XLA compiles one program per bucket — identical retrace economics
(SURVEY.md §7 hard parts: dynamic shapes / bucketed padding).
"""
from __future__ import annotations

import logging

from ..base import MXNetError
from .module import BaseModule, Module

__all__ = ["BucketingModule"]


class BucketingModule(BaseModule):
    def __init__(self, sym_gen, default_bucket_key=None, logger=logging,
                 context=None, **kwargs):
        super().__init__(logger)
        assert default_bucket_key is not None
        self._sym_gen = sym_gen
        self._default_bucket_key = default_bucket_key
        self._context = context
        self._kwargs = kwargs
        self._buckets = {}
        self._curr_module = None
        self._curr_bucket_key = None

    @property
    def symbol(self):
        return self._curr_module.symbol

    def _gen_module(self, bucket_key):
        if bucket_key not in self._buckets:
            symbol, data_names, label_names = self._sym_gen(bucket_key)
            mod = Module(symbol, data_names, label_names,
                         logger=self.logger, context=self._context,
                         **self._kwargs)
            self._buckets[bucket_key] = mod
        return self._buckets[bucket_key]

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             **kwargs):
        self._curr_module = self._gen_module(self._default_bucket_key)
        self._curr_bucket_key = self._default_bucket_key
        self._curr_module.bind(data_shapes, label_shapes, for_training)
        self.binded = True
        self.for_training = for_training

    def switch_bucket(self, bucket_key, data_shapes, label_shapes=None):
        mod = self._gen_module(bucket_key)
        if not mod.binded:
            src = self._buckets[self._default_bucket_key]
            # shared_module bind: the bucket executor adopts the default
            # bucket's parameter arrays directly (no throwaway zero
            # allocation) — reference BucketingModule does the same
            mod.bind(data_shapes, label_shapes, self.for_training,
                     shared_module=src if src.params_initialized else None)
            if src.params_initialized:
                self._share_into(mod)
        self._curr_module = mod
        self._curr_bucket_key = bucket_key

    def _share_into(self, mod):
        """All buckets train ONE parameter storage (adopted at bind via
        shared_module, or here for buckets bound before init_params) and
        ONE optimizer/kvstore state. State keys are parameter NAMES
        (Module.update), so buckets whose parameters are a SUBSET of the
        default bucket's work like the reference."""
        src = self._buckets[self._default_bucket_key]
        src_args = src._exec.arg_dict
        io_names = set(mod._data_names) | set(mod._label_names)
        for name in list(mod._exec.arg_dict):
            if name in io_names:
                continue
            if name not in src_args:
                raise MXNetError(
                    f"bucket parameter '{name}' does not exist in the "
                    f"default bucket ({self._default_bucket_key}); choose "
                    "default_bucket_key so its symbol owns every "
                    "parameter (reference BucketingModule requires the "
                    "same)")
            if tuple(mod._exec.arg_dict[name].shape) != \
                    tuple(src_args[name].shape):
                raise MXNetError(
                    f"bucket parameter '{name}' has shape "
                    f"{mod._exec.arg_dict[name].shape} but the shared "
                    f"storage is {src_args[name].shape}; sym_gen must "
                    "produce length-independent parameters")
            mod._exec.arg_dict[name] = src_args[name]
        mod.params_initialized = True
        if src._kvstore is not None and src._kvstore.num_workers > 1 and \
                set(mod._trainable_names()) != set(src._trainable_names()):
            # multi-process sync stores allreduce a coalesced bucket per
            # step: workers on different buckets pushing different key
            # sets would desynchronize the collective
            raise MXNetError(
                "bucket symbols use different parameter SETS; with a "
                "multi-worker sync kvstore every bucket must push the "
                "same keys (use identical parameters across buckets, or "
                "dist_async)")
        if src.optimizer_initialized:
            mod._optimizer = src._optimizer
            mod._updater_states = src._updater_states
            mod._kvstore = src._kvstore
            mod._update_on_kvstore = src._update_on_kvstore
            mod._batch_size = src._batch_size
            mod.optimizer_initialized = True

    def init_params(self, **kwargs):
        # params live on the DEFAULT bucket's module; every other bucket
        # shares its handles (see _share_into)
        self._buckets[self._default_bucket_key].init_params(**kwargs)
        for key, mod in self._buckets.items():
            if key != self._default_bucket_key and mod.binded:
                self._share_into(mod)
        self.params_initialized = True

    def init_optimizer(self, **kwargs):
        self._buckets[self._default_bucket_key].init_optimizer(**kwargs)
        for key, mod in self._buckets.items():
            if key != self._default_bucket_key and mod.binded:
                self._share_into(mod)
        self.optimizer_initialized = True

    def forward(self, data_batch, is_train=None):
        key = getattr(data_batch, "bucket_key", None) or \
            self._default_bucket_key
        if key != self._curr_bucket_key:
            self.switch_bucket(key, data_batch.provide_data,
                               data_batch.provide_label)
        self._curr_module.forward(data_batch, is_train)

    def backward(self, out_grads=None):
        self._curr_module.backward(out_grads)

    def update(self):
        # all buckets alias ONE parameter storage (_share_into adopts the
        # default bucket's NDArray handles), so updating through the
        # current bucket updates every bucket
        self._curr_module.update()

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        self._curr_module.update_metric(eval_metric, labels)

    def get_outputs(self, merge_multi_context=True):
        return self._curr_module.get_outputs()

    def get_params(self):
        # params live on the DEFAULT bucket's module (the superset);
        # reading from a subset bucket would drop parameters silently
        return self._buckets[self._default_bucket_key].get_params()
